"""Oracle MembershipView tests, mirroring the reference MembershipViewTest.java
scenario matrix (SURVEY.md §4.1)."""
import pytest

from rapid_tpu.oracle import (
    MembershipView,
    NodeAlreadyInRingError,
    NodeNotInRingError,
    UUIDAlreadySeenError,
)
from rapid_tpu.types import Endpoint, NodeId

K = 10
_id_counter = 0


def fresh_id() -> NodeId:
    global _id_counter
    _id_counter += 1
    return NodeId(0xABCD_0000 + _id_counter, _id_counter * 7919)


def ep(i: int, host: str = "127.0.0.1") -> Endpoint:
    return Endpoint(host, i)


def test_one_ring_addition():
    view = MembershipView(K)
    addr = ep(123)
    view.ring_add(addr, fresh_id())
    for k in range(K):
        ring = view.get_ring(k)
        assert ring == [addr]


def test_multiple_ring_additions():
    view = MembershipView(K)
    for i in range(10):
        view.ring_add(ep(i), fresh_id())
    for k in range(K):
        assert len(view.get_ring(k)) == 10


def test_ring_readditions_rejected():
    view = MembershipView(K)
    for i in range(10):
        view.ring_add(ep(i), fresh_id())
    for i in range(10):
        with pytest.raises(NodeAlreadyInRingError):
            view.ring_add(ep(i), fresh_id())


def test_ring_deletions_of_absent_nodes_rejected():
    view = MembershipView(K)
    for i in range(10):
        with pytest.raises(NodeNotInRingError):
            view.ring_delete(ep(i))


def test_ring_additions_and_deletions():
    view = MembershipView(K)
    for i in range(10):
        view.ring_add(ep(i), fresh_id())
    for i in range(10):
        view.ring_delete(ep(i))
    for k in range(K):
        assert view.get_ring(k) == []


def test_monitoring_relationship_single_node_and_absent():
    view = MembershipView(K)
    n1 = ep(1)
    view.ring_add(n1, fresh_id())
    assert view.get_subjects_of(n1) == []
    assert view.get_observers_of(n1) == []

    n2 = ep(2)
    with pytest.raises(NodeNotInRingError):
        view.get_subjects_of(n2)
    with pytest.raises(NodeNotInRingError):
        view.get_observers_of(n2)


def test_monitoring_relationship_empty_view():
    view = MembershipView(K)
    with pytest.raises(NodeNotInRingError):
        view.get_subjects_of(ep(1))
    with pytest.raises(NodeNotInRingError):
        view.get_observers_of(ep(1))


def test_monitoring_relationship_two_nodes():
    view = MembershipView(K)
    n1, n2 = ep(1), ep(2)
    view.ring_add(n1, fresh_id())
    view.ring_add(n2, fresh_id())
    assert len(view.get_subjects_of(n1)) == K
    assert len(view.get_observers_of(n1)) == K
    assert len(set(view.get_subjects_of(n1))) == 1
    assert len(set(view.get_observers_of(n1))) == 1


def test_monitoring_relationship_three_nodes_with_delete():
    view = MembershipView(K)
    n1, n2, n3 = ep(1), ep(2), ep(3)
    for n in (n1, n2, n3):
        view.ring_add(n, fresh_id())
    assert len(view.get_subjects_of(n1)) == K
    assert len(view.get_observers_of(n1)) == K
    assert len(set(view.get_subjects_of(n1))) == 2
    assert len(set(view.get_observers_of(n1))) == 2
    view.ring_delete(n2)
    assert len(view.get_subjects_of(n1)) == K
    assert len(view.get_observers_of(n1)) == K
    assert len(set(view.get_subjects_of(n1))) == 1
    assert len(set(view.get_observers_of(n1))) == 1


def test_monitoring_relationship_multiple_nodes():
    view = MembershipView(K)
    nodes = [ep(i) for i in range(1000)]
    for n in nodes:
        view.ring_add(n, fresh_id())
    for n in nodes[:100]:
        assert len(view.get_subjects_of(n)) == K
        assert len(view.get_observers_of(n)) == K


def test_observer_subject_duality():
    """If s is a subject of o on ring k, then o is an observer of s on ring k."""
    view = MembershipView(K)
    nodes = [ep(i) for i in range(50)]
    for n in nodes:
        view.ring_add(n, fresh_id())
    for o in nodes:
        subjects = view.get_subjects_of(o)
        for k, s in enumerate(subjects):
            assert view.get_observers_of(s)[k] == o


def test_monitoring_relationship_bootstrap():
    view = MembershipView(K)
    n = ep(1234)
    view.ring_add(n, fresh_id())
    joiner = ep(1235)
    expected = view.get_expected_observers_of(joiner)
    assert len(expected) == K
    assert set(expected) == {n}


def test_monitoring_relationship_bootstrap_multiple():
    view = MembershipView(K)
    joiner = ep(1233)
    for i in range(20):
        view.ring_add(ep(1234 + i), fresh_id())
        # gatekeeper list always has one entry per ring
        assert len(view.get_expected_observers_of(joiner)) == K
    # with 20 nodes the K gatekeepers should be mostly distinct
    assert K - 3 <= len(set(view.get_expected_observers_of(joiner))) <= K


def test_node_unique_id_no_deletions():
    view = MembershipView(K)
    n1 = ep(1)
    id1 = fresh_id()
    view.ring_add(n1, id1)

    # same host, same id
    with pytest.raises(UUIDAlreadySeenError):
        view.ring_add(ep(1), NodeId(id1.high, id1.low))
    # same host, different id
    with pytest.raises(NodeAlreadyInRingError):
        view.ring_add(ep(1), fresh_id())
    # different host, same id
    n3 = ep(2)
    with pytest.raises(UUIDAlreadySeenError):
        view.ring_add(n3, NodeId(id1.high, id1.low))
    # different host, different id: fine
    view.ring_add(n3, fresh_id())
    assert len(view.get_ring(0)) == 2


def test_node_unique_id_with_deletions():
    view = MembershipView(K)
    n1, n2 = ep(1), ep(2)
    id2 = fresh_id()
    view.ring_add(n1, fresh_id())
    view.ring_add(n2, id2)
    view.ring_delete(n2)
    assert len(view.get_ring(0)) == 1
    # rejoin with the same id is rejected; a fresh id works
    with pytest.raises(UUIDAlreadySeenError):
        view.ring_add(n2, NodeId(id2.high, id2.low))
    view.ring_add(n2, fresh_id())
    assert len(view.get_ring(0)) == 2


def test_node_configuration_change():
    view = MembershipView(K)
    seen = set()
    for i in range(1000):
        view.ring_add(ep(i), NodeId(i, i))
        seen.add(view.get_current_configuration_id())
    assert len(seen) == 1000


def test_node_configurations_across_views():
    """Same nodes added in opposite orders: all intermediate configuration ids
    differ, the final ones agree (order-independence of the fingerprint)."""
    v1, v2 = MembershipView(K), MembershipView(K)
    n = 1000
    ids1, ids2 = [], []
    for i in range(n):
        v1.ring_add(ep(i), NodeId(i, i))
        ids1.append(v1.get_current_configuration_id())
    for i in reversed(range(n)):
        v2.ring_add(ep(i), NodeId(i, i))
        ids2.append(v2.get_current_configuration_id())
    assert all(a != b for a, b in zip(ids1[:-1], ids2[:-1]))
    assert ids1[-1] == ids2[-1]


def test_configuration_snapshot_roundtrip():
    """A Configuration snapshot bootstraps an identical view (the checkpoint
    format; reference MembershipView.java:443-462)."""
    view = MembershipView(K)
    for i in range(64):
        view.ring_add(ep(i), NodeId(i * 3, i * 5))
    cfg = view.get_configuration()
    assert cfg.get_configuration_id() == view.get_current_configuration_id()
    restored = MembershipView(K, cfg.node_ids, cfg.endpoints)
    assert restored.get_current_configuration_id() == view.get_current_configuration_id()
    for k in range(K):
        assert restored.get_ring(k) == view.get_ring(k)
    for n in view.get_ring(0)[:10]:
        assert restored.get_observers_of(n) == view.get_observers_of(n)


def test_incremental_configuration_id_matches_recompute():
    """The view maintains its configuration id incrementally (modular sums
    updated on ring_add/ring_delete); a full O(N) re-hash over the snapshot
    must agree after every mutation."""
    view = MembershipView(K)
    for i in range(200):
        view.ring_add(ep(i), NodeId(i * 3 + 1, i * 5 + 2))
        if i % 7 == 0:
            cfg = view.get_configuration()
            assert cfg.get_configuration_id() == cfg.recompute_configuration_id()
            assert cfg.get_configuration_id() == view.get_current_configuration_id()
    for i in range(0, 200, 3):
        view.ring_delete(ep(i))
        cfg = view.get_configuration()
        assert cfg.get_configuration_id() == cfg.recompute_configuration_id()
        assert cfg.get_configuration_id() == view.get_current_configuration_id()
