"""Whole-cluster simulation tests in one process, mirroring the reference
ClusterTest.java scenario matrix (SURVEY.md §4.4): sequential and parallel
joins, crash faults detected by the real probe-based FD, bulk failures,
concurrent join+failure, graceful leave, and kick notification."""
import pytest

from rapid_tpu.events import ClusterEvents
from rapid_tpu.faults import CrashFault, ComposedFault, OneWayPartitionFault
from rapid_tpu.oracle.cluster import Cluster
from rapid_tpu.oracle.simulation import SimNetwork
from rapid_tpu.settings import Settings
from rapid_tpu.types import Endpoint

SETTINGS = Settings()


def ep(i: int) -> Endpoint:
    return Endpoint("10.0.0.1", 1234 + i)


def make_network(fault_model=None, settings=SETTINGS) -> SimNetwork:
    if fault_model is None:
        return SimNetwork(settings)
    return SimNetwork(settings, fault_model)


def wait_until(network: SimNetwork, predicate, max_ticks: int = 1000) -> bool:
    for _ in range(max_ticks):
        if predicate():
            return True
        network.step()
    return predicate()


def boot_cluster(network: SimNetwork, n: int, parallel: bool = False,
                 settings=SETTINGS):
    """Seed at ep(0); n-1 joiners; returns the list of Cluster objects."""
    clusters = [Cluster(network, ep(0), settings).start()]
    joiners = []
    for i in range(1, n):
        c = Cluster(network, ep(i), settings)
        joiners.append(c)
    if parallel:
        for c in joiners:
            c.join(ep(0))
        ok = wait_until(
            network,
            lambda: all(c.is_active for c in joiners)
            and all(c.get_membership_size() == n for c in joiners + clusters),
            max_ticks=3000,
        )
        assert ok, "parallel joins did not converge"
    else:
        for c in joiners:
            c.join(ep(0))
            assert wait_until(network, lambda: c.is_active, 500), \
                f"{c.listen_address} failed to join"
    clusters.extend(joiners)
    return clusters


def verify_agreement(clusters, expected_size=None):
    active = [c for c in clusters if c.is_active]
    lists = {tuple(c.get_memberlist()) for c in active}
    assert len(lists) == 1, f"views diverged: {len(lists)} distinct"
    configs = {c.get_configuration_id() for c in active}
    assert len(configs) == 1
    if expected_size is not None:
        assert len(next(iter(lists))) == expected_size


def test_single_node_start():
    network = make_network()
    c = Cluster(network, ep(0)).start()
    assert c.get_membership_size() == 1
    assert c.get_memberlist() == [ep(0)]


def test_single_join():
    network = make_network()
    seed = Cluster(network, ep(0)).start()
    joiner = Cluster(network, ep(1)).join(ep(0))
    assert wait_until(network, lambda: joiner.is_active, 200)
    verify_agreement([seed, joiner], expected_size=2)


@pytest.mark.parametrize("n", [5, 10, 20])
def test_sequential_joins(n):
    network = make_network()
    clusters = boot_cluster(network, n)
    assert wait_until(
        network,
        lambda: all(c.get_membership_size() == n for c in clusters), 300)
    verify_agreement(clusters, expected_size=n)


@pytest.mark.parametrize("n", [10, 21])
def test_parallel_joins(n):
    network = make_network()
    clusters = boot_cluster(network, n, parallel=True)
    verify_agreement(clusters, expected_size=n)


def test_join_with_metadata():
    network = make_network()
    Cluster(network, ep(0), metadata={"role": b"seed"}).start()
    joiner = Cluster(network, ep(1), metadata={"role": b"worker"}).join(ep(0))
    assert wait_until(network, lambda: joiner.is_active, 200)
    md = joiner.get_cluster_metadata()
    assert md.get(ep(1), {}).get("role") == b"worker"
    # seed's metadata travels to the joiner through the join response
    assert md.get(ep(0), {}).get("role") == b"seed"


def test_crash_one_of_five():
    crash = CrashFault()
    network = make_network(crash)
    clusters = boot_cluster(network, 5)
    victim = clusters[2]
    crash.crashes[victim.listen_address] = network.tick + 1

    survivors = clusters[:2] + clusters[3:]
    ok = wait_until(
        network,
        lambda: all(c.get_membership_size() == 4 for c in survivors),
        max_ticks=3000,
    )
    assert ok, "crash was not detected and removed"
    verify_agreement(survivors, expected_size=4)
    assert victim.listen_address not in survivors[0].get_memberlist()


def test_crash_quarter_of_twenty():
    crash = CrashFault()
    network = make_network(crash)
    n = 20
    clusters = boot_cluster(network, n)
    victims = clusters[3:8:1][:5]
    for v in victims:
        crash.crashes[v.listen_address] = network.tick + 1
    survivors = [c for c in clusters if c not in victims]
    ok = wait_until(
        network,
        lambda: all(c.get_membership_size() == n - len(victims)
                    for c in survivors),
        max_ticks=5000,
    )
    assert ok, "bulk crash was not fully removed"
    verify_agreement(survivors, expected_size=n - len(victims))


def test_view_change_events_fire():
    network = make_network()
    seed = Cluster(network, ep(0))
    events = []
    seed.register_subscription(
        ClusterEvents.VIEW_CHANGE, lambda c: events.append(c))
    seed.start()
    assert len(events) == 1  # initial view
    joiner = Cluster(network, ep(1)).join(ep(0))
    assert wait_until(network, lambda: joiner.is_active, 200)
    assert len(events) == 2
    assert set(events[-1].membership) == {ep(0), ep(1)}


def test_graceful_leave():
    network = make_network()
    clusters = boot_cluster(network, 5)
    leaver = clusters[4]
    leaver.leave_gracefully()
    survivors = clusters[:4]
    ok = wait_until(
        network,
        lambda: all(c.get_membership_size() == 4 for c in survivors),
        max_ticks=2000,
    )
    assert ok, "graceful leave was not propagated"
    verify_agreement(survivors, expected_size=4)


def test_one_way_partition_removes_only_target():
    """Asymmetric 'firewall': node cannot be probed (ingress blocked); the
    cluster should remove exactly that node (paper Fig. 9 behavior)."""
    n = 8
    partition = OneWayPartitionFault()
    network = make_network(partition)
    clusters = boot_cluster(network, n)
    target = clusters[3].listen_address
    partition.from_set = frozenset(
        c.listen_address for c in clusters if c.listen_address != target)
    partition.to_set = frozenset({target})
    partition.start_tick = network.tick + 1

    survivors = [c for c in clusters if c.listen_address != target]
    ok = wait_until(
        network,
        lambda: all(c.get_membership_size() == n - 1 for c in survivors),
        max_ticks=3000,
    )
    assert ok, "one-way partition target not removed"
    verify_agreement(survivors, expected_size=n - 1)
    assert target not in survivors[0].get_memberlist()


def test_kicked_node_gets_notified():
    """Survivors' failure detectors blacklist a healthy victim (injected via
    the public FD SPI, like the reference's StaticFailureDetector). The
    network stays healthy, so the victim receives the consensus votes,
    decides the view change that removes it, and fires KICKED."""
    from rapid_tpu.oracle.testkit import StaticFailureDetector

    network = make_network()
    fd = StaticFailureDetector()
    clusters = [Cluster(network, ep(0), SETTINGS, fd_factory=fd).start()]
    for i in range(1, 5):
        c = Cluster(network, ep(i), SETTINGS, fd_factory=fd).join(ep(0))
        assert wait_until(network, lambda: c.is_active, 500)
        clusters.append(c)

    victim = clusters[2]
    kicked = []
    victim.register_subscription(ClusterEvents.KICKED, kicked.append)
    fd.add_failed_nodes([victim.listen_address])

    survivors = [c for c in clusters if c is not victim]
    ok = wait_until(
        network,
        lambda: all(c.get_membership_size() == 4 for c in survivors)
        and len(kicked) > 0,
        max_ticks=3000,
    )
    assert ok, "victim was never told it was kicked"
    verify_agreement(survivors, expected_size=4)


def test_concurrent_join_and_crash():
    crash = CrashFault()
    network = make_network(crash)
    n = 10
    clusters = boot_cluster(network, n)
    victim = clusters[5]
    crash.crashes[victim.listen_address] = network.tick + 1
    late_joiner = Cluster(network, ep(100)).join(ep(0))

    survivors = [c for c in clusters if c is not victim]
    ok = wait_until(
        network,
        lambda: late_joiner.is_active
        and all(c.get_membership_size() == n for c in survivors + [late_joiner]),
        max_ticks=5000,
    )
    assert ok, "concurrent join+crash did not converge"
    verify_agreement(survivors + [late_joiner], expected_size=n)
    members = survivors[0].get_memberlist()
    assert victim.listen_address not in members
    assert ep(100) in members


def test_ingress_packet_loss_removes_only_target():
    """80% ingress packet loss on one node (paper Fig. 10): the lossy node
    should be removed, and only it."""
    from rapid_tpu.faults import PacketDropFault

    n = 8
    drop = PacketDropFault(p=0.0, ingress=True, egress=False, seed=7)
    network = make_network(drop)
    clusters = boot_cluster(network, n)
    target = clusters[4].listen_address
    drop.p = 0.8
    drop.targets = frozenset({target})

    survivors = [c for c in clusters if c.listen_address != target]
    ok = wait_until(
        network,
        lambda: all(c.get_membership_size() == n - 1 for c in survivors),
        max_ticks=6000,
    )
    assert ok, "lossy node not removed"
    verify_agreement(survivors, expected_size=n - 1)
    assert target not in survivors[0].get_memberlist()
