"""Latency adversary family: per-edge delay, jitter, and reordering.

The acceptance contract of the delay tentpole, pinned here:

- ``DelayRule`` validation is genuine-input-only plus one budget: a
  rule whose worst-case draw cannot fit the delivery ring raises the
  structured ``DelayBudgetError`` up front; overlapping directed-edge
  coverage (including implied reverse directions) is rejected.
- Ring boundary semantics are exact: a ``delay_ticks=0`` rule is
  bit-identical to no rule at all; ``delay_ticks == D - 1`` rides the
  ring horizon and still matches both referees; one past the horizon
  refuses; a delay-free schedule is bit-identical across ring depths
  (``D=1`` degenerates to the old next-tick wire).
- Both referees stay exact under latency at N=64 (and N=256, slow):
  ``run_adversarial_differential`` (host engine vs oracle) and
  ``run_receiver_differential`` (device kernel vs host engine) for
  delay-only, delay + partition, and asymmetric-jitter reordering.
- A classic-Paxos fallback triggers *purely* from a slow link: a slow
  voter subset delays fast votes past the fallback timer with zero
  drops anywhere, and the classic 1a/1b/2a/2b chain decides —
  bit-identical on both referees.
- Inert delay-rule padding (``pad_delay_rules``, used by
  ``stack_receiver_members`` to batch heterogeneous members) never
  changes a member's outcome, bit for bit.
"""
import numpy as np
import pytest

from rapid_tpu.engine import fleet as fleet_mod
from rapid_tpu.engine import receiver as rx_mod
from rapid_tpu.engine.diff import (run_adversarial_differential,
                                   run_receiver_differential)
from rapid_tpu.faults import (AdversarySchedule, DelayBudgetError, DelayRule,
                              LinkWindow, validate_schedule)
from rapid_tpu.settings import Settings

SETTINGS = Settings()
RING = SETTINGS.delivery_ring_depth


def _assert_exact(result):
    result.assert_identical()
    assert result.engine_phase_counters == result.oracle_phase_counters
    assert result.engine_config_ids == result.oracle_config_ids


def _assert_tree_equal(a, b, what):
    import jax

    leaves_a, tree_a = jax.tree_util.tree_flatten(a)
    leaves_b, tree_b = jax.tree_util.tree_flatten(b)
    assert tree_a == tree_b, f"{what}: treedefs diverged"
    for i, (x, y) in enumerate(zip(leaves_a, leaves_b)):
        assert np.array_equal(np.asarray(x), np.asarray(y)), \
            f"{what}: leaf {i} diverged"


def _events(result):
    """Per-slot event streams as comparable tuples."""
    return [[(e.kind, e.tick, e.config_id, tuple(e.slots))
             for e in stream]
            for stream in result.engine_events_by_slot]


def _phase_total(result, key):
    return sum(d[key] for d in result.engine_phase_counters)


def _crash_sched(n, delays, seed=5, crash_slot=None, windows=()):
    """A crash burst plus the given delay rules: the crash forces a view
    change, so latency has protocol traffic to act on."""
    slot = crash_slot if crash_slot is not None else n - 1
    return AdversarySchedule(n=n, crashes=((slot, 11),), windows=windows,
                             delays=tuple(delays), seed=seed)


# ---------------------------------------------------------------------------
# validation: genuine input errors + the ring budget
# ---------------------------------------------------------------------------


def test_delay_rule_field_validation():
    n = 8
    all_slots = frozenset(range(n))

    def _sched(rule):
        return AdversarySchedule(n=n, delays=(rule,), seed=0)

    with pytest.raises(ValueError, match="non-empty"):
        validate_schedule(_sched(DelayRule(src_slots=frozenset(),
                                           dst_slots=all_slots)))
    with pytest.raises(ValueError, match="outside universe"):
        validate_schedule(_sched(DelayRule(src_slots=frozenset({n + 3}),
                                           dst_slots=all_slots)))
    with pytest.raises(ValueError, match="delay_ticks must be >= 0"):
        validate_schedule(_sched(DelayRule(src_slots=all_slots,
                                           dst_slots=all_slots,
                                           delay_ticks=-1)))
    with pytest.raises(ValueError, match="jitter_ticks must be >= 0"):
        validate_schedule(_sched(DelayRule(src_slots=all_slots,
                                           dst_slots=all_slots,
                                           jitter_ticks=-2)))
    with pytest.raises(ValueError, match="reverse_delay_ticks"):
        validate_schedule(_sched(DelayRule(src_slots=all_slots,
                                           dst_slots=all_slots,
                                           reverse_delay_ticks=-2)))
    with pytest.raises(ValueError, match="zero-length delay rule"):
        validate_schedule(_sched(DelayRule(src_slots=all_slots,
                                           dst_slots=all_slots,
                                           start_tick=30, end_tick=30)))


def test_delay_budget_error_is_structured():
    """Worst case = max(base, reverse) + jitter; one past ``D - 1``
    raises the structured refusal, exactly at the horizon passes."""
    n = 8
    rule = DelayRule(src_slots=frozenset({0}), dst_slots=frozenset({1}),
                     delay_ticks=2, jitter_ticks=2)
    sched = AdversarySchedule(n=n, delays=(rule,), seed=0)
    with pytest.raises(DelayBudgetError) as exc:
        validate_schedule(sched, ring_depth=4)
    err = exc.value
    assert err.ring_depth == 4 and err.max_delay == 4
    assert err.base_ticks == 2 and err.jitter_ticks == 2
    assert "delivery_ring_depth" in str(err)
    # same rule fits a deeper ring; no ring_depth means no budget check
    validate_schedule(sched, ring_depth=5)
    validate_schedule(sched)
    # the reverse base counts toward the worst case too
    rev = DelayRule(src_slots=frozenset({0}), dst_slots=frozenset({1}),
                    delay_ticks=1, reverse_delay_ticks=3, jitter_ticks=1)
    with pytest.raises(DelayBudgetError):
        validate_schedule(AdversarySchedule(n=n, delays=(rev,), seed=0),
                          ring_depth=4)
    # DelayBudgetError is a ValueError: one except arm catches both
    assert issubclass(DelayBudgetError, ValueError)


def test_overlapping_delay_rules_rejected():
    a = DelayRule(src_slots=frozenset({0, 1}), dst_slots=frozenset({2}),
                  delay_ticks=1)
    b = DelayRule(src_slots=frozenset({1}), dst_slots=frozenset({2, 3}),
                  delay_ticks=2)
    with pytest.raises(ValueError, match="overlapping delay rules"):
        validate_schedule(AdversarySchedule(n=8, delays=(a, b), seed=0))
    # disjoint tick ranges never overlap
    validate_schedule(AdversarySchedule(
        n=8, delays=(DelayRule(src_slots=frozenset({0, 1}),
                               dst_slots=frozenset({2}),
                               delay_ticks=1, end_tick=40),
                     DelayRule(src_slots=frozenset({1}),
                               dst_slots=frozenset({2, 3}),
                               delay_ticks=2, start_tick=40)), seed=0))
    # a rule's implied reverse direction counts as coverage
    fwd = DelayRule(src_slots=frozenset({0}), dst_slots=frozenset({1}),
                    delay_ticks=1, reverse_delay_ticks=2)
    back = DelayRule(src_slots=frozenset({1}), dst_slots=frozenset({0}),
                     delay_ticks=1)
    with pytest.raises(ValueError, match="overlapping delay rules"):
        validate_schedule(AdversarySchedule(n=8, delays=(fwd, back), seed=0))


def test_lowering_refuses_over_budget_and_shared_path():
    """Receiver lowering enforces the ring budget of the settings it is
    handed; the shared-state lowering refuses delay schedules outright
    (the shared wire cannot represent per-edge delays)."""
    rule = DelayRule(src_slots=frozenset({0}), dst_slots=frozenset({1}),
                     delay_ticks=RING)  # max_delay == RING > RING - 1
    sched = _crash_sched(8, [rule])
    with pytest.raises(DelayBudgetError):
        fleet_mod.lower_receiver_schedule(sched, SETTINGS)
    with pytest.raises(DelayBudgetError):
        run_receiver_differential(sched, 40, SETTINGS)
    ok = _crash_sched(8, [DelayRule(src_slots=frozenset({0}),
                                    dst_slots=frozenset({1}),
                                    delay_ticks=1)])
    with pytest.raises(ValueError, match="lower_receiver_schedule"):
        fleet_mod.lower_schedule(ok, SETTINGS)


# ---------------------------------------------------------------------------
# ring boundary semantics
# ---------------------------------------------------------------------------


def test_delay_zero_is_bit_identical_to_no_rule():
    """A ``delay_ticks=0`` rule must be a provable no-op: same event
    streams, config ids and per-phase counters as the same schedule
    with no delays at all — through the device referee."""
    n = 16
    zero = DelayRule(src_slots=frozenset(range(6)),
                     dst_slots=frozenset(range(6, n)), delay_ticks=0)
    with_rule = run_receiver_differential(_crash_sched(n, [zero]), 160,
                                          SETTINGS)
    without = run_receiver_differential(_crash_sched(n, []), 160, SETTINGS)
    _assert_exact(with_rule)
    _assert_exact(without)
    assert _events(with_rule) == _events(without)
    assert with_rule.engine_config_ids == without.engine_config_ids
    assert with_rule.engine_phase_counters == without.engine_phase_counters


def test_delay_at_ring_horizon_is_exact():
    """``delay_ticks == D - 1`` occupies the deepest ring slot a message
    can take; both referees must still agree bit for bit, and the run
    must actually decide (the delay shifts, not starves, the decide)."""
    n = 16
    horizon = DelayRule(src_slots=frozenset(range(5)),
                        dst_slots=frozenset(range(5, n)),
                        delay_ticks=RING - 1)
    sched = _crash_sched(n, [horizon])
    dev = run_receiver_differential(sched, 200, SETTINGS)
    _assert_exact(dev)
    _assert_exact(run_adversarial_differential(sched, 200, SETTINGS))
    assert any(e.kind == "view_change"
               for e in dev.engine_events_by_slot[0])


def test_delay_free_schedule_identical_across_ring_depths():
    """``D=1`` degenerates to the old next-tick wire: a delay-free
    schedule must produce bit-identical streams at D=1 and the default
    depth (the ring axis is inert when nothing draws a delay)."""
    sched = _crash_sched(16, [])
    deep = run_receiver_differential(sched, 160, SETTINGS)
    shallow = run_receiver_differential(
        sched, 160, SETTINGS.with_(delivery_ring_depth=1))
    _assert_exact(deep)
    _assert_exact(shallow)
    assert _events(deep) == _events(shallow)
    assert deep.engine_config_ids == shallow.engine_config_ids
    assert deep.engine_phase_counters == shallow.engine_phase_counters


# ---------------------------------------------------------------------------
# N=64 differentials: delay-only, delay+partition, jitter reorder
# ---------------------------------------------------------------------------


def test_delay_only_differentials_n64():
    n = 64
    rule = DelayRule(src_slots=frozenset(range(12)),
                     dst_slots=frozenset(range(12, n)), delay_ticks=2)
    sched = _crash_sched(n, [rule])
    _assert_exact(run_adversarial_differential(sched, 200, SETTINGS))
    _assert_exact(run_receiver_differential(sched, 200, SETTINGS))


def test_delay_plus_partition_differentials_n64():
    """Latency composes with drops: a one-way partition isolates one
    group while a disjoint edge set runs slow — delivery-tick drop
    evaluation and send-tick delay evaluation must not interfere."""
    n = 64
    iso = frozenset(range(52, 64))
    rest = frozenset(range(52))
    sched = AdversarySchedule(
        n=n,
        windows=(LinkWindow(src_slots=rest, dst_slots=iso, start_tick=6),),
        delays=(DelayRule(src_slots=frozenset(range(10)),
                          dst_slots=frozenset(range(10, 40)),
                          delay_ticks=2, jitter_ticks=1),),
        seed=17)
    host = run_adversarial_differential(sched, 240, SETTINGS)
    _assert_exact(host)
    _assert_exact(run_receiver_differential(sched, 240, SETTINGS))
    # the partition must have actually dropped traffic — latency never
    # drops anything, so every drop here is the window's
    assert sum(r.link_dropped for r in host.engine_metrics) > 0


def test_asymmetric_jitter_reorder_differentials_n64():
    """Jitter on an asymmetric edge set reorders messages in flight;
    receivers must process them in announce order on both referees —
    and the jitter must actually spread arrivals (non-zero bound with
    a base of zero exercises pure reordering)."""
    n = 64
    rule = DelayRule(src_slots=frozenset(range(8)),
                     dst_slots=frozenset(range(8, n)),
                     delay_ticks=0, jitter_ticks=2,
                     reverse_delay_ticks=1)
    sched = _crash_sched(n, [rule], seed=23)
    _assert_exact(run_adversarial_differential(sched, 200, SETTINGS))
    _assert_exact(run_receiver_differential(sched, 200, SETTINGS))


# ---------------------------------------------------------------------------
# the headline: a classic fallback decided purely by a slow link
# ---------------------------------------------------------------------------


def _slow_voters_sched(n, n_slow, delay, start=100, seed=9):
    """Crash slot 5; make the top ``n_slow`` slots slow enough that the
    fast round misses quorum until the organic fallback timer fires.
    The rule starts after boot convergence (tick 100) so only the
    post-crash consensus traffic rides the slow link. No windows, no
    drops — latency is the only adversary surface."""
    slow = frozenset(range(n - n_slow, n))
    return AdversarySchedule(
        n=n, crashes=((5, 11),),
        delays=(DelayRule(src_slots=slow, dst_slots=frozenset(range(n)),
                          delay_ticks=delay, start_tick=start),),
        seed=seed)


def test_slow_link_triggers_classic_fallback_n16():
    """6 of 15 surviving voters delayed 30 ticks: only 9 on-time fast
    votes circulate, short of the fast quorum of 13, so the decision
    must come from the classic 1a/1b/2a/2b chain — with zero drops
    anywhere (latency alone caused the fallback), on both referees.
    Empirically: proposal at 152, classic decide at 176 (the fast path
    alone decides at 123)."""
    n, ring = 16, 32
    settings = SETTINGS.with_(delivery_ring_depth=ring)
    sched = _slow_voters_sched(n, 6, 30)
    host = run_adversarial_differential(sched, 400, settings)
    _assert_exact(host)
    dev = run_receiver_differential(sched, 400, settings)
    _assert_exact(dev)
    for phase in ("phase1a_sent", "phase1b_sent", "phase2a_sent",
                  "phase2b_sent"):
        assert _phase_total(dev, phase) > 0, f"{phase} never fired"
    assert sum(r.link_dropped for r in host.engine_metrics) == 0
    # the survivors converge on one post-crash view cutting slot 5
    vcs = [e for e in dev.engine_events_by_slot[0] if e.kind == "view_change"]
    assert vcs and {s for vc in vcs for s in vc.slots} == {5}


def test_slow_link_triggers_classic_fallback_n64():
    """Same mechanism at N=64: 16 slow voters of 63 survivors leave 47
    on-time fast votes, short of the fast quorum of 49. Delay 40 keeps
    the late votes clear of the classic round's own messages (see
    test_cross_phase_reorder_is_refused_not_diverged for what happens
    when they collide)."""
    n, ring = 64, 48
    settings = SETTINGS.with_(delivery_ring_depth=ring)
    sched = _slow_voters_sched(n, 16, 40)
    host = run_adversarial_differential(sched, 400, settings)
    _assert_exact(host)
    dev = run_receiver_differential(sched, 400, settings)
    _assert_exact(dev)
    for phase in ("phase1a_sent", "phase1b_sent", "phase2a_sent",
                  "phase2b_sent"):
        assert _phase_total(dev, phase) > 0, f"{phase} never fired"
    assert sum(r.link_dropped for r in host.engine_metrics) == 0


def test_cross_phase_reorder_is_refused_not_diverged():
    """Delay 30 at N=64 lands the slow voters' fast votes on the same
    arrival tick as the classic round's freshly-sent phase-2a: oracle
    wseq order processes the older votes first, which the kernel's
    fixed group order cannot reproduce. The kernel must refuse with the
    sticky cross-phase flag — never report a silently divergent run —
    while the host referee stays oracle-exact on the same schedule."""
    n, ring = 64, 32
    settings = SETTINGS.with_(delivery_ring_depth=ring)
    sched = _slow_voters_sched(n, 16, 30)
    _assert_exact(run_adversarial_differential(sched, 400, settings))
    with pytest.raises(rx_mod.ReceiverEnvelopeError,
                       match="cross-phase-send-order-inversion"):
        run_receiver_differential(sched, 400, settings)


# ---------------------------------------------------------------------------
# N=256, slow-marked
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_delay_family_differentials_n256():
    n = 256
    delay_only = _crash_sched(
        n, [DelayRule(src_slots=frozenset(range(30)),
                      dst_slots=frozenset(range(30, n)),
                      delay_ticks=2, jitter_ticks=1)], seed=41)
    _assert_exact(run_adversarial_differential(delay_only, 200, SETTINGS))
    _assert_exact(run_receiver_differential(delay_only, 200, SETTINGS))


@pytest.mark.slow
def test_slow_link_classic_fallback_n256():
    """Fast quorum at N=256 is 193 of 255 survivors; 64 slow voters
    leave 191 on-time votes — two short — so latency alone forces the
    classic chain at fleet-representative scale.  The delay must beat
    the earliest recovery-timer draw (~64 ticks after the proposal at
    this scale and seed — expovariate jitter scales with N), or the
    late votes complete the fast quorum before any timer fires."""
    n, ring = 256, 96
    settings = SETTINGS.with_(delivery_ring_depth=ring)
    sched = _slow_voters_sched(n, 64, 80)
    host = run_adversarial_differential(sched, 400, settings)
    _assert_exact(host)
    dev = run_receiver_differential(sched, 400, settings)
    _assert_exact(dev)
    assert _phase_total(dev, "phase1a_sent") > 0
    assert _phase_total(dev, "phase2b_sent") > 0
    assert sum(r.link_dropped for r in host.engine_metrics) == 0


# ---------------------------------------------------------------------------
# inert padding
# ---------------------------------------------------------------------------


def test_pad_delay_rules_is_inert_bit_identically():
    """Padding a member's delay rules (as ``stack_receiver_members``
    does to batch heterogeneous fleets) never changes its outcome —
    growing from zero rules materializes the seed limbs and all-false
    masks, growing an existing set appends inert rows."""
    n, ticks = 16, 120
    no_delay = fleet_mod.lower_receiver_schedule(
        _crash_sched(n, [], seed=3), SETTINGS)
    with_delay = fleet_mod.lower_receiver_schedule(
        _crash_sched(n, [DelayRule(src_slots=frozenset(range(4)),
                                   dst_slots=frozenset(range(4, n)),
                                   delay_ticks=1, jitter_ticks=1)],
                     seed=3), SETTINGS)
    for member, grown in ((no_delay, 3), (with_delay, 4)):
        base_final, base_logs = rx_mod.receiver_simulate(
            member.state, member.faults, ticks, SETTINGS)
        padded = fleet_mod.pad_delay_rules(member.faults, grown)
        assert padded.n_delay_rules == grown
        pad_final, pad_logs = rx_mod.receiver_simulate(
            member.state, padded, ticks, SETTINGS)
        _assert_tree_equal(pad_final, base_final, "padded final state")
        _assert_tree_equal(pad_logs, base_logs, "padded logs")
    with pytest.raises(ValueError):
        fleet_mod.pad_delay_rules(with_delay.faults, 0)  # shrink refused
