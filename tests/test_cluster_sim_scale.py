"""1k-node oracle cluster-sim coverage (slow tier).

View-level 1k-node tests exist (tests/test_membership_view.py), but until
this file nothing exercised the *cluster simulation* — real SimNetwork,
probe-based failure detectors, alert batching, consensus — at that scale.
Bootstrapping 1k nodes through the sequential join protocol is O(N^3)
messages, so the cluster is statically wired (the same shortcut the engine
differential uses) and the join protocol itself is exercised by a small
batch of real joiners on top.
"""
import pytest

from rapid_tpu.engine.diff import (
    boot_static_cluster,
    default_endpoints,
    default_node_ids,
)
from rapid_tpu.faults import CrashFault
from rapid_tpu.oracle.cluster import Cluster
from rapid_tpu.settings import Settings
from rapid_tpu.types import Endpoint

SETTINGS = Settings()
N = 1000


def verify_agreement(clusters, expected_size):
    active = [c for c in clusters if c.is_active]
    sizes = {c.get_membership_size() for c in active}
    assert sizes == {expected_size}, f"sizes diverged: {sorted(sizes)[:5]}..."
    configs = {c.get_configuration_id() for c in active}
    assert len(configs) == 1, f"{len(configs)} distinct configuration ids"


@pytest.mark.slow
def test_thousand_node_contested_consensus():
    """Contested consensus at 1k nodes: two camps split the vote far below
    the fast quorum, slot 0's fallback timer fires, and the classic-Paxos
    round decides — bit-identical between oracle and engine, including the
    per-phase 1a/1b/2a/2b message counts."""
    from rapid_tpu.engine.diff import run_fallback_differential

    n = N
    values = [[0], [1]]
    # 120 voters (60 per camp) keep the oracle's delivery count tractable;
    # the other 880 members still promise and accept in the classic round.
    votes = {s: (6, s % 2) for s in range(120)}
    delays = {s: (10 if s == 0 else 100) for s in votes}
    res = run_fallback_differential(n, values, votes, delays, n_ticks=30)
    res.assert_identical()
    assert res.plan_info["mode"] == "classic"
    assert [e.kind for e in res.oracle_events] == ["view_change"]
    # every member promised and accepted: 1b unicasts and 2a fan-out at N
    assert sum(c["phase1b_sent"] for c in res.oracle_phase_counters) == n
    assert sum(c["phase2b_sent"] for c in res.oracle_phase_counters) == n * n


@pytest.mark.slow
def test_thousand_node_cluster_sim_bootstrap():
    crash = CrashFault()
    endpoints = default_endpoints(N)
    network, clusters, _ = boot_static_cluster(
        SETTINGS, endpoints, default_node_ids(N), crash)
    verify_agreement(clusters, N)

    # Steady state: a converged 1k cluster stays quiescent (no protocol
    # messages, only probes) across several FD intervals.
    network.run_ticks(30)
    assert network.counters.sent == 0
    assert network.counters.probes_sent > 0
    assert network.counters.probes_failed == 0
    verify_agreement(clusters, N)

    # Real join protocol on top of the statically-wired base.
    joiners = [Cluster(network, Endpoint("joiner%d.sim" % i, 5000), SETTINGS)
               for i in range(2)]
    for j in joiners:
        j.join(endpoints[0])
    for _ in range(600):
        if all(j.is_active for j in joiners) and \
                clusters[0].get_membership_size() == N + 2:
            break
        network.step()
    assert all(j.is_active for j in joiners), "1k-cluster joins timed out"
    clusters.extend(joiners)
    verify_agreement(clusters, N + 2)

    # Crash burst: the probe FD detects, the cut converges, one view change
    # removes all four.
    victims = [endpoints[i] for i in (10, 400, 700, 999)]
    t0 = network.tick
    for v in victims:
        crash.crashes[v] = t0 + 1
    removed_size = N + 2 - len(victims)
    for _ in range(160):
        if clusters[0].get_membership_size() == removed_size:
            break
        network.step()
    survivors = [c for c in clusters
                 if c.listen_address not in set(victims)]
    verify_agreement(survivors, removed_size)
    memberlist = survivors[0].get_memberlist()
    assert not any(v in memberlist for v in victims)
