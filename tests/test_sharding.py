"""Multi-chip sharding: slot-axis partitioning of the tick engine.

The conftest forces an 8-device virtual CPU mesh, so these tests
exercise the real partitioned program. The claims pinned here:

- running any scenario (steady crash burst, contested consensus, churn)
  on the 8-way slot mesh is *bitwise identical* to the single-device
  run — every StepLog column, every final-state leaf;
- the sharding is real, not decorative: the compiled program carries
  non-replicated slot-axis shardings through ``cut.aggregate``'s
  fixpoint and the vote-count tally (checked at both the jaxpr and the
  lowered-HLO level);
- the fleet axis composes with the mesh: a vmapped F=4 campaign shards
  each member's slot axis (``P(None, 'slots')``) and stays bit-identical
  to the unsharded fleet run;
- ``spec_for`` shards exactly the capacity axis, replicates scalars,
  static LUTs, and non-divisible shapes, and ``slot_mesh`` fails loudly
  when the device pool is too small.
"""
import importlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from rapid_tpu.engine import cut, sharding
from rapid_tpu.engine import fleet as fleet_mod
from rapid_tpu.engine import votes
from rapid_tpu.engine.churn import synthetic_churn_schedule
from rapid_tpu.engine.paxos import synthetic_contested_schedule
from rapid_tpu.engine.state import I32_MAX, crash_faults, init_state
from rapid_tpu.engine.step import simulate

step_mod = importlib.import_module("rapid_tpu.engine.step")
from rapid_tpu.faults import random_adversary_schedule
from rapid_tpu.settings import Settings

SETTINGS = Settings()
N_DEVICES = 8


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < N_DEVICES:
        pytest.skip("needs the conftest-forced 8-device CPU mesh")
    return sharding.slot_mesh(N_DEVICES)


def _synthetic_uids(n, seed=0):
    from rapid_tpu import hashing

    hi, lo = hashing.np_to_limbs(np.arange(1, n + 1, dtype=np.uint64))
    hi, lo = hashing.hash64_limbs(np, hi, lo, seed=0xBEEF ^ seed)
    return hashing.np_from_limbs(hi, lo)


def _assert_tree_equal(a, b, what):
    for field, x, y in zip(type(a)._fields, a, b):
        assert np.array_equal(np.asarray(x), np.asarray(y)), \
            f"{what}: field {field} diverged"


def _run_pair(mesh, state, faults, ticks, churn=None, fallback=None):
    """(unsharded, sharded) results of the same scenario."""
    base = simulate(state, faults, ticks, SETTINGS, churn, fallback)
    c = int(state.member.shape[0])
    s_state = sharding.shard_put(state, mesh, c)
    s_faults = sharding.shard_put(faults, mesh, c)
    shard = simulate(s_state, s_faults, ticks, SETTINGS, churn, fallback,
                     mesh=mesh)
    return base, shard


def _assert_partitioned(final_state):
    """The run must actually be sharded, not silently replicated."""
    spec = final_state.member.sharding.spec
    assert sharding.AXIS in tuple(spec), \
        f"final state is not slot-partitioned: {spec}"


# ---------------------------------------------------------------------------
# bitwise parity: sharded == unsharded on every scenario class
# ---------------------------------------------------------------------------


def test_steady_crash_burst_parity(mesh):
    n = 64
    state = init_state(_synthetic_uids(n), id_fp_sum=0, settings=SETTINGS)
    crash_ticks = [I32_MAX] * n
    for slot in (3, 17, 40):
        crash_ticks[slot] = 5
    faults = crash_faults(crash_ticks)
    (base_final, base_logs), (s_final, s_logs) = _run_pair(
        mesh, state, faults, 130)
    _assert_tree_equal(base_logs, s_logs, "steady logs")
    _assert_tree_equal(base_final, s_final, "steady final state")
    _assert_partitioned(s_final)


def test_contested_fallback_parity(mesh):
    n = 16
    ticks = 120
    uids = _synthetic_uids(n)
    schedule, _ = synthetic_contested_schedule(n, SETTINGS, ticks, uids=uids)
    state = init_state(uids, id_fp_sum=0, settings=SETTINGS)
    faults = crash_faults([I32_MAX] * n)
    (base_final, base_logs), (s_final, s_logs) = _run_pair(
        mesh, state, faults, ticks, fallback=schedule)
    _assert_tree_equal(base_logs, s_logs, "contested logs")
    _assert_tree_equal(base_final, s_final, "contested final state")
    _assert_partitioned(s_final)
    # The scenario must actually exercise the classic chain.
    assert int(np.asarray(s_logs.decide_now).sum()) >= 1


def test_churn_parity(mesh):
    n, burst, ticks = 24, 8, 120
    period = SETTINGS.churn_decide_delay_ticks + 3
    cycles = max(1, (ticks - 10) // (2 * period))
    capacity = n + cycles * burst  # divisible by 8: n and burst both are
    assert capacity % N_DEVICES == 0
    schedule, id_fps, _ = synthetic_churn_schedule(
        capacity, n, SETTINGS, start=10, burst=burst, period=period)
    member = np.zeros(capacity, bool)
    member[:n] = True
    state = init_state(_synthetic_uids(capacity), id_fp_sum=0,
                       settings=SETTINGS, member=member, id_fps=id_fps)
    faults = crash_faults([I32_MAX] * capacity)
    (base_final, base_logs), (s_final, s_logs) = _run_pair(
        mesh, state, faults, ticks, churn=schedule)
    _assert_tree_equal(base_logs, s_logs, "churn logs")
    _assert_tree_equal(base_final, s_final, "churn final state")
    _assert_partitioned(s_final)
    # The scenario must actually reconfigure the view at least twice.
    assert int(np.asarray(s_logs.decide_now).sum()) >= 2


# ---------------------------------------------------------------------------
# the program is really partitioned: jaxpr + lowered HLO evidence
# ---------------------------------------------------------------------------


def _walk_eqns(jaxpr):
    """Yield every eqn in a jaxpr, recursing into sub-jaxprs."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            vals = v if isinstance(v, (list, tuple)) else (v,)
            for sub in vals:
                if hasattr(sub, "jaxpr"):
                    yield from _walk_eqns(sub.jaxpr)


def _constraint_specs(fn, *args):
    """PartitionSpecs of every sharding-constraint eqn in fn's jaxpr."""
    jaxpr = jax.make_jaxpr(fn)(*args).jaxpr
    specs = []
    for eqn in _walk_eqns(jaxpr):
        if "sharding_constraint" in eqn.primitive.name:
            sh = eqn.params.get("sharding")
            if sh is not None and hasattr(sh, "spec"):
                specs.append(tuple(sh.spec))
    return specs


def test_cut_aggregate_fixpoint_stays_sharded(mesh):
    """The while_loop body of the report fixpoint re-commits P('slots')
    on the [C, K] report matrix — the reduction never collapses to an
    all-gathered layout between iterations."""
    n = 64
    state = init_state(_synthetic_uids(n), id_fp_sum=0, settings=SETTINGS)
    k = SETTINGS.K
    down = jnp.zeros((n, k), bool)
    up = jnp.zeros((n, k), bool)

    specs = _constraint_specs(
        lambda st, d, u: cut.aggregate(jnp, st, d, u, jnp.asarray(True),
                                       SETTINGS, mesh=mesh),
        state, down, up)
    assert (sharding.AXIS,) in specs, \
        f"no slot-axis constraint inside cut.aggregate: {specs}"


def test_vote_count_tally_stays_sharded(mesh):
    """The scattered per-slot vote tally re-partitions over 'slots'."""
    n = 64
    hi = jnp.arange(n, dtype=jnp.uint32)
    lo = jnp.arange(n, dtype=jnp.uint32)
    valid = jnp.ones((n,), bool)
    specs = _constraint_specs(
        lambda a, b, v: votes.segmented_vote_count(jnp, a, b, v, mesh=mesh),
        hi, lo, valid)
    assert (sharding.AXIS,) in specs, \
        f"no slot-axis constraint in segmented_vote_count: {specs}"


def test_step_hlo_carries_device_sharding(mesh):
    """The lowered tick program annotates arrays with the 8-device
    sharding — partitioning survives all the way into HLO, it is not a
    tracing-only fiction."""
    n = 64
    state = init_state(_synthetic_uids(n), id_fp_sum=0, settings=SETTINGS)
    faults = crash_faults([I32_MAX] * n)

    lowered = jax.jit(
        lambda st, fa: step_mod.step(st, fa, SETTINGS, mesh=mesh)
    ).lower(state, faults)
    txt = lowered.as_text()
    assert "devices=[" in txt and "Sharding" in txt, \
        "lowered step HLO carries no device-sharding annotations"

    # And the whole scanned program, with the carry constrained:
    sim_lowered = jax.jit(
        lambda st, fa: step_mod._simulate.__wrapped__(
            st, fa, 16, SETTINGS, None, None, mesh)
    ).lower(state, faults)
    assert "devices=[" in sim_lowered.as_text()


def test_unsharded_jaxpr_is_unchanged():
    """mesh=None must compile every constraint out — the single-device
    program contains no sharding-constraint eqns at all."""
    n = 16
    state = init_state(_synthetic_uids(n), id_fp_sum=0, settings=SETTINGS)
    faults = crash_faults([I32_MAX] * n)
    specs = _constraint_specs(
        lambda st, fa: step_mod.step(st, fa, SETTINGS), state, faults)
    assert specs == []


# ---------------------------------------------------------------------------
# fleet x mesh composition (F=4)
# ---------------------------------------------------------------------------


def test_fleet_composes_with_mesh_f4(mesh):
    """A vmapped 4-member campaign on the mesh == the unsharded fleet,
    bit for bit, with each member's slot axis partitioned."""
    n, ticks = 16, 80
    members = [fleet_mod.lower_schedule(
        random_adversary_schedule(n, seed=s, ticks=ticks), SETTINGS)
        for s in (2, 5, 9, 13)]
    fleet = fleet_mod.stack_members(members)

    base_finals, base_logs = fleet_mod.fleet_simulate(fleet, ticks, SETTINGS)
    s_finals, s_logs = fleet_mod.fleet_simulate(fleet, ticks, SETTINGS,
                                                mesh=mesh)
    _assert_tree_equal(base_logs, s_logs, "fleet logs")
    _assert_tree_equal(base_finals, s_finals, "fleet final states")

    # [F, C] leaves shard the slot axis, replicate the fleet axis.
    spec = tuple(s_finals.member.sharding.spec)
    assert sharding.AXIS in spec and spec[0] is None, \
        f"fleet member axis not replicated / slot axis not sharded: {spec}"


# ---------------------------------------------------------------------------
# spec_for / slot_mesh unit behavior
# ---------------------------------------------------------------------------


def test_spec_for_shards_only_the_capacity_axis(mesh):
    c = 64
    assert sharding.spec_for((c,), c, mesh) == P(sharding.AXIS)
    assert sharding.spec_for((c, SETTINGS.K), c, mesh) == P(sharding.AXIS)
    # trailing capacity axis ([W, C], [I, P, C]) shards that axis
    assert sharding.spec_for((3, c), c, mesh) == P(None, sharding.AXIS)
    assert sharding.spec_for((2, 5, c), c, mesh) == \
        P(None, None, sharding.AXIS)
    # scalars, static LUTs, and capacity-free shapes replicate
    assert sharding.spec_for((), c, mesh) == P()
    assert sharding.spec_for((256, 8), c, mesh) == P()
    # non-divisible capacity falls back to full replication
    assert sharding.spec_for((60,), 60, mesh) == P()


def test_slot_mesh_rejects_oversized_request():
    with pytest.raises(ValueError, match="xla_force_host_platform"):
        sharding.slot_mesh(len(jax.devices()) + 1)


def test_shard_put_places_state_on_mesh(mesh):
    n = 32
    state = init_state(_synthetic_uids(n), id_fp_sum=0, settings=SETTINGS)
    placed = sharding.shard_put(state, mesh, n)
    assert sharding.AXIS in tuple(placed.member.sharding.spec)
    assert sharding.AXIS in tuple(placed.reports.sharding.spec)
    # scalar leaves (the tick counter, config-id limbs) stay replicated
    shardings = sharding.state_shardings(state, mesh)
    assert tuple(shardings.tick.spec) == ()


# ---------------------------------------------------------------------------
# fleet-axis sharding: P('fleet') over whole members (campaign layout)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fleet_mesh():
    if len(jax.devices()) < N_DEVICES:
        pytest.skip("needs the conftest-forced 8-device CPU mesh")
    return sharding.fleet_axis_mesh(N_DEVICES)


def test_fleet_axis_parity_shared_f8(fleet_mesh):
    """An F=8 shared-state fleet with one member per device == the
    unsharded fleet, bit for bit, and the member axis is genuinely
    P('fleet') on both inputs and outputs."""
    n, ticks = 16, 80
    members = [fleet_mod.lower_schedule(
        random_adversary_schedule(n, seed=s, ticks=ticks), SETTINGS)
        for s in range(8)]
    fleet = fleet_mod.stack_members(members)

    base_finals, base_logs = fleet_mod.fleet_simulate(fleet, ticks,
                                                      SETTINGS)
    placed = sharding.fleet_axis_put(fleet, fleet_mesh, 8)
    s_finals, s_logs = fleet_mod.fleet_simulate(placed, ticks, SETTINGS,
                                                fleet_mesh=fleet_mesh)
    _assert_tree_equal(base_logs, s_logs, "fleet-axis logs")
    _assert_tree_equal(base_finals, s_finals, "fleet-axis final states")
    assert tuple(placed.state.member.sharding.spec)[0] == \
        sharding.FLEET_AXIS
    assert tuple(s_finals.member.sharding.spec)[0] == sharding.FLEET_AXIS


def test_fleet_axis_parity_receiver_f8(fleet_mesh):
    """The per-receiver fleet path shards its member axis the same way
    and stays bit-identical."""
    from rapid_tpu.faults import (SCENARIO_KINDS, ScenarioWeights,
                                  sample_adversary_schedule)

    link_weights = ScenarioWeights(
        **{k: (1.0 if k in ("partition", "flip_flop") else 0.0)
           for k in SCENARIO_KINDS})
    schedules = [sample_adversary_schedule(16, s, 80, link_weights).schedule
                 for s in range(8)]
    members = [fleet_mod.lower_receiver_schedule(s, SETTINGS)
               for s in schedules]
    fleet = fleet_mod.stack_receiver_members(members)

    base_finals, base_logs = fleet_mod.receiver_fleet_simulate(
        fleet, 80, SETTINGS)
    placed = sharding.fleet_axis_put(fleet, fleet_mesh, 8)
    s_finals, s_logs = fleet_mod.receiver_fleet_simulate(
        placed, 80, SETTINGS, fleet_mesh=fleet_mesh)
    _assert_tree_equal(base_logs, s_logs, "rx fleet-axis logs")
    _assert_tree_equal(base_finals, s_finals, "rx fleet-axis finals")
    assert tuple(s_finals.member.sharding.spec)[0] == sharding.FLEET_AXIS


def test_fleet_axis_spec_unit(fleet_mesh):
    """Axis 0 shards iff it is the fleet axis and divides the mesh;
    everything else — scalars, constants, non-dividing fleets —
    replicates."""
    assert sharding.fleet_axis_spec_for((8,), 8, fleet_mesh) == \
        P(sharding.FLEET_AXIS)
    assert sharding.fleet_axis_spec_for((8, 24, 24), 8, fleet_mesh) == \
        P(sharding.FLEET_AXIS)
    # a non-dividing fleet replicates (divisibility guard)
    assert sharding.fleet_axis_spec_for((6, 24), 6, fleet_mesh) == P()
    # a leaf without the fleet axis (static LUT) replicates
    assert sharding.fleet_axis_spec_for((256, 8), 8, fleet_mesh) == P()
    assert sharding.fleet_axis_spec_for((), 8, fleet_mesh) == P()


def test_fleet_axis_excludes_slot_mesh(mesh, fleet_mesh):
    """The two layouts are mutually exclusive per dispatch — asking for
    both is a contract violation, not silent precedence."""
    n = 16
    members = [fleet_mod.lower_schedule(
        random_adversary_schedule(n, seed=s, ticks=40), SETTINGS)
        for s in range(2)]
    fleet = fleet_mod.stack_members(members)
    with pytest.raises(ValueError, match="mutually exclusive"):
        step_mod.fleet_body(fleet.state, fleet.faults, fleet.churn,
                            fleet.fallback, 40, SETTINGS, mesh=mesh,
                            fleet_mesh=fleet_mesh)


def test_fleet_axis_default_path_traces_no_constraints():
    """fleet_mesh=None must trace the byte-identical pre-sharding
    jaxpr — zero sharding-constraint eqns on the default path."""
    n = 16
    members = [fleet_mod.lower_schedule(
        random_adversary_schedule(n, seed=s, ticks=40), SETTINGS)
        for s in range(2)]
    fleet = fleet_mod.stack_members(members)
    specs = _constraint_specs(
        lambda st, fa, ch, fb: step_mod.fleet_body(st, fa, ch, fb, 40,
                                                   SETTINGS),
        fleet.state, fleet.faults, fleet.churn, fleet.fallback)
    assert specs == []
