"""Two-phase join gatekeeper edge cases (oracle).

The seed's phase-1 verdict and the gatekeepers' phase-2 config check are
the paths the churn planner (``rapid_tpu.engine.churn``) mirrors
host-side; these tests pin the oracle behaviors it relies on: departed
UUIDs stay burned forever, stale phase-2 configs answer CONFIG_CHANGED
(or stream the configuration when the joiner already made it in), and a
join colliding with an in-progress cut proposal still converges through
the retry machinery.
"""
import random

from rapid_tpu.faults import CrashFault
from rapid_tpu.oracle.cluster import Cluster, default_rng
from rapid_tpu.oracle.simulation import SimNetwork
from rapid_tpu.settings import Settings
from rapid_tpu.types import Endpoint, JoinMessage, JoinStatusCode, NodeId

SETTINGS = Settings()


def ep(i: int) -> Endpoint:
    return Endpoint("10.0.0.1", 1234 + i)


def node_id_of(i: int) -> NodeId:
    """Replicate the first identifier a cluster at ep(i) draws."""
    rng = default_rng(SETTINGS, ep(i))
    return NodeId(rng.getrandbits(64), rng.getrandbits(64))


def wait_until(network: SimNetwork, predicate, max_ticks: int = 1000) -> bool:
    for _ in range(max_ticks):
        if predicate():
            return True
        network.step()
    return predicate()


def boot(network: SimNetwork, n: int):
    clusters = [Cluster(network, ep(0), SETTINGS).start()]
    for i in range(1, n):
        c = Cluster(network, ep(i), SETTINGS)
        c.join(ep(0))
        assert wait_until(network, lambda: c.is_active, 500)
        clusters.append(c)
    return clusters


class ScriptedRng(random.Random):
    """Yields a fixed prefix of getrandbits values, then real randomness."""

    def __new__(cls, script, seed=12345):
        return super().__new__(cls, seed)

    def __init__(self, script, seed=12345):
        super().__init__(seed)
        self._script = list(script)

    def getrandbits(self, k):
        if self._script:
            return self._script.pop(0)
        return super().getrandbits(k)


def test_rejoin_with_departed_uuid_retries_to_success():
    network = SimNetwork(SETTINGS)
    clusters = boot(network, 4)
    leaver = clusters[2]
    departed_id = node_id_of(2)
    assert clusters[0].membership_service.view.is_identifier_present(
        departed_id)

    leaver.leave_gracefully()
    assert wait_until(
        network, lambda: clusters[0].get_membership_size() == 3, 200)
    # The identifier stays burned even though the host slot is free.
    view = clusters[0].membership_service.view
    assert view.is_safe_to_join(ep(9), departed_id) \
        is JoinStatusCode.UUID_ALREADY_IN_RING

    # A joiner whose rng re-draws the departed UUID must burn one attempt
    # on UUID_ALREADY_IN_RING and succeed with the next identifier.
    rejoiner = Cluster(network, ep(9), SETTINGS,
                       rng=ScriptedRng([departed_id.high, departed_id.low]))
    rejoiner.join(ep(0))
    assert wait_until(network, lambda: rejoiner.is_active, 500)
    assert rejoiner.get_membership_size() == 4
    assert not rejoiner.join_failed
    assert not clusters[0].membership_service.view.is_host_present(ep(2))


def test_rejoin_same_endpoint_after_leave():
    network = SimNetwork(SETTINGS)
    clusters = boot(network, 4)
    leaver = clusters[1]
    leaver.leave_gracefully()
    assert wait_until(
        network, lambda: clusters[0].get_membership_size() == 3, 200)

    back = Cluster(network, ep(1), SETTINGS)
    back.join(ep(0))
    assert wait_until(network, lambda: back.is_active, 500)
    assert clusters[0].get_membership_size() == 4


def test_phase2_stale_config_answers_config_changed():
    network = SimNetwork(SETTINGS)
    clusters = boot(network, 3)
    service = clusters[0].membership_service

    replies = []
    service._handle_join_phase2(JoinMessage(
        sender=ep(7), node_id=NodeId(1, 2), configuration_id=0xDEAD,
        ring_numbers=(0,), metadata=()), replies.append)
    assert len(replies) == 1
    assert replies[0].status_code is JoinStatusCode.CONFIG_CHANGED
    assert replies[0].configuration_id \
        == service.view.get_current_configuration_id()
    # No UP alert was parked for the stale joiner.
    assert ep(7) not in service._joiners_to_respond_to


def test_phase2_stale_config_streams_already_added_joiner():
    network = SimNetwork(SETTINGS)
    clusters = boot(network, 3)
    service = clusters[0].membership_service
    member_ep = ep(1)
    member_id = node_id_of(1)

    replies = []
    service._handle_join_phase2(JoinMessage(
        sender=member_ep, node_id=member_id, configuration_id=0xDEAD,
        ring_numbers=(0,), metadata=()), replies.append)
    assert len(replies) == 1
    assert replies[0].status_code is JoinStatusCode.SAFE_TO_JOIN
    assert replies[0].configuration_id \
        == service.view.get_current_configuration_id()
    assert set(replies[0].endpoints) == {ep(0), ep(1), ep(2)}
    assert member_id in replies[0].identifiers


def test_join_during_in_progress_cut_proposal_converges():
    crash_at = 30
    network = SimNetwork(SETTINGS, CrashFault({ep(3): crash_at}))
    clusters = boot(network, 8)
    boot_done = network.tick

    # The crash is detected at the first FD multiple past the boot churn;
    # launch joins shortly before the proposal pipeline so the phase-1/2
    # exchanges straddle the announced cut. Some attempts eat
    # CONFIG_CHANGED and retry; all must converge.
    detect_eta = ((boot_done // SETTINGS.fd_interval_ticks) + 1) \
        * SETTINGS.fd_interval_ticks \
        + SETTINGS.fd_failure_threshold * SETTINGS.fd_interval_ticks
    joiners = [Cluster(network, ep(20 + i), SETTINGS) for i in range(2)]
    for i, joiner in enumerate(joiners):
        network.at(detect_eta - 1 + i, lambda c=joiner: c.join(ep(0)))

    assert wait_until(
        network,
        lambda: all(j.is_active for j in joiners)
        and clusters[0].get_membership_size() == 9,
        1500)
    sizes = {c.get_membership_size()
             for c in clusters + joiners if c is not clusters[3]}
    assert sizes == {9}  # 8 booted - 1 crashed + 2 joined
    assert not any(j.join_failed for j in joiners)
