"""Streaming observatory: load servo, SLO windows, status API.

The load-bearing proofs:

- the servo's control law is quantized and deterministic: pinned
  throughput makes the whole closed loop a pure function of the target
  (identical rate traces whatever walls it observes), and the state
  dict round-trips exactly;
- closed-loop traffic sampling is chunk-split invariant *including
  mid-stream retargeting*: the rng stream advances one uniform per tick
  whatever the rate, so a servo-driven resident run is bit-identical
  across chunkings (the full pipeline test runs two residents at
  different ``stream_chunk_ticks`` under a pinned servo);
- forced saturation behaves: a target far past what burst admission can
  lower produces a monotonically growing backlog, which the sweep's
  slope rule classifies as unstable;
- the rolling SLO windows are bounded and exact: nearest-rank
  percentiles over fixed bucket edges, eviction after
  ``window_chunks``, and both view-change folds (engine stream,
  per-slot receiver) are chunk-boundary invariant;
- the status API never perturbs the protocol stream: a resident run
  with the file + socket publishers attached emits byte-identical
  non-wall JSONL to one without, and the socket serves ``status`` /
  ``watch`` / unknown-command correctly;
- the new schema v10 validators accept the shapes the service emits and
  reject the mutations they exist to catch.
"""
import json
import os
import threading

import numpy as np
import pytest

from rapid_tpu.service import (LoadServo, ServoConfig, StatusPublisher,
                               TrafficConfig, TrafficGenerator,
                               boot_resident, read_status)
from rapid_tpu.settings import Settings
from rapid_tpu.telemetry.slo import (DEFAULT_BUCKET_EDGES,
                                     ReceiverViewChangeFold, SloWindows,
                                     ViewChangeFold)
from rapid_tpu.telemetry.schema import (validate_load_sweep,
                                        validate_slo_window,
                                        validate_status_snapshot,
                                        validate_streaming_stream)

SETTINGS = Settings()


# ---------------------------------------------------------------------------
# servo control law
# ---------------------------------------------------------------------------


def test_servo_config_validates():
    with pytest.raises(ValueError):
        ServoConfig(target_events_per_sec=0.0)
    with pytest.raises(ValueError):
        ServoConfig(target_events_per_sec=10.0, gain=0.0)
    with pytest.raises(ValueError):
        ServoConfig(target_events_per_sec=10.0, rate_quantum_per_ktick=0.0)
    with pytest.raises(ValueError):
        ServoConfig(target_events_per_sec=10.0, min_rate_per_ktick=2.0,
                    max_rate_per_ktick=1.0)
    with pytest.raises(ValueError):
        ServoConfig(target_events_per_sec=10.0, pinned_ticks_per_sec=-1.0)


def test_servo_rate_quantized_and_clamped():
    servo = LoadServo(ServoConfig(target_events_per_sec=10.0,
                                  initial_ticks_per_sec=1000.0))
    # 1000 * 10 / 1000 = 10 events/ktick, already on the 0.25 grid.
    assert servo.rate_per_ktick == 10.0
    # Every committed rate lands exactly on the quantum grid.
    servo.observe(ticks=512, wall_s=512 / 1537.0, backlog=0)
    q = servo.config.rate_quantum_per_ktick
    assert servo.rate_per_ktick == round(servo.rate_per_ktick / q) * q
    # An absurd target clamps at the rate ceiling.
    hot = LoadServo(ServoConfig(target_events_per_sec=1e9,
                                initial_ticks_per_sec=1000.0))
    assert hot.rate_per_ktick == hot.config.max_rate_per_ktick


def test_servo_pinned_is_pure_function_of_target():
    cfg = ServoConfig(target_events_per_sec=80.0,
                      pinned_ticks_per_sec=4000.0)
    a, b = LoadServo(cfg), LoadServo(cfg)
    # Feed the two servos wildly different measured walls: pinned
    # throughput must ignore them all, so the rate trace depends on the
    # target alone.
    for wall in (0.01, 3.0, 0.5, 120.0):
        a.observe(ticks=512, wall_s=wall, backlog=0)
        b.observe(ticks=512, wall_s=wall * 7 + 0.2, backlog=0)
        assert a.rate_per_ktick == b.rate_per_ktick == 20.0
        assert a.ticks_per_sec_estimate == 4000.0
    assert a.updates == b.updates == 0


def test_servo_skips_unmeasurable_walls_and_tracks_backlog():
    servo = LoadServo(ServoConfig(target_events_per_sec=10.0))
    before = servo.rate_per_ktick
    servo.observe(ticks=512, wall_s=1e-9, backlog=17)
    assert servo.updates == 0 and servo.rate_per_ktick == before
    assert servo.backlog == 17
    servo.observe(ticks=512, wall_s=0.25, backlog=3)
    assert servo.updates == 1 and servo.backlog == 3


def test_servo_state_dict_round_trip():
    servo = LoadServo(ServoConfig(target_events_per_sec=42.0))
    servo.observe(ticks=512, wall_s=0.1, backlog=5)
    twin = LoadServo.from_state(servo.state_dict())
    assert twin.state_dict() == servo.state_dict()
    assert twin.rate_per_ktick == servo.rate_per_ktick
    assert twin.ticks_per_sec_estimate == servo.ticks_per_sec_estimate


# ---------------------------------------------------------------------------
# closed-loop sampling: rate-independent rng advancement
# ---------------------------------------------------------------------------


def _drain_chunks(gen, total, chunk, retarget=None):
    """Run ``total`` ticks in ``chunk``-sized windows, collecting
    (kind, tick, slot) event tuples; ``retarget`` maps a tick boundary
    to a new join rate applied there."""
    from rapid_tpu.engine.state import I32_MAX

    events = []
    for start in range(0, total, chunk):
        if retarget and start in retarget:
            gen.set_join_rate(retarget[start])
        schedule, _ = gen.next_chunk(chunk)
        if schedule is None:
            continue
        for kind, ticks in (("join", schedule.join_tick),
                            ("leave", schedule.leave_tick)):
            for slot, tick in enumerate(np.asarray(ticks)):
                if tick != I32_MAX:
                    events.append((kind, int(tick), slot))
    return sorted(events)


def _closed_gen(rate=40.0):
    cfg = TrafficConfig(seed=11, join_rate_per_ktick=rate,
                        leave_burst_rate_per_ktick=4.0, leave_burst_size=2,
                        closed_loop=True)
    return TrafficGenerator(cfg, SETTINGS, 32, 12)


def test_closed_loop_chunk_split_invariant_under_retargeting():
    # Same seed, same retarget schedule (rate doubles at tick 256),
    # different chunkings: the drawn event streams must be identical —
    # closed-loop sampling consumes exactly one uniform per tick
    # whatever the rate, so retargeting never shifts the stream.
    retarget = {256: 80.0}
    a = _drain_chunks(_closed_gen(), 512, 64, retarget)
    b = _drain_chunks(_closed_gen(), 512, 256, retarget)
    assert a == b
    assert a, "expected the closed-loop stream to draw events"


def test_open_loop_rejects_retargeting():
    cfg = TrafficConfig(seed=0, join_rate_per_ktick=10.0)
    gen = TrafficGenerator(cfg, SETTINGS, 24, 10)
    with pytest.raises(ValueError):
        gen.set_join_rate(20.0)
    with pytest.raises(ValueError):
        _closed_gen().set_join_rate(-1.0)


def test_resident_closed_loop_chunk_split_invariance():
    # The full-pipeline form of the invariance: two servo-driven
    # residents (pinned throughput model, so the rate trace is a pure
    # function of the target) at different chunk sizes reach the same
    # tick with bit-identical engine state.
    def run(chunk_ticks, n_chunks):
        settings = SETTINGS.with_(stream_chunk_ticks=chunk_ticks)
        traffic = TrafficConfig(seed=5, join_rate_per_ktick=0.0,
                                leave_burst_rate_per_ktick=4.0,
                                leave_burst_size=2, closed_loop=True)
        servo = LoadServo(ServoConfig(target_events_per_sec=60.0,
                                      pinned_ticks_per_sec=2000.0))
        eng = boot_resident(settings, 24, 10, seed=0,
                            traffic_config=traffic, servo=servo,
                            write_ticks=False)
        eng.run(n_chunks)
        eng.flush()
        state = eng.state
        eng.close()
        return state

    import jax

    a = run(32, 8)
    b = run(64, 4)
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        assert np.array_equal(np.asarray(x), np.asarray(y))


def test_forced_saturation_backlog_grows_monotonically():
    # A target far past what burst admission can lower: the servo pins
    # the rate at its ceiling and the offered-minus-applied backlog must
    # grow monotonically — the signature the load sweep classifies as
    # unstable.
    settings = SETTINGS.with_(stream_chunk_ticks=64)
    traffic = TrafficConfig(seed=3, join_rate_per_ktick=0.0,
                            leave_burst_rate_per_ktick=0.0,
                            closed_loop=True)
    servo = LoadServo(ServoConfig(target_events_per_sec=1e6,
                                  pinned_ticks_per_sec=1000.0))
    eng = boot_resident(settings, 24, 10, seed=0, traffic_config=traffic,
                        servo=servo, write_ticks=False)
    eng.run(6)
    eng.flush()
    backlogs = [r["servo"]["backlog"] for r in eng.chunk_records]
    eng.close()
    assert all(b2 >= b1 for b1, b2 in zip(backlogs, backlogs[1:]))
    assert backlogs[-1] > backlogs[0] > 0
    # The sweep's slope rule calls this unstable at any sane threshold.
    slope = (backlogs[-1] - backlogs[0]) / (len(backlogs) - 1)
    assert slope > 5.0


# ---------------------------------------------------------------------------
# rolling SLO windows
# ---------------------------------------------------------------------------


def test_slo_window_percentiles_nearest_rank():
    slo = SloWindows(window_chunks=4)
    block = slo.fold_chunk({"decide_latency": [1, 2, 3, 100],
                            "ticks_to_view_change": [500] * 99 + [4000]})
    lat = block["metrics"]["decide_latency"]
    # Samples 1,2,3,100 land in buckets with edges 1,2,4,128.
    assert lat["count"] == 4
    assert lat["p50"] == 2 and lat["p95"] == 128 and lat["p99"] == 128
    ttvc = block["metrics"]["ticks_to_view_change"]
    assert ttvc["p50"] == 512 and ttvc["p99"] == 512
    assert ttvc["counts"][DEFAULT_BUCKET_EDGES.index(4096)] == 1
    assert validate_slo_window(block) == []


def test_slo_window_evicts_beyond_window():
    slo = SloWindows(window_chunks=2)
    slo.fold_chunk({"decide_latency": [1000]})
    slo.fold_chunk({"decide_latency": [1]})
    block = slo.fold_chunk({"decide_latency": [1]})
    lat = block["metrics"]["decide_latency"]
    # The 1000-tick sample fell out of the 2-chunk window.
    assert lat["count"] == 2 and lat["p99"] == 1
    assert block["chunks"] == 2
    empty = SloWindows(window_chunks=2).block()
    assert empty["metrics"]["decide_latency"]["p50"] is None


def test_slo_state_dict_round_trip():
    slo = SloWindows(window_chunks=3)
    slo.fold_chunk({"decide_latency": [5, 7], "ticks_to_view_change": [9]})
    twin = SloWindows.from_state(slo.state_dict())
    assert twin.block() == slo.block()


class _Row:
    def __init__(self, tick, announce=False, decide=False):
        self.tick = tick
        self.announce = announce
        self.decide = decide


def test_view_change_fold_chunk_boundary_invariant():
    rows = [_Row(0), _Row(3, announce=True), _Row(7, decide=True),
            _Row(12, announce=True), _Row(13, announce=True),
            _Row(20, decide=True), _Row(31, decide=True)]
    whole = ViewChangeFold(0).fold(rows)
    assert whole["ticks_to_view_change"] == [7, 13, 11]
    assert whole["decide_latency"] == [4, 7]

    split = ViewChangeFold(0)
    merged = {"ticks_to_view_change": [], "decide_latency": []}
    for cut in (rows[:2], rows[2:5], rows[5:]):
        part = split.fold(cut)
        for key in merged:
            merged[key].extend(part[key])
    assert merged == whole


def test_receiver_view_change_fold_per_slot_and_split_invariant():
    ticks = np.arange(8)
    announce = np.zeros((8, 3), bool)
    decide = np.zeros((8, 3), bool)
    announce[1, 0] = True
    decide[3, 0] = True      # slot 0: announce@1 -> decide@3
    decide[5, [0, 2]] = True  # slot 0 again (no announce), slot 2 cold
    announce[6, 1] = True
    decide[7, 1] = True      # slot 1: announce@6 -> decide@7

    whole = ReceiverViewChangeFold(3).fold(ticks, announce, decide)
    assert whole["ticks_to_view_change"] == [3, 2, 5, 7]
    assert whole["decide_latency"] == [2, 1]

    split = ReceiverViewChangeFold(3)
    merged = {"ticks_to_view_change": [], "decide_latency": []}
    for lo, hi in ((0, 4), (4, 6), (6, 8)):
        part = split.fold(ticks[lo:hi], announce[lo:hi], decide[lo:hi])
        for key in merged:
            merged[key].extend(part[key])
    assert merged == whole
    twin = ReceiverViewChangeFold.from_state(split.state_dict())
    assert twin.state_dict() == split.state_dict()


# ---------------------------------------------------------------------------
# status API
# ---------------------------------------------------------------------------


def test_status_file_and_socket_serve_latest(tmp_path):
    file_path = str(tmp_path / "status.json")
    sock_path = str(tmp_path / "status.sock")
    pub = StatusPublisher(file_path=file_path, socket_path=sock_path)
    try:
        pub.publish({"record": "status_snapshot", "tick": 1})
        pub.publish({"record": "status_snapshot", "tick": 2})
        with open(file_path) as fh:
            assert json.load(fh)["tick"] == 2
        assert read_status(sock_path)[0]["tick"] == 2
        err = read_status(sock_path, command="bogus")[0]
        assert "error" in err
    finally:
        pub.close()
    assert not os.path.exists(sock_path)


def test_status_watch_streams_subsequent_snapshots(tmp_path):
    sock_path = str(tmp_path / "status.sock")
    pub = StatusPublisher(socket_path=sock_path)
    try:
        pub.publish({"tick": 1})
        got = []
        done = threading.Event()

        def subscriber():
            got.extend(read_status(sock_path, command="watch",
                                   max_lines=3, timeout=10.0))
            done.set()

        t = threading.Thread(target=subscriber, daemon=True)
        t.start()
        # The subscriber receives the latest snapshot at subscription
        # time, then every subsequent publish (it may register between
        # publishes, so only monotonicity is deterministic here).
        for tick in (2, 3, 4, 5):
            pub.publish({"tick": tick})
            if done.wait(0.05):
                break
        assert done.wait(10.0)
        t.join(10.0)
        assert len(got) == 3
        ticks = [s["tick"] for s in got]
        assert ticks == sorted(ticks) and ticks[0] >= 1
    finally:
        pub.close()


def _wall_free(record):
    """Strip the wall-clock-derived fields (and the process-global
    live-buffer gauge) so what remains is the deterministic protocol
    stream."""
    drop = {"wall_s", "compile_s", "ticks_per_sec", "events_per_sec",
            "live_buffer_bytes", "ticks_per_sec_estimate"}
    if not isinstance(record, dict):
        return record
    return {k: _wall_free(v) for k, v in record.items() if k not in drop}


def test_status_publisher_does_not_perturb_stream(tmp_path):
    # The non-perturbation proof: one servo-driven resident run with the
    # status file + socket attached, one without, pinned throughput so
    # the servo trace is deterministic — the non-wall JSONL fields must
    # be identical line for line.
    def run(status):
        sink = str(tmp_path / ("with.jsonl" if status else "without.jsonl"))
        settings = SETTINGS.with_(stream_chunk_ticks=32)
        traffic = TrafficConfig(seed=9, join_rate_per_ktick=0.0,
                                leave_burst_rate_per_ktick=4.0,
                                leave_burst_size=2, closed_loop=True)
        servo = LoadServo(ServoConfig(target_events_per_sec=50.0,
                                      pinned_ticks_per_sec=2000.0))
        eng = boot_resident(settings, 24, 10, seed=0,
                            traffic_config=traffic, servo=servo,
                            slo=SloWindows(window_chunks=4),
                            status=status, sink=sink, write_ticks=False)
        eng.run(4)
        eng.summary()
        eng.close()
        with open(sink) as fh:
            return fh.readlines()

    pub = StatusPublisher(file_path=str(tmp_path / "status.json"),
                          socket_path=str(tmp_path / "status.sock"))
    with_status = run(pub)
    without = run(None)
    assert len(with_status) == len(without)
    for line_a, line_b in zip(with_status, without):
        assert _wall_free(json.loads(line_a)) == _wall_free(json.loads(line_b))
    assert validate_streaming_stream(with_status) == []
    # The published file is itself a valid status snapshot.
    with open(tmp_path / "status.json") as fh:
        assert validate_status_snapshot(json.load(fh)) == []


class _RecordingPublisher:
    """Duck-typed StatusPublisher that records frames instead of
    serving them — makes heartbeat-cadence assertions deterministic."""

    def __init__(self):
        self.frames = []

    def publish(self, snapshot):
        self.frames.append(snapshot)

    def close(self):
        pass


def test_status_frame_every_chunk_even_with_zero_view_changes(tmp_path):
    # A quiet resident (no traffic, no faults) closes every chunk with
    # zero view changes. Watch subscribers must still get one frame per
    # chunk — the heartbeat itself is the signal that the service is
    # alive, not the view changes inside it.
    pub = _RecordingPublisher()
    eng = boot_resident(SETTINGS.with_(stream_chunk_ticks=32), 24, 10,
                        seed=0, status=pub,
                        sink=str(tmp_path / "quiet.jsonl"),
                        write_ticks=False)
    eng.run(4)
    eng.close()
    assert len(pub.frames) == 4
    for frame in pub.frames:
        assert validate_status_snapshot(frame) == []
        assert frame["lineage"]["spans"] == 0


def test_rx_resident_status_frame_every_chunk(tmp_path):
    from rapid_tpu.service import boot_resident_receiver

    pub = _RecordingPublisher()
    eng = boot_resident_receiver(SETTINGS, 16, seed=3, horizon_ticks=64,
                                 chunk_ticks=16, status=pub,
                                 sink=str(tmp_path / "rx.jsonl"))
    eng.run(4)
    eng.close()
    assert len(pub.frames) == 4
    for frame in pub.frames:
        assert validate_status_snapshot(frame) == []
        assert frame["source"] == "resident_receiver"
        assert frame["lineage"] is not None


# ---------------------------------------------------------------------------
# schema v10 validators
# ---------------------------------------------------------------------------


def _sweep_payload():
    def rate(target, stable):
        slo = SloWindows(window_chunks=4)
        block = slo.fold_chunk({"decide_latency": [3],
                                "ticks_to_view_change": [40]})
        cfg = ServoConfig(target_events_per_sec=target,
                          pinned_ticks_per_sec=2000.0)
        return {"target_events_per_sec": target,
                "achieved_events_per_sec": target * 0.97,
                "rate_per_ktick": 0.5 * target / 2.0,
                "ticks_per_sec": 2000.0,
                "chunks": 4, "events": 40,
                "backlog_final": 0 if stable else 400,
                "backlog_slope_per_chunk": 0.0 if stable else 99.0,
                "stable": stable,
                "servo_config": cfg.as_dict(),
                "slo": block}

    from rapid_tpu.telemetry.schema import SCHEMA_VERSION
    return {"record": "load_sweep", "schema_version": SCHEMA_VERSION,
            "n": 24, "capacity": 96, "chunk_ticks": 512,
            "chunks_per_rate": 4, "warmup_chunks": 1, "seed": 0,
            "backlog_slope_threshold": 5.0,
            "targets": [50.0, 100.0, 800.0],
            "rates": [rate(50.0, True), rate(100.0, True),
                      rate(800.0, False)],
            "knee": {"target_events_per_sec": 100.0,
                     "achieved_events_per_sec": 97.0,
                     "ticks_to_view_change_p99": 64},
            "wall_s": 12.5}


def test_validate_load_sweep_accepts_and_rejects():
    payload = _sweep_payload()
    assert validate_load_sweep(payload) == []
    wrong_knee = json.loads(json.dumps(payload))
    wrong_knee["knee"]["target_events_per_sec"] = 50.0
    assert any("knee" in e for e in validate_load_sweep(wrong_knee))
    missing_rate = json.loads(json.dumps(payload))
    missing_rate["rates"] = missing_rate["rates"][:2]
    assert validate_load_sweep(missing_rate)
    no_knee = json.loads(json.dumps(payload))
    no_knee["knee"] = None
    assert any("knee" in e for e in validate_load_sweep(no_knee))


def test_validate_status_snapshot_rejects_wrong_record():
    snap = {"record": "not_a_snapshot"}
    assert validate_status_snapshot(snap)
