"""Per-kernel cost observatory: every sub-kernel lowers and reports
non-trivial XLA costs, the dominance report validates against the
telemetry schema, and the --profile-sweep CLI path round-trips."""
import json

import pytest

from rapid_tpu.settings import Settings
from rapid_tpu.telemetry import profile as tprofile
from rapid_tpu.telemetry import schema as tschema

SETTINGS = Settings()

ALL_KERNELS = set(tprofile.KERNEL_ORDER)
SUB_KERNELS = ALL_KERNELS - {"full_step"}


def test_kernel_cases_cover_the_step_with_and_without_fallback():
    from rapid_tpu.engine.paxos import empty_fallback_schedule

    state, faults = tprofile.synthetic_state(64, SETTINGS, warmup_ticks=2)
    lean = [name for name, _, _ in
            tprofile.kernel_cases(state, faults, SETTINGS, fallback=None)]
    assert lean == ["topology_rebuild", "monitor", "cut_aggregate",
                    "vote_count", "full_step"]
    c = int(state.member.shape[0])
    full = [name for name, _, _ in
            tprofile.kernel_cases(state, faults, SETTINGS,
                                  fallback=empty_fallback_schedule(c))]
    assert full == list(tprofile.KERNEL_ORDER)


def test_measure_kernel_reports_static_and_measured_costs():
    state, faults = tprofile.synthetic_state(64, SETTINGS, warmup_ticks=2)
    name, fn, args = tprofile.kernel_cases(state, faults, SETTINGS)[0]
    cost = tprofile.measure_kernel(name, fn, args, repeats=2)
    assert cost.kernel == "topology_rebuild"
    assert cost.flops > 0
    assert cost.bytes_accessed > 0
    assert cost.argument_bytes > 0
    assert cost.peak_bytes >= cost.argument_bytes
    assert cost.compile_s > 0
    assert 0 < cost.wall_best_s <= cost.wall_median_s
    assert cost.repeats == 2


def test_dominance_report_schema_and_dominants():
    report = tprofile.dominance_report([64], SETTINGS, repeats=1,
                                       warmup_ticks=2)
    assert tschema.validate_bench_payload(report) == []
    assert report["bench"] == "kernel_profile_sweep"
    assert report["schema_version"] == tschema.SCHEMA_VERSION
    assert report["sizes"] == [64]
    (run,) = report["runs"]
    assert run["n"] == 64
    assert {k["kernel"] for k in run["kernels"]} == ALL_KERNELS
    # full_step is the composed reference and never dominant
    for axis in ("wall_clock", "flops", "bytes"):
        assert run["dominant"][axis] in SUB_KERNELS
    assert report["dominant_by_n"] == {"64": run["dominant"]["wall_clock"]}
    assert run["subkernel_wall_fraction"] is None \
        or run["subkernel_wall_fraction"] > 0


def test_schema_rejects_corrupt_dominance_report():
    report = tprofile.dominance_report([32], SETTINGS, repeats=1,
                                       warmup_ticks=0,
                                       include_fallback=False)
    assert tschema.validate_bench_payload(report) == []
    # dominant kernel must name a profiled kernel
    bad = json.loads(json.dumps(report))
    bad["runs"][0]["dominant"]["wall_clock"] = "warp_drive"
    assert tschema.validate_bench_payload(bad)
    # schema_version is mandatory and pinned
    bad = json.loads(json.dumps(report))
    bad["schema_version"] = tschema.SCHEMA_VERSION + 1
    assert tschema.validate_bench_payload(bad)
    bad = json.loads(json.dumps(report))
    del bad["schema_version"]
    assert tschema.validate_bench_payload(bad)
    # a kernel row missing a cost field is rejected
    bad = json.loads(json.dumps(report))
    del bad["runs"][0]["kernels"][0]["flops"]
    assert tschema.validate_bench_payload(bad)


def test_profile_sweep_cli_writes_schema_valid_report(tmp_path):
    from benchmarks.bench_engine import main as bench_main

    out = tmp_path / "profile.json"
    rc = bench_main(["--profile-sweep", "--profile-sizes", "64",
                     "--profile-repeats", "1", "--out", str(out)])
    assert rc == 0
    payload = json.loads(out.read_text())
    assert tschema.validate_bench_payload(payload) == []
    assert payload["bench"] == "kernel_profile_sweep"
    assert list(payload["dominant_by_n"]) == ["64"]


def test_committed_dominance_artifact_is_schema_valid():
    # benchmarks/dominance_report.json is the ROADMAP pjit-gate artifact;
    # it must stay schema-valid and name a dominant kernel at every N.
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmarks", "dominance_report.json")
    if not os.path.exists(path):
        pytest.skip("dominance_report.json not generated")
    with open(path) as fh:
        payload = json.load(fh)
    assert tschema.validate_bench_payload(payload) == []
    assert set(payload["dominant_by_n"]) == \
        {str(n) for n in payload["sizes"]}
    assert all(dom in SUB_KERNELS
               for dom in payload["dominant_by_n"].values())


def test_committed_report_topology_rebuild_not_dominant():
    # The static-order hoist demoted topology_rebuild from the top of the
    # wall-clock ranking; the committed artifact must reflect that at
    # every swept N, else the sort crept back into the view-change path.
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmarks", "dominance_report.json")
    if not os.path.exists(path):
        pytest.skip("dominance_report.json not generated")
    with open(path) as fh:
        payload = json.load(fh)
    for run in payload["runs"]:
        ranked = sorted((k for k in run["kernels"]
                         if k["kernel"] != "full_step"),
                        key=lambda k: k["wall_median_s"], reverse=True)
        assert ranked[0]["kernel"] != "topology_rebuild", (
            f"topology_rebuild tops wall-clock at n={run['n']}")
        assert run["dominant"]["wall_clock"] != "topology_rebuild"
