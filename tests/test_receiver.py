"""Per-receiver engine: device-exact link faults.

The claims pinned here close the fleet fidelity envelope:

- ``run_receiver_differential`` is bit-identical to the host per-slot
  adversary referee — per-slot event streams, per-tick counters,
  per-phase consensus traffic and per-slot final config ids — for crash
  bursts, one-way partitions, classic-fallback chains and sampled
  partition/flip-flop scenarios;
- LinkWindow *boundary* semantics are exact: a one-tick window, a
  delivery exactly at window close, and a flip-flop phase edge all
  reproduce at N=64 through both referee layers (oracle vs host engine,
  host engine vs device kernel);
- a stacked per-receiver fleet member is bit-identical to the same
  scenario run unbatched (vmap never changes the protocol);
- the memory table ``receiver_field_shapes`` pins the real state
  (shapes and itemsizes), the budget gate raises the structured
  ``ReceiverBudgetError``, and envelope flags decode to named reasons;
- the shared-state step's jaxpr is untouched by per-receiver mode —
  the fast path retraces nothing when the new engine is off.
"""
import importlib

import numpy as np
import pytest

import jax

from rapid_tpu.engine import fleet as fleet_mod
from rapid_tpu.engine import receiver as rx_mod
from rapid_tpu.engine.diff import (run_adversarial_differential,
                                   run_receiver_differential)
from rapid_tpu.engine.state import I32_MAX, crash_faults, init_state
from rapid_tpu.faults import (SCENARIO_KINDS, AdversarySchedule, LinkWindow,
                              ScenarioWeights, ScriptedPropose,
                              sample_adversary_schedule)
from rapid_tpu.settings import Settings

step_mod = importlib.import_module("rapid_tpu.engine.step")

SETTINGS = Settings()
TICKS = 120


def _assert_tree_equal(a, b, what):
    for field, x, y in zip(type(a)._fields, a, b):
        assert np.array_equal(np.asarray(x), np.asarray(y)), \
            f"{what}: field {field} diverged"


def _assert_exact(result):
    result.assert_identical()
    assert result.engine_phase_counters == result.oracle_phase_counters
    assert result.engine_config_ids == result.oracle_config_ids


# ---------------------------------------------------------------------------
# differential exactness
# ---------------------------------------------------------------------------


def test_crash_burst_differential():
    sched = AdversarySchedule(n=8, crashes=((1, 4), (5, 4), (6, 12)),
                              seed=3)
    _assert_exact(run_receiver_differential(sched, 80, SETTINGS))


def test_one_way_partition_differential():
    iso = frozenset(range(4))
    rest = frozenset(range(4, 16))
    sched = AdversarySchedule(
        n=16, crashes=((9, 30),),
        windows=(LinkWindow(src_slots=rest, dst_slots=iso, start_tick=6),),
        seed=7)
    result = run_receiver_differential(sched, 160, SETTINGS)
    _assert_exact(result)
    # the isolated side must actually diverge from the rest: different
    # slots end on different configs, or the check is vacuous
    assert len(set(result.engine_config_ids)) > 1


def test_classic_chain_partition_exercises_all_phases():
    """An isolated majority-breaking group forces the classic fallback;
    every Paxos phase must carry traffic and still match per-slot."""
    iso = frozenset(range(5))
    rest = frozenset(range(5, 16))
    sched = AdversarySchedule(
        n=16,
        windows=(LinkWindow(src_slots=rest, dst_slots=iso, start_tick=6,
                            two_way=True),),
        seed=13)
    result = run_receiver_differential(sched, 160, SETTINGS)
    _assert_exact(result)
    totals = {k: sum(row[k] for row in result.engine_phase_counters)
              for k in result.engine_phase_counters[0]}
    for phase in ("phase1a_sent", "phase1b_sent", "phase2a_sent",
                  "phase2b_sent"):
        assert totals[phase] > 0, f"{phase} never fired"


@pytest.mark.parametrize("kind", ["partition", "flip_flop"])
def test_sampled_link_fault_schedules_are_device_exact(kind):
    weights = ScenarioWeights(
        **{k: (1.0 if k == kind else 0.0) for k in SCENARIO_KINDS})
    for seed in range(6):
        sc = sample_adversary_schedule(16, seed, TICKS, weights)
        assert sc.kind == kind
        _assert_exact(run_receiver_differential(sc.schedule, TICKS,
                                                SETTINGS))


def test_link_window_boundary_semantics_n64():
    """Satellite: deliveries exactly at a window's open/close tick, a
    one-tick window, and a flip-flop phase edge — exact at N=64 through
    both referee layers (oracle vs host engine, host engine vs device).

    FD probes are the traffic probe: they evaluate link reachability at
    ticks ≡ 0 (mod ``fd_interval_ticks``), so the windows are pinned to
    those delivery ticks. ``w_one`` blacks out exactly one probe tick;
    ``w_edge`` *opens* exactly on a probe tick and its half-open
    ``end_tick`` lands exactly on the next-but-one, which must get
    through; ``w_flip`` flips phase exactly at every probe tick."""
    n = 64
    iso_a = frozenset(range(8))            # one-tick blackout at t=30
    iso_b = frozenset(range(8, 20))        # opens at 30, ends AT 50
    iso_c = frozenset(range(20, 28))       # flip-flop, period = interval
    rest = frozenset(range(n))
    sched = AdversarySchedule(
        n=n,
        windows=(
            # src excludes iso_b so the one-tick window shares no directed
            # edge with w_edge's two-way reverse (the validator rejects
            # overlapping static windows on the same edge)
            LinkWindow(src_slots=rest - iso_a - iso_b, dst_slots=iso_a,
                       start_tick=30, end_tick=31),
            LinkWindow(src_slots=rest - iso_b, dst_slots=iso_b,
                       start_tick=30, end_tick=50, two_way=True),
            LinkWindow(src_slots=rest - iso_c, dst_slots=iso_c,
                       start_tick=30, period_ticks=10),
        ),
        seed=21)
    dev = run_receiver_differential(sched, TICKS, SETTINGS)
    _assert_exact(dev)
    host = run_adversarial_differential(sched, TICKS, SETTINGS)
    _assert_exact(host)
    pf = {m.tick: m.probes_failed for m in dev.engine_metrics}
    # t=30: all three windows bite (w_one's single tick is exactly here)
    # t=40: w_edge still active, w_flip in its open phase, w_one gone
    # t=50: w_edge's end_tick — the probe must pass; w_flip blocks again
    # t=60: only w_flip, open phase -> clean tick
    # t=70: w_flip blocked phase again, same edges as t=50
    assert pf[30] > pf[40] + pf[50] > 0
    assert pf[40] > 0 and pf[50] > 0
    assert pf[60] == 0
    assert pf[70] == pf[50]


# ---------------------------------------------------------------------------
# fleet batching
# ---------------------------------------------------------------------------


def test_fleet_slice_matches_unbatched_receiver_run():
    """Member i of a stacked per-receiver fleet == the same scenario
    run through ``receiver_simulate`` alone, bit for bit. The mix
    includes a latency member so the stack pads delay rules across
    link-fault-only members (inert padding must never change them)."""
    link_weights = ScenarioWeights(
        **{k: (1.0 if k in ("partition", "flip_flop") else 0.0)
           for k in SCENARIO_KINDS})
    jitter_weights = ScenarioWeights(
        **{k: (1.0 if k == "jitter" else 0.0) for k in SCENARIO_KINDS})
    schedules = [sample_adversary_schedule(16, s, 80, link_weights).schedule
                 for s in (2, 5, 9)]
    schedules.append(sample_adversary_schedule(
        16, 4, 80, jitter_weights,
        ring_depth=SETTINGS.delivery_ring_depth).schedule)
    members = [fleet_mod.lower_receiver_schedule(s, SETTINGS)
               for s in schedules]
    fleet = fleet_mod.stack_receiver_members(members)
    w = int(fleet.faults.link_src.shape[1])
    r = int(fleet.faults.delay_src.shape[1])
    f_finals, f_logs = fleet_mod.receiver_fleet_simulate(fleet, 80,
                                                         SETTINGS)
    for i, m in enumerate(members):
        s_final, s_logs = rx_mod.receiver_simulate(
            m.state, fleet_mod.pad_delay_rules(
                fleet_mod.pad_link_windows(m.faults, w), r),
            80, SETTINGS)
        sl_final = jax.tree_util.tree_map(lambda x, i=i: x[i], f_finals)
        sl_logs = jax.tree_util.tree_map(lambda x, i=i: x[i], f_logs)
        _assert_tree_equal(sl_final, s_final, f"member {i} final")
        _assert_tree_equal(sl_logs, s_logs, f"member {i} logs")
        rx_mod.check_flags(sl_final.flags)


def test_lower_receiver_schedule_rejects_proposes():
    sched = AdversarySchedule(n=8, proposes=(
        ScriptedPropose(slot=0, tick=5, proposal=(1,), delay_ticks=3),),
        seed=0)
    with pytest.raises(ValueError, match="propose"):
        fleet_mod.lower_receiver_schedule(sched, SETTINGS)
    with pytest.raises(ValueError, match="propose"):
        run_receiver_differential(sched, 40, SETTINGS)


# ---------------------------------------------------------------------------
# memory table, budget gate, envelope flags
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("ring_depth", [1, 4, 6])
def test_field_shapes_pin_real_state(ring_depth):
    """Every entry of the sizing table matches a real instantiation —
    shape and itemsize, across delivery-ring depths (the ring scales
    the wire planes by D) — so ``receiver_state_bytes`` cannot drift."""
    from rapid_tpu.oracle.membership_view import id_fingerprint, uid_of
    from rapid_tpu.engine.diff import default_endpoints, default_node_ids

    n = 12
    uids = [uid_of(e) for e in default_endpoints(n)]
    fp = sum(id_fingerprint(i) for i in default_node_ids(n)) \
        & ((1 << 64) - 1)
    rs = rx_mod.init_receiver_state(
        uids, fp,
        SETTINGS.with_(capacity=n, delivery_ring_depth=ring_depth),
        seed=0)
    table = rx_mod.receiver_field_shapes(n, SETTINGS.K,
                                         ring_depth=ring_depth)
    total = 0
    for field, leaf in zip(type(rs)._fields, rs):
        shape, itemsize = table[field]
        arr = np.asarray(leaf)
        assert arr.shape == shape, f"{field}: {arr.shape} != {shape}"
        assert arr.dtype.itemsize == itemsize, \
            f"{field}: itemsize {arr.dtype.itemsize} != {itemsize}"
        total += arr.nbytes
    assert total == rx_mod.receiver_state_bytes(n, SETTINGS.K,
                                                ring_depth=ring_depth)


def test_budget_gate_raises_structured_error():
    tight = SETTINGS.with_(receiver_capacity_cap=8)
    with pytest.raises(fleet_mod.ReceiverBudgetError) as exc:
        fleet_mod.check_receiver_budget(16, 4, tight)
    err = exc.value
    assert err.capacity == 16 and err.fleet_size == 4 and err.cap == 8
    assert err.member_bytes == rx_mod.receiver_state_bytes(16, tight.K)
    assert err.total_bytes == 4 * err.member_bytes
    assert "receiver_capacity_cap" in str(err)
    # under the cap: returns the per-member bytes, raises nothing
    assert fleet_mod.check_receiver_budget(8, 4, tight) == \
        rx_mod.receiver_state_bytes(8, tight.K)


def test_campaign_refuses_oversized_per_receiver_fleet():
    """The campaign surfaces the budget refusal before any device work
    (acceptance: structured error naming the measured budget, not OOM)."""
    from rapid_tpu.campaign import CampaignConfig, run_campaign

    cfg = CampaignConfig(
        clusters=2, n=16, ticks=40, fleet_size=2, seed=1,
        weights=ScenarioWeights(crash=0, partition=1, flip_flop=0,
                                contested=0, churn=0),
        settings=Settings(receiver_capacity_cap=8))
    with pytest.raises(fleet_mod.ReceiverBudgetError, match="over budget"):
        run_campaign(cfg)


def test_envelope_flags_decode_and_raise():
    assert rx_mod.decode_flags(0) == []
    names = rx_mod.decode_flags(rx_mod.FLAG_DECIDE_NOT_IN_VIEW
                                | rx_mod.FLAG_DRAWS_EXHAUSTED)
    assert "decide-host-not-in-view" in names
    assert "fallback-delay-draws-exhausted" in names
    rx_mod.check_flags(0)  # clean: no raise
    with pytest.raises(rx_mod.ReceiverEnvelopeError,
                       match="draws-exhausted"):
        rx_mod.check_flags(rx_mod.FLAG_DRAWS_EXHAUSTED)


def test_init_rejects_batched_windows():
    with pytest.raises(ValueError, match="batching"):
        rx_mod.init_receiver_state(
            [1, 2, 3, 4], 0,
            SETTINGS.with_(capacity=4, batching_window_ticks=2), seed=0)


# ---------------------------------------------------------------------------
# fleet sharding specs
# ---------------------------------------------------------------------------


def test_fleet_spec_for_skips_fleet_axis():
    from jax.sharding import PartitionSpec as P

    from rapid_tpu.engine import sharding

    mesh = sharding.slot_mesh(8)
    c = 16
    assert sharding.fleet_spec_for((4, c, c), c, mesh) == \
        P(None, sharding.AXIS)
    assert sharding.fleet_spec_for((4, c, c, 10), c, mesh) == \
        P(None, sharding.AXIS)
    assert sharding.fleet_spec_for((4,), c, mesh) == P()
    # F == C must never shard the fleet axis itself
    assert sharding.fleet_spec_for((c, c), c, mesh) == \
        P(None, sharding.AXIS)
    # capacity not dividing the mesh replicates (divisibility guard)
    assert sharding.fleet_spec_for((4, 12, 12), 12, mesh) == P()


# ---------------------------------------------------------------------------
# shared-state fast path is untouched
# ---------------------------------------------------------------------------


def _shared_step_jaxpr(settings):
    n = 16
    from rapid_tpu import hashing

    hi, lo = hashing.np_to_limbs(np.arange(1, n + 1, dtype=np.uint64))
    hi, lo = hashing.hash64_limbs(np, hi, lo, seed=0xBEEF)
    uids = hashing.np_from_limbs(hi, lo)
    state = init_state(uids, id_fp_sum=0, settings=settings)
    faults = crash_faults([I32_MAX] * n)
    return str(jax.make_jaxpr(
        lambda st, fa: step_mod.step(st, fa, settings))(state, faults))


def test_shared_step_jaxpr_unchanged_by_receiver_mode():
    """The per-receiver engine is a separate kernel: flipping its only
    Settings knob — and having imported the module at all — must leave
    the shared-state step's traced program byte-identical."""
    base = _shared_step_jaxpr(SETTINGS)
    assert base == _shared_step_jaxpr(
        SETTINGS.with_(receiver_capacity_cap=64))
    assert "receiver" not in base
