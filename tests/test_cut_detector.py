"""Oracle cut-detector tests, mirroring the reference CutDetectionTest.java
scenario matrix (K=10, H=8, L=2 — the tests sweep K/H/L rather than the
production 10/9/4)."""
import pytest

from rapid_tpu.oracle import MembershipView, MultiNodeCutDetector
from rapid_tpu.types import AlertMessage, EdgeStatus, Endpoint, NodeId

K, H, L = 10, 8, 2
CONFIG = -1  # does not affect these tests

_id = 0


def fresh_id() -> NodeId:
    global _id
    _id += 1
    return NodeId(_id, _id * 31)


def alert(src: Endpoint, dst: Endpoint, status: EdgeStatus, ring: int) -> AlertMessage:
    return AlertMessage(src, dst, status, CONFIG, (ring,))


def src(i: int) -> Endpoint:
    return Endpoint("127.0.0.1", i)


def test_invalid_khl_rejected():
    for k, h, l in [(2, 1, 1), (10, 11, 4), (10, 9, 10), (10, 9, 0)]:
        with pytest.raises(ValueError):
            MultiNodeCutDetector(k, h, l)


def test_cut_detection_single_node():
    wb = MultiNodeCutDetector(K, H, L)
    dst = Endpoint("127.0.0.2", 2)
    for i in range(H - 1):
        ret = wb.aggregate_for_proposal(alert(src(i + 1), dst, EdgeStatus.UP, i))
        assert ret == []
        assert wb.get_num_proposals() == 0
    ret = wb.aggregate_for_proposal(alert(src(H), dst, EdgeStatus.UP, H - 1))
    assert len(ret) == 1
    assert wb.get_num_proposals() == 1


def test_cut_detection_blocked_by_one_blocker():
    wb = MultiNodeCutDetector(K, H, L)
    dst1 = Endpoint("127.0.0.2", 2)
    dst2 = Endpoint("127.0.0.3", 2)
    for dst in (dst1, dst2):
        for i in range(H - 1):
            assert wb.aggregate_for_proposal(alert(src(i + 1), dst, EdgeStatus.UP, i)) == []
    # dst1 crosses H while dst2 is still in flux: blocked
    assert wb.aggregate_for_proposal(alert(src(H), dst1, EdgeStatus.UP, H - 1)) == []
    assert wb.get_num_proposals() == 0
    # dst2 crosses H: both emitted as one cut
    ret = wb.aggregate_for_proposal(alert(src(H), dst2, EdgeStatus.UP, H - 1))
    assert len(ret) == 2
    assert wb.get_num_proposals() == 1


def test_cut_detection_blocked_by_three_blockers():
    wb = MultiNodeCutDetector(K, H, L)
    dsts = [Endpoint(f"127.0.0.{i}", 2) for i in (2, 3, 4)]
    for dst in dsts:
        for i in range(H - 1):
            assert wb.aggregate_for_proposal(alert(src(i + 1), dst, EdgeStatus.UP, i)) == []
    assert wb.aggregate_for_proposal(alert(src(H), dsts[0], EdgeStatus.UP, H - 1)) == []
    assert wb.aggregate_for_proposal(alert(src(H), dsts[2], EdgeStatus.UP, H - 1)) == []
    assert wb.get_num_proposals() == 0
    ret = wb.aggregate_for_proposal(alert(src(H), dsts[1], EdgeStatus.UP, H - 1))
    assert len(ret) == 3
    assert wb.get_num_proposals() == 1


def test_cut_detection_multiple_blockers_past_h():
    wb = MultiNodeCutDetector(K, H, L)
    dsts = [Endpoint(f"127.0.0.{i}", 2) for i in (2, 3, 4)]
    for dst in dsts:
        for i in range(H - 1):
            assert wb.aggregate_for_proposal(alert(src(i + 1), dst, EdgeStatus.UP, i)) == []
    # extra (duplicate-ring) reports past H for dst1 and dst3 change nothing
    wb.aggregate_for_proposal(alert(src(H), dsts[0], EdgeStatus.UP, H - 1))
    assert wb.aggregate_for_proposal(alert(src(H + 1), dsts[0], EdgeStatus.UP, H - 1)) == []
    wb.aggregate_for_proposal(alert(src(H), dsts[2], EdgeStatus.UP, H - 1))
    assert wb.aggregate_for_proposal(alert(src(H + 1), dsts[2], EdgeStatus.UP, H - 1)) == []
    assert wb.get_num_proposals() == 0
    ret = wb.aggregate_for_proposal(alert(src(H), dsts[1], EdgeStatus.UP, H - 1))
    assert len(ret) == 3
    assert wb.get_num_proposals() == 1


def test_cut_detection_below_l_not_blocking():
    wb = MultiNodeCutDetector(K, H, L)
    dst1 = Endpoint("127.0.0.2", 2)
    dst2 = Endpoint("127.0.0.3", 2)  # stays below L: not a blocker
    dst3 = Endpoint("127.0.0.4", 2)
    for i in range(H - 1):
        assert wb.aggregate_for_proposal(alert(src(i + 1), dst1, EdgeStatus.UP, i)) == []
    for i in range(L - 1):
        assert wb.aggregate_for_proposal(alert(src(i + 1), dst2, EdgeStatus.UP, i)) == []
    for i in range(H - 1):
        assert wb.aggregate_for_proposal(alert(src(i + 1), dst3, EdgeStatus.UP, i)) == []
    assert wb.aggregate_for_proposal(alert(src(H), dst1, EdgeStatus.UP, H - 1)) == []
    ret = wb.aggregate_for_proposal(alert(src(H), dst3, EdgeStatus.UP, H - 1))
    assert len(ret) == 2
    assert wb.get_num_proposals() == 1


def test_cut_detection_batch():
    wb = MultiNodeCutDetector(K, H, L)
    endpoints = [Endpoint("127.0.0.2", 2 + i) for i in range(3)]
    proposal = []
    for endpoint in endpoints:
        for ring in range(K):
            proposal.extend(
                wb.aggregate_for_proposal(alert(src(1), endpoint, EdgeStatus.UP, ring))
            )
    assert len(proposal) == 3


def test_cut_detection_link_invalidation():
    """Mixed failure scenario: dst stuck at H-1 reports; its remaining
    observers themselves fail. invalidate_failing_edges() implicitly reports
    the missing edges and unsticks the cut (CutDetectionTest.java:254-301)."""
    view = MembershipView(K)
    wb = MultiNodeCutDetector(K, H, L)
    endpoints = [Endpoint("127.0.0.2", 2 + i) for i in range(30)]
    for n in endpoints:
        view.ring_add(n, fresh_id())

    dst = endpoints[0]
    observers = view.get_observers_of(dst)
    assert len(observers) == K

    # alerts from observers[0 .. H-1) about dst
    for i in range(H - 1):
        assert wb.aggregate_for_proposal(alert(observers[i], dst, EdgeStatus.DOWN, i)) == []

    # alerts *about* observers[H-1 .. K) (themselves fully reported)
    failed_observers = set()
    for i in range(H - 1, K):
        observers_of_observer = view.get_observers_of(observers[i])
        failed_observers.add(observers[i])
        for j in range(K):
            assert wb.aggregate_for_proposal(
                alert(observers_of_observer[j], observers[i], EdgeStatus.DOWN, j)
            ) == []
    assert wb.get_num_proposals() == 0

    ret = wb.invalidate_failing_edges(view)
    assert len(ret) == 4
    assert wb.get_num_proposals() == 1
    for node in ret:
        assert node in failed_observers or node == dst
