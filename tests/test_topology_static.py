"""Static-order topology: sort-free equivalence and identifier redraws.

The engine hoists the per-ring lexsort out of the tick loop
(``topology.ring_permutations`` at boot, sort-free ``build_topology`` /
``ring0_positions`` per view change). These tests pin:

- bit-identical output of the sort-free path against the *old* lexsort
  implementation, kept below as a NumPy reference, across seeds, K, and
  membership masks;
- ``rank_and_insert`` (the UUID-redraw incremental update) against a
  from-scratch re-sort, including slots that actually move;
- the jitted redraw phase end to end (scheduled uid swap inside
  ``lax.scan``) and the oracle-triangulated UUID-collision scenario via
  ``run_churn_differential``;
- the acceptance criterion itself: no sort primitive traced in the
  jitted topology / ring-0 kernels, nor anywhere in the jitted step
  beyond the vote-counting segmented bincount.
"""
import numpy as np
import pytest

from rapid_tpu import hashing
from rapid_tpu.engine.paxos import ring0_positions
from rapid_tpu.engine.state import I32_MAX, crash_faults, init_state
from rapid_tpu.engine.topology import (build_topology, rank_and_insert,
                                       ring_permutations)
from rapid_tpu.settings import Settings
from rapid_tpu.types import NodeId

SETTINGS = Settings()


# ---------------------------------------------------------------------------
# the pre-hoist implementation, kept verbatim as the NumPy reference
# ---------------------------------------------------------------------------


def legacy_build_topology(uid_hi, uid_lo, member, k):
    """The old per-view-change lexsort ``build_topology`` (NumPy only)."""
    c = uid_hi.shape[0]
    member = member.astype(bool)
    n = member.sum().astype(np.int32)
    slots = np.arange(c, dtype=np.int32)
    pos = np.arange(c, dtype=np.int32)

    subj_cols, obs_cols, gk_cols = [], [], []
    for ring in range(k):
        khi, klo = hashing.hash64_limbs(np, uid_hi, uid_lo, seed=ring)
        order = np.lexsort((uid_lo, uid_hi, klo, khi)).astype(np.int32)
        member_s = member[order]
        midx = np.where(member_s, pos, np.int32(-1))
        incl = np.maximum.accumulate(midx)
        prev = np.concatenate([np.full((1,), -1, np.int32), incl[:-1]])
        prev = np.where(prev < 0, incl[-1], prev)
        prev = np.maximum(prev, 0)
        nidx = np.where(member_s, pos, np.int32(c))
        incl_n = np.minimum.accumulate(nidx[::-1])[::-1]
        nxt = np.concatenate([incl_n[1:], np.full((1,), c, np.int32)])
        first_m = np.minimum(incl_n[0], c - 1)
        nxt = np.where(nxt >= c, first_m, nxt)
        rank = np.argsort(order).astype(np.int32)
        pred = order[prev][rank]
        succ = order[nxt][rank]
        subj_cols.append(np.where(member, pred, slots))
        obs_cols.append(np.where(member, succ, slots))
        gk_cols.append(np.where(member, slots, pred))
    subj_idx = np.stack(subj_cols, axis=1)
    obs_idx = np.stack(obs_cols, axis=1)
    gk_idx = np.stack(gk_cols, axis=1)

    eq = subj_idx[:, :, None] == subj_idx[:, None, :]
    earlier = np.tril(np.ones((k, k), bool), k=-1)[None, :, :]
    usable = member & (n >= 2)
    fd_active = ~(eq & earlier).any(axis=2) & usable[:, None]
    fd_first = np.argmax(eq, axis=2).astype(np.int32)
    return subj_idx, obs_idx, gk_idx, fd_active, fd_first


def legacy_ring0_positions(uid_hi, uid_lo, member):
    """The old per-view-change lexsort ``ring0_positions`` (NumPy only)."""
    khi, klo = hashing.hash64_limbs(np, uid_hi, uid_lo, seed=0)
    order = np.lexsort((uid_lo, uid_hi, klo, khi)).astype(np.int32)
    member_s = member.astype(bool)[order]
    mrank_s = np.cumsum(member_s.astype(np.int32)) - 1
    rank = np.argsort(order).astype(np.int32)
    mpos = mrank_s[rank]
    return np.where(member, mpos, np.int32(I32_MAX))


def synthetic_limbs(c, seed):
    hi, lo = hashing.np_to_limbs(np.arange(1, c + 1, dtype=np.uint64))
    hi, lo = hashing.hash64_limbs(np, hi, lo, seed=0xABC0 ^ seed)
    uids = hashing.np_from_limbs(hi, lo)
    assert len(np.unique(uids)) == c, "synthetic uids must be distinct"
    return hi, lo


def membership_masks(c, rng):
    yield np.ones(c, bool)
    yield np.zeros(c, bool)
    single = np.zeros(c, bool)
    single[int(rng.integers(c))] = True
    yield single
    for p in (0.2, 0.5, 0.9):
        yield rng.random(c) < p


# ---------------------------------------------------------------------------
# sort-free equivalence property sweep
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [0, 1, 7])
@pytest.mark.parametrize("k", [1, 3, 10])
def test_sortfree_build_topology_matches_legacy(seed, k):
    import jax.numpy as jnp

    rng = np.random.default_rng(100 * seed + k)
    c = int(rng.integers(3, 70))
    uid_hi, uid_lo = synthetic_limbs(c, seed)
    order, rank = ring_permutations(np, uid_hi, uid_lo, k)
    order_j, rank_j = jnp.asarray(order), jnp.asarray(rank)

    for member in membership_masks(c, rng):
        legacy = legacy_build_topology(uid_hi, uid_lo, member, k)
        host = build_topology(np, member, order, rank)
        device = build_topology(jnp, jnp.asarray(member), order_j, rank_j)
        for name, a, b, d in zip(
                ("subj_idx", "obs_idx", "gk_idx", "fd_active", "fd_first"),
                legacy, host, device):
            np.testing.assert_array_equal(
                np.asarray(b), np.asarray(a),
                err_msg=f"{name} host diverged (seed={seed} k={k})")
            np.testing.assert_array_equal(
                np.asarray(d), np.asarray(a),
                err_msg=f"{name} device diverged (seed={seed} k={k})")


@pytest.mark.parametrize("seed", [0, 5])
def test_sortfree_ring0_positions_matches_legacy(seed):
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    c = int(rng.integers(3, 70))
    uid_hi, uid_lo = synthetic_limbs(c, seed)
    order, rank = ring_permutations(np, uid_hi, uid_lo, 1)
    for member in membership_masks(c, rng):
        legacy = legacy_ring0_positions(uid_hi, uid_lo, member)
        host = ring0_positions(np, member, order, rank)
        device = ring0_positions(jnp, jnp.asarray(member),
                                 jnp.asarray(order), jnp.asarray(rank))
        np.testing.assert_array_equal(np.asarray(host), legacy)
        np.testing.assert_array_equal(np.asarray(device), legacy)


def test_ring_permutations_are_inverse_and_device_identical():
    import jax.numpy as jnp

    uid_hi, uid_lo = synthetic_limbs(57, 3)
    order, rank = ring_permutations(np, uid_hi, uid_lo, SETTINGS.K)
    pos = np.arange(57, dtype=np.int32)
    for ring in range(SETTINGS.K):
        np.testing.assert_array_equal(rank[order[:, ring], ring], pos)
    order_j, rank_j = ring_permutations(
        jnp, jnp.asarray(uid_hi), jnp.asarray(uid_lo), SETTINGS.K)
    np.testing.assert_array_equal(np.asarray(order_j), order)
    np.testing.assert_array_equal(np.asarray(rank_j), rank)


# ---------------------------------------------------------------------------
# rank-and-insert: incremental redraw vs from-scratch re-sort
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [0, 3])
def test_rank_and_insert_matches_resort(seed):
    k = 5
    c = 41
    uid_hi, uid_lo = synthetic_limbs(c, seed)
    uid_hi, uid_lo = uid_hi.copy(), uid_lo.copy()
    order, rank = ring_permutations(np, uid_hi, uid_lo, k)
    rng = np.random.default_rng(seed)
    for _ in range(6):
        slot = int(rng.integers(c))
        uid_hi[slot] = np.uint32(rng.integers(1 << 32))
        uid_lo[slot] = np.uint32(rng.integers(1 << 32))
        order, rank = rank_and_insert(np, slot, uid_hi, uid_lo, order, rank)
        oref, rref = ring_permutations(np, uid_hi, uid_lo, k)
        np.testing.assert_array_equal(order, oref)
        np.testing.assert_array_equal(rank, rref)


def test_rank_and_insert_traced_slot_matches_host():
    import jax
    import jax.numpy as jnp

    k = 4
    c = 23
    uid_hi, uid_lo = synthetic_limbs(c, 9)
    uid_hi, uid_lo = uid_hi.copy(), uid_lo.copy()
    order, rank = ring_permutations(np, uid_hi, uid_lo, k)
    slot = 11
    uid_hi[slot], uid_lo[slot] = np.uint32(0xDEAD), np.uint32(0xBEEF)

    jitted = jax.jit(lambda s, h, lo, o, r: rank_and_insert(jnp, s, h, lo,
                                                            o, r))
    order_j, rank_j = jitted(jnp.int32(slot), jnp.asarray(uid_hi),
                             jnp.asarray(uid_lo), jnp.asarray(order),
                             jnp.asarray(rank))
    oref, rref = ring_permutations(np, uid_hi, uid_lo, k)
    np.testing.assert_array_equal(np.asarray(order_j), oref)
    np.testing.assert_array_equal(np.asarray(rank_j), rref)


def test_scheduled_redraw_moves_ring_position_in_scan():
    """End to end through the jitted scan: a scheduled redraw swaps a
    dormant slot's identity and its ring arrays match a from-scratch
    re-sort of the new universe."""
    import jax.numpy as jnp

    from rapid_tpu.engine.churn import empty_schedule
    from rapid_tpu.engine.step import simulate

    n, c = 12, 13
    slot = 12
    hi, lo = synthetic_limbs(c, 4)
    uids = hashing.np_from_limbs(hi, lo)
    member = [True] * n + [False]
    state = init_state(uids, 0, SETTINGS, member=member)

    new_uid = np.uint64(hashing.hash64(0x5EED, seed=7))
    new_hi, new_lo = hashing.to_limbs(int(new_uid))
    sched = empty_schedule(c)
    redraw_tick = np.full(c, I32_MAX, np.int32)
    redraw_tick[slot] = 3
    zeros = np.zeros(c, np.uint32)
    sched = sched._replace(
        redraw_tick=redraw_tick,
        redraw_hi=zeros.copy(), redraw_lo=zeros.copy(),
        redraw_idfp_hi=zeros.copy(), redraw_idfp_lo=zeros.copy())
    sched.redraw_hi[slot] = new_hi
    sched.redraw_lo[slot] = new_lo
    sched.redraw_idfp_hi[slot] = 0x1234
    sched.redraw_idfp_lo[slot] = 0x5678

    faults = crash_faults([I32_MAX] * c)
    final, _ = simulate(state, faults, 6, SETTINGS, churn=sched)

    uids_after = uids.copy()
    uids_after[slot] = new_uid
    hi2, lo2 = hashing.np_to_limbs(uids_after)
    oref, rref = ring_permutations(np, hi2, lo2, SETTINGS.K)
    np.testing.assert_array_equal(np.asarray(final.ring_order), oref)
    np.testing.assert_array_equal(np.asarray(final.ring_rank), rref)
    # derived topology re-scanned from the moved order
    topo = build_topology(np, np.asarray(member), oref, rref)
    for got, want in zip((final.subj_idx, final.obs_idx, final.gk_idx,
                          final.fd_active, final.fd_first), topo):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # identity limbs and fingerprints swapped in place
    assert int(final.uid_hi[slot]) == new_hi
    assert int(final.uid_lo[slot]) == new_lo
    assert int(final.idfp_hi[slot]) == 0x1234
    assert int(final.idfp_lo[slot]) == 0x5678
    mh, ml = hashing.hash64_limbs(
        np, np.uint32(new_hi), np.uint32(new_lo), seed=0x6D656D62)
    assert int(final.mfp_hi[slot]) == int(mh)
    assert int(final.mfp_lo[slot]) == int(ml)
    # px_pos re-scanned: members keep positions, dormant slot stays masked
    np.testing.assert_array_equal(
        np.asarray(final.px_pos),
        np.asarray(ring0_positions(np, np.asarray(member), oref, rref)))


# ---------------------------------------------------------------------------
# UUID-collision redraw, triangulated planner / oracle / engine
# ---------------------------------------------------------------------------


def test_uuid_redraw_triangulates_against_oracle():
    from rapid_tpu.engine.churn import plan_churn
    from rapid_tpu.engine.diff import (default_endpoints, default_node_ids,
                                       run_churn_differential)
    from rapid_tpu.oracle.cluster import default_rng

    n, capacity, joiner = 64, 65, 64
    endpoints = default_endpoints(capacity)
    # Burn the joiner's first NodeId draw into an initial member, so the
    # phase-1 evaluation answers UUID_ALREADY_IN_RING on both sides and
    # the retry redraws through the engine's rank-and-insert path.
    rng = default_rng(SETTINGS, endpoints[joiner])
    collide = NodeId(rng.getrandbits(64), rng.getrandbits(64))
    node_ids = list(default_node_ids(n))
    node_ids[3] = collide

    plan = plan_churn(endpoints, n, node_ids, 40, SETTINGS,
                      joins={joiner: 5})
    # join() at 5 -> PreJoin 6 collides -> redraw lands with the reply at 7
    assert plan.redraws == {joiner: 7}
    assert plan.schedule.redraw_tick is not None
    assert plan.schedule.redraw_tick[joiner] == 7

    res = run_churn_differential(n=n, capacity=capacity, n_ticks=40,
                                 joins={joiner: 5}, node_ids=node_ids)
    res.assert_identical()
    # retry start 7 -> PreJoin 8 -> enqueue 10 -> flush 11 -> announce 12
    # -> decide 13
    assert [(e.kind, e.tick, e.slots) for e in res.engine_events] == [
        ("proposal", 12, (joiner,)), ("view_change", 13, (joiner,))]
    assert res.engine_members == frozenset(range(capacity))


def test_uuid_redraw_without_collision_schedules_nothing():
    from rapid_tpu.engine.churn import plan_churn
    from rapid_tpu.engine.diff import default_endpoints, default_node_ids

    endpoints = default_endpoints(65)
    plan = plan_churn(endpoints, 64, default_node_ids(64), 40, SETTINGS,
                      joins={64: 5})
    assert plan.redraws == {}
    assert plan.schedule.redraw_tick is None  # phase compiles out


# ---------------------------------------------------------------------------
# jaxpr inspection: the acceptance criterion itself
# ---------------------------------------------------------------------------


def _count_sorts(jaxpr) -> int:
    """Count sort primitives in a jaxpr, recursing into sub-jaxprs
    (cond branches, scan bodies, closed calls)."""
    total = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "sort":
            total += 1
        for v in eqn.params.values():
            for x in (v if isinstance(v, (list, tuple)) else (v,)):
                inner = getattr(x, "jaxpr", x)
                if hasattr(inner, "eqns"):
                    total += _count_sorts(inner)
    return total


def test_no_sort_traced_in_topology_or_ring0_kernels():
    import jax
    import jax.numpy as jnp

    c = 32
    uid_hi, uid_lo = synthetic_limbs(c, 2)
    order, rank = ring_permutations(np, uid_hi, uid_lo, SETTINGS.K)
    member = jnp.ones(c, bool)
    order_j, rank_j = jnp.asarray(order), jnp.asarray(rank)

    topo = jax.make_jaxpr(
        lambda m, o, r: build_topology(jnp, m, o, r))(member, order_j,
                                                      rank_j)
    assert _count_sorts(topo.jaxpr) == 0

    r0 = jax.make_jaxpr(
        lambda m, o, r: ring0_positions(jnp, m, o, r))(member, order_j,
                                                       rank_j)
    assert _count_sorts(r0.jaxpr) == 0

    rai = jax.make_jaxpr(
        lambda s, h, lo, o, r: rank_and_insert(jnp, s, h, lo, o, r))(
        jnp.int32(3), jnp.asarray(uid_hi), jnp.asarray(uid_lo), order_j,
        rank_j)
    assert _count_sorts(rai.jaxpr) == 0

    # sanity: the boot-time permutation builder is where the sort lives
    perms = jax.make_jaxpr(
        lambda h, lo: ring_permutations(jnp, h, lo, SETTINGS.K))(
        jnp.asarray(uid_hi), jnp.asarray(uid_lo))
    assert _count_sorts(perms.jaxpr) == SETTINGS.K


def test_step_sorts_only_for_vote_counting():
    """The full jitted step — churn phase with redraws included — traces
    exactly the vote-count segmented bincount's sorts and nothing else;
    every topology/ring-0 sort is gone from the tick loop."""
    import jax
    import jax.numpy as jnp

    from rapid_tpu.engine.churn import empty_schedule
    from rapid_tpu.engine.step import step
    from rapid_tpu.engine.votes import segmented_vote_count

    c = 16
    hi, lo = synthetic_limbs(c, 1)
    uids = hashing.np_from_limbs(hi, lo)
    state = init_state(uids, 0, SETTINGS)
    faults = crash_faults([I32_MAX] * c)
    sched = empty_schedule(c)
    sched = sched._replace(
        redraw_tick=np.full(c, I32_MAX, np.int32),
        redraw_hi=np.zeros(c, np.uint32), redraw_lo=np.zeros(c, np.uint32),
        redraw_idfp_hi=np.zeros(c, np.uint32),
        redraw_idfp_lo=np.zeros(c, np.uint32))

    stepx = jax.make_jaxpr(
        lambda st, f, ch: step(st, f, SETTINGS, ch, None))(state, faults,
                                                           sched)
    votes_only = jax.make_jaxpr(
        lambda h, lo, v: segmented_vote_count(jnp, h, lo, v))(
        jnp.zeros(c, jnp.uint32), jnp.zeros(c, jnp.uint32),
        jnp.zeros(c, bool))
    assert _count_sorts(votes_only.jaxpr) > 0  # the one legitimate sort
    assert _count_sorts(stepx.jaxpr) == _count_sorts(votes_only.jaxpr)
