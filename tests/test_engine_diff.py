"""Oracle-vs-engine differentials: bit-identical cut decisions.

Each scenario replays through the python oracle and the jax engine and
asserts identical proposal emission ticks and contents, view-change ticks
and contents, 64-bit configuration ids, and per-tick message counts
(``DiffResult.assert_identical``). Scenarios respect the crash-burst
envelope documented in ``rapid_tpu.engine.diff``: all crashes in a burst
share their first failing FD tick.

Churn differentials (``run_churn_differential``) triangulate a third
party — the host planner — against oracle and engine; counters are not
compared there (join/leave RPCs are host-side protocol by design).
"""
import pytest

from rapid_tpu.engine.churn import ChurnEnvelopeError
from rapid_tpu.engine.diff import run_churn_differential, run_differential


def test_differential_n64_single_crash():
    res = run_differential(64, {7: 5}, 130)
    res.assert_identical()
    kinds = [(e.kind, e.tick, e.slots) for e in res.engine_events]
    assert kinds == [("proposal", 112, (7,)), ("view_change", 113, (7,))]


def test_differential_n64_crash_burst():
    res = run_differential(64, {3: 5, 17: 5, 40: 7}, 130)
    res.assert_identical()
    assert [e.slots for e in res.engine_events] == [(3, 17, 40)] * 2


def test_differential_n64_two_sequential_bursts():
    # Second burst crashes at 201/205: both first fail at FD tick 210
    # (same cohort), long after the first removal completes at 113.
    res = run_differential(64, {3: 5, 17: 5, 40: 201, 41: 205}, 360)
    res.assert_identical()
    assert [(e.kind, e.tick) for e in res.engine_events] == [
        ("proposal", 112), ("view_change", 113),
        ("proposal", 312), ("view_change", 313),
    ]
    assert res.engine_events[2].slots == (40, 41)


def test_differential_n64_no_faults_quiescent():
    res = run_differential(64, {}, 60)
    res.assert_identical()
    assert res.engine_events == []
    # a healthy cluster sends no messages at all (probes are counted apart)
    assert all(c["sent"] == 0 for c in res.engine_counters)
    assert any(c["probes_sent"] > 0 for c in res.engine_counters)


def test_differential_n256_crash_burst():
    res = run_differential(256, {5: 11, 100: 13, 200: 15, 250: 19}, 140)
    res.assert_identical()
    assert [(e.kind, e.tick, e.slots) for e in res.engine_events] == [
        ("proposal", 122, (5, 100, 200, 250)),
        ("view_change", 123, (5, 100, 200, 250)),
    ]


@pytest.mark.slow
def test_differential_n256_large_burst():
    res = run_differential(256, {s: 5 for s in range(0, 64, 2)}, 140)
    res.assert_identical()
    assert len(res.engine_events) == 2
    assert res.engine_events[1].slots == tuple(range(0, 64, 2))


# ---------------------------------------------------------------------------
# churn differentials: joins, graceful leaves, mixed churn + crash
# ---------------------------------------------------------------------------


def test_churn_differential_n64_join_burst():
    res = run_churn_differential(n=64, capacity=68, n_ticks=40,
                                 joins={64: 5, 65: 5, 66: 5, 67: 5})
    res.assert_identical()
    # join() at 5 -> PreJoin 6 -> reply 7 -> UP enqueue 8 -> flush 9 ->
    # announce 10 -> decide 11
    assert [(e.kind, e.tick, e.slots) for e in res.engine_events] == [
        ("proposal", 10, (64, 65, 66, 67)),
        ("view_change", 11, (64, 65, 66, 67)),
    ]
    assert res.engine_members == frozenset(range(68))


def test_churn_differential_n64_leave_burst():
    res = run_churn_differential(n=64, capacity=64, n_ticks=40,
                                 leaves={3: 5, 17: 5, 40: 5})
    res.assert_identical()
    # leave at 5 -> DOWN enqueue 6 -> flush 7 -> announce 8 -> decide 9
    assert [(e.kind, e.tick, e.slots) for e in res.engine_events] == [
        ("proposal", 8, (3, 17, 40)),
        ("view_change", 9, (3, 17, 40)),
    ]
    assert res.engine_members == frozenset(range(64)) - {3, 17, 40}


def test_churn_differential_n64_mixed_crash_join_leave():
    res = run_churn_differential(n=64, capacity=66, n_ticks=180,
                                 crashes={3: 5, 17: 5, 40: 5},
                                 joins={64: 120, 65: 120},
                                 leaves={7: 140})
    res.assert_identical()
    assert [(e.kind, e.tick, e.slots) for e in res.engine_events] == [
        ("proposal", 112, (3, 17, 40)), ("view_change", 113, (3, 17, 40)),
        ("proposal", 125, (64, 65)), ("view_change", 126, (64, 65)),
        ("proposal", 143, (7,)), ("view_change", 144, (7,)),
    ]
    assert res.engine_members == (frozenset(range(66))
                                  - {3, 17, 40, 7})


def test_churn_planner_predicts_oracle_partial_emission():
    """The crash pair {4, 9} at n=64 makes the *real* oracle emit a
    partial proposal (slot 4 crosses H while 9 is still below L), which
    the batched engine cannot reproduce — the planner must reject the
    scenario before either side runs."""
    with pytest.raises(ChurnEnvelopeError, match="partial"):
        run_churn_differential(n=64, capacity=65, n_ticks=130,
                               crashes={4: 5, 9: 5}, joins={64: 118})


def test_churn_differential_join_then_leave_same_slot():
    res = run_churn_differential(n=16, capacity=18, n_ticks=60,
                                 joins={16: 5}, leaves={16: 30})
    res.assert_identical()
    assert [e.slots for e in res.engine_events] == [(16,)] * 4
    assert res.engine_members == frozenset(range(16))


def test_churn_differential_n256_join_and_leave_bursts():
    res = run_churn_differential(
        n=256, capacity=260, n_ticks=60,
        joins={s: 5 for s in range(256, 260)},
        leaves={11: 30, 42: 30, 197: 30})
    res.assert_identical()
    assert [(e.kind, e.tick) for e in res.engine_events] == [
        ("proposal", 10), ("view_change", 11),
        ("proposal", 33), ("view_change", 34),
    ]
    assert res.engine_events[0].slots == (256, 257, 258, 259)
    assert res.engine_events[2].slots == (11, 42, 197)
    assert res.engine_members == frozenset(range(260)) - {11, 42, 197}


def test_churn_planner_rejects_overlapping_pipeline():
    # The leave alert (enqueue 10) lands while the join pipeline
    # (enqueue 8, announce 10, decide 11) is still in flight.
    with pytest.raises(ChurnEnvelopeError, match="in flight"):
        run_churn_differential(n=16, capacity=17, n_ticks=40,
                               joins={16: 5}, leaves={3: 9})


def test_churn_planner_rejects_view_change_inside_leave_hop():
    # The join decides at tick 11, exactly when slot 3's LeaveMessages
    # (sent at 10) deliver: the observers were resolved against the old
    # view, the ring numbers against the new one.
    with pytest.raises(ChurnEnvelopeError, match="view changed"):
        run_churn_differential(n=16, capacity=17, n_ticks=40,
                               joins={16: 5}, leaves={3: 10})


def test_churn_planner_rejects_leaver_crashing_mid_hop():
    with pytest.raises(ChurnEnvelopeError, match="leaver"):
        run_churn_differential(n=16, capacity=16, n_ticks=40,
                               leaves={3: 5}, crashes={3: 6})
