"""Oracle-vs-engine differentials: bit-identical cut decisions.

Each scenario replays through the python oracle and the jax engine and
asserts identical proposal emission ticks and contents, view-change ticks
and contents, 64-bit configuration ids, and per-tick message counts
(``DiffResult.assert_identical``). Scenarios respect the crash-burst
envelope documented in ``rapid_tpu.engine.diff``: all crashes in a burst
share their first failing FD tick.
"""
import pytest

from rapid_tpu.engine.diff import run_differential


def test_differential_n64_single_crash():
    res = run_differential(64, {7: 5}, 130)
    res.assert_identical()
    kinds = [(e.kind, e.tick, e.slots) for e in res.engine_events]
    assert kinds == [("proposal", 112, (7,)), ("view_change", 113, (7,))]


def test_differential_n64_crash_burst():
    res = run_differential(64, {3: 5, 17: 5, 40: 7}, 130)
    res.assert_identical()
    assert [e.slots for e in res.engine_events] == [(3, 17, 40)] * 2


def test_differential_n64_two_sequential_bursts():
    # Second burst crashes at 201/205: both first fail at FD tick 210
    # (same cohort), long after the first removal completes at 113.
    res = run_differential(64, {3: 5, 17: 5, 40: 201, 41: 205}, 360)
    res.assert_identical()
    assert [(e.kind, e.tick) for e in res.engine_events] == [
        ("proposal", 112), ("view_change", 113),
        ("proposal", 312), ("view_change", 313),
    ]
    assert res.engine_events[2].slots == (40, 41)


def test_differential_n64_no_faults_quiescent():
    res = run_differential(64, {}, 60)
    res.assert_identical()
    assert res.engine_events == []
    # a healthy cluster sends no messages at all (probes are counted apart)
    assert all(c["sent"] == 0 for c in res.engine_counters)
    assert any(c["probes_sent"] > 0 for c in res.engine_counters)


def test_differential_n256_crash_burst():
    res = run_differential(256, {5: 11, 100: 13, 200: 15, 250: 19}, 140)
    res.assert_identical()
    assert [(e.kind, e.tick, e.slots) for e in res.engine_events] == [
        ("proposal", 122, (5, 100, 200, 250)),
        ("view_change", 123, (5, 100, 200, 250)),
    ]


@pytest.mark.slow
def test_differential_n256_large_burst():
    res = run_differential(256, {s: 5 for s in range(0, 64, 2)}, 140)
    res.assert_identical()
    assert len(res.engine_events) == 2
    assert res.engine_events[1].slots == tuple(range(0, 64, 2))
