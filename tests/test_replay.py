"""Deterministic single-member replay: the triage exemplars of a
recorder-on campaign must replay bit-identically — expected block and
flight-recorder ring both — through ``rapid_tpu.replay``, in each
dispatch mode, and the verifier must actually fail on tampered data."""
import copy
import json

import pytest

from rapid_tpu import replay as replay_mod
from rapid_tpu.campaign import CampaignConfig, run_campaign

#: Cheapest recorder-on campaign that flags members in BOTH dispatch
#: modes: seed 0 of the default mix samples churn members (shared path,
#: never decide inside 120 ticks) and slow_asym members (per-receiver
#: path, same anomaly), so triage carries exemplars for each.
CFG = CampaignConfig(clusters=8, n=24, ticks=120, fleet_size=4,
                     spot_checks=0, flight_recorder=24)


@pytest.fixture(scope="module")
def payload():
    return run_campaign(CFG)


def _exemplar_refs(payload, mode):
    triage = payload["campaign"]["triage"]
    return [ex for block in triage["classes"].values()
            for ex in block["exemplars"]
            if ex["mode"] == mode and ex["expected"] is not None]


def _assert_verified(record, exemplar):
    assert record["match"] is True
    assert record["mismatches"] is None
    assert record["recorder_match"] is True
    assert record["triage_class"] is not None
    # Identity fields come from the replayed sampling chain, not the
    # exemplar — equality proves the chain reconstructed the member.
    assert record["member"] == exemplar["member"]
    assert record["kind"] == exemplar["kind"]
    assert record["seed"] == exemplar["seed"]
    assert record["replayed"] == exemplar["expected"]
    assert record["recorder"] == exemplar["recorder"]
    assert record["recorder"]["window"] == CFG.flight_recorder


def test_campaign_flags_members_in_both_modes(payload):
    # Guard for the fixture config itself: the replay tests below need
    # at least one verified exemplar on each engine path.
    assert _exemplar_refs(payload, "shared")
    assert _exemplar_refs(payload, "per_receiver")


def test_shared_exemplar_replays_bit_identical(payload):
    ex = _exemplar_refs(payload, "shared")[0]
    record = replay_mod.replay_member(payload, ex["dispatch"],
                                      ex["member_index"])
    assert record["mode"] == "shared"
    _assert_verified(record, ex)


def test_receiver_exemplar_replays_bit_identical(payload):
    ex = _exemplar_refs(payload, "per_receiver")[0]
    record = replay_mod.replay_member(payload, ex["dispatch"],
                                      ex["member_index"])
    assert record["mode"] == "per_receiver"
    _assert_verified(record, ex)


def test_unflagged_member_replays_without_verdict(payload):
    flagged = {(ex["dispatch"], ex["member_index"])
               for mode in ("shared", "per_receiver")
               for ex in _exemplar_refs(payload, mode)}
    shared_d = _exemplar_refs(payload, "shared")[0]["dispatch"]
    target = next((shared_d, j) for j in range(CFG.fleet_size)
                  if (shared_d, j) not in flagged)
    record = replay_mod.replay_member(payload, *target)
    assert record["match"] is None
    assert record["triage_class"] is None
    # The member still gets the full fold and its recorder ring.
    assert set(record["replayed"]) == set(
        _exemplar_refs(payload, "shared")[0]["expected"])
    assert record["recorder"]["ticks_recorded"] == CFG.ticks


def test_tampered_expected_block_fails_verification(payload):
    tampered = copy.deepcopy(payload)
    ex = _exemplar_refs(tampered, "shared")[0]
    key = next(k for k, v in ex["expected"].items()
               if isinstance(v, int))
    ex["expected"][key] += 1
    record = replay_mod.replay_member(tampered, ex["dispatch"],
                                      ex["member_index"])
    assert record["match"] is False
    assert key in record["mismatches"]


def test_tampered_recorder_ring_fails_verification(payload):
    tampered = copy.deepcopy(payload)
    ex = _exemplar_refs(tampered, "shared")[0]
    ex["recorder"]["rows"][-1][0] += 1
    record = replay_mod.replay_member(tampered, ex["dispatch"],
                                      ex["member_index"])
    assert record["match"] is True  # the fold itself is untouched
    assert record["recorder_match"] is False


def test_out_of_range_refs_rejected(payload):
    with pytest.raises(ValueError, match="out of range"):
        replay_mod.replay_member(payload, 99, 0)
    with pytest.raises(ValueError, match="padded slots|out of range"):
        replay_mod.replay_member(payload, 0, CFG.fleet_size)


def test_pre_v8_payload_rejected(payload):
    old = copy.deepcopy(payload)
    del old["campaign"]["weights"]
    with pytest.raises(ValueError, match="schema >= 8"):
        replay_mod.replay_member(old, 0, 0)
    with pytest.raises(ValueError, match="campaign"):
        replay_mod.replay_member({"bench": "x"}, 0, 0)


def test_cli_roundtrip_writes_artifacts(payload, tmp_path, capsys):
    ex = _exemplar_refs(payload, "shared")[0]
    ppath = tmp_path / "campaign.json"
    ppath.write_text(json.dumps(payload))
    metrics = tmp_path / "member.jsonl"
    trace = tmp_path / "member_trace.json"
    out = tmp_path / "replay.json"
    rc = replay_mod.main([
        "--payload", str(ppath),
        "--member", f"{ex['dispatch']}:{ex['member_index']}",
        "--metrics", str(metrics), "--trace", str(trace),
        "--out", str(out)])
    assert rc == 0
    record = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert record["match"] is True and record["recorder_match"] is True
    assert record == json.loads(out.read_text())
    rows = [json.loads(line) for line in
            metrics.read_text().splitlines()]
    assert len(rows) == CFG.ticks
    assert json.loads(trace.read_text())["traceEvents"]


def test_cli_exit_one_on_mismatch(payload, tmp_path, capsys):
    tampered = copy.deepcopy(payload)
    ex = _exemplar_refs(tampered, "shared")[0]
    key = next(k for k, v in ex["expected"].items()
               if isinstance(v, int))
    ex["expected"][key] += 1
    ppath = tmp_path / "tampered.json"
    ppath.write_text(json.dumps(tampered))
    rc = replay_mod.main([
        "--payload", str(ppath),
        "--member", f"{ex['dispatch']}:{ex['member_index']}"])
    assert rc == 1
    record = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert record["match"] is False
