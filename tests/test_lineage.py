"""Consensus lineage observatory: phase-attributed view-change spans.

The acceptance contract:

- the lineage fold is *derived* data proven bit-identical between the
  host oracle and the device engine at N=64 (and N=256 under the slow
  marker) across four scenario families — steady (the empty stream is
  part of the contract), crash burst, delay adversary (per-slot), and a
  contested classic fallback;
- every span obeys the phase-order invariants (announce <= first vote
  <= decide) and the telescoping identity: the five durations sum
  exactly to ``ticks_to_view_change``;
- flight-recorder rings that evicted a window's opening emit that span
  with ``truncated: true`` and no milestone/duration claims — explicit
  ignorance instead of invented ticks;
- the streaming ``LineageFold`` is chunk-split invariant and its
  checkpoint state round-trips through JSON;
- the schema v12 field-name constants pin the lineage module's tuples.
"""
import dataclasses
import json

import numpy as np
import pytest

from rapid_tpu.engine.diff import LINEAGE_FAMILIES, run_lineage_differential
from rapid_tpu.telemetry.lineage import (LINEAGE_DURATIONS,
                                         LINEAGE_MILESTONES, LineageFold,
                                         PhaseColumns, fold_spans,
                                         lineage_from_recorder,
                                         lineage_summary)
from rapid_tpu.telemetry.schema import (LINEAGE_DURATION_NAMES,
                                        LINEAGE_MILESTONE_NAMES,
                                        validate_lineage_span,
                                        validate_lineage_summary)


# ---------------------------------------------------------------------------
# oracle vs engine, four families
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def results64():
    return {family: run_lineage_differential(family, 64)
            for family in LINEAGE_FAMILIES}


def test_lineage_bit_identical_n64_all_families(results64):
    for family, res in results64.items():
        res.assert_identical()
    # Steady state must fold the empty stream on both sides.
    assert all(not spans
               for spans in results64["steady"].engine_spans.values())
    # The fault families must actually exercise the fold.
    for family in ("crash_burst", "delay", "contested"):
        assert any(results64[family].engine_spans.values()), family
    # The contested family covers the classic 1a/1b/2a/2b milestones.
    contested = [s for spans in results64["contested"].engine_spans.values()
                 for s in spans]
    assert any(s["fallback"] and s["milestones"]["phase1a_tick"] is not None
               for s in contested)


@pytest.mark.slow
@pytest.mark.parametrize("family", LINEAGE_FAMILIES)
def test_lineage_bit_identical_n256(family):
    run_lineage_differential(family, 256).assert_identical()


# ---------------------------------------------------------------------------
# span invariants
# ---------------------------------------------------------------------------


def test_span_invariants_and_telescoping_sum(results64):
    spans = [s
             for family in ("crash_burst", "delay", "contested")
             for stream in results64[family].engine_spans.values()
             for s in stream]
    assert spans
    for sp in spans:
        assert validate_lineage_span(sp) == []
        assert not sp["truncated"]
        ms, d = sp["milestones"], sp["decide_tick"]
        assert sp["window_start"] < d
        for name in LINEAGE_MILESTONES:
            if ms[name] is not None:
                assert sp["window_start"] < ms[name] <= d, name
        if ms["announce_tick"] is not None:
            if ms["first_vote_tick"] is not None:
                assert ms["announce_tick"] <= ms["first_vote_tick"]
        dur = sp["durations"]
        assert all(v is not None and v >= 0 for v in dur.values())
        assert sum(dur.values()) == sp["ticks_to_view_change"]
        if sp["fallback"]:
            assert dur["fast_vote_wait"] == 0
        else:
            assert dur["fallback_wait"] == 0
            assert dur["classic_phase_ticks"] == 0
    summary = lineage_summary(spans)
    assert validate_lineage_summary(summary) == []
    assert summary["spans"] == len(spans)
    assert summary["fallbacks"] >= 1


# ---------------------------------------------------------------------------
# recorder-ring truncation
# ---------------------------------------------------------------------------

_RING_GAUGES = ("tick", "alerts_in_flight", "cut_reports", "vote_tally",
                "announces", "decides", "px_timers_armed")


def _ring_payload(rows, ticks_recorded):
    return {"gauges": list(_RING_GAUGES),
            "rows": [list(r) for r in rows],
            "ticks_recorded": int(ticks_recorded)}


def _ring_rows(first_tick):
    # [tick, alerts, cut_reports, vote_tally, announces, decides, timers]
    t = first_tick
    return [
        [t + 0, 2, 0, 0, 0, 0, 0],
        [t + 1, 0, 3, 0, 1, 0, 0],
        [t + 2, 0, 0, 5, 0, 1, 0],   # decide closes window 1
        [t + 3, 4, 0, 0, 0, 0, 0],
        [t + 4, 0, 2, 0, 1, 0, 0],
        [t + 5, 0, 0, 6, 0, 1, 0],   # decide closes window 2
    ]


def test_recorder_truncated_head_is_explicit():
    # Ring evicted ticks before the retained range: the first in-ring
    # decide's window opened in the evicted past, so that span must be
    # truncated with no milestone/duration claims.
    payload = _ring_payload(_ring_rows(40), ticks_recorded=45 + 6)
    spans = lineage_from_recorder(payload)
    assert [s["truncated"] for s in spans] == [True, False]
    head = spans[0]
    assert head["window_start"] is None
    assert head["ticks_to_view_change"] is None
    assert all(v is None for v in head["milestones"].values())
    assert all(v is None for v in head["durations"].values())
    assert validate_lineage_span(head) == []
    # The second window opened inside the ring: fully attributed.
    tail = spans[1]
    assert tail["window_start"] == 42 and tail["decide_tick"] == 45
    assert sum(tail["durations"].values()) == 3
    # Truncation is counted, not averaged away.
    assert lineage_summary(spans)["truncated"] == 1


def test_recorder_full_ring_is_not_truncated():
    payload = _ring_payload(_ring_rows(1), ticks_recorded=6)
    spans = lineage_from_recorder(payload)
    assert [s["truncated"] for s in spans] == [False, False]
    # Ring streams cannot see classic-phase traffic; the fold must not
    # invent 1a..2b boundaries.
    assert all(s["milestones"]["phase1a_tick"] is None for s in spans)


# ---------------------------------------------------------------------------
# streaming fold: chunk-split invariance + checkpoint round trip
# ---------------------------------------------------------------------------


def _synthetic_cols():
    # Two windows; the second decided by classic fallback with every
    # milestone on a distinct tick, so any chunk-boundary bug shifts a
    # boundary and fails the comparison.
    T = 16
    z = lambda: np.zeros(T, np.int64)
    cols = {f.name: z() for f in dataclasses.fields(PhaseColumns)}
    cols["tick"] = np.arange(1, T + 1, dtype=np.int64)
    cols["announce"] = np.zeros(T, bool)
    cols["decide"] = np.zeros(T, bool)
    cols["alert_sent"][[0, 8]] = 3
    cols["alert_delivered"][[1, 9]] = 2
    cols["announce"][[2, 10]] = True
    cols["fast_vote_sent"][[3, 11]] = 5
    cols["decide"][4] = True
    cols["timers_armed"][11] = 1
    cols["phase1a_sent"][12] = 4
    cols["phase1b_sent"][13] = 3
    cols["phase2a_sent"][14] = 4
    cols["phase2b_sent"][15] = 3
    cols["decide"][15] = True
    return PhaseColumns(**cols)


def _slice_cols(cols, lo, hi):
    vals = {}
    for f in dataclasses.fields(PhaseColumns):
        v = getattr(cols, f.name)
        vals[f.name] = None if v is None else v[lo:hi]
    return PhaseColumns(**vals)


def test_lineage_fold_chunk_split_invariant():
    cols = _synthetic_cols()
    whole = fold_spans(cols, start_tick=0)
    assert [s["fallback"] for s in whole] == [False, True]
    assert sum(whole[1]["durations"].values()) == 11
    T = cols.tick.size
    for step in (1, 2, 3, 5, 7, 16):
        fold = LineageFold(0)
        spans = []
        for lo in range(0, T, step):
            spans.extend(fold.fold_columns(_slice_cols(cols, lo, lo + step)))
        assert spans == whole, f"chunk size {step}"


def test_lineage_fold_state_round_trips_through_json():
    cols = _synthetic_cols()
    whole = fold_spans(cols, start_tick=0)
    for cut in (3, 6, 12):
        fold = LineageFold(0)
        spans = fold.fold_columns(_slice_cols(cols, 0, cut))
        # Checkpoint: the open window crosses the save/restore boundary.
        blob = json.loads(json.dumps(fold.state_dict()))
        resumed = LineageFold.from_state(blob)
        spans += resumed.fold_columns(_slice_cols(cols, cut, cols.tick.size))
        assert spans == whole, f"cut at {cut}"


# ---------------------------------------------------------------------------
# schema pins
# ---------------------------------------------------------------------------


def test_schema_constants_pin_lineage_module():
    assert tuple(LINEAGE_DURATION_NAMES) == LINEAGE_DURATIONS
    assert tuple(LINEAGE_MILESTONE_NAMES) == LINEAGE_MILESTONES
