"""Engine kernel tests: topology parity, jitted smoke, vote counting.

The heavyweight oracle-vs-engine differentials live in
``tests/test_engine_diff.py``; these are the fast structural checks.
"""
import numpy as np
import pytest

from rapid_tpu import hashing
from rapid_tpu.engine import (
    build_topology,
    engine_step,
    init_state,
    simulate,
    state_config_id,
    reset_trace_count,
    trace_count,
)
from rapid_tpu.engine.state import I32_MAX, crash_faults
from rapid_tpu.engine.topology import ring_permutations
from rapid_tpu.oracle.membership_view import MembershipView, uid_of
from rapid_tpu.settings import Settings
from rapid_tpu.types import Endpoint, NodeId

SETTINGS = Settings()


def make_members(n):
    endpoints = [Endpoint(f"n{i}.sim", 5000) for i in range(n)]
    node_ids = [NodeId(i + 1, (i + 1) * 7919) for i in range(n)]
    view = MembershipView(SETTINGS.K, node_ids, endpoints)
    return endpoints, node_ids, view


def boot_engine(n, start_tick=0):
    endpoints, _, view = make_members(n)
    uids = [uid_of(e) for e in endpoints]
    return endpoints, view, init_state(uids, view._id_fp_sum, SETTINGS,
                                       start_tick=start_tick)


# ---------------------------------------------------------------------------
# topology kernel
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n", [2, 5, 64])
def test_topology_matches_oracle(n):
    import jax.numpy as jnp

    endpoints, _, view = make_members(n)
    uids = np.asarray([uid_of(e) for e in endpoints], dtype=np.uint64)
    uid_hi, uid_lo = hashing.np_to_limbs(uids)
    order, rank = ring_permutations(np, uid_hi, uid_lo, SETTINGS.K)
    member = jnp.ones((n,), bool)
    subj_idx, obs_idx, _, fd_active, _ = build_topology(
        jnp, member, jnp.asarray(order), jnp.asarray(rank))
    subj_idx = np.asarray(subj_idx)
    obs_idx = np.asarray(obs_idx)
    fd_active = np.asarray(fd_active)

    slot_of = {e: i for i, e in enumerate(endpoints)}
    for i, e in enumerate(endpoints):
        oracle_subj = [slot_of[s] for s in view.get_subjects_of(e)]
        oracle_obs = [slot_of[o] for o in view.get_observers_of(e)]
        assert list(subj_idx[i]) == oracle_subj
        assert list(obs_idx[i]) == oracle_obs
        # one failure detector per *unique* subject, first ring wins
        seen = set()
        expect_active = []
        for s in oracle_subj:
            expect_active.append(s not in seen)
            seen.add(s)
        assert list(fd_active[i]) == expect_active


def test_topology_nonmember_rows_masked():
    import jax.numpy as jnp

    endpoints, _, _ = make_members(8)
    uids = np.asarray([uid_of(e) for e in endpoints], dtype=np.uint64)
    uid_hi, uid_lo = hashing.np_to_limbs(uids)
    order, rank = ring_permutations(np, uid_hi, uid_lo, SETTINGS.K)
    member = jnp.asarray([True] * 6 + [False] * 2)
    subj_idx, obs_idx, gk_idx, fd_active, _ = build_topology(
        jnp, member, jnp.asarray(order), jnp.asarray(rank))
    assert np.all(np.asarray(subj_idx)[6:] == np.arange(6, 8)[:, None])
    assert np.all(np.asarray(obs_idx)[6:] == np.arange(6, 8)[:, None])
    assert not np.asarray(fd_active)[6:].any()
    # member rows never point at a non-member
    assert np.asarray(subj_idx)[:6].max() < 6
    assert np.asarray(obs_idx)[:6].max() < 6


@pytest.mark.parametrize("n,extra", [(5, 3), (32, 4)])
def test_topology_gatekeepers_match_oracle(n, extra):
    import jax.numpy as jnp

    endpoints, _, _ = make_members(n + extra)
    view = MembershipView(SETTINGS.K,
                          [NodeId(i + 1, (i + 1) * 7919) for i in range(n)],
                          endpoints[:n])
    uids = np.asarray([uid_of(e) for e in endpoints], dtype=np.uint64)
    uid_hi, uid_lo = hashing.np_to_limbs(uids)
    order, rank = ring_permutations(np, uid_hi, uid_lo, SETTINGS.K)
    member = jnp.asarray([True] * n + [False] * extra)
    _, _, gk_idx, _, _ = build_topology(
        jnp, member, jnp.asarray(order), jnp.asarray(rank))
    gk_idx = np.asarray(gk_idx)

    slot_of = {e: i for i, e in enumerate(endpoints)}
    for s in range(n, n + extra):
        oracle_gk = [slot_of[g]
                     for g in view.get_expected_observers_of(endpoints[s])]
        assert list(gk_idx[s]) == oracle_gk
    # member rows of gk_idx are self-pointers
    assert np.all(gk_idx[:n] == np.arange(n)[:, None])


# ---------------------------------------------------------------------------
# consensus kernel
# ---------------------------------------------------------------------------


def test_segmented_vote_count_matches_bincount():
    import jax.numpy as jnp

    from rapid_tpu.engine.votes import count_fast_round, segmented_vote_count

    rng = np.random.default_rng(7)
    c = 65
    values = rng.integers(0, 4, size=c)  # 4 distinct proposals
    vote_hi = jnp.asarray(values.astype(np.uint32))
    vote_lo = jnp.asarray((values * 977).astype(np.uint32))
    valid = jnp.asarray(rng.random(c) < 0.8)

    counts = np.asarray(segmented_vote_count(jnp, vote_hi, vote_lo, valid))
    valid_np = np.asarray(valid)
    for i in range(c):
        expect = int(np.sum(valid_np & (values == values[i]))) \
            if valid_np[i] else 0
        assert counts[i] == expect

    n_member = jnp.int32(c)
    decided, winner = count_fast_round(jnp, vote_hi, vote_lo, valid, n_member)
    quorum = c - (c - 1) // 4
    best = max(int(np.sum(valid_np & (values == v))) for v in range(4))
    assert int(winner) == best
    assert bool(decided) == (int(valid_np.sum()) >= quorum and best >= quorum)


def test_fast_quorum_formula():
    import jax.numpy as jnp

    from rapid_tpu.engine.votes import fast_quorum

    for n, expect in [(1, 1), (4, 4), (5, 4), (16, 13), (100, 76)]:
        assert int(fast_quorum(jnp, jnp.int32(n))) == expect


# ---------------------------------------------------------------------------
# jitted step smoke (tier-1 acceptance: one step = one jitted call)
# ---------------------------------------------------------------------------


def test_engine_step_smoke_n64_single_trace():
    from dataclasses import replace

    # A distinct (but behaviorally identical) Settings instance guarantees a
    # fresh jit cache entry, so the trace count below is deterministic even
    # if other tests already compiled the step at this shape; the reset
    # makes the counter itself independent of test execution order.
    settings = replace(SETTINGS, seed=1234)
    endpoints, _, view = make_members(64)
    uids = [uid_of(e) for e in endpoints]
    state = init_state(uids, view._id_fp_sum, settings)
    faults = crash_faults([I32_MAX] * 64)

    reset_trace_count()
    before = trace_count()
    assert before == 0
    state1, log1 = engine_step(state, faults, settings)
    first_trace = trace_count() - before
    assert first_trace == 1, "first call should trace the step body once"
    assert int(state1.tick) == 1
    assert int(log1.n_member) == 64

    # further calls reuse the compiled step: the traced body never reruns
    state2, _ = engine_step(state1, faults, settings)
    state3, _ = engine_step(state2, faults, settings)
    assert trace_count() - before == 1
    assert int(state3.tick) == 3
    assert state_config_id(state3) == view.get_current_configuration_id()


def test_simulate_scan_compiles_step_body_exactly_once():
    from dataclasses import replace

    # Compile stability for the scanned path: lax.scan must trace the
    # tick body once for the whole run, and an identical second run must
    # hit the jit cache without retracing (fresh Settings row as above).
    settings = replace(SETTINGS, seed=4321)
    endpoints, _, view = make_members(32)
    uids = [uid_of(e) for e in endpoints]
    state = init_state(uids, view._id_fp_sum, settings)
    crash = [I32_MAX] * 32
    crash[3] = 5
    faults = crash_faults(crash)

    reset_trace_count()
    final, logs = simulate(state, faults, 40, settings)
    assert trace_count() == 1, \
        "a 40-tick scan must trace the step body exactly once"
    assert int(final.tick) == 40

    simulate(state, faults, 40, settings)
    assert trace_count() == 1, "identical rerun must not retrace"


def test_simulate_scan_matches_stepwise():
    _, _, state = boot_engine(16)
    crash = [I32_MAX] * 16
    crash[2] = 3
    faults = crash_faults(crash)

    final_scan, logs = simulate(state, faults, 25, SETTINGS)
    s = state
    for _ in range(25):
        s, _ = engine_step(s, faults, SETTINGS)
    assert int(final_scan.tick) == int(s.tick) == 25
    assert np.array_equal(np.asarray(final_scan.fc), np.asarray(s.fc))
    assert np.array_equal(np.asarray(final_scan.member),
                          np.asarray(s.member))
    assert np.asarray(logs.tick).tolist() == list(range(1, 26))


def test_engine_detects_and_removes_crash_burst():
    """End-to-end engine-only: a crash burst yields one view change with
    the oracle-predicted timing (notify t1+100, decide t1+103)."""
    _, view, state = boot_engine(32)
    crash = [I32_MAX] * 32
    for s in (4, 9):
        crash[s] = 5
    faults = crash_faults(crash)
    final, logs = simulate(state, faults, 130, SETTINGS)

    ann = np.asarray(logs.announce_now)
    dec = np.asarray(logs.decide_now)
    ticks = np.asarray(logs.tick)
    assert ticks[ann].tolist() == [112]
    assert ticks[dec].tolist() == [113]
    i = int(np.argmax(dec))
    assert np.nonzero(np.asarray(logs.decision[i]))[0].tolist() == [4, 9]
    assert int(np.asarray(logs.n_member)[i]) == 30
    # config id after the removal matches the oracle view algebra
    view.ring_delete(Endpoint("n4.sim", 5000))
    view.ring_delete(Endpoint("n9.sim", 5000))
    assert state_config_id(final) == view.get_current_configuration_id()


def test_bench_engine_emits_json_with_trailing_newline(capsys):
    import importlib.util
    import json
    import pathlib

    path = pathlib.Path(__file__).resolve().parent.parent / "benchmarks" \
        / "bench_engine.py"
    spec = importlib.util.spec_from_file_location("bench_engine", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rc = mod.main(["--n", "64", "--ticks", "8"])
    out = capsys.readouterr().out
    assert rc == 0
    assert out.endswith("\n"), "BENCH JSON must end with a newline"
    payload = json.loads(out)
    assert payload["bench"] == "engine_tick"
    assert payload["n"] == 64
    assert payload["ticks_per_sec"] > 0
    assert payload["final_members"] == 64


def test_bench_engine_churn_scenario_writes_out_file(tmp_path):
    import importlib.util
    import json
    import pathlib

    path = pathlib.Path(__file__).resolve().parent.parent / "benchmarks" \
        / "bench_engine.py"
    spec = importlib.util.spec_from_file_location("bench_engine", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = tmp_path / "bench.json"
    rc = mod.main(["--scenario", "churn", "--n", "64", "--ticks", "40",
                   "--burst", "4", "--seed", "7", "--out", str(out)])
    assert rc == 0
    text = out.read_text()
    assert text.endswith("\n"), "BENCH JSON must end with a newline"
    payload = json.loads(text)
    assert payload["bench"] == "engine_tick"
    assert payload["scenario"] == "churn"
    assert payload["n"] == 64
    assert payload["churn_bursts"] > 0
    assert payload["decisions"] == payload["churn_bursts"]
    assert payload["ticks_per_sec"] > 0
    # every join burst decided and the matching leave burst decided too:
    # membership oscillates back to n by the end of the run
    assert payload["final_members"] == 64


# ---------------------------------------------------------------------------
# 64-bit limb helpers added for the engine
# ---------------------------------------------------------------------------


def test_limb_sub_and_sum():
    rng = np.random.default_rng(11)
    vals = rng.integers(0, 1 << 64, size=33, dtype=np.uint64)
    hi, lo = hashing.np_to_limbs(vals)
    shi, slo = hashing.sum64(np, hi, lo)
    expect = int(vals.sum(dtype=np.uint64))
    assert hashing.from_limbs(int(shi), int(slo)) == expect

    a, b = int(vals[0]), int(vals[1])
    ahi, alo = hashing.to_limbs(a)
    bhi, blo = hashing.to_limbs(b)
    with np.errstate(over="ignore"):  # mod-2^32 wraparound is the semantics
        dhi, dlo = hashing.sub64(np, np.uint32(ahi), np.uint32(alo),
                                 np.uint32(bhi), np.uint32(blo))
    assert hashing.from_limbs(int(dhi), int(dlo)) == (a - b) % (1 << 64)


def test_hash64_limbs_dynseed_matches_static():
    rng = np.random.default_rng(13)
    vals = rng.integers(0, 1 << 64, size=16, dtype=np.uint64)
    hi, lo = hashing.np_to_limbs(vals)
    for seed in (0, 1, 12345):
        ehi, elo = hashing.hash64_limbs(np, hi, lo, seed=seed)
        shi, slo = hashing.to_limbs(seed)
        with np.errstate(over="ignore"):  # mod-2^32 wraparound semantics
            dhi, dlo = hashing.hash64_limbs_dynseed(
                np, hi, lo, np.uint32(shi), np.uint32(slo))
        assert np.array_equal(ehi, dhi) and np.array_equal(elo, dlo)
