"""On-device invariant monitor: clean runs stay silent, seeded
corruptions flag the exact bit at the exact tick, escalation names both,
and the disabled path compiles the checks out entirely."""
from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rapid_tpu import hashing
from rapid_tpu.engine import invariants
from rapid_tpu.engine.invariants import (ALL_BITS, BIT_OF,
                                         InvariantViolationError, check_run,
                                         check_step, describe_bits,
                                         expand_violations)
from rapid_tpu.engine.paxos import synthetic_contested_schedule
from rapid_tpu.engine.state import I32_MAX, crash_faults, init_state
from rapid_tpu.engine.step import simulate
from rapid_tpu.settings import Settings
from rapid_tpu.telemetry.metrics import engine_metrics, summarize

# Distinct seeds keep each test's Settings a fresh jit-cache row, so no
# test inherits another's compiled step.
SETTINGS = Settings(invariant_checks=True, seed=7001)


def synthetic_uids(n: int, seed: int = 0) -> np.ndarray:
    """Same synthetic identity scheme as benchmarks/bench_engine.py."""
    hi, lo = hashing.np_to_limbs(np.arange(1, n + 1, dtype=np.uint64))
    hi, lo = hashing.hash64_limbs(np, hi, lo, seed=0xBEEF ^ (seed & 0xFFFF))
    return hashing.np_from_limbs(hi, lo)


def boot(n: int, settings=SETTINGS, member=None):
    return init_state(synthetic_uids(n), id_fp_sum=0, settings=settings,
                      member=member)


def no_faults(n: int):
    return crash_faults([I32_MAX] * n)


def bit(name: str) -> int:
    return 1 << BIT_OF[name]


# ---------------------------------------------------------------------------
# registry / decoding
# ---------------------------------------------------------------------------


def test_bit_registry_is_append_only_contract():
    # Bit positions are part of the telemetry contract; renumbering would
    # silently re-label persisted BENCH artifacts.
    assert [b for _, b in invariants.INVARIANT_BITS] == \
        [0, 1, 2, 3, 4, 5, 6]
    assert BIT_OF["ring_degree"] == 0
    assert BIT_OF["memsum"] == 5
    assert BIT_OF["ghost_reports"] == 6
    assert ALL_BITS == 0b1111111


def test_describe_bits_decodes_in_bit_order():
    assert describe_bits(0) == []
    assert describe_bits(bit("memsum") | bit("ring_degree")) == \
        ["ring_degree", "memsum"]
    assert describe_bits(ALL_BITS) == [n for n, _ in
                                       invariants.INVARIANT_BITS]


# ---------------------------------------------------------------------------
# clean runs: monitor on, zero violations
# ---------------------------------------------------------------------------


def test_clean_steady_run_n256_zero_violations():
    n = 256
    crash = [I32_MAX] * n
    for slot in range(0, n, 64):
        crash[slot] = 5
    state = boot(n)
    final, logs = simulate(state, crash_faults(crash), 130, SETTINGS)
    assert int(np.asarray(logs.inv_bits).max()) == 0
    assert expand_violations(logs) == []
    check_run(logs)  # no-op on a clean run
    metrics = engine_metrics(logs)
    summary = summarize(metrics)
    assert summary.invariant_violations == 0
    assert summary.decisions >= 1  # the crash burst actually decided


def test_clean_contested_run_exercises_rank_invariants():
    # Classic-Paxos fallback rounds mutate every px_* rank array; the
    # rank_order / unique_decide checks must stay silent through them.
    n = 64
    settings = replace(SETTINGS, seed=7002)
    uids = synthetic_uids(n)
    sched, info = synthetic_contested_schedule(n, settings, 48, uids=uids)
    state = init_state(uids, id_fp_sum=0, settings=settings)
    _, logs = simulate(state, no_faults(n), 48, settings,
                       fallback=sched)
    assert info["instances"] >= 1
    assert int(np.asarray(logs.inv_bits).max()) == 0


# ---------------------------------------------------------------------------
# injected corruptions: exact bit, exact tick
# ---------------------------------------------------------------------------


def test_memsum_corruption_flags_bit5_from_first_tick():
    n = 64
    settings = replace(SETTINGS, seed=7003)
    state = boot(n, settings)
    state = state._replace(memsum_lo=state.memsum_lo + jnp.uint32(1))
    _, logs = simulate(state, no_faults(n), 4, settings)
    rows = expand_violations(logs)
    assert rows[0] == (1, bit("memsum"), ["memsum"])
    assert len(rows) == 4  # the corrupted sum persists every tick


def test_broken_ring_edge_flags_ring_degree():
    # A member row whose observer edge self-points is not a single K-ring
    # cycle any more; the monitor must flag it even though no alert fires.
    n = 64
    settings = replace(SETTINGS, seed=7004)
    state = boot(n, settings)
    state = state._replace(obs_idx=state.obs_idx.at[5, 0].set(5))
    _, logs = simulate(state, no_faults(n), 3, settings)
    rows = expand_violations(logs)
    assert rows[0] == (1, bit("ring_degree"), ["ring_degree"])


def test_dormant_row_corruption_flags_ring_degree():
    # Dormant rows must self-point both directions; pointing one at a
    # member slot means the topology rebuild was corrupted.
    n = 64
    member = np.ones(n, bool)
    member[-8:] = False
    settings = replace(SETTINGS, seed=7005)
    state = boot(n, settings, member=member)
    state = state._replace(subj_idx=state.subj_idx.at[n - 1, 0].set(0))
    _, logs = simulate(state, no_faults(n), 3, settings)
    rows = expand_violations(logs)
    assert rows[0] == (1, bit("ring_degree"), ["ring_degree"])


def test_rank_corruption_flags_rank_order():
    # vrnd > rnd violates the classic-Paxos promise ordering (and a
    # non-zero vrnd without a value is doubly malformed — same bit).
    n = 64
    settings = replace(SETTINGS, seed=7006)
    state = boot(n, settings)
    state = state._replace(px_vrnd_r=state.px_vrnd_r.at[3].set(5))
    _, logs = simulate(state, no_faults(n), 3, settings)
    rows = expand_violations(logs)
    assert rows[0] == (1, bit("rank_order"), ["rank_order"])


def test_empty_proposal_decide_flags_unique_decide():
    # Forge a fast round about to reach quorum for an *empty* proposal
    # mask: every member voted, fingerprints agree, but the decision
    # carries no change — a protocol impossibility the monitor must flag
    # the tick the votes land.
    n = 64
    settings = replace(SETTINGS, seed=7007)
    state = boot(n, settings)
    state = state._replace(
        announced=jnp.asarray(True),
        vote_pending=jnp.asarray(True),
        voters=state.member,
        announce_tick=state.tick,  # votes land next tick
    )
    _, logs = simulate(state, no_faults(n), 2, settings)
    rows = expand_violations(logs)
    assert rows, "forged empty-proposal quorum was not flagged"
    tick, bits, names = rows[0]
    assert tick == 1
    assert bits & bit("unique_decide")
    assert "unique_decide" in names


# ---------------------------------------------------------------------------
# check_step unit semantics (direct call, no scan)
# ---------------------------------------------------------------------------


def _step_bits(pre, post, decide=False, fast=False, classic=False,
               classic_mask=None):
    n = pre.member.shape[0]
    return int(check_step(
        jnp, pre, post,
        decide_now=jnp.asarray(decide),
        fast_decide=jnp.asarray(fast),
        classic_decide=jnp.asarray(classic),
        fast_mask=pre.proposal,
        classic_mask=(jnp.zeros(n, bool) if classic_mask is None
                      else classic_mask)))


def test_check_step_epoch_regression_flags_epoch_monotone():
    pre = boot(8, replace(SETTINGS, seed=7008))
    post = pre._replace(epoch=pre.epoch - jnp.int32(1))
    bits = _step_bits(pre, post)
    assert bits & bit("epoch_monotone")
    # decide_now=True must demand epoch advance by exactly one
    assert _step_bits(pre, pre, decide=True) & bit("epoch_monotone")
    assert not _step_bits(pre, pre._replace(epoch=pre.epoch + 1),
                          decide=True) & bit("epoch_monotone")


def test_check_step_report_retraction_flags_report_monotone():
    base = boot(8, replace(SETTINGS, seed=7009))
    pre = base._replace(reports=base.reports.at[0, 0].set(True))
    post = pre._replace(reports=jnp.zeros_like(pre.reports))
    assert _step_bits(pre, post) & bit("report_monotone")
    # ...but a decided view change legitimately clears the detector
    assert not _step_bits(pre, post._replace(epoch=pre.epoch + 1),
                          decide=True, fast=True) & bit("report_monotone")


def test_check_step_double_decide_flags_unique_decide():
    pre = boot(8, replace(SETTINGS, seed=7010))
    pre = pre._replace(announced=jnp.asarray(True),
                       proposal=pre.proposal.at[0].set(True))
    post = pre._replace(epoch=pre.epoch + 1)
    both = _step_bits(pre, post, decide=True, fast=True, classic=True,
                      classic_mask=pre.proposal)
    assert both & bit("unique_decide")
    # an un-announced fast decision is equally impossible
    ghost = pre._replace(announced=jnp.asarray(False))
    assert _step_bits(ghost, ghost._replace(epoch=ghost.epoch + 1),
                      decide=True, fast=True) & bit("unique_decide")
    # a legitimate single-source decision passes
    assert not _step_bits(pre, post, decide=True, fast=True) \
        & bit("unique_decide")


def test_check_step_ghost_report_flags_ghost_reports():
    # A report cell filling with no alert in flight and no invalidation
    # derivation is exactly the stale-partition ghost bit 6 flags.
    base = boot(8, replace(SETTINGS, seed=7015))
    post = base._replace(reports=base.reports.at[0, 0].set(True))
    assert _step_bits(base, post) & bit("ghost_reports")
    # ...a cell whose ring observer had an alert in flight is legitimate
    obs0 = int(np.asarray(base.obs_idx)[0, 0])
    pre = base._replace(
        pending_deliver=base.pending_deliver.at[obs0, 0].set(True))
    assert not _step_bits(pre, post._replace(
        pending_deliver=pre.pending_deliver)) & bit("ghost_reports")
    # ...and so is one derived by edge invalidation: destination and ring
    # observer both already sit at the low watermark.
    obs4 = int(np.asarray(base.obs_idx)[0, 4])
    reports = base.reports.at[0, :4].set(True).at[obs4, :4].set(True)
    pre = base._replace(reports=reports)
    impl = pre._replace(reports=reports.at[0, 4].set(True))
    assert not _step_bits(pre, impl) & bit("ghost_reports")


def test_ghost_report_corruption_flagged_in_simulated_run():
    # Seed a crash run whose delivered alerts corrupt: spoof one report
    # cell into the state mid-flight by pre-filling a cell the monitor can
    # prove nothing delivered — tick 1 post-state of a doctored pre-state.
    n = 64
    settings = replace(SETTINGS, seed=7016)
    state = boot(n, settings)
    doctored = state._replace(reports=state.reports.at[2, 3].set(True))
    bits = int(check_step(
        jnp, state, doctored,
        decide_now=jnp.asarray(False), fast_decide=jnp.asarray(False),
        classic_decide=jnp.asarray(False), fast_mask=state.proposal,
        classic_mask=jnp.zeros(n, bool), settings=settings))
    assert bits == bit("ghost_reports")
    assert describe_bits(bits) == ["ghost_reports"]


# ---------------------------------------------------------------------------
# escalation
# ---------------------------------------------------------------------------


def test_check_run_raises_naming_tick_and_invariants(tmp_path):
    n = 64
    settings = replace(SETTINGS, seed=7011)
    state = boot(n, settings)
    state = state._replace(memsum_lo=state.memsum_lo + jnp.uint32(1))
    final, logs = simulate(state, no_faults(n), 4, settings)
    metrics = engine_metrics(logs)
    artifact = str(tmp_path / "inv.jsonl")
    with pytest.raises(InvariantViolationError) as exc:
        check_run(logs, metrics=metrics, artifact=artifact)
    err = exc.value
    assert err.report.tick == 1
    assert err.report.field == "invariants.memsum"
    assert err.report.engine == bit("memsum")
    assert "tick 1" in str(err) and "memsum" in str(err)
    # the JSONL artifact landed and carries the violation records
    lines = (tmp_path / "inv.jsonl").read_text().strip().splitlines()
    assert lines
    assert any("invariant_violation" in ln for ln in lines)


def test_telemetry_gauge_counts_violating_ticks():
    n = 64
    settings = replace(SETTINGS, seed=7012)
    state = boot(n, settings)
    state = state._replace(px_vrnd_r=state.px_vrnd_r.at[0].set(9))
    final, logs = simulate(state, no_faults(n), 5, settings)
    metrics = engine_metrics(logs)
    assert all(m.invariant_violations == bit("rank_order")
               for m in metrics)
    assert summarize(metrics).invariant_violations == 5


# ---------------------------------------------------------------------------
# zero overhead when disabled
# ---------------------------------------------------------------------------


def test_disabled_monitor_never_calls_check_step(monkeypatch):
    import importlib

    step_module = importlib.import_module("rapid_tpu.engine.step")
    calls = []
    real = invariants.check_step

    def spy(*args, **kwargs):
        calls.append(1)
        return real(*args, **kwargs)

    # step.py calls invariants.check_step by module attribute, so the spy
    # sees every compile-time entry into the monitor.
    monkeypatch.setattr(invariants, "check_step", spy)

    n = 16
    off = Settings(invariant_checks=False, seed=7013)
    on = replace(off, invariant_checks=True)
    state = boot(n, off)
    faults = no_faults(n)

    step_module.step(state, faults, off)
    assert calls == [], "disabled monitor must never enter invariants.py"
    step_module.step(state, faults, on)
    assert len(calls) == 1

    # The flag is static: the enabled jaxpr strictly grows, the disabled
    # one carries only the constant-zero inv_bits leaf.
    off_eqns = len(jax.make_jaxpr(
        lambda s, f: step_module.step(s, f, off))(state, faults).eqns)
    on_eqns = len(jax.make_jaxpr(
        lambda s, f: step_module.step(s, f, on))(state, faults).eqns)
    assert on_eqns > off_eqns


def test_disabled_monitor_logs_constant_zero_bits():
    n = 32
    settings = Settings(invariant_checks=False, seed=7014)
    state = boot(n, settings)
    # Even a corrupted state logs 0 with the monitor off: the checks are
    # compiled out, not merely ignored.
    state = state._replace(memsum_lo=state.memsum_lo + jnp.uint32(1))
    _, logs = simulate(state, no_faults(n), 3, settings)
    assert int(np.asarray(logs.inv_bits).max()) == 0
