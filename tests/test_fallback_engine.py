"""Classic-Paxos fallback kernel vs the oracle (engine.paxos).

The acceptance contract: ``run_fallback_differential`` proves the batched
kernel bit-identical to ``oracle.paxos`` — decision values, decided tick,
configuration id, and per-phase 1a/1b/2a/2b message counts — at N=64 and
N=256 for a two-way split vote, a three-way split, and a fallback timer
racing a late fast-round quorum. Alongside: the host planner's envelope
rejections, the engine/oracle rank-index and quorum-size parity pins, and
the synthetic contested benchmark schedule.
"""
import numpy as np
import pytest

from rapid_tpu import hashing
from rapid_tpu.engine.diff import (
    default_endpoints,
    engine_events,
    run_adversarial_differential,
    run_fallback_differential,
)
from rapid_tpu.faults import AdversarySchedule, ScriptedPropose
from rapid_tpu.engine.paxos import (
    FallbackEnvelopeError,
    classic_rank_index,
    plan_fallback,
    synthetic_contested_schedule,
)
from rapid_tpu.engine.votes import fast_quorum
from rapid_tpu.oracle.membership_view import uid_of
from rapid_tpu.oracle.paxos import FastPaxos, classic_rank_node_index
from rapid_tpu.oracle.testkit import (
    ManualScheduler,
    NoOpBroadcaster,
    NoOpClient,
)
from rapid_tpu.settings import Settings
from rapid_tpu.types import Endpoint, FastRoundPhase2bMessage

SETTINGS = Settings()


# ---------------------------------------------------------------------------
# contested scenarios (parametrized by cluster size)
# ---------------------------------------------------------------------------


def two_way_split(n):
    """Half the members vote to remove slot 0, half to remove slot 1; no
    fast quorum, slot 0's timer fires first and the classic round decides."""
    values = [[0], [1]]
    votes = {s: (6, s % 2) for s in range(n)}
    delays = {s: (10 if s == 0 else 100) for s in range(n)}
    return values, votes, delays, 30


def three_way_split(n):
    """Three camps, none near the fast quorum; the highest slot's timer
    fires first so the coordinator is not a slot-0 special case."""
    a = n - 2 * (n // 3)
    values = [[0], [1], [2]]
    votes = {s: (6, 0 if s < a else (1 if s < a + n // 3 else 2))
             for s in range(n)}
    delays = {s: (10 if s == n - 1 else 100) for s in range(n)}
    return values, votes, delays, 30


def fallback_racing_fast_quorum(n):
    """A straggler's vote completes the fast quorum at tick 20, one tick
    after slot 0's fallback timer fired: the phase-1a broadcast is on the
    wire when the decision lands and must die on arrival — counted, but
    with no protocol effect."""
    q = n - (n - 1) // 4
    values = [[0], [1]]
    votes = {s: (6, 0 if s < q - 1 else 1) for s in range(n - 1)}
    votes[n - 1] = (19, 0)
    delays = {s: 100 for s in range(n)}
    delays[0] = 13
    return values, votes, delays, 30


SCENARIOS = {
    "two_way_split": two_way_split,
    "three_way_split": three_way_split,
    "racing_fast_quorum": fallback_racing_fast_quorum,
}


@pytest.mark.parametrize("n", [64, 256])
@pytest.mark.parametrize("scenario", sorted(SCENARIOS))
def test_fallback_differential_bit_identical(n, scenario):
    values, votes, delays, ticks = SCENARIOS[scenario](n)
    res = run_fallback_differential(n, values, votes, delays, ticks)
    res.assert_identical()
    # exactly one decision, at the tick and value the planner predicted
    assert [e.kind for e in res.oracle_events] == ["view_change"]
    assert res.oracle_events[0].tick == res.plan_info["decide_tick"]
    winner = int(res.plan_info["winner"])
    assert res.oracle_events[0].slots == tuple(sorted(values[winner]))
    # the contested path really ran: classic rounds carry 1a/1b/2a/2b
    # traffic, the racing scenario a dead-on-arrival 1a broadcast
    total_1a = sum(c["phase1a_sent"] for c in res.engine_phase_counters)
    assert total_1a == n
    if res.plan_info["mode"] == "classic":
        assert sum(c["phase2b_sent"] for c in res.engine_phase_counters) > 0
    else:
        assert res.plan_info["racing"] is True
        assert sum(c["phase1b_sent"] for c in res.engine_phase_counters) == 0


def test_fallback_phase_totals_reach_run_summary():
    """The per-phase traffic shows up in RunSummary.fallback_phase_sent."""
    from rapid_tpu.telemetry.metrics import summarize

    n = 8
    values, votes, delays, ticks = two_way_split(n)
    res = run_fallback_differential(n, values, votes, delays, ticks)
    res.assert_identical()
    summary = summarize(res.engine_metrics)
    expected = {
        phase: sum(c[f"{phase}_sent"] for c in res.oracle_phase_counters)
        for phase in ("fast_vote", "phase1a", "phase1b", "phase2a",
                      "phase2b")
    }
    assert summary.fallback_phase_sent == expected
    assert expected["phase1a"] == n and expected["phase2b"] == n * n


# ---------------------------------------------------------------------------
# fleet-kernel envelope rejections -> adversary-engine exact runs
#
# The fleet kernel's planner still guards itself, but a rejection is now a
# routing hint, not a dead end: every scenario it refuses must run
# bit-identically through ``run_adversarial_differential``. Each test below
# asserts both halves of that contract.
# ---------------------------------------------------------------------------


def _base_scenario(n=8):
    values = [[0], [1]]
    votes = {s: (6, s % 2) for s in range(n)}
    delays = {s: (10 if s == 0 else 100) for s in range(n)}
    return values, votes, delays


def _adversary_equivalent(n, values, votes, delays, seed=11):
    """Lower a planner-style (values, votes, delays) scenario to the
    equivalent unscripted ``AdversarySchedule``."""
    proposes = tuple(
        ScriptedPropose(slot=s, tick=tick, proposal=tuple(values[pid]),
                        delay_ticks=delays[s])
        for s, (tick, pid) in sorted(votes.items()))
    return AdversarySchedule(n=n, proposes=proposes, seed=seed)


def _phase_total(res, key):
    return sum(d[key] for d in res.engine_phase_counters)


def test_timer_firing_mid_fast_count_runs_exactly():
    n = 8
    q = n - (n - 1) // 4
    values = [[0], [1]]
    votes = {s: (6, 0) for s in range(q - 1)}
    votes[n - 1] = (10, 0)  # straggler completes the fast quorum at 11
    delays = {s: 100 for s in votes}
    delays[0] = 2           # fires at 8, while votes are still arriving
    with pytest.raises(FallbackEnvelopeError, match="before the fast"):
        plan_fallback(n, values, votes, delays, SETTINGS)
    res = run_adversarial_differential(
        _adversary_equivalent(n, values, votes, delays), 120)
    res.assert_identical()
    # The mid-count fire really started a classic round before the fast
    # quorum completed, and the view change still landed on every survivor.
    assert _phase_total(res, "phase1a_sent") > 0
    assert any(ev.kind == "view_change"
               for ev in res.engine_events_by_slot[1])


def test_tied_first_timers_run_exactly():
    values, votes, delays = _base_scenario()
    delays[1] = delays[0]
    with pytest.raises(FallbackEnvelopeError, match="unique first"):
        plan_fallback(8, values, votes, delays, SETTINGS)
    res = run_adversarial_differential(
        _adversary_equivalent(8, values, votes, delays), 120)
    res.assert_identical()
    # Both tied coordinators broadcast 1a; rank order breaks the tie.
    assert _phase_total(res, "phase1a_sent") >= 16
    assert any(any(ev.kind == "view_change" for ev in evs)
               for evs in res.engine_events_by_slot)


def test_second_fire_during_classic_round_runs_exactly():
    values, votes, delays = _base_scenario()
    delays[1] = delays[0] + 2  # lands between 1a and the decide
    with pytest.raises(FallbackEnvelopeError, match="rank race"):
        plan_fallback(8, values, votes, delays, SETTINGS)
    res = run_adversarial_differential(
        _adversary_equivalent(8, values, votes, delays), 120)
    res.assert_identical()
    assert _phase_total(res, "phase1a_sent") >= 16
    assert any(any(ev.kind == "view_change" for ev in evs)
               for evs in res.engine_events_by_slot)


def test_plan_rejects_pre_start_propose_tick():
    values, votes, delays = _base_scenario()
    votes[3] = (0, 1)
    with pytest.raises(FallbackEnvelopeError, match="tick >= 1"):
        plan_fallback(8, values, votes, delays, SETTINGS)


def test_plan_rejects_non_member_voter():
    values, votes, delays = _base_scenario()
    member = np.ones(8, bool)
    member[5] = False
    with pytest.raises(FallbackEnvelopeError, match="not a member"):
        plan_fallback(8, values, votes, delays, SETTINGS, member=member)


# ---------------------------------------------------------------------------
# engine/oracle parity pins: rank index and fast-quorum size
# ---------------------------------------------------------------------------


def test_classic_rank_index_matches_oracle():
    endpoints = default_endpoints(32)
    uids = np.asarray([uid_of(e) for e in endpoints], np.uint64)
    hi, lo = hashing.np_to_limbs(uids)
    idx = classic_rank_index(np, hi, lo)
    for s, e in enumerate(endpoints):
        assert int(idx[s]) == classic_rank_node_index(e)


@pytest.mark.parametrize("n", list(range(2, 17)) + [20, 21])
def test_fast_quorum_matches_oracle_minimal_decide(n):
    """Pin the engine's quorum size to the oracle's observed behavior: the
    smallest number of identical fast votes that makes FastPaxos decide.
    Catches the ceil(3N/4) misreading, which diverges at N % 4 == 0."""
    proposal = (Endpoint("p.sim", 1),)
    min_votes = None
    for k in range(1, n + 1):
        decided = []
        fp = FastPaxos(Endpoint("me.sim", 0), 1, n, NoOpClient(),
                       NoOpBroadcaster(), ManualScheduler(), decided.append)
        for i in range(k):
            fp.handle_messages(
                FastRoundPhase2bMessage(Endpoint("v.sim", i), 1, proposal))
        if decided:
            min_votes = k
            break
    assert min_votes == int(fast_quorum(np, np.int32(n)))
    if n % 4 == 0:
        assert min_votes != -(-3 * n) // 4  # ceil(3N/4) undercounts here


# ---------------------------------------------------------------------------
# synthetic contested schedule (the benchmark workload), engine-only
# ---------------------------------------------------------------------------


def test_synthetic_contested_schedule_decides_every_instance():
    from rapid_tpu.engine.state import I32_MAX, crash_faults, init_state
    from rapid_tpu.engine.step import simulate

    n, ticks = 32, 70
    endpoints = default_endpoints(n)
    uids = np.asarray([uid_of(e) for e in endpoints], np.uint64)
    sched, info = synthetic_contested_schedule(n, SETTINGS, ticks, uids=uids)
    assert info["instances"] >= 2

    state = init_state(uids, id_fp_sum=0, settings=SETTINGS)
    faults = crash_faults([I32_MAX] * n)
    final, logs = simulate(state, faults, ticks, SETTINGS, fallback=sched)
    decided = [e for e in engine_events(logs) if e.kind == "view_change"]
    assert [e.tick for e in decided] == info["decide_ticks"]
    assert all(len(e.slots) == 1 for e in decided)
    assert int(np.asarray(final.member).sum()) == n - info["instances"]
