"""Consensus tests, mirroring the reference PaxosTests.java scenario matrix:
fallback recovery, classic-round learning of fast-round results, and the
exhaustive coordinator-rule tables with 100 shuffled quorums per case."""
import random

import pytest

from rapid_tpu.oracle.paxos import FastPaxos, Paxos, classic_rank_node_index
from rapid_tpu.oracle.testkit import (
    DirectBroadcaster,
    DirectMessagingClient,
    ManualScheduler,
    NoOpBroadcaster,
    NoOpClient,
)
from rapid_tpu.types import (
    Endpoint,
    FastRoundPhase2bMessage,
    Phase1aMessage,
    Phase1bMessage,
    Phase2aMessage,
    Phase2bMessage,
    Rank,
)

MAX_INT = 2**31 - 1


def hosts(*specs):
    return tuple(Endpoint.parse(s) for s in specs)


P1 = hosts("127.0.0.1:5891", "127.0.0.1:5821")
P2 = hosts("127.0.0.1:5821", "127.0.0.1:5872")
NOISE = hosts("127.0.0.1:1", "127.0.0.1:2")


def make_instances(n, on_decide, drop_types=None, seed=123):
    instances = {}
    client = DirectMessagingClient(instances, drop_types=drop_types or set())
    broadcaster = DirectBroadcaster(instances, client)
    scheduler = ManualScheduler()
    rng = random.Random(seed)
    for i in range(n):
        addr = Endpoint("127.0.0.1", 1234 + i)
        instances[addr] = FastPaxos(
            addr, configuration_id=1, membership_size=n, client=client,
            broadcaster=broadcaster, scheduler=scheduler,
            on_decide=lambda hosts_, a=addr: on_decide(a, hosts_),
            rng=rng,
        )
    return instances, scheduler, client


@pytest.mark.parametrize("num_nodes", [5, 6, 10, 11, 20])
def test_recovery_for_single_propose(num_nodes):
    """One node proposes; the fast round can't reach quorum, so its fallback
    classic round drives everyone to the proposed value."""
    decisions = {}
    instances, scheduler, _ = make_instances(num_nodes, decisions.__setitem__)
    proposal = list(hosts("172.14.12.3:1234"))
    first = next(iter(instances.values()))
    first.propose(proposal, recovery_delay_ticks=5)
    assert decisions == {}
    scheduler.advance_by(10)
    assert len(decisions) == num_nodes
    assert all(d == proposal for d in decisions.values())


@pytest.mark.parametrize("num_nodes", [5, 6, 10, 11, 20])
def test_recovery_from_fast_round_with_different_proposals(num_nodes):
    """Every node proposes its own address: conflicting fast round, classic
    fallback converges everyone on one of the proposed values."""
    decisions = {}
    instances, scheduler, _ = make_instances(num_nodes, decisions.__setitem__)
    for addr, fp in instances.items():
        fp.propose([addr], recovery_delay_ticks=10)
    scheduler.advance_by(1000)
    assert len(decisions) == num_nodes
    values = {tuple(d) for d in decisions.values()}
    assert len(values) == 1
    decided = next(iter(values))
    assert len(decided) == 1
    assert decided[0] in instances


@pytest.mark.parametrize("num_nodes", [5, 6, 10, 11, 20])
def test_classic_round_after_successful_fast_round(num_nodes):
    """Fast-round messages all lost, but every node voted (locally) for the
    same value; a classic round must learn that result."""
    decisions = {}
    instances, scheduler, client = make_instances(
        num_nodes, decisions.__setitem__, drop_types={FastRoundPhase2bMessage}
    )
    proposal = list(hosts("127.0.0.1:1234"))
    for fp in instances.values():
        fp.propose(proposal, recovery_delay_ticks=10**9)
    assert decisions == {}
    for fp in instances.values():
        fp.start_classic_paxos_round()
    assert len(decisions) == num_nodes
    assert all(d == proposal for d in decisions.values())


@pytest.mark.parametrize(
    "num_nodes,p1,p2,p2_votes,choices",
    [
        (6, P1, P2, 5, (P2,)),
        (6, P1, P2, 1, (P1,)),
        (6, P1, P2, 4, (P1, P2)),
        (6, P1, P2, 2, (P1, P2)),
        (5, P1, P2, 4, (P2,)),
        (5, P1, P2, 1, (P1,)),
        (10, P1, P2, 4, (P1, P2)),
        (10, P1, P2, 1, (P1, P2)),
    ],
)
def test_classic_round_after_fast_round_mixed_values(num_nodes, p1, p2, p2_votes, choices):
    """Mixed fast-round votes lost in transit; classic round must pick a value
    consistent with the Fast Paxos coordinator rule."""
    decisions = {}
    instances, scheduler, client = make_instances(
        num_nodes, decisions.__setitem__, drop_types={FastRoundPhase2bMessage}
    )
    for i, fp in enumerate(instances.values()):
        fp.propose(list(p1 if i < num_nodes - p2_votes else p2),
                   recovery_delay_ticks=10**9)
    assert decisions == {}
    for fp in instances.values():
        fp.start_classic_paxos_round()
    assert len(decisions) == num_nodes
    values = {tuple(d) for d in decisions.values()}
    assert len(values) == 1
    assert next(iter(values)) in choices


def _phase1b(vrnd: Rank, vval, config=1):
    return Phase1bMessage(Endpoint("0.0.0.0", 0), config, rnd=Rank(0, 0),
                          vrnd=vrnd, vval=tuple(vval))


COORDINATOR_CASES = [
    # (N, p1_count@rank(1,1), p2_count@rank(0,MAX), proposals, valid indices)
    (6, 4, 2, (P1, P2, NOISE), {0}),
    (6, 5, 1, (P1, P2, NOISE), {0}),
    (6, 6, 0, (P1, P2, NOISE), {0}),
    (9, 6, 3, (P1, P2, NOISE), {0, 1}),
    (9, 7, 2, (P1, P2, NOISE), {0}),
    (9, 8, 1, (P1, P2, NOISE), {0}),
    (6, 1, 5, (P1, P2, NOISE), {0, 1}),
    (6, 2, 4, (P1, P2, NOISE), {0, 1}),
    (6, 3, 3, (P1, P2, NOISE), {0}),
    (6, 3, 3, (P2, P1, NOISE), {0}),
    (6, 4, 1, (P1, P2, NOISE), {0}),
    (6, 5, 1, (P1, P2, NOISE), {0}),
    (9, 6, 1, (P1, P2, NOISE), {0, 1, 2}),
    (9, 7, 1, (P1, P2, NOISE), {0}),
    (9, 8, 1, (P1, P2, NOISE), {0}),
    (6, 1, 2, (P1, P2, NOISE), {0, 1, 2}),
    (6, 2, 1, (P1, P2, NOISE), {0, 1, 2}),
    (6, 3, 0, (P1, P2, NOISE), {0}),
    (6, 3, 0, (P2, P1, NOISE), {0}),
]


@pytest.mark.parametrize("n,p1n,p2n,proposals,valid", COORDINATOR_CASES)
def test_coordinator_rule(n, p1n, p2n, proposals, valid):
    """Value selection with proposals at different ranks
    (PaxosTests.java coordinatorRuleTests tables)."""
    valid_values = {proposals[i] for i in valid}
    rng = random.Random(n * 1000 + p1n * 100 + p2n)
    paxos = Paxos(Endpoint("127.0.0.1", 1234), 1, n, NoOpClient(),
                  NoOpBroadcaster(), lambda _: None)
    for _ in range(100):
        messages = (
            [_phase1b(Rank(1, 1), proposals[0]) for _ in range(p1n)]
            + [_phase1b(Rank(0, MAX_INT), proposals[1]) for _ in range(p2n)]
            + [_phase1b(Rank(0, i), NOISE) for i in range(p1n + p2n, n)]
        )
        rng.shuffle(messages)
        quorum = messages[: n // 2 + 1]
        chosen = paxos.select_proposal_using_coordinator_rule(quorum)
        assert chosen in valid_values, f"chose {chosen}"


SAME_RANK_CASES = [
    (6, 4, 2, (P1, P2, NOISE), {0, 1}),
    (6, 5, 1, (P1, P2, NOISE), {0}),
    (6, 6, 0, (P1, P2, NOISE), {0}),
    (9, 6, 3, (P1, P2, NOISE), {0, 1}),
    (9, 7, 2, (P1, P2, NOISE), {0}),
    (9, 8, 1, (P1, P2, NOISE), {0}),
    (6, 3, 3, (P1, P2, NOISE), {0, 1}),
    (6, 3, 3, (P2, P1, NOISE), {0, 1}),
    (6, 4, 1, (P1, P2, NOISE), {0, 1}),
    (6, 5, 0, (P1, P2, NOISE), {0}),
    (9, 6, 1, (P1, P2, NOISE), {0, 1, 2}),
    (9, 7, 1, (P1, P2, NOISE), {0}),
    (9, 8, 1, (P1, P2, NOISE), {0}),
    (6, 1, 2, (P1, P2, NOISE), {0, 1, 2}),
    (6, 2, 1, (P1, P2, NOISE), {0, 1, 2}),
    (6, 3, 0, (P1, P2, NOISE), {0}),
    (6, 3, 0, (P2, P1, NOISE), {0}),
]


@pytest.mark.parametrize("n,p1n,p2n,proposals,valid", SAME_RANK_CASES)
def test_coordinator_rule_same_rank(n, p1n, p2n, proposals, valid):
    """Value selection with two proposals at the same (highest) rank
    (PaxosTests.java coordinatorRuleTestsSameRank tables)."""
    valid_values = {proposals[i] for i in valid}
    rng = random.Random(n * 1000 + p1n * 100 + p2n + 7)
    paxos = Paxos(Endpoint("127.0.0.1", 1234), 1, n, NoOpClient(),
                  NoOpBroadcaster(), lambda _: None)
    top = Rank(1, 1)
    for _ in range(100):
        messages = (
            [_phase1b(top, proposals[0]) for _ in range(p1n)]
            + [_phase1b(top, proposals[1]) for _ in range(p2n)]
            + [_phase1b(Rank(0, i), proposals[2]) for i in range(p1n + p2n, n)]
        )
        rng.shuffle(messages)
        quorum = messages[: n // 2 + 1]
        chosen = paxos.select_proposal_using_coordinator_rule(quorum)
        assert chosen in valid_values, f"chose {chosen}"


# ---------------------------------------------------------------------------
# Fast-round quorum tables (FastPaxosWithoutFallbackTests.java:85-148)
# ---------------------------------------------------------------------------

FAST_QUORUM_TABLE = [
    (6, 5), (48, 37), (50, 38), (100, 76), (102, 77),   # even N
    (5, 4), (51, 39), (49, 37), (99, 75), (101, 76),    # odd N
]


def _fast_paxos_single(n, on_decide):
    addr = Endpoint("127.0.0.1", 1234)
    return FastPaxos(addr, configuration_id=1, membership_size=n,
                     client=NoOpClient(), broadcaster=NoOpBroadcaster(),
                     scheduler=ManualScheduler(), on_decide=on_decide)


@pytest.mark.parametrize("n,quorum", FAST_QUORUM_TABLE)
def test_fast_quorum_no_conflicts(n, quorum):
    assert quorum == n - (n - 1) // 4
    decided = []
    fp = _fast_paxos_single(n, decided.append)
    proposal = hosts("127.0.0.1:1235")
    for i in range(quorum - 1):
        fp.handle_messages(
            FastRoundPhase2bMessage(Endpoint("127.0.0.2", i), 1, proposal)
        )
        assert decided == []
    fp.handle_messages(
        FastRoundPhase2bMessage(Endpoint("127.0.0.2", quorum - 1), 1, proposal)
    )
    assert decided == [list(proposal)]


FAST_QUORUM_CONFLICTS = [
    # (N, quorum, conflicts, decision expected)
    (6, 5, 1, True), (48, 37, 1, True), (50, 38, 1, True),
    (100, 76, 1, True), (102, 77, 1, True),
    (48, 37, 11, True), (50, 38, 12, True), (100, 76, 24, True), (102, 77, 25, True),
    (6, 5, 2, False), (48, 37, 14, False), (50, 38, 13, False),
    (100, 76, 25, False), (102, 77, 26, False),
]


@pytest.mark.parametrize("n,quorum,conflicts,change_expected", FAST_QUORUM_CONFLICTS)
def test_fast_quorum_with_conflicts(n, quorum, conflicts, change_expected):
    decided = []
    fp = _fast_paxos_single(n, decided.append)
    proposal = hosts("127.0.0.1:1235")
    conflict = hosts("127.0.0.1:1236")
    for i in range(conflicts):
        fp.handle_messages(
            FastRoundPhase2bMessage(Endpoint("127.0.0.2", i), 1, conflict)
        )
        assert decided == []
    non_conflict_count = min(conflicts + quorum - 1, n - 1)
    for i in range(conflicts, non_conflict_count):
        fp.handle_messages(
            FastRoundPhase2bMessage(Endpoint("127.0.0.2", i), 1, proposal)
        )
        assert decided == []
    fp.handle_messages(
        FastRoundPhase2bMessage(Endpoint("127.0.0.2", non_conflict_count), 1, proposal)
    )
    assert (decided == [list(proposal)]) == change_expected
    # stale-configuration and duplicate-sender votes are ignored
    fp.handle_messages(FastRoundPhase2bMessage(Endpoint("127.0.0.3", 999), 2, proposal))


# ---------------------------------------------------------------------------
# stale configurations, duplicate decisions, rank ordering
# ---------------------------------------------------------------------------


class _RecordingClient(NoOpClient):
    def __init__(self):
        self.sent = []

    def send_message(self, remote, request, on_response=None):
        self.sent.append((remote, request))


class _RecordingBroadcaster(NoOpBroadcaster):
    def __init__(self):
        self.broadcasts = []

    def broadcast(self, request):
        self.broadcasts.append(request)


def test_stale_configuration_phase1b_replies_are_ignored():
    """Phase-1b replies from an older configuration must not count toward
    the coordinator's majority or trigger phase 2a."""
    client = _RecordingClient()
    bcast = _RecordingBroadcaster()
    paxos = Paxos(Endpoint("127.0.0.1", 1234), 1, 3, client, bcast,
                  lambda _: None)
    paxos.start_phase1a(2)
    crnd = paxos._crnd
    for i in range(3):
        paxos.handle_phase1b(Phase1bMessage(
            Endpoint("127.0.0.2", i), 7, rnd=crnd, vrnd=Rank(1, 1), vval=P1))
    assert paxos._phase1b_messages == {}
    assert [type(b) for b in bcast.broadcasts] == [Phase1aMessage]
    # the same replies at the current configuration do complete phase 1
    for i in range(3):
        paxos.handle_phase1b(Phase1bMessage(
            Endpoint("127.0.0.2", i), 1, rnd=crnd, vrnd=Rank(1, 1), vval=P1))
    assert paxos._cval == P1
    assert type(bcast.broadcasts[-1]) is Phase2aMessage


def test_stale_configuration_1a_2a_2b_are_ignored():
    """The acceptor/learner handlers filter on configuration id without
    mutating any state or replying."""
    decided = []
    client = _RecordingClient()
    paxos = Paxos(Endpoint("127.0.0.1", 1234), 1, 3, client,
                  _RecordingBroadcaster(), decided.append)
    sender = Endpoint("127.0.0.2", 1)
    rank = Rank(2, 99)
    paxos.handle_phase1a(Phase1aMessage(sender, 7, rank))
    assert client.sent == [] and paxos._rnd == Rank(0, 0)
    paxos.handle_phase2a(Phase2aMessage(sender, 7, rnd=rank, vval=P1))
    assert paxos._vrnd == Rank(0, 0) and paxos._vval == ()
    for i in range(3):
        paxos.handle_phase2b(Phase2bMessage(
            Endpoint("127.0.0.2", i), 7, rnd=rank, endpoints=P1))
    assert decided == [] and paxos._accept_responses == {}


def test_classic_majority_after_fast_decision_is_ignored():
    """A classic phase-2b majority landing after the fast round already
    decided hits the idempotent decision funnel (_on_decided_wrapped):
    one external decision, no re-fire."""
    decided = []
    fp = _fast_paxos_single(5, decided.append)
    proposal = hosts("127.0.0.1:1235")
    for i in range(4):  # quorum = 5 - 1
        fp.handle_messages(
            FastRoundPhase2bMessage(Endpoint("127.0.0.2", i), 1, proposal))
    assert decided == [list(proposal)]
    rank = Rank(2, 7)
    for i in range(3):  # classic majority for a different value
        fp.handle_messages(Phase2bMessage(
            Endpoint("127.0.0.3", i), 1, rnd=rank, endpoints=P2))
    assert decided == [list(proposal)]


def test_rank_tie_breaking_across_node_indices():
    """Competing round-2 coordinators order by classic_rank_node_index: an
    acceptor re-promises only to the higher-indexed rank, and the losing
    coordinator's retries bounce off the promise."""
    a, b = Endpoint("127.0.0.1", 5891), Endpoint("127.0.0.1", 5821)
    ia, ib = classic_rank_node_index(a), classic_rank_node_index(b)
    assert ia != ib
    (low, li), (high, hi) = sorted(((a, ia), (b, ib)), key=lambda t: t[1])
    client = _RecordingClient()
    acceptor = Paxos(Endpoint("127.0.0.1", 1), 1, 3, client,
                     _RecordingBroadcaster(), lambda _: None)
    acceptor.handle_phase1a(Phase1aMessage(low, 1, Rank(2, li)))
    assert [r for r, _ in client.sent] == [low]
    acceptor.handle_phase1a(Phase1aMessage(high, 1, Rank(2, hi)))
    assert [r for r, _ in client.sent] == [low, high]
    assert acceptor._rnd == Rank(2, hi)
    acceptor.handle_phase1a(Phase1aMessage(low, 1, Rank(2, li)))
    assert [r for r, _ in client.sent] == [low, high]
    assert acceptor._rnd == Rank(2, hi)


def test_straggler_fallback_after_fast_decision_is_idempotent():
    """A node partitioned during the fast round falls back to a classic round
    after the others already decided; duplicate decisions must be ignored."""
    decisions = {}

    def on_decide(addr, value):
        assert addr not in decisions, "double decision delivered"
        decisions[addr] = value

    instances, scheduler, client = make_instances(5, on_decide)
    addrs = list(instances)
    straggler = addrs[-1]
    proposal = list(hosts("127.0.0.9:1"))

    # fast votes from everyone but the straggler reach everyone but the straggler
    client.drop_types.add(FastRoundPhase2bMessage)
    instances[straggler].propose(proposal, recovery_delay_ticks=50)
    client.drop_types.remove(FastRoundPhase2bMessage)
    for a in addrs[:-1]:
        orig = client.instances.pop(straggler)
        instances[a].propose(proposal, recovery_delay_ticks=10**9)
        client.instances[straggler] = orig
    assert len(decisions) == 4  # quorum 5 - 1 = 4 reached without straggler

    # straggler's fallback fires: classic round completes against decided nodes
    scheduler.advance_by(100)
    assert len(decisions) == 5
    assert all(v == proposal for v in decisions.values())
