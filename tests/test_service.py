"""Streaming service mode: resident driver, checkpoint/restore, traffic.

The load-bearing proofs:

- chunked ``simulate_chunk`` chains are bit-identical to one
  uninterrupted scan (engine and receiver, dense and packed carries,
  flight recorder included);
- a checkpoint save/load round trip is bit-exact for every family
  (engine, receiver_dense, receiver_packed under ``"packed"`` *and*
  ``"pallas"``), and a restored carry *continues* byte-identically;
- restore is strict: version mismatch raises ``CheckpointVersionError``
  naming saved vs expected, statics mismatch raises
  ``CheckpointCompatError`` naming every differing field, leaf drift
  raises ``CheckpointError``;
- the traffic generator is chunk-split invariant (10x100 ticks draw the
  same events as 1x1000), stays inside the churn envelope, and its
  generated history replays exactly through the host oracle referee
  (``run_churn_differential``);
- the ``two_zone`` preset (``faults``) yields schedules the device
  receiver reproduces bit-identically;
- the resident engine's JSONL stream validates, and a mid-run
  save/restore resumes bit-identically (traffic rng included).
"""
import json
import os

import numpy as np
import pytest

from rapid_tpu.engine import rx_packed
from rapid_tpu.engine.churn import empty_schedule
from rapid_tpu.engine.diff import (run_churn_differential,
                                   run_receiver_differential)
from rapid_tpu.engine.fleet import lower_receiver_schedule
from rapid_tpu.engine.receiver import receiver_simulate, receiver_simulate_chunk
from rapid_tpu.engine.state import I32_MAX, crash_faults, init_state
from rapid_tpu.engine.step import simulate, simulate_chunk
from rapid_tpu.faults import (DelayBudgetError, scenario_weights_preset,
                              sample_adversary_schedule, two_zone_schedule)
from rapid_tpu.service import (CheckpointCompatError, CheckpointError,
                               CheckpointVersionError, ResidentEngine,
                               TrafficConfig, TrafficGenerator, boot_resident,
                               load_checkpoint, restore_receiver_carry,
                               save_engine, save_receiver)
from rapid_tpu.service.resident import synthetic_uids
from rapid_tpu.settings import Settings
from rapid_tpu.telemetry.schema import (validate_checkpoint_manifest,
                                        validate_streaming_stream)

SETTINGS = Settings()
REC = SETTINGS.with_(flight_recorder_window=8)
PACKED_REC = REC.with_(rx_kernel="packed")
PALLAS_REC = REC.with_(rx_kernel="pallas")

TRAFFIC = TrafficConfig(seed=7, join_rate_per_ktick=60.0,
                        leave_burst_rate_per_ktick=8.0, leave_burst_size=2,
                        diurnal_amplitude=0.4, diurnal_period_ticks=256)


def _tree_equal(a, b, what="tree"):
    import jax

    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb), f"{what}: leaf count {len(la)} != {len(lb)}"
    for i, (x, y) in enumerate(zip(la, lb)):
        assert np.array_equal(np.asarray(x), np.asarray(y)), \
            f"{what}: leaf {i} diverged"


def _concat_logs(parts):
    import jax

    return jax.tree_util.tree_map(
        lambda *xs: np.concatenate([np.asarray(x) for x in xs], axis=0),
        *parts)


def _boot_engine(n=10, capacity=24, settings=SETTINGS, seed=0,
                 traffic=None):
    id_fps = traffic.boot_id_fps() if traffic is not None else None
    member = np.zeros(capacity, bool)
    member[:n] = True
    state = init_state(synthetic_uids(capacity, seed), id_fp_sum=0,
                       settings=settings, member=member, id_fps=id_fps)
    return state, crash_faults([I32_MAX] * capacity)


def _receiver_member(settings, n=12, seed=3):
    sched = two_zone_schedule(n, seed, 60,
                              ring_depth=settings.delivery_ring_depth)
    return lower_receiver_schedule(sched, settings)


# ---------------------------------------------------------------------------
# chunked scans == one uninterrupted scan
# ---------------------------------------------------------------------------


def test_engine_chunked_bit_identical_with_churn_and_recorder():
    gen = TrafficGenerator(TRAFFIC, REC, capacity=24, n_initial=10)
    state, faults = _boot_engine(settings=REC, traffic=gen)
    sched, info = gen.next_chunk(64)
    assert info["events"] > 0 and sched is not None
    want_final, want_logs, want_rec = simulate(state, faults, 64, REC,
                                               churn=sched)
    # Enqueue ticks are absolute, so the full-window schedule is inert
    # outside each chunk's tick range — both chunks can share it.
    f1, l1, r1 = simulate_chunk(state, faults, 32, REC, churn=sched,
                                donate=False)
    f2, l2, r2 = simulate_chunk(f1, faults, 32, REC, churn=sched, rec=r1,
                                donate=False)
    _tree_equal(f2, want_final, "final state")
    _tree_equal(_concat_logs([l1, l2]), want_logs, "logs")
    _tree_equal(r2, want_rec, "recorder ring")


@pytest.mark.parametrize("settings", [REC, PACKED_REC],
                         ids=["dense", "packed"])
def test_receiver_chunked_bit_identical(settings):
    member = _receiver_member(settings)
    want = receiver_simulate_chunk(member.state, member.faults, 40,
                                   settings, donate=False)
    carry, logs, rec = member.state, [], None
    for _ in range(2):
        carry, log, rec = receiver_simulate_chunk(
            carry, member.faults, 20, settings, rec=rec, donate=False)
        logs.append(log)
    _tree_equal(carry, want[0], "final carry")
    _tree_equal(_concat_logs(logs), want[1], "logs")
    _tree_equal(rec, want[2], "recorder ring")


# ---------------------------------------------------------------------------
# checkpoint round trips: bit-exact restore + bit-identical continuation
# ---------------------------------------------------------------------------


def test_engine_checkpoint_round_trip_continues_identically(tmp_path):
    state, faults = _boot_engine(settings=REC)
    live, logs, rec = simulate_chunk(state, faults, 32, REC, donate=False)
    manifest = save_engine(str(tmp_path / "ck"), live, REC, rec=rec,
                           host={"note": "test"})
    assert validate_checkpoint_manifest(manifest) == []
    cp = load_checkpoint(str(tmp_path / "ck"), REC)
    assert cp.family == "engine" and cp.tick == 32
    assert cp.host == {"note": "test"}
    _tree_equal(cp.parts["state"], live, "restored engine state")
    _tree_equal(cp.parts["recorder"], rec, "restored recorder")
    a = simulate_chunk(live, faults, 32, REC, rec=rec, donate=False)
    b = simulate_chunk(cp.parts["state"], faults, 32, REC,
                       rec=cp.parts["recorder"], donate=False)
    _tree_equal(a[0], b[0], "continuation final")
    _tree_equal(a[1], b[1], "continuation StepLog")
    _tree_equal(a[2], b[2], "continuation recorder")


@pytest.mark.parametrize("settings", [REC, PACKED_REC, PALLAS_REC],
                         ids=["dense", "packed", "pallas"])
def test_receiver_checkpoint_round_trip_continues_identically(
        settings, tmp_path):
    # 20-tick chunks share the jit cache with the chunked test above.
    member = _receiver_member(settings)
    carry, _, rec = receiver_simulate_chunk(member.state, member.faults,
                                            20, settings, donate=False)
    save_receiver(str(tmp_path / "ck"), carry, settings, tick=20, rec=rec)
    cp = load_checkpoint(str(tmp_path / "ck"), settings)
    want_family = ("receiver_dense" if settings.rx_kernel == "xla"
                   else "receiver_packed")
    assert cp.family == want_family
    restored = restore_receiver_carry(cp, settings)
    _tree_equal(restored, carry, "restored receiver carry")
    _tree_equal(cp.parts["recorder"], rec, "restored recorder")
    a = receiver_simulate_chunk(carry, member.faults, 20, settings,
                                rec=rec, donate=False)
    b = receiver_simulate_chunk(restored, member.faults, 20, settings,
                                rec=cp.parts["recorder"], donate=False)
    _tree_equal(a[0], b[0], "continuation final")
    _tree_equal(a[1], b[1], "continuation logs")
    _tree_equal(a[2], b[2], "continuation recorder")


def test_checkpoint_version_mismatch_is_structured(tmp_path):
    state, _ = _boot_engine()
    save_engine(str(tmp_path / "ck"), state, SETTINGS)
    mpath = tmp_path / "ck" / "manifest.json"
    manifest = json.loads(mpath.read_text())
    manifest["checkpoint_version"] = 99
    mpath.write_text(json.dumps(manifest))
    with pytest.raises(CheckpointVersionError) as exc:
        load_checkpoint(str(tmp_path / "ck"), SETTINGS)
    assert exc.value.saved == 99 and exc.value.expected == 1
    assert "99" in str(exc.value) and "1" in str(exc.value)


def test_checkpoint_statics_mismatch_names_fields(tmp_path):
    member = _receiver_member(PACKED_REC)
    carry, _, rec = receiver_simulate_chunk(member.state, member.faults,
                                            20, PACKED_REC, donate=False)
    save_receiver(str(tmp_path / "ck"), carry, PACKED_REC, tick=20, rec=rec)
    with pytest.raises(CheckpointCompatError) as exc:
        load_checkpoint(str(tmp_path / "ck"), PALLAS_REC)
    assert set(exc.value.mismatches) == {"rx_kernel"}
    assert "rx_kernel" in str(exc.value)
    with pytest.raises(CheckpointCompatError) as exc:
        load_checkpoint(str(tmp_path / "ck"),
                        PACKED_REC.with_(flight_recorder_window=16))
    assert "flight_recorder_window" in exc.value.mismatches


def test_checkpoint_leaf_drift_rejected(tmp_path):
    state, _ = _boot_engine()
    save_engine(str(tmp_path / "ck"), state, SETTINGS)
    mpath = tmp_path / "ck" / "manifest.json"
    manifest = json.loads(mpath.read_text())
    manifest["leaves"] = manifest["leaves"][:-1]
    mpath.write_text(json.dumps(manifest))
    with pytest.raises(CheckpointError, match="leaf table"):
        load_checkpoint(str(tmp_path / "ck"), SETTINGS)


# ---------------------------------------------------------------------------
# traffic generator: determinism, envelope, oracle replay
# ---------------------------------------------------------------------------


def test_traffic_chunk_split_invariance():
    settings = SETTINGS.with_(stream_chunk_ticks=800)
    one = TrafficGenerator(TRAFFIC, settings, capacity=32, n_initial=10)
    many = TrafficGenerator(TRAFFIC, settings, capacity=32, n_initial=10)
    sched, _ = one.next_chunk(800)
    for _ in range(8):
        many.next_chunk(100)
    assert one._calls == many._calls
    assert one.events == many.events > 0
    assert (one.joins, one.leaves, one.bursts) == \
        (many.joins, many.leaves, many.bursts)
    assert one.state_dict() == many.state_dict()


def test_traffic_envelope_and_schedule_shape():
    settings = SETTINGS.with_(stream_chunk_ticks=1200)
    gen = TrafficGenerator(TRAFFIC, settings, capacity=32, n_initial=10)
    schedule, info = gen.next_chunk(1200)
    assert info["events"] == info["joins"] + info["leaves"] > 0
    ticks = sorted(t for _, t, _ in gen._calls)
    spacing = SETTINGS.churn_decide_delay_ticks + 3
    assert all(b - a >= spacing for a, b in zip(ticks, ticks[1:]))
    assert min(ticks) >= spacing
    # A slot may join then leave inside one window (one enqueue per
    # field), but never the reverse: rejoin is blocked by the recycle
    # delay, so wherever both fields are set the join precedes.
    jt = np.asarray(schedule.join_tick)
    lt = np.asarray(schedule.leave_tick)
    both = (jt != I32_MAX) & (lt != I32_MAX)
    assert (jt[both] < lt[both]).all()
    # Leave bursts never cross the membership floor.
    assert info["n_members"] >= TRAFFIC.min_members


def test_traffic_replays_through_oracle_referee():
    config = TrafficConfig(seed=11, join_rate_per_ktick=50.0,
                           leave_burst_rate_per_ktick=8.0,
                           leave_burst_size=2, min_members=6,
                           reuse_slots=False)
    gen = TrafficGenerator(config, SETTINGS, capacity=24, n_initial=8)
    ticks = 420
    for _ in range(4):
        gen.next_chunk(ticks // 4)
    assert gen.events > 0
    joins, leaves = gen.churn_calls(SETTINGS)
    res = run_churn_differential(n=8, capacity=24, n_ticks=ticks,
                                 joins=joins, leaves=leaves,
                                 settings=SETTINGS)
    res.assert_identical()


def test_traffic_churn_calls_requires_no_slot_reuse():
    gen = TrafficGenerator(TRAFFIC, SETTINGS, capacity=32, n_initial=10)
    with pytest.raises(ValueError, match="reuse_slots"):
        gen.churn_calls(SETTINGS)


def test_traffic_state_dict_round_trip_resumes_stream():
    a = TrafficGenerator(TRAFFIC, SETTINGS, capacity=32, n_initial=10)
    a.next_chunk(256)
    b = TrafficGenerator.from_state(a.state_dict(), SETTINGS)
    sa, ia = a.next_chunk(256)
    sb, ib = b.next_chunk(256)
    assert ia == ib
    if sa is None:
        assert sb is None
    else:
        _tree_equal(sa, sb, "resumed schedule")


def test_traffic_oversized_window_rejected_not_corrupted():
    config = TrafficConfig(seed=1, join_rate_per_ktick=80.0,
                           leave_burst_rate_per_ktick=12.0)
    gen = TrafficGenerator(config, SETTINGS, capacity=20, n_initial=10)
    # A window far past the slot-recycle delay eventually revisits a
    # slot, which one per-slot enqueue-tick schedule cannot encode.
    with pytest.raises(ValueError, match="slot-recycle delay"):
        for _ in range(4):
            gen.next_chunk(4000)


# ---------------------------------------------------------------------------
# two_zone preset
# ---------------------------------------------------------------------------


def test_two_zone_schedule_deterministic_and_budget_checked():
    a = two_zone_schedule(16, 5, 80)
    b = two_zone_schedule(16, 5, 80)
    assert a == b
    assert len(a.delays) == 1 and a.crashes
    zone_b = set(range(8, 16))
    assert {slot for slot, _ in a.crashes} <= zone_b
    with pytest.raises(DelayBudgetError):
        two_zone_schedule(16, 5, 80, ring_depth=2)


def test_two_zone_preset_lookup():
    weights = scenario_weights_preset("two_zone")
    assert weights.slow_asym > 0 and weights.partition == 0
    sc = sample_adversary_schedule(16, 9, 80, weights)
    assert sc.kind in ("slow_asym", "crash")
    with pytest.raises(ValueError, match="unknown scenario-weights"):
        scenario_weights_preset("nope")


def test_two_zone_device_exact():
    schedule = two_zone_schedule(16, 2, 80)
    res = run_receiver_differential(schedule, 80, SETTINGS)
    res.assert_identical()
    assert res.engine_phase_counters == res.oracle_phase_counters
    assert res.engine_config_ids == res.oracle_config_ids


# ---------------------------------------------------------------------------
# resident engine: stream validity + save/restore resume
# ---------------------------------------------------------------------------


def _resident_settings():
    return REC.with_(stream_chunk_ticks=64)


def test_resident_stream_validates_and_memory_stays_flat(tmp_path):
    settings = _resident_settings()
    sink = str(tmp_path / "stream.jsonl")
    eng = boot_resident(settings, capacity=24, n_initial=10, seed=0,
                        traffic_config=TRAFFIC, sink=sink,
                        write_ticks=False)
    eng.run(2)
    eng.verify_round_trip(str(tmp_path / "ck"))
    eng.run(2)
    summary = eng.summary()
    eng.close()
    with open(sink) as fh:
        lines = fh.readlines()
    assert validate_streaming_stream(lines) == []
    ck = summary["checkpoint"]
    assert ck["state_identical"] and ck["logs_identical"]
    assert ck["final_identical"] and ck["recorder_identical"]
    assert ck["continuation_recorder_identical"]
    assert summary["ticks"] == 5 * 64 and summary["chunks"] == 5
    marks = summary["live_buffer_bytes"]
    assert marks["steady_max"] is not None
    assert marks["steady_max"] <= marks["max"]


def test_resident_chunk0_compile_split_pins_rates(tmp_path):
    # Schema v10: the one-time trace+compile wall is split out of
    # chunk 0 (``compile_s``) so every heartbeat rate measures
    # execution. Later chunks re-enter the compiled executable and
    # report null.
    from rapid_tpu.campaign import _rate as rate_fn

    settings = _resident_settings()
    eng = boot_resident(settings, capacity=24, n_initial=10, seed=0,
                        traffic_config=TRAFFIC, write_ticks=False)
    eng.run(3)
    eng.flush()
    recs = eng.chunk_records
    summary = eng.summary()
    eng.close()
    assert recs[0]["compile_s"] is not None and recs[0]["compile_s"] > 0
    assert all(r["compile_s"] is None for r in recs[1:])
    assert summary["compile_s"] == recs[0]["compile_s"]
    for r in recs:
        assert r["ticks_per_sec"] == rate_fn(r["ticks"], r["wall_s"])


@pytest.mark.parametrize("settings", [PACKED_REC, REC],
                         ids=["packed", "dense"])
def test_rx_resident_round_trip_and_stream_validate(tmp_path, settings):
    from rapid_tpu.service import ResidentReceiver, boot_resident_receiver
    from rapid_tpu.telemetry.slo import SloWindows

    sink = str(tmp_path / "rx.jsonl")
    rx = boot_resident_receiver(settings, 16, seed=3, horizon_ticks=64,
                                chunk_ticks=16,
                                slo=SloWindows(window_chunks=4), sink=sink)
    rx.run(1)
    block = rx.verify_round_trip(str(tmp_path / "ck"))
    assert block["state_identical"] and block["logs_identical"]
    assert block["final_identical"] and block["recorder_identical"]
    assert block["continuation_recorder_identical"]
    rx.run(1)
    path = str(tmp_path / "ck2")
    rx.save(path)
    twin = ResidentReceiver.restore(path, rx._faults, settings)
    assert twin.chunks == rx.chunks and twin.ticks == rx.ticks
    rx.run(1)
    twin.run(1)
    _tree_equal(twin.carry, rx.carry, "resumed receiver carry")
    _tree_equal(twin._rec, rx._rec, "resumed receiver recorder")
    summary = rx.summary()
    rx.close()
    twin.close()
    assert summary["source"] == "resident_receiver"
    assert summary["chunks"] == 4 and summary["ticks"] == 64
    with open(sink) as fh:
        assert validate_streaming_stream(fh.readlines()) == []


def test_resident_save_restore_resumes_bit_identically(tmp_path):
    settings = _resident_settings()
    eng = boot_resident(settings, capacity=24, n_initial=10, seed=0,
                        traffic_config=TRAFFIC)
    eng.run(2)
    path = str(tmp_path / "ck")
    eng.save(path)
    faults = crash_faults([I32_MAX] * 24)
    twin = ResidentEngine.restore(path, faults, settings)
    assert twin.chunks == eng.chunks and twin.ticks == eng.ticks
    assert twin.traffic.state_dict() == eng.traffic.state_dict()
    eng.run(2)
    twin.run(2)
    _tree_equal(twin.state, eng.state, "resumed engine state")
    _tree_equal(twin._rec, eng._rec, "resumed recorder ring")
    assert twin.traffic.state_dict() == eng.traffic.state_dict()
    eng.close()
    twin.close()
