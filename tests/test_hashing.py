"""Differential tests: python-int hash == numpy limb hash == jnp limb hash."""
import numpy as np
import pytest

from rapid_tpu import hashing as H


def _rand_u64(rng, n):
    return rng.integers(0, 1 << 64, size=n, dtype=np.uint64)


def test_splitmix64_known_values():
    # splitmix64(seed=0) first outputs, from the public reference sequence
    # (Steele et al., "Fast Splittable Pseudorandom Number Generators").
    assert H.splitmix64(0) == 0xE220A8397B1DCDAF
    assert H.splitmix64(H.splitmix64(0) ^ 0) != H.splitmix64(0)


def test_limbs_roundtrip():
    rng = np.random.default_rng(0)
    xs = _rand_u64(rng, 100)
    hi, lo = H.np_to_limbs(xs)
    assert np.array_equal(H.np_from_limbs(hi, lo), xs)


@pytest.mark.parametrize("seed", [0, 1, 9, 0xDEADBEEF, (1 << 64) - 1])
def test_numpy_limbs_match_python(seed):
    rng = np.random.default_rng(42)
    xs = _rand_u64(rng, 256)
    hi, lo = H.np_to_limbs(xs)
    rhi, rlo = H.hash64_limbs(np, hi, lo, seed=seed)
    got = H.np_from_limbs(rhi, rlo)
    want = np.array([H.hash64(int(x), seed) for x in xs], dtype=np.uint64)
    assert np.array_equal(got, want)


def test_jnp_limbs_match_numpy():
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    xs = _rand_u64(rng, 512)
    hi, lo = H.np_to_limbs(xs)
    for seed in (0, 3, 123456789):
        nhi, nlo = H.hash64_limbs(np, hi, lo, seed=seed)
        jhi, jlo = H.hash64_limbs(jnp, jnp.asarray(hi), jnp.asarray(lo), seed=seed)
        assert np.array_equal(np.asarray(jhi), nhi)
        assert np.array_equal(np.asarray(jlo), nlo)


def test_mul32_wide_exhaustive_edges():
    edge = np.array(
        [0, 1, 2, 0xFFFF, 0x10000, 0x7FFFFFFF, 0x80000000, 0xFFFFFFFE, 0xFFFFFFFF],
        dtype=np.uint32,
    )
    a = np.repeat(edge, len(edge))
    b = np.tile(edge, len(edge))
    hi, lo = H.mul32_wide(np, a, b)
    prod = a.astype(object) * b.astype(object)
    assert np.array_equal(hi.astype(object) * (1 << 32) + lo.astype(object), prod)


def test_fingerprint_bytes_distinct():
    seen = {H.fingerprint_bytes(f"host-{i}".encode()) for i in range(10000)}
    assert len(seen) == 10000
