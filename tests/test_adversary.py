"""On-device fault adversary vs the oracle (engine.adversary).

The acceptance contract: ``run_adversarial_differential`` accepts seeded,
unscripted fault schedules — asymmetric partitions, flip-flop links, tied
timers, mid-fast-count fires, crash bursts straddling an FD-interval
boundary — with no planner pre-rejection, and proves the per-slot engine
bit-identical to the oracle: every slot's event stream, total and
per-phase message counters, and every slot's final configuration id, at
N=64 and N=256, including a classic-Paxos fallback decided under a
one-way partition. Divergences surface through the forensics-enabled
``assert_identical`` with partition gauges in the report context.
"""
import pytest

from rapid_tpu.engine.diff import run_adversarial_differential
from rapid_tpu.faults import (
    AdversarySchedule,
    LinkWindow,
    ScriptedPropose,
    random_adversary_schedule,
    validate_schedule,
)
from rapid_tpu.telemetry.forensics import DivergenceError


def _phase_total(res, key):
    return sum(d[key] for d in res.engine_phase_counters)


def _view_changes(res, slot):
    return [e for e in res.engine_events_by_slot[slot]
            if e.kind == "view_change"]


# ---------------------------------------------------------------------------
# crashes
# ---------------------------------------------------------------------------


def test_single_crash_bit_identical():
    sched = AdversarySchedule(n=8, crashes=((3, 5),), seed=1)
    res = run_adversarial_differential(sched, 160)
    res.assert_identical()
    # Every survivor converges on the same post-removal view; the crashed
    # slot records nothing and its view freezes at the boot config.
    survivor_cfgs = {res.engine_config_ids[s] for s in range(8) if s != 3}
    assert len(survivor_cfgs) == 1
    assert res.engine_config_ids[3] not in survivor_cfgs
    assert not res.engine_events_by_slot[3]
    assert all(_view_changes(res, s) for s in range(8) if s != 3)


@pytest.mark.parametrize("n,crashes", [
    (64, ((1, 5), (2, 5), (40, 15), (41, 15))),
    (256, ((1, 5), (2, 5), (3, 5), (4, 5),
           (200, 15), (201, 15), (202, 15), (203, 15))),
])
def test_straddling_burst_two_view_changes(n, crashes):
    """A crash burst straddling an FD-interval boundary is detected in two
    waves and must produce two view changes — the documented stale-state
    gap in the old fleet planner, now run exactly."""
    sched = AdversarySchedule(n=n, crashes=crashes, seed=2)
    res = run_adversarial_differential(sched, 260)
    res.assert_identical()
    crashed = {s for s, _ in crashes}
    survivor = next(s for s in range(n) if s not in crashed)
    vcs = _view_changes(res, survivor)
    assert len(vcs) == 2
    assert vcs[0].tick < vcs[1].tick
    assert vcs[0].config_id != vcs[1].config_id
    removed = {s for vc in vcs for s in vc.slots}
    assert removed == crashed


# ---------------------------------------------------------------------------
# asymmetric partitions
# ---------------------------------------------------------------------------


def _one_way_partition(n, iso, start=3):
    """Block rest->iso only: rest-side observers' probes to iso subjects
    fail (detection), while iso nodes — whose own probes still succeed —
    stay quiet and never hear the removal votes."""
    rest = frozenset(range(n)) - iso
    return LinkWindow(src_slots=rest, dst_slots=iso, start_tick=start)


def test_one_way_partition_classic_fallback_n64():
    """20 of 64 slots isolated one-way: only 44 fast votes circulate,
    short of the fast quorum of 49, so the decision must come from the
    organic jittered classic-Paxos fallback — under the partition."""
    n, iso = 64, frozenset(range(44, 64))
    sched = AdversarySchedule(
        n=n, windows=(_one_way_partition(n, iso),), seed=7)
    res = run_adversarial_differential(sched, 300)
    res.assert_identical()
    assert _phase_total(res, "phase1a_sent") > 0
    rest_cfgs = {res.engine_config_ids[s] for s in sorted(set(range(n)) - iso)}
    iso_cfgs = {res.engine_config_ids[s] for s in sorted(iso)}
    # The reachable side converges on one new view; the isolated side
    # never hears about it and keeps the boot view.
    assert len(rest_cfgs) == 1 and len(iso_cfgs) == 1
    assert rest_cfgs != iso_cfgs
    survivor = 0
    assert {s for vc in _view_changes(res, survivor) for s in vc.slots} == iso


def test_one_way_partition_fast_decide_n256():
    """40 of 256 slots isolated: 216 reachable voters clear the fast
    quorum of 193, so the fast round decides despite the partition."""
    n, iso = 256, frozenset(range(216, 256))
    sched = AdversarySchedule(
        n=n, windows=(_one_way_partition(n, iso),), seed=9)
    res = run_adversarial_differential(sched, 240)
    res.assert_identical()
    survivor = 0
    vcs = _view_changes(res, survivor)
    assert vcs and {s for vc in vcs for s in vc.slots} == iso
    assert all(not res.engine_events_by_slot[s] for s in iso)


def test_flip_flop_link_window_bit_identical():
    """A periodically healing link plus a crash: reachability flips every
    7 ticks, exercising delivery-tick mask evaluation on both sides."""
    win = LinkWindow(src_slots=frozenset({0, 1, 2}),
                     dst_slots=frozenset({5, 6}),
                     start_tick=4, end_tick=140, period_ticks=7,
                     two_way=True)
    sched = AdversarySchedule(n=8, crashes=((6, 9),), windows=(win,),
                              seed=13)
    res = run_adversarial_differential(sched, 220)
    res.assert_identical()


def test_partition_gauges_surface_in_engine_metrics():
    n, iso = 8, frozenset({6, 7})
    sched = AdversarySchedule(
        n=n, windows=(_one_way_partition(n, iso),), seed=3)
    res = run_adversarial_differential(sched, 200)
    res.assert_identical()
    rows = res.engine_metrics
    assert max(r.partitioned_edges for r in rows) == len(iso) * (n - len(iso))
    assert sum(r.link_dropped for r in rows) > 0


# ---------------------------------------------------------------------------
# unscripted seeded schedules
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_random_schedule_bit_identical(seed):
    sched = random_adversary_schedule(16, seed, 300)
    run_adversarial_differential(sched, 300).assert_identical()


def test_scripted_proposes_mix_with_organic_faults():
    """Scripted tied-delay proposes racing a crash-driven organic cut."""
    proposes = (ScriptedPropose(slot=0, tick=20, proposal=(5,),
                                delay_ticks=12),
                ScriptedPropose(slot=1, tick=20, proposal=(6,),
                                delay_ticks=12))
    sched = AdversarySchedule(n=8, crashes=((7, 25),), proposes=proposes,
                              seed=21)
    res = run_adversarial_differential(sched, 200)
    res.assert_identical()


# ---------------------------------------------------------------------------
# validation and forensics
# ---------------------------------------------------------------------------


def test_validate_schedule_genuine_input_errors_only():
    with pytest.raises(ValueError, match="outside universe"):
        validate_schedule(AdversarySchedule(n=4, crashes=((9, 5),)))
    with pytest.raises(ValueError, match=">= 1"):
        validate_schedule(AdversarySchedule(n=4, crashes=((1, 0),)))
    dup = (ScriptedPropose(slot=2, tick=5, proposal=(0,), delay_ticks=3),
           ScriptedPropose(slot=2, tick=9, proposal=(1,), delay_ticks=3))
    with pytest.raises(ValueError, match="one scripted propose"):
        validate_schedule(AdversarySchedule(n=4, proposes=dup))


def test_divergence_report_names_slot_and_writes_artifact(tmp_path):
    sched = AdversarySchedule(n=8, crashes=((2, 5),), seed=5)
    res = run_adversarial_differential(sched, 160)
    res.engine_config_ids[0] ^= 1  # simulate a per-slot view divergence
    artifact = str(tmp_path / "divergence.jsonl")
    with pytest.raises(DivergenceError, match="slot0.config_id"):
        res.assert_identical(artifact=artifact)
    assert (tmp_path / "divergence.jsonl").exists()
