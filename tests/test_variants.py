"""Protocol-variant lab: differentials, kernels, and the rapid jaxpr pin.

The contract (``rapid_tpu.variants``):

- ``run_variant_differential`` is bit-identical — decisions, config ids,
  per-tick variant-model message counts — against the variant-aware
  oracle accounting at N=64 and N=256, for both "ring" and "hier", over
  crash bursts and contested consensus;
- scenarios where "hier" legitimately behaves differently (skewed crash
  bursts killing an intra-group quorum) are *rejected* by the envelope
  check, and the engine really does refuse the view change there;
- ``protocol_variant="rapid"`` traces a byte-identical jaxpr to the
  default settings (same discipline as the ``rx_kernel`` knob);
- the ring tally kernel (``votes.scan_vote_count``) is property-tested
  bit-identical to ``segmented_vote_count``;
- ``ScenarioWeights`` field names match the sampler's kind table.
"""
import numpy as np
import jax
import pytest

import importlib

from rapid_tpu import hashing
from rapid_tpu.engine import votes as votes_mod
from rapid_tpu.engine.diff import (default_endpoints, default_node_ids,
                                   run_variant_differential)
from rapid_tpu.engine.state import I32_MAX, crash_faults, init_state
from rapid_tpu.settings import Settings
from rapid_tpu.variants import VARIANTS
from rapid_tpu.variants import hier as hier_mod
from rapid_tpu.variants.oracle import VariantEnvelopeError

# The engine package re-exports the ``step`` *function*, shadowing the
# submodule attribute (same workaround as tests/test_fleet.py).
step_mod = importlib.import_module("rapid_tpu.engine.step")

SETTINGS = Settings()

CRASH_SCENARIOS = {
    64: ({3: 5, 17: 5, 40: 7}, 130),
    256: ({5: 11, 100: 13, 200: 15, 250: 19}, 140),
}


def two_way_split(n):
    """Contested: two camps, no fast quorum, classic round recovers
    (same scenario family as ``tests/test_fallback_engine.py``)."""
    values = [[0], [1]]
    votes = {s: (6, s % 2) for s in range(n)}
    delays = {s: (10 if s == 0 else 100) for s in range(n)}
    return values, votes, delays, 30


# ---------------------------------------------------------------------------
# differentials: variant engine vs variant-aware oracle accounting
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n", [64, 256])
@pytest.mark.parametrize("variant", ["ring", "hier"])
def test_variant_differential_crash_burst(variant, n):
    crashes, ticks = CRASH_SCENARIOS[n]
    res = run_variant_differential(n, crashes, ticks, variant)
    res.assert_identical()
    # the burst really decided, and the variant accounting is in effect:
    # ring's whole run costs O(N) messages per exchange, so its total is
    # far below the rapid O(N^2) announce alone
    assert any(e.kind == "view_change" for e in res.engine_events)
    assert res.engine_message_total == res.oracle_message_total
    if variant == "ring":
        assert res.engine_message_total < n * n


@pytest.mark.parametrize("n", [64, 256])
@pytest.mark.parametrize("variant", ["ring", "hier"])
def test_variant_differential_contested(variant, n):
    values, votes, delays, ticks = two_way_split(n)
    res = run_variant_differential(n, {}, ticks, variant,
                                   contested=(values, votes, delays))
    res.assert_identical()
    assert any(e.kind == "view_change" for e in res.engine_events)
    # the classic fallback chain ran identically under the variant
    assert sum(c["phase1a_sent"] for c in res.engine_phase_counters) == n


def test_rapid_variant_is_identity():
    res = run_variant_differential(64, {7: 5}, 130, "rapid")
    res.assert_identical()


def test_contested_rejects_crashes():
    with pytest.raises(ValueError, match="crash-free"):
        run_variant_differential(64, {7: 5}, 30, "ring",
                                 contested=two_way_split(64)[:3])


# ---------------------------------------------------------------------------
# hier envelope: skewed bursts are rejected, and the engine agrees
# ---------------------------------------------------------------------------


def _skewed_burst(n=64):
    """Crashes that kill two groups' intra-group quorums while the flat
    3/4 quorum still holds: per failing group g, crash
    ``(m_g - 1) // 4 + 1`` members."""
    from rapid_tpu.oracle.membership_view import uid_of

    uids = np.asarray([uid_of(e) for e in default_endpoints(n)], np.uint64)
    hi = (uids >> np.uint64(32)).astype(np.uint32)
    lo = (uids & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    n_groups = hier_mod.hier_group_count(n)
    gid = np.asarray(hier_mod.group_ids(np, hi, lo, n_groups))
    crashes = {}
    broken = 0
    for g in np.argsort(np.bincount(gid, minlength=n_groups)):
        members = np.nonzero(gid == g)[0]
        need = (len(members) - 1) // 4 + 1
        if len(members) == 0 or len(crashes) + need > n - votes_needed(n):
            continue
        for s in members[:need]:
            crashes[int(s)] = 5
        broken += 1
        if broken == 2:
            break
    assert broken == 2, "could not build a skewed burst at this size"
    return crashes


def votes_needed(n):
    return n - (n - 1) // 4


def test_hier_rejects_skewed_burst():
    n = 64
    crashes = _skewed_burst(n)
    # flat quorum still decides this burst...
    res = run_variant_differential(n, crashes, 130, "rapid")
    res.assert_identical()
    assert any(e.kind == "view_change" for e in res.engine_events)
    # ...so the scenario is outside the hier envelope and must be
    # rejected, not silently compared
    with pytest.raises(VariantEnvelopeError, match="hier envelope"):
        run_variant_differential(n, crashes, 130, "hier")
    # and the hier engine really refuses the view change: it announces
    # the proposal but never decides
    settings = SETTINGS.with_(protocol_variant="hier")
    from rapid_tpu.oracle.membership_view import id_fingerprint, uid_of

    endpoints = default_endpoints(n)
    uids = [uid_of(e) for e in endpoints]
    id_fp_sum = sum(id_fingerprint(nid)
                    for nid in default_node_ids(n)) & hashing.MASK64
    state = init_state(uids, id_fp_sum, settings)
    faults = crash_faults([crashes.get(s, I32_MAX) for s in range(n)])
    _, logs = step_mod.simulate(state, faults, 130, settings)
    assert np.asarray(logs.announce_now).any()
    assert not np.asarray(logs.decide_now).any()


def test_np_hier_decide_matches_device_rule():
    """The numpy twin and the engine kernel agree over random masks."""
    rng = np.random.default_rng(7)
    n = 64
    n_groups = hier_mod.hier_group_count(n)
    hi = rng.integers(0, 2**32, n).astype(np.uint32)
    lo = rng.integers(0, 2**32, n).astype(np.uint32)
    import jax.numpy as jnp

    for _ in range(50):
        member = rng.random(n) < rng.uniform(0.3, 1.0)
        valid = member & (rng.random(n) < rng.uniform(0.3, 1.0))
        host = hier_mod.np_hier_decide(np, member, valid, hi, lo, n_groups)
        dev, tally = hier_mod.hier_count_fast_round(
            jnp, jnp.asarray(member), jnp.asarray(valid),
            jnp.asarray(hi), jnp.asarray(lo), n_groups)
        assert bool(dev) == host
        assert int(tally) == int(valid.sum())


# ---------------------------------------------------------------------------
# ring tally kernel: scan_vote_count == segmented_vote_count, bit for bit
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("c", [64, 256])
def test_scan_vote_count_matches_segmented(c):
    import jax.numpy as jnp

    rng = np.random.default_rng(c)
    for trial in range(25):
        # few distinct fingerprints => long tied runs; sprinkle of
        # full-width randoms => singleton runs and hi-limb ties
        pool = rng.integers(0, 2**64, rng.integers(1, 6), dtype=np.uint64)
        fps = pool[rng.integers(0, len(pool), c)]
        wild = rng.random(c) < 0.2
        fps = np.where(wild, rng.integers(0, 2**64, c, dtype=np.uint64), fps)
        if trial % 3 == 0:  # force hi-limb collisions with distinct lo
            fps = fps & np.uint64(0xFFFFFFFF)
        hi = jnp.asarray((fps >> np.uint64(32)).astype(np.uint32))
        lo = jnp.asarray((fps & np.uint64(0xFFFFFFFF)).astype(np.uint32))
        valid = jnp.asarray(rng.random(c) < rng.uniform(0.0, 1.0))
        ref = votes_mod.segmented_vote_count(jnp, hi, lo, valid)
        scan = votes_mod.scan_vote_count(jnp, hi, lo, valid)
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(scan))


# ---------------------------------------------------------------------------
# the rapid jaxpr pin + knob validation
# ---------------------------------------------------------------------------


def _step_jaxpr(settings):
    n = 16
    hi, lo = hashing.np_to_limbs(np.arange(1, n + 1, dtype=np.uint64))
    hi, lo = hashing.hash64_limbs(np, hi, lo, seed=0xBEEF)
    uids = hashing.np_from_limbs(hi, lo)
    state = init_state(uids, id_fp_sum=0, settings=settings)
    faults = crash_faults([I32_MAX] * n)
    return str(jax.make_jaxpr(
        lambda st, fa: step_mod.step(st, fa, settings))(state, faults))


def test_rapid_jaxpr_byte_identical_to_default():
    """variant="rapid" is the default engine, not a near-copy: the traced
    step must be byte-identical with the knob at its default and set
    explicitly, while "ring" and "hier" trace different programs."""
    base = _step_jaxpr(SETTINGS)
    assert base == _step_jaxpr(SETTINGS.with_(protocol_variant="rapid"))
    ring = _step_jaxpr(SETTINGS.with_(protocol_variant="ring"))
    hier = _step_jaxpr(SETTINGS.with_(protocol_variant="hier"))
    assert ring != base
    assert hier != base
    assert ring != hier


def test_protocol_variant_validated():
    with pytest.raises(ValueError, match="protocol_variant"):
        Settings(protocol_variant="mesh")
    assert VARIANTS == ("rapid", "ring", "hier")
    for v in VARIANTS:
        assert Settings(protocol_variant=v).protocol_variant == v


# ---------------------------------------------------------------------------
# sampler kind table cannot drift from ScenarioWeights again
# ---------------------------------------------------------------------------


def test_scenario_weights_fields_match_kind_table():
    import dataclasses

    from rapid_tpu.faults import DELAY_KINDS, SCENARIO_KINDS, ScenarioWeights

    fields = tuple(f.name for f in dataclasses.fields(ScenarioWeights))
    assert fields == SCENARIO_KINDS
    assert set(DELAY_KINDS) <= set(SCENARIO_KINDS)
    # items() yields the same names, in the same order
    assert tuple(k for k, _ in ScenarioWeights().items()) == SCENARIO_KINDS
