"""Campaign driver: determinism, schema v4 payloads, and fleet folds.

The campaign block of a bench payload is exact-compared by
``scripts/bench_compare.py``, so everything derived from the campaign
seed — member scenarios, spot-check selection, nearest-rank
distributions — must be bit-stable across processes and across the
dispatch split. Wall-clock fields are the only permitted variation.
"""
import copy
import json

import pytest

from rapid_tpu.campaign import CampaignConfig, run_campaign
from rapid_tpu.telemetry import metrics as tmetrics
from rapid_tpu.telemetry import schema as tschema
from rapid_tpu.telemetry.metrics import (RunSummary, merge_summaries,
                                         summary_distributions)

#: Machine-dependent payload keys, excluded from determinism diffs.
WALL_KEYS = ("boot_s", "wall_s", "fold_s", "spot_check_s", "ticks_per_sec",
             "rounds_per_sec", "platform")

TINY = CampaignConfig(clusters=6, n=16, ticks=80, seed=9, fleet_size=3,
                      headroom=8, spot_checks=0)


def _strip_wall(payload):
    out = copy.deepcopy(payload)
    for key in WALL_KEYS:
        out.pop(key, None)
    return out


@pytest.fixture(scope="module")
def tiny_payload():
    return run_campaign(TINY)


def test_campaign_is_deterministic_across_dispatches(tiny_payload):
    """Same seed, two runs (each split into 2 dispatches of 3): every
    non-wall field of the payload — merged telemetry, scenario-kind
    counts, distributions — is bit-identical."""
    assert tiny_payload["dispatches"] == 2
    again = run_campaign(TINY)
    assert json.dumps(_strip_wall(tiny_payload), sort_keys=True) == \
        json.dumps(_strip_wall(again), sort_keys=True)


def test_campaign_payload_passes_schema_v4(tiny_payload):
    assert tiny_payload["schema_version"] == tschema.SCHEMA_VERSION == 4
    assert tschema.validate_bench_payload(tiny_payload) == []
    camp = tiny_payload["campaign"]
    assert camp["clusters"] == TINY.clusters
    assert sum(camp["scenario_kinds"].values()) == TINY.clusters
    dists = camp["distributions"]
    assert dists["clusters"] == TINY.clusters
    for key in tschema.CAMPAIGN_DISTRIBUTIONS:
        assert set(dists[key]) == {"count", "p50", "p90", "p99", "max"}
    # v4: the per-receiver accounting block must reconcile with the
    # scenario-kind split and carry a real memory figure
    pr = camp["per_receiver"]
    assert pr["enabled"] is True
    assert 0 <= pr["members"] <= TINY.clusters
    assert sum(pr["kinds"].values()) == pr["members"]
    assert pr["member_state_bytes"] > 0
    assert pr["capacity"] >= TINY.n


def test_spot_check_graceful_degradation(monkeypatch, tmp_path):
    """A spot-check divergence must not kill the campaign outright: with
    ``max_spot_failures`` headroom the payload records structured failure
    members (error line + forensics artifact path) and still validates;
    with the default of 0 the campaign aborts, naming the members."""
    from types import SimpleNamespace

    from rapid_tpu.engine import diff as diff_mod
    from rapid_tpu.telemetry.forensics import DivergenceError

    class _DivergingResult:
        def assert_identical(self, artifact=None):
            if artifact:
                with open(artifact, "w") as fh:
                    fh.write('{"synthetic": true}\n')
            report = SimpleNamespace(render=lambda: "synthetic divergence")
            raise DivergenceError(report, artifact)

    def _diverge(schedule, n_ticks, settings=None):
        return _DivergingResult()

    monkeypatch.setattr(diff_mod, "run_receiver_differential", _diverge)
    monkeypatch.setattr(diff_mod, "run_adversarial_differential", _diverge)

    kw = dict(clusters=2, n=16, ticks=60, seed=11, fleet_size=2,
              headroom=8, spot_checks=2, artifact_dir=str(tmp_path))
    payload = run_campaign(CampaignConfig(max_spot_failures=2, **kw))
    spot = payload["campaign"]["spot_checks"]
    assert spot["run"] == 2 and spot["failed"] == 2 and spot["passed"] == 0
    assert spot["max_failures"] == 2
    for rec in spot["members"]:
        assert rec["passed"] is False
        assert rec["error"] == "synthetic divergence"
        assert rec["artifact"] and rec["artifact"].startswith(str(tmp_path))
    assert tschema.validate_bench_payload(payload) == []

    with pytest.raises(RuntimeError, match="spot-check divergence"):
        run_campaign(CampaignConfig(**kw))


def _summary(**kw):
    base = dict(source="engine", n_ticks=10, announcements=0, decisions=0,
                ticks_to_first_announce=None, ticks_to_first_decide=None,
                messages_per_view_change=None, view_changes=[],
                total_sent=0, total_delivered=0, total_dropped=0,
                total_timeouts=0, total_probes_sent=0,
                total_probes_failed=0)
    base.update(kw)
    return RunSummary(**base)


def test_merge_summaries_gauge_semantics():
    """Counters sum, peak gauges take the max, firsts take the min —
    exactly what GAUGE_SEMANTICS documents."""
    a = _summary(decisions=1, announcements=2, total_sent=100,
                 ticks_to_first_decide=30, invariant_violations=1,
                 max_partitioned_edges=7, total_link_dropped=4,
                 fallback_phase_sent={"fast_vote": 10, "phase1a": 3},
                 view_changes=[{"messages_sent": 60}])
    b = _summary(decisions=2, announcements=2, total_sent=50,
                 ticks_to_first_decide=12, max_partitioned_edges=5,
                 total_link_dropped=9,
                 fallback_phase_sent={"fast_vote": 4},
                 view_changes=[{"messages_sent": 20},
                               {"messages_sent": 10}])
    m = merge_summaries([a, b])
    assert m.decisions == 3 and m.announcements == 4
    assert m.total_sent == 150 and m.total_link_dropped == 13
    assert m.invariant_violations == 1
    assert m.max_partitioned_edges == 7        # max, never 12
    assert m.ticks_to_first_decide == 12       # min, earliest member
    assert m.fallback_phase_sent == {"fast_vote": 14, "phase1a": 3}
    assert m.messages_per_view_change == pytest.approx(90 / 3)
    assert m.view_changes == []                # a distribution, not a log
    with pytest.raises(ValueError):
        merge_summaries([])


def test_gauge_semantics_covers_real_fields():
    fields = set(RunSummary.__dataclass_fields__)
    assert set(tschema.GAUGE_SEMANTICS) <= fields
    # Every peak/min rule named in the schema is honoured by the fold
    # above; anything not listed defaults to "total".
    assert tschema.GAUGE_SEMANTICS["max_partitioned_edges"] == "max"
    assert tschema.GAUGE_SEMANTICS["ticks_to_first_decide"] == "min"


def test_nearest_rank_distributions_are_exact():
    vals = [5, 1, 9, 3, 7]
    d = tmetrics._dist(vals)
    assert d == {"count": 5, "p50": 5, "p90": 9, "p99": 9, "max": 9}
    empty = tmetrics._dist([])
    assert empty["count"] == 0 and empty["p50"] is None


def test_merged_telemetry_matches_member_fold(tiny_payload):
    """The payload's merged telemetry block must agree with its own
    distributions on the observables both report."""
    tel = tiny_payload["telemetry"]
    dists = tiny_payload["campaign"]["distributions"]
    assert tel["source"] == "fleet"
    assert tel["n_ticks"] == TINY.ticks
    # every decided cluster contributes at least one decision to the sum
    assert tel["decisions"] >= dists["decided_clusters"]
    assert tiny_payload["decisions"] == tel["decisions"]
    assert dists["ticks_to_first_decide"]["count"] == \
        dists["decided_clusters"]
