"""Campaign driver: determinism, schema v7 payloads, and fleet folds.

The campaign block of a bench payload is exact-compared by
``scripts/bench_compare.py``, so everything derived from the campaign
seed — member scenarios, spot-check selection, nearest-rank
distributions, and the structural fields of the dispatch timeline —
must be bit-stable across processes and across the dispatch split.
Wall-clock fields (and the per-dispatch memory watermarks, which see
process-global allocator state) are the only permitted variation.
"""
import copy
import json

import pytest

from rapid_tpu.campaign import (MIN_MEASURABLE_WALL_S, CampaignConfig,
                                run_campaign)
from rapid_tpu.faults import SCENARIO_KINDS, ScenarioWeights
from rapid_tpu.telemetry import metrics as tmetrics
from rapid_tpu.telemetry import schema as tschema
from rapid_tpu.telemetry.metrics import (RunSummary, merge_summaries,
                                         summary_distributions)

#: Machine-dependent payload keys, excluded from determinism diffs.
WALL_KEYS = ("boot_s", "wall_s", "fold_s", "compile_s", "device_busy_s",
             "host_blocked_s", "spot_check_s", "total_s", "ticks_per_sec",
             "rounds_per_sec", "clusters_per_sec", "platform",
             "observatory")

#: Machine-dependent fields of one dispatch_timeline record; the
#: structural remainder (index, mode, member counts, kinds, padding,
#: compiled) is seed-deterministic and exact-compared by bench_compare.py.
DISPATCH_WALL_KEYS = ("stages", "wall_s", "clusters_per_sec",
                      "host_blocked_frac", "memory")

#: TINY draws from the full default mix (all eight kinds, latency
#: family included); seed 9 happens to sample latency members only, so
#: every dispatch routes per-receiver — the observatory assertions
#: below are written mode-generically.
TINY = CampaignConfig(clusters=6, n=16, ticks=80, seed=9, fleet_size=3,
                      headroom=8, spot_checks=0)

#: Cheapest campaign whose members straddle both dispatch modes: seed 1
#: of the crash/partition-only mix samples two crash members (shared
#: path) and two partition members (per-receiver path).
STRADDLE = CampaignConfig(
    clusters=4, n=16, ticks=60, seed=1, fleet_size=2, headroom=8,
    weights=ScenarioWeights(
        **{k: (1.0 if k in ("crash", "partition") else 0.0)
           for k in SCENARIO_KINDS}))


def _strip_wall(payload):
    out = copy.deepcopy(payload)
    for key in WALL_KEYS:
        out.pop(key, None)
    for rec in out.get("dispatch_timeline", []):
        for key in DISPATCH_WALL_KEYS:
            rec.pop(key, None)
    return out


@pytest.fixture(scope="module")
def tiny_payload():
    return run_campaign(TINY)


def test_campaign_is_deterministic_across_dispatches(tiny_payload,
                                                     tmp_path_factory):
    """Same seed, two runs (each split into 2 dispatches of 3): every
    non-wall field of the payload — merged telemetry, scenario-kind
    counts, distributions, timeline structure — is bit-identical. The
    second run also exercises --trace/--progress to prove the I/O knobs
    don't perturb the campaign."""
    tmp = tmp_path_factory.mktemp("observatory")
    assert tiny_payload["dispatches"] == 2
    again = run_campaign(TINY, trace_path=str(tmp / "trace.json"),
                         progress_path=str(tmp / "progress.jsonl"))
    assert json.dumps(_strip_wall(tiny_payload), sort_keys=True) == \
        json.dumps(_strip_wall(again), sort_keys=True)

    # Perfetto artifact: parseable, newline-terminated, non-empty.
    raw = (tmp / "trace.json").read_bytes()
    assert raw.endswith(b"\n")
    trace = json.loads(raw)
    names = {e.get("name") for e in trace["traceEvents"]}
    assert {"sample", "lower", "stack", "compile", "execute",
            "fold"} <= names
    # Heartbeat: one parseable line per dispatch plus the final
    # campaign record, every line newline-terminated.
    praw = (tmp / "progress.jsonl").read_bytes()
    assert praw.endswith(b"\n")
    lines = [json.loads(ln) for ln in praw.splitlines() if ln.strip()]
    beats = [ln for ln in lines if ln["record"] == "dispatch"]
    assert len(beats) == len(again["dispatch_timeline"])
    assert beats[-1]["clusters_done"] == TINY.clusters
    # v7: each heartbeat names its dispatch pool and the live pipeline
    # depth, and the stream validates against the progress schema.
    assert tschema.validate_progress_stream(
        praw.decode().splitlines()) == []
    for beat, rec in zip(beats, again["dispatch_timeline"]):
        assert beat["pool_id"] == rec["pool_id"]
        assert beat["pool_shape"] == rec["pool_shape"]
        assert 0 <= beat["in_flight_dispatches"] < 2
    # spot checks run before any dispatch, so every heartbeat carries
    # the real failure count (0 here: TINY requests no spot checks)
    assert all(b["spot_failures"] == 0 for b in beats)
    assert lines[-1]["record"] == "campaign"


def test_campaign_payload_passes_schema_v12(tiny_payload):
    assert tiny_payload["schema_version"] == tschema.SCHEMA_VERSION == 12
    assert tschema.validate_bench_payload(tiny_payload) == []
    camp = tiny_payload["campaign"]
    assert camp["clusters"] == TINY.clusters
    assert sum(camp["scenario_kinds"].values()) == TINY.clusters
    dists = camp["distributions"]
    assert dists["clusters"] == TINY.clusters
    for key in tschema.CAMPAIGN_DISTRIBUTIONS:
        assert set(dists[key]) == {"count", "p50", "p90", "p99", "max"}
    # v4: the per-receiver accounting block must reconcile with the
    # scenario-kind split and carry a real memory figure
    pr = camp["per_receiver"]
    assert pr["enabled"] is True
    assert 0 <= pr["members"] <= TINY.clusters
    assert sum(pr["kinds"].values()) == pr["members"]
    assert pr["member_state_bytes"] > 0
    assert pr["capacity"] >= TINY.n
    # v6: the ring depth the dispatch was sized for, and per-regime
    # decide tails keyed only by known regimes with one entry per
    # latency kind that sampled members
    assert pr["ring_depth"] == 4
    regimes = camp["delay_regimes"]
    assert set(regimes) <= set(tschema.DELAY_REGIMES)
    latency_kinds = {k for k in camp["scenario_kinds"]
                     if k in ("delay", "jitter", "slow_asym")}
    assert latency_kinds <= set(regimes)
    for dist in regimes.values():
        assert set(dist) == {"count", "p50", "p90", "p99", "max"}
    # v7: the dispatch plan's kind-homogeneous pools, reconciling with
    # the timeline's dispatch count and member total.
    pools = camp["pools"]
    assert [p["pool_id"] for p in pools] == list(range(len(pools)))
    assert sum(p["members"] for p in pools) == TINY.clusters
    assert sum(p["dispatches"] for p in pools) == tiny_payload["dispatches"]
    for p in pools:
        assert sum(p["kinds"].values()) == p["members"]
        assert p["fleet_size"] <= TINY.fleet_size
        assert set(p["shape"]) == set(tschema.DISPATCH_PADDING_SPEC)
    # v12: the campaign-wide lineage tails, per kind and per regime.
    lin = camp["lineage"]
    assert tschema.validate_campaign_lineage(lin) == []
    assert set(lin["by_kind"]) <= set(camp["scenario_kinds"])
    assert set(lin["by_regime"]) <= set(tschema.DELAY_REGIMES) | {"no_delay"}


def test_dispatch_timeline_observatory(tiny_payload):
    """v5 tentpole: one record per dispatch, explicit compile split
    (dispatch 0 pays the AOT compile, the same-shape successor is a pure
    executable-cache hit), stage walls that reconcile with the dispatch
    wall, and non-negative padding-waste accounting."""
    timeline = tiny_payload["dispatch_timeline"]
    assert len(timeline) == tiny_payload["dispatches"]
    assert tschema.validate_dispatch_timeline(timeline) == []
    assert sum(r["members"] for r in timeline) == TINY.clusters

    first = timeline[0]
    assert first["compiled"] is True and first["stages"]["compile"] > 0
    later = next(r for r in timeline[1:] if r["mode"] == first["mode"])
    assert later["compiled"] is False and later["stages"]["compile"] == 0.0

    for rec in timeline:
        stage_sum = sum(rec["stages"][s] for s in tschema.DISPATCH_STAGES)
        assert stage_sum == pytest.approx(
            rec["wall_s"], rel=tschema.STAGE_SUM_TOLERANCE, abs=1e-3)
        assert rec["fleet_size"] >= rec["members"]
        assert rec["pad_members"] == rec["fleet_size"] - rec["members"]
        for key, val in rec["padding"].items():
            assert isinstance(val, int) and val >= 0, (key, val)
        if rec["host_blocked_frac"] is not None:
            assert 0.0 <= rec["host_blocked_frac"] <= 1.0
        assert rec["memory"]["live_buffer_bytes"] >= 0
        # v7: every record names its pool, and the pool's stacking
        # maxima bound what any member could have needed.
        assert rec["pool_id"] < len(tiny_payload["campaign"]["pools"])
        assert set(rec["pool_shape"]) == set(tschema.DISPATCH_PADDING_SPEC)

    obs = tiny_payload["observatory"]
    assert tschema.validate_observatory(obs) == []
    assert obs["min_measurable_wall_s"] == MIN_MEASURABLE_WALL_S
    # The three wall components partition the campaign wall.
    assert obs["host_blocked_s"] + obs["device_busy_s"] + obs["compile_s"] \
        <= tiny_payload["wall_s"] + 1e-6
    assert obs["overlap_headroom_s"] <= min(obs["host_blocked_s"],
                                            obs["device_busy_s"]) + 1e-9
    # Every mode the timeline used compiled an executable in this
    # process; unused modes stay None. (Seed 9 of the default mix draws
    # latency members only, so TINY routes everything per-receiver.)
    used_modes = {r["mode"] for r in timeline}
    for mode in ("shared", "per_receiver"):
        info = obs["compile"][mode]
        if mode in used_modes:
            assert info is not None and info["compile_s"] > 0
        else:
            assert info is None
    assert tiny_payload["clusters_per_sec"] is not None
    assert tiny_payload["total_s"] >= tiny_payload["wall_s"]
    # v7: the pipeline block reports the double-buffer depth actually
    # reached, and the per-pool compile ledger reconciles with the
    # timeline's compiled flags.
    pipe = obs["pipeline"]
    assert pipe["enabled"] is True and pipe["max_in_flight"] == 2
    assert 1 <= pipe["peak_in_flight"] <= pipe["max_in_flight"]
    compiled_pools = {r["pool_id"] for r in timeline if r["compiled"]}
    assert {p["pool_id"] for p in obs["compile"]["pools"]} == compiled_pools


def test_campaign_straddling_both_dispatch_modes():
    """Satellite: a campaign whose members split across the shared and
    per-receiver engines must emit one timeline record per mode, fold
    both halves into the same distributions, and keep the member lists
    disjoint and exhaustive."""
    payload = run_campaign(STRADDLE)
    assert tschema.validate_bench_payload(payload) == []
    timeline = payload["dispatch_timeline"]
    modes = {r["mode"] for r in timeline}
    assert modes == {"shared", "per_receiver"}
    assert sum(r["members"] for r in timeline) == STRADDLE.clusters
    camp = payload["campaign"]
    assert camp["distributions"]["clusters"] == STRADDLE.clusters
    assert camp["per_receiver"]["members"] == 2
    assert camp["scenario_kinds"] == {"crash": 2, "partition": 2}
    # Both modes were compiled fresh in this process, so the observatory
    # carries an AOT compile report for each.
    for mode in ("shared", "per_receiver"):
        info = payload["observatory"]["compile"][mode]
        assert info is not None and info["compile_s"] > 0


def test_pipelined_driver_matches_serial(tiny_payload):
    """Tentpole pin: the double-buffered driver changes *when* the host
    fences, not *what* the campaign computes — every non-wall field of
    the payload is bit-identical to the serial (``pipeline=False``)
    driver's, and only the observatory admits which driver ran."""
    import dataclasses

    serial = run_campaign(dataclasses.replace(TINY, pipeline=False))
    assert json.dumps(_strip_wall(tiny_payload), sort_keys=True) == \
        json.dumps(_strip_wall(serial), sort_keys=True)
    assert serial["observatory"]["pipeline"] == {
        "enabled": False, "max_in_flight": 1, "peak_in_flight": 1}


#: Mixed crash+contested campaign for the pooled-padding pin: crash
#: members lower to single-pid fallback tables, contested members to
#: many-pid tables, so a global-maxima stack (the v6 behaviour) pads
#: every crash member up to the contested pid count.
POOLED = CampaignConfig(
    clusters=8, n=16, ticks=60, seed=4, fleet_size=4, headroom=8,
    weights=ScenarioWeights(
        **{k: (1.0 if k in ("crash", "contested") else 0.0)
           for k in SCENARIO_KINDS}))


def test_pools_collapse_padding_below_global_maxima():
    """Satellite: kind-homogeneous pools must beat the old single-
    global-maxima stacking strictly on padding waste, and pool
    membership must be deterministic in the campaign seed."""
    from rapid_tpu.campaign import (_build_pools, _sample_scenario,
                                    _shared_dims)

    payload = run_campaign(POOLED)
    camp = payload["campaign"]
    kinds = camp["scenario_kinds"]
    assert set(kinds) == {"crash", "contested"} and min(kinds.values()) >= 2

    # Reconstruct the old driver's waste: every shared member padded to
    # the campaign-global maxima across *all* shared members.
    scenarios = [_sample_scenario(POOLED, i) for i in range(POOLED.clusters)]
    dims = [_shared_dims(sc) for sc in scenarios]
    global_shape = tuple(max(d[j] for d in dims) for j in range(3))
    f = POOLED.fleet_size
    n_dispatch = -(-len(dims) // f)
    global_padding = {
        "window_rows": n_dispatch * f * global_shape[0],
        "fallback_instances": n_dispatch * f * global_shape[1],
        "fallback_pids": n_dispatch * f * global_shape[2],
    }
    for d in dims:  # live rows don't count as waste (trailing pads do)
        global_padding["window_rows"] -= d[0]
        global_padding["fallback_instances"] -= d[1]
        global_padding["fallback_pids"] -= d[2]

    pooled_padding = {k: sum(r["padding"][k]
                             for r in payload["dispatch_timeline"])
                      for k in ("window_rows", "fallback_instances",
                                "fallback_pids")}
    assert sum(pooled_padding.values()) < sum(global_padding.values())
    # The dominant waste axis — inert contested pid rows on crash
    # members — collapses outright within the crash pool.
    assert pooled_padding["fallback_pids"] < global_padding["fallback_pids"]

    # Pool membership is a pure function of the sampled scenarios.
    rebuilt = _build_pools(scenarios, list(range(POOLED.clusters)), [], f)
    assert [p["members"] for p in rebuilt] == \
        [p["members"] for p in _build_pools(
            scenarios, list(range(POOLED.clusters)), [], f)]
    assert sorted(i for p in rebuilt for i in p["members"]) == \
        list(range(POOLED.clusters))
    # Each pool is kind-pure on the axis that defines it: no crash
    # member shares a pool with a contested member.
    for p in camp["pools"]:
        assert len(p["kinds"]) == 1


def test_merge_summaries_zero_decide_and_single_member():
    """Satellite: members that never announce/decide keep their first-
    event gauges None through the fold (min over non-None values, None
    when no member decided), and a single-member fleet folds to itself."""
    silent = _summary()
    m = merge_summaries([silent, silent])
    assert m.decisions == 0 and m.announcements == 0
    assert m.ticks_to_first_decide is None
    assert m.ticks_to_first_announce is None
    assert m.messages_per_view_change is None

    # One silent + one deciding member: the firsts come from the decider.
    decider = _summary(decisions=1, announcements=1,
                       ticks_to_first_announce=40, ticks_to_first_decide=55)
    m = merge_summaries([silent, decider])
    assert m.ticks_to_first_decide == 55
    assert m.ticks_to_first_announce == 40

    solo = _summary(decisions=2, total_sent=7, ticks_to_first_decide=13,
                    fallback_phase_sent={"phase2a": 5})
    m = merge_summaries([solo])
    assert m.decisions == 2 and m.total_sent == 7
    assert m.ticks_to_first_decide == 13
    assert m.fallback_phase_sent == {"phase2a": 5}


def test_schema_accepts_null_rates(tiny_payload):
    """Satellite: sub-millisecond walls clamp their rates to null rather
    than reporting astronomical throughput; the schema must accept that
    shape at both the run and campaign level."""
    payload = copy.deepcopy(tiny_payload)
    payload["ticks_per_sec"] = None
    payload["rounds_per_sec"] = None
    payload["clusters_per_sec"] = None
    for rec in payload["dispatch_timeline"]:
        rec["clusters_per_sec"] = None
        rec["host_blocked_frac"] = None
    payload["observatory"]["host_blocked_frac"] = None
    payload["observatory"]["device_busy_frac"] = None
    assert tschema.validate_bench_payload(payload) == []


def test_spot_check_graceful_degradation(monkeypatch, tmp_path):
    """A spot-check divergence must not kill the campaign outright: with
    ``max_spot_failures`` headroom the payload records structured failure
    members (error line + forensics artifact path) and still validates;
    with the default of 0 the campaign aborts, naming the members."""
    from types import SimpleNamespace

    from rapid_tpu.engine import diff as diff_mod
    from rapid_tpu.telemetry.forensics import DivergenceError

    class _DivergingResult:
        def assert_identical(self, artifact=None):
            if artifact:
                with open(artifact, "w") as fh:
                    fh.write('{"synthetic": true}\n')
            report = SimpleNamespace(render=lambda: "synthetic divergence")
            raise DivergenceError(report, artifact)

    def _diverge(schedule, n_ticks, settings=None):
        return _DivergingResult()

    monkeypatch.setattr(diff_mod, "run_receiver_differential", _diverge)
    monkeypatch.setattr(diff_mod, "run_adversarial_differential", _diverge)

    kw = dict(clusters=2, n=16, ticks=60, seed=11, fleet_size=2,
              headroom=8, spot_checks=2, artifact_dir=str(tmp_path))
    payload = run_campaign(CampaignConfig(max_spot_failures=2, **kw))
    spot = payload["campaign"]["spot_checks"]
    assert spot["run"] == 2 and spot["failed"] == 2 and spot["passed"] == 0
    assert spot["max_failures"] == 2
    for rec in spot["members"]:
        assert rec["passed"] is False
        assert rec["error"] == "synthetic divergence"
        assert rec["artifact"] and rec["artifact"].startswith(str(tmp_path))
    assert tschema.validate_bench_payload(payload) == []

    with pytest.raises(RuntimeError, match="spot-check divergence"):
        run_campaign(CampaignConfig(**kw))


def _summary(**kw):
    base = dict(source="engine", n_ticks=10, announcements=0, decisions=0,
                ticks_to_first_announce=None, ticks_to_first_decide=None,
                messages_per_view_change=None, view_changes=[],
                total_sent=0, total_delivered=0, total_dropped=0,
                total_timeouts=0, total_probes_sent=0,
                total_probes_failed=0)
    base.update(kw)
    return RunSummary(**base)


def test_merge_summaries_gauge_semantics():
    """Counters sum, peak gauges take the max, firsts take the min —
    exactly what GAUGE_SEMANTICS documents."""
    a = _summary(decisions=1, announcements=2, total_sent=100,
                 ticks_to_first_decide=30, invariant_violations=1,
                 max_partitioned_edges=7, total_link_dropped=4,
                 fallback_phase_sent={"fast_vote": 10, "phase1a": 3},
                 view_changes=[{"messages_sent": 60}])
    b = _summary(decisions=2, announcements=2, total_sent=50,
                 ticks_to_first_decide=12, max_partitioned_edges=5,
                 total_link_dropped=9,
                 fallback_phase_sent={"fast_vote": 4},
                 view_changes=[{"messages_sent": 20},
                               {"messages_sent": 10}])
    m = merge_summaries([a, b])
    assert m.decisions == 3 and m.announcements == 4
    assert m.total_sent == 150 and m.total_link_dropped == 13
    assert m.invariant_violations == 1
    assert m.max_partitioned_edges == 7        # max, never 12
    assert m.ticks_to_first_decide == 12       # min, earliest member
    assert m.fallback_phase_sent == {"fast_vote": 14, "phase1a": 3}
    assert m.messages_per_view_change == pytest.approx(90 / 3)
    assert m.view_changes == []                # a distribution, not a log
    with pytest.raises(ValueError):
        merge_summaries([])


def test_gauge_semantics_covers_real_fields():
    fields = set(RunSummary.__dataclass_fields__)
    assert set(tschema.GAUGE_SEMANTICS) <= fields
    # Every peak/min rule named in the schema is honoured by the fold
    # above; anything not listed defaults to "total".
    assert tschema.GAUGE_SEMANTICS["max_partitioned_edges"] == "max"
    assert tschema.GAUGE_SEMANTICS["ticks_to_first_decide"] == "min"


def test_nearest_rank_distributions_are_exact():
    vals = [5, 1, 9, 3, 7]
    d = tmetrics._dist(vals)
    assert d == {"count": 5, "p50": 5, "p90": 9, "p99": 9, "max": 9}
    empty = tmetrics._dist([])
    assert empty["count"] == 0 and empty["p50"] is None


def test_merged_telemetry_matches_member_fold(tiny_payload):
    """The payload's merged telemetry block must agree with its own
    distributions on the observables both report."""
    tel = tiny_payload["telemetry"]
    dists = tiny_payload["campaign"]["distributions"]
    assert tel["source"] == "fleet"
    assert tel["n_ticks"] == TINY.ticks
    # every decided cluster contributes at least one decision to the sum
    assert tel["decisions"] >= dists["decided_clusters"]
    assert tiny_payload["decisions"] == tel["decisions"]
    assert dists["ticks_to_first_decide"]["count"] == \
        dists["decided_clusters"]
