"""Receiver memory diet: packed bit-plane carry + pallas hot loop.

Pins the PR's exactness contract (ISSUE 16):

- ``pack -> unpack`` is a bit-exact round trip on random planes AND on
  real booted/stepped receiver states (``obs_full`` recomputed from the
  group-12 invariant, epochs rebased through the shared-base delta);
- epoch-delta saturation clamps AND flags (never silently wrong), and
  widening to 16-bit deltas is the documented escape hatch;
- ``rx_kernel="packed"`` / ``"pallas"`` scans are bit-identical to the
  dense ``"xla"`` scan — finals, logs, flags — including a member that
  combines a two-way partition window with delay+jitter rules;
- the default path traces zero pallas calls and the pallas kernel's own
  jaxpr holds no dense ``[C, C]`` intermediate;
- the budget gate sizes the *actual* lowered pytree: analytic bytes
  match XLA's measured argument bytes within 1%, and the structured
  error carries both packed and unpacked figures.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from rapid_tpu.engine import fleet as fleet_mod
from rapid_tpu.engine import receiver as rx_mod
from rapid_tpu.engine import rx_packed, rx_pallas
from rapid_tpu.engine.diff import run_receiver_differential
from rapid_tpu.faults import (SCENARIO_KINDS, AdversarySchedule, DelayRule,
                              LinkWindow, ScenarioWeights,
                              sample_adversary_schedule)
from rapid_tpu.settings import Settings

SETTINGS = Settings()
PACKED = SETTINGS.with_(rx_kernel="packed")
PALLAS = SETTINGS.with_(rx_kernel="pallas")


def _assert_tree_equal(a, b, what):
    for field, x, y in zip(type(a)._fields, a, b):
        assert np.array_equal(np.asarray(x), np.asarray(y)), \
            f"{what}: field {field} diverged"


def _delay_partition_schedule(n=16, seed=11):
    """A member combining a two-way partition window with delay+jitter
    rules — the adversary mix the pallas acceptance gate names."""
    return AdversarySchedule(
        n=n,
        windows=(LinkWindow(src_slots=frozenset(range(4)),
                            dst_slots=frozenset(range(4, n)),
                            start_tick=10, end_tick=40, two_way=True),),
        delays=(DelayRule(src_slots=frozenset(range(0, n // 2)),
                          dst_slots=frozenset(range(n // 2, n)),
                          delay_ticks=1, jitter_ticks=2,
                          start_tick=5, end_tick=50),),
        seed=seed)


def _booted(n=12, seed=0):
    weights = ScenarioWeights(
        **{k: (1.0 if k == "partition" else 0.0) for k in SCENARIO_KINDS})
    sc = sample_adversary_schedule(n, seed, 80, weights)
    return fleet_mod.lower_receiver_schedule(sc.schedule, SETTINGS)


# ---------------------------------------------------------------------------
# pack/unpack round trips
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("shape", [(12,), (3, 16), (5, 7, 13), (4, 64)])
def test_pack_bits_round_trip_random(shape):
    rng = np.random.default_rng(sum(shape))
    x = jnp.asarray(rng.integers(0, 2, size=shape, dtype=np.uint8)
                    .astype(bool))
    packed = rx_packed._pack_bits(jnp, x)
    assert packed.dtype == jnp.uint8
    assert packed.shape == shape[:-1] + (-(-shape[-1] // 8),)
    back = rx_packed._unpack_bits(jnp, packed, shape[-1])
    assert np.array_equal(np.asarray(back), np.asarray(x))


def test_pack_unpack_round_trip_booted_and_stepped():
    """Every field, dtype and shape of the dense state survives a packed
    round trip — on the boot state and after real protocol ticks (the
    group-12 ``obs_full`` invariant is what makes the plane droppable)."""
    member = _booted()
    rs = member.state
    for label, state in (("boot", rs),):
        ps = rx_packed.pack_receiver_state(state, SETTINGS)
        back = rx_packed.unpack_receiver_state(ps, state.delay_table,
                                               SETTINGS)
        _assert_tree_equal(back, state, f"{label} round trip")
        for field, leaf in zip(type(back)._fields, back):
            want = np.asarray(getattr(state, field))
            assert np.asarray(leaf).dtype == want.dtype, field
    final, _ = rx_mod.receiver_simulate(rs, member.faults, 48, SETTINGS)
    ps = rx_packed.pack_receiver_state(final, SETTINGS)
    back = rx_packed.unpack_receiver_state(ps, final.delay_table, SETTINGS)
    _assert_tree_equal(back, final, "stepped round trip")


def test_packed_carry_is_actually_smaller():
    for c in (64, 256, 1024, 4096):
        dense = rx_packed.dense_state_bytes(c, SETTINGS)
        carry = rx_packed.packed_state_bytes(c, SETTINGS)
        bundle = rx_packed.bundle_state_bytes(c, SETTINGS)
        assert carry < bundle < dense
        assert dense / carry > 3.0, f"C={c}: carry diet regressed"
        assert dense / bundle > 2.5, f"C={c}: bundle diet regressed"


# ---------------------------------------------------------------------------
# saturation guards: clamp AND flag, never silently wrong
# ---------------------------------------------------------------------------


def test_epoch_delta_saturation_flags_and_widening():
    member = _booted()
    rs = member.state
    # exactly at the int8 ceiling: no flag, exact round trip
    edge = rs._replace(epoch=rs.epoch.at[0].set(rs.epoch.min() + 127))
    ps = rx_packed.pack_receiver_state(edge, SETTINGS)
    assert ps.epoch_delta.dtype == jnp.int8
    assert int(ps.flags) & rx_mod.FLAG_EPOCH_DELTA_SAT == 0
    back = rx_packed.unpack_receiver_state(ps, rs.delay_table, SETTINGS)
    assert np.array_equal(np.asarray(back.epoch), np.asarray(edge.epoch))
    # one past the ceiling: clamped AND flagged sticky
    over = rs._replace(epoch=rs.epoch.at[0].set(rs.epoch.min() + 128))
    ps = rx_packed.pack_receiver_state(over, SETTINGS)
    assert int(ps.flags) & rx_mod.FLAG_EPOCH_DELTA_SAT
    with pytest.raises(rx_mod.ReceiverEnvelopeError,
                       match="epoch-delta-saturated"):
        rx_mod.check_flags(int(ps.flags))
    # the documented fallback: widen to 16-bit deltas — flag clears and
    # the round trip is exact again
    wide = SETTINGS.with_(rx_epoch_delta_bits=16)
    ps = rx_packed.pack_receiver_state(over, wide)
    assert ps.epoch_delta.dtype == jnp.int16
    assert int(ps.flags) & rx_mod.FLAG_EPOCH_DELTA_SAT == 0
    back = rx_packed.unpack_receiver_state(ps, rs.delay_table, wide)
    assert np.array_equal(np.asarray(back.epoch), np.asarray(over.epoch))


def test_narrow_field_saturation_flags():
    member = _booted()
    rs = member.state
    bad = rs._replace(pb_vrnd_i=rs.pb_vrnd_i.at[0].set(40000))
    ps = rx_packed.pack_receiver_state(bad, SETTINGS)
    assert int(ps.flags) & rx_mod.FLAG_PACK_NARROW_SAT
    with pytest.raises(rx_mod.ReceiverEnvelopeError,
                       match="packed-narrow-overflow"):
        rx_mod.check_flags(int(ps.flags))
    names = rx_mod.decode_flags(rx_mod.FLAG_EPOCH_DELTA_SAT
                                | rx_mod.FLAG_PACK_NARROW_SAT)
    assert "epoch-delta-saturated" in names
    assert "packed-narrow-overflow" in names


# ---------------------------------------------------------------------------
# jaxpr guards
# ---------------------------------------------------------------------------


def test_xla_mode_traces_zero_pallas_calls():
    member = _booted()
    jaxpr = jax.make_jaxpr(
        lambda s, f: rx_mod.receiver_step(s, f, SETTINGS))(
            member.state, member.faults)
    assert "pallas" not in str(jaxpr)


def test_pallas_mode_traces_the_kernel():
    member = _booted()
    jaxpr = jax.make_jaxpr(
        lambda s, f: rx_mod.receiver_step(s, f, PALLAS))(
            member.state, member.faults)
    assert "pallas_call" in str(jaxpr)


def test_pallas_kernel_jaxpr_has_no_dense_plane():
    """The kernel's own program works on packed ``[C, C/8]`` uint8 tiles:
    no ``[C, C]`` intermediate may appear inside the pallas_call."""
    c = 64
    msgs = jnp.zeros((c, c), bool)
    crashed = jnp.zeros((c,), bool)
    pemat = jnp.zeros((c, c // 8), jnp.uint8)
    jaxpr = jax.make_jaxpr(rx_pallas.account)(msgs, crashed, pemat)
    calls = [e for e in jaxpr.eqns if "pallas" in e.primitive.name]
    assert len(calls) == 1
    inner = str(calls[0].params["jaxpr"])
    assert f"{c},{c}]" not in inner, "dense [C,C] plane inside the kernel"
    assert f"{c},{c // 8}]" in inner


# ---------------------------------------------------------------------------
# scan bit-identity: packed and pallas vs the dense reference
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("settings", [PACKED, PALLAS],
                         ids=["packed", "pallas"])
def test_scan_bit_identical_to_dense(settings):
    sched = _delay_partition_schedule()
    member = fleet_mod.lower_receiver_schedule(sched, SETTINGS)
    want_final, want_logs = rx_mod.receiver_simulate(
        member.state, member.faults, 60, SETTINGS)
    got_final, got_logs = rx_mod.receiver_simulate(
        member.state, member.faults, 60, settings)
    _assert_tree_equal(got_final, want_final, "final state")
    _assert_tree_equal(got_logs, want_logs, "logs")
    rx_mod.check_flags(int(np.asarray(got_final.flags)))


def test_packed_differential_device_exact():
    """The oracle referee holds through the packed layout too."""
    sched = _delay_partition_schedule()
    result = run_receiver_differential(sched, 60, PACKED)
    result.assert_identical()


def test_fleet_returns_packed_finals_and_view_folds():
    """Packed dispatches keep their finals packed (the diet applies to
    outputs); ``receiver_final_view`` recovers exactly the fields the
    host fold reads, equal to the dense run's."""
    sched = _delay_partition_schedule()
    dense_member = fleet_mod.lower_receiver_schedule(sched, SETTINGS)
    want_final, want_logs = rx_mod.receiver_simulate(
        dense_member.state, dense_member.faults, 60, SETTINGS)

    member = fleet_mod.lower_receiver_schedule(sched, PACKED)
    assert isinstance(member.state, rx_packed.PackedReceiverBundle)
    fleet = fleet_mod.stack_receiver_members([member])
    finals, logs = fleet_mod.receiver_fleet_simulate(fleet, 60, PACKED)
    assert isinstance(finals, rx_packed.PackedReceiverState)
    view = rx_mod.receiver_final_view(
        jax.tree_util.tree_map(lambda x: x[0], finals))
    assert np.array_equal(view.member, np.asarray(want_final.member))
    assert np.array_equal(view.stopped, np.asarray(want_final.stopped))
    assert np.array_equal(view.cfg_hi, np.asarray(want_final.cfg_hi))
    assert np.array_equal(view.cfg_lo, np.asarray(want_final.cfg_lo))
    assert int(view.flags) == int(np.asarray(want_final.flags))
    mlogs = jax.tree_util.tree_map(lambda x: x[0], logs)
    _assert_tree_equal(mlogs, want_logs, "fleet logs")
    # dense finals pass through the view shim untouched
    assert rx_mod.receiver_final_view(want_final) is want_final


# ---------------------------------------------------------------------------
# budget gate: actual-pytree sizing, structured error, measured pin
# ---------------------------------------------------------------------------


def test_budget_gate_packed_attrs():
    tight = PACKED.with_(receiver_capacity_cap=8)
    with pytest.raises(fleet_mod.ReceiverBudgetError) as exc:
        fleet_mod.check_receiver_budget(16, 4, tight)
    err = exc.value
    assert err.packed_bytes == rx_packed.bundle_state_bytes(16, tight)
    assert err.unpacked_bytes == rx_mod.receiver_state_bytes(
        16, tight.K, ring_depth=tight.delivery_ring_depth)
    assert err.member_bytes == err.packed_bytes
    assert err.packed_bytes < err.unpacked_bytes
    assert "packed layout" in str(err)
    assert fleet_mod.check_receiver_budget(8, 4, tight) == \
        rx_packed.bundle_state_bytes(8, tight)
    # dense mode still reports dense bytes but names the diet headroom
    with pytest.raises(fleet_mod.ReceiverBudgetError) as exc:
        fleet_mod.check_receiver_budget(
            16, 4, SETTINGS.with_(receiver_capacity_cap=8))
    err = exc.value
    assert err.member_bytes == err.unpacked_bytes
    assert err.packed_bytes is not None
    assert err.packed_bytes < err.unpacked_bytes


def test_budget_matches_measured_argument_bytes():
    """Satellite (b): the analytic member figure the budget gate uses
    must match XLA's measured argument bytes (minus the faults operand)
    within 1%, for both layouts, from ``profile.receiver_memory_block``."""
    from rapid_tpu.telemetry.profile import receiver_memory_block

    blk = receiver_memory_block(SETTINGS, n=16, fleet_sizes=(1,))
    c = blk["capacity"]
    weights = ScenarioWeights(crash=0.0, partition=1.0, flip_flop=0.0,
                              contested=0.0, churn=0.0)
    sc = sample_adversary_schedule(16, 0, 8 * SETTINGS.fd_interval_ticks,
                                   weights)
    member = fleet_mod.lower_receiver_schedule(sc.schedule, SETTINGS,
                                               fleet_size=1)
    fleet = fleet_mod.stack_receiver_members([member])
    faults_bytes = rx_packed._tree_bytes(
        jax.eval_shape(lambda t: t, fleet.faults))
    for entry, analytic in (
            (blk["fleets"][0],
             fleet_mod.check_receiver_budget(c, 1, SETTINGS)),
            (blk["packed_fleets"][0],
             fleet_mod.check_receiver_budget(c, 1, PACKED))):
        measured = entry["argument_bytes"] - faults_bytes
        assert abs(measured - analytic) <= 0.01 * analytic, \
            f"measured {measured} vs analytic {analytic}"
    assert blk["member_state_bytes_packed"] < blk["member_state_bytes"]
    curve = {row["capacity"]: row for row in blk["bytes_per_member_curve"]}
    assert curve[1024]["dense_bytes"] == rx_packed.dense_state_bytes(
        1024, SETTINGS)
