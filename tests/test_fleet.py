"""Fleet mode: adversary lowering, vmapped batching, and the sampler.

The core claims pinned here:

- a fleet member's slice of a batched run is bit-identical to the same
  scenario run through the unbatched ``simulate`` (vmap changes the
  batch dimension, never the protocol);
- the vmapped scan traces the tick body exactly once, and the jaxpr of
  the fleet program does not grow with F (no per-member retrace or
  unrolling);
- inert padding (link windows, fallback instances/pids) added so
  heterogeneous scenarios can batch never changes a member's outcome;
- every draw of ``sample_adversary_schedule`` passes
  ``validate_schedule`` and respects the kind weights.
"""
import numpy as np
import pytest

import jax

import importlib

from rapid_tpu.engine import fleet as fleet_mod
from rapid_tpu.engine.state import pad_link_windows
from rapid_tpu.engine.step import simulate

# rapid_tpu.engine re-exports the `step` *function*, which shadows the
# module under `from rapid_tpu.engine import step`.
step_mod = importlib.import_module("rapid_tpu.engine.step")
from rapid_tpu.faults import (SCENARIO_KINDS, AdversarySchedule, LinkWindow,
                              ScenarioWeights, ScriptedPropose,
                              random_adversary_schedule,
                              sample_adversary_schedule, validate_schedule)
from rapid_tpu.settings import Settings

SETTINGS = Settings()
N = 16
TICKS = 120


def _only(kind: str) -> ScenarioWeights:
    """Weights drawing exclusively ``kind`` (every other kind zeroed)."""
    return ScenarioWeights(**{k: (1.0 if k == kind else 0.0)
                              for k in SCENARIO_KINDS})


def _contested_schedule(n: int, seed: int = 11) -> AdversarySchedule:
    """Split votes: no fast quorum, explicit timers, classic fallback."""
    return AdversarySchedule(n=n, proposes=tuple(
        ScriptedPropose(slot=i, tick=5, proposal=(0,) if i % 2 else (1,),
                        delay_ticks=4 + i % 3)
        for i in range(n)), seed=seed)


def _members(schedules):
    return [fleet_mod.lower_schedule(s, SETTINGS) for s in schedules]


def _assert_tree_equal(a, b, what):
    for field, x, y in zip(type(a)._fields, a, b):
        assert np.array_equal(np.asarray(x), np.asarray(y)), \
            f"{what}: field {field} diverged"


def test_fleet_member_matches_unbatched_simulate():
    """Slicing member i out of a fleet run == running it alone."""
    schedules = [random_adversary_schedule(N, seed=3, ticks=TICKS),
                 random_adversary_schedule(N, seed=7, ticks=TICKS),
                 _contested_schedule(N)]
    members = _members(schedules)
    fleet = fleet_mod.stack_members(members)
    finals, logs = fleet_mod.fleet_simulate(fleet, TICKS, SETTINGS)

    w = max(m.faults.n_windows for m in members)
    n_pids = max(m.fallback.table_mask.shape[1] for m in members)
    for i, m in enumerate(members):
        padded = m._replace(
            faults=pad_link_windows(m.faults, w),
            fallback=fleet_mod._pad_fallback(m.fallback, 1, n_pids))
        final, log = simulate(padded.state, padded.faults, TICKS, SETTINGS,
                              padded.churn, padded.fallback)
        _assert_tree_equal(log, fleet_mod.member_logs(logs, i),
                           f"member {i} logs")
        _assert_tree_equal(
            final, jax.tree_util.tree_map(lambda x: x[i], finals),
            f"member {i} final state")


def test_contested_member_decides_via_device_classic_chain():
    """The lowered split-vote scenario recovers through the on-device
    classic-Paxos phases (1a traffic + a decision), not the fast round."""
    members = _members([_contested_schedule(N)])
    _, logs = fleet_mod.fleet_simulate(fleet_mod.stack_members(members),
                                       TICKS, SETTINGS)
    log = fleet_mod.member_logs(logs, 0)
    assert int(np.asarray(log.decide_now).sum()) >= 1
    assert int(np.asarray(log.px1a_senders).sum()) > 0


def test_fleet_traces_tick_body_exactly_once():
    """F members, one trace: batching is an XLA dimension, not a loop."""
    schedules = [random_adversary_schedule(N, seed=s, ticks=40)
                 for s in range(6)]
    fleet = fleet_mod.stack_members(_members(schedules))
    step_mod.reset_trace_count()
    fleet_mod.reset_fleet_trace_count()
    finals, _ = fleet_mod.fleet_simulate(fleet, 40, SETTINGS)
    jax.block_until_ready(finals)
    assert fleet_mod.fleet_trace_count() == 1
    assert step_mod.trace_count() == 1
    # Re-dispatch with fresh scenarios of the same shape: zero retraces.
    fleet2 = fleet_mod.stack_members(
        _members([random_adversary_schedule(N, seed=s, ticks=40)
                  for s in range(10, 16)]))
    finals2, _ = fleet_mod.fleet_simulate(fleet2, 40, SETTINGS)
    jax.block_until_ready(finals2)
    assert fleet_mod.fleet_trace_count() == 1
    assert step_mod.trace_count() == 1


def test_fleet_jaxpr_size_is_f_invariant():
    """The traced program must not grow with the fleet axis."""
    def eqn_count(f):
        fleet = fleet_mod.stack_members(
            _members([random_adversary_schedule(N, seed=s, ticks=30)
                      for s in range(f)]))
        jaxpr = jax.make_jaxpr(
            lambda st, fa, ch, fb: step_mod.fleet_body(
                st, fa, ch, fb, 30, SETTINGS)
        )(fleet.state, fleet.faults, fleet.churn, fleet.fallback)
        return len(jaxpr.jaxpr.eqns)

    assert eqn_count(2) == eqn_count(5)


def test_inert_padding_changes_nothing():
    """Window/instance/pid padding must be protocol-invisible."""
    schedule = random_adversary_schedule(N, seed=5, ticks=TICKS)
    m = fleet_mod.lower_schedule(schedule, SETTINGS)
    padded = m._replace(
        faults=pad_link_windows(m.faults, m.faults.n_windows + 2),
        fallback=fleet_mod._pad_fallback(m.fallback, 3, 4))
    base = simulate(m.state, m.faults, TICKS, SETTINGS, m.churn, m.fallback)
    alt = simulate(padded.state, padded.faults, TICKS, SETTINGS,
                   padded.churn, padded.fallback)
    _assert_tree_equal(base[1], alt[1], "padded logs")
    _assert_tree_equal(base[0], alt[0], "padded final state")


def test_boot_cache_is_bit_transparent():
    """Memoized boot state (shared and per-receiver) must be invisible:
    lowering the same schedules with a cold cache and a warm cache
    yields bit-identical members — including churn members, whose
    id-fingerprint limbs are patched onto the cached template."""
    churn_weights = _only("churn")
    schedules = [random_adversary_schedule(N, seed=s, ticks=TICKS)
                 for s in (3, 8)]
    churn_sc = sample_adversary_schedule(N, 7, TICKS, churn_weights)
    assert churn_sc.wants_churn
    link_weights = ScenarioWeights(
        **{k: (1.0 if k == "partition" else 0.0) for k in SCENARIO_KINDS})
    rx_schedules = [sample_adversary_schedule(
        N, s, 80, link_weights).schedule for s in (2, 5)]

    def lower_all():
        from rapid_tpu.engine import churn as churn_mod

        members = [fleet_mod.lower_schedule(s, SETTINGS)
                   for s in schedules]
        churn_plan, id_fps, _ = churn_mod.synthetic_churn_schedule(
            N + 8, N, SETTINGS.with_(capacity=N + 8), start=10, burst=4)
        members.append(fleet_mod.lower_schedule(
            churn_sc.schedule, SETTINGS.with_(capacity=N + 8),
            churn=churn_plan, id_fps=id_fps))
        rx_members = [fleet_mod.lower_receiver_schedule(s, SETTINGS)
                      for s in rx_schedules]
        return members, rx_members

    fleet_mod.clear_boot_caches()
    cold_members, cold_rx = lower_all()   # populates the caches
    warm_members, warm_rx = lower_all()   # every boot is a cache hit
    for i, (cold, warm) in enumerate(zip(cold_members, warm_members)):
        _assert_tree_equal(cold.state, warm.state, f"member {i} state")
    for i, (cold, warm) in enumerate(zip(cold_rx, warm_rx)):
        _assert_tree_equal(cold.state, warm.state, f"rx member {i} state")
    # Distinct seeds must not collapse onto one cached delay table.
    assert not np.array_equal(
        np.asarray(cold_rx[0].state.delay_table),
        np.asarray(cold_rx[1].state.delay_table))


def test_pad_link_windows_rejects_shrink():
    m = fleet_mod.lower_schedule(
        random_adversary_schedule(N, seed=1, ticks=60), SETTINGS)
    if m.faults.n_windows == 0:
        pytest.skip("seed drew no windows")
    with pytest.raises(ValueError):
        pad_link_windows(m.faults, m.faults.n_windows - 1)


def test_sampled_schedules_all_validate():
    """Property: every draw passes validate_schedule — including the
    delivery-ring budget check the sampler must respect — over many
    seeds, sizes and tick budgets; the default mix covers every kind,
    latency family included."""
    ring = SETTINGS.delivery_ring_depth
    kinds = set()
    for n, ticks in ((8, 60), (32, 300)):
        for seed in range(150):
            sc = sample_adversary_schedule(n, seed, ticks, ring_depth=ring)
            validate_schedule(sc.schedule, ring_depth=ring)  # must not raise
            assert sc.schedule.n == n
            assert sc.schedule.seed == seed
            kinds.add(sc.kind)
    assert kinds == set(SCENARIO_KINDS)


def test_sampler_respects_weights_and_is_deterministic():
    only_contested = _only("contested")
    for seed in range(40):
        sc = sample_adversary_schedule(N, seed, 200, only_contested)
        assert sc.kind == "contested"
        assert sc.schedule.proposes
        again = sample_adversary_schedule(N, seed, 200, only_contested)
        assert again == sc
    with pytest.raises(ValueError):
        ScenarioWeights(**{k: 0.0 for k in SCENARIO_KINDS}).items()


def test_churn_kind_flags_wants_churn():
    sc = sample_adversary_schedule(N, 0, 200, _only("churn"))
    assert sc.kind == "churn" and sc.wants_churn
    assert not sc.schedule.windows and not sc.schedule.proposes


def test_latency_kinds_sample_in_envelope():
    """Property: every latency-family draw carries at least one delay
    rule whose worst case fits the ring it was sampled for, pairs a
    crash burst with the rule (so the member decides *under* latency),
    and the kind-specific shape holds: ``jitter`` draws a non-zero
    jitter bound, ``slow_asym`` a differing reverse base."""
    ring = SETTINGS.delivery_ring_depth
    for kind in ("delay", "jitter", "slow_asym"):
        for seed in range(25):
            sc = sample_adversary_schedule(N, seed, 200, _only(kind),
                                           ring_depth=ring)
            assert sc.kind == kind
            assert sc.schedule.delays and sc.schedule.crashes
            validate_schedule(sc.schedule, ring_depth=ring)
            for r in sc.schedule.delays:
                assert r.max_delay() <= ring - 1
                if kind == "jitter":
                    assert r.jitter_ticks > 0
                if kind == "slow_asym":
                    assert r.reverse_delay_ticks >= 0
                    assert r.reverse_delay_ticks != r.delay_ticks


def test_validate_schedule_rejects_malformed_windows():
    """Zero-length and empty-endpoint windows are silent no-ops in the
    engine (they never match a delivery), so the validator refuses them
    up front rather than letting a campaign run a fault that never
    fired."""
    iso = frozenset(range(4))
    rest = frozenset(range(N)) - iso

    def _sched(win):
        return AdversarySchedule(n=N, windows=(win,), seed=0)

    with pytest.raises(ValueError, match="zero-length window"):
        validate_schedule(_sched(LinkWindow(src_slots=rest, dst_slots=iso,
                                            start_tick=10, end_tick=10)))
    with pytest.raises(ValueError, match="zero-length window"):
        validate_schedule(_sched(LinkWindow(src_slots=rest, dst_slots=iso,
                                            start_tick=12, end_tick=10)))
    with pytest.raises(ValueError, match="non-empty"):
        validate_schedule(_sched(LinkWindow(src_slots=frozenset(),
                                            dst_slots=iso,
                                            start_tick=0, end_tick=5)))
    with pytest.raises(ValueError, match="non-empty"):
        validate_schedule(_sched(LinkWindow(src_slots=iso,
                                            dst_slots=frozenset(),
                                            start_tick=0, end_tick=5)))


def test_validate_schedule_rejects_overlapping_static_windows():
    """Two static windows covering the same directed edge at the same
    tick are ambiguous authorship of one drop; the validator rejects
    the pair, including through ``two_way`` expansion. Flip-flop
    (periodic) windows are exempt — their phases interleave by design."""
    a = LinkWindow(src_slots=frozenset({4, 5}), dst_slots=frozenset({0, 1}),
                   start_tick=5, end_tick=20)
    b = LinkWindow(src_slots=frozenset({5, 6}), dst_slots=frozenset({1, 2}),
                   start_tick=15, end_tick=30)
    with pytest.raises(ValueError, match="overlapping static windows"):
        validate_schedule(AdversarySchedule(n=N, windows=(a, b), seed=0))

    # identical edges but disjoint tick ranges: fine
    c = LinkWindow(src_slots=frozenset({5, 6}), dst_slots=frozenset({1, 2}),
                   start_tick=20, end_tick=30)
    validate_schedule(AdversarySchedule(n=N, windows=(a, c), seed=0))

    # the reverse direction added by two_way collides with a forward one
    fwd = LinkWindow(src_slots=frozenset({0}), dst_slots=frozenset({1}),
                     start_tick=0, end_tick=50)
    rev = LinkWindow(src_slots=frozenset({1}), dst_slots=frozenset({0}),
                     start_tick=10, end_tick=20, two_way=True)
    with pytest.raises(ValueError, match="overlapping static windows"):
        validate_schedule(AdversarySchedule(n=N, windows=(fwd, rev), seed=0))

    # flip-flop windows may share edges with a static window
    flip = LinkWindow(src_slots=frozenset({4, 5}),
                      dst_slots=frozenset({0, 1}),
                      start_tick=5, end_tick=40, period_ticks=5)
    validate_schedule(AdversarySchedule(n=N, windows=(a, flip), seed=0))
