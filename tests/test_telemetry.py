"""Telemetry layer tests: unified metrics, trace export, forensics.

One real differential run (N=64 crash burst, module-scoped) feeds the
metric-parity and summary assertions; the forensics tests perturb a
deep copy of that run to prove a deliberately-divergent engine produces
a first-divergence report naming tick and field. Trace-export validity
is checked structurally: timestamps sorted, B/E pairs matched per
(pid, tid), instants on the decision tick.
"""
import copy
import json

import pytest

from rapid_tpu.engine.diff import (
    ChurnDiffResult,
    ViewEvent,
    read_events_jsonl,
    run_differential,
    write_events_jsonl,
)
from rapid_tpu.settings import Settings
from rapid_tpu.telemetry import (
    COUNTER_FIELDS,
    UNOBSERVED,
    DivergenceError,
    TickMetrics,
    counters_equal,
    read_jsonl,
    summarize,
    write_jsonl,
)
from rapid_tpu.telemetry import schema as tschema

SETTINGS = Settings()


@pytest.fixture(scope="module")
def diff_result():
    """One N=64 crash-burst differential shared by the module's tests."""
    return run_differential(64, {3: 5, 17: 5}, 130)


# ---------------------------------------------------------------------------
# unified TickMetrics
# ---------------------------------------------------------------------------


def test_engine_and_oracle_metrics_agree(diff_result):
    eng = diff_result.engine_metrics
    orc = diff_result.oracle_metrics
    assert len(eng) == len(orc) == 130
    for e, o in zip(eng, orc):
        assert e.source == "engine" and o.source == "oracle"
        assert counters_equal(e, o), (e, o)
        # announce/decide flags are protocol-visible on both sides
        assert (e.announce, e.decide) == (o.announce, o.decide)
        # gauges are engine-side observables only
        assert o.n_member == UNOBSERVED and o.vote_tally == UNOBSERVED
        assert e.n_member in (62, 64)


def test_engine_gauges_traverse_protocol_phases(diff_result):
    eng = diff_result.engine_metrics
    # the crash burst must fill the cut detector and inject alerts
    assert max(m.cut_reports for m in eng) > 0
    assert max(m.alerts_in_flight for m in eng) > 0
    # the decision tick carries a quorum-meeting tally and shrinks the view
    decide = [m for m in eng if m.decide]
    assert len(decide) == 1
    m = decide[0]
    assert m.quorum == 49  # fast_quorum(64) = 64 - 63 // 4
    assert m.vote_tally >= m.quorum
    assert m.epoch == 1
    after = [x for x in eng if x.tick > m.tick]
    assert all(x.n_member == 62 for x in after)


def test_tick_metrics_jsonl_round_trip(tmp_path, diff_result):
    path = tmp_path / "metrics.jsonl"
    write_jsonl(diff_result.engine_metrics, path)
    back = read_jsonl(path)
    assert back == diff_result.engine_metrics
    # every line is standalone JSON with the full field set
    with open(path) as fh:
        first = json.loads(fh.readline())
    assert set(first) == set(TickMetrics(0, "engine").as_dict())


def test_run_summary(diff_result):
    s = summarize(diff_result.engine_metrics)
    assert s.source == "engine"
    assert s.n_ticks == 130
    assert s.announcements == 1 and s.decisions == 1
    assert s.ticks_to_first_announce == 112
    assert s.ticks_to_first_decide == 113
    assert len(s.view_changes) == 1
    vc = s.view_changes[0]
    assert vc["announce_tick"] == 112 and vc["decide_tick"] == 113
    assert vc["messages_sent"] > 0
    assert s.messages_per_view_change == vc["messages_sent"]
    assert s.total_sent >= s.total_delivered
    # oracle stream folds to the same protocol summary
    o = summarize(diff_result.oracle_metrics)
    assert (o.decisions, o.ticks_to_first_decide, o.total_sent) == \
        (s.decisions, s.ticks_to_first_decide, s.total_sent)


def test_view_event_jsonl_round_trip(tmp_path, diff_result):
    path = tmp_path / "events.jsonl"
    write_events_jsonl(diff_result.engine_events, path)
    assert read_events_jsonl(path) == diff_result.engine_events


# ---------------------------------------------------------------------------
# divergence forensics
# ---------------------------------------------------------------------------


def test_clean_run_has_no_divergence(diff_result):
    assert diff_result.first_divergence() is None
    diff_result.assert_identical()  # must not raise


def test_perturbed_counters_name_tick_and_field(tmp_path, diff_result):
    bad = copy.deepcopy(diff_result)
    bad.engine_counters[50]["sent"] += 16
    artifact = tmp_path / "div.jsonl"
    with pytest.raises(DivergenceError) as exc:
        bad.assert_identical(artifact=str(artifact))
    report = exc.value.report
    assert report.tick == 51
    assert report.field == "counters.sent"
    assert report.engine == 16 and report.oracle == 0
    assert "tick 51" in str(exc.value)
    assert report.context, "report must carry trailing context records"
    # artifact: context records first, the divergence record last
    lines = [json.loads(line) for line in
             artifact.read_text().splitlines()]
    assert lines[-1]["record"] == "divergence"
    assert lines[-1]["field"] == "counters.sent"
    assert all(rec["record"] == "tick_metrics" for rec in lines[:-1])


def test_perturbed_events_report_earliest_field(diff_result):
    bad = copy.deepcopy(diff_result)
    bad.engine_events[0] = ViewEvent(
        tick=bad.engine_events[0].tick, kind="view_change",
        config_id=bad.engine_events[0].config_id,
        slots=bad.engine_events[0].slots)
    with pytest.raises(DivergenceError) as exc:
        bad.assert_identical()
    assert exc.value.report.field == "events[0].kind"
    assert exc.value.report.tick == 112

    bad = copy.deepcopy(diff_result)
    del bad.engine_events[1]
    with pytest.raises(DivergenceError) as exc:
        bad.assert_identical()
    assert exc.value.report.field == "events.length"
    assert exc.value.report.tick == 113


def test_churn_plan_divergence_is_attributed():
    # Fabricated triangle: the planner's stream disagrees with the oracle
    # while the engine matches — forensics must blame the plan_* side.
    ev = [ViewEvent(20, "proposal", 7, (64,)),
          ViewEvent(21, "view_change", 9, (64,))]
    plan = [ev[0], ViewEvent(21, "view_change", 10, (64,))]
    res = ChurnDiffResult(
        n_initial=4, capacity=5, n_ticks=40,
        oracle_events=ev, engine_events=list(ev), plan_events=plan,
        oracle_config_id=9, engine_config_id=9, plan_config_id=10,
        oracle_members=frozenset({0, 1, 2, 3, 4}),
        engine_members=frozenset({0, 1, 2, 3, 4}),
        plan_members=frozenset({0, 1, 2, 3, 4}))
    with pytest.raises(DivergenceError) as exc:
        res.assert_identical()
    assert exc.value.report.field == "plan_events[1].config_id"
    assert exc.value.report.tick == 21


# ---------------------------------------------------------------------------
# trace export
# ---------------------------------------------------------------------------


def _paired_b_e(events):
    stacks = {}
    for e in events:
        key = (e["pid"], e["tid"])
        if e["ph"] == "B":
            stacks.setdefault(key, []).append(e)
        elif e["ph"] == "E":
            if not stacks.get(key):
                return False
            stacks[key].pop()
    return all(not s for s in stacks.values())


def test_trace_export_structure(tmp_path):
    from rapid_tpu.engine.state import I32_MAX, crash_faults, init_state
    from rapid_tpu.engine.step import simulate
    from rapid_tpu.oracle.membership_view import uid_of
    from rapid_tpu.telemetry.trace import (
        VIRTUAL_PID,
        WALL_PID,
        TraceWriter,
        trace_from_logs,
        wall_span,
    )
    from rapid_tpu.types import Endpoint

    n = 16
    uids = [uid_of(Endpoint(f"n{i}.sim", 5000)) for i in range(n)]
    state = init_state(uids, id_fp_sum=0, settings=SETTINGS)
    crash = [I32_MAX] * n
    crash[2] = 3
    writer = TraceWriter()
    with wall_span(writer, "device_dispatch", {"ticks": 130}):
        _, logs = simulate(state, crash_faults(crash), 130, SETTINGS)
    trace_from_logs(logs, SETTINGS, writer=writer)

    path = tmp_path / "trace.json"
    writer.write(path)
    payload = json.loads(path.read_text())
    events = payload["traceEvents"]

    ts = [e["ts"] for e in events]
    assert ts == sorted(ts)
    assert _paired_b_e(events)

    walls = [e for e in events
             if e["pid"] == WALL_PID and e["ph"] == "B"]
    assert [e["name"] for e in walls] == ["device_dispatch"]

    instants = [e for e in events if e["ph"] == "i"]
    assert all(e["pid"] == VIRTUAL_PID for e in instants)
    by_name = {e["name"]: e for e in instants}
    assert set(by_name) == {"proposal", "view_change"}
    # the view-change instant lands inside its decision tick's window
    us_per_tick = SETTINGS.tick_ms * 1000
    decide_tick = by_name["view_change"]["args"]["tick"]
    assert decide_tick * us_per_tick <= by_name["view_change"]["ts"] \
        < (decide_tick + 1) * us_per_tick
    assert by_name["view_change"]["args"]["config_id"].startswith("0x")

    slices = {e["name"] for e in events
              if e["pid"] == VIRTUAL_PID and e["ph"] == "B"}
    assert {"deliver", "flush", "monitor"} <= slices
    counters = {e["name"] for e in events if e["ph"] == "C"}
    assert counters == {"membership", "alerts_in_flight", "cut_reports"}


# ---------------------------------------------------------------------------
# shared artifact writers (the trailing-newline contract)
# ---------------------------------------------------------------------------


def test_artifact_writers_terminate_with_newline(tmp_path):
    """Every JSON/JSONL artifact the repo emits goes through the shared
    writers, so the newline-termination contract is pinned here once:
    `tail -n 1 | python -c ...` and `wc -l` must see complete lines."""
    from rapid_tpu.telemetry import (json_artifact_line, write_json_artifact,
                                     write_jsonl_artifact)

    line = json_artifact_line({"b": 1, "a": 2}, sort_keys=True)
    assert line.endswith("\n") and not line[:-1].endswith("\n")
    assert json.loads(line) == {"a": 2, "b": 1}
    assert line.index('"a"') < line.index('"b"')

    path = tmp_path / "artifact.json"
    write_json_artifact(path, {"x": [1, 2]}, indent=2)
    raw = path.read_bytes()
    assert raw.endswith(b"\n") and not raw.endswith(b"\n\n")
    assert json.loads(raw) == {"x": [1, 2]}

    jsonl = tmp_path / "records.jsonl"
    write_jsonl_artifact(jsonl, ({"i": i} for i in range(3)))
    raw = jsonl.read_bytes()
    assert raw.endswith(b"\n")
    rows = [json.loads(ln) for ln in raw.splitlines()]
    assert rows == [{"i": 0}, {"i": 1}, {"i": 2}]

    # empty record streams still produce a valid (empty) artifact
    empty = tmp_path / "empty.jsonl"
    write_jsonl_artifact(empty, [])
    assert empty.read_bytes() == b""


def test_artifact_consumers_ride_the_shared_writers(tmp_path, diff_result):
    """The migrated call sites — metrics JSONL, trace JSON, forensics
    JSONL — all end their files with exactly one newline."""
    from rapid_tpu.telemetry.trace import TraceWriter, wall_span

    mpath = tmp_path / "metrics.jsonl"
    write_jsonl(diff_result.engine_metrics[:4], mpath)
    assert mpath.read_bytes().endswith(b"\n")

    writer = TraceWriter()
    with wall_span(writer, "noop", {}):
        pass
    tpath = tmp_path / "trace.json"
    writer.write(tpath)
    traw = tpath.read_bytes()
    assert traw.endswith(b"\n") and not traw.endswith(b"\n\n")
    json.loads(traw)

    bad = copy.deepcopy(diff_result)
    bad.engine_counters[50]["sent"] += 16
    fpath = tmp_path / "forensics.jsonl"
    with pytest.raises(DivergenceError):
        bad.assert_identical(artifact=str(fpath))
    assert fpath.read_bytes().endswith(b"\n")


# ---------------------------------------------------------------------------
# bench payload schema (the tier-1 smoke contract)
# ---------------------------------------------------------------------------


def test_bench_run_payload_passes_schema():
    import os
    import sys

    # benchmarks/ is a repo-root namespace package, not installed
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    from benchmarks.bench_engine import run

    payload = run(64, 20, crash_frac=0.02, crash_tick=5,
                  settings=SETTINGS)
    assert tschema.validate_bench_payload(payload) == []
    assert payload["telemetry"]["source"] == "engine"
    assert payload["telemetry"]["n_ticks"] == 20
    assert "ticks_to_first_decide" in payload
    assert "messages_per_view_change" in payload


def test_schema_rejects_malformed_payload():
    good = {
        "bench": "engine_tick", "n": 64, "ticks": 20, "wall_s": 0.1,
        "schema_version": tschema.SCHEMA_VERSION,
        "ticks_per_sec": 200.0, "rounds_per_sec": 40.0,
        "telemetry": summarize([]).as_dict(),
    }
    assert tschema.validate_bench_payload(good) == []
    bad = dict(good)
    bad.pop("telemetry")
    assert any("telemetry" in e for e in
               tschema.validate_bench_payload(bad))
    bad = dict(good)
    bad["telemetry"] = dict(good["telemetry"], decisions="three")
    assert any("decisions" in e for e in
               tschema.validate_bench_payload(bad))
    suite = {"bench": "engine_tick_suite",
             "schema_version": tschema.SCHEMA_VERSION, "steady": good}
    assert any("churn" in e for e in
               tschema.validate_bench_payload(suite))


def test_schema_version_is_mandatory_and_pinned():
    good = {
        "bench": "engine_tick", "n": 64, "ticks": 20, "wall_s": 0.1,
        "schema_version": tschema.SCHEMA_VERSION,
        "ticks_per_sec": 200.0, "rounds_per_sec": 40.0,
        "telemetry": summarize([]).as_dict(),
    }
    assert tschema.validate_bench_payload(good) == []
    missing = {k: v for k, v in good.items() if k != "schema_version"}
    assert any("schema_version" in e for e in
               tschema.validate_bench_payload(missing))
    stale = dict(good, schema_version=tschema.SCHEMA_VERSION + 1)
    assert any("schema_version" in e for e in
               tschema.validate_bench_payload(stale))
    mistyped = dict(good, schema_version="1")
    assert any("schema_version" in e for e in
               tschema.validate_bench_payload(mistyped))
