"""Equivalence of the array-native fault-mask overrides vs the scalar path.

The engine materializes fault models as mask tensors; these tests pin the
vectorized ``crash_mask``/``edge_mask`` overrides to the per-edge
``is_crashed``/``edge_ok`` oracle semantics at small N, where the generic
O(n^2) loop is still affordable.
"""
import numpy as np
import pytest

from rapid_tpu.faults import (
    HEALTHY,
    ComposedFault,
    CrashFault,
    FaultModel,
    FlipFlopFault,
    OneWayPartitionFault,
    PacketDropFault,
)
from rapid_tpu.types import Endpoint

N = 24
ENDPOINTS = [Endpoint(f"f{i}.sim", 7000) for i in range(N)]
TICKS = [0, 1, 7, 10, 199, 200, 205, 399, 400, 1000]


def scalar_edge_mask(model, endpoints, tick):
    """The base-class loop, inlined so overrides can't shadow it."""
    n = len(endpoints)
    mask = np.ones((n, n), dtype=bool)
    for i, s in enumerate(endpoints):
        for j, d in enumerate(endpoints):
            mask[i, j] = model.edge_ok(s, d, tick)
    return mask


def scalar_crash_mask(model, endpoints, tick):
    return np.array([model.is_crashed(e, tick) for e in endpoints],
                    dtype=bool)


def models():
    third = frozenset(ENDPOINTS[::3])
    return [
        HEALTHY,
        CrashFault({ENDPOINTS[2]: 5, ENDPOINTS[9]: 200}),
        PacketDropFault(p=0.5, seed=3),
        PacketDropFault(p=0.8, targets=third, ingress=True, egress=False,
                        seed=11),
        PacketDropFault(p=0.3, targets=third, ingress=False, egress=True,
                        seed=12),
        OneWayPartitionFault(from_set=frozenset(ENDPOINTS[:8]),
                             to_set=third, start_tick=10, end_tick=400),
        FlipFlopFault(targets=third, period_ticks=200, start_tick=5),
        FlipFlopFault(targets=third, period_ticks=100, one_way=False),
        ComposedFault([
            CrashFault({ENDPOINTS[0]: 7}),
            OneWayPartitionFault(from_set=third,
                                 to_set=frozenset(ENDPOINTS[1:2])),
            PacketDropFault(p=0.2, seed=5),
        ]),
    ]


@pytest.mark.parametrize("model", models(), ids=lambda m: type(m).__name__)
def test_edge_mask_matches_scalar_path(model):
    for tick in TICKS:
        vec = model.edge_mask(ENDPOINTS, tick)
        ref = scalar_edge_mask(model, ENDPOINTS, tick)
        assert vec.shape == (N, N) and vec.dtype == np.bool_
        assert np.array_equal(vec, ref), \
            f"{type(model).__name__} diverged at tick {tick}"


@pytest.mark.parametrize("model", models(), ids=lambda m: type(m).__name__)
def test_crash_mask_matches_scalar_path(model):
    for tick in TICKS:
        vec = model.crash_mask(ENDPOINTS, tick)
        ref = scalar_crash_mask(model, ENDPOINTS, tick)
        assert np.array_equal(vec, ref)


def test_base_class_shortcut_requires_no_edge_ok_calls():
    """The healthy fast path must not invoke edge_ok at all."""

    class Counting(FaultModel):
        calls = 0

    model = Counting()
    orig = FaultModel.edge_ok

    def counting_edge_ok(self, src, dst, tick):
        Counting.calls += 1
        return orig(self, src, dst, tick)

    # The shortcut keys off ``type(self).edge_ok is FaultModel.edge_ok``;
    # a subclass that *does* override must still go through the loop.
    class Overriding(FaultModel):
        def edge_ok(self, src, dst, tick):
            Overriding.calls += 1
            return True

    Overriding.calls = 0
    mask = model.edge_mask(ENDPOINTS, 0)
    assert mask.all() and Counting.calls == 0

    o = Overriding()
    mask = o.edge_mask(ENDPOINTS, 0)
    assert mask.all() and Overriding.calls == N * N


def test_engine_edge_drop_matches_host_bernoulli():
    """The engine's in-jit drop sampler bit-matches faults._bernoulli."""
    import jax.numpy as jnp

    from rapid_tpu.engine.monitor import edge_drop
    from rapid_tpu.engine.state import EngineFaults
    from rapid_tpu.faults import _bernoulli
    from rapid_tpu.hashing import np_to_limbs
    from rapid_tpu.oracle.membership_view import uid_of

    uids = np.array([uid_of(e) for e in ENDPOINTS], dtype=np.uint64)
    uid_hi, uid_lo = np_to_limbs(uids)
    src = np.arange(N, dtype=np.int32)
    dst = np.roll(src, 7)
    for tick in (0, 3, 250):
        for p, seed in ((0.5, 3), (0.9, 44)):
            faults = EngineFaults(
                crash_tick=jnp.full((N,), 1 << 30, jnp.int32),
                drop_p=p, drop_seed=seed)
            got = np.asarray(edge_drop(
                jnp, faults, jnp.asarray(src), jnp.asarray(dst),
                jnp.asarray(uid_hi), jnp.asarray(uid_lo), jnp.int32(tick)))
            expect = np.array([
                _bernoulli(seed, int(uids[s]), int(uids[d]), tick, p)
                for s, d in zip(src, dst)])
            assert np.array_equal(got, expect)
