"""On-device flight recorder: bounded gauge ring + first-occurrence
stamps threaded through both scan kernels, byte-identical jaxpr with the
recorder off, and host-side extraction helpers."""
import importlib
from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from rapid_tpu import hashing
from rapid_tpu.engine import recorder
from rapid_tpu.engine.state import I32_MAX, crash_faults, init_state
from rapid_tpu.faults import AdversarySchedule, LinkWindow
from rapid_tpu.settings import Settings

step_module = importlib.import_module("rapid_tpu.engine.step")
receiver_module = importlib.import_module("rapid_tpu.engine.receiver")
fleet_module = importlib.import_module("rapid_tpu.engine.fleet")

# Distinct seeds keep each test's Settings a fresh jit-cache row (same
# discipline as test_invariants.py).
OFF = Settings(seed=9101)
ON = replace(OFF, flight_recorder_window=8)


def synthetic_uids(n: int, seed: int = 0) -> np.ndarray:
    """Same synthetic identity scheme as benchmarks/bench_engine.py."""
    hi, lo = hashing.np_to_limbs(np.arange(1, n + 1, dtype=np.uint64))
    hi, lo = hashing.hash64_limbs(np, hi, lo, seed=0xBEEF ^ (seed & 0xFFFF))
    return hashing.np_from_limbs(hi, lo)


def boot(n: int, settings):
    return init_state(synthetic_uids(n), id_fp_sum=0, settings=settings)


def no_faults(n: int):
    return crash_faults([I32_MAX] * n)


def crash_burst(n: int, tick: int = 3, count: int = 4):
    ticks = [I32_MAX] * n
    for slot in range(count):
        ticks[slot] = tick
    return crash_faults(ticks)


# ---------------------------------------------------------------------------
# configuration / ring mechanics
# ---------------------------------------------------------------------------


def test_settings_reject_negative_window():
    with pytest.raises(ValueError):
        Settings(flight_recorder_window=-1)


def test_init_requires_positive_window():
    with pytest.raises(ValueError):
        recorder.init(OFF)
    rec = recorder.init(ON)
    assert rec.ring.shape == (8, recorder.N_GAUGES)
    assert int(np.asarray(rec.count)) == 0
    assert int(np.asarray(rec.first_decide)) == -1
    assert np.all(np.asarray(rec.ring) == recorder.UNOBSERVED)


def test_ring_rows_chronological_after_wraparound():
    # Push synthetic rows past the window; extraction must return the
    # last W in chronological order, not raw ring order.
    rec = recorder.init(ON)
    for tick in range(1, 12):
        row = jnp.full((recorder.N_GAUGES,), tick, jnp.int32)
        rec = recorder._push(rec, row, jnp.int32(tick),
                             jnp.asarray(False), jnp.asarray(False),
                             jnp.asarray(False), jnp.asarray(False))
    rows = np.asarray(recorder.ring_rows(rec))
    assert rows.shape == (8, recorder.N_GAUGES)
    assert list(rows[:, 0]) == list(range(4, 12))


# ---------------------------------------------------------------------------
# zero overhead when off: byte-identical jaxpr, recorder never entered
# ---------------------------------------------------------------------------


def test_shared_off_jaxpr_byte_identical_to_raw_scan():
    n = 16
    state, faults = boot(n, OFF), no_faults(n)

    def raw(s, f):
        def body(carry, _):
            return step_module.step(carry, f, OFF)

        return lax.scan(body, s, None, length=10)

    off = str(jax.make_jaxpr(
        lambda s, f: step_module._simulate.__wrapped__(s, f, 10, OFF))(
            state, faults))
    ref = str(jax.make_jaxpr(raw)(state, faults))
    assert off == ref


def test_receiver_off_jaxpr_byte_identical_to_raw_scan():
    settings = replace(OFF, capacity=12, seed=9102)
    schedule = AdversarySchedule(n=12, seed=3)
    member = fleet_module.lower_receiver_schedule(schedule, settings)

    def raw(rs, f):
        def body(carry, _):
            return receiver_module.receiver_step(carry, f, settings)

        return lax.scan(body, rs, None, length=10)

    off = str(jax.make_jaxpr(
        lambda rs, f: receiver_module._simulate.__wrapped__(
            rs, f, 10, settings))(member.state, member.faults))
    ref = str(jax.make_jaxpr(raw)(member.state, member.faults))
    assert off == ref


def test_off_never_calls_record_step(monkeypatch):
    calls = []
    real = recorder.record_step

    def spy(*args, **kwargs):
        calls.append(1)
        return real(*args, **kwargs)

    # step.py calls recorder.record_step by module attribute, so the spy
    # sees every compile-time entry into the recorder.
    monkeypatch.setattr(recorder, "record_step", spy)

    n = 16
    off = replace(OFF, seed=9103)
    state, faults = boot(n, off), no_faults(n)
    jax.make_jaxpr(
        lambda s, f: step_module._simulate.__wrapped__(s, f, 3, off))(
            state, faults)
    assert calls == [], "recorder off must never enter recorder.py"

    on = replace(off, flight_recorder_window=4)
    jax.make_jaxpr(
        lambda s, f: step_module._simulate.__wrapped__(s, f, 3, on))(
            state, faults)
    assert len(calls) == 1  # the scan body traces once


def test_receiver_off_never_calls_record_receiver_step(monkeypatch):
    calls = []
    real = recorder.record_receiver_step

    def spy(*args, **kwargs):
        calls.append(1)
        return real(*args, **kwargs)

    monkeypatch.setattr(recorder, "record_receiver_step", spy)

    settings = replace(OFF, capacity=12, seed=9104)
    schedule = AdversarySchedule(n=12, seed=4)
    member = fleet_module.lower_receiver_schedule(schedule, settings)
    jax.make_jaxpr(
        lambda rs, f: receiver_module._simulate.__wrapped__(
            rs, f, 3, settings))(member.state, member.faults)
    assert calls == []

    on = replace(settings, flight_recorder_window=4)
    jax.make_jaxpr(
        lambda rs, f: receiver_module._simulate.__wrapped__(
            rs, f, 3, on))(member.state, member.faults)
    assert len(calls) == 1  # the scan body traces once


# ---------------------------------------------------------------------------
# recorder on: transparent to the protocol, rings carry real gauges
# ---------------------------------------------------------------------------


def test_shared_recorder_transparent_and_ring_matches_logs():
    # Same shape as test_invariants' clean steady run: the crash burst
    # at tick 5 saturates the FD and actually decides inside 130 ticks,
    # so the first_announce/first_decide stamps carry real ticks.
    n = 64
    off = replace(OFF, seed=9105)
    on = replace(off, flight_recorder_window=8)
    state, faults = boot(n, off), crash_burst(n, tick=5, count=8)

    _, logs_off = step_module.simulate(state, faults, 130, off)
    final, logs_on, rec = step_module.simulate(state, faults, 130, on)
    np.testing.assert_array_equal(np.asarray(logs_off.decide_now),
                                  np.asarray(logs_on.decide_now))
    np.testing.assert_array_equal(np.asarray(logs_off.epoch),
                                  np.asarray(logs_on.epoch))

    assert int(np.asarray(rec.count)) == 130
    rows = np.asarray(recorder.ring_rows(rec))
    assert rows.shape == (8, recorder.N_GAUGES)
    gauge = {name: i for i, name in enumerate(recorder.GAUGE_NAMES)}
    # The ring's last-W ticks mirror the full StepLog gauges exactly.
    np.testing.assert_array_equal(rows[:, gauge["tick"]],
                                  np.asarray(logs_on.tick)[-8:])
    np.testing.assert_array_equal(rows[:, gauge["epoch"]],
                                  np.asarray(logs_on.epoch)[-8:])
    # Receiver-only gauges stay unobserved in the shared kernel.
    assert np.all(rows[:, gauge["sent"]] == recorder.UNOBSERVED)
    assert np.all(rows[:, gauge["flags"]] == recorder.UNOBSERVED)

    stamps = recorder.stamps(rec)
    decides = np.asarray(logs_on.decide_now)
    first_decide = int(np.asarray(logs_on.tick)[decides.argmax()])
    assert decides.any()
    assert stamps["first_decide"] == first_decide
    assert 0 < stamps["first_announce"] <= stamps["first_decide"]
    assert stamps["first_violation"] == -1


def test_receiver_recorder_transparent_and_flags_gauge():
    n = 12
    settings = replace(OFF, capacity=n, seed=9106)
    on = replace(settings, flight_recorder_window=6)
    schedule = AdversarySchedule(
        n=n, seed=9, crashes=((0, 4),),
        windows=(LinkWindow(src_slots=frozenset(range(0, 4)),
                            dst_slots=frozenset(range(4, 12)),
                            start_tick=2, end_tick=9),))
    member = fleet_module.lower_receiver_schedule(schedule, settings)

    _, logs_off = receiver_module.receiver_simulate(
        member.state, member.faults, 25, settings)
    member_on = fleet_module.lower_receiver_schedule(schedule, on)
    _, logs_on, rec = receiver_module.receiver_simulate(
        member_on.state, member_on.faults, 25, on)
    np.testing.assert_array_equal(np.asarray(logs_off.sent),
                                  np.asarray(logs_on.sent))
    np.testing.assert_array_equal(np.asarray(logs_off.decide),
                                  np.asarray(logs_on.decide))

    rows = np.asarray(recorder.ring_rows(rec))
    gauge = {name: i for i, name in enumerate(recorder.GAUGE_NAMES)}
    assert rows.shape == (6, recorder.N_GAUGES)
    np.testing.assert_array_equal(rows[:, gauge["sent"]],
                                  np.asarray(logs_on.sent)[-6:])
    # Shared-only gauges stay unobserved in the receiver kernel.
    assert np.all(rows[:, gauge["epoch"]] == recorder.UNOBSERVED)
    assert np.all(rows[:, gauge["vote_tally"]] == recorder.UNOBSERVED)


def test_fleet_recorder_slices_per_member():
    n = 16
    on = replace(OFF, flight_recorder_window=5, seed=9107)
    members = [
        fleet_module.lower_schedule(
            AdversarySchedule(n=n, seed=s, crashes=((0, 2 + s),)), on)
        for s in range(3)
    ]
    fleet = fleet_module.stack_members(members)
    finals, logs, recs = fleet_module.fleet_simulate(fleet, 12, on)
    assert recs.ring.shape == (3, 5, recorder.N_GAUGES)
    for i in range(3):
        one = recorder.member_recorder(recs, i)
        payload = recorder.recorder_payload(one)
        assert payload["window"] == 5
        assert payload["ticks_recorded"] == 12
        assert payload["gauges"] == list(recorder.GAUGE_NAMES)
        assert len(payload["rows"]) == 5
        # Per-member slice equals a solo run of the same member.
        solo = fleet_module.stack_members([members[i]])
        _, _, solo_rec = fleet_module.fleet_simulate(solo, 12, on)
        np.testing.assert_array_equal(
            np.asarray(one.ring),
            np.asarray(recorder.member_recorder(solo_rec, 0).ring))
