"""Cluster event subscription types.

Reference: ClusterEvents.java:19-24, ClusterStatusChange.java:20-49,
NodeStatusChange.java:26-40.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import List, Tuple

from rapid_tpu.types import EdgeStatus, Endpoint, Metadata


class ClusterEvents(enum.Enum):
    VIEW_CHANGE_PROPOSAL = 0
    VIEW_CHANGE = 1
    VIEW_CHANGE_ONE_STEP_FAILED = 2  # declared (as in the reference), never fired
    KICKED = 3


@dataclass(frozen=True)
class NodeStatusChange:
    endpoint: Endpoint
    status: EdgeStatus
    metadata: Tuple[Tuple[str, bytes], ...] = ()


@dataclass(frozen=True)
class ClusterStatusChange:
    configuration_id: int
    membership: Tuple[Endpoint, ...]
    status_changes: Tuple[NodeStatusChange, ...]
