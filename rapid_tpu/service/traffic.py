"""Open-loop traffic generator: seeded arrival processes -> ChurnSchedule.

The resident engine (``service.resident``) consumes membership traffic
chunk by chunk; this module generates it the way a live deployment
would see it — *open loop*, arrivals keep coming whether or not the
protocol has caught up:

- **Poisson joins** — per-tick arrivals drawn ``Poisson(lambda_t)``,
  ``lambda_t = join_rate_per_ktick / 1000`` nodes/tick;
- **correlated leave bursts** — at exponentially-distributed instants a
  *contiguous block* of current members departs together (a rack/zone
  going away, not independent attrition; under the ``two_zone`` slot
  split of ``faults.two_zone_schedule`` a block is one zone's slice);
- **diurnal waves** — ``lambda_t`` modulated by
  ``1 + amplitude * sin(2*pi*t / period)``, so soak runs sweep through
  load peaks and troughs instead of a flat rate.

Arrivals accumulate into *bursts* lowered onto the existing
``ChurnSchedule`` enqueue-tick encoding (``engine.churn``), under the
same envelope ``synthetic_churn_schedule`` obeys: one alert pipeline in
flight (bursts spaced ``>= churn_decide_delay_ticks + 1`` ticks, default
``+ 3``), each burst homogeneous (all-joins or all-leaves) with its
epoch expectation equal to the count of previously decided bursts, and
dormant identifier fingerprints drawn from the same
``hash64(slot, seed=0x6964)`` stream. Because the encoding and epoch
accounting are exactly the planner's, a generated horizon can be
replayed through the host oracle referee: :meth:`TrafficGenerator
.churn_calls` rewrites enqueue ticks back to ``Cluster.join()`` /
``leave_gracefully()`` call ticks for ``engine.churn.plan_churn`` /
``diff.run_churn_differential`` (run with ``reuse_slots=False`` — the
oracle remembers identifiers forever, so slot recycling is an
engine-only economy for unbounded soaks).

Determinism: one ``numpy`` PCG64 stream, advanced strictly per tick, so
the chunk split never changes the traffic — 10 chunks of 100 ticks draw
the identical event sequence as 1 chunk of 1000. The full generator
state (rng snapshot included) round-trips through
:meth:`TrafficGenerator.state_dict` for the checkpoint ``host`` blob.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional

import numpy as np

from rapid_tpu import hashing
from rapid_tpu.engine.churn import ChurnSchedule, empty_schedule
from rapid_tpu.settings import Settings

#: Seed namespace for dormant-slot identifier fingerprints — must match
#: ``engine.churn.synthetic_churn_schedule`` so generated joiners carry
#: the same identities the engine-side boot expects.
ID_FP_SEED = 0x6964


@dataclasses.dataclass(frozen=True)
class TrafficConfig:
    """Knobs for one seeded arrival process (all rates in events per
    1000 ticks of virtual time; the bench reports the wall-clock
    events/sec this sustains)."""

    seed: int = 0
    #: Mean Poisson join arrivals per 1000 ticks.
    join_rate_per_ktick: float = 20.0
    #: Mean correlated leave *bursts* per 1000 ticks (exponential
    #: inter-arrival), each removing ``leave_burst_size`` members.
    leave_burst_rate_per_ktick: float = 2.0
    leave_burst_size: int = 4
    #: Diurnal modulation of the join rate: 0 = flat, 0.8 = swings
    #: between 0.2x and 1.8x the base rate over ``diurnal_period_ticks``.
    diurnal_amplitude: float = 0.0
    diurnal_period_ticks: int = 2000
    #: Minimum ticks between burst enqueues; 0 derives the same
    #: ``churn_decide_delay_ticks + 3`` spacing
    #: ``synthetic_churn_schedule`` uses.
    burst_spacing_ticks: int = 0
    #: Cap on joins lowered into one burst (excess stays queued —
    #: open-loop backpressure, never dropped).
    max_join_burst: int = 8
    #: Leave bursts never shrink membership below this floor.
    min_members: int = 8
    #: Recycle slots whose members left (engine-only semantics; disable
    #: for oracle-refereed replays, where identifiers live forever). A
    #: freed slot cools down for ``max(burst spacing,
    #: Settings.stream_chunk_ticks)`` ticks before it may rejoin — the
    #: delay depends only on when the slot left, never on where a chunk
    #: boundary fell, so recycling preserves chunk-split invariance.
    reuse_slots: bool = True
    #: Closed-loop sampling (``service.servo``): joins draw exactly one
    #: uniform per tick and invert the Poisson CDF, so the rng stream
    #: advances identically whatever the current rate — the servo may
    #: retarget ``set_join_rate`` between chunks without perturbing the
    #: seeded stream, and a recorded rate trace replays byte-exactly.
    #: False keeps the historical ``rng.poisson`` draw (whose stream
    #: consumption is rate-dependent) and rejects ``set_join_rate``.
    closed_loop: bool = False

    def __post_init__(self) -> None:
        if self.join_rate_per_ktick < 0 or self.leave_burst_rate_per_ktick < 0:
            raise ValueError("traffic rates must be >= 0")
        if not (0.0 <= self.diurnal_amplitude <= 1.0):
            raise ValueError(
                f"diurnal_amplitude must be in [0, 1], got "
                f"{self.diurnal_amplitude}")
        if self.diurnal_period_ticks < 1:
            raise ValueError("diurnal_period_ticks must be >= 1")
        if self.leave_burst_size < 1 or self.max_join_burst < 1:
            raise ValueError("burst sizes must be >= 1")

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def _poisson_inverse(u: float, lam: float) -> int:
    """Poisson sample by CDF inversion from one uniform draw —
    deterministic in ``(u, lam)`` and rate-independent in rng
    consumption. Per-tick lambdas here are O(1) (at most
    ``max_rate_per_ktick / 1000`` per tick times the diurnal swing), so
    the walk terminates in a handful of steps; the hard cap guards a
    pathological hand-built config."""
    if lam <= 0.0:
        return 0
    p = math.exp(-lam)
    cum = p
    k = 0
    while u > cum and k < 4096:
        k += 1
        p *= lam / k
        cum += p
    return k


class TrafficGenerator:
    """Stateful chunk-by-chunk lowering of one arrival process.

    ``capacity`` slots total; ``[0, n_initial)`` boot as members, the
    rest are the dormant joiner pool. Call :meth:`next_chunk` with
    consecutive tick windows; each returns a ``ChurnSchedule`` covering
    exactly that window (or None when no event falls inside it).
    """

    def __init__(self, config: TrafficConfig, settings: Settings,
                 capacity: int, n_initial: int, start_tick: int = 0,
                 start_epoch: int = 0):
        if n_initial >= capacity:
            raise ValueError(
                f"capacity ({capacity}) must exceed n_initial "
                f"({n_initial}) to leave a joiner pool")
        self.config = config
        self.capacity = int(capacity)
        self.n_initial = int(n_initial)
        spacing = config.burst_spacing_ticks
        if spacing == 0:
            spacing = settings.churn_decide_delay_ticks + 3
        if spacing <= settings.churn_decide_delay_ticks:
            raise ValueError(
                f"burst_spacing_ticks ({spacing}) must exceed the "
                f"enqueue->decide delay "
                f"({settings.churn_decide_delay_ticks}) so at most one "
                f"alert pipeline is in flight")
        self._spacing = int(spacing)
        self._decide_delay = int(settings.churn_decide_delay_ticks)
        # Slot-recycle eligibility is *history-only* (freed at tick t ->
        # re-join-eligible at t + recycle), never per-chunk bookkeeping:
        # chunk-split invariance demands that whether a slot can rejoin
        # depends on when it left, not on where a chunk boundary fell.
        # recycle >= stream_chunk_ticks also guarantees a slot appears
        # at most once per field in any schedule covering a window of
        # up to stream_chunk_ticks — the ChurnSchedule encoding's limit.
        self._recycle = max(self._spacing,
                            int(settings.stream_chunk_ticks))
        self._rng = np.random.Generator(np.random.PCG64(config.seed))
        # The live join rate: config.join_rate_per_ktick until a servo
        # retargets it (closed_loop only — see set_join_rate).
        self._rate_per_ktick = float(config.join_rate_per_ktick)
        self._members = sorted(range(n_initial))
        # FIFO of [slot, eligible_tick]; the boot pool is eligible
        # immediately.
        self._free = [[s, 0] for s in range(n_initial, capacity)]
        self._epoch = int(start_epoch)
        self._tick = int(start_tick)
        # First burst lands one full spacing in, so rewriting enqueue
        # ticks back to Cluster-call ticks (``churn_calls``) never goes
        # below tick 1.
        self._next_enqueue = int(start_tick) + self._spacing
        self._pending_joins = 0
        self._pending_leaves = 0
        self.events = 0
        self.joins = 0
        self.leaves = 0
        self.bursts = 0
        self._calls: list = []   # (kind, enqueue_tick, slots) history

    # --- boot-side helpers ------------------------------------------------

    def boot_id_fps(self) -> np.ndarray:
        """Identifier fingerprints for every dormant slot (the
        ``init_state(id_fps=...)`` argument), same stream as
        ``synthetic_churn_schedule``."""
        id_fps = np.zeros(self.capacity, np.uint64)
        for s in range(self.n_initial, self.capacity):
            id_fps[s] = np.uint64(hashing.hash64(s, seed=ID_FP_SEED))
        return id_fps

    @property
    def n_members(self) -> int:
        return len(self._members)

    # --- the arrival process ---------------------------------------------

    def set_join_rate(self, rate_per_ktick: float) -> None:
        """Retarget the join rate (events per kilotick) — the servo's
        actuator. Only legal on closed-loop generators, where the rng
        advancement is rate-independent; changing the Poisson lambda of
        the open-loop ``rng.poisson`` draw would silently shift the
        seeded stream."""
        if not self.config.closed_loop:
            raise ValueError(
                "set_join_rate requires TrafficConfig.closed_loop=True "
                "(open-loop rng advancement is rate-dependent)")
        if rate_per_ktick < 0:
            raise ValueError(
                f"rate_per_ktick must be >= 0, got {rate_per_ktick}")
        self._rate_per_ktick = float(rate_per_ktick)

    def _join_rate(self, t: int) -> float:
        base = self._rate_per_ktick / 1000.0
        amp = self.config.diurnal_amplitude
        if amp == 0.0:
            return base
        return base * (1.0 + amp * math.sin(
            2.0 * math.pi * t / self.config.diurnal_period_ticks))

    def _emit_leave_burst(self, t: int, chunk_bursts: list) -> None:
        floor = self.config.min_members
        take = min(self._pending_leaves, max(0, len(self._members) - floor))
        if take <= 0:
            self._pending_leaves = 0
            return
        # Correlated departure: a contiguous block of the live slot
        # order leaves together.
        start = int(self._rng.integers(0, len(self._members)))
        slots = [self._members[(start + i) % len(self._members)]
                 for i in range(take)]
        for s in slots:
            self._members.remove(s)
            if self.config.reuse_slots:
                self._free.append([s, t + self._recycle])
        self._pending_leaves -= len(slots)
        chunk_bursts.append(("leave", t, self._epoch, sorted(slots)))
        self._calls.append(("leave", t, tuple(sorted(slots))))
        self._epoch += 1
        self.leaves += len(slots)
        self.events += len(slots)
        self.bursts += 1
        self._next_enqueue = t + self._spacing

    def _emit_join_burst(self, t: int, chunk_bursts: list) -> None:
        want = min(self._pending_joins, self.config.max_join_burst)
        slots = []
        kept = []
        while self._free and len(slots) < want:
            entry = self._free.pop(0)
            s, eligible = entry
            # Slots still cooling down stay queued in FIFO order.
            if eligible > t:
                kept.append(entry)
            else:
                slots.append(s)
        self._free = kept + self._free
        if not slots:
            return
        for s in slots:
            self._members.append(s)
        self._members.sort()
        self._pending_joins -= len(slots)
        chunk_bursts.append(("join", t, self._epoch, sorted(slots)))
        self._calls.append(("join", t, tuple(sorted(slots))))
        self._epoch += 1
        self.joins += len(slots)
        self.events += len(slots)
        self.bursts += 1
        self._next_enqueue = t + self._spacing

    def next_chunk(self, n_ticks: int) -> tuple:
        """Advance the process over the next ``n_ticks`` ticks; returns
        ``(schedule, info)`` where ``schedule`` is a ``ChurnSchedule``
        whose enqueue ticks all fall in ``(tick, tick + n_ticks]`` (None
        when the window is quiet) and ``info`` counts what was lowered.
        """
        leave_per_tick = self.config.leave_burst_rate_per_ktick / 1000.0
        chunk_bursts: list = []
        t0 = self._tick
        closed = self.config.closed_loop
        for t in range(t0 + 1, t0 + int(n_ticks) + 1):
            if closed:
                self._pending_joins += _poisson_inverse(
                    self._rng.random(), self._join_rate(t))
            else:
                self._pending_joins += int(
                    self._rng.poisson(self._join_rate(t)))
            if self._rng.random() < leave_per_tick:
                self._pending_leaves += self.config.leave_burst_size
            if t < self._next_enqueue:
                continue
            if self._pending_leaves > 0:
                self._emit_leave_burst(t, chunk_bursts)
            elif self._pending_joins > 0:
                self._emit_join_burst(t, chunk_bursts)
        self._tick = t0 + int(n_ticks)
        info = {
            "bursts": len(chunk_bursts),
            "joins": sum(len(b[3]) for b in chunk_bursts if b[0] == "join"),
            "leaves": sum(len(b[3]) for b in chunk_bursts if b[0] == "leave"),
            "backlog_joins": self._pending_joins,
            "backlog_leaves": self._pending_leaves,
            "n_members": len(self._members),
        }
        info["events"] = info["joins"] + info["leaves"]
        if not chunk_bursts:
            return None, info
        schedule = empty_schedule(self.capacity)
        from rapid_tpu.engine.state import I32_MAX
        for kind, t, epoch, slots in chunk_bursts:
            for s in slots:
                field = (schedule.join_tick if kind == "join"
                         else schedule.leave_tick)
                if field[s] != I32_MAX:
                    # Structurally impossible for windows within the
                    # slot-recycle delay; an oversized manual window can
                    # revisit a slot, which the per-slot enqueue-tick
                    # encoding cannot express.
                    raise ValueError(
                        f"chunk window of {n_ticks} ticks revisits slot "
                        f"{s} ({kind}); windows must not exceed the "
                        f"slot-recycle delay ({self._recycle} ticks)")
                if kind == "join":
                    schedule.join_tick[s] = t
                    schedule.join_epoch[s] = epoch
                else:
                    schedule.leave_tick[s] = t
                    schedule.leave_epoch[s] = epoch
        return schedule, info

    # --- oracle-referee bridge -------------------------------------------

    def churn_calls(self, settings: Settings) -> tuple:
        """The generated history as ``Cluster`` call ticks —
        ``(joins, leaves)`` dicts of ``slot -> call tick`` in
        ``plan_churn`` / ``diff.run_churn_differential`` form (enqueue
        minus the join/leave RPC pipeline delays). Only meaningful with
        ``reuse_slots=False``: the oracle remembers identifiers forever.
        """
        if self.config.reuse_slots:
            raise ValueError(
                "churn_calls requires reuse_slots=False (the oracle "
                "referee never recycles identifiers)")
        joins: dict = {}
        leaves: dict = {}
        for kind, t, slots in self._calls:
            if kind == "join":
                for s in slots:
                    joins[s] = t - settings.join_enqueue_delay_ticks
            else:
                for s in slots:
                    leaves[s] = t - settings.leave_enqueue_delay_ticks
        return joins, leaves

    # --- checkpoint host blob --------------------------------------------

    def state_dict(self) -> dict:
        """JSON-serializable snapshot (rng stream included) for the
        checkpoint ``host`` blob; exact resume via :meth:`from_state`."""
        rng_state = self._rng.bit_generator.state
        return {
            "kind": "traffic_generator",
            "config": self.config.as_dict(),
            "capacity": self.capacity,
            "n_initial": self.n_initial,
            "rng": {"state": int(rng_state["state"]["state"]),
                    "inc": int(rng_state["state"]["inc"]),
                    "has_uint32": int(rng_state["has_uint32"]),
                    "uinteger": int(rng_state["uinteger"])},
            "rate_per_ktick": self._rate_per_ktick,
            "members": list(self._members),
            "free": [[int(s), int(e)] for s, e in self._free],
            "epoch": self._epoch,
            "tick": self._tick,
            "next_enqueue": self._next_enqueue,
            "pending_joins": self._pending_joins,
            "pending_leaves": self._pending_leaves,
            "events": self.events,
            "joins": self.joins,
            "leaves": self.leaves,
            "bursts": self.bursts,
        }

    @classmethod
    def from_state(cls, state: dict, settings: Settings
                   ) -> "TrafficGenerator":
        config = TrafficConfig(**state["config"])
        gen = cls(config, settings, state["capacity"], state["n_initial"])
        gen._rng.bit_generator.state = {
            "bit_generator": "PCG64",
            "state": {"state": state["rng"]["state"],
                      "inc": state["rng"]["inc"]},
            "has_uint32": state["rng"]["has_uint32"],
            "uinteger": state["rng"]["uinteger"],
        }
        gen._rate_per_ktick = float(
            state.get("rate_per_ktick", config.join_rate_per_ktick))
        gen._members = list(state["members"])
        gen._free = [[int(s), int(e)] for s, e in state["free"]]
        gen._epoch = int(state["epoch"])
        gen._tick = int(state["tick"])
        gen._next_enqueue = int(state["next_enqueue"])
        gen._pending_joins = int(state["pending_joins"])
        gen._pending_leaves = int(state["pending_leaves"])
        gen.events = int(state["events"])
        gen.joins = int(state["joins"])
        gen.leaves = int(state["leaves"])
        gen.bursts = int(state["bursts"])
        return gen
