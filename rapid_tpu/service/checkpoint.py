"""Versioned checkpoint/restore for resident-engine carries.

A checkpoint is a directory artifact with two files:

- ``manifest.json`` — ``record: "rapid_tpu_checkpoint"``, the pinned
  ``CHECKPOINT_VERSION``, the telemetry ``schema_version``, the carry
  *family*, the tick the carry had reached, a snapshot of the
  layout-bearing ``Settings`` statics, a leaf table
  (``name``/``dtype``/``shape`` per array), and an optional ``host``
  blob (JSON-serializable driver state, e.g. the traffic generator's
  rng snapshot) — validated by ``telemetry.schema
  .validate_checkpoint_manifest``;
- ``arrays.npz`` — every pytree leaf under ``<part>.<field>`` keys,
  saved with ``allow_pickle=False`` so a checkpoint can never smuggle
  code.

Families map parts to carry types:

- ``"engine"`` — ``state`` (``EngineState``);
- ``"receiver_dense"`` — ``state`` (``ReceiverState``, the
  ``rx_kernel="xla"`` carry);
- ``"receiver_packed"`` — ``packed`` (``rx_packed.PackedReceiverState``,
  the ``"packed"``/``"pallas"`` carry, epoch-delta base and sticky flags
  included) plus ``delay_table`` (the scan constant that lives outside
  the packed carry);

every family optionally carries ``recorder``
(``engine.recorder.RecorderState``) so a restored run resumes the gauge
ring mid-fill.

Restore is strict, never best-effort: a version mismatch raises
``CheckpointVersionError`` naming saved vs expected version; a statics
mismatch (restoring a packed carry under ``rx_kernel="xla"``, a
different ring depth, a different recorder window) raises
``CheckpointCompatError`` naming every differing field; leaf-table
drift between manifest and npz raises ``CheckpointError``. Round-trips
are bit-exact — ``tests/test_service.py`` proves a restored carry
continues byte-identically (``StepLog`` columns and recorder ring) to
the uninterrupted scan for all three families.
"""
from __future__ import annotations

import json
import os
from typing import NamedTuple, Optional

import numpy as np

import jax.numpy as jnp

from rapid_tpu.engine import recorder as recorder_mod
from rapid_tpu.engine.state import EngineState, ReceiverState
from rapid_tpu.settings import Settings
from rapid_tpu.telemetry import write_json_artifact

#: Bump on any incompatible change to the directory layout, the leaf
#: key scheme, or the manifest fields. Restore refuses other versions.
CHECKPOINT_VERSION = 1

CHECKPOINT_RECORD = "rapid_tpu_checkpoint"

MANIFEST_NAME = "manifest.json"
ARRAYS_NAME = "arrays.npz"

FAMILIES = ("engine", "receiver_dense", "receiver_packed")

#: Settings fields that shape the saved arrays (or gate which carry
#: layout is legal); snapshotted at save and compared field-by-field at
#: restore.
STATIC_FIELDS = ("K", "delivery_ring_depth", "rx_kernel",
                 "rx_epoch_delta_bits", "flight_recorder_window")


class CheckpointError(ValueError):
    """Malformed or internally inconsistent checkpoint artifact."""


class CheckpointVersionError(CheckpointError):
    """Saved checkpoint version differs from this build's pin."""

    def __init__(self, saved: int, expected: int):
        self.saved = saved
        self.expected = expected
        super().__init__(
            f"checkpoint was saved as version {saved} but this build "
            f"reads version {expected}; re-save with a matching build "
            f"(no cross-version migration is defined)")


class CheckpointCompatError(CheckpointError):
    """Saved layout statics differ from the restoring ``Settings``."""

    def __init__(self, mismatches: dict):
        self.mismatches = dict(mismatches)
        detail = ", ".join(
            f"{k}: saved={s!r} expected={e!r}"
            for k, (s, e) in sorted(self.mismatches.items()))
        super().__init__(
            f"checkpoint statics do not match the restoring Settings "
            f"({detail}); restore with the Settings the run was saved "
            f"under")


class Checkpoint(NamedTuple):
    """A restored checkpoint: ``parts`` maps part name to the rebuilt
    pytree (``delay_table`` restores as a bare array)."""

    family: str
    tick: int
    parts: dict
    host: Optional[dict]
    manifest: dict


def _part_cls(family: str, part: str):
    """The NamedTuple class a part rebuilds into (None = bare array)."""
    if part == "recorder":
        return recorder_mod.RecorderState
    if family == "engine" and part == "state":
        return EngineState
    if family == "receiver_dense" and part == "state":
        return ReceiverState
    if family == "receiver_packed" and part == "packed":
        from rapid_tpu.engine import rx_packed
        return rx_packed.PackedReceiverState
    if family == "receiver_packed" and part == "delay_table":
        return None
    raise CheckpointError(
        f"unknown checkpoint part {part!r} for family {family!r}")


def _leaves(family: str, parts: dict) -> dict:
    """Flatten the parts to ``<part>.<field> -> np.ndarray``."""
    flat = {}
    for part, tree in parts.items():
        cls = _part_cls(family, part)
        if cls is None:
            flat[part] = np.asarray(tree)
            continue
        if not isinstance(tree, cls) and tuple(getattr(
                tree, "_fields", ())) != cls._fields:
            raise CheckpointError(
                f"part {part!r} of family {family!r} must be a "
                f"{cls.__name__} (got {type(tree).__name__})")
        for field in cls._fields:
            flat[f"{part}.{field}"] = np.asarray(getattr(tree, field))
    return flat


def save_checkpoint(path: str, family: str, parts: dict,
                    settings: Settings, *, tick: Optional[int] = None,
                    host: Optional[dict] = None) -> dict:
    """Write one checkpoint directory; returns the manifest dict.

    ``parts`` maps part names (see module docstring) to live pytrees —
    device arrays are pulled to host np copies, so saving never blocks
    on (or donates away) the buffers a resident run keeps using.
    ``tick`` defaults to ``parts["state"].tick`` for the engine family
    and is required otherwise.
    """
    from rapid_tpu.telemetry.schema import SCHEMA_VERSION

    if family not in FAMILIES:
        raise CheckpointError(
            f"unknown checkpoint family {family!r}; expected one of "
            f"{FAMILIES}")
    if tick is None:
        state = parts.get("state")
        if family == "engine" and state is not None:
            tick = int(np.asarray(state.tick))
        else:
            raise CheckpointError(
                f"tick is required when saving family {family!r}")
    flat = _leaves(family, parts)
    os.makedirs(path, exist_ok=True)
    manifest = {
        "record": CHECKPOINT_RECORD,
        "checkpoint_version": CHECKPOINT_VERSION,
        "schema_version": SCHEMA_VERSION,
        "family": family,
        "tick": int(tick),
        "statics": {f: getattr(settings, f) for f in STATIC_FIELDS},
        "leaves": [{"name": name, "dtype": str(arr.dtype),
                    "shape": list(arr.shape)}
                   for name, arr in sorted(flat.items())],
        "host": host,
    }
    np.savez(os.path.join(path, ARRAYS_NAME), **flat)
    write_json_artifact(os.path.join(path, MANIFEST_NAME), manifest,
                        indent=2, sort_keys=True)
    return manifest


def _check_statics(manifest: dict, settings: Settings) -> None:
    saved = manifest.get("statics", {})
    mismatches = {}
    for field in STATIC_FIELDS:
        want = getattr(settings, field)
        got = saved.get(field)
        if got != want:
            mismatches[field] = (got, want)
    if mismatches:
        raise CheckpointCompatError(mismatches)


def load_checkpoint(path: str, settings: Optional[Settings] = None,
                    ) -> Checkpoint:
    """Read one checkpoint directory back into device pytrees.

    With ``settings`` given, the saved layout statics are compared
    field-by-field (``CheckpointCompatError`` on any difference) —
    always pass it when the carry will be fed back into a scan.
    """
    mpath = os.path.join(path, MANIFEST_NAME)
    try:
        with open(mpath) as fh:
            manifest = json.load(fh)
    except FileNotFoundError:
        raise CheckpointError(f"no checkpoint manifest at {mpath}")
    except json.JSONDecodeError as exc:
        raise CheckpointError(f"unparseable checkpoint manifest "
                              f"{mpath}: {exc}")
    if manifest.get("record") != CHECKPOINT_RECORD:
        raise CheckpointError(
            f"{mpath} is not a checkpoint manifest "
            f"(record={manifest.get('record')!r})")
    saved_version = manifest.get("checkpoint_version")
    if saved_version != CHECKPOINT_VERSION:
        raise CheckpointVersionError(saved_version, CHECKPOINT_VERSION)
    family = manifest.get("family")
    if family not in FAMILIES:
        raise CheckpointError(
            f"unknown checkpoint family {family!r}; expected one of "
            f"{FAMILIES}")
    if settings is not None:
        _check_statics(manifest, settings)

    with np.load(os.path.join(path, ARRAYS_NAME),
                 allow_pickle=False) as npz:
        arrays = {name: npz[name] for name in npz.files}
    declared = {leaf["name"]: leaf for leaf in manifest.get("leaves", ())}
    if set(declared) != set(arrays):
        missing = sorted(set(declared) - set(arrays))
        extra = sorted(set(arrays) - set(declared))
        raise CheckpointError(
            f"checkpoint leaf table does not match {ARRAYS_NAME} "
            f"(missing from npz: {missing}, undeclared: {extra})")
    for name, arr in arrays.items():
        leaf = declared[name]
        if (str(arr.dtype) != leaf["dtype"]
                or list(arr.shape) != list(leaf["shape"])):
            raise CheckpointError(
                f"leaf {name!r} drifted from its manifest entry: npz "
                f"{arr.dtype}{list(arr.shape)} vs declared "
                f"{leaf['dtype']}{leaf['shape']}")

    grouped: dict = {}
    for name, arr in arrays.items():
        part, _, field = name.partition(".")
        # copy=True: jnp.asarray on CPU may zero-copy-alias the npz
        # temporaries, which is unsafe under a later donated dispatch.
        if not field:
            grouped[part] = jnp.array(arr, copy=True)
            continue
        grouped.setdefault(part, {})[field] = jnp.array(arr, copy=True)
    parts = {}
    for part, fields in grouped.items():
        cls = _part_cls(family, part)
        if cls is None:
            parts[part] = fields
            continue
        if set(fields) != set(cls._fields):
            missing = sorted(set(cls._fields) - set(fields))
            extra = sorted(set(fields) - set(cls._fields))
            raise CheckpointError(
                f"part {part!r} fields do not match {cls.__name__} "
                f"(missing: {missing}, extra: {extra})")
        parts[part] = cls(**fields)
    return Checkpoint(family=family, tick=int(manifest["tick"]),
                      parts=parts, host=manifest.get("host"),
                      manifest=manifest)


# --- carry-level conveniences (what the resident service calls) ----------

def save_engine(path: str, state: EngineState, settings: Settings, *,
                rec=None, host: Optional[dict] = None) -> dict:
    parts = {"state": state}
    if rec is not None:
        parts["recorder"] = rec
    return save_checkpoint(path, "engine", parts, settings, host=host)


def save_receiver(path: str, carry, settings: Settings, *, tick: int,
                  rec=None, host: Optional[dict] = None) -> dict:
    """Checkpoint a receiver carry in whichever layout it is running:
    a dense ``ReceiverState`` or a packed ``PackedReceiverBundle``."""
    if isinstance(carry, ReceiverState):
        family, parts = "receiver_dense", {"state": carry}
    else:
        family = "receiver_packed"
        parts = {"packed": carry.packed, "delay_table": carry.delay_table}
    if rec is not None:
        parts["recorder"] = rec
    return save_checkpoint(path, family, parts, settings, tick=tick,
                           host=host)


def restore_receiver_carry(cp: Checkpoint, settings: Settings):
    """The scan-ready carry from a receiver checkpoint (dense state, or
    a rebuilt ``PackedReceiverBundle`` for the packed family)."""
    if cp.family == "receiver_dense":
        return cp.parts["state"]
    if cp.family == "receiver_packed":
        from rapid_tpu.engine import rx_packed
        return rx_packed.PackedReceiverBundle(
            packed=cp.parts["packed"],
            delay_table=cp.parts["delay_table"])
    raise CheckpointError(
        f"not a receiver checkpoint (family {cp.family!r})")
