"""Service entry points: ``python -m rapid_tpu.service --soak`` /
``--load-sweep`` / ``--rx-soak``.

``--soak`` runs the resident engine for ``--ticks`` ticks in
``Settings.stream_chunk_ticks``-sized chunks under seeded traffic,
performs one save/restore round-trip at the midpoint
(``ResidentEngine.verify_round_trip`` — restored carry proven bitwise
identical, continuation proven byte-identical), and prints the final
``stream_summary`` record as one JSON line on stdout. Exit status is
nonzero if any identity check failed or the live-buffer watermark grew.
``--target-rate`` attaches the closed-loop load servo (events/sec);
``--status`` / ``--status-socket`` attach the live status API.

``--load-sweep`` runs one fresh servo-driven resident per ``--targets``
entry, classifies each as stable/unstable by the backlog slope over the
measured chunks, locates the knee (largest stable target), and prints
one ``record: "load_sweep"`` line — the form committed as
``benchmarks/load_sweep.json``. Exit status is nonzero unless the sweep
brackets the knee (at least one stable and one unstable target).

``--rx-soak`` is the per-receiver twin of ``--soak``: a resident
receiver member (``service.rx_resident``, two-zone schedule, packed
carry by default) with the same midpoint checkpoint proof and the same
exit gates — the form committed as ``benchmarks/rx_soak.json``.

``--out`` receives the JSONL metrics stream; ``--artifact``
additionally writes a compact JSON document (summary + chunk records,
no tick rows).
"""
from __future__ import annotations

import argparse
import json
import sys
import tempfile

from rapid_tpu.campaign import _rate
from rapid_tpu.service.resident import boot_resident
from rapid_tpu.service.rx_resident import boot_resident_receiver
from rapid_tpu.service.servo import LoadServo, ServoConfig
from rapid_tpu.service.status import StatusPublisher
from rapid_tpu.service.traffic import TrafficConfig
from rapid_tpu.settings import Settings
from rapid_tpu.telemetry import write_json_artifact
from rapid_tpu.telemetry.slo import SloWindows


def _summary_gate(summary: dict, block: dict) -> bool:
    """The soak pass/fail verdict shared by ``--soak`` and
    ``--rx-soak``: every checkpoint identity proven, and the live-buffer
    steady-state watermark within 10% of the first chunk's working set
    (double-buffering keeps two chunks of logs alive; the first drain
    already sees that — ``steady_max`` excludes the verify chunk, which
    transiently holds the live and restored branches side by side)."""
    identity_keys = ("state_identical", "logs_identical", "final_identical")
    ok = all(block[k] for k in identity_keys)
    if block["recorder_identical"] is False \
            or block["continuation_recorder_identical"] is False:
        ok = False
    marks = summary["live_buffer_bytes"]
    if marks["steady_max"] is not None and marks["first"] \
            and marks["steady_max"] > 1.10 * marks["first"]:
        print(f"live-buffer watermark grew: {marks}", file=sys.stderr)
        ok = False
    if not ok:
        print(f"soak FAILED: checkpoint block {block}", file=sys.stderr)
    return ok


def _run_soak(args) -> int:
    settings = Settings(stream_chunk_ticks=args.chunk,
                        flight_recorder_window=args.recorder)
    closed = args.closed_loop or args.target_rate is not None
    traffic = TrafficConfig(
        seed=args.seed,
        join_rate_per_ktick=args.rate,
        leave_burst_rate_per_ktick=args.leave_rate,
        leave_burst_size=args.leave_burst,
        diurnal_amplitude=args.diurnal,
        diurnal_period_ticks=args.diurnal_period,
        closed_loop=closed)
    servo = None
    if args.target_rate is not None:
        servo = LoadServo(ServoConfig(
            target_events_per_sec=args.target_rate,
            pinned_ticks_per_sec=args.pinned_tps))
    slo = (SloWindows(window_chunks=args.slo_window)
           if args.slo_window else None)
    status = None
    if args.status or args.status_socket:
        status = StatusPublisher(file_path=args.status,
                                 socket_path=args.status_socket)
    n_chunks = max(2, -(-args.ticks // args.chunk))
    ckdir = args.checkpoint_dir or tempfile.mkdtemp(prefix="rapid_soak_ck_")

    eng = boot_resident(settings, args.capacity, args.n, seed=args.seed,
                        traffic_config=traffic, servo=servo, slo=slo,
                        status=status, sink=args.out,
                        write_ticks=not args.no_tick_rows)
    # First half, one save/restore round-trip (itself one chunk), the
    # remainder.
    first = n_chunks // 2
    eng.run(first)
    block = eng.verify_round_trip(ckdir)
    eng.run(n_chunks - first - 1)
    summary = eng.summary()
    eng.close()

    if args.artifact:
        write_json_artifact(args.artifact,
                            {"record": "soak_artifact",
                             "schema_version": summary["schema_version"],
                             "summary": summary,
                             "chunks": eng.chunk_records},
                            indent=2, sort_keys=True)

    print(json.dumps(summary, sort_keys=True))
    return 0 if _summary_gate(summary, block) else 1


def _run_load_sweep(args) -> int:
    from rapid_tpu.telemetry.schema import SCHEMA_VERSION

    import time as time_mod

    settings = Settings(stream_chunk_ticks=args.chunk,
                        flight_recorder_window=0)
    targets = [float(t) for t in args.targets.split(",") if t.strip()]
    if len(targets) < 2:
        print("load-sweep needs at least two --targets", file=sys.stderr)
        return 2
    t_wall0 = time_mod.perf_counter()
    rates = []
    for target in targets:
        # Each target gets a fresh resident + servo from the same seed:
        # every executable shape repeats, so only the first target pays
        # the compile (its chunk 0 reports compile_s and excludes it
        # from the measured wall).
        traffic = TrafficConfig(
            seed=args.seed,
            join_rate_per_ktick=0.0,
            leave_burst_rate_per_ktick=args.leave_rate,
            leave_burst_size=args.leave_burst,
            closed_loop=True)
        servo = LoadServo(ServoConfig(
            target_events_per_sec=target,
            pinned_ticks_per_sec=args.pinned_tps))
        slo = SloWindows(window_chunks=args.slo_window)
        eng = boot_resident(settings, args.capacity, args.n,
                            seed=args.seed, traffic_config=traffic,
                            servo=servo, slo=slo, write_ticks=False)
        eng.run(args.warmup + args.chunks_per_rate)
        eng.flush()
        recs = eng.chunk_records[args.warmup:]
        wall = sum(r["wall_s"] for r in recs)
        ticks = sum(r["ticks"] for r in recs)
        events = sum(r["traffic"]["events"] for r in recs)
        backlogs = [r["servo"]["backlog"] for r in recs]
        # The saturation verdict: mean per-chunk backlog growth over the
        # measured window. Below the knee the offered-minus-applied
        # backlog is bounded (slope ~0); past it the backlog grows
        # monotonically chunk over chunk.
        slope = ((backlogs[-1] - backlogs[0])
                 / max(1, len(backlogs) - 1))
        stable = slope <= args.slope_threshold
        rates.append({
            "target_events_per_sec": target,
            "achieved_events_per_sec": _rate(events, wall),
            "rate_per_ktick": eng.servo.rate_per_ktick,
            "ticks_per_sec": _rate(ticks, wall),
            "chunks": len(recs),
            "events": events,
            "backlog_final": backlogs[-1],
            "backlog_slope_per_chunk": slope,
            "stable": bool(stable),
            "servo_config": servo.config.as_dict(),
            "slo": recs[-1]["slo"],
        })
        eng.close()

    knee = None
    stable_rates = [r for r in rates if r["stable"]]
    if stable_rates:
        best = max(stable_rates, key=lambda r: r["target_events_per_sec"])
        knee = {
            "target_events_per_sec": best["target_events_per_sec"],
            "achieved_events_per_sec": best["achieved_events_per_sec"],
            "ticks_to_view_change_p99":
                best["slo"]["metrics"]["ticks_to_view_change"]["p99"],
        }
    payload = {
        "record": "load_sweep",
        "schema_version": SCHEMA_VERSION,
        "n": args.n,
        "capacity": args.capacity,
        "chunk_ticks": args.chunk,
        "chunks_per_rate": args.chunks_per_rate,
        "warmup_chunks": args.warmup,
        "seed": args.seed,
        "backlog_slope_threshold": args.slope_threshold,
        "targets": targets,
        "rates": rates,
        "knee": knee,
        "wall_s": time_mod.perf_counter() - t_wall0,
    }
    if args.artifact:
        write_json_artifact(args.artifact, payload, indent=2,
                            sort_keys=True)
    print(json.dumps(payload, sort_keys=True))
    n_stable = len(stable_rates)
    n_unstable = len(rates) - n_stable
    if n_stable == 0 or n_unstable == 0:
        print(f"load sweep did not bracket the knee: {n_stable} stable, "
              f"{n_unstable} unstable target(s) — widen --targets",
              file=sys.stderr)
        return 1
    return 0


def _run_rx_soak(args) -> int:
    settings = Settings(rx_kernel=args.kernel,
                        flight_recorder_window=args.recorder)
    n_chunks = max(2, -(-args.ticks // args.chunk))
    ckdir = args.checkpoint_dir or tempfile.mkdtemp(prefix="rapid_rx_ck_")
    slo = (SloWindows(window_chunks=args.slo_window)
           if args.slo_window else None)
    rx = boot_resident_receiver(
        settings, args.n, seed=args.seed,
        horizon_ticks=args.horizon or n_chunks * args.chunk,
        chunk_ticks=args.chunk, slo=slo, sink=args.out)
    first = n_chunks // 2
    rx.run(first)
    block = rx.verify_round_trip(ckdir)
    rx.run(n_chunks - first - 1)
    summary = rx.summary()
    rx.close()

    if args.artifact:
        write_json_artifact(args.artifact,
                            {"record": "rx_soak_artifact",
                             "schema_version": summary["schema_version"],
                             "summary": summary,
                             "chunks": rx.chunk_records},
                            indent=2, sort_keys=True)

    print(json.dumps(summary, sort_keys=True))
    return 0 if _summary_gate(summary, block) else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m rapid_tpu.service")
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument("--soak", action="store_true",
                      help="run the chunked resident-engine soak")
    mode.add_argument("--load-sweep", action="store_true",
                      help="servo-driven saturation sweep over --targets")
    mode.add_argument("--rx-soak", action="store_true",
                      help="run the receiver-resident soak")
    ap.add_argument("--n", type=int, default=24,
                    help="initial converged members (--rx-soak: the "
                         "receiver capacity C)")
    ap.add_argument("--capacity", type=int, default=96,
                    help="slot universe (members + joiner pool)")
    ap.add_argument("--ticks", type=int, default=102400,
                    help="total ticks (rounded up to whole chunks)")
    ap.add_argument("--chunk", type=int, default=512,
                    help="chunk size in ticks (Settings."
                         "stream_chunk_ticks for the engine modes)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--rate", type=float, default=20.0,
                    help="Poisson join arrivals per 1000 ticks")
    ap.add_argument("--leave-rate", type=float, default=2.0,
                    help="correlated leave bursts per 1000 ticks")
    ap.add_argument("--leave-burst", type=int, default=4)
    ap.add_argument("--diurnal", type=float, default=0.3,
                    help="diurnal join-rate amplitude in [0, 1]")
    ap.add_argument("--diurnal-period", type=int, default=4096)
    ap.add_argument("--recorder", type=int, default=8,
                    help="flight_recorder_window (0 disables)")
    ap.add_argument("--closed-loop", action="store_true",
                    help="closed-loop traffic sampling (implied by "
                         "--target-rate)")
    ap.add_argument("--target-rate", type=float, default=None,
                    help="attach the load servo steering toward this "
                         "many events/sec")
    ap.add_argument("--pinned-tps", type=float, default=None,
                    help="pin the servo throughput model (deterministic "
                         "replays)")
    ap.add_argument("--slo-window", type=int, default=8,
                    help="rolling SLO window in chunks (0 disables)")
    ap.add_argument("--status", default=None,
                    help="atomically-replaced live status JSON file")
    ap.add_argument("--status-socket", default=None,
                    help="unix-domain status/watch line-protocol socket")
    ap.add_argument("--targets", default="50,200,800,1600,3200",
                    help="comma list of events/sec targets (--load-sweep)")
    ap.add_argument("--chunks-per-rate", type=int, default=12,
                    help="measured chunks per target (--load-sweep)")
    ap.add_argument("--warmup", type=int, default=3,
                    help="unmeasured warmup chunks per target "
                         "(--load-sweep)")
    ap.add_argument("--slope-threshold", type=float, default=5.0,
                    help="max stable backlog growth per chunk "
                         "(--load-sweep)")
    ap.add_argument("--kernel", default="packed",
                    choices=("xla", "packed", "pallas"),
                    help="receiver kernel (--rx-soak)")
    ap.add_argument("--horizon", type=int, default=None,
                    help="fault-schedule horizon in ticks (--rx-soak; "
                         "default: the whole run)")
    ap.add_argument("--out", default=None,
                    help="JSONL metrics sink (default: no stream file)")
    ap.add_argument("--no-tick-rows", action="store_true",
                    help="sink gets heartbeats + summary only")
    ap.add_argument("--artifact", default=None,
                    help="compact JSON artifact (summary + chunk "
                         "records, or the load_sweep payload)")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="where the mid-soak checkpoint lands "
                         "(default: a temp dir)")
    args = ap.parse_args(argv)
    if args.load_sweep:
        return _run_load_sweep(args)
    if args.rx_soak:
        return _run_rx_soak(args)
    if not args.soak:
        ap.error("nothing to do: pass --soak, --load-sweep, or --rx-soak")
    return _run_soak(args)


if __name__ == "__main__":
    sys.exit(main())
