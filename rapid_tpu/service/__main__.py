"""Soak entry point: ``python -m rapid_tpu.service --soak``.

Runs the resident engine for ``--ticks`` ticks in
``Settings.stream_chunk_ticks``-sized chunks under open-loop traffic,
performs one save/restore round-trip at the midpoint
(``ResidentEngine.verify_round_trip`` — restored carry proven bitwise
identical, continuation proven byte-identical), and prints the final
``stream_summary`` record as one JSON line on stdout. Exit status is
nonzero if any identity check failed or the live-buffer watermark grew.

``--out`` receives the JSONL metrics stream (tick rows + chunk
heartbeats + the summary); ``--artifact`` additionally writes a compact
JSON document (summary + chunk records, no tick rows) — the form
committed as ``benchmarks/soak.json``.
"""
from __future__ import annotations

import argparse
import json
import sys
import tempfile

from rapid_tpu.service.resident import boot_resident
from rapid_tpu.service.traffic import TrafficConfig
from rapid_tpu.settings import Settings
from rapid_tpu.telemetry import write_json_artifact


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m rapid_tpu.service")
    ap.add_argument("--soak", action="store_true",
                    help="run the chunked soak (the only mode today)")
    ap.add_argument("--n", type=int, default=24,
                    help="initial converged members")
    ap.add_argument("--capacity", type=int, default=96,
                    help="slot universe (members + joiner pool)")
    ap.add_argument("--ticks", type=int, default=102400,
                    help="total ticks (rounded up to whole chunks)")
    ap.add_argument("--chunk", type=int, default=512,
                    help="Settings.stream_chunk_ticks")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--rate", type=float, default=20.0,
                    help="Poisson join arrivals per 1000 ticks")
    ap.add_argument("--leave-rate", type=float, default=2.0,
                    help="correlated leave bursts per 1000 ticks")
    ap.add_argument("--leave-burst", type=int, default=4)
    ap.add_argument("--diurnal", type=float, default=0.3,
                    help="diurnal join-rate amplitude in [0, 1]")
    ap.add_argument("--diurnal-period", type=int, default=4096)
    ap.add_argument("--recorder", type=int, default=8,
                    help="flight_recorder_window (0 disables)")
    ap.add_argument("--out", default=None,
                    help="JSONL metrics sink (default: no stream file)")
    ap.add_argument("--no-tick-rows", action="store_true",
                    help="sink gets heartbeats + summary only")
    ap.add_argument("--artifact", default=None,
                    help="compact soak JSON (summary + chunk records)")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="where the mid-soak checkpoint lands "
                         "(default: a temp dir)")
    args = ap.parse_args(argv)
    if not args.soak:
        ap.error("nothing to do: pass --soak")

    settings = Settings(stream_chunk_ticks=args.chunk,
                        flight_recorder_window=args.recorder)
    traffic = TrafficConfig(
        seed=args.seed,
        join_rate_per_ktick=args.rate,
        leave_burst_rate_per_ktick=args.leave_rate,
        leave_burst_size=args.leave_burst,
        diurnal_amplitude=args.diurnal,
        diurnal_period_ticks=args.diurnal_period)
    n_chunks = max(2, -(-args.ticks // args.chunk))
    ckdir = args.checkpoint_dir or tempfile.mkdtemp(prefix="rapid_soak_ck_")

    eng = boot_resident(settings, args.capacity, args.n, seed=args.seed,
                        traffic_config=traffic, sink=args.out,
                        write_ticks=not args.no_tick_rows)
    # First half, one save/restore round-trip (itself one chunk), the
    # remainder.
    first = n_chunks // 2
    eng.run(first)
    block = eng.verify_round_trip(ckdir)
    eng.run(n_chunks - first - 1)
    summary = eng.summary()
    eng.close()

    if args.artifact:
        write_json_artifact(args.artifact,
                            {"record": "soak_artifact",
                             "schema_version": summary["schema_version"],
                             "summary": summary,
                             "chunks": eng.chunk_records},
                            indent=2, sort_keys=True)

    print(json.dumps(summary, sort_keys=True))
    identity_keys = ("state_identical", "logs_identical", "final_identical")
    ok = all(block[k] for k in identity_keys)
    if block["recorder_identical"] is False \
            or block["continuation_recorder_identical"] is False:
        ok = False
    marks = summary["live_buffer_bytes"]
    # Flat-watermark gate: steady state may not grow past the first
    # chunk's working set by more than 10% (double-buffering keeps two
    # chunks of logs alive; the first drain already sees that).
    # ``steady_max`` excludes the verify chunk, which transiently holds
    # the live and restored branches side by side.
    if marks["steady_max"] is not None and marks["first"] \
            and marks["steady_max"] > 1.10 * marks["first"]:
        print(f"live-buffer watermark grew: {marks}", file=sys.stderr)
        ok = False
    if not ok:
        print(f"soak FAILED: checkpoint block {block}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
