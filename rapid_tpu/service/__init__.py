"""Streaming service mode: the engine as a long-lived membership process.

Six pieces (see ``ROADMAP.md`` "Streaming service mode" and "Streaming
observatory"):

- ``resident`` — the chunked, donated, double-buffered driver around
  ``engine.step.simulate_chunk``, streaming ``TickMetrics`` JSONL;
- ``rx_resident`` — the per-receiver twin around
  ``engine.receiver.receiver_simulate_chunk`` (layout-preserving: dense
  or packed carry), with the same heartbeats and checkpoint proof;
- ``checkpoint`` — versioned save/restore of every scan carry family
  (engine, dense receiver, packed receiver, recorder ring), proven
  bit-identical across the save/load boundary;
- ``traffic`` — the seeded arrival processes (Poisson joins, correlated
  leave bursts, diurnal waves) lowered chunk-by-chunk onto
  ``ChurnSchedule``; ``closed_loop=True`` samples joins by CDF
  inversion from one uniform per tick, so rate changes never shift the
  seeded stream;
- ``servo`` — the deterministic target-rate load servo (events/sec ->
  quantized events/ktick from committed heartbeat walls);
- ``status`` — the read-only live status API (atomic status file +
  unix-socket line protocol with ``watch`` subscriptions).

``python -m rapid_tpu.service --soak`` runs the long-haul gate;
``--load-sweep`` drives the saturation sweep that locates the knee;
``--rx-soak`` runs the packed receiver-resident soak.
"""
from rapid_tpu.service.checkpoint import (
    CHECKPOINT_VERSION,
    Checkpoint,
    CheckpointCompatError,
    CheckpointError,
    CheckpointVersionError,
    load_checkpoint,
    restore_receiver_carry,
    save_checkpoint,
    save_engine,
    save_receiver,
)
from rapid_tpu.service.resident import ResidentEngine, boot_resident
from rapid_tpu.service.rx_resident import (ResidentReceiver,
                                           boot_resident_receiver)
from rapid_tpu.service.servo import LoadServo, ServoConfig
from rapid_tpu.service.status import (StatusFile, StatusPublisher,
                                      StatusSocket, read_status)
from rapid_tpu.service.traffic import TrafficConfig, TrafficGenerator

__all__ = [
    "CHECKPOINT_VERSION",
    "Checkpoint",
    "CheckpointCompatError",
    "CheckpointError",
    "CheckpointVersionError",
    "LoadServo",
    "ResidentEngine",
    "ResidentReceiver",
    "ServoConfig",
    "StatusFile",
    "StatusPublisher",
    "StatusSocket",
    "TrafficConfig",
    "TrafficGenerator",
    "boot_resident",
    "boot_resident_receiver",
    "load_checkpoint",
    "read_status",
    "restore_receiver_carry",
    "save_checkpoint",
    "save_engine",
    "save_receiver",
]
