"""Streaming service mode: the engine as a long-lived membership process.

Three pieces (see ``ROADMAP.md`` "Streaming service mode"):

- ``resident`` — the chunked, donated, double-buffered driver around
  ``engine.step.simulate_chunk``, streaming ``TickMetrics`` JSONL;
- ``checkpoint`` — versioned save/restore of every scan carry family
  (engine, dense receiver, packed receiver, recorder ring), proven
  bit-identical across the save/load boundary;
- ``traffic`` — the seeded open-loop arrival processes (Poisson joins,
  correlated leave bursts, diurnal waves) lowered chunk-by-chunk onto
  ``ChurnSchedule``.

``python -m rapid_tpu.service --soak`` runs the long-haul gate: >=100k
ticks in chunks at constant memory with one mid-soak save/restore
round-trip proven bit-identical.
"""
from rapid_tpu.service.checkpoint import (
    CHECKPOINT_VERSION,
    Checkpoint,
    CheckpointCompatError,
    CheckpointError,
    CheckpointVersionError,
    load_checkpoint,
    restore_receiver_carry,
    save_checkpoint,
    save_engine,
    save_receiver,
)
from rapid_tpu.service.resident import ResidentEngine, boot_resident
from rapid_tpu.service.traffic import TrafficConfig, TrafficGenerator

__all__ = [
    "CHECKPOINT_VERSION",
    "Checkpoint",
    "CheckpointCompatError",
    "CheckpointError",
    "CheckpointVersionError",
    "ResidentEngine",
    "TrafficConfig",
    "TrafficGenerator",
    "boot_resident",
    "load_checkpoint",
    "restore_receiver_carry",
    "save_checkpoint",
    "save_engine",
    "save_receiver",
]
