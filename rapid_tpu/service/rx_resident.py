"""Receiver-resident driver: the per-receiver scan as a chunked service.

``ResidentEngine`` made the shared-state engine a long-lived process;
the per-receiver wire (``engine.receiver`` / ``engine.rx_packed``) had
the chunk entry point (``receiver_simulate_chunk``) but nothing drove
it as a service. This driver is the receiver twin, sharing the
resident conventions file for file:

- the stream runs as fixed-size chunks over the layout-preserving
  carry — a dense ``ReceiverState`` under ``rx_kernel="xla"``, a
  ``rx_packed.PackedReceiverBundle`` under the packed layouts (the
  first dispatch converts via ``as_bundle``; every later chunk re-feeds
  the bundle verbatim), so a C>=1024 soak holds exactly one packed
  working set on device;
- dispatch is double-buffered and carries are donated, identical to
  ``ResidentEngine``; the chunk heartbeats are the same
  ``record: "chunk"`` shape (``telemetry.schema.STREAM_CHUNK_SPEC``,
  with ``traffic``/``servo`` null — there is no churn generator on the
  receiver wire) and carry the same rolling ``slo`` block, folded
  per-slot by ``telemetry.slo.ReceiverViewChangeFold`` (each live slot
  runs its own protocol instance);
- :meth:`verify_round_trip` checkpoints mid-soak through
  ``service.checkpoint``'s ``receiver_dense``/``receiver_packed``
  families (``save_receiver`` / ``restore_receiver_carry``) and proves
  the restore exact the same two ways: bitwise-equal restored pytrees,
  and byte-identical continuation logs/final/recorder from the live
  and restored branches — then adopts the restored branch as the
  continuing carry, so the committed soak artifact is itself evidence
  that a packed save/restore loses nothing.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Optional

import numpy as np

import jax

from rapid_tpu.engine.receiver import receiver_simulate_chunk
from rapid_tpu.faults import two_zone_schedule
from rapid_tpu.service import checkpoint as checkpoint_mod
from rapid_tpu.service.resident import (_dealias, _live_buffer_bytes,
                                        _rate, _tree_equal)
from rapid_tpu.service.status import StatusPublisher
from rapid_tpu.settings import Settings
from rapid_tpu.telemetry import json_artifact_line
from rapid_tpu.telemetry.lineage import (LineageFold, lineage_summary,
                                         receiver_phase_columns)
from rapid_tpu.telemetry.metrics import _dist
from rapid_tpu.telemetry.slo import ReceiverViewChangeFold, SloWindows


class ResidentReceiver:
    """One resident per-receiver member plus its I/O loop.

    ``chunk_ticks`` is the receiver analogue of
    ``Settings.stream_chunk_ticks`` (a static of the chunk executable):
    per-receiver ticks at large C cost orders of magnitude more wall
    than shared-state ticks, so the chunk size is a driver parameter
    rather than a layout setting.
    """

    def __init__(self, carry, faults, settings: Settings, *,
                 capacity: int, chunk_ticks: int,
                 slo: Optional[SloWindows] = None,
                 status: Optional[StatusPublisher] = None,
                 sink: Optional[str] = None, donate: bool = True):
        if chunk_ticks < 1:
            raise ValueError(f"chunk_ticks must be >= 1, got {chunk_ticks}")
        self.settings = settings
        self.capacity = int(capacity)
        self.chunk_ticks = int(chunk_ticks)
        self._carry = _dealias(carry)
        self._faults = faults
        self._rec = None
        self.slo = slo
        self._vc_fold = (ReceiverViewChangeFold(self.capacity)
                         if slo is not None else None)
        self._lineage = LineageFold(0)
        self.lineage_spans: list = []
        self._lineage_window: deque = deque(
            maxlen=slo.window_chunks if slo is not None else 8)
        self.status = status
        self._donate = donate
        self._sink = open(sink, "w") if sink else None
        self._pending = None
        self.chunk_records: list = []
        self.chunks = 0
        self.ticks = 0
        self.announces = 0
        self.decides = 0
        self._ttvc: list = []
        self.checkpoint_block: Optional[dict] = None
        self.compile_s: Optional[float] = None
        self._dispatches = 0
        self._wall0 = time.perf_counter()
        self._last_drain_wall = self._wall0
        self._watermarks: list = []

    @property
    def carry(self):
        """The current carry (chunk-boundary accurate after ``flush``)."""
        return self._carry

    # --- internals --------------------------------------------------------

    def _emit(self, record: dict) -> None:
        if self._sink is not None:
            self._sink.write(json_artifact_line(record, sort_keys=True))
            self._sink.flush()

    def _dispatch(self) -> dict:
        t0 = time.perf_counter()
        out = receiver_simulate_chunk(
            self._carry, self._faults, self.chunk_ticks, self.settings,
            rec=self._rec, donate=self._donate)
        dispatch_wall = time.perf_counter() - t0
        # Same chunk-0 convention as ResidentEngine._dispatch: the first
        # dispatch blocks on trace + compile, so its wall is the compile
        # cost the heartbeat splits out of the rates.
        compile_s = dispatch_wall if self._dispatches == 0 else None
        self._dispatches += 1
        if compile_s is not None:
            self.compile_s = compile_s
        if self.settings.flight_recorder_window:
            self._carry, logs, self._rec = out
        else:
            self._carry, logs = out
        pending = {"index": self.chunks, "logs": logs,
                   "checkpoint": None, "compile_s": compile_s}
        self.chunks += 1
        self.ticks += self.chunk_ticks
        return pending

    def _drain(self, pending: dict) -> None:
        logs = pending["logs"]
        jax.block_until_ready(logs)
        ticks_col = np.asarray(logs.tick)
        announce_tc = np.asarray(logs.announce, bool)
        decide_tc = np.asarray(logs.decide, bool)
        announces = int(announce_tc.sum())
        decides = int(decide_tc.sum())
        self.announces += announces
        self.decides += decides
        now = time.perf_counter()
        wall = now - self._last_drain_wall
        self._last_drain_wall = now
        compile_s = pending.get("compile_s")
        if compile_s is not None:
            compile_s = min(compile_s, wall)
            wall = wall - compile_s
        live = _live_buffer_bytes()
        self._watermarks.append(live)
        slo_block = None
        if self.slo is not None:
            samples = self._vc_fold.fold(ticks_col, announce_tc, decide_tc)
            self._ttvc.extend(samples["ticks_to_view_change"])
            slo_block = self.slo.fold_chunk(samples)
        new_spans = self._lineage.fold_columns(receiver_phase_columns(logs))
        self.lineage_spans.extend(new_spans)
        self._lineage_window.append(new_spans)
        lineage_block = lineage_summary(
            [sp for chunk in self._lineage_window for sp in chunk])
        record = {
            "record": "chunk",
            "index": pending["index"],
            "tick": (int(ticks_col[-1]) if ticks_col.size else self.ticks),
            "ticks": self.chunk_ticks,
            "wall_s": wall,
            "compile_s": compile_s,
            "ticks_per_sec": _rate(self.chunk_ticks, wall),
            "events_per_sec": None,
            "announces": announces,
            "decides": decides,
            "live_buffer_bytes": live,
            "traffic": None,
            "servo": None,
            "slo": slo_block,
            "lineage": lineage_block,
            "checkpoint": pending["checkpoint"],
        }
        self.chunk_records.append(record)
        self._emit(record)
        if self.status is not None:
            # One frame per chunk, unconditionally — watch cadence must
            # match chunk cadence even when a chunk closes zero view
            # changes (the heartbeat itself is the signal).
            self.status.publish(self._status_snapshot(record))

    def _status_snapshot(self, record: dict) -> dict:
        """Chunk-boundary ``status_snapshot`` (receiver flavour): built
        purely from drained host data, never perturbing the stream."""
        from rapid_tpu.telemetry.schema import SCHEMA_VERSION

        return {
            "record": "status_snapshot",
            "schema_version": SCHEMA_VERSION,
            "source": "resident_receiver",
            "tick": record["tick"],
            "chunks": self.chunks,
            "epoch": -1,
            "n_members": self.capacity,
            "ticks_per_sec": record["ticks_per_sec"],
            "events_per_sec": None,
            "backlog": None,
            "live_buffer_bytes": record["live_buffer_bytes"],
            "servo": None,
            "slo": record["slo"],
            "lineage": record["lineage"],
            "checkpoint": self.checkpoint_block,
            "wall_s": time.perf_counter() - self._wall0,
        }

    # --- public loop ------------------------------------------------------

    def flush(self) -> None:
        """Drain the in-flight chunk, if any."""
        if self._pending is not None:
            pending, self._pending = self._pending, None
            self._drain(pending)

    def run(self, n_chunks: int) -> None:
        """Run ``n_chunks`` chunks, double-buffered."""
        for _ in range(int(n_chunks)):
            dispatched = self._dispatch()
            self.flush()
            self._pending = dispatched
        self.flush()

    # --- checkpoint/restore ----------------------------------------------

    def _host_blob(self) -> dict:
        blob = {"chunks": self.chunks, "ticks": self.ticks,
                "capacity": self.capacity,
                "chunk_ticks": self.chunk_ticks,
                "announces": self.announces, "decides": self.decides}
        if self.slo is not None:
            blob["slo"] = self.slo.state_dict()
            blob["vc_fold"] = self._vc_fold.state_dict()
        blob["lineage"] = {"fold": self._lineage.state_dict(),
                           "spans": self.lineage_spans,
                           "window": [list(c) for c in self._lineage_window]}
        return blob

    def save(self, path: str) -> dict:
        """Checkpoint the receiver carry in whichever layout it runs
        (``receiver_dense`` or ``receiver_packed`` family) — drains the
        in-flight chunk first so the saved carry is a chunk boundary."""
        self.flush()
        return checkpoint_mod.save_receiver(
            path, self._carry, self.settings, tick=self.ticks,
            rec=self._rec, host=self._host_blob())

    @classmethod
    def restore(cls, path: str, faults, settings: Settings,
                **kw) -> "ResidentReceiver":
        cp = checkpoint_mod.load_checkpoint(path, settings)
        carry = checkpoint_mod.restore_receiver_carry(cp, settings)
        host = cp.host or {}
        slo = kw.pop("slo", None)
        if slo is None and "slo" in host:
            slo = SloWindows.from_state(host["slo"])
        rx = cls(carry, faults, settings,
                 capacity=int(host["capacity"]),
                 chunk_ticks=kw.pop("chunk_ticks",
                                    int(host["chunk_ticks"])),
                 slo=slo, **kw)
        if rx.slo is not None and "vc_fold" in host:
            rx._vc_fold = ReceiverViewChangeFold.from_state(host["vc_fold"])
        if "lineage" in host:
            lin = host["lineage"]
            rx._lineage = LineageFold.from_state(lin["fold"])
            rx.lineage_spans = list(lin["spans"])
            for chunk in lin["window"]:
                rx._lineage_window.append(list(chunk))
        rec = cp.parts.get("recorder")
        rx._rec = _dealias(rec) if rec is not None else None
        rx.chunks = int(host.get("chunks", 0))
        rx.ticks = int(host.get("ticks", cp.tick))
        rx.announces = int(host.get("announces", 0))
        rx.decides = int(host.get("decides", 0))
        return rx

    def verify_round_trip(self, path: str) -> dict:
        """Save, restore, and prove the restore exact (the receiver twin
        of ``ResidentEngine.verify_round_trip``); returns the
        ``checkpoint`` block the summary embeds, and adopts the restored
        branch as the continuing carry."""
        self.flush()
        self.save(path)
        cp = checkpoint_mod.load_checkpoint(path, self.settings)
        restored = checkpoint_mod.restore_receiver_carry(cp, self.settings)
        r_rec = cp.parts.get("recorder")
        state_identical = _tree_equal(self._carry, restored)
        recorder_identical = (_tree_equal(self._rec, r_rec)
                              if self._rec is not None else None)

        n = self.chunk_ticks
        live = receiver_simulate_chunk(self._carry, self._faults, n,
                                       self.settings, rec=self._rec,
                                       donate=False)
        rest = receiver_simulate_chunk(restored, self._faults, n,
                                       self.settings, rec=r_rec,
                                       donate=False)
        if self.settings.flight_recorder_window:
            l_final, l_logs, l_rec = live
            r_final, r_logs, r_rec2 = rest
            cont_rec_ok = _tree_equal(l_rec, r_rec2)
        else:
            l_final, l_logs = live
            r_final, r_logs = rest
            r_rec2 = None
            cont_rec_ok = None
        block = {
            "version": checkpoint_mod.CHECKPOINT_VERSION,
            "tick": cp.tick,
            "state_identical": bool(state_identical),
            "recorder_identical": recorder_identical,
            "logs_identical": bool(_tree_equal(l_logs, r_logs)),
            "final_identical": bool(_tree_equal(l_final, r_final)),
            "continuation_recorder_identical": cont_rec_ok,
        }
        self._carry = _dealias(r_final)
        self._rec = _dealias(r_rec2) if r_rec2 is not None else None
        pending = {"index": self.chunks, "logs": r_logs,
                   "checkpoint": block, "compile_s": None}
        self.chunks += 1
        self.ticks += n
        self._drain(pending)
        self.checkpoint_block = block
        return block

    # --- summary ----------------------------------------------------------

    def summary(self) -> dict:
        """The final ``record: "stream_summary"`` line
        (``source: "resident_receiver"``, traffic/servo null)."""
        from rapid_tpu.telemetry.schema import SCHEMA_VERSION

        self.flush()
        wall = time.perf_counter() - self._wall0
        marks = self._watermarks
        record = {
            "record": "stream_summary",
            "schema_version": SCHEMA_VERSION,
            "source": "resident_receiver",
            "n": self.capacity,
            "capacity": self.capacity,
            "ticks": self.ticks,
            "chunks": self.chunks,
            "chunk_ticks": self.chunk_ticks,
            "events_injected": 0,
            "joins": 0,
            "leaves": 0,
            "bursts": 0,
            "announcements": self.announces,
            "decisions": self.decides,
            "wall_s": wall,
            "compile_s": self.compile_s,
            "ticks_per_sec": _rate(self.ticks, wall),
            "events_per_sec": None,
            "ticks_to_view_change": _dist(self._ttvc),
            "lineage": lineage_summary(self.lineage_spans),
            "servo": None,
            "slo": self.slo.block() if self.slo is not None else None,
            "live_buffer_bytes": {
                "first": marks[0] if marks else None,
                "max": max(marks) if marks else None,
                "steady_max": max(
                    (r["live_buffer_bytes"] for r in self.chunk_records
                     if not r["checkpoint"]), default=None),
                "last": marks[-1] if marks else None,
            },
            "traffic": None,
            "checkpoint": self.checkpoint_block,
        }
        self._emit(record)
        return record

    def close(self) -> None:
        if self._sink is not None:
            self._sink.close()
            self._sink = None
        if self.status is not None:
            self.status.close()
            self.status = None


def boot_resident_receiver(settings: Settings, n: int, *, seed: int = 0,
                           horizon_ticks: int, chunk_ticks: int,
                           slo: Optional[SloWindows] = None,
                           status: Optional[StatusPublisher] = None,
                           sink: Optional[str] = None,
                           donate: bool = True) -> ResidentReceiver:
    """Boot the named two-zone deployment as a resident receiver member:
    ``faults.two_zone_schedule`` lowered through
    ``fleet.lower_receiver_schedule``, carry handed to the driver in
    whatever layout ``settings.rx_kernel`` selects. ``horizon_ticks``
    bounds the fault schedule, not the run — chunks past the horizon
    tick on with the faults gone inert."""
    from rapid_tpu.engine.fleet import lower_receiver_schedule

    sched = two_zone_schedule(n, seed, int(horizon_ticks),
                              ring_depth=settings.delivery_ring_depth)
    member = lower_receiver_schedule(sched, settings)
    # member.state is already in the layout rx_kernel selects: a dense
    # ReceiverState under "xla", a PackedReceiverBundle otherwise.
    return ResidentReceiver(member.state, member.faults, settings,
                            capacity=n, chunk_ticks=chunk_ticks, slo=slo,
                            status=status, sink=sink, donate=donate)
