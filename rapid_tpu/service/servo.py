"""Target-rate load servo: drive the traffic generator in events/sec.

The open-loop generator (``service.traffic``) is parameterized in
events per *kilotick of virtual time*; what the ROADMAP gate asks for
is a requested rate in events per *wall second*. The two are linked by
the measured chunk throughput: at ``tps`` ticks/sec, hitting
``target`` events/sec needs ``1000 * target / tps`` events per
kilotick. This module closes that loop deterministically:

- the control law runs only on **committed** observations — each chunk
  heartbeat's compile-excluded wall — and both the throughput estimate
  and the output rate are **quantized** to fixed grids
  (``tps_quantum``, ``rate_quantum_per_ktick``), so the applied-rate
  trace recorded in the heartbeats is exactly reproducible: replaying
  it (or pinning the throughput model) regenerates a byte-identical
  event schedule;
- rng-stream advancement is rate-independent: closed-loop generators
  draw exactly one uniform per tick for joins
  (``TrafficConfig.closed_loop`` — Poisson by CDF inversion), so a
  rate adjustment never shifts the seeded stream and the achieved
  trace still replays exactly through the host oracle referee;
- **backlog is the saturation observable**: the servo never chases the
  generator's offered-minus-applied backlog, it only reports it. Below
  the knee the backlog stays bounded; past the knee the requested
  per-ktick rate exceeds what burst admission can lower and the
  backlog grows without bound — which is precisely what the load sweep
  classifies as unstable;
- ``pinned_ticks_per_sec`` freezes the throughput model, making the
  whole closed loop a pure function of the seed and the target — the
  chunk-split-invariance and forced-saturation tests run in this mode,
  and so does any cross-machine replay of a committed sweep.

Walls below ``campaign.MIN_MEASURABLE_WALL_S`` are skipped (the same
null-rate convention every heartbeat uses): a sub-millisecond chunk
wall is timer noise, not a throughput observation.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from rapid_tpu.campaign import MIN_MEASURABLE_WALL_S


@dataclasses.dataclass(frozen=True)
class ServoConfig:
    """One closed-loop rate target plus the control-law constants
    (``telemetry.schema.SERVO_CONFIG_SPEC``)."""

    #: Requested wall-clock event rate the servo steers toward.
    target_events_per_sec: float
    #: Throughput prior used until the first committed observation.
    initial_ticks_per_sec: float = 1000.0
    #: Freeze the throughput model (tests, replays): the control law
    #: becomes a pure function of seed + target.
    pinned_ticks_per_sec: Optional[float] = None
    #: EWMA weight of the newest committed throughput observation.
    gain: float = 0.5
    #: Output rate grid (events per kilotick); committed rates land
    #: exactly on multiples of this quantum.
    rate_quantum_per_ktick: float = 0.25
    min_rate_per_ktick: float = 0.0
    max_rate_per_ktick: float = 1024.0
    #: Committed walls quantize to this ticks/sec grid before entering
    #: the estimate, so the recorded trace fully determines the law.
    tps_quantum: float = 1.0

    def __post_init__(self) -> None:
        if self.target_events_per_sec <= 0:
            raise ValueError("target_events_per_sec must be > 0")
        if not (0.0 < self.gain <= 1.0):
            raise ValueError(f"gain must be in (0, 1], got {self.gain}")
        if self.rate_quantum_per_ktick <= 0 or self.tps_quantum <= 0:
            raise ValueError("quantization steps must be > 0")
        if self.min_rate_per_ktick < 0 \
                or self.max_rate_per_ktick <= self.min_rate_per_ktick:
            raise ValueError("need 0 <= min_rate < max_rate")
        for f in ("initial_ticks_per_sec", "pinned_ticks_per_sec"):
            v = getattr(self, f)
            if v is not None and v <= 0:
                raise ValueError(f"{f} must be > 0, got {v}")

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def _quantize(value: float, quantum: float) -> float:
    return round(value / quantum) * quantum


class LoadServo:
    """The committed control loop: ``observe`` one chunk's heartbeat
    wall, read the next chunk's ``rate_per_ktick``."""

    def __init__(self, config: ServoConfig):
        self.config = config
        pinned = config.pinned_ticks_per_sec
        self._tps = _quantize(
            config.initial_ticks_per_sec if pinned is None else pinned,
            config.tps_quantum)
        self._rate = self._rate_for(self._tps)
        self.updates = 0
        self.backlog = 0

    def _rate_for(self, tps: float) -> float:
        want = 1000.0 * self.config.target_events_per_sec / max(tps, 1e-9)
        want = _quantize(want, self.config.rate_quantum_per_ktick)
        return min(max(want, self.config.min_rate_per_ktick),
                   self.config.max_rate_per_ktick)

    @property
    def rate_per_ktick(self) -> float:
        """The committed rate for the next chunk (quantized)."""
        return self._rate

    @property
    def ticks_per_sec_estimate(self) -> float:
        return self._tps

    def observe(self, *, ticks: int, wall_s: float, backlog: int) -> None:
        """Commit one drained chunk: its compile-excluded wall updates
        the throughput estimate (unless pinned), the new rate derives
        from the updated estimate, and the offered-minus-applied
        backlog is recorded as the saturation observable."""
        self.backlog = int(backlog)
        if self.config.pinned_ticks_per_sec is not None:
            return
        if wall_s < MIN_MEASURABLE_WALL_S:
            return
        measured = _quantize(ticks / wall_s, self.config.tps_quantum)
        gain = self.config.gain
        self._tps = _quantize(gain * measured + (1.0 - gain) * self._tps,
                              self.config.tps_quantum)
        self._rate = self._rate_for(self._tps)
        self.updates += 1

    def chunk_block(self, applied_rate: float) -> dict:
        """The heartbeat ``servo`` block for a chunk that ran at
        ``applied_rate`` (``telemetry.schema.SERVO_CHUNK_SPEC``)."""
        return {
            "target_events_per_sec": self.config.target_events_per_sec,
            "rate_per_ktick": applied_rate,
            "ticks_per_sec_estimate": self._tps,
            "backlog": self.backlog,
            "updates": self.updates,
        }

    # --- checkpoint host blob --------------------------------------------

    def state_dict(self) -> dict:
        return {"kind": "load_servo",
                "config": self.config.as_dict(),
                "tps": self._tps,
                "rate": self._rate,
                "updates": self.updates,
                "backlog": self.backlog}

    @classmethod
    def from_state(cls, state: dict) -> "LoadServo":
        servo = cls(ServoConfig(**state["config"]))
        servo._tps = float(state["tps"])
        servo._rate = float(state["rate"])
        servo.updates = int(state["updates"])
        servo.backlog = int(state["backlog"])
        return servo
