"""Live status API for resident runs: atomic file + line-protocol socket.

A resident service is only observable through its JSONL sink today,
which nothing external can poll mid-run. This module publishes the
latest chunk-boundary snapshot two read-only ways:

- **status file** — the snapshot JSON is written to ``<path>.tmp`` and
  ``os.replace``d over ``<path>``, so a reader never sees a torn
  document (rename is atomic on POSIX);
- **status socket** — a unix-domain stream socket speaking a one-line
  protocol: a client sends ``status\\n`` and receives the latest
  snapshot as one JSON line, or sends ``watch\\n`` and receives the
  latest snapshot followed by every subsequent one until it
  disconnects. Unknown commands answer one ``{"error": ...}`` line.

Non-perturbation is the design invariant, proven by test and by the
tier-1 smoke (byte-identical non-wall JSONL with the socket on vs
off): ``publish`` consumes an already-drained host-side dict — it
never touches device state, never blocks the engine loop (watch fan-out
is bounded ``put_nowait`` queues; a slow subscriber drops frames, the
engine never waits), and every socket client is served from its own
thread.
"""
from __future__ import annotations

import json
import os
import queue
import socket
import threading
from typing import List, Optional

from rapid_tpu.telemetry import json_artifact_line

#: Frames a slow ``watch`` subscriber may buffer before older frames
#: are dropped (the publisher never blocks on a reader).
WATCH_QUEUE_DEPTH = 64


class StatusFile:
    """Atomically-replaced status JSON document."""

    def __init__(self, path: str):
        self.path = path
        self._tmp = path + ".tmp"

    def publish(self, line: str) -> None:
        with open(self._tmp, "w") as fh:
            fh.write(line)
        os.replace(self._tmp, self.path)

    def close(self) -> None:
        pass


class StatusSocket:
    """Unix-domain line-protocol endpoint serving the latest snapshot."""

    def __init__(self, path: str):
        self.path = path
        if os.path.exists(path):
            os.unlink(path)
        self._latest: Optional[str] = None
        self._lock = threading.Lock()
        self._watchers: List[queue.Queue] = []
        self._closed = threading.Event()
        self._server = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._server.bind(path)
        self._server.listen(8)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="status-accept", daemon=True)
        self._accept_thread.start()

    # --- publisher side (the engine loop) --------------------------------

    def publish(self, line: str) -> None:
        with self._lock:
            self._latest = line
            for q in self._watchers:
                try:
                    q.put_nowait(line)
                except queue.Full:
                    # Drop the oldest frame for this subscriber; the
                    # publisher must never block on a slow reader.
                    try:
                        q.get_nowait()
                    except queue.Empty:
                        pass
                    try:
                        q.put_nowait(line)
                    except queue.Full:
                        pass

    def close(self) -> None:
        self._closed.set()
        try:
            self._server.close()
        finally:
            if os.path.exists(self.path):
                os.unlink(self.path)
        with self._lock:
            for q in self._watchers:
                try:
                    q.put_nowait(None)
                except queue.Full:
                    pass

    # --- subscriber side --------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             name="status-conn", daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            with conn, conn.makefile("rw", encoding="utf-8",
                                     newline="\n") as fh:
                for raw in fh:
                    cmd = raw.strip()
                    if cmd == "status":
                        with self._lock:
                            latest = self._latest
                        fh.write(latest if latest is not None
                                 else '{"error": "no snapshot yet"}\n')
                        fh.flush()
                    elif cmd == "watch":
                        self._watch(fh)
                        return
                    elif cmd:
                        fh.write(json.dumps(
                            {"error": f"unknown command {cmd!r}"}) + "\n")
                        fh.flush()
        except (OSError, ValueError):
            pass  # client went away mid-write; nothing to clean up

    def _watch(self, fh) -> None:
        q: queue.Queue = queue.Queue(maxsize=WATCH_QUEUE_DEPTH)
        with self._lock:
            latest = self._latest
            self._watchers.append(q)
        try:
            if latest is not None:
                fh.write(latest)
                fh.flush()
            while not self._closed.is_set():
                try:
                    line = q.get(timeout=0.25)
                except queue.Empty:
                    continue
                if line is None:
                    return
                fh.write(line)
                fh.flush()
        except (OSError, ValueError):
            pass
        finally:
            with self._lock:
                if q in self._watchers:
                    self._watchers.remove(q)


class StatusPublisher:
    """File and/or socket fan-out for one resident run's snapshots."""

    def __init__(self, file_path: Optional[str] = None,
                 socket_path: Optional[str] = None):
        self._outs = []
        if file_path:
            self._outs.append(StatusFile(file_path))
        if socket_path:
            self._outs.append(StatusSocket(socket_path))

    def publish(self, snapshot: dict) -> None:
        line = json_artifact_line(snapshot, sort_keys=True)
        for out in self._outs:
            out.publish(line)

    def close(self) -> None:
        for out in self._outs:
            out.close()


def read_status(socket_path: str, command: str = "status",
                max_lines: int = 1, timeout: float = 10.0) -> List[dict]:
    """Tiny line-protocol client (tests and smokes): send one command,
    collect up to ``max_lines`` snapshot lines."""
    out: List[dict] = []
    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as sk:
        sk.settimeout(timeout)
        sk.connect(socket_path)
        sk.sendall((command + "\n").encode())
        with sk.makefile("r", encoding="utf-8") as fh:
            for line in fh:
                out.append(json.loads(line))
                if len(out) >= max_lines:
                    break
    return out
