"""Resident engine: the tick loop as a long-lived, chunked service.

Everything else in the repo is "boot, scan T ticks, exit"; Rapid itself
(``Cluster.Builder``) is a resident process serving live join/leave
traffic. This driver closes that gap:

- the stream runs as fixed-size ``lax.scan`` segments
  (``Settings.stream_chunk_ticks``, static) — every chunk re-enters the
  same compiled executable with the previous chunk's final carry
  (``engine.step.simulate_chunk``), so an unbounded run pays one
  compile;
- dispatch is **double-buffered**: chunk ``k`` is launched (JAX async
  dispatch) *before* chunk ``k-1``'s logs are pulled to the host, so
  metrics normalization, JSONL writes and traffic generation overlap
  device compute instead of serializing with it;
- carries are **donated** — XLA reuses the state (and recorder ring)
  buffers for the chunk's outputs, so the device working set stays flat
  at steady state (the soak artifact commits the live-buffer watermark
  per chunk to prove it);
- an attached :class:`~rapid_tpu.service.traffic.TrafficGenerator`
  lowers its next window of arrivals into each chunk's
  ``ChurnSchedule`` (quiet windows reuse one inert all-``I32_MAX``
  schedule so the executable signature never changes);
- :meth:`ResidentEngine.save` / :meth:`ResidentEngine.restore` move the
  whole service through ``service.checkpoint`` — engine state, recorder
  ring mid-fill, and the traffic generator's rng snapshot in the
  ``host`` blob — and :meth:`verify_round_trip` *proves* a restore is
  exact: restored pytrees bitwise-equal the live ones, and one
  continuation chunk run from both produces byte-identical ``StepLog``
  columns and recorder rings.

The metrics stream is JSONL (``telemetry.write`` conventions): one
``TickMetrics`` row per tick (optional), one ``record: "chunk"``
heartbeat per chunk, one final ``record: "stream_summary"`` line —
validated by ``telemetry.schema.validate_streaming_stream``.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from rapid_tpu.engine import churn as churn_mod
from rapid_tpu.engine.state import (I32_MAX, EngineFaults, EngineState,
                                    crash_faults, init_state)
from rapid_tpu.engine.step import simulate_chunk
from rapid_tpu.service import checkpoint as checkpoint_mod
from rapid_tpu.service.servo import LoadServo
from rapid_tpu.service.status import StatusPublisher
from rapid_tpu.service.traffic import TrafficConfig, TrafficGenerator
from rapid_tpu.settings import Settings
from rapid_tpu.telemetry import engine_metrics, json_artifact_line, summarize
from rapid_tpu.telemetry.lineage import LineageFold, lineage_summary
from rapid_tpu.telemetry.metrics import _dist
from rapid_tpu.telemetry.slo import SloWindows, ViewChangeFold

# One rate convention across campaign heartbeats and the service stream:
# a wall below the floor reports null instead of a garbage rate.
from rapid_tpu.campaign import MIN_MEASURABLE_WALL_S, _rate  # noqa: F401


def _tree_equal(a, b) -> bool:
    """Bitwise pytree equality on the host."""
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb))


def _dealias(tree):
    """Copy every leaf onto its own buffer. ``init_state`` shares one
    zeros buffer across several fields; donating such a carry would hand
    the same buffer to XLA twice."""
    return jax.tree_util.tree_map(lambda x: jnp.array(x, copy=True), tree)


def _live_buffer_bytes() -> int:
    return int(sum(int(np.prod(a.shape)) * a.dtype.itemsize
                   for a in jax.live_arrays()))


def synthetic_uids(n: int, seed: int = 0) -> np.ndarray:
    """Distinct 64-bit node identities (same stream as the benches)."""
    from rapid_tpu import hashing

    hi, lo = hashing.np_to_limbs(np.arange(1, n + 1, dtype=np.uint64))
    hi, lo = hashing.hash64_limbs(np, hi, lo, seed=0xBEEF ^ (seed & 0xFFFF))
    return hashing.np_from_limbs(hi, lo)


class ResidentEngine:
    """One resident shared-state engine plus its I/O loop.

    ``sink`` (a path or None) receives the JSONL metrics stream;
    ``write_ticks=False`` keeps only chunk heartbeats and the summary
    (100k-tick soaks at small N don't need 100k rows committed).
    """

    def __init__(self, state: EngineState, faults: EngineFaults,
                 settings: Settings, *,
                 traffic: Optional[TrafficGenerator] = None,
                 servo: Optional[LoadServo] = None,
                 slo: Optional[SloWindows] = None,
                 status: Optional[StatusPublisher] = None,
                 sink: Optional[str] = None, write_ticks: bool = True,
                 donate: bool = True, n_initial: Optional[int] = None):
        self.settings = settings
        self.capacity = int(state.member.shape[0])
        self.n_initial = (int(np.asarray(state.member).sum())
                          if n_initial is None else int(n_initial))
        self._state = _dealias(state)
        self._rec = None
        self._faults = faults
        self.traffic = traffic
        if servo is not None and traffic is None:
            raise ValueError("a servo needs an attached traffic generator")
        self.servo = servo
        self.slo = slo
        self._vc_fold = ViewChangeFold(0) if slo is not None else None
        # Lineage rides the same drained gauge rows as the SLO fold; the
        # rolling window matches the SLO window so a heartbeat's lineage
        # block decomposes the same chunks the slo block summarizes.
        self._lineage = LineageFold(0)
        self.lineage_spans: list = []
        self._lineage_window: deque = deque(
            maxlen=slo.window_chunks if slo is not None else 8)
        self.status = status
        self._inert_schedule = (churn_mod.empty_schedule(self.capacity)
                                if traffic is not None else None)
        self._donate = donate
        self._sink = open(sink, "w") if sink else None
        self._write_ticks = write_ticks
        self._pending = None
        self.metrics: list = []
        self.chunk_records: list = []
        self.chunks = 0
        self.ticks = 0
        self.checkpoint_block: Optional[dict] = None
        self.compile_s: Optional[float] = None
        self._dispatches = 0
        self._wall0 = time.perf_counter()
        self._last_drain_wall = self._wall0
        self._watermarks: list = []

    @property
    def state(self) -> EngineState:
        """The current carry (chunk-boundary accurate after ``flush``)."""
        return self._state

    # --- internals --------------------------------------------------------

    def _next_schedule(self):
        if self.traffic is None:
            return None, None
        if self.servo is not None:
            # The committed rate from the last drained heartbeat drives
            # this whole chunk; closed-loop sampling keeps the rng
            # stream advancement identical whatever the rate.
            self.traffic.set_join_rate(self.servo.rate_per_ktick)
        schedule, tinfo = self.traffic.next_chunk(
            self.settings.stream_chunk_ticks)
        # Quiet windows reuse one inert schedule: same pytree structure,
        # same shapes -> same executable as a busy chunk.
        return (self._inert_schedule if schedule is None else schedule,
                tinfo)

    def _emit(self, record: dict) -> None:
        if self._sink is not None:
            self._sink.write(json_artifact_line(record, sort_keys=True))
            self._sink.flush()

    def _dispatch(self, *, donate: Optional[bool] = None) -> dict:
        schedule, tinfo = self._next_schedule()
        applied_rate = (self.servo.rate_per_ktick
                        if self.servo is not None else None)
        t0 = time.perf_counter()
        out = simulate_chunk(
            self._state, self._faults, self.settings.stream_chunk_ticks,
            self.settings, churn=schedule, rec=self._rec,
            donate=self._donate if donate is None else donate)
        dispatch_wall = time.perf_counter() - t0
        # The first dispatch of this process blocks on trace + compile
        # before the async enqueue returns; its wall is the compile cost
        # the chunk-0 heartbeat reports separately (execution itself is
        # async and lands in the drain wall).
        compile_s = dispatch_wall if self._dispatches == 0 else None
        self._dispatches += 1
        if compile_s is not None:
            self.compile_s = compile_s
        if self.settings.flight_recorder_window:
            self._state, logs, self._rec = out
        else:
            self._state, logs = out
        pending = {"index": self.chunks, "logs": logs, "tinfo": tinfo,
                   "checkpoint": None, "compile_s": compile_s,
                   "servo_rate": applied_rate}
        self.chunks += 1
        self.ticks += self.settings.stream_chunk_ticks
        return pending

    def _drain(self, pending: dict) -> None:
        logs = pending["logs"]
        jax.block_until_ready(logs)
        rows = engine_metrics(logs)
        self.metrics.extend(rows)
        if self._write_ticks:
            for row in rows:
                self._emit(row.as_dict())
        now = time.perf_counter()
        wall = now - self._last_drain_wall
        self._last_drain_wall = now
        compile_s = pending.get("compile_s")
        if compile_s is not None:
            # The drain wall of the first chunk folds the one-time
            # trace/compile cost in; report it separately and exclude it
            # from wall_s, so chunk-0 rates (and the servo's control
            # input) measure execution throughput, not the compiler.
            compile_s = min(compile_s, wall)
            wall = wall - compile_s
        live = _live_buffer_bytes()
        self._watermarks.append(live)
        tinfo = pending["tinfo"]
        backlog = ((tinfo["backlog_joins"] + tinfo["backlog_leaves"])
                   if tinfo else None)
        servo_block = None
        if self.servo is not None:
            self.servo.observe(ticks=self.settings.stream_chunk_ticks,
                               wall_s=wall, backlog=backlog or 0)
            servo_block = self.servo.chunk_block(pending["servo_rate"])
        slo_block = None
        if self.slo is not None:
            slo_block = self.slo.fold_chunk(self._vc_fold.fold(rows))
        new_spans = self._lineage.fold(rows)
        self.lineage_spans.extend(new_spans)
        self._lineage_window.append(new_spans)
        lineage_block = lineage_summary(
            [sp for chunk in self._lineage_window for sp in chunk])
        record = {
            "record": "chunk",
            "index": pending["index"],
            "tick": rows[-1].tick if rows else self.ticks,
            "ticks": self.settings.stream_chunk_ticks,
            "wall_s": wall,
            "compile_s": compile_s,
            "ticks_per_sec": _rate(self.settings.stream_chunk_ticks, wall),
            "events_per_sec": _rate(tinfo["events"], wall) if tinfo else None,
            "announces": sum(r.announce for r in rows),
            "decides": sum(r.decide for r in rows),
            "live_buffer_bytes": live,
            "traffic": tinfo,
            "servo": servo_block,
            "slo": slo_block,
            "lineage": lineage_block,
            "checkpoint": pending["checkpoint"],
        }
        self.chunk_records.append(record)
        self._emit(record)
        if self.status is not None:
            self.status.publish(self._status_snapshot(record, rows))

    def _status_snapshot(self, record: dict, rows) -> dict:
        """The chunk-boundary ``status_snapshot`` block (``telemetry
        .schema.STATUS_SNAPSHOT_SPEC``) — built purely from
        already-drained host data, so publishing can never perturb the
        protocol stream."""
        from rapid_tpu.telemetry.schema import SCHEMA_VERSION

        last = rows[-1] if rows else None
        tinfo = record["traffic"]
        backlog = ((tinfo["backlog_joins"] + tinfo["backlog_leaves"])
                   if tinfo else None)
        return {
            "record": "status_snapshot",
            "schema_version": SCHEMA_VERSION,
            "source": "resident",
            "tick": record["tick"],
            "chunks": self.chunks,
            "epoch": int(last.epoch) if last is not None else -1,
            "n_members": (int(last.n_member)
                          if last is not None else self.n_initial),
            "ticks_per_sec": record["ticks_per_sec"],
            "events_per_sec": record["events_per_sec"],
            "backlog": backlog,
            "live_buffer_bytes": record["live_buffer_bytes"],
            "servo": record["servo"],
            "slo": record["slo"],
            "lineage": record["lineage"],
            "checkpoint": self.checkpoint_block,
            "wall_s": time.perf_counter() - self._wall0,
        }

    # --- public loop ------------------------------------------------------

    def flush(self) -> None:
        """Drain the in-flight chunk, if any."""
        if self._pending is not None:
            pending, self._pending = self._pending, None
            self._drain(pending)

    def run(self, n_chunks: int) -> None:
        """Run ``n_chunks`` chunks, double-buffered: chunk ``k`` is
        dispatched before chunk ``k-1``'s host I/O runs."""
        for _ in range(int(n_chunks)):
            dispatched = self._dispatch()
            self.flush()
            self._pending = dispatched
        self.flush()

    # --- checkpoint/restore ----------------------------------------------

    def _host_blob(self) -> dict:
        blob = {"chunks": self.chunks, "ticks": self.ticks,
                "n_initial": self.n_initial}
        if self.traffic is not None:
            blob["traffic"] = self.traffic.state_dict()
        if self.servo is not None:
            blob["servo"] = self.servo.state_dict()
        if self.slo is not None:
            blob["slo"] = self.slo.state_dict()
            blob["vc_fold"] = self._vc_fold.state_dict()
        blob["lineage"] = {"fold": self._lineage.state_dict(),
                           "spans": self.lineage_spans,
                           "window": [list(c) for c in self._lineage_window]}
        return blob

    def save(self, path: str) -> dict:
        """Checkpoint the full service (engine carry, recorder ring,
        traffic generator) — drains the in-flight chunk first so the
        saved carry is a chunk boundary."""
        self.flush()
        return checkpoint_mod.save_engine(
            path, self._state, self.settings, rec=self._rec,
            host=self._host_blob())

    @classmethod
    def restore(cls, path: str, faults: EngineFaults, settings: Settings,
                **kw) -> "ResidentEngine":
        cp = checkpoint_mod.load_checkpoint(path, settings)
        if cp.family != "engine":
            raise checkpoint_mod.CheckpointError(
                f"ResidentEngine.restore needs an engine checkpoint, "
                f"got family {cp.family!r}")
        host = cp.host or {}
        traffic = kw.pop("traffic", None)
        if traffic is None and "traffic" in host:
            traffic = TrafficGenerator.from_state(host["traffic"], settings)
        servo = kw.pop("servo", None)
        if servo is None and "servo" in host:
            servo = LoadServo.from_state(host["servo"])
        slo = kw.pop("slo", None)
        if slo is None and "slo" in host:
            slo = SloWindows.from_state(host["slo"])
        eng = cls(cp.parts["state"], faults, settings, traffic=traffic,
                  servo=servo, slo=slo,
                  n_initial=host.get("n_initial"), **kw)
        if eng.slo is not None and "vc_fold" in host:
            eng._vc_fold = ViewChangeFold.from_state(host["vc_fold"])
        if "lineage" in host:
            lin = host["lineage"]
            eng._lineage = LineageFold.from_state(lin["fold"])
            eng.lineage_spans = list(lin["spans"])
            for chunk in lin["window"]:
                eng._lineage_window.append(list(chunk))
        rec = cp.parts.get("recorder")
        # Own buffers before the first donated dispatch: the npz-backed
        # host arrays must not be handed to XLA as donations.
        eng._rec = _dealias(rec) if rec is not None else None
        eng.chunks = int(host.get("chunks", 0))
        eng.ticks = int(host.get("ticks", cp.tick))
        return eng

    def verify_round_trip(self, path: str) -> dict:
        """Save, restore, and prove the restore exact; returns the
        ``checkpoint`` block the summary embeds.

        Two layers of proof: (a) every restored pytree leaf is bitwise
        equal to its live twin; (b) one continuation chunk run from the
        live carry and from the restored carry (same traffic window,
        undonated so both inputs survive) produces byte-identical
        ``StepLog`` columns, final states, and recorder rings. The
        restored branch then *becomes* the stream — continuation after
        restore is the run from here on, so the committed soak is itself
        evidence that a restore loses nothing.
        """
        self.flush()
        self.save(path)
        cp = checkpoint_mod.load_checkpoint(path, self.settings)
        r_state = cp.parts["state"]
        r_rec = cp.parts.get("recorder")
        state_identical = _tree_equal(self._state, r_state)
        recorder_identical = (_tree_equal(self._rec, r_rec)
                              if self._rec is not None else None)

        schedule, tinfo = self._next_schedule()
        n = self.settings.stream_chunk_ticks
        live = simulate_chunk(self._state, self._faults, n, self.settings,
                              churn=schedule, rec=self._rec, donate=False)
        rest = simulate_chunk(r_state, self._faults, n, self.settings,
                              churn=schedule, rec=r_rec, donate=False)
        if self.settings.flight_recorder_window:
            l_final, l_logs, l_rec = live
            r_final, r_logs, r_rec2 = rest
            cont_rec_ok = _tree_equal(l_rec, r_rec2)
        else:
            l_final, l_logs = live
            r_final, r_logs = rest
            l_rec = r_rec2 = None
            cont_rec_ok = None
        block = {
            "version": checkpoint_mod.CHECKPOINT_VERSION,
            "tick": cp.tick,
            "state_identical": bool(state_identical),
            "recorder_identical": recorder_identical,
            "logs_identical": bool(_tree_equal(l_logs, r_logs)),
            "final_identical": bool(_tree_equal(l_final, r_final)),
            "continuation_recorder_identical": cont_rec_ok,
        }
        # Adopt the restored branch as the continuing carry.
        self._state = _dealias(r_final)
        self._rec = _dealias(r_rec2) if r_rec2 is not None else None
        pending = {"index": self.chunks, "logs": r_logs, "tinfo": tinfo,
                   "checkpoint": block, "compile_s": None,
                   "servo_rate": (self.servo.rate_per_ktick
                                  if self.servo is not None else None)}
        self.chunks += 1
        self.ticks += n
        self._drain(pending)
        self.checkpoint_block = block
        return block

    # --- summary ----------------------------------------------------------

    def summary(self) -> dict:
        """The final ``record: "stream_summary"`` line (also written to
        the sink): protocol totals, sustained rates, decide-latency
        tails, the live-buffer watermark, and the checkpoint proof."""
        from rapid_tpu.telemetry.schema import SCHEMA_VERSION

        self.flush()
        s = summarize(self.metrics) if self.metrics else None
        ttvc = [vc["ticks_to_decide"] for vc in s.view_changes] if s else []
        wall = time.perf_counter() - self._wall0
        marks = self._watermarks
        record = {
            "record": "stream_summary",
            "schema_version": SCHEMA_VERSION,
            "source": "resident",
            "n": self.n_initial,
            "capacity": self.capacity,
            "ticks": self.ticks,
            "chunks": self.chunks,
            "chunk_ticks": self.settings.stream_chunk_ticks,
            "events_injected": self.traffic.events if self.traffic else 0,
            "joins": self.traffic.joins if self.traffic else 0,
            "leaves": self.traffic.leaves if self.traffic else 0,
            "bursts": self.traffic.bursts if self.traffic else 0,
            "announcements": s.announcements if s else 0,
            "decisions": s.decisions if s else 0,
            "wall_s": wall,
            "compile_s": self.compile_s,
            "ticks_per_sec": _rate(self.ticks, wall),
            "events_per_sec": _rate(
                self.traffic.events if self.traffic else 0, wall),
            "ticks_to_view_change": _dist(ttvc),
            "lineage": lineage_summary(self.lineage_spans),
            "servo": ({"config": self.servo.config.as_dict(),
                       "final": self.servo.chunk_block(
                           self.servo.rate_per_ktick)}
                      if self.servo is not None else None),
            "slo": self.slo.block() if self.slo is not None else None,
            # ``steady_max`` excludes verify-round-trip chunks, which
            # transiently hold both the live and the restored branch;
            # the flat-memory gate reads it.
            "live_buffer_bytes": {
                "first": marks[0] if marks else None,
                "max": max(marks) if marks else None,
                "steady_max": max(
                    (r["live_buffer_bytes"] for r in self.chunk_records
                     if not r["checkpoint"]), default=None),
                "last": marks[-1] if marks else None,
            },
            "traffic": self.traffic.config.as_dict() if self.traffic
            else None,
            "checkpoint": self.checkpoint_block,
        }
        self._emit(record)
        return record

    def close(self) -> None:
        if self._sink is not None:
            self._sink.close()
            self._sink = None
        if self.status is not None:
            self.status.close()
            self.status = None


def boot_resident(settings: Settings, capacity: int, n_initial: int, *,
                  seed: int = 0,
                  traffic_config: Optional[TrafficConfig] = None,
                  servo: Optional[LoadServo] = None,
                  slo: Optional[SloWindows] = None,
                  status: Optional[StatusPublisher] = None,
                  sink: Optional[str] = None, write_ticks: bool = True,
                  donate: bool = True) -> ResidentEngine:
    """Boot a converged ``n_initial``-member cluster with a dormant
    joiner pool and (optionally) an attached traffic generator."""
    traffic = None
    id_fps = None
    if traffic_config is not None:
        traffic = TrafficGenerator(traffic_config, settings, capacity,
                                   n_initial)
        id_fps = traffic.boot_id_fps()
    uids = synthetic_uids(capacity, seed)
    member = np.zeros(capacity, bool)
    member[:n_initial] = True
    state = init_state(uids, id_fp_sum=0, settings=settings, member=member,
                       id_fps=id_fps)
    faults = crash_faults([I32_MAX] * capacity)
    return ResidentEngine(state, faults, settings, traffic=traffic,
                          servo=servo, slo=slo, status=status,
                          sink=sink, write_ticks=write_ticks, donate=donate,
                          n_initial=n_initial)
