"""Seeded 64-bit hashing shared by the host oracle and the TPU kernels.

The reference orders each ring by a seeded XXHash of the endpoint
(MembershipView.java:47,562-587) and derives configuration identifiers from a
37x polynomial over XXHashes (MembershipView.java:540-556). Protocol semantics
only require a *fixed pseudorandom total order* and a collision-resistant
configuration fingerprint — not XXHash specifically — so (per SURVEY.md §7
"hash parity") both sides of this framework share one hash: splitmix64-style
finalizers.

TPUs have no native 64-bit integers without enabling jax x64 globally (which
would double the cost of every int op in the hot kernels), so the canonical
implementation here operates on (hi, lo) uint32 limb pairs and is written
against an array-namespace parameter ``xp`` that may be ``numpy`` or
``jax.numpy``. The oracle and the engine call the *same* function, so ring
order and config ids agree by construction.

All Python-int helpers treat values as unsigned 64-bit.
"""
from __future__ import annotations

from typing import Tuple

import numpy as np

MASK64 = (1 << 64) - 1
MASK32 = (1 << 32) - 1

# splitmix64 constants
_GAMMA = 0x9E3779B97F4A7C15
_MIX1 = 0xBF58476D1CE4E5B9
_MIX2 = 0x94D049BB133111EB


# ---------------------------------------------------------------------------
# Pure-Python reference (host-side scalars: endpoint/uuid fingerprints)
# ---------------------------------------------------------------------------


def splitmix64(x: int) -> int:
    """The splitmix64 finalizer on a python int (unsigned 64-bit)."""
    z = (x + _GAMMA) & MASK64
    z = ((z ^ (z >> 30)) * _MIX1) & MASK64
    z = ((z ^ (z >> 27)) * _MIX2) & MASK64
    return z ^ (z >> 31)


def hash64(x: int, seed: int = 0) -> int:
    """Seeded 64-bit hash of a 64-bit value."""
    return splitmix64((x ^ splitmix64(seed & MASK64)) & MASK64)


def fingerprint_bytes(data: bytes, seed: int = 0) -> int:
    """64-bit fingerprint of a byte string (FNV-1a 64 core + splitmix finalize).

    Host-side only: used to turn endpoint hostnames into uint64 identities.
    """
    h = 0xCBF29CE484222325 ^ hash64(seed)
    for b in data:
        h = ((h ^ b) * 0x100000001B3) & MASK64
    return splitmix64(h)


# ---------------------------------------------------------------------------
# Limb-based (hi, lo) uint32 implementation, numpy/jax.numpy polymorphic
# ---------------------------------------------------------------------------


def _u32(xp, v: int):
    return xp.uint32(v & MASK32)


def mul32_wide(xp, a, b):
    """32x32 -> 64 multiply on uint32 arrays, returning (hi, lo) uint32."""
    a = a.astype(xp.uint32)
    b = b.astype(xp.uint32)
    a0 = a & xp.uint32(0xFFFF)
    a1 = a >> xp.uint32(16)
    b0 = b & xp.uint32(0xFFFF)
    b1 = b >> xp.uint32(16)
    # partial products, each fits in 32 bits
    p00 = a0 * b0
    p01 = a0 * b1
    p10 = a1 * b0
    p11 = a1 * b1
    # mid = p01 + (p00 >> 16) cannot wrap uint32 (max 0xFFFEFFFF); only the
    # subsequent + p10 can carry into bit 32.
    mid = p01 + (p00 >> xp.uint32(16))
    mid2 = mid + p10
    carry = (mid2 < p10).astype(xp.uint32)
    lo = (p00 & xp.uint32(0xFFFF)) | (mid2 << xp.uint32(16))
    hi = p11 + (mid2 >> xp.uint32(16)) + (carry << xp.uint32(16))
    return hi, lo


def add64(xp, ahi, alo, bhi, blo):
    lo = alo + blo
    carry = (lo < alo).astype(xp.uint32)
    hi = ahi + bhi + carry
    return hi, lo


def xor64(ahi, alo, bhi, blo):
    return ahi ^ bhi, alo ^ blo


def neg64(xp, hi, lo):
    """Two's-complement negation on (hi, lo) uint32 limbs."""
    return add64(xp, ~hi, ~lo, xp.zeros_like(hi), xp.ones_like(lo))


def sub64(xp, ahi, alo, bhi, blo):
    """a - b mod 2^64 on (hi, lo) uint32 limbs."""
    nhi, nlo = neg64(xp, bhi, blo)
    return add64(xp, ahi, alo, nhi, nlo)


def sum64(xp, hi, lo):
    """Sum of an array of (hi, lo) uint64 values mod 2^64, as scalar limbs.

    jax without x64 has no 64-bit integers, so a plain ``sum`` cannot carry;
    this folds the array pairwise with ``add64`` (log2(n) static steps), which
    keeps every intermediate in uint32 limbs and is jit-friendly.
    """
    hi = hi.reshape(-1).astype(xp.uint32)
    lo = lo.reshape(-1).astype(xp.uint32)
    n = hi.shape[0]
    while n > 1:
        if n % 2:
            hi = xp.concatenate([hi, xp.zeros((1,), xp.uint32)])
            lo = xp.concatenate([lo, xp.zeros((1,), xp.uint32)])
            n += 1
        hi, lo = add64(xp, hi[0::2], lo[0::2], hi[1::2], lo[1::2])
        n //= 2
    return hi[0], lo[0]


def sum64_axis(xp, hi, lo):
    """Sum (hi, lo) uint64 limb arrays mod 2^64 along the LAST axis.

    Batched companion to ``sum64``: leading axes are preserved, so a
    ``[C, C]`` limb matrix reduces to per-row ``[C]`` sums with carries
    intact.  Same pairwise log-fold, same jit-friendliness.
    """
    hi = hi.astype(xp.uint32)
    lo = lo.astype(xp.uint32)
    n = hi.shape[-1]
    if n == 0:
        shape = hi.shape[:-1]
        return xp.zeros(shape, xp.uint32), xp.zeros(shape, xp.uint32)
    while n > 1:
        if n % 2:
            pad = [(0, 0)] * (hi.ndim - 1) + [(0, 1)]
            hi = xp.pad(hi, pad)
            lo = xp.pad(lo, pad)
            n += 1
        hi, lo = add64(xp, hi[..., 0::2], lo[..., 0::2], hi[..., 1::2], lo[..., 1::2])
        n //= 2
    return hi[..., 0], lo[..., 0]


def shr64(xp, hi, lo, n: int):
    """Logical right shift by constant 0 < n < 64."""
    assert 0 < n < 64
    if n < 32:
        new_lo = (lo >> xp.uint32(n)) | (hi << xp.uint32(32 - n))
        new_hi = hi >> xp.uint32(n)
    else:
        new_lo = hi >> xp.uint32(n - 32) if n > 32 else hi
        new_hi = xp.zeros_like(hi)
    return new_hi, new_lo


def mul64(xp, ahi, alo, bhi, blo):
    """Low 64 bits of a 64x64 multiply, on (hi, lo) uint32 limbs."""
    hi_ll, lo_ll = mul32_wide(xp, alo, blo)
    hi = hi_ll + alo * bhi + ahi * blo  # mod 2^32 per term
    return hi, lo_ll


def _mul64_const(xp, hi, lo, c: int):
    chi = _u32(xp, c >> 32)
    clo = _u32(xp, c)
    return mul64(xp, hi, lo, chi, clo)


def splitmix64_limbs(xp, hi, lo):
    """splitmix64 finalizer on (hi, lo) uint32 arrays; matches splitmix64()."""
    hi = hi.astype(xp.uint32)
    lo = lo.astype(xp.uint32)
    hi, lo = add64(xp, hi, lo, _u32(xp, _GAMMA >> 32), _u32(xp, _GAMMA))
    shi, slo = shr64(xp, hi, lo, 30)
    hi, lo = xor64(hi, lo, shi, slo)
    hi, lo = _mul64_const(xp, hi, lo, _MIX1)
    shi, slo = shr64(xp, hi, lo, 27)
    hi, lo = xor64(hi, lo, shi, slo)
    hi, lo = _mul64_const(xp, hi, lo, _MIX2)
    shi, slo = shr64(xp, hi, lo, 31)
    return xor64(hi, lo, shi, slo)


def hash64_limbs(xp, hi, lo, seed: int = 0):
    """Seeded hash on (hi, lo) uint32 arrays; matches hash64()."""
    s = splitmix64(seed & MASK64)
    hi2 = hi.astype(xp.uint32) ^ _u32(xp, s >> 32)
    lo2 = lo.astype(xp.uint32) ^ _u32(xp, s)
    return splitmix64_limbs(xp, hi2, lo2)


def hash64_limbs_dynseed(xp, hi, lo, seed_hi, seed_lo):
    """``hash64_limbs`` with the seed as (hi, lo) limb arrays/scalars.

    Needed on device when the seed is a traced value (e.g. the simulation
    tick inside a jitted step); matches ``hash64(x, seed)`` bit-for-bit.
    """
    shi, slo = splitmix64_limbs(xp, xp.asarray(seed_hi, xp.uint32),
                                xp.asarray(seed_lo, xp.uint32))
    return splitmix64_limbs(xp, hi.astype(xp.uint32) ^ shi,
                            lo.astype(xp.uint32) ^ slo)


# ---------------------------------------------------------------------------
# Conversions
# ---------------------------------------------------------------------------


def to_limbs(x: int) -> Tuple[int, int]:
    x &= MASK64
    return (x >> 32) & MASK32, x & MASK32


def from_limbs(hi: int, lo: int) -> int:
    return ((int(hi) & MASK32) << 32) | (int(lo) & MASK32)


def np_to_limbs(arr: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    arr = arr.astype(np.uint64)
    return (arr >> np.uint64(32)).astype(np.uint32), (arr & np.uint64(MASK32)).astype(np.uint32)


def np_from_limbs(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    return (hi.astype(np.uint64) << np.uint64(32)) | lo.astype(np.uint64)
