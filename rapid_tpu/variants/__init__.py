"""Protocol-variant lab: dissemination/consensus variants of the engine.

The paper's protocol broadcasts alerts and fast-round votes all-to-all —
O(N^2) messages per exchange, the wall between the 100k profile sweeps
and the 1M-node target. This package holds the variant layer selected by
the static ``Settings.protocol_variant`` knob:

``"rapid"``
    The reference protocol. The knob's default; ``engine/step.py`` must
    trace a byte-identical jaxpr under it (pinned by
    ``tests/test_variants.py`` like the ``rx_kernel`` knob).

``"ring"`` (:mod:`rapid_tpu.variants.ring`)
    Transport-only: vote tallies and cut-report delivery lower through
    the static ring-0 permutation (Ring-Paxos-style circulation — one
    lap to aggregate, one lap to disseminate), so each broadcast-shaped
    exchange costs 2N messages instead of S*N. Decisions, config ids
    and every protocol state bit stay identical to "rapid"; only the
    logged message factors — and the variant-aware oracle's counts —
    change.

``"hier"`` (:mod:`rapid_tpu.variants.hier`)
    Two-level hierarchical consensus (Fast-Raft-style): slots hash into
    G = max(2, isqrt(capacity)) seeded groups, an announce decides only
    when >= fast_quorum(G_nonempty) groups each reach their intra-group
    fast quorum, and the verdict round among group aggregators is
    counted as an inter-group all-to-all. The classic-Paxos fallback
    instance is reused verbatim as the top-level settle path.

:mod:`rapid_tpu.variants.oracle` hosts the variant-aware transform of
the host oracle's per-tick counters, which
``engine.diff.run_variant_differential`` compares bit-for-bit against
the engine's expanded StepLog factors.
"""
from __future__ import annotations

#: Every value ``Settings.protocol_variant`` accepts, default first.
VARIANTS = ("rapid", "ring", "hier")
