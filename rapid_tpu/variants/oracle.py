"""Variant-aware oracle counters: host-side message-count transforms.

The python oracle always *runs* the reference protocol — decisions, config
ids and event ticks are variant-invariant inside each variant's envelope
(ring is transport-only; hier scenarios are admitted only when the
two-level quorum rule agrees with the flat one, certified here by
``hier.np_hier_decide``). What changes is the wire accounting, and this
module recomputes the oracle's per-tick counters under a variant's
message model from host-side facts alone:

- the oracle's per-tick totals (``SimNetwork.tick_history``) and
  per-phase consensus counts (``consensus_history``) — used to decompose
  totals into traffic classes and to gate "did an exchange happen";
- the oracle event stream — replayed into per-tick membership masks
  (pre/post any view change at that tick, matching the engine's
  state/mid split);
- the fault schedule — crash masks per tick.

``engine.diff.run_variant_differential`` compares these transformed
counters bit-for-bit against the engine's expanded StepLog factors, so
the O(N) ring counts and the hier exchange formula are checked exactly,
per tick, with no engine-derived quantity on the oracle side.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from rapid_tpu.variants import hier as hier_mod

#: Phase keys of ``SimNetwork.consensus_history``.
_PHASES = ("fast_vote", "phase1a", "phase1b", "phase2a", "phase2b")


class VariantEnvelopeError(ValueError):
    """Scenario outside a variant's bit-identical envelope.

    Raised before any comparison runs — e.g. a crash burst skewed into
    few hier groups, where the two-level quorum legitimately refuses a
    view change the flat quorum accepts. Such scenarios are protocol
    *behavior* differences, not bugs, and the differential only certifies
    scenarios where the variant and the reference must agree.
    """


def _membership_masks(
    n: int, events, n_ticks: int,
) -> Tuple[List[np.ndarray], List[np.ndarray]]:
    """Per-tick membership masks from the oracle event stream.

    Returns (pre, post), each indexed by tick-1: ``pre[i]`` is the
    membership before any view change at tick i+1 (the engine's
    ``state.member`` during vote delivery), ``post[i]`` after it (the
    engine's ``mid.member`` during flush/announce). Crash differentials
    only remove members, so view-change slots are cleared.
    """
    member = np.ones(n, bool)
    removals: Dict[int, List[int]] = {}
    for e in events:
        if e.kind == "view_change":
            removals.setdefault(e.tick, []).extend(e.slots)
    pre: List[np.ndarray] = []
    post: List[np.ndarray] = []
    for t in range(1, n_ticks + 1):
        pre.append(member.copy())
        for s in removals.get(t, ()):
            member[s] = False
        post.append(member.copy())
    return pre, post


def _crash_masks(n: int, crash_ticks: Dict[int, int],
                 n_ticks: int) -> List[np.ndarray]:
    """``crashed[i][s]`` == slot s is crashed during tick i+1."""
    tick_of = np.full(n, np.iinfo(np.int64).max, np.int64)
    for s, t in crash_ticks.items():
        tick_of[s] = t
    return [tick_of <= t for t in range(1, n_ticks + 1)]


def _uid_limbs(uids: Sequence[int]) -> Tuple[np.ndarray, np.ndarray]:
    u = np.asarray(uids, np.uint64)
    return ((u >> np.uint64(32)).astype(np.uint32),
            (u & np.uint64(0xFFFFFFFF)).astype(np.uint32))


def check_hier_envelope(n: int, crash_ticks: Dict[int, int], events,
                        n_ticks: int, uids: Sequence[int],
                        n_groups: int) -> None:
    """Certify every announce lands the same way under both quorum rules.

    For each oracle proposal at tick ``ta``, the reference decided iff a
    view change fired at ``ta + 1``; the hier rule's verdict is
    recomputed host-side from the same voter/validity masks via the
    independent ``np_hier_decide`` twin. Any disagreement means the
    scenario exercises genuinely different protocol behavior — raise
    ``VariantEnvelopeError`` naming the announce instead of producing a
    vacuous differential.
    """
    pre, post = _membership_masks(n, events, n_ticks)
    crashed = _crash_masks(n, crash_ticks, n_ticks)
    uid_hi, uid_lo = _uid_limbs(uids)
    decide_ticks = {e.tick for e in events if e.kind == "view_change"}
    for e in events:
        if e.kind != "proposal":
            continue
        ta = e.tick
        td = ta + 1
        if td > n_ticks:
            continue
        voters = post[ta - 1] & ~crashed[ta - 1]
        valid = voters & ~crashed[td - 1]
        # Group sizes come from the decide-tick membership (the engine's
        # ``state.member`` — crashed slots are members until removed),
        # not from the voter set: a group's quorum is over its members.
        member = pre[td - 1]
        gate = bool((member & ~crashed[td - 1]).any())
        hier_decides = gate and hier_mod.np_hier_decide(
            np, member, valid, uid_hi, uid_lo, n_groups)
        rapid_decided = td in decide_ticks
        if hier_decides != rapid_decided:
            raise VariantEnvelopeError(
                f"announce at tick {ta} is outside the hier envelope: "
                f"flat quorum {'decides' if rapid_decided else 'fails'} "
                f"at tick {td} but the {n_groups}-group rule "
                f"{'decides' if hier_decides else 'fails'} "
                f"(voters={int(voters.sum())}, valid={int(valid.sum())})")


def variant_oracle_counters(
    variant: str,
    n: int,
    crash_ticks: Dict[int, int],
    events,
    tick_counters: List[Dict[str, int]],
    phase_counters: List[Dict[str, int]],
    uids: Sequence[int],
    contested: bool = False,
) -> Tuple[List[Dict[str, int]], List[Dict[str, int]]]:
    """The oracle's counters under ``variant``'s message model.

    Returns (tick_counters, phase_counters) shaped exactly like the
    inputs. ``contested`` selects the scripted-consensus accounting
    (fast votes are the scripted ``pxvote`` class, delivered == previous
    sent — crash-free envelope) over the organic-announce accounting
    (fast votes are the live vote class with crash-lossy delivery).
    ``variant == "rapid"`` is the identity.
    """
    if variant == "rapid":
        return ([dict(d) for d in tick_counters],
                [dict(d) for d in phase_counters])

    n_ticks = len(tick_counters)
    n_groups = hier_mod.hier_group_count(n)
    if variant == "hier":
        check_hier_envelope(n, crash_ticks, events, n_ticks, uids, n_groups)
        if contested:
            # The scripted contested instance runs the untouched
            # classic top-level fallback; hier only reshapes the organic
            # announce path, so contested accounting is the identity.
            return ([dict(d) for d in tick_counters],
                    [dict(d) for d in phase_counters])

    pre, post = _membership_masks(n, events, n_ticks)
    crashed = _crash_masks(n, crash_ticks, n_ticks)
    uid_hi, uid_lo = _uid_limbs(uids)

    out_tick: List[Dict[str, int]] = []
    out_phase: List[Dict[str, int]] = []
    prev_batch = prev_vote = prev_fast = 0
    for i in range(n_ticks):
        tk = dict(tick_counters[i])
        ph = dict(phase_counters[i])
        phase_sent = sum(ph[f"{p}_sent"] for p in _PHASES)
        phase_delivered = sum(ph[f"{p}_delivered"] for p in _PHASES)
        batch_sent = tk["sent"] - phase_sent
        batch_delivered = tk["delivered"] - phase_delivered
        fast_sent = ph["fast_vote_sent"]
        fast_delivered = ph["fast_vote_delivered"]

        m_post = int(post[i].sum())
        a_post = int((post[i] & ~crashed[i]).sum())
        a_pre = int((pre[i] & ~crashed[i]).sum())

        if variant == "ring":
            batch_sent = 2 * m_post if batch_sent > 0 else 0
            batch_delivered = 2 * a_post if batch_delivered > 0 else 0
            if contested:
                fast_sent = 2 * m_post if fast_sent > 0 else 0
                fast_delivered = prev_fast
            else:
                fast_sent = 2 * m_post if fast_sent > 0 else 0
                fast_delivered = 2 * a_pre if fast_delivered > 0 else 0
        else:  # hier, organic mode
            if fast_sent > 0:
                fast_sent = int(hier_mod.hier_exchange_messages(
                    np, post[i] & ~crashed[i], post[i],
                    uid_hi, uid_lo, n_groups))
            if fast_delivered > 0:
                voters = post[i - 1] & ~crashed[i - 1]
                valid = voters & ~crashed[i]
                fast_delivered = int(hier_mod.hier_exchange_messages(
                    np, valid, pre[i] & ~crashed[i],
                    uid_hi, uid_lo, n_groups))

        ph["fast_vote_sent"] = fast_sent
        ph["fast_vote_delivered"] = fast_delivered
        other_sent = sum(ph[f"{p}_sent"] for p in _PHASES[1:])
        other_delivered = sum(ph[f"{p}_delivered"] for p in _PHASES[1:])
        tk["sent"] = batch_sent + fast_sent + other_sent
        tk["delivered"] = batch_delivered + fast_delivered + other_delivered
        if contested:
            # Scripted fast votes are a px class: always delivered next
            # tick (crash-free), excluded from the dropped ledger.
            tk["dropped"] = prev_batch - batch_delivered
        else:
            tk["dropped"] = ((prev_batch - batch_delivered)
                            + (prev_vote - fast_delivered))
        prev_batch = batch_sent
        prev_vote = fast_sent
        prev_fast = fast_sent
        out_tick.append(tk)
        out_phase.append(ph)
    return out_tick, out_phase
