"""Ring dissemination: O(N) vote counting over the static ring-0 order.

Ring Paxos observes that a fixed ring sustains near-wire atomic-broadcast
throughput because every message makes exactly one lap instead of S*N
unicasts. The engine already carries the per-configuration ring-0
permutation (``state.ring_order`` / ``state.ring_rank`` — mutual
inverses, see ``engine.state``), so the variant is transport-only:

- vote tallies enter the ring in ring-0 position order, accumulate as a
  segmented scan along the lap (``votes.scan_vote_count``), and are read
  back out at each slot's rank — a permutation round trip that is the
  identity on values, so decisions and config ids are bit-identical to
  "rapid";
- cut-report delivery circulates the same way
  (``cut.ring_deliver_reports``);
- the per-tick message factors collapse to "one lap up, one lap down":
  a broadcast-shaped exchange costs 2 sender-units * N recipients
  instead of S * N. ``variants.oracle`` applies the same accounting to
  the host oracle so ``run_variant_differential`` checks the counts
  exactly.
"""
from __future__ import annotations

from rapid_tpu.engine import votes


def ring_count_fast_round(xp, state, vote_hi, vote_lo, valid, n_member,
                          mesh=None):
    """``votes.count_fast_round`` lowered through the ring-0 permutation.

    Votes are gathered into ring-lap order (``ring_order[:, 0]``), tallied
    with the associative-scan kernel (the shape a circulating partial
    tally lowers to), and scattered back through the inverse permutation
    (``ring_rank[:, 0]``). Permuting the inputs permutes the per-slot
    counts identically, and the quorum reductions are permutation
    invariant — bit-identical to the dense path.
    """
    perm = state.ring_order[:, 0]
    inv = state.ring_rank[:, 0]
    counts = votes.scan_vote_count(
        xp, vote_hi[perm], vote_lo[perm], valid[perm], mesh=mesh)[inv]
    quorum = votes.fast_quorum(xp, n_member)
    winner_count = counts.max()
    total = valid.sum().astype(xp.int32)
    return (total >= quorum) & (winner_count >= quorum), winner_count


def ring_pair_factor(xp, any_mask):
    """i32 scalar: the ring variant's sender factor for one exchange.

    Whenever any slot in ``any_mask`` has something to send, the exchange
    costs exactly one aggregation lap plus one dissemination lap — a
    sender factor of 2, independent of how many slots contribute. The
    recipient factor (N) is unchanged, giving the 2N-per-tick count the
    variant-aware oracle reproduces.
    """
    return xp.where(any_mask.any(), 2, 0).astype(xp.int32)
