"""Two-level hierarchical consensus: seeded groups + inter-group verdict.

Fast Raft keeps fast-path quorums small by partitioning nodes into
groups: each group runs its own fast quorum and a compact inter-group
instance settles the global order. Mapped onto the batched engine:

- every slot hashes into one of ``G = max(2, isqrt(capacity))`` seeded
  groups (``group_ids`` — identity-derived, so the partition is stable
  across configurations and reproducible host-side);
- a fast-round announce decides only when at least
  ``fast_quorum(G_nonempty)`` groups each gather intra-group fast
  quorums over their own members (``hier_count_fast_round``);
- message accounting (``hier_exchange_messages``): one intra-group vote
  per voter, an all-to-all verdict round among the live group
  aggregators (``G_live^2``), and one relayed verdict per member.

The hierarchical decide rule is strictly harder than the flat one (a
skewed crash burst can kill one group's quorum while the global 3/4
quorum still holds), so the differential harness only admits scenarios
where both rules agree — ``np_hier_decide`` is the independent host
twin that certifies the envelope. When the fast path fails, the classic
Paxos fallback instance is reused verbatim as the top-level settle
path, so contested scenarios are count-identical to "rapid".
"""
from __future__ import annotations

import math

from rapid_tpu import hashing
from rapid_tpu.engine import votes

#: Seed for the identity -> group hash ("hier" in ASCII).
HIER_GROUP_SEED = 0x68696572


def hier_group_count(capacity: int) -> int:
    """Static number of groups G for a given slot capacity.

    sqrt(C) balances intra-group quorum size against the G^2 verdict
    round; the floor of 2 keeps the two-level structure meaningful (and
    ``fast_quorum`` well-defined) at toy sizes.
    """
    return max(2, math.isqrt(capacity))


def group_ids(xp, uid_hi, uid_lo, n_groups):
    """i32 [C]: each slot's group, hashed from its identity.

    Identity-derived (not slot-index-derived) so the host oracle can
    recompute the partition from endpoint UUIDs alone, and so the
    partition survives slot renumbering across configurations.
    """
    _, lo = hashing.hash64_limbs(xp, uid_hi, uid_lo, seed=HIER_GROUP_SEED)
    return (lo % xp.uint32(n_groups)).astype(xp.int32)


def hier_count_fast_round(xp, member, valid, uid_hi, uid_lo, n_groups,
                          mesh=None):
    """Returns (decided, tally): the two-level fast-round decide rule.

    ``member`` masks the announce-time membership (group sizes), ``valid``
    the delivered votes. Per group g: m_g members, v_g valid votes; the
    group reaches quorum when ``v_g >= fast_quorum(m_g)`` and is
    non-empty. The announce decides when the number of quorate groups
    reaches ``fast_quorum(#non-empty groups)``. ``tally`` is the total
    delivered votes — same gauge the dense path logs as winner_count
    (the crash-fault pipeline is single-proposal, so the winner's count
    is the valid total).
    """
    del mesh  # [G] reductions are tiny; no re-constraint needed.
    gid = group_ids(xp, uid_hi, uid_lo, n_groups)
    onehot = gid[None, :] == xp.arange(n_groups, dtype=xp.int32)[:, None]
    m_g = (onehot & member[None, :]).sum(axis=1).astype(xp.int32)
    v_g = (onehot & valid[None, :]).sum(axis=1).astype(xp.int32)
    group_yes = (v_g >= votes.fast_quorum(xp, m_g)) & (m_g > 0)
    n_live = (m_g > 0).sum().astype(xp.int32)
    decided = group_yes.sum().astype(xp.int32) >= votes.fast_quorum(
        xp, n_live)
    tally = valid.sum().astype(xp.int32)
    return decided, tally


def np_hier_decide(np, member_mask, valid_mask, uid_hi, uid_lo, n_groups):
    """Host twin of ``hier_count_fast_round``'s decide bit, via bincount.

    Written against numpy (passed in as ``np``) with an independent
    reduction (``bincount`` instead of the one-hot matmul) so the
    differential harness's envelope check does not share code with the
    engine kernel it certifies.
    """
    gid = np.asarray(
        group_ids(np, np.asarray(uid_hi, np.uint32),
                  np.asarray(uid_lo, np.uint32), n_groups))
    m_g = np.bincount(gid, weights=np.asarray(member_mask, np.int64),
                      minlength=n_groups).astype(np.int64)
    v_g = np.bincount(gid, weights=np.asarray(valid_mask, np.int64),
                      minlength=n_groups).astype(np.int64)
    quorum_g = m_g - (m_g - 1) // 4
    group_yes = (v_g >= quorum_g) & (m_g > 0)
    n_live = int((m_g > 0).sum())
    need = n_live - (n_live - 1) // 4
    return int(group_yes.sum()) >= need


def hier_exchange_messages(xp, voters, relay_targets, uid_hi, uid_lo,
                           n_groups):
    """i32 scalar: messages for one hierarchical fast-round exchange.

    ``voters`` masks the slots casting intra-group votes (one unicast to
    their group aggregator each), the aggregators of the G_live groups
    holding at least one voter exchange verdicts all-to-all
    (``G_live^2``), and the settled verdict is relayed to every slot in
    ``relay_targets``. The [G, C] broadcast keeps this xp-agnostic so
    ``variants.oracle`` reuses it verbatim with numpy.
    """
    gid = group_ids(xp, uid_hi, uid_lo, n_groups)
    onehot = gid[None, :] == xp.arange(n_groups, dtype=xp.int32)[:, None]
    g_live = (onehot & voters[None, :]).any(axis=1).sum().astype(xp.int32)
    n_votes = voters.sum().astype(xp.int32)
    n_relay = relay_targets.sum().astype(xp.int32)
    return n_votes + g_live * g_live + n_relay
