"""Deterministic single-member replay of a campaign anomaly.

``campaign.py`` folds thousands of clusters into percentiles; the
``triage`` block names the anomalous members as ``(dispatch,
member_index)`` refs. This tool closes the loop: given only a campaign
payload, it reconstructs the *exact* sampled schedule of one member
from the campaign seed (the sampling chain, dispatch pools, and chunk
plan are all bit-deterministic in ``CampaignConfig``), re-runs that one
cluster unbatched — stacked to its pool's program shape so the padded
member program is reproduced bit-for-bit, fleet axis of one — and
emits everything the in-fleet fold threw away: full per-tick
``TickMetrics`` (``--metrics`` JSONL), a Perfetto trace of the
protocol's virtual time (``--trace``), the member's flight-recorder
ring when the campaign ran with one, and an optional host oracle
differential (``--oracle``, with ``--forensics`` naming the divergence
JSONL).

When the member is a triage exemplar, the replay is *verified*: every
field of the exemplar's ``expected`` block — decide ticks, config ids,
counter folds, fallback phase totals, sticky flags — must match the
fresh fold bit-for-bit (exit 1 on any mismatch), proving the replay is
the member the fleet ran, not a lookalike.

CLI::

    python -m rapid_tpu.replay --payload CAMPAIGN.json --member 3:17 \
        --metrics member.jsonl --trace member_trace.json --oracle
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, Optional, Tuple

__all__ = ["replay_member", "main"]


def _find_exemplar(payload: Dict[str, object], dispatch: int,
                   member_index: int) -> Tuple[Optional[str],
                                               Optional[Dict[str, object]]]:
    """Locate the triage exemplar for (dispatch, member_index), if the
    campaign flagged this member; returns (class_name, exemplar)."""
    triage = (payload.get("campaign") or {}).get("triage") or {}
    for name, block in (triage.get("classes") or {}).items():
        for ex in block.get("exemplars", ()):
            if (ex.get("dispatch") == dispatch
                    and ex.get("member_index") == member_index
                    and ex.get("expected") is not None):
                return name, ex
    return None, None


def _diff_blocks(expected: Dict[str, object], replayed: Dict[str, object]
                 ) -> Dict[str, Dict[str, object]]:
    """Field-by-field mismatches between the exemplar's expected block
    and the fresh fold ({} == bit-identical)."""
    out: Dict[str, Dict[str, object]] = {}
    for key in sorted(set(expected) | set(replayed)):
        if expected.get(key) != replayed.get(key):
            out[key] = {"expected": expected.get(key),
                        "replayed": replayed.get(key)}
    return out


def replay_member(payload: Dict[str, object], dispatch: int,
                  member_index: int, *, oracle: bool = False,
                  lineage: bool = False,
                  metrics_path: Optional[str] = None,
                  trace_path: Optional[str] = None,
                  forensics_path: Optional[str] = None
                  ) -> Dict[str, object]:
    """Re-run one campaign member from the payload's campaign block.

    Returns the replay record: member identity (global campaign index,
    kind, mode, seed), the freshly folded ``replayed`` block in the
    exemplar ``expected`` format, the recorder payload (when the
    campaign carried a flight recorder), the exemplar match verdict
    (``match`` is None when the member was not flagged), the member's
    reconstructed lineage span tree when ``lineage`` is set (verified
    against the exemplar's recorded spans when the member was flagged),
    and the oracle differential result when requested.
    """
    import jax

    from rapid_tpu import campaign as campaign_mod
    from rapid_tpu.telemetry import lineage as lineage_lib
    from rapid_tpu.engine import receiver as receiver_mod
    from rapid_tpu.engine import recorder as recorder_mod
    from rapid_tpu.engine.fleet import (fleet_simulate,
                                        lower_receiver_schedule,
                                        receiver_fleet_simulate,
                                        stack_members,
                                        stack_receiver_members)
    from rapid_tpu.faults import ScenarioWeights
    from rapid_tpu.settings import Settings
    from rapid_tpu.telemetry import metrics as metrics_mod
    from rapid_tpu.telemetry.trace import TraceWriter, trace_from_logs

    camp = payload.get("campaign")
    if not camp:
        raise ValueError("payload has no campaign block — replay needs a "
                         "rapid_tpu.campaign artifact")
    for key in ("seed", "clusters", "n", "ticks", "headroom", "weights",
                "fleet_size"):
        if key not in camp:
            raise ValueError(
                f"campaign block lacks {key!r} — replay needs a "
                "schema >= 8 payload (re-run the campaign on this tree)")
    # The wire protocol is campaign identity (schema v11): replaying a
    # ring/hier campaign on the reference engine would fold different
    # message counts. Pre-v11 payloads default to the reference.
    protocol_variant = str(camp.get("protocol_variant", "rapid"))
    cfg = campaign_mod.CampaignConfig(
        clusters=camp["clusters"], n=camp["n"], ticks=camp["ticks"],
        seed=camp["seed"], fleet_size=camp["fleet_size"],
        headroom=camp["headroom"],
        weights=ScenarioWeights(**camp["weights"]),
        per_receiver=camp["per_receiver"]["enabled"],
        flight_recorder=int(camp.get("flight_recorder") or 0),
        protocol_variant=protocol_variant)

    # The deterministic chain, replayed verbatim from run_campaign:
    # sample -> route -> pools -> chunk plan. Same seed, same plan.
    # rx_kernel is echoed in the payload: replaying a packed/pallas
    # campaign on the dense layout would re-lower a different member
    # program and break the bit-identical-fold contract.
    base = Settings()
    rx_kernel = camp["per_receiver"].get("rx_kernel", "xla")
    if rx_kernel != "xla":
        base = base.with_(rx_kernel=rx_kernel)
    if protocol_variant != "rapid":
        base = base.with_(protocol_variant=protocol_variant)
    c = cfg.n + cfg.headroom
    settings = base.with_(capacity=c)
    rx_settings = base.with_(capacity=cfg.n)
    if cfg.flight_recorder:
        settings = settings.with_(flight_recorder_window=cfg.flight_recorder)
        rx_settings = rx_settings.with_(
            flight_recorder_window=cfg.flight_recorder)
    f = max(1, cfg.fleet_size)
    total = -(-cfg.clusters // f) * f
    scenarios = [campaign_mod._sample_scenario(cfg, i)
                 for i in range(total)]
    rx_idx = [i for i, sc in enumerate(scenarios)
              if (cfg.per_receiver and campaign_mod._receiver_eligible(sc))
              or campaign_mod._delay_member(sc)]
    sh_idx = [i for i in range(total) if i not in set(rx_idx)]
    pools = campaign_mod._build_pools(scenarios, sh_idx, rx_idx, f)
    plan = [(pool, chunk) for pool in pools
            for chunk in campaign_mod._chunks(pool["members"],
                                              pool["fleet_size"])]
    if not (0 <= dispatch < len(plan)):
        raise ValueError(f"dispatch {dispatch} out of range: the plan has "
                         f"{len(plan)} dispatches")
    pool, chunk = plan[dispatch]
    if not (0 <= member_index < len(chunk)):
        raise ValueError(
            f"member_index {member_index} out of range: dispatch "
            f"{dispatch} carries {len(chunk)} real members (padded slots "
            "are cycled copies and have no campaign identity)")
    i = chunk[member_index]
    sc = scenarios[i]
    mode, shape = pool["mode"], pool["shape"]

    # One-member fleet stacked to the pool maxima: the member's padded
    # program — window rows, fallback tables, delay-rule planes — is
    # the one the campaign dispatch ran, so the fold is bit-identical,
    # not merely equivalent.
    writer = TraceWriter() if trace_path else None
    rec = None
    if mode == "shared":
        member = campaign_mod._lower_shared(cfg, settings, i, sc)
        fleet = stack_members([member], n_windows=shape[0],
                              n_instances=shape[1], n_pids=shape[2])
        result = fleet_simulate(fleet, cfg.ticks, settings)
        if cfg.flight_recorder:
            finals, logs, recs = result
            rec = recorder_mod.member_recorder(recs, 0)
        else:
            finals, logs = result
        jax.block_until_ready(logs)
        summary = metrics_mod.fleet_summaries(logs)[0]
        mlog = jax.tree_util.tree_map(lambda x: x[0], logs)
        rows = metrics_mod.engine_metrics(mlog)
        import numpy as np
        cid = (int(np.asarray(mlog.config_hi)[-1]) << 32
               | int(np.asarray(mlog.config_lo)[-1]))
        meta = {"flags": 0, "config_ids": [f"{cid:016x}"]}
        lineage_spans = (lineage_lib.fold_spans(
            lineage_lib.engine_phase_columns(mlog)) if lineage else None)
        if writer is not None:
            trace_from_logs(mlog, settings, writer=writer)
    else:
        member = lower_receiver_schedule(sc.schedule, rx_settings,
                                         fleet_size=1)
        fleet = stack_receiver_members([member], n_windows=shape[0],
                                       n_delay_rules=shape[1])
        result = receiver_fleet_simulate(fleet, cfg.ticks, rx_settings)
        if cfg.flight_recorder:
            finals, logs, recs = result
            rec = recorder_mod.member_recorder(recs, 0)
        else:
            finals, logs = result
        jax.block_until_ready(logs)
        import numpy as np
        # Packed fleets return PackedReceiverState finals; the view shim
        # unpacks the handful of fields the fold reads (no-op on dense).
        mrs = receiver_mod.receiver_final_view(
            jax.tree_util.tree_map(lambda x: x[0], finals))
        mlog = jax.tree_util.tree_map(lambda x: x[0], logs)
        run = receiver_mod.receiver_run_payload(mrs, mlog, cfg.n,
                                                cfg.ticks)
        rows = run.metrics()
        summary = metrics_mod.summarize(rows)
        cids = sorted(set(receiver_mod.receiver_config_ids(mrs)[:cfg.n]))
        meta = {"flags": int(np.asarray(mrs.flags)),
                "config_ids": [f"{x:016x}" for x in cids]}
        lineage_spans = None
        if lineage:
            # Exactly the campaign's per-receiver fold: spans from the
            # member's own counters, critical path attributed with the
            # host delay rule when the schedule carries one.
            lineage_spans = lineage_lib.fold_spans(
                lineage_lib.receiver_phase_columns(mlog))
            if sc.schedule.delays:
                for sp in lineage_spans:
                    sp["critical_path"] = lineage_lib.receiver_critical_path(
                        mlog, sp, sc.schedule)

    replayed = campaign_mod._expected_block(summary, meta)
    recorder_payload = (recorder_mod.recorder_payload(rec)
                        if rec is not None else None)

    if metrics_path:
        metrics_mod.write_jsonl(rows, metrics_path)
    if writer is not None:
        writer.write(trace_path)

    cls, exemplar = _find_exemplar(payload, dispatch, member_index)
    mismatches = None
    recorder_match = None
    lineage_match = None
    if exemplar is not None:
        mismatches = _diff_blocks(exemplar["expected"], replayed)
        if exemplar.get("recorder") is not None \
                and recorder_payload is not None:
            recorder_match = exemplar["recorder"] == recorder_payload
        if lineage_spans is not None \
                and exemplar.get("lineage") is not None:
            lineage_match = exemplar["lineage"] == lineage_spans

    oracle_block = None
    if oracle:
        oracle_block = {"run": False, "passed": None, "error": None,
                        "artifact": None}
        if protocol_variant != "rapid":
            oracle_block["error"] = (
                "oracle referee replays the reference protocol only; "
                "variant exactness lives in "
                "engine.diff.run_variant_differential")
        elif sc.wants_churn:
            oracle_block["error"] = ("oracle referee replays fault "
                                     "surfaces only; churn members are "
                                     "ineligible")
        else:
            from rapid_tpu.engine.diff import (
                run_adversarial_differential, run_receiver_differential)
            from rapid_tpu.telemetry.forensics import DivergenceError

            referee_settings = base.with_(capacity=0)
            runner = run_receiver_differential if mode == "per_receiver" \
                else run_adversarial_differential
            oracle_block["run"] = True
            try:
                res = runner(sc.schedule, cfg.ticks, referee_settings)
                res.assert_identical(artifact=forensics_path)
                oracle_block["passed"] = True
            except (DivergenceError,
                    receiver_mod.ReceiverEnvelopeError) as err:
                oracle_block["passed"] = False
                oracle_block["error"] = str(err).splitlines()[0]
                oracle_block["artifact"] = forensics_path

    return {
        "record": "replay",
        "dispatch": dispatch,
        "member_index": member_index,
        "member": i,
        "kind": sc.kind,
        "mode": mode,
        "seed": campaign_mod._member_seed(cfg, i),
        "ticks": cfg.ticks,
        "n": cfg.n,
        "replayed": replayed,
        "recorder": recorder_payload,
        "triage_class": cls,
        "match": (not mismatches) if mismatches is not None else None,
        "mismatches": mismatches or None,
        "recorder_match": recorder_match,
        "lineage": lineage_spans,
        "lineage_match": lineage_match,
        "oracle": oracle_block,
    }


def _parse_member(text: str) -> Tuple[int, int]:
    d, _, j = text.partition(":")
    try:
        return int(d), int(j)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"--member wants DISPATCH:MEMBER_INDEX (e.g. 3:17), got "
            f"{text!r}")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Replay one campaign member deterministically from "
                    "its payload (see rapid_tpu/replay.py docstring)")
    parser.add_argument("--payload", required=True, metavar="FILE",
                        help="campaign JSON artifact (schema >= 8, "
                             "written by python -m rapid_tpu.campaign "
                             "--out)")
    parser.add_argument("--member", required=True, type=_parse_member,
                        metavar="D:I",
                        help="dispatch index and member index within "
                             "that dispatch, as shown in triage "
                             "exemplar refs")
    parser.add_argument("--metrics", type=str, default=None, metavar="FILE",
                        help="write the member's full per-tick "
                             "TickMetrics stream as JSONL")
    parser.add_argument("--trace", type=str, default=None, metavar="FILE",
                        help="write a Perfetto trace of the member's "
                             "protocol virtual time (shared-state "
                             "members only)")
    parser.add_argument("--forensics", type=str, default=None,
                        metavar="FILE",
                        help="divergence JSONL artifact path for "
                             "--oracle (written only on divergence)")
    parser.add_argument("--oracle", action="store_true",
                        help="also replay the schedule through the host "
                             "oracle referee and report the differential")
    parser.add_argument("--lineage", action="store_true",
                        help="reconstruct the member's lineage span tree "
                             "(phase boundaries, durations, critical "
                             "path) and verify it against the exemplar's "
                             "recorded spans when the member was flagged")
    parser.add_argument("--out", type=str, default=None, metavar="FILE",
                        help="write the replay record JSON here too")
    args = parser.parse_args(argv)

    with open(args.payload) as fh:
        payload = json.load(fh)
    dispatch, member_index = args.member
    record = replay_member(payload, dispatch, member_index,
                           oracle=args.oracle, lineage=args.lineage,
                           metrics_path=args.metrics,
                           trace_path=args.trace,
                           forensics_path=args.forensics)
    if args.out:
        from rapid_tpu.telemetry import write_json_artifact

        write_json_artifact(args.out, record, indent=2)
    print(json.dumps(record), flush=True)
    failed = (record["match"] is False
              or record["recorder_match"] is False
              or record["lineage_match"] is False
              or (record["oracle"] or {}).get("passed") is False)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
