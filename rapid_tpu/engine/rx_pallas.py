"""Hand-written pallas kernel for the receiver deliver/aggregate loop.

``receiver_step`` calls ``_account`` once per delivery group — the hot
loop of the per-receiver engine: elementwise bool algebra over ``[C, C]``
message planes plus three full-plane popcount reductions. Under
``Settings.rx_kernel = "pallas"`` that loop runs here instead, over
*packed* operands:

- ``pm``   uint8 ``[C, ceil(C/8)]`` — the message plane, packed
  little-endian along the dst axis (bit ``d`` of byte ``b`` in row ``s``
  is ``msgs[s, 8*b + d]``);
- ``pe``   uint8 ``[C, ceil(C/8)]`` — the blocked-edge plane from
  ``monitor.link_blocked_packed`` (same layout; no dense ``[C, C]``
  reachability plane is ever materialized on this path);
- ``src``  uint8 ``[C, 1]`` — 0xFF where the sender is alive, else 0
  (a crashed *sender* kills its whole row);
- ``pd``   uint8 ``[1, ceil(C/8)]`` — the alive-receiver bitmask
  (a crashed *receiver* kills its column).

The kernel computes ``ok = pm & src & pd`` then splits it against the
blocked plane — ``deliv = ok & ~pe``, ``linkd = ok & pe`` — and reduces
per-row popcounts with the classic SWAR ladder (add-shift-mask, no
lookup table: uint8 lanes stay uint8-wide in VMEM). One fused pass,
bitwise ops over packed uint8 tiles — exactly the shape pallas wins on.

Exactness: the padding bits (when C % 8 != 0) are provably zero in every
operand (``packbits`` zero-pads; the blocked plane inherits zero pads
from its dst packbits), so ``deliv``'s pads are zero and the popcounts
equal the dense ``.sum()`` counts bit-for-bit; ``dropped`` is recovered
as ``popcount(pm) - popcount(deliv)`` (valid because ``deliv`` is a
subset of ``pm``), matching the dense ``(msgs & ~deliv).sum()``. All
counts are int32, the dense ``_account`` dtypes.

CI story: off-TPU the kernel runs under ``interpret=True`` (pallas
lowers it with jax ops, still one traced call site), so tier-1 exercises
the exact kernel program bit-for-bit on CPU; on TPU it compiles to
Mosaic. The jaxpr guard in ``tests/test_rx_packed.py`` pins that the
kernel's own jaxpr contains no dense ``[C, C]`` intermediate and that
``rx_kernel = "xla"`` traces zero pallas calls.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _popcount_rows(bytes_u8):
    """Per-row popcount of a uint8 plane via the SWAR ladder."""
    v = bytes_u8
    v = v - ((v >> 1) & 0x55)
    v = (v & 0x33) + ((v >> 2) & 0x33)
    v = (v + (v >> 4)) & 0x0F
    return v.astype(jnp.int32).sum(axis=1)


def _account_kernel(pm_ref, pe_ref, src_ref, pd_ref, dv_ref, cnt_ref):
    pm = pm_ref[...]
    ok = pm & src_ref[...] & pd_ref[...]
    pe = pe_ref[...]
    dv = ok & ~pe
    dv_ref[...] = dv
    pad = jnp.zeros(pm.shape[:1], jnp.int32)
    cnt_ref[...] = jnp.stack(
        [_popcount_rows(pm), _popcount_rows(dv), _popcount_rows(ok & pe),
         pad], axis=1)


def account(msgs, crashed, pemat):
    """Packed-plane twin of ``receiver._account``: delivery mask plus
    (delivered, dropped, link_dropped) int32 counts, bit-identical to the
    dense path. ``pemat`` is the packed blocked plane
    (``monitor.link_blocked_packed``)."""
    c = msgs.shape[0]
    pm = jnp.packbits(msgs, axis=-1, bitorder="little")
    src = jnp.where(crashed, jnp.uint8(0), jnp.uint8(0xFF))[:, None]
    pdst = jnp.packbits(~crashed, bitorder="little")[None, :]
    cb = pm.shape[1]
    dv_p, cnt = pl.pallas_call(
        _account_kernel,
        out_shape=(jax.ShapeDtypeStruct((c, cb), jnp.uint8),
                   jax.ShapeDtypeStruct((c, 4), jnp.int32)),
        interpret=_interpret(),
    )(pm, pemat, src, pdst)
    deliv = jnp.unpackbits(dv_p, axis=-1, count=c,
                           bitorder="little").astype(bool)
    total = cnt[:, 0].sum()
    delivered = cnt[:, 1].sum()
    linkd = cnt[:, 2].sum()
    return deliv, delivered, total - delivered, linkd
