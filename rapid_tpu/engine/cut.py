"""Cut-detection kernel: L/H watermark crossings as elementwise reductions.

Mirrors ``MultiNodeCutDetector._aggregate`` over the whole membership at
once. The oracle's per-report bookkeeping reduces to three facts about the
per-destination distinct-ring report counts:

- ``pre_proposal``  (in flux)  == destinations with count in ``[L, H)``;
- ``proposal``                 == destinations with count ``>= H``;
- a proposal is emitted exactly at an H-crossing while no destination is
  in flux (``updates_in_progress == 0``).

Counts only change when reports arrive, and within a delivery tick every
alive receiver processes the identical alert stream (crash-fault envelope,
see ``state``), so evaluating the three conditions on the end-of-tick
counts reproduces the sequential detector's emission tick and contents.

Destinations are members (DOWN alerts: crashes and graceful leaves) or
dormant joiner slots (UP alerts from their gatekeepers); the reporter for
``(dst, ring)`` is ``obs_idx[dst, ring]`` for members and ``gk_idx`` for
joiners — the oracle's ``get_observers_of`` vs
``get_expected_observers_of`` split (MultiNodeCutDetector.java).

``invalidate_failing_edges`` is the fixpoint of: for every in-flux
destination, each ring whose observer is itself in (pre-)proposal (count
``>= L``) is implicitly reported. The oracle iterates this once per
received batch — and only once a link-DOWN event has been seen in the
current configuration (``_seen_link_down_events``), which the
``seen_down`` latch mirrors; monotone counts make the end-of-tick
fixpoint land in the same place (the differential harness enforces it).
"""
from __future__ import annotations

from jax import lax

from rapid_tpu.engine import sharding
from rapid_tpu.engine.state import EngineState


def deliver_reports(xp, state: EngineState, src_alive):
    """bool [C, K]: monitor DOWN reports landing in the detector this tick.

    ``pending_deliver[obs, j]`` says observer ``obs`` reported its ring-j
    subject two ticks ago; re-index to (destination, ring) via ``obs_idx``
    (the ring-j observer of dst is the unique reporter for (dst, j)) and
    mask batches whose sender crashed before delivery — the virtual network
    drops a message when its source is crashed at delivery time.
    """
    by_dst = xp.take_along_axis(state.pending_deliver, state.obs_idx, axis=0)
    return by_dst & src_alive[state.obs_idx]


def ring_deliver_reports(xp, state: EngineState, src_alive):
    """bool [C, K]: ``deliver_reports`` lowered through the static ring-0
    permutation — the ring dissemination variant's cut-delivery kernel.

    Instead of every observer unicasting its report to every receiver,
    contributions enter the ring in ring-0 position order (one token per
    slot), circulate one lap, and are read back out at each observer's
    rank. ``ring_order[:, 0]`` and ``ring_rank[:, 0]`` are inverse
    permutations (``ring_order[ring_rank[s, 0], 0] == s``), so gathering
    through the round trip is the identity on values: the result is
    bit-identical to ``deliver_reports`` while the lowering — and the
    O(N) per-tick message count the variant-aware oracle checks — is the
    ring's. Churn-report delivery (``deliver_churn_reports``) stays
    dense: join/leave batches are rare and already O(K) per event.
    """
    contrib = state.pending_deliver & src_alive[:, None]
    token = contrib[state.ring_order[:, 0]]
    by_slot = token[state.ring_rank[:, 0]]
    by_dst = xp.take_along_axis(by_slot, state.obs_idx, axis=0)
    return by_dst


def deliver_churn_reports(xp, state: EngineState, src_alive):
    """(down, up) bool [C, K]: churn-pipeline reports landing this tick.

    ``churn_deliver[dst]`` says dst's scheduled join/leave alert batch was
    flushed last tick: a graceful leave reaches dst's K observers (one
    LeaveMessage each, so every ring reports), a join is enqueued at dst's
    K gatekeepers with their ring numbers. Per-ring sources are
    ``obs_idx`` for members (leavers), ``gk_idx`` for dormant joiners;
    rings whose source crashed before the batch delivery are dropped,
    exactly like the monitor path.
    """
    src = xp.where(state.member[:, None], state.obs_idx, state.gk_idx)
    ok = state.churn_deliver[:, None] & src_alive[src]
    down = ok & state.member[:, None]
    up = ok & ~state.member[:, None]
    return down, up


def aggregate(xp, state: EngineState, delivered_down, delivered_up,
              any_receiver, settings, mesh=None):
    """Apply one tick of reports; returns (reports, seen_down,
    announce_now, proposal, explicit_added, implicit_added).

    ``any_receiver`` gates on an alive node existing to process the batch
    (the shared detector stands in for every alive receiver's copy).
    ``delivered_down`` are DOWN alerts (valid only for member dsts),
    ``delivered_up`` UP alerts (valid only for non-member dsts) — the
    oracle's ``_filter_alert`` presence checks. ``explicit_added`` counts
    report cells filled by delivered alerts this tick, ``implicit_added``
    the cells filled by the edge-invalidation fixpoint (telemetry gauges;
    neither feeds back into the protocol state).

    ``mesh`` (static) partitions the capacity axis of the ``[C, K]``
    report matrix across devices: the fixpoint's ``lax.while_loop``
    carry is re-constrained every iteration so the per-destination count
    reduction and the mask algebra stay sharded — only the
    ``obs_in_sets`` gather crosses device boundaries.
    """
    lo, hi = settings.L, settings.H
    c = state.member.shape[0]
    gate = any_receiver & ~state.announced
    new_down = delivered_down & state.member[:, None] & gate
    new_up = delivered_up & ~state.member[:, None] & gate
    new = new_down | new_up
    explicit_added = (new & ~state.reports).sum().astype(xp.int32)
    reports = state.reports | new
    seen_down = state.seen_down | new_down.any()
    any_new = new.any()

    eff_obs = xp.where(state.member[:, None], state.obs_idx, state.gk_idx)

    def fix_body(r):
        counts = r.sum(axis=1)
        flux = (counts >= lo) & (counts < hi)
        obs_in_sets = (counts >= lo)[eff_obs]
        add = flux[:, None] & obs_in_sets & ~r
        return sharding.constrain(r | add, mesh, c)

    def fixpoint(r):
        def body(carry):
            r_cur, _ = carry
            r_next = fix_body(r_cur)
            return r_next, (r_next != r_cur).any()

        r_final, _ = lax.while_loop(lambda c: c[1], body,
                                    (r, xp.asarray(True)))
        return r_final

    # Only iterate the fixpoint on ticks that actually delivered reports,
    # and only once a DOWN alert has been seen in this configuration (the
    # oracle runs invalidate per batch receipt, gated on
    # ``_seen_link_down_events`` — pure join traffic never invalidates).
    pre_fixpoint = reports.sum().astype(xp.int32)
    reports = lax.cond(any_new & seen_down, fixpoint, lambda r: r, reports)
    implicit_added = reports.sum().astype(xp.int32) - pre_fixpoint

    counts = reports.sum(axis=1)
    in_flux = ((counts >= lo) & (counts < hi)).any()
    crossed = counts >= hi
    announce_now = any_new & ~in_flux & crossed.any() & ~state.announced
    return (reports, seen_down, announce_now, crossed,
            explicit_added, implicit_added)


def receiver_aggregate(xp, reports, member, obs_full, delivered_down,
                       gate, seen_down, settings):
    """Per-receiver ``aggregate``: every slot runs its own detector copy.

    ``reports``/``delivered_down`` are ``[C, C, K]`` (receiver, dst, ring),
    ``member``/``obs_full`` the per-receiver view and observer tables,
    ``gate``/``seen_down`` ``[C]``. The invalidation fixpoint is ONE global
    ``lax.while_loop`` over the full tensor with per-receiver add gating
    (ungated rows are fixed points), so divergent receivers don't trace
    per-slot control flow. Returns
    ``(reports, seen_down, any_new, in_flux, crossed)`` with the announce
    decision left to the caller (it also needs the announced latch).
    """
    lo, hi = settings.L, settings.H
    c = member.shape[0]
    new = delivered_down & member[:, :, None] & gate[:, None, None]
    reports = reports | new
    any_new = new.any(axis=(1, 2))
    seen_down = seen_down | any_new
    fix_gate = any_new & seen_down
    ridx = xp.arange(c, dtype=xp.int32)[:, None, None]

    def fix_body(r):
        counts = r.sum(axis=2)
        flux = (counts >= lo) & (counts < hi)
        obs_in_sets = (counts >= lo)[ridx, obs_full]
        add = flux[:, :, None] & obs_in_sets & ~r & fix_gate[:, None, None]
        return r | add

    def body(carry):
        r_cur, _ = carry
        r_next = fix_body(r_cur)
        return r_next, (r_next != r_cur).any()

    reports = lax.cond(
        fix_gate.any(),
        lambda r: lax.while_loop(lambda cr: cr[1], body,
                                 (r, xp.asarray(True)))[0],
        lambda r: r,
        reports)

    counts = reports.sum(axis=2)
    in_flux = ((counts >= lo) & (counts < hi)).any(axis=1)
    crossed = counts >= hi
    return reports, seen_down, any_new, in_flux, crossed
