"""Cut-detection kernel: L/H watermark crossings as elementwise reductions.

Mirrors ``MultiNodeCutDetector._aggregate`` over the whole membership at
once. The oracle's per-report bookkeeping reduces to three facts about the
per-destination distinct-ring report counts:

- ``pre_proposal``  (in flux)  == destinations with count in ``[L, H)``;
- ``proposal``                 == destinations with count ``>= H``;
- a proposal is emitted exactly at an H-crossing while no destination is
  in flux (``updates_in_progress == 0``).

Counts only change when reports arrive, and within a delivery tick every
alive receiver processes the identical alert stream (crash-fault envelope,
see ``state``), so evaluating the three conditions on the end-of-tick
counts reproduces the sequential detector's emission tick and contents.

``invalidate_failing_edges`` is the fixpoint of: for every in-flux
destination, each ring whose observer is itself in (pre-)proposal (count
``>= L``) is implicitly reported. The oracle iterates this once per
received batch; monotone counts make the end-of-tick fixpoint land in the
same place (the differential harness enforces it).
"""
from __future__ import annotations

from jax import lax

from rapid_tpu.engine.state import EngineState


def deliver_reports(xp, state: EngineState, src_alive):
    """bool [C, K]: reports landing in the detector this tick.

    ``pending_deliver[obs, j]`` says observer ``obs`` reported its ring-j
    subject two ticks ago; re-index to (destination, ring) via ``obs_idx``
    (the ring-j observer of dst is the unique reporter for (dst, j)) and
    mask batches whose sender crashed before delivery — the virtual network
    drops a message when its source is crashed at delivery time.
    """
    by_dst = xp.take_along_axis(state.pending_deliver, state.obs_idx, axis=0)
    return by_dst & src_alive[state.obs_idx]


def aggregate(xp, state: EngineState, delivered, any_receiver, settings):
    """Apply one tick of reports; returns (reports, announce_now, proposal).

    ``any_receiver`` gates on an alive node existing to process the batch
    (the shared detector stands in for every alive receiver's copy).
    """
    lo, hi = settings.L, settings.H
    gate = any_receiver & ~state.announced
    new = delivered & state.member[:, None] & gate
    reports = state.reports | new
    any_new = new.any()

    def fix_body(r):
        counts = r.sum(axis=1)
        flux = (counts >= lo) & (counts < hi)
        obs_in_sets = (counts >= lo)[state.obs_idx]
        add = flux[:, None] & obs_in_sets & ~r
        return r | add

    def fixpoint(r):
        def body(carry):
            r_cur, _ = carry
            r_next = fix_body(r_cur)
            return r_next, (r_next != r_cur).any()

        r_final, _ = lax.while_loop(lambda c: c[1], body,
                                    (r, xp.asarray(True)))
        return r_final

    # Only iterate the fixpoint on ticks that actually delivered reports
    # (the oracle runs invalidate only on batch receipt).
    reports = lax.cond(any_new, fixpoint, lambda r: r, reports)

    counts = reports.sum(axis=1)
    in_flux = ((counts >= lo) & (counts < hi)).any()
    crossed = (counts >= hi) & state.member
    announce_now = any_new & ~in_flux & crossed.any() & ~state.announced
    return reports, announce_now, crossed
