"""Adversary engine: exact replay of unscripted fault schedules.

The fleet engine (``engine.step``) lowers the whole cluster onto one
shared membership view per tick — ideal for the jitted steady-state, but
its planner (``engine.paxos.plan_fallback`` / ``engine.churn``) used to
*pre-reject* any schedule whose behaviour the shared view cannot carry:
asymmetric partitions that leave nodes with divergent views, tied or
mid-fast-count fallback timers, crash bursts whose alerts straddle a
view change. This module lifts that envelope: it executes an arbitrary
seeded :class:`rapid_tpu.faults.AdversarySchedule` with **per-node**
protocol state — per-slot membership views and config epochs, per-slot
cut-detector report tables, per-slot Fast Paxos instances with organic
jittered fallback timers — and reproduces the oracle bit-for-bit with no
scenario screening at all.

Exactness comes from replaying the oracle's two global orderings rather
than deriving them:

- ``SimNetwork`` delivers every message in-flight for a tick in global
  send-sequence order (``sorted(in_flight.pop(t))``), so the engine
  stamps each send with a global sequence number and delivers in that
  order;
- ``SimScheduler`` runs due jobs in global registration-handle order, so
  the engine allocates handles at the same points (per-node FD jobs then
  the alert batcher at boot, scripted proposes afterwards, fallback
  timers at propose time) and pops them identically.

Everything else is slot-indexed protocol state in host python/numpy:
identities, ring keys, and config ids reuse the shared
``rapid_tpu.hashing`` kernels (the same limb math the jitted topology
kernel uses), link windows evaluate through the same
:class:`rapid_tpu.faults.LinkWindow` normal form the jitted step's mask
kernels consume, and the per-tick gauge definitions match
``engine.monitor.partitioned_edge_count``. The tick loop is
host-orchestrated; lowering the per-node state onto a ``lax.scan`` with
a ``[C]`` epoch axis is the fleet-mode follow-up tracked in ROADMAP.md.

``engine.diff.run_adversarial_differential`` drives this engine and the
oracle from the same schedule and asserts per-slot view events, per-tick
message counters, per-phase consensus traffic, and final per-slot
configuration ids are identical.
"""
from __future__ import annotations

import heapq
import itertools
import math
import random
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

import numpy as np

from rapid_tpu import hashing
from rapid_tpu.faults import AdversarySchedule, ScriptedPropose, \
    delay_of_slots, validate_schedule
from rapid_tpu.settings import DEFAULT_SETTINGS, Settings

MASK64 = hashing.MASK64

#: Shared identity-seed constants (the oracle's membership_view and the
#: jitted topology kernel hash with the same ones).
_SEED_MEMBER = 0x6D656D62
_SEED_RANK = 0x72616E6B

#: Wire message kind -> consensus phase counter name (batches and probes
#: carry no phase).
_PHASE_OF = {
    "vote": "fast_vote",
    "1a": "phase1a",
    "1b": "phase1b",
    "2a": "phase2a",
    "2b": "phase2b",
}

#: Message counter keys, matching the oracle's ``NetworkCounters``.
COUNTER_KEYS = ("sent", "delivered", "dropped", "timeouts",
                "probes_sent", "probes_failed")

#: Per-phase counter keys, matching ``SimNetwork.consensus_history``.
PHASE_KEYS = tuple(f"{p}_{d}" for p in
                   ("fast_vote", "phase1a", "phase1b", "phase2a", "phase2b")
                   for d in ("sent", "delivered"))


def adversary_rngs(seed: int, n: int) -> List[random.Random]:
    """Per-slot jitter rngs; both differential sides build the same list
    (the oracle's default per-cluster rng hashes object ids, so the
    harness must inject these explicitly)."""
    return [random.Random(seed * 1000003 + slot) for slot in range(n)]


class AdversaryExecutionError(RuntimeError):
    """A schedule drove the protocol somewhere the oracle itself would
    crash (e.g. a decided proposal removing an already-removed node)."""


class _PaxosInstance:
    """One Fast Paxos instance: slot-indexed mirror of the per-config
    consensus state (``oracle.paxos``). Ranks are ``(round, node_index)``
    tuples — the same lexicographic order as the oracle's ``Rank``."""

    __slots__ = ("node", "cfg", "n", "rnd", "vrnd", "vval", "crnd", "cval",
                 "p1b", "p2b", "px_decided", "fp_decided",
                 "votes_received", "votes_per_proposal", "timer_handle")

    def __init__(self, node: int, cfg: int, n: int) -> None:
        self.node = node
        self.cfg = cfg
        self.n = n
        self.rnd = (0, 0)
        self.vrnd = (0, 0)
        self.vval: Tuple[int, ...] = ()
        self.crnd = (0, 0)
        self.cval: Tuple[int, ...] = ()
        self.p1b: Dict[int, Tuple[Tuple[int, int], Tuple[int, ...]]] = {}
        self.p2b: Dict[Tuple[int, int], Dict[int, Tuple[int, ...]]] = {}
        self.px_decided = False
        self.fp_decided = False
        self.votes_received: Set[int] = set()
        self.votes_per_proposal: Dict[Tuple[int, ...], int] = {}
        self.timer_handle: Optional[int] = None


class _Node:
    """Per-slot membership service state: own view + config epoch, own
    cut-detector tables, own alert pipeline, own consensus instance."""

    __slots__ = ("member_key", "memsum", "cfg", "stopped", "announced",
                 "queue", "last_enq", "bcast", "reports", "pre", "prop",
                 "updates", "seen_down", "fds", "fd_jobs", "batcher_job",
                 "px")

    def __init__(self, member_key: FrozenSet[int], memsum: int,
                 cfg: int) -> None:
        self.member_key = member_key
        self.memsum = memsum
        self.cfg = cfg
        self.stopped = False
        self.announced = False
        self.queue: List[Tuple[int, int, int, Tuple[int, ...]]] = []
        self.last_enq = -1
        self.bcast: List[int] = []
        self.reports: Dict[int, Dict[int, int]] = {}
        self.pre: Dict[int, None] = {}
        self.prop: Dict[int, None] = {}
        self.updates = 0
        self.seen_down = False
        self.fds: List[dict] = []
        self.fd_jobs: List[dict] = []
        self.batcher_job: Optional[dict] = None
        self.px: Optional[_PaxosInstance] = None


@dataclass
class AdversaryRun:
    """Everything the adversarial differential compares.

    ``events_by_slot[r]`` holds ``(tick, kind, config_id, slots)`` tuples
    (kind in {"proposal", "view_change"}, slots ascending); counters and
    phase histories carry per-tick deltas starting at tick 1.
    """

    n: int
    n_ticks: int
    events_by_slot: List[List[Tuple[int, str, int, Tuple[int, ...]]]]
    tick_history: List[Dict[str, int]]
    phase_history: List[Dict[str, int]]
    partitioned_edges: List[int]
    link_dropped: List[int]
    config_ids: List[int]
    members_by_slot: List[FrozenSet[int]]
    stopped: List[bool]
    totals: Dict[str, int] = field(default_factory=dict)
    phase_totals: Dict[str, int] = field(default_factory=dict)

    def metrics(self) -> List:
        """Normalize into ``telemetry.metrics.TickMetrics`` rows (engine
        source) so forensics reports can name the fault context —
        partitioned-edge and link-drop gauges — of a divergent tick."""
        from rapid_tpu.telemetry.metrics import TickMetrics

        ann = {e[0] for evs in self.events_by_slot for e in evs
               if e[1] == "proposal"}
        dec = {e[0] for evs in self.events_by_slot for e in evs
               if e[1] == "view_change"}
        out = []
        for i, c in enumerate(self.tick_history):
            tick = i + 1
            px = self.phase_history[i]
            out.append(TickMetrics(
                tick=tick, source="engine", **c,
                partitioned_edges=self.partitioned_edges[i],
                link_dropped=self.link_dropped[i],
                px_fast_vote_sent=px["fast_vote_sent"],
                px_phase1a_sent=px["phase1a_sent"],
                px_phase1b_sent=px["phase1b_sent"],
                px_phase2a_sent=px["phase2a_sent"],
                px_phase2b_sent=px["phase2b_sent"],
                announce=tick in ann, decide=tick in dec))
        return out


class AdversaryEngine:
    """Slot-indexed executor of one :class:`AdversarySchedule`.

    ``uids`` are the 64-bit node identities in slot order and
    ``id_fp_sum`` the (removal-invariant) identifier fingerprint sum —
    both supplied by the harness so this module never imports the
    oracle. All protocol state lives in slot coordinates.
    """

    def __init__(self, schedule: AdversarySchedule, uids: Sequence[int],
                 id_fp_sum: int, settings: Optional[Settings] = None) -> None:
        validate_schedule(schedule)
        if len(uids) != schedule.n:
            raise ValueError("uids must cover the schedule universe")
        self.schedule = schedule
        self.settings = settings or DEFAULT_SETTINGS
        self.n = schedule.n
        self.k = self.settings.K
        self.uids = [int(u) & MASK64 for u in uids]
        self.id_fp_sum = int(id_fp_sum) & MASK64
        self.memfp = [hashing.hash64(u, seed=_SEED_MEMBER)
                      for u in self.uids]
        self.rank_idx = [hashing.hash64(u, seed=_SEED_RANK) & 0x7FFFFFFF
                         for u in self.uids]
        self.ringkey = [[hashing.hash64(u, seed=k) for k in range(self.k)]
                        for u in self.uids]
        self.rngs = adversary_rngs(schedule.seed, self.n)
        self.crash_ticks = schedule.crash_tick_array()

        # replicated scheduler + wire
        self.now = 0
        self._heap: List[Tuple[int, int, tuple]] = []
        self._hseq = itertools.count()
        self._cancelled: Set[int] = set()
        self._wire: Dict[int, List[tuple]] = {}
        self._wseq = itertools.count()

        self.counters = dict.fromkeys(COUNTER_KEYS, 0)
        self.phase_counters = dict.fromkeys(PHASE_KEYS, 0)
        self.tick_history: List[Dict[str, int]] = []
        self.phase_history: List[Dict[str, int]] = []
        self.part_edges_history: List[int] = []
        self.link_dropped_history: List[int] = []
        self.events: List[List[tuple]] = [[] for _ in range(self.n)]

        self._topo_cache: Dict[FrozenSet[int], dict] = {}
        self._E: Optional[np.ndarray] = None
        self._crashed_now: Optional[np.ndarray] = None
        self._link_dropped_tick = 0

        self.nodes: List[_Node] = []
        self._boot()

    # -- identity / topology -------------------------------------------------

    def _r0key(self, slot: int) -> Tuple[int, int]:
        """View-independent global ring-0 sort key (proposal ordering)."""
        return (self.ringkey[slot][0], self.uids[slot])

    def _config_id(self, memsum: int) -> int:
        return hashing.splitmix64(
            (hashing.splitmix64(self.id_fp_sum) + memsum) & MASK64)

    def _rings(self, member_key: FrozenSet[int]) -> dict:
        """Per-view ring topology: K-ring subject/observer tables plus the
        ring-0 broadcast order. Same sort key as the jitted topology
        kernel: (hash64(uid, seed=ring), uid)."""
        topo = self._topo_cache.get(member_key)
        if topo is not None:
            return topo
        members = sorted(member_key)
        subj: Dict[int, List[int]] = {}
        obs: Dict[int, List[int]] = {}
        if len(members) >= 2:
            for k in range(self.k):
                order = sorted(members,
                               key=lambda s: (self.ringkey[s][k],
                                              self.uids[s]))
                pos = {s: i for i, s in enumerate(order)}
                for s in members:
                    i = pos[s]
                    subj.setdefault(s, [0] * self.k)[k] = \
                        order[(i - 1) % len(order)]
                    obs.setdefault(s, [0] * self.k)[k] = \
                        order[(i + 1) % len(order)]
        ring0 = sorted(members,
                       key=lambda s: (self.ringkey[s][0], self.uids[s]))
        topo = {"subj": subj, "obs": obs, "ring0": ring0}
        self._topo_cache[member_key] = topo
        return topo

    # -- replicated scheduler ------------------------------------------------

    def _schedule(self, delay: int, task: tuple) -> int:
        handle = next(self._hseq)
        heapq.heappush(self._heap, (self.now + max(0, delay), handle, task))
        return handle

    def _schedule_periodic(self, interval: int, task: tuple) -> dict:
        job = {"cancelled": False, "interval": interval, "task": task}
        self._schedule(interval - (self.now % interval), ("periodic", job))
        return job

    def _run_due(self) -> None:
        while self._heap and self._heap[0][0] <= self.now:
            _, handle, task = heapq.heappop(self._heap)
            if handle in self._cancelled:
                self._cancelled.discard(handle)
                continue
            self._dispatch(task)

    def _dispatch(self, task: tuple) -> None:
        kind = task[0]
        if kind == "periodic":
            job = task[1]
            inner = job["task"]
            r = inner[1]
            if job["cancelled"] or self.nodes[r].stopped:
                return
            if inner[0] == "fd":
                self._fd_tick(r, inner[2])
            else:
                self._batcher_tick(r)
            self._schedule(job["interval"], ("periodic", job))
        elif kind == "timer":
            px = task[1]
            if not px.fp_decided:
                self._start_phase1a(px, 2)
        elif kind == "scripted":
            p: ScriptedPropose = task[1]
            ordered = tuple(sorted(p.proposal, key=self._r0key))
            self._propose(p.slot, self.nodes[p.slot], ordered,
                          p.delay_ticks)

    # -- boot ----------------------------------------------------------------

    def _boot(self) -> None:
        universe = frozenset(range(self.n))
        memsum = sum(self.memfp) & MASK64
        cfg = self._config_id(memsum)
        topo = self._rings(universe)
        # Per node in slot order: broadcaster + consensus instance (no
        # scheduling), then FD jobs, then the alert batcher — the exact
        # handle order the oracle's service constructor produces.
        for r in range(self.n):
            nd = _Node(universe, memsum, cfg)
            nd.bcast = list(topo["ring0"])
            nd.px = _PaxosInstance(r, cfg, self.n)
            self.nodes.append(nd)
            self._create_fds(r, nd)
            nd.batcher_job = self._schedule_periodic(1, ("batcher", r))
        # Scripted proposes register after boot, in schedule order.
        for p in self.schedule.proposes:
            self._schedule(p.tick - self.now, ("scripted", p))

    def _create_fds(self, r: int, nd: _Node) -> None:
        topo = self._rings(nd.member_key)
        subjects = topo["subj"].get(r, [])
        for subject in dict.fromkeys(subjects):
            fd = {"subject": subject, "fc": 0, "notified": False,
                  "cfg": nd.cfg}
            nd.fds.append(fd)
            nd.fd_jobs.append(self._schedule_periodic(
                self.settings.fd_interval_ticks, ("fd", r, fd)))

    # -- fault evaluation ----------------------------------------------------

    def _edge_matrix(self, tick: int) -> Optional[np.ndarray]:
        """bool [n, n]: directed edges blocked by active link windows at
        the delivery tick (None when the schedule has no windows)."""
        if not self.schedule.windows:
            return None
        blocked = np.zeros((self.n, self.n), dtype=bool)
        for w in self.schedule.windows:
            if not w.active(tick):
                continue
            s = np.zeros(self.n, dtype=bool)
            d = np.zeros(self.n, dtype=bool)
            s[list(w.src_slots)] = True
            d[list(w.dst_slots)] = True
            blocked |= s[:, None] & d[None, :]
            if w.two_way:
                blocked |= d[:, None] & s[None, :]
        return blocked

    def _partitioned_edges(self, tick: int, crashed: np.ndarray) -> int:
        """Gauge matching ``engine.monitor.partitioned_edge_count``:
        per-window alive directed pairs, self-edges excluded, overlapping
        windows counted once each."""
        total = 0
        for w in self.schedule.windows:
            if not w.active(tick):
                continue
            src_m = sum(1 for s in w.src_slots if not crashed[s])
            dst_m = sum(1 for s in w.dst_slots if not crashed[s])
            both = sum(1 for s in (w.src_slots & w.dst_slots)
                       if not crashed[s])
            pairs = src_m * dst_m - both
            total += pairs * 2 if w.two_way else pairs
        return total

    # -- wire ----------------------------------------------------------------

    def _send(self, src: int, dst: int, kind: str, payload: tuple) -> None:
        self.counters["sent"] += 1
        phase = _PHASE_OF.get(kind)
        if phase:
            self.phase_counters[phase + "_sent"] += 1
        # Delay rules are evaluated at send time (latency is a property of
        # the wire the message entered); crashes and link windows still
        # apply at the delivery tick. Within a tick the global wseq sort
        # keeps send order, so jittered delays reorder across ticks
        # exactly like the oracle's per-tick in-flight buckets.
        delay = delay_of_slots(self.schedule.delays, self.schedule.seed,
                               src, dst, self.now)
        self._wire.setdefault(self.now + 1 + delay, []).append(
            (next(self._wseq), src, dst, kind, payload))

    def _broadcast(self, src: int, kind: str, payload: tuple) -> None:
        for dst in self.nodes[src].bcast:
            self._send(src, dst, kind, payload)

    # -- failure detection + alert pipeline ----------------------------------

    def _fd_tick(self, r: int, fd: dict) -> None:
        nd = self.nodes[r]
        if fd["fc"] >= self.settings.fd_failure_threshold:
            if not fd["notified"]:
                fd["notified"] = True
                self._edge_failure_notification(r, nd, fd)
            return
        self.counters["probes_sent"] += 1
        subject = fd["subject"]
        fail = (bool(self._crashed_now[subject])
                or bool(self._crashed_now[r])
                or (self._E is not None and self._E[r, subject]))
        if fail:
            self.counters["probes_failed"] += 1
            fd["fc"] += 1

    def _edge_failure_notification(self, r: int, nd: _Node,
                                   fd: dict) -> None:
        if fd["cfg"] != nd.cfg:
            return
        subjects = self._rings(nd.member_key)["subj"].get(r, [])
        rings = tuple(k for k, s in enumerate(subjects)
                      if s == fd["subject"])
        nd.last_enq = self.now
        nd.queue.append((fd["cfg"], r, fd["subject"], rings))

    def _batcher_tick(self, r: int) -> None:
        nd = self.nodes[r]
        if not nd.queue or nd.last_enq < 0:
            return
        if self.now - nd.last_enq < self.settings.batching_window_ticks:
            return
        alerts = tuple(nd.queue)
        nd.queue.clear()
        self._broadcast(r, "batch", alerts)

    # -- cut detection -------------------------------------------------------

    def _handle_batch(self, r: int, nd: _Node, alerts: tuple) -> None:
        if nd.announced:
            return
        cfg = nd.cfg
        proposal: Dict[int, None] = {}
        for acfg, asrc, adst, rings in alerts:
            if acfg != cfg:
                continue
            if adst not in nd.member_key:
                continue
            for ring in rings:
                for node in self._aggregate(nd, asrc, adst, ring):
                    proposal[node] = None
        for node in self._invalidate(nd):
            proposal[node] = None
        if proposal:
            nd.announced = True
            self._record(r, "proposal", cfg, tuple(sorted(proposal)))
            ordered = tuple(sorted(proposal, key=self._r0key))
            self._propose(r, nd, ordered, None)

    def _aggregate(self, nd: _Node, src: int, dst: int,
                   ring: int) -> List[int]:
        nd.seen_down = True
        reports = nd.reports.setdefault(dst, {})
        if ring in reports:
            return []
        reports[ring] = src
        num = len(reports)
        if num == self.settings.L:
            nd.updates += 1
            nd.pre[dst] = None
        if num == self.settings.H:
            nd.pre.pop(dst, None)
            nd.prop[dst] = None
            nd.updates -= 1
            if nd.updates == 0:
                flushed = list(nd.prop)
                nd.prop.clear()
                return flushed
        return []

    def _invalidate(self, nd: _Node) -> List[int]:
        if not nd.seen_down:
            return []
        obs_table = self._rings(nd.member_key)["obs"]
        out: List[int] = []
        for node in list(nd.pre):
            for ring, ob in enumerate(obs_table.get(node, [])):
                if ob in nd.prop or ob in nd.pre:
                    out.extend(self._aggregate(nd, ob, node, ring))
        return out

    # -- consensus -----------------------------------------------------------

    def _propose(self, r: int, nd: _Node, ordered: Tuple[int, ...],
                 recovery_delay: Optional[int]) -> None:
        px = nd.px
        if not px.rnd[0] > 1:
            px.rnd = (1, 1)
            px.vrnd = (1, 1)
            px.vval = tuple(ordered)
        self._broadcast(r, "vote", (px.cfg, tuple(ordered)))
        if recovery_delay is None:
            u = self.rngs[r].random()
            jitter_ms = -1000.0 * math.log(1.0 - u) * px.n
            recovery_delay = self.settings.fallback_base_delay_ticks + \
                max(0, round(jitter_ms / self.settings.tick_ms))
        px.timer_handle = self._schedule(recovery_delay, ("timer", px))

    def _start_phase1a(self, px: _PaxosInstance, round_: int) -> None:
        if px.crnd[0] > round_:
            return
        px.crnd = (round_, self.rank_idx[px.node])
        self._broadcast(px.node, "1a", (px.cfg, px.crnd))

    def _handle_vote(self, px: _PaxosInstance, src: int,
                     payload: tuple) -> None:
        cfg, prop = payload
        if cfg != px.cfg:
            return
        if src in px.votes_received:
            return
        if px.fp_decided:
            return
        px.votes_received.add(src)
        count = px.votes_per_proposal.get(prop, 0) + 1
        px.votes_per_proposal[prop] = count
        f = (px.n - 1) // 4
        if len(px.votes_received) >= px.n - f and count >= px.n - f:
            self._decide(px, prop)

    def _handle_1a(self, px: _PaxosInstance, src: int,
                   payload: tuple) -> None:
        cfg, rank = payload
        if cfg != px.cfg:
            return
        if px.rnd < rank:
            px.rnd = rank
        else:
            return
        self._send(px.node, src, "1b", (px.cfg, px.rnd, px.vrnd, px.vval))

    def _handle_1b(self, px: _PaxosInstance, src: int,
                   payload: tuple) -> None:
        cfg, rnd, vrnd, vval = payload
        if cfg != px.cfg:
            return
        if px.crnd != rnd:
            return
        px.p1b[src] = (vrnd, tuple(vval))
        if len(px.p1b) > px.n // 2:
            chosen = self._select_proposal(list(px.p1b.values()), px.n)
            if not px.cval and chosen:
                px.cval = chosen
                self._broadcast(px.node, "2a", (px.cfg, px.crnd, chosen))

    @staticmethod
    def _select_proposal(msgs: List[Tuple[Tuple[int, int],
                                          Tuple[int, ...]]],
                         n: int) -> Tuple[int, ...]:
        """The coordinator's CP-safe value-choice rule, replicated."""
        max_vrnd = max(vrnd for vrnd, _ in msgs)
        collected = [vval for vrnd, vval in msgs
                     if vrnd == max_vrnd and len(vval) > 0]
        chosen: Optional[Tuple[int, ...]] = None
        if len(set(collected)) == 1:
            chosen = collected[0]
        elif len(collected) > 1:
            counters: Dict[Tuple[int, ...], int] = {}
            for value in collected:
                count = counters.setdefault(value, 0)
                if count + 1 > n // 4:
                    chosen = value
                    break
                counters[value] = count + 1
        if chosen is None:
            chosen = next((vval for _, vval in msgs if len(vval) > 0), ())
        return chosen

    def _handle_2a(self, px: _PaxosInstance, src: int,
                   payload: tuple) -> None:
        cfg, rnd, vval = payload
        if cfg != px.cfg:
            return
        if px.rnd <= rnd and px.vrnd != rnd:
            px.rnd = rnd
            px.vrnd = rnd
            px.vval = tuple(vval)
            self._broadcast(px.node, "2b", (px.cfg, rnd, px.vval))

    def _handle_2b(self, px: _PaxosInstance, src: int,
                   payload: tuple) -> None:
        cfg, rnd, vval = payload
        if cfg != px.cfg:
            return
        in_rnd = px.p2b.setdefault(rnd, {})
        in_rnd[src] = vval
        if len(in_rnd) > px.n // 2 and not px.px_decided:
            px.px_decided = True
            self._decide(px, tuple(vval))

    def _decide(self, px: _PaxosInstance, hosts: Tuple[int, ...]) -> None:
        if px.fp_decided:
            return
        px.fp_decided = True
        if px.timer_handle is not None:
            self._cancelled.add(px.timer_handle)
            px.timer_handle = None
        self._decide_view_change(px.node, hosts)

    def _decide_view_change(self, r: int, hosts: Tuple[int, ...]) -> None:
        nd = self.nodes[r]
        for job in nd.fd_jobs:
            job["cancelled"] = True
        nd.fd_jobs = []
        nd.fds = []
        members = set(nd.member_key)
        for s in hosts:
            if s not in members:
                raise AdversaryExecutionError(
                    f"decided proposal removes slot {s} which is not in "
                    f"node {r}'s view (the oracle would crash here too)")
            members.discard(s)
            nd.memsum = (nd.memsum - self.memfp[s]) & MASK64
        nd.member_key = frozenset(members)
        nd.cfg = self._config_id(nd.memsum)
        self._record(r, "view_change", nd.cfg, tuple(sorted(hosts)))
        nd.reports = {}
        nd.pre = {}
        nd.prop = {}
        nd.updates = 0
        nd.seen_down = False
        nd.announced = False
        nd.px = _PaxosInstance(r, nd.cfg, len(nd.member_key))
        nd.bcast = list(self._rings(nd.member_key)["ring0"])
        if r in nd.member_key:
            self._create_fds(r, nd)
        else:
            nd.stopped = True
            if nd.batcher_job is not None:
                nd.batcher_job["cancelled"] = True

    def _record(self, r: int, kind: str, cfg: int,
                slots: Tuple[int, ...]) -> None:
        self.events[r].append((self.now, kind, cfg, slots))

    # -- tick loop -----------------------------------------------------------

    def _handle(self, dst: int, src: int, kind: str, payload: tuple) -> None:
        nd = self.nodes[dst]
        if nd.stopped:
            return
        if kind == "batch":
            self._handle_batch(dst, nd, payload)
        elif kind == "vote":
            self._handle_vote(nd.px, src, payload)
        elif kind == "1a":
            self._handle_1a(nd.px, src, payload)
        elif kind == "1b":
            self._handle_1b(nd.px, src, payload)
        elif kind == "2a":
            self._handle_2a(nd.px, src, payload)
        elif kind == "2b":
            self._handle_2b(nd.px, src, payload)

    def step(self) -> None:
        t = self.now + 1
        self.now = t
        before = dict(self.counters)
        before_phase = dict(self.phase_counters)
        self._E = self._edge_matrix(t)
        self._crashed_now = self.crash_ticks <= t
        self._link_dropped_tick = 0
        for _, src, dst, kind, payload in sorted(self._wire.pop(t, [])):
            if self._crashed_now[src]:
                self.counters["dropped"] += 1
                continue
            blocked = self._E is not None and self._E[src, dst]
            if self._crashed_now[dst] or blocked:
                self.counters["dropped"] += 1
                if not self._crashed_now[dst]:
                    self._link_dropped_tick += 1
                continue
            self.counters["delivered"] += 1
            phase = _PHASE_OF.get(kind)
            if phase:
                self.phase_counters[phase + "_delivered"] += 1
            self._handle(dst, src, kind, payload)
        self._run_due()
        self.tick_history.append(
            {k: self.counters[k] - before[k] for k in COUNTER_KEYS})
        self.phase_history.append(
            {k: self.phase_counters[k] - before_phase[k]
             for k in PHASE_KEYS})
        self.part_edges_history.append(
            self._partitioned_edges(t, self._crashed_now))
        self.link_dropped_history.append(self._link_dropped_tick)

    def run(self, n_ticks: int) -> AdversaryRun:
        for _ in range(n_ticks):
            self.step()
        return AdversaryRun(
            n=self.n,
            n_ticks=n_ticks,
            events_by_slot=[list(evs) for evs in self.events],
            tick_history=list(self.tick_history),
            phase_history=list(self.phase_history),
            partitioned_edges=list(self.part_edges_history),
            link_dropped=list(self.link_dropped_history),
            config_ids=[nd.cfg for nd in self.nodes],
            members_by_slot=[nd.member_key for nd in self.nodes],
            stopped=[nd.stopped for nd in self.nodes],
            totals=dict(self.counters),
            phase_totals=dict(self.phase_counters),
        )
