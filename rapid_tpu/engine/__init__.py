"""Batched JAX tick engine: all N simulated nodes advance as arrays.

The host oracle (``rapid_tpu.oracle``) runs the protocol one event at a
time; the engine runs the same steady-state pipeline — K-ring probe
monitoring, multi-node cut detection, Fast Paxos fast-round vote counting —
as one jit-compiled step over ``[capacity]``-shaped tensors, scanned with
``lax.scan``. ``rapid_tpu.engine.diff`` replays crash-fault scenarios
through both and asserts bit-identical cut decisions.
"""
from rapid_tpu.engine.state import (
    EngineFaults,
    EngineState,
    StepLog,
    init_state,
    state_config_id,
)
from rapid_tpu.engine.step import engine_step, simulate, step, trace_count
from rapid_tpu.engine.topology import build_topology

__all__ = [
    "EngineFaults",
    "EngineState",
    "StepLog",
    "build_topology",
    "engine_step",
    "init_state",
    "simulate",
    "state_config_id",
    "step",
    "trace_count",
]
