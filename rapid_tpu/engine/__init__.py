"""Batched JAX tick engine: all N simulated nodes advance as arrays.

The host oracle (``rapid_tpu.oracle``) runs the protocol one event at a
time; the engine runs the same steady-state pipeline — K-ring probe
monitoring, multi-node cut detection, Fast Paxos fast-round vote counting —
as one jit-compiled step over ``[capacity]``-shaped tensors, scanned with
``lax.scan``. Dynamic membership rides the same step: ``rapid_tpu.engine
.churn`` compiles join/leave scenarios into a ``ChurnSchedule`` of
per-slot alert enqueue ticks, and a decided proposal reconfigures the
view inside the scan. ``rapid_tpu.engine.diff`` replays crash and churn
scenarios through both sides and asserts bit-identical cut decisions.
"""
from rapid_tpu.engine.churn import (
    ChurnEnvelopeError,
    ChurnPlan,
    ChurnSchedule,
    empty_schedule,
    plan_churn,
    synthetic_churn_schedule,
)
from rapid_tpu.engine.invariants import (
    INVARIANT_BITS,
    InvariantViolationError,
    check_run,
    check_step,
    describe_bits,
)
from rapid_tpu.engine.sharding import (
    constrain,
    constrain_tree,
    shard_put,
    slot_mesh,
    spec_for,
    state_shardings,
)
from rapid_tpu.engine.state import (
    EngineFaults,
    EngineState,
    StepLog,
    init_state,
    state_config_id,
)
from rapid_tpu.engine.step import (
    engine_step,
    reset_trace_count,
    simulate,
    simulate_chunk,
    step,
    trace_count,
)
from rapid_tpu.engine.fleet import (
    FleetMember,
    fleet_simulate,
    fleet_trace_count,
    lower_schedule,
    member_logs,
    reset_fleet_trace_count,
    stack_members,
)
from rapid_tpu.engine.topology import (build_topology, rank_and_insert,
                                       ring_permutations)

__all__ = [
    "ChurnEnvelopeError",
    "ChurnPlan",
    "ChurnSchedule",
    "EngineFaults",
    "EngineState",
    "FleetMember",
    "INVARIANT_BITS",
    "InvariantViolationError",
    "StepLog",
    "build_topology",
    "check_run",
    "check_step",
    "constrain",
    "constrain_tree",
    "describe_bits",
    "empty_schedule",
    "engine_step",
    "fleet_simulate",
    "fleet_trace_count",
    "init_state",
    "lower_schedule",
    "member_logs",
    "plan_churn",
    "rank_and_insert",
    "reset_fleet_trace_count",
    "reset_trace_count",
    "ring_permutations",
    "shard_put",
    "simulate",
    "simulate_chunk",
    "slot_mesh",
    "spec_for",
    "stack_members",
    "state_config_id",
    "state_shardings",
    "step",
    "synthetic_churn_schedule",
    "trace_count",
]
