"""Dynamic-membership churn: batched join/leave lifecycle for the engine.

The engine keeps the slot universe fixed (``capacity >= N``): joiners are
pre-allocated *dormant* slots whose ``member`` flag flips when a decided
join proposal lands, leavers stay allocated but drop out of the member
mask. What moves between host and device:

- **Device** (``ChurnSchedule``, consumed by ``engine.step`` phase 4a):
  per-slot enqueue ticks for join-UP and leave-DOWN alert bursts, each
  guarded by the configuration epoch expected at enqueue time. From the
  enqueue on, the alert rides the same batched pipeline as monitor DOWNs
  (flush after one quiescent batching window, deliver one hop later,
  aggregate, announce, fast-round vote, decide) and a decided proposal
  triggers the full view reconfiguration *inside* the jitted scan:
  membership XOR, fingerprint-sum updates, a sort-free re-scan of the
  static ring order, detector/cut/consensus reset scoped by the epoch
  bump. UUID-retry identifier redraws ride the same schedule
  (``redraw_*`` fields, applied by ``apply_redraws``): at the scheduled
  tick the dormant slot's identity limbs are swapped in and its ring
  position updated by ``topology.rank_and_insert`` — still no sort in
  the jitted path. Schedules without redraws leave the ``redraw_*``
  fields ``None`` and compile the phase out entirely.

- **Host** (``plan_churn``): everything the oracle does with *messages
  that are not alert broadcasts* — the two-phase join gatekeeping
  (PreJoin at the seed, JoinMessages at the K gatekeepers), NodeId
  retries on UUID collisions, graceful-leave LeaveMessage fan-out, and
  the failure detectors' notify bookkeeping. The planner replays that
  protocol against a host-side ``MembershipView`` mirror and compiles it
  down to the enqueue ticks above, raising ``ChurnEnvelopeError``
  whenever the scenario leaves the envelope in which the batched engine
  is bit-identical to the oracle.

The churn envelope (checked per scenario, not assumed):

- one alert pipeline in flight at a time: join/leave alerts enqueued
  while a proposal is announcing/deciding would be dropped by the
  oracle's config-id filter but re-driven by its join retry logic, which
  the single-shot schedule does not model (crash notifications in the
  same window are dropped *consistently* on both sides and merely
  re-notify after the decide, so they stay in the envelope);
- the view must not change between a join's phase-1 evaluation and its
  alert enqueue (the oracle would answer CONFIG_CHANGED and retry);
- every burst must produce exactly one proposal emission containing all
  its destinations. The oracle's ``MultiNodeCutDetector`` emits at the
  instant a destination crosses H with zero destinations in flux — a
  same-tick burst where one destination is stuck below L while another
  crosses H emits a *partial* proposal. The planner replays the exact
  sequential per-batch aggregation (real ``MultiNodeCutDetector``,
  batches in service-creation order) and rejects partial emissions;
- joins must decide before their ``join_timeout_ticks`` retry fires (a
  heap tie goes to the timeout task: its handle predates the response),
  the seed must stay an alive member through phase 1, leavers must
  outlive their LeaveMessage hop, joiners their wiring response hop;
- a decide at tick D with ``(D+1) % fd_interval_ticks == 0`` under
  crash faults is rejected: the freshly wired joiner's failure
  detectors first fire at ``D+1+I`` in the oracle but the engine's
  uniform ``fd_gate`` would probe its row at ``D+1``.

``plan_churn`` returns the device schedule *and* the predicted event
stream (proposals/view changes with ticks, slots and 64-bit config ids),
so the differential harness (``engine.diff.run_churn_differential``) can
triangulate oracle vs engine vs plan.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from rapid_tpu import hashing
from rapid_tpu.engine.state import I32_MAX
from rapid_tpu.oracle.cluster import default_rng
from rapid_tpu.oracle.cut_detector import MultiNodeCutDetector
from rapid_tpu.oracle.membership_view import (MembershipView, id_fingerprint,
                                              uid_of)
from rapid_tpu.settings import Settings
from rapid_tpu.types import (AlertMessage, EdgeStatus, Endpoint,
                             JoinStatusCode, NodeId)


class ChurnEnvelopeError(ValueError):
    """The scenario leaves the envelope where the batched engine is
    bit-identical to the oracle (see module docstring). For fault-only
    scenarios (crashes, partitions, scripted proposes — no joins/leaves)
    no such envelope exists anymore: route them to
    ``engine.diff.run_adversarial_differential``, whose per-slot adversary
    engine executes straddling bursts, partition-driven quorum loss and
    the classic-Paxos fallback exactly."""


class ChurnSchedule(NamedTuple):
    """Device-side churn schedule: per-slot alert enqueue ticks.

    ``I32_MAX`` means never. ``*_epoch`` is the configuration epoch the
    planner expects at the enqueue tick; the engine injects the alert
    only while the expectation holds, mirroring the oracle's config-id
    filter expiring stale alerts. A NamedTuple of arrays is a jax pytree,
    so the schedule threads through ``jit``/``lax.scan`` untouched.

    The ``redraw_*`` fields script UUID-retry identifier redraws: at
    ``redraw_tick[s]`` (the oracle's response hop after the collision)
    dormant slot ``s`` swaps its identity to the ``redraw_hi/lo`` uid
    limbs and the ``redraw_idfp_*`` identifier-fingerprint limbs, and
    ``topology.rank_and_insert`` moves its ring position incrementally.
    They are ``None`` (and the engine phase compiles out) when the
    scenario has no collisions — the overwhelmingly common case. The
    planner schedules at most one redraw per tick; multiple retries of
    one slot collapse to a single redraw at the last retry carrying the
    final identity, exact because a dormant slot's intermediate identity
    is protocol-invisible (its gatekeeper row is only read at its join
    alert delivery, its fingerprints only at the decide).
    """

    join_tick: np.ndarray    # int32 [C]
    join_epoch: np.ndarray   # int32 [C]
    leave_tick: np.ndarray   # int32 [C]
    leave_epoch: np.ndarray  # int32 [C]
    redraw_tick: object = None      # int32 [C] or None (= no redraws)
    redraw_hi: object = None        # uint32 [C] replacement uid limbs
    redraw_lo: object = None
    redraw_idfp_hi: object = None   # uint32 [C] replacement id-fp limbs
    redraw_idfp_lo: object = None


def empty_schedule(c: int) -> ChurnSchedule:
    return ChurnSchedule(
        join_tick=np.full(c, I32_MAX, np.int32),
        join_epoch=np.zeros(c, np.int32),
        leave_tick=np.full(c, I32_MAX, np.int32),
        leave_epoch=np.zeros(c, np.int32),
    )


def apply_redraws(xp, state, schedule: ChurnSchedule, t):
    """Jitted redraw phase: apply this tick's identifier redraw, if any.

    At most one slot redraws per tick (the planner enforces it), so the
    update is a ``lax.cond`` around: swap the selected slot's uid /
    member-fingerprint / identifier-fingerprint limbs, move its ring
    position via ``topology.rank_and_insert``, and re-scan the derived
    topology plus ring-0 positions from the updated order — all O(C·K),
    no sort. Call only when ``schedule.redraw_tick is not None``.
    """
    from jax import lax

    from rapid_tpu import hashing
    from rapid_tpu.engine import paxos as paxos_mod
    from rapid_tpu.engine import topology as topology_mod
    from rapid_tpu.oracle.membership_view import _SEED_MEMBER

    redraw_now = (t == schedule.redraw_tick) & ~state.member

    def apply(st):
        sel = xp.argmax(redraw_now).astype(xp.int32)
        new_hi = schedule.redraw_hi[sel]
        new_lo = schedule.redraw_lo[sel]
        uid_hi = st.uid_hi.at[sel].set(new_hi)
        uid_lo = st.uid_lo.at[sel].set(new_lo)
        mfp_hi, mfp_lo = hashing.hash64_limbs(
            xp, new_hi, new_lo, seed=_SEED_MEMBER)
        ring_order, ring_rank = topology_mod.rank_and_insert(
            xp, sel, uid_hi, uid_lo, st.ring_order, st.ring_rank)
        subj_idx, obs_idx, gk_idx, fd_active, fd_first = \
            topology_mod.build_topology(xp, st.member, ring_order, ring_rank)
        return st._replace(
            uid_hi=uid_hi, uid_lo=uid_lo,
            mfp_hi=st.mfp_hi.at[sel].set(mfp_hi),
            mfp_lo=st.mfp_lo.at[sel].set(mfp_lo),
            idfp_hi=st.idfp_hi.at[sel].set(schedule.redraw_idfp_hi[sel]),
            idfp_lo=st.idfp_lo.at[sel].set(schedule.redraw_idfp_lo[sel]),
            ring_order=ring_order, ring_rank=ring_rank,
            subj_idx=subj_idx, obs_idx=obs_idx, gk_idx=gk_idx,
            fd_active=fd_active, fd_first=fd_first,
            px_pos=paxos_mod.ring0_positions(
                xp, st.member, ring_order, ring_rank),
        )

    return lax.cond(redraw_now.any(), apply, lambda st: st, state)


@dataclass
class ChurnPlan:
    """Output of ``plan_churn``: the compiled schedule plus the planner's
    own prediction of the protocol-visible event stream."""

    schedule: ChurnSchedule
    id_fps: np.ndarray                   # uint64 [C] identifier fingerprints
    joiner_ids: Dict[int, NodeId]        # slot -> decided NodeId
    wired: Dict[int, int]                # slot -> tick the joiner's service starts
    events: List[Tuple[int, str, int, Tuple[int, ...]]]
    final_members: frozenset
    final_config_id: int
    redraws: Dict[int, int] = None       # slot -> scheduled redraw tick

    def __post_init__(self):
        if self.redraws is None:
            self.redraws = {}


def plan_churn(
    endpoints: Sequence[Endpoint],
    initial_n: int,
    node_ids: Sequence[NodeId],
    n_ticks: int,
    settings: Settings,
    joins: Optional[Dict[int, int]] = None,
    leaves: Optional[Dict[int, int]] = None,
    crashes: Optional[Dict[int, int]] = None,
    seed_slot: int = 0,
) -> ChurnPlan:
    """Compile a churn scenario into a device schedule.

    ``endpoints`` is the full slot universe (initial members first, then
    dormant joiner slots), ``joins``/``leaves`` map slot -> the tick the
    host calls ``Cluster.join(seed)`` / ``leave_gracefully()``, and
    ``crashes`` maps slot -> crash tick (the same fault model handed to
    the engine). The planner advances a host-side mirror of the oracle
    tick by tick — view, failure-detector counters, the single alert
    pipeline — and raises ``ChurnEnvelopeError`` the moment the scenario
    exits the bit-identical envelope.
    """
    joins = dict(joins or {})
    leaves = dict(leaves or {})
    crashes = dict(crashes or {})
    c = len(endpoints)
    if not (0 < initial_n <= c):
        raise ValueError(f"initial_n {initial_n} out of range for C={c}")
    if len(node_ids) < initial_n:
        raise ValueError("need a NodeId per initial member")
    for s, t0 in joins.items():
        if not (initial_n <= s < c):
            raise ChurnEnvelopeError(
                f"join slot {s} is not a dormant slot (initial membership "
                f"owns [0, {initial_n}))")
        if t0 < 1:
            raise ValueError(f"join tick {t0} for slot {s} must be >= 1")
    for s, t0 in leaves.items():
        if not (0 <= s < c):
            raise ValueError(f"leave slot {s} out of range")
        if t0 < 1:
            raise ValueError(f"leave tick {t0} for slot {s} must be >= 1")

    view = MembershipView(settings.K, list(node_ids[:initial_n]),
                          list(endpoints[:initial_n]))
    slot_of = {e: i for i, e in enumerate(endpoints)}
    members = set(range(initial_n))
    creation_order = list(range(initial_n))
    epoch = 0
    fd_gate = 0
    fd_cnt: Dict[int, int] = {}
    fd_notified: set = set()
    pending: Optional[dict] = None
    leave_epochs: Dict[int, int] = {}
    events: List[Tuple[int, str, int, Tuple[int, ...]]] = []
    wired: Dict[int, int] = {}
    interval = settings.fd_interval_ticks

    def alive(s: int, t: int) -> bool:
        ct = crashes.get(s)
        return ct is None or t < ct

    # Joiner state machines. The NodeId sequence replicates the oracle's
    # Cluster rng exactly (same seed formula, same draw order: one 128-bit
    # id per attempt, drawn before the service ever touches the rng).
    js: Dict[int, dict] = {}
    for s, t0 in joins.items():
        rng = default_rng(settings, endpoints[s])
        first_id = NodeId(rng.getrandbits(64), rng.getrandbits(64))
        js[s] = {
            "attempt": 1, "start": t0,
            "node_id": first_id, "first_id": first_id, "redraw": None,
            "rng": rng, "p1_epoch": None, "enq": None, "done": False,
        }

    def announce_sim(dsts: Dict[int, str], t_ann: int) -> Optional[set]:
        """Replay the oracle's sequential per-batch cut aggregation at the
        delivery tick; returns the first emitted proposal as a slot set,
        or None if the burst never emits."""
        det = MultiNodeCutDetector(settings.K, settings.H, settings.L)
        batches: Dict[int, list] = {}
        for d, kind in dsts.items():
            ep = endpoints[d]
            if kind == "join":
                srcs = view.get_expected_observers_of(ep)
                status = EdgeStatus.UP
            else:
                srcs = view.get_observers_of(ep)
                status = EdgeStatus.DOWN
            per_src: Dict[Endpoint, List[int]] = {}
            for ring, src_ep in enumerate(srcs):
                per_src.setdefault(src_ep, []).append(ring)
            for src_ep, rings in per_src.items():
                src = slot_of[src_ep]
                if not alive(src, t_ann):
                    continue  # batch dropped at delivery, sender crashed
                batches.setdefault(src, []).append((kind, d, AlertMessage(
                    edge_src=src_ep, edge_dst=ep, edge_status=status,
                    configuration_id=0, ring_numbers=tuple(rings))))
        # Batches arrive in the senders' service-creation order (the
        # scheduler-handle order of their periodic batcher jobs); within a
        # batch, leave/join alerts (message deliveries, in sender-op
        # order = destination slot order under the harness's sorted
        # scheduling) precede crash notifications (run-due FD tasks,
        # which fire in the source's failure-detector creation order:
        # its subjects deduplicated in ring order).
        kind_rank = {"leave": 0, "join": 1, "crash": 2}

        def alert_order(src_ep: Endpoint):
            fd_order = {e: i for i, e in enumerate(
                dict.fromkeys(view.get_subjects_of(src_ep)))}

            def key(a):
                kind, d, _ = a
                return (kind_rank[kind],
                        fd_order.get(endpoints[d], d) if kind == "crash"
                        else d)
            return key

        for src in (s for s in creation_order if s in batches):
            prop: Dict[Endpoint, None] = {}
            for _, _, alert in sorted(
                    batches[src], key=alert_order(endpoints[src])):
                for node in det.aggregate_for_proposal(alert):
                    prop[node] = None
            for node in det.invalidate_failing_edges(view):
                prop[node] = None
            if prop:
                return {slot_of[e] for e in prop}
        return None

    schedule = empty_schedule(c)

    for t in range(1, n_ticks + 1):
        # -- A: fast-round votes arrive; a quorum decides the view change
        if pending is not None and pending["decide"] == t:
            nm = pending["n"]
            votes_alive = sum(1 for v in pending["voters"] if alive(v, t))
            if votes_alive < nm - (nm - 1) // 4:
                raise ChurnEnvelopeError(
                    f"tick {t}: only {votes_alive}/{nm} fast-round votes "
                    "survive to the decide tick — no fast quorum, the "
                    "oracle would fall back to classic paxos")
            if not any(alive(m, t) for m in members):
                raise ChurnEnvelopeError(
                    f"tick {t}: no alive member left to count the votes")
            dsts = pending["dsts"]
            removed = sorted(d for d in dsts if d in members)
            joined = [d for d in dsts if d not in members]
            for d in removed:
                view.ring_delete(endpoints[d])
                members.discard(d)
            for d in joined:
                view.ring_add(endpoints[d], js[d]["node_id"])
                members.add(d)
            epoch += 1
            fd_gate = t
            fd_cnt.clear()
            fd_notified.clear()
            events.append((t, "view_change",
                           view.get_current_configuration_id(),
                           tuple(sorted(dsts))))
            # Joiners get their parked SAFE_TO_JOIN response one hop
            # later, in proposal (ring-0 hash) order -> service creation
            # order for the batch pipeline.
            for d in sorted(joined,
                            key=lambda d: view.ring0_sort_key(endpoints[d])):
                st = js[d]
                st["done"] = True
                wired[d] = t + 1
                if not (t + 1 < st["start"] + settings.join_timeout_ticks):
                    raise ChurnEnvelopeError(
                        f"slot {d}: join decided at tick {t} but the "
                        f"response at {t + 1} loses to the timeout retry "
                        f"scheduled at {st['start']}+"
                        f"{settings.join_timeout_ticks}")
                if crashes and (t + 1) % interval == 0:
                    raise ChurnEnvelopeError(
                        f"slot {d}: wired at tick {t + 1}, an FD-interval "
                        "multiple — under crash faults the joiner's "
                        "detectors would skip it but the engine's fd_gate "
                        "would not")
                if not alive(d, t + 1):
                    raise ChurnEnvelopeError(
                        f"slot {d}: joiner crashes before its wiring "
                        f"response at tick {t + 1}")
                creation_order.append(d)
            pending = None

        # -- B: the flushed alert burst lands; H-crossing announces ------
        if pending is not None and pending["announce"] == t:
            emitted = announce_sim(pending["dsts"], t)
            if emitted is None:
                raise ChurnEnvelopeError(
                    f"tick {t}: burst {sorted(pending['dsts'])} never "
                    "emits a proposal (a destination is short of H "
                    "distinct-ring reports or stuck in flux)")
            if emitted != set(pending["dsts"]):
                raise ChurnEnvelopeError(
                    f"tick {t}: the oracle emits a partial proposal "
                    f"{sorted(emitted)} != scheduled "
                    f"{sorted(pending['dsts'])} (mid-batch H-crossing "
                    "with zero in-flux destinations)")
            voters = {m for m in members if alive(m, t)}
            if not voters:
                raise ChurnEnvelopeError(
                    f"tick {t}: no alive member left to announce")
            events.append((t, "proposal",
                           view.get_current_configuration_id(),
                           tuple(sorted(pending["dsts"]))))
            pending["voters"] = voters
            pending["n"] = len(members)

        new_enq: List[Tuple[int, str]] = []

        # -- C: two-phase join gatekeeping (host protocol mirror) --------
        for s in sorted(js):
            st = js[s]
            if st["done"]:
                continue
            p1 = st["start"] + 1  # PreJoin hop: seed evaluates phase 1
            if t == p1:
                if seed_slot not in members:
                    raise ChurnEnvelopeError(
                        f"slot {s}: join seed {seed_slot} is no longer a "
                        f"member at tick {t}")
                if not alive(seed_slot, t) or not alive(seed_slot, t + 1) \
                        or not alive(s, t + 1):
                    raise ChurnEnvelopeError(
                        f"slot {s}: seed or joiner dies during the "
                        f"phase-1 exchange around tick {t}")
                status = view.is_safe_to_join(endpoints[s], st["node_id"])
                if status is JoinStatusCode.HOSTNAME_ALREADY_IN_RING:
                    raise ChurnEnvelopeError(
                        f"slot {s}: endpoint already in the ring at its "
                        f"phase-1 evaluation (tick {t}) — rejoin before "
                        "removal is outside the envelope")
                if status is JoinStatusCode.UUID_ALREADY_IN_RING:
                    st["attempt"] += 1
                    if st["attempt"] > settings.join_attempts:
                        raise ChurnEnvelopeError(
                            f"slot {s}: {settings.join_attempts} join "
                            "attempts exhausted on UUID collisions")
                    st["node_id"] = NodeId(st["rng"].getrandbits(64),
                                           st["rng"].getrandbits(64))
                    st["start"] = t + 1  # retry PreJoin goes out with the reply
                    # The oracle draws the fresh NodeId when the collision
                    # response lands, one hop after this evaluation; the
                    # engine applies the redraw at that tick. Repeat
                    # collisions overwrite: one redraw, final identity.
                    st["redraw"] = t + 1
                    continue
                st["p1_epoch"] = epoch
                st["enq"] = t + 2  # reply hop + JoinMessage hop
            elif st["enq"] == t:
                if epoch != st["p1_epoch"]:
                    raise ChurnEnvelopeError(
                        f"slot {s}: view changed between join phase 1 and "
                        f"the gatekeeper enqueue at tick {t} — the oracle "
                        "answers CONFIG_CHANGED and retries")
                if not alive(s, t):
                    raise ChurnEnvelopeError(
                        f"slot {s}: joiner crashes before its "
                        f"JoinMessages deliver at tick {t}")
                new_enq.append((s, "join"))
            elif st["enq"] is None \
                    and t >= st["start"] + settings.join_timeout_ticks:
                raise ChurnEnvelopeError(
                    f"slot {s}: join attempt times out undecided at "
                    f"tick {t}")

        # -- D: graceful leaves (LeaveMessage hop) -----------------------
        for s, t0 in sorted(leaves.items()):
            if t == t0:
                if s not in members:
                    raise ChurnEnvelopeError(
                        f"slot {s}: leave_gracefully() at tick {t} but the "
                        "slot is not a member")
                if not alive(s, t):
                    raise ChurnEnvelopeError(
                        f"slot {s}: leaver already crashed at its "
                        f"leave_gracefully() tick {t}")
                leave_epochs[s] = epoch  # observers resolved against this view
            elif t == t0 + 1:
                if not alive(s, t):
                    raise ChurnEnvelopeError(
                        f"slot {s}: leaver crashes before its "
                        f"LeaveMessages deliver at tick {t}")
                if leave_epochs.get(s) != epoch:
                    raise ChurnEnvelopeError(
                        f"slot {s}: view changed during the LeaveMessage "
                        f"hop ending at tick {t}")
                new_enq.append((s, "leave"))

        # -- E: failure-detector interval (notify bookkeeping) -----------
        if t % interval == 0 and t > fd_gate:
            for s in sorted(members):
                if alive(s, t) or s in fd_notified:
                    continue
                if fd_cnt.get(s, 0) >= settings.fd_failure_threshold:
                    fd_notified.add(s)
                    new_enq.append((s, "crash"))
                else:
                    fd_cnt[s] = fd_cnt.get(s, 0) + 1

        # -- F: enqueue into the (single) alert pipeline -----------------
        if new_enq:
            if pending is not None:
                non_crash = [(s, k) for s, k in new_enq if k != "crash"]
                if non_crash:
                    raise ChurnEnvelopeError(
                        f"tick {t}: churn alerts {non_crash} enqueued "
                        "while the pipeline deciding at tick "
                        f"{pending['decide']} is in flight — the oracle "
                        "drops and retries them, the single-shot schedule "
                        "cannot")
                # Crash notifications enqueued mid-pipeline are dropped by
                # the decide's reset on both sides; the FD re-notifies
                # after the view change (fd_cnt/fd_notified clear at A).
            else:
                pending = {
                    "enqueue": t,
                    "announce": t + settings.churn_announce_delay_ticks,
                    "decide": t + settings.churn_decide_delay_ticks,
                    "dsts": {s: k for s, k in new_enq},
                }
                for s, kind in new_enq:
                    if kind == "join":
                        schedule.join_tick[s] = t
                        schedule.join_epoch[s] = epoch
                    elif kind == "leave":
                        schedule.leave_tick[s] = t
                        schedule.leave_epoch[s] = epoch

    # Boot fingerprints carry each joiner's *first* attempt; a scheduled
    # redraw swaps in the final identity before anything reads it.
    id_fps = np.zeros(c, np.uint64)
    joiner_ids: Dict[int, NodeId] = {}
    redraws: Dict[int, int] = {}
    for s, st in js.items():
        joiner_ids[s] = st["node_id"]
        if st["redraw"] is not None:
            redraws[s] = st["redraw"]
            id_fps[s] = np.uint64(id_fingerprint(st["first_id"]))
        else:
            id_fps[s] = np.uint64(id_fingerprint(st["node_id"]))
    if redraws:
        by_tick: Dict[int, int] = {}
        for s, rt in redraws.items():
            if rt in by_tick:
                raise ChurnEnvelopeError(
                    f"slots {by_tick[rt]} and {s} both redraw their "
                    f"NodeId at tick {rt} — the engine applies one "
                    "identifier redraw per tick")
            by_tick[rt] = s
        redraw_tick = np.full(c, I32_MAX, np.int32)
        redraw_hi = np.zeros(c, np.uint32)
        redraw_lo = np.zeros(c, np.uint32)
        redraw_idfp_hi = np.zeros(c, np.uint32)
        redraw_idfp_lo = np.zeros(c, np.uint32)
        for s, rt in redraws.items():
            redraw_tick[s] = rt
            # The engine's ring key is the *endpoint* uid, which a NodeId
            # redraw does not move — so the scripted replacement limbs
            # equal the boot limbs and rank_and_insert lands the slot back
            # on its own position. The fingerprint swap is the real work.
            redraw_hi[s], redraw_lo[s] = hashing.to_limbs(
                uid_of(endpoints[s]))
            redraw_idfp_hi[s], redraw_idfp_lo[s] = hashing.to_limbs(
                id_fingerprint(js[s]["node_id"]))
        schedule = schedule._replace(
            redraw_tick=redraw_tick, redraw_hi=redraw_hi,
            redraw_lo=redraw_lo, redraw_idfp_hi=redraw_idfp_hi,
            redraw_idfp_lo=redraw_idfp_lo)

    return ChurnPlan(
        schedule=schedule,
        id_fps=id_fps,
        joiner_ids=joiner_ids,
        wired=wired,
        events=events,
        final_members=frozenset(members),
        final_config_id=view.get_current_configuration_id(),
        redraws=redraws,
    )


def synthetic_churn_schedule(
    c: int,
    n_initial: int,
    settings: Settings,
    start: int = 10,
    period: Optional[int] = None,
    burst: int = 8,
) -> Tuple[ChurnSchedule, np.ndarray, dict]:
    """A sustained-churn workload for benchmarks (engine-only, no oracle).

    Alternating join/leave bursts: cycle ``i`` activates ``burst`` fresh
    dormant slots (epoch ``2i``) then gracefully removes exactly those
    slots (epoch ``2i+1``), so membership oscillates between ``n_initial``
    and ``n_initial + burst`` and every burst decides before the next
    enqueues. Returns (schedule, id_fps, info) where ``info`` carries the
    burst count and the tick of the last decide.
    """
    if period is None:
        period = settings.churn_decide_delay_ticks + 3
    if period <= settings.churn_decide_delay_ticks:
        raise ValueError("period must exceed the enqueue->decide delay")
    headroom = c - n_initial
    cycles = headroom // burst
    schedule = empty_schedule(c)
    id_fps = np.zeros(c, np.uint64)
    for s in range(n_initial, c):
        id_fps[s] = np.uint64(hashing.hash64(s, seed=0x6964))
    last_decide = 0
    for cyc in range(cycles):
        slots = range(n_initial + cyc * burst, n_initial + (cyc + 1) * burst)
        jt = start + (2 * cyc) * period
        lt = start + (2 * cyc + 1) * period
        for s in slots:
            schedule.join_tick[s] = jt
            schedule.join_epoch[s] = 2 * cyc
            schedule.leave_tick[s] = lt
            schedule.leave_epoch[s] = 2 * cyc + 1
        last_decide = lt + settings.churn_decide_delay_ticks
    info = {"bursts": 2 * cycles, "burst_size": burst, "period": period,
            "last_decide": last_decide}
    return schedule, id_fps, info
