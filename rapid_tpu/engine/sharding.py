"""Slot-universe sharding: the tick engine over a 1-D device mesh.

The engine's working set is the flat *slot universe* — ``[C]`` and
``[C, K]`` arrays indexed by slot — and every hot kernel the observatory
names (``cut_aggregate`` tops FLOPs/bytes at every N, ``vote_count``
tops wall clock at 10k/100k) is a slot-parallel reduction. This module
partitions that capacity axis over a 1-D ``jax.sharding.Mesh`` so a
v5e-8-shaped device set (or the 8 virtual CPU devices the test suite
forces) each own ``C / n_devices`` slots:

- **what shards**: any array whose leading-or-later axis equals the
  capacity ``C`` — ``member``/``uid_*``/``fc [C, K]``/``reports
  [C, K]``/``px_* [C]``, the fault tensors ``link_src [W, C]``, the
  fallback script rows ``prop_tick [I, C]`` / ``table_mask [I, P, C]``;
- **what replicates**: scalars (``tick``, the limb sums, latches), the
  tiny per-instance fallback tables ``table_hi/lo [I, P]``, and — via
  the divisibility guard — anything whose capacity axis does not divide
  the mesh (a ``[256, 8]`` LUT constant never has a capacity axis and
  is always replicated).

One deliberately *non*-local axis remains: gathers like
``fc[obs_idx]`` and the ``vote_count`` lexsort are cross-slot, so XLA
inserts collectives for them — the win is that the elementwise bulk of
``cut_aggregate``'s fixpoint and the monitor stays partitioned, and the
``lax.scan`` carry keeps its sharding across ticks (committed input
shardings + ``with_sharding_constraint`` on the carry, no per-tick
reshard).

Everything here is a no-op when ``mesh is None``: the kernels take
``mesh`` as a *static* jit argument (``Mesh`` is hashable), so the
default single-device path traces byte-identical jaxprs to the
pre-sharding engine. All engine arithmetic is integer/boolean/modular
uint32 — order-independent reductions — so sharded and unsharded runs
must agree *bitwise*, which ``tests/test_sharding.py`` and
``__graft_entry__.dryrun_multichip`` both assert.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

#: The one mesh axis name: the partitioned capacity ("slot") dimension.
AXIS = "slots"


def slot_mesh(n_devices: Optional[int] = None, devices=None):
    """A 1-D mesh over ``devices`` (default: all), axis name ``AXIS``.

    ``n_devices`` trims the device list (e.g. exactly 8 of a larger
    host) and errors when fewer are available — callers that want
    graceful degradation check ``len(jax.devices())`` first
    (``__graft_entry__.dryrun_multichip``).
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"need {n_devices} devices for the slot mesh, have "
                f"{len(devices)} — force more with XLA_FLAGS="
                f"--xla_force_host_platform_device_count={n_devices} "
                f"before importing jax")
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (AXIS,))


def mesh_size(mesh) -> int:
    """Number of devices along the slot axis."""
    return int(mesh.shape[AXIS])


def spec_for(shape: Sequence[int], capacity: int, mesh):
    """The ``PartitionSpec`` for one leaf: shard the first capacity-sized
    axis, replicate everything else.

    The divisibility guard (SNIPPETS.md [3]) replicates any array whose
    capacity axis does not divide the mesh — sharding would force uneven
    padding and XLA reshards mid-step. Scalars, the ``[256, 8]`` scan
    LUTs, and per-instance fallback tables never match and replicate.

    Packed receiver planes (``rx_packed``) need no special casing: the
    bit-packing shrinks only the *trailing* axis (``[C, C] ->
    [C, C/8]``), so the leading capacity-sized slot axis this spec keys
    on is untouched and packed leaves shard exactly like dense ones.
    """
    from jax.sharding import PartitionSpec as P

    n_dev = mesh_size(mesh)
    for axis, dim in enumerate(shape):
        if dim == capacity and capacity % n_dev == 0:
            return P(*([None] * axis + [AXIS]))
    return P()


def sharding_for(x, capacity: int, mesh):
    """The committed ``NamedSharding`` for one array leaf."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, spec_for(jnp.shape(x), capacity, mesh))


def constrain(x, mesh, capacity: int):
    """``with_sharding_constraint`` under ``spec_for``; identity when
    ``mesh is None`` (the single-device path compiles the constraint
    out — no jaxpr change at all)."""
    if mesh is None:
        return x
    import jax

    return jax.lax.with_sharding_constraint(
        x, sharding_for(x, capacity, mesh))


def replicate(x, mesh):
    """Pin ``x`` fully replicated on ``mesh``; identity when ``mesh is
    None``.

    This is the escape hatch for block-carry temporaries whose tiny
    leading dimension (e.g. ``C/8`` packed bytes) the partitioner would
    otherwise spread over more devices than it has elements: XLA's SPMD
    slice/concat handling on such over-partitioned arrays reads shard
    *padding* (observed miscompile on the CPU backend — a ``x[:-1]``
    of a ``[2]``-element carry returned pad garbage on an 8-way mesh).
    Pinning the region replicated keeps those ops off the partitioner.
    """
    if mesh is None:
        return x
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, PartitionSpec()))


def constrain_tree(tree, mesh, capacity: int):
    """``constrain`` every array leaf of a pytree (states, logs,
    schedules). Identity when ``mesh is None``."""
    if mesh is None:
        return tree
    import jax

    return jax.tree_util.tree_map(
        lambda x: constrain(x, mesh, capacity), tree)


def shard_put(tree, mesh, capacity: Optional[int] = None):
    """``device_put`` a pytree with committed per-leaf shardings.

    This is how inputs *enter* the mesh: committed shardings make GSPMD
    propagate the layout through the jitted step instead of defaulting
    to replication. ``capacity`` defaults to the first leaf's leading
    dimension (the slot universe's ``C``).
    """
    import jax

    leaves = jax.tree_util.tree_leaves(tree)
    if capacity is None:
        capacity = _infer_capacity(leaves)
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding_for(x, capacity, mesh)), tree)


def _infer_capacity(leaves) -> int:
    """The slot-universe capacity: the largest leading dimension among
    rank>=1 leaves (scalars carry no shape; ``[W, C]``/``[I, C]``
    tensors have small leading dims)."""
    import jax.numpy as jnp

    dims = [d for leaf in leaves for d in jnp.shape(leaf)]
    if not dims:
        raise ValueError("cannot infer capacity from an all-scalar pytree")
    return max(dims)


def fleet_spec_for(shape: Sequence[int], capacity: int, mesh):
    """``spec_for`` for leaves that carry a leading *fleet* axis.

    Fleet-stacked pytrees (``fleet.stack_receiver_members``) prepend an
    ``F`` axis to every leaf: ``[F, C, C]`` report matrices,
    ``[F, C, C, K]`` observer tables, ``[F, W, C]`` window masks. Axis 0
    is the vmapped member dimension and must stay replicated — when
    ``F == C`` (an 8-member fleet of 8-slot clusters, or any fleet sized
    to its capacity) ``spec_for`` would otherwise shard the fleet axis
    itself. This wrapper skips axis 0 and shards the first *later*
    capacity-sized axis that divides the mesh: ``[F, C, C]`` leaves get
    ``P(None, "slots")`` (trailing axes replicated), scalars-per-member
    ``[F]`` and non-dividing axes replicate under the same divisibility
    guard as ``spec_for``.
    """
    from jax.sharding import PartitionSpec as P

    n_dev = mesh_size(mesh)
    for axis, dim in enumerate(shape):
        if axis == 0:
            continue
        if dim == capacity and capacity % n_dev == 0:
            return P(*([None] * axis + [AXIS]))
    return P()


def fleet_shard_put(tree, mesh, capacity: int):
    """``device_put`` a fleet-stacked pytree under ``fleet_spec_for``.

    The explicit ``capacity`` (not inferred) keeps an ``F >= C`` fleet
    from being mistaken for the slot universe."""
    import jax
    from jax.sharding import NamedSharding

    return jax.tree_util.tree_map(
        lambda x: jax.device_put(
            x, NamedSharding(mesh, fleet_spec_for(jax.numpy.shape(x),
                                                  capacity, mesh))),
        tree)


#: The fleet-parallel mesh axis name: the vmapped member dimension.
FLEET_AXIS = "fleet"


def fleet_axis_mesh(n_devices: Optional[int] = None, devices=None):
    """A 1-D mesh over ``devices``, axis name ``FLEET_AXIS``.

    The data-parallel dual of ``slot_mesh``: instead of splitting one
    cluster's slot universe across devices, each device owns whole fleet
    members. Campaign dispatches are embarrassingly parallel along the
    fleet axis — no collectives at all — so this is the layout that
    scales clusters/sec with device count. Same trim-and-error contract
    as ``slot_mesh``.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"need {n_devices} devices for the fleet mesh, have "
                f"{len(devices)} — force more with XLA_FLAGS="
                f"--xla_force_host_platform_device_count={n_devices} "
                f"before importing jax")
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (FLEET_AXIS,))


def fleet_axis_spec_for(shape: Sequence[int], fleet_size: int, mesh):
    """The ``PartitionSpec`` for one fleet-stacked leaf: shard axis 0
    when it is the fleet axis, replicate everything else.

    Fleet-stacked pytrees carry ``F`` as the leading dimension of every
    leaf (``[F]`` scalars-per-member through ``[F, C, C, K]`` observer
    tables). Sharding that one axis as ``P("fleet")`` splits members
    across devices with zero cross-device traffic. The divisibility
    guard replicates when ``F`` does not divide the mesh (uneven member
    padding would force reshards), which also keeps static-shaped
    constants without a fleet axis replicated.
    """
    from jax.sharding import PartitionSpec as P

    n_dev = int(mesh.shape[FLEET_AXIS])
    if shape and shape[0] == fleet_size and fleet_size % n_dev == 0:
        return P(FLEET_AXIS)
    return P()


def fleet_axis_constrain_tree(tree, mesh, fleet_size: int):
    """``with_sharding_constraint`` every leaf under
    ``fleet_axis_spec_for``; identity when ``mesh is None`` (the
    default path traces a byte-identical jaxpr — no constraint eqns)."""
    if mesh is None:
        return tree
    import jax
    from jax.sharding import NamedSharding

    return jax.tree_util.tree_map(
        lambda x: jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, fleet_axis_spec_for(
                jax.numpy.shape(x), fleet_size, mesh))),
        tree)


def fleet_axis_put(tree, mesh, fleet_size: int):
    """``device_put`` a fleet-stacked pytree with committed
    ``P("fleet")`` shardings so member shards land on their owning
    device before dispatch (GSPMD then keeps them there)."""
    import jax
    from jax.sharding import NamedSharding

    return jax.tree_util.tree_map(
        lambda x: jax.device_put(
            x, NamedSharding(mesh, fleet_axis_spec_for(
                jax.numpy.shape(x), fleet_size, mesh))),
        tree)


def state_shardings(state, mesh):
    """Per-leaf ``NamedSharding`` pytree for an ``EngineState`` (or any
    slot-universe pytree) — usable as jit ``in_shardings``/
    ``out_shardings`` or for documentation/introspection."""
    import jax

    capacity = int(state.member.shape[0])
    return jax.tree_util.tree_map(
        lambda x: sharding_for(x, capacity, mesh), state)
