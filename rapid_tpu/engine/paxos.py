"""Classic-Paxos fallback kernel: batched consensus recovery.

When conflicting proposals split the fast round below its quorum
``N - floor((N-1)/4)``, the oracle (``rapid_tpu.oracle.paxos``) recovers
with single-decree classic Paxos: every proposer arms a jittered fallback
timer at ``propose`` time, the first timer to fire starts phase 1a with
rank ``(2, classic_rank_node_index)``, acceptors promise (1b, unicast to
the coordinator), the coordinator picks a value with the Fast Paxos
coordinator rule (Lamport tr-2005-112 Fig. 2) once a majority of promises
arrived, and phase 2a/2b drive the decision at a ``> N/2`` accept count.
This module is the batched engine port of that machinery over the
``[capacity]`` slot universe:

- rank state (``rnd``/``vrnd``/``crnd``) as per-slot ``(round, node_index)``
  int32 pairs, with ``classic_rank_index`` computed from the same 64-bit
  identity hash as the oracle's ``classic_rank_node_index`` so classic
  ranks order identically above the fast round's ``(1, 1)``;
- per-slot fallback timers (``px_timer``) armed at scripted ``propose``
  ticks and cancelled by any decision (the oracle's
  ``_on_decided_wrapped`` scheduler cancel);
- values as small integer proposal ids (*pids*) into a static per-instance
  proposal table, fingerprinted with ``votes.proposal_fingerprint`` so the
  fast-round tally reuses ``votes.segmented_vote_count`` unchanged;
- the coordinator rule as masked segmented reductions over the ring-0
  arrival order of phase-1b messages (``coordinator_rule_pid``);
- phase-1a/1b/2a/2b message generation and counting through the same
  send-tick/deliver-next-tick pipeline as alert batches, logged as
  per-tick sender/recipient factors in ``StepLog``.

Scenario envelope (fleet kernel only)
-------------------------------------
The scripted contested instances (``FallbackSchedule``) reproduce the
oracle bit-for-bit (``engine.diff.run_fallback_differential`` asserts it)
under the conditions ``plan_fallback`` checks per scenario:

- crash-free runs with a quiet alert path (no cut-detector proposals
  while a scripted instance is live) — conflicting proposals come from
  the script;
- one classic round per instance: exactly one effective timer fire, all
  other timers landing at/after the decide tick (where the oracle
  cancels them), and no fast-round votes delivered mid-round;
- in the fast/classic race, a timer may fire one tick before the fast
  decision: its phase-1a broadcast is counted but dead on arrival (the
  oracle's new consensus instance rejects the stale configuration id).

These bounds describe what the *jitted shared-view kernel* can carry —
one membership view and one decide latch per tick — not what the repo
can execute. Tied first timers, mid-fast-count fires, multi-coordinator
rank races and partition-driven asymmetric vote delivery are first-class
scenarios for the per-slot adversary engine: build an
``rapid_tpu.faults.AdversarySchedule`` and run it through
``engine.diff.run_adversarial_differential``, which asserts the same
bit-identical contract with no planner screening at all. A
``FallbackEnvelopeError`` from ``plan_fallback`` therefore means "route
this scenario to the adversary engine", never "unsupported".

Everything here is shape-static: the schedule is a pytree of
``[instances, capacity]`` arrays, so it threads through ``jit`` /
``lax.scan`` and a run with ``fallback=None`` compiles the whole
subsystem out.
"""
from __future__ import annotations

import math
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from rapid_tpu import hashing
from rapid_tpu.engine import sharding
from rapid_tpu.engine.state import I32_MAX
from rapid_tpu.engine.votes import fast_quorum, proposal_fingerprint, \
    segmented_vote_count
from rapid_tpu.settings import Settings

_RANK_SEED = 0x72616E6B  # matches oracle.paxos.classic_rank_node_index


class FallbackEnvelopeError(ValueError):
    """The contested scenario leaves the envelope of the *jitted fleet
    kernel* (module docstring). The scenario itself is executable: run it
    through ``engine.diff.run_adversarial_differential``, whose per-slot
    adversary engine replays it bit-identically with no screening."""


class FallbackSchedule(NamedTuple):
    """Scripted contested consensus instances, one row per instance.

    ``prop_pid[i, s] >= 0`` means slot ``s`` calls ``propose`` with
    proposal ``table_mask[i, pid]`` at tick ``prop_tick[i, s]`` and arms
    its fallback timer for ``prop_delay[i, s]`` ticks (the oracle's
    explicit ``recovery_delay_ticks``, standing in for the per-node
    expovariate jitter so both sides share one deterministic draw).
    Instance ``i`` is live only while the configuration epoch equals
    ``inst_epoch[i]`` — the engine analogue of the oracle's
    configuration-id filter on consensus messages. ``table_hi``/``lo``
    are the per-pid ``proposal_fingerprint`` limbs feeding the fast-round
    segmented tally.
    """

    inst_epoch: np.ndarray   # int32 [I]
    prop_tick: np.ndarray    # int32 [I, C]
    prop_pid: np.ndarray     # int32 [I, C]  (-1 = no vote)
    prop_delay: np.ndarray   # int32 [I, C]
    table_mask: np.ndarray   # bool  [I, P, C]
    table_hi: np.ndarray     # uint32 [I, P]
    table_lo: np.ndarray     # uint32 [I, P]


def empty_fallback_schedule(c: int, instances: int = 1,
                            pids: int = 1) -> FallbackSchedule:
    return FallbackSchedule(
        inst_epoch=np.arange(instances, dtype=np.int32),
        prop_tick=np.full((instances, c), I32_MAX, np.int32),
        prop_pid=np.full((instances, c), -1, np.int32),
        prop_delay=np.zeros((instances, c), np.int32),
        table_mask=np.zeros((instances, pids, c), bool),
        table_hi=np.zeros((instances, pids), np.uint32),
        table_lo=np.zeros((instances, pids), np.uint32),
    )


# ---------------------------------------------------------------------------
# device kernels
# ---------------------------------------------------------------------------


def classic_rank_index(xp, uid_hi, uid_lo):
    """i32 [C]: the oracle's ``classic_rank_node_index`` per slot —
    the low 31 bits of ``hash64(uid, seed=0x72616E6B)``."""
    _, lo = hashing.hash64_limbs(xp, uid_hi, uid_lo, seed=_RANK_SEED)
    return (lo & xp.uint32(0x7FFFFFFF)).astype(xp.int32)


def ring0_positions(xp, member, ring_order, ring_rank):
    """i32 [C]: each member's position in ring-0 order (the broadcaster's
    recipient order, hence the phase-1b arrival order at the coordinator);
    non-members read ``I32_MAX``.

    Sort-free: gathers the member mask through the static ring-0 order
    (``EngineState.ring_order[:, 0]`` — the same ``hash64(uid, seed=0)``
    key with the uid as tiebreak that ``topology.ring_permutations``
    sorted once at boot) and prefix-sums member positions."""
    member_b = member.astype(bool)
    member_s = member_b[ring_order[:, 0]]
    mrank_s = xp.cumsum(member_s.astype(xp.int32)) - 1
    mpos = mrank_s[ring_rank[:, 0]]
    return xp.where(member_b, mpos, xp.int32(I32_MAX))


def rank_lt(ar, ai, br, bi):
    """(ar, ai) < (br, bi) lexicographically (the oracle's Rank order)."""
    return (ar < br) | ((ar == br) & (ai < bi))


def rank_eq(ar, ai, br, bi):
    return (ar == br) & (ai == bi)


def coordinator_rule_pid(xp, promised, pos, vval_pid, n, n_pids: int):
    """The Fast Paxos Fig. 2 value-selection rule over arrival order.

    The oracle's coordinator re-evaluates the rule at every phase-1b
    arrival past the majority until it yields a non-empty value
    (``Paxos.handle_phase1b`` + ``select_proposal_using_coordinator_rule``).
    Promises arrive in ring-0 order (broadcast recipient order fixes the
    reply sequence), so the first effective prefix has length
    ``m* = max(N//2 + 1, first_value_position + 1)`` and the rule reduces
    to masked segmented counts over that prefix:

    - one distinct voted value -> that value;
    - else the value whose cumulative count first exceeds ``N//4`` in
      arrival order (the earliest ``(N//4 + 1)``-th occurrence);
    - else the first voted value in arrival order.

    Returns the chosen pid, or -1 when no promise carries a value (the
    oracle broadcasts no phase 2a in that case). Assumes the fallback
    envelope's single-round ``vrnd`` structure: a promise carries a value
    iff its ``vrnd`` is the fast round, which is the unique maximum.
    """
    big = xp.int32(I32_MAX)
    n4 = (n // 4).astype(xp.int32)
    has_val = promised & (vval_pid >= 0)
    pos_hv = xp.where(has_val, pos, big)
    first_hv = pos_hv.min()
    m_star = xp.maximum(n // 2 + 1, first_hv + 1)
    cand = has_val & (pos < m_star)
    pid_ids = xp.arange(n_pids, dtype=xp.int32)
    pid_masks = cand[None, :] & (vval_pid[None, :] == pid_ids[:, None])
    cnt = pid_masks.sum(axis=1).astype(xp.int32)
    distinct = (cnt > 0).sum().astype(xp.int32)
    single_pid = xp.argmax(cnt > 0).astype(xp.int32)
    # Position of each pid's (N//4 + 1)-th occurrence within the prefix.
    sorted_pos = xp.sort(xp.where(pid_masks, pos[None, :], big), axis=1)
    cross = xp.where(cnt >= n4 + 1, sorted_pos[:, n4], big)
    cross_pid = xp.argmin(cross).astype(xp.int32)
    has_cross = cross.min() < big
    fb_pid = vval_pid[xp.argmin(pos_hv)]
    chosen = xp.where(distinct == 1, single_pid,
                      xp.where(has_cross, cross_pid, fb_pid))
    return xp.where(has_val.any(), chosen, xp.int32(-1))


def _instance_row(xp, sched: FallbackSchedule, epoch):
    """Gather the schedule row of the current epoch's instance."""
    e = xp.clip(epoch, 0, sched.inst_epoch.shape[0] - 1)
    live = sched.inst_epoch[e] == epoch
    return e, live


def chain_deliver(xp, state, sched: FallbackSchedule, t, n, mesh=None):
    """Classic-chain deliveries at tick ``t``: 2b -> 2a -> 1b.

    These messages were sent during the previous tick's delivery phase,
    so they sort before fast-round votes and phase-1a broadcasts (task-
    phase sends) in the oracle's per-tick seq order. Returns
    ``(state, counts, classic_decide, classic_pid)`` where ``counts``
    holds the phase-2a/2b sender factors generated by these deliveries.
    Later chain stages are gated off once an earlier message decided —
    the oracle's fresh consensus instance rejects their configuration id.

    Per-slot rank/vote updates are elementwise selects over ``[C]``
    arrays; ``mesh`` (static) pins the updated state to the slot
    partition so the coordinator-rule reductions cannot pull the carry
    back to a replicated layout.
    """
    epoch = state.epoch
    e, live = _instance_row(xp, sched, epoch)
    maj = n // 2

    # -- phase 2b: everyone counts accept votes; decide past majority ----
    arr2b = live & (state.c2b_tick + 1 == t) & (state.c2b_epoch == epoch)
    classic_decide = arr2b & (state.c2b_cnt > maj)
    classic_pid = state.c2b_pid
    gate = ~classic_decide

    # -- phase 2a: acceptors accept and broadcast phase 2b ---------------
    arr2a = live & gate & (state.c2a_tick + 1 == t) \
        & (state.c2a_epoch == epoch)
    accept = state.member & ~rank_lt(state.c2a_rank_r, state.c2a_rank_i,
                                     state.px_rnd_r, state.px_rnd_i) \
        & ~rank_eq(state.px_vrnd_r, state.px_vrnd_i,
                   state.c2a_rank_r, state.c2a_rank_i) & arr2a
    n_accept = accept.sum().astype(xp.int32)
    state = state._replace(
        px_rnd_r=xp.where(accept, state.c2a_rank_r, state.px_rnd_r),
        px_rnd_i=xp.where(accept, state.c2a_rank_i, state.px_rnd_i),
        px_vrnd_r=xp.where(accept, state.c2a_rank_r, state.px_vrnd_r),
        px_vrnd_i=xp.where(accept, state.c2a_rank_i, state.px_vrnd_i),
        px_vval=xp.where(accept, state.c2a_pid, state.px_vval),
        c2b_tick=xp.where(arr2a, t, state.c2b_tick),
        c2b_cnt=xp.where(arr2a, n_accept, state.c2b_cnt),
        c2b_pid=xp.where(arr2a, state.c2a_pid, state.c2b_pid),
        c2b_epoch=xp.where(arr2a, epoch, state.c2b_epoch),
    )

    # -- phase 1b: coordinator applies the rule past majority ------------
    arr1b = live & gate & (state.c1b_tick + 1 == t) \
        & (state.c1b_epoch == epoch)
    n_promise = state.c1b_mask.sum().astype(xp.int32)
    pos = state.px_pos
    chosen = coordinator_rule_pid(xp, state.c1b_mask, pos, state.px_vval,
                                  n, sched.table_mask.shape[1])
    do2a = arr1b & (n_promise > maj) & (chosen >= 0)
    state = state._replace(
        c2a_tick=xp.where(do2a, t, state.c2a_tick),
        c2a_pid=xp.where(do2a, chosen, state.c2a_pid),
        c2a_rank_r=xp.where(do2a, state.c1a_rank_r, state.c2a_rank_r),
        c2a_rank_i=xp.where(do2a, state.c1a_rank_i, state.c2a_rank_i),
        c2a_epoch=xp.where(do2a, epoch, state.c2a_epoch),
        px_cval=xp.where(
            do2a & (xp.arange(state.px_cval.shape[0]) == state.c1a_coord),
            chosen, state.px_cval),
    )
    counts = {
        "px2a_senders": do2a.astype(xp.int32),
        "px2a_recipients": xp.where(do2a, n, 0).astype(xp.int32),
        "px2b_senders": xp.where(arr2a, n_accept, 0).astype(xp.int32),
        "px2b_recipients": xp.where(arr2a, n, 0).astype(xp.int32),
    }
    state = sharding.constrain_tree(state, mesh, state.member.shape[0])
    return state, counts, classic_decide, classic_pid


def fast_tally(xp, state, sched: FallbackSchedule, t, n, blocked,
               mesh=None):
    """Scripted fast-round tally at tick ``t`` (after chain messages,
    before phase-1a broadcasts, in seq order).

    The delivered-vote set is derived from the schedule (a vote sent at
    its propose tick arrives one tick later, and the instance epoch gate
    expires stale votes exactly as the oracle's configuration-id check).
    Reuses the limb-fingerprint segmented counter from ``votes.py``,
    threading ``mesh`` (static) so the per-slot tally re-partitions
    after the global sort. Returns ``(fast_decide, win_pid, tally,
    quorum)``.
    """
    epoch = state.epoch
    e, live = _instance_row(xp, sched, epoch)
    pid = sched.prop_pid[e]
    delivered = live & state.member & (pid >= 0) \
        & (sched.prop_tick[e] + 1 <= t)
    safe_pid = xp.clip(pid, 0, sched.table_mask.shape[1] - 1)
    vote_hi = sched.table_hi[e][safe_pid]
    vote_lo = sched.table_lo[e][safe_pid]
    per_vote = segmented_vote_count(xp, vote_hi, vote_lo, delivered,
                                    mesh=mesh)
    total = delivered.sum().astype(xp.int32)
    quorum = fast_quorum(xp, n)
    decided = ~blocked & (total >= quorum) & (per_vote.max() >= quorum)
    win_pid = xp.where(delivered & (per_vote >= quorum), pid,
                       xp.int32(I32_MAX)).min()
    tally = xp.where(total > 0, per_vote.max(), 0).astype(xp.int32)
    return decided, win_pid, tally, quorum


def phase1a_deliver(xp, state, sched: FallbackSchedule, t, n, decided_now,
                    mesh=None):
    """Phase-1a delivery at tick ``t`` (last in seq order: the broadcast
    was a task-phase send). Acceptors with a lower rank promise and
    unicast phase 1b to the coordinator; a decision earlier this tick
    (or an epoch change since the send) kills the broadcast in flight.
    ``mesh`` (static) pins the promise-mask update to the slot
    partition."""
    epoch = state.epoch
    _, live = _instance_row(xp, sched, epoch)
    arr1a = live & ~decided_now & (state.c1a_tick + 1 == t) \
        & (state.c1a_epoch == epoch)
    promise = state.member & rank_lt(state.px_rnd_r, state.px_rnd_i,
                                     state.c1a_rank_r, state.c1a_rank_i) \
        & arr1a
    n_promise = promise.sum().astype(xp.int32)
    state = state._replace(
        px_rnd_r=xp.where(promise, state.c1a_rank_r, state.px_rnd_r),
        px_rnd_i=xp.where(promise, state.c1a_rank_i, state.px_rnd_i),
        c1b_mask=xp.where(arr1a, promise, state.c1b_mask),
        c1b_tick=xp.where(arr1a, t, state.c1b_tick),
        c1b_epoch=xp.where(arr1a, epoch, state.c1b_epoch),
    )
    counts = {"px1b_senders": xp.where(arr1a, n_promise, 0).astype(xp.int32)}
    state = sharding.constrain_tree(state, mesh, state.member.shape[0])
    return state, counts


def task_phase(xp, state, sched: FallbackSchedule, t, n, decided_now,
               mesh=None):
    """Task-phase sends at tick ``t``: scripted proposes (fast-round vote
    broadcast + own-vote registration + timer arming, in that order per
    the oracle's ``FastPaxos.propose``), then timer fires (phase-1a
    broadcast). Propose tasks hold pre-start scheduler handles, so they
    run before timer tasks due the same tick; a decision this tick
    cancelled every timer before the task queue ran. ``mesh`` (static)
    pins the timer/rank updates to the slot partition after the
    coordinator argmax/gather."""
    epoch = state.epoch
    e, live = _instance_row(xp, sched, epoch)
    pid = sched.prop_pid[e]

    send = live & state.member & (pid >= 0) & (sched.prop_tick[e] == t)
    n_send = send.sum().astype(xp.int32)
    # register_fast_round_vote: only while the slot's rank round is <= 1
    reg = send & (state.px_rnd_r <= 1)
    state = state._replace(
        px_rnd_r=xp.where(reg, 1, state.px_rnd_r),
        px_rnd_i=xp.where(reg, 1, state.px_rnd_i),
        px_vrnd_r=xp.where(reg, 1, state.px_vrnd_r),
        px_vrnd_i=xp.where(reg, 1, state.px_vrnd_i),
        px_vval=xp.where(reg, pid, state.px_vval),
        px_timer=xp.where(send, t + sched.prop_delay[e], state.px_timer),
    )

    fire = state.member & ~decided_now & (state.px_timer == t)
    n_fire = fire.sum().astype(xp.int32)
    coord = xp.argmax(fire).astype(xp.int32)
    rank_i = classic_rank_index(xp, state.uid_hi, state.uid_lo)[coord]
    any_fire = fire.any()
    slots = xp.arange(state.px_crnd_r.shape[0], dtype=xp.int32)
    state = state._replace(
        px_timer=xp.where(fire, I32_MAX, state.px_timer),
        c1a_tick=xp.where(any_fire, t, state.c1a_tick),
        c1a_coord=xp.where(any_fire, coord, state.c1a_coord),
        c1a_rank_r=xp.where(any_fire, 2, state.c1a_rank_r),
        c1a_rank_i=xp.where(any_fire, rank_i, state.c1a_rank_i),
        c1a_epoch=xp.where(any_fire, epoch, state.c1a_epoch),
        px_crnd_r=xp.where(any_fire & (slots == coord), 2, state.px_crnd_r),
        px_crnd_i=xp.where(any_fire & (slots == coord), rank_i,
                           state.px_crnd_i),
    )
    counts = {
        "pxvote_senders": n_send,
        "pxvote_recipients": xp.where(send.any(), n, 0).astype(xp.int32),
        "px1a_senders": n_fire,
        "px1a_recipients": xp.where(any_fire, n, 0).astype(xp.int32),
    }
    state = sharding.constrain_tree(state, mesh, state.member.shape[0])
    return state, counts


# ---------------------------------------------------------------------------
# host planner: envelope validation + outcome prediction
# ---------------------------------------------------------------------------


def np_ring0_positions(uids: np.ndarray, member: np.ndarray) -> np.ndarray:
    """Host mirror of ``ring0_positions`` over uint64 uids (host-side, so
    it runs its own boot lexsort via ``topology.ring_permutations``)."""
    from rapid_tpu.engine.topology import ring_permutations

    hi, lo = hashing.np_to_limbs(np.asarray(uids, np.uint64))
    order, rank = ring_permutations(np, hi, lo, 1)
    return np.asarray(ring0_positions(np, np.asarray(member, bool),
                                      order, rank))


def host_coordinator_rule(n: int, positions: Dict[int, int],
                          votes: Dict[int, int]) -> int:
    """Python mirror of ``coordinator_rule_pid`` over slot -> ring0
    position and slot -> pid maps (voters only). Used by the planner to
    predict classic-round outcomes without running either simulation."""
    if not votes:
        return -1
    order = sorted(votes, key=lambda s: positions[s])
    first = positions[order[0]]
    m_star = max(n // 2 + 1, first + 1)
    prefix = [s for s in order if positions[s] < m_star]
    pids = [votes[s] for s in prefix]
    if len(set(pids)) == 1:
        return pids[0]
    counters: Dict[int, int] = {}
    for value in pids:
        count = counters.setdefault(value, 0)
        if count + 1 > n // 4:
            return value
        counters[value] = count + 1
    return pids[0]


def expovariate_delay_ticks(u: float, n: int, settings: Settings) -> int:
    """The oracle's ``FastPaxos.get_random_delay_ticks`` for a given
    uniform draw — base delay plus expovariate jitter with rate 1/N."""
    jitter_ms = -1000.0 * math.log(1.0 - u) * n
    return settings.fallback_base_delay_ticks + max(
        0, round(jitter_ms / settings.tick_ms))


def plan_fallback(
    n: int,
    values: Sequence[Sequence[int]],
    votes: Dict[int, Tuple[int, int]],
    delays: Dict[int, int],
    settings: Settings,
    uids: Optional[np.ndarray] = None,
    capacity: Optional[int] = None,
    epoch: int = 0,
    member: Optional[np.ndarray] = None,
) -> Tuple[FallbackSchedule, Dict[str, object]]:
    """Compile one contested instance and validate the envelope.

    ``values[p]`` lists the member slots proposal ``p`` removes;
    ``votes[s] = (tick, pid)`` scripts slot ``s``'s propose call;
    ``delays[s]`` is its fallback delay in ticks. ``member`` optionally
    names the live electorate as a bool ``[capacity]`` mask (defaults to
    slots ``[0, n)``) — used when chaining instances whose decisions
    removed members. Raises ``FallbackEnvelopeError`` for scenarios the
    batched kernel does not reproduce bit-identically. Returns the
    single-instance schedule plus an info dict with the predicted decide
    tick, mode and winning pid.
    """
    c = capacity if capacity is not None else n
    if member is None:
        member = np.zeros(c, bool)
        member[:n] = True
    else:
        member = np.asarray(member, bool)
    n_live = int(member.sum())
    if not values:
        raise FallbackEnvelopeError("need at least one proposal value")
    for p, val in enumerate(values):
        if not val:
            raise FallbackEnvelopeError(f"proposal {p} is empty")
        if any(s < 0 or s >= c or not member[s] for s in val):
            raise FallbackEnvelopeError(f"proposal {p} removes a non-member")
    if not votes:
        raise FallbackEnvelopeError("need at least one scripted propose")
    for s, (tick, pid) in votes.items():
        if s < 0 or s >= c or not member[s]:
            raise FallbackEnvelopeError(f"voter {s} is not a member")
        if not 0 <= pid < len(values):
            raise FallbackEnvelopeError(f"voter {s} votes unknown pid {pid}")
        if tick < 1:
            # The oracle can only schedule a propose at a future tick and
            # the engine sends during the task phase of tick >= 1.
            raise FallbackEnvelopeError(
                f"voter {s} proposes at tick {tick}; scripted proposes "
                "need tick >= 1")
        if s not in delays:
            raise FallbackEnvelopeError(f"voter {s} has no fallback delay")
        if delays[s] < 1:
            raise FallbackEnvelopeError(f"voter {s} delay must be >= 1")

    # Replay the fast-round tally on virtual time to find the decide tick.
    quorum = n_live - (n_live - 1) // 4
    by_arrival: Dict[int, List[int]] = {}
    for s, (tick, pid) in votes.items():
        by_arrival.setdefault(tick + 1, []).append(pid)
    counts: Dict[int, int] = {}
    total = 0
    fast_decide_tick = None
    fast_pid = None
    for arr in sorted(by_arrival):
        for pid in by_arrival[arr]:
            counts[pid] = counts.get(pid, 0) + 1
            total += 1
        if fast_decide_tick is None and total >= quorum:
            best = max(counts, key=lambda p: counts[p])
            if counts[best] >= quorum:
                fast_decide_tick, fast_pid = arr, best

    fires = {s: votes[s][0] + delays[s] for s in votes}
    min_fire = min(fires.values())
    info: Dict[str, object] = {"n": n_live, "quorum": quorum}

    if fast_decide_tick is not None:
        # Fast path, possibly racing a timer: a fire one tick before the
        # decision puts a phase-1a in flight that dies on arrival; any
        # earlier fire starts a real classic round mid-count.
        if min_fire < fast_decide_tick - 1:
            raise FallbackEnvelopeError(
                f"timer fires at {min_fire}, before the fast decision at "
                f"{fast_decide_tick} completes — outside the fleet-kernel "
                "envelope; run this mid-fast-count fire through "
                "run_adversarial_differential")
        info.update(mode="fast", decide_tick=fast_decide_tick,
                    winner=fast_pid,
                    racing=bool(min_fire == fast_decide_tick - 1))
    else:
        firing = [s for s, f in fires.items() if f == min_fire]
        if len(firing) != 1:
            raise FallbackEnvelopeError(
                f"{len(firing)} timers fire together at {min_fire}; the "
                "fleet kernel needs a unique first coordinator — run tied "
                "timers through run_adversarial_differential")
        decide = min_fire + 4  # 1a -> 1b -> 2a -> 2b -> decide
        late = [s for s, f in fires.items()
                if s != firing[0] and f < decide]
        if late:
            raise FallbackEnvelopeError(
                f"timers of {late} fire during the classic round "
                f"({min_fire}..{decide}); the oracle starts a rank race the "
                "fleet kernel cannot carry — run it through "
                "run_adversarial_differential")
        late_votes = [s for s, (tick, _) in votes.items() if tick >= min_fire]
        if late_votes:
            raise FallbackEnvelopeError(
                f"proposes of {late_votes} land mid-classic-round — run "
                "them through run_adversarial_differential")
        if uids is None:
            from rapid_tpu.engine.diff import default_endpoints
            from rapid_tpu.oracle.membership_view import uid_of
            uids = np.asarray([uid_of(e) for e in default_endpoints(c)],
                              np.uint64)
        pos = np_ring0_positions(np.asarray(uids, np.uint64), member)
        winner = host_coordinator_rule(
            n_live, {s: int(pos[s]) for s in votes},
            {s: pid for s, (_, pid) in votes.items()})
        info.update(mode="classic", decide_tick=decide, winner=winner,
                    coordinator=firing[0], fire_tick=min_fire)

    sched = empty_fallback_schedule(c, instances=1, pids=len(values))
    sched.inst_epoch[0] = epoch
    for s, (tick, pid) in votes.items():
        sched.prop_tick[0, s] = tick
        sched.prop_pid[0, s] = pid
        sched.prop_delay[0, s] = delays[s]
    for p, val in enumerate(values):
        sched.table_mask[0, p, list(val)] = True
    _fingerprint_tables(sched, uids, c)
    return sched, info


def _fingerprint_tables(sched: FallbackSchedule, uids, c: int) -> None:
    """Fill ``table_hi``/``table_lo`` from the masks (host-side numpy)."""
    if uids is None:
        from rapid_tpu.oracle.membership_view import uid_of

        from rapid_tpu.engine.diff import default_endpoints
        uids = np.asarray([uid_of(e) for e in default_endpoints(c)],
                          np.uint64)
    uhi, ulo = hashing.np_to_limbs(np.asarray(uids, np.uint64))
    for i in range(sched.table_mask.shape[0]):
        for p in range(sched.table_mask.shape[1]):
            hi, lo = proposal_fingerprint(np, sched.table_mask[i, p],
                                          uhi, ulo)
            sched.table_hi[i, p] = hi
            sched.table_lo[i, p] = lo


def concat_schedules(parts: Sequence[FallbackSchedule]) -> FallbackSchedule:
    """Stack single-instance schedules into one multi-instance script."""
    return FallbackSchedule(*[np.concatenate([getattr(p, f) for p in parts])
                              for f in FallbackSchedule._fields])


def synthetic_contested_schedule(
    n: int, settings: Settings, n_ticks: int, start: int = 5,
    period: Optional[int] = None, uids: Optional[np.ndarray] = None,
) -> Tuple[FallbackSchedule, Dict[str, object]]:
    """Benchmark workload: repeated two-way contested instances.

    Every ``period`` ticks the surviving members split into two camps
    proposing to remove two different members; no fast quorum forms, the
    lowest-slot member's timer fires after the base delay and the classic
    round decides 4 ticks later. The winner of each round (predicted with
    the host rule mirror) shapes the next instance's electorate.
    ``uids`` must match the engine state's identities (defaults to the
    differential harness endpoints).
    """
    if uids is None:
        from rapid_tpu.engine.diff import default_endpoints
        from rapid_tpu.oracle.membership_view import uid_of
        uids = np.asarray([uid_of(e) for e in default_endpoints(n)],
                          np.uint64)
    base = settings.fallback_base_delay_ticks
    round_len = base + 4
    if period is None:
        period = round_len + 6
    member = np.ones(n, bool)
    parts: List[FallbackSchedule] = []
    decides: List[int] = []
    tick = start
    epoch = 0
    while tick + round_len < n_ticks and member.sum() > 4:
        members = np.nonzero(member)[0]
        victims = members[-2:]
        values = [[int(victims[0])], [int(victims[1])]]
        votes = {int(s): (tick, int(i % 2))
                 for i, s in enumerate(members)}
        delays = {int(s): (base if s == members[0] else base + period)
                  for s in members}
        sched, info = plan_fallback(
            n, values, votes, delays, settings, uids=uids, capacity=n,
            epoch=epoch, member=member.copy())
        parts.append(sched)
        decides.append(int(info["decide_tick"]))
        member[values[int(info["winner"])]] = False
        tick += period
        epoch += 1
    info = {"instances": len(parts), "decide_ticks": decides,
            "period": period}
    if not parts:
        return empty_fallback_schedule(n), info
    return concat_schedules(parts), info


def build_delay_table(
    seed: int,
    capacity: int,
    n_draws: int,
    settings: Settings,
) -> np.ndarray:
    """Precompute every fallback-timer delay the per-receiver kernel can draw.

    The oracle draws ``u = rngs[slot].random()`` lazily, once per announce,
    and maps it through ``expovariate_delay_ticks(u, px.n)`` where ``px.n``
    is the *current instance size* — a value only known on device. The
    draw sequence per slot is deterministic (``adversary_rngs``), so the
    host can enumerate the first ``n_draws`` uniforms per slot and tabulate
    the delay for every possible instance size ``m`` in ``0..capacity``:
    ``table[slot, draw, m]``. The device then gathers
    ``table[r, draws[r], px_n[r]]`` — bit-exact including python's
    banker's rounding, which jnp.round does not reproduce.
    """
    from rapid_tpu.engine.adversary import adversary_rngs

    rngs = adversary_rngs(seed, capacity)
    table = np.zeros((capacity, n_draws, capacity + 1), np.int32)
    for s in range(capacity):
        for d in range(n_draws):
            u = rngs[s].random()
            for m in range(capacity + 1):
                table[s, d, m] = expovariate_delay_ticks(u, m, settings)
    return table
