"""Monitoring kernel: batched probe outcomes and tombstone counters.

Reproduces ``PingPongFailureDetector`` semantics over the whole ``[C, K]``
edge array at once. Per failure-detector tick (global ticks ``t`` with
``t % fd_interval == 0`` and ``t > fd_gate`` — the oracle aligns every
node's FD job to global tick multiples):

- a slot at/over the failure threshold notifies exactly once (the oracle
  checks the threshold *before* probing, so a saturated detector never
  probes again);
- every other active slot probes its subject: the probe fails if the
  subject or the observer is crashed, a link window blocks the
  observer->subject edge, or the fault model drops it probabilistically
  (the oracle's synchronous probe fast path evaluates reachability at
  probe time with exactly these checks);
- failed probes increment the per-edge tombstone counter.

A notification fans out to *all* rings covered by that unique subject via
``fd_first``, mirroring ``get_ring_numbers`` in the oracle's DOWN alert.
"""
from __future__ import annotations

from rapid_tpu import hashing
from rapid_tpu.engine.state import EngineFaults, EngineState


def crashed_at(faults: EngineFaults, tick):
    """bool [C]: crashed at ``tick`` (crash_tick <= tick)."""
    return faults.crash_tick <= tick


def link_window_active(xp, faults: EngineFaults, tick):
    """bool [W]: which link windows block at delivery tick ``tick``."""
    start = faults.link_start
    in_span = (start <= tick) & (tick < faults.link_end)
    period = xp.maximum(faults.link_period, 1)
    off_phase = (((tick - start) // period) % 2) == 0
    return in_span & xp.where(faults.link_period > 0, off_phase, True)


def link_blocked(xp, faults: EngineFaults, src_idx, dst_idx, tick):
    """Directed link-window drop mask for broadcastable slot-index arrays.

    Shape = broadcast of ``src_idx``/``dst_idx``. The number of windows is
    a static python int (tiny), so this is a python loop of W fused masked
    gathers — no ``[C, C]`` matrix is ever built, keeping the shared step
    usable at 100k slots. Returns all-False when the model has no windows.
    """
    shape = xp.broadcast_shapes(xp.shape(src_idx), xp.shape(dst_idx))
    blocked = xp.zeros(shape, bool)
    if faults.n_windows == 0:
        return blocked
    active = link_window_active(xp, faults, tick)
    for w in range(faults.n_windows):
        src_w, dst_w = faults.link_src[w], faults.link_dst[w]
        hit = src_w[src_idx] & dst_w[dst_idx]
        hit |= faults.link_two_way[w] & dst_w[src_idx] & src_w[dst_idx]
        blocked |= active[w] & hit
    return blocked


def link_blocked_matrix(xp, faults: EngineFaults, tick):
    """bool [C, C]: full directed edge drop matrix at delivery tick ``tick``.

    The per-receiver kernel evaluates reachability per (sender, receiver)
    edge for every wire class, so it pays for the dense matrix once per
    tick instead of W masked gathers per message set. Self-edges can block
    (a slot in both a window's src and dst sets drops its own broadcasts),
    exactly as the oracle's ``_edge_matrix``. All-False when no windows.
    """
    c = faults.crash_tick.shape[0]
    blocked = xp.zeros((c, c), bool)
    if faults.n_windows == 0:
        return blocked
    active = link_window_active(xp, faults, tick)
    for w in range(faults.n_windows):
        src_w, dst_w = faults.link_src[w], faults.link_dst[w]
        hit = src_w[:, None] & dst_w[None, :]
        hit |= faults.link_two_way[w] & (dst_w[:, None] & src_w[None, :])
        blocked |= active[w] & hit
    return blocked


def link_blocked_packed(xp, faults: EngineFaults, tick):
    """uint8 [C, ceil(C/8)]: ``link_blocked_matrix`` as little-endian
    bit-planes, built per window from the [C] slot masks — the dense
    [C, C] plane is never materialized. Row ``s`` packs the dst axis, so
    bit ``d`` of byte ``b`` in row ``s`` is ``blocked[s, 8*b + d]``;
    trailing pad bits (when C % 8 != 0) are always zero, matching
    ``xp.packbits``'s zero padding. Consumed by the pallas deliver
    kernel (``engine.rx_pallas``) next to the packed message planes.
    """
    c = faults.crash_tick.shape[0]
    blocked = xp.zeros((c, -(-c // 8)), xp.uint8)
    if faults.n_windows == 0:
        return blocked
    active = link_window_active(xp, faults, tick)
    zero = xp.uint8(0)
    for w in range(faults.n_windows):
        src_w, dst_w = faults.link_src[w], faults.link_dst[w]
        pdst = xp.packbits(dst_w, bitorder="little")
        psrc = xp.packbits(src_w, bitorder="little")
        hit = xp.where(src_w[:, None], pdst[None, :], zero)
        hit |= xp.where(faults.link_two_way[w] & dst_w[:, None],
                        psrc[None, :], zero)
        blocked |= xp.where(active[w], hit, zero)
    return blocked


def delay_matrix(xp, faults: EngineFaults, tick):
    """i32 [C, C]: extra delivery delay of a message sent src->dst at
    ``tick`` (send-time evaluation — latency is a property of the wire a
    message entered, while crash/window masks apply at delivery).

    Bit-matches ``faults.delay_of_slots``: jitter is the high limb of
    ``hash64(src ^ hash64(dst, seed=tick), seed=schedule_seed ^ 0x6A1770)``
    taken mod ``jitter_bound + 1`` (the seed xor is pre-materialized into
    ``delay_seed_hi/lo`` at lowering), the forward direction of a rule
    wins over its implied reverse, and overlapping rules combine by max.
    The number of rules is a static python int, so R = 0 returns a
    constant-zero matrix the compiler folds away; a padded inert rule
    (empty slot sets, bound 0) contributes exactly 0 on every edge, which
    is what makes fleet-stacking padding provably inert.
    """
    c = faults.crash_tick.shape[0]
    total = xp.zeros((c, c), xp.int32)
    if faults.n_delay_rules == 0:
        return total
    slots = xp.arange(c, dtype=xp.uint32)
    t32 = tick.astype(xp.uint32)
    thi, tlo = hashing.hash64_limbs_dynseed(
        xp, xp.zeros_like(slots), slots, xp.zeros_like(t32), t32)
    xhi = xp.broadcast_to(thi[None, :], (c, c))
    xlo = slots[:, None] ^ tlo[None, :]
    rhi, _ = hashing.hash64_limbs_dynseed(
        xp, xhi, xlo, faults.delay_seed_hi, faults.delay_seed_lo)
    for r in range(faults.n_delay_rules):
        active = ((faults.delay_start[r] <= tick)
                  & (tick < faults.delay_end[r]))
        src_r, dst_r = faults.delay_src[r], faults.delay_dst[r]
        fwd = src_r[:, None] & dst_r[None, :]
        rev = ((faults.delay_rev[r] >= 0)
               & (dst_r[:, None] & src_r[None, :]))
        jit = (rhi % (faults.delay_jit[r].astype(xp.uint32)
                      + xp.uint32(1))).astype(xp.int32)
        d = xp.where(fwd, faults.delay_base[r] + jit,
                     xp.where(rev,
                              xp.maximum(faults.delay_rev[r], 0) + jit, 0))
        total = xp.maximum(total, xp.where(active, d, 0))
    return total


def partitioned_edge_count(xp, faults: EngineFaults, member, tick):
    """i32 gauge: directed member->member pairs blocked by active windows.

    Counted per window (overlapping windows count once each — a cheap,
    deterministic definition that avoids materializing the [C, C] edge
    matrix), self-edges excluded.
    """
    if faults.n_windows == 0:
        return xp.int32(0)
    active = link_window_active(xp, faults, tick)
    total = xp.int32(0)
    for w in range(faults.n_windows):
        src_m = (faults.link_src[w] & member).sum().astype(xp.int32)
        dst_m = (faults.link_dst[w] & member).sum().astype(xp.int32)
        both = (faults.link_src[w] & faults.link_dst[w]
                & member).sum().astype(xp.int32)
        pairs = src_m * dst_m - both
        two = xp.where(faults.link_two_way[w], pairs, 0)
        total = total + xp.where(active[w], pairs + two, 0)
    return total


def edge_drop(xp, faults: EngineFaults, src_idx, dst_idx, uid_hi, uid_lo, tick):
    """bool with the shape of ``src_idx``: fault model drops src->dst now.

    Bit-matches ``faults._bernoulli``: drop iff the high 32 bits of
    ``hash64(src_uid ^ hash64(dst_uid, seed=tick), seed=drop_seed ^ 0xD809F)``
    are below ``p * 2^32``. ``drop_p`` is static, so the healthy case
    compiles to nothing.
    """
    if faults.drop_p <= 0.0:
        return xp.zeros(src_idx.shape, bool)
    dhi, dlo = uid_hi[dst_idx], uid_lo[dst_idx]
    t32 = tick.astype(xp.uint32)
    thi, tlo = hashing.hash64_limbs_dynseed(
        xp, dhi, dlo, xp.zeros_like(t32), t32)
    xhi = uid_hi[src_idx] ^ thi
    xlo = uid_lo[src_idx] ^ tlo
    rhi, _ = hashing.hash64_limbs(xp, xhi, xlo,
                                  seed=faults.drop_seed ^ 0xD809F)
    drop = rhi < xp.uint32(int(faults.drop_p * float(1 << 32)) & 0xFFFFFFFF)
    if faults.drop_targets is not None:
        applies = xp.zeros(src_idx.shape, bool)
        if faults.drop_ingress:
            applies |= faults.drop_targets[dst_idx]
        if faults.drop_egress:
            applies |= faults.drop_targets[src_idx]
        drop &= applies
    return drop


def monitor_tick(xp, state: EngineState, faults: EngineFaults, settings):
    """One FD interval for every node at once.

    Returns (fc, notified, notify_expanded, probes_sent, probes_failed):
    ``notify_expanded`` is the ``[C, K]`` per-(observer, ring) alert mask to
    feed the flush pipeline.
    """
    t = state.tick
    crashed = crashed_at(faults, t)
    obs_slots = xp.arange(state.fc.shape[0], dtype=xp.int32)[:, None]
    subj = state.subj_idx
    obs_bcast = xp.broadcast_to(obs_slots, subj.shape)
    probe_fail = (crashed[subj] | crashed[:, None]
                  | link_blocked(xp, faults, obs_bcast, subj, t)
                  | edge_drop(xp, faults, obs_bcast,
                              subj, state.uid_hi, state.uid_lo, t))

    at_threshold = state.fc >= settings.fd_failure_threshold
    probing = state.fd_active & ~at_threshold
    notify_now = state.fd_active & at_threshold & ~state.notified
    notified = state.notified | notify_now
    fc = xp.where(probing & probe_fail, state.fc + 1, state.fc)

    # Fan the unique-subject notification out to every ring it covers.
    notify_expanded = xp.take_along_axis(notify_now, state.fd_first, axis=1)
    probes_sent = probing.sum().astype(xp.int32)
    probes_failed = (probing & probe_fail).sum().astype(xp.int32)
    return fc, notified, notify_expanded, probes_sent, probes_failed
