"""Engine array state: the slot universe and the per-tick carry.

Array layout
------------
The engine works over a fixed *slot universe* of ``capacity`` slots, one per
simulated node (slot order = node creation order, which for the oracle's
static bootstrap equals endpoint order). Slots beyond the initial
membership are *dormant*: present in every array, excluded by the
``member`` mask, and activated when a decided join proposal lands (see
``rapid_tpu.engine.churn``). All protocol state is slot-indexed:

- identity: 64-bit node uids as ``(hi, lo)`` uint32 limb pairs (TPUs have no
  native 64-bit ints; see ``rapid_tpu.hashing``), plus per-slot membership
  and identifier fingerprints for the running configuration-id sums;
- topology: the static per-ring hash order ``ring_order``/``ring_rank``
  (lexsorted once at boot by ``topology.ring_permutations``; moved only
  by UUID-retry identifier redraws via ``topology.rank_and_insert``),
  and the derived ``subj_idx[n, k]`` / ``obs_idx[n, k]`` — node ``n``'s
  ring-``k`` subject (predecessor) and observer (successor) slot, plus
  ``gk_idx`` — a dormant slot's join gatekeepers — re-scanned sort-free
  from that order on every view change;
- monitoring: per unique-subject tombstone counters ``fc`` and the
  notified-once latch, mirroring ``PingPongFailureDetector``;
- alert pipeline: the oracle's enqueue -> flush(+1 tick) -> deliver(+1 tick)
  path as two ``[capacity, K]`` report buffers, with a parallel
  ``[capacity]`` churn pipeline for scheduled join/leave alerts;
- cut detection: the per-(destination, ring) report matrix plus the
  announced-proposal latch, mirroring ``MultiNodeCutDetector``; the
  ``seen_down`` latch mirrors the detector's
  ``_seen_link_down_events`` gate on edge invalidation;
- consensus: the pending fast-round vote and its proposal fingerprint;
- ``epoch`` counts decided view changes — the device-side stand-in for the
  oracle's configuration-id checks at alert-enqueue time.

Scenario envelope
-----------------
The *shared-state* engine in this package reproduces the oracle
bit-for-bit for crash-fault scenarios plus scheduled join/leave churn
(``rapid_tpu.engine.diff`` asserts it): crashes make every alive receiver
see the identical alert stream, so one shared cut-detector state stands
in for all N per-node detectors. Fault models that split the receiver
set — asymmetric partitions, flip-flop links, bursts straddling FD
intervals — are handled *exactly* by the per-receiver adversary engine
(``rapid_tpu.engine.adversary`` + ``diff.run_adversarial_differential``),
which replicates detector/consensus state per node. The shared step still
applies link-window masks to its failure-detector probes (``EngineFaults``
link fields below), so link faults perturb monitoring at benchmark scale,
but its shared cut state remains an approximation for them. For *on
device* exactness under link faults, ``ReceiverState`` (below) +
``rapid_tpu.engine.receiver`` replicate view state per receiver — the
memory-heavy mode fleet lowering selects per member kind. The churn
envelope (what join/leave schedules the shared state reproduces exactly)
is documented in ``rapid_tpu.engine.churn``.
"""
from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import numpy as np

from rapid_tpu import hashing
from rapid_tpu.settings import Settings

I32_MAX = np.iinfo(np.int32).max


class EngineFaults:
    """Device-side fault model (crash + probe drop + link windows).

    ``crash_tick[n]`` is the tick at/after which slot ``n`` is crashed
    (``I32_MAX`` = never). ``drop_p``/``drop_seed``/``drop_targets`` mirror
    ``faults.PacketDropFault`` via the same splitmix64 Bernoulli draw, so a
    future drop-scenario differential can bit-match the oracle.

    The ``link_*`` arrays window-encode ``faults.LinkWindow`` directed
    reachability masks: window ``w`` blocks src->dst deliveries at tick
    ``t`` when ``link_src[w, src] & link_dst[w, dst]`` and the window is
    active (``link_start[w] <= t < link_end[w]`` and, for flip-flop
    windows with ``link_period[w] > 0``, the off-phase
    ``((t - start) // period) % 2 == 0``); ``link_two_way[w]`` also blocks
    the reverse direction. ``W = 0`` (the default) compiles the link logic
    out entirely — the step branches on the static leading dimension.

    The ``delay_*`` arrays rule-encode ``faults.DelayRule`` per-edge link
    latencies for the per-receiver delivery ring: rule ``r`` holds a
    src->dst slot-set pair, a base delay (``delay_base``), a jitter bound
    (``delay_jit``, drawn per (edge, send tick) via the shared hash with
    seed limbs ``delay_seed_hi/lo``), an optional reverse-direction base
    (``delay_rev``, -1 = none), and an active tick range. ``R = 0``
    compiles the delay logic out (``monitor.delay_matrix`` returns a
    constant-zero matrix the compiler folds away).

    Registered as a pytree with the drop *configuration* as static aux data:
    the step function branches on ``drop_p`` in Python, so it must not be a
    traced leaf — changing it retriggers a (cheap, rare) retrace instead.
    """

    def __init__(self, crash_tick, drop_p: float = 0.0, drop_seed: int = 0,
                 drop_targets=None, drop_ingress: bool = True,
                 drop_egress: bool = True, link_src=None, link_dst=None,
                 link_start=None, link_end=None, link_period=None,
                 link_two_way=None, delay_src=None, delay_dst=None,
                 delay_base=None, delay_rev=None, delay_jit=None,
                 delay_start=None, delay_end=None, delay_seed_hi=None,
                 delay_seed_lo=None) -> None:
        self.crash_tick = crash_tick  # i32 [C]
        self.drop_p = float(drop_p)
        self.drop_seed = int(drop_seed)
        self.drop_targets = drop_targets  # bool [C] or None = everywhere
        self.drop_ingress = bool(drop_ingress)
        self.drop_egress = bool(drop_egress)
        self.link_src = link_src          # bool [W, C] or None (W = 0)
        self.link_dst = link_dst          # bool [W, C]
        self.link_start = link_start      # i32 [W]
        self.link_end = link_end          # i32 [W]
        self.link_period = link_period    # i32 [W] (0 = static window)
        self.link_two_way = link_two_way  # bool [W]
        self.delay_src = delay_src        # bool [R, C] or None (R = 0)
        self.delay_dst = delay_dst        # bool [R, C]
        self.delay_base = delay_base      # i32 [R]
        self.delay_rev = delay_rev        # i32 [R] (-1 = no reverse delay)
        self.delay_jit = delay_jit        # i32 [R] jitter bound (inclusive)
        self.delay_start = delay_start    # i32 [R]
        self.delay_end = delay_end        # i32 [R]
        self.delay_seed_hi = delay_seed_hi  # u32 scalar jitter-hash seed
        self.delay_seed_lo = delay_seed_lo  # u32 scalar

    @property
    def n_windows(self) -> int:
        return 0 if self.link_src is None else int(self.link_src.shape[0])

    @property
    def n_delay_rules(self) -> int:
        return 0 if self.delay_src is None else int(self.delay_src.shape[0])

    def tree_flatten(self):
        children = (self.crash_tick, self.drop_targets, self.link_src,
                    self.link_dst, self.link_start, self.link_end,
                    self.link_period, self.link_two_way, self.delay_src,
                    self.delay_dst, self.delay_base, self.delay_rev,
                    self.delay_jit, self.delay_start, self.delay_end,
                    self.delay_seed_hi, self.delay_seed_lo)
        aux = (self.drop_p, self.drop_seed, self.drop_targets is None,
               self.drop_ingress, self.drop_egress)
        return children, aux

    @classmethod
    def tree_unflatten(cls, aux, children):
        (crash_tick, drop_targets, link_src, link_dst, link_start,
         link_end, link_period, link_two_way, delay_src, delay_dst,
         delay_base, delay_rev, delay_jit, delay_start, delay_end,
         delay_seed_hi, delay_seed_lo) = children
        drop_p, drop_seed, targets_none, ingress, egress = aux
        return cls(crash_tick, drop_p, drop_seed,
                   None if targets_none else drop_targets, ingress, egress,
                   link_src, link_dst, link_start, link_end, link_period,
                   link_two_way, delay_src, delay_dst, delay_base,
                   delay_rev, delay_jit, delay_start, delay_end,
                   delay_seed_hi, delay_seed_lo)


def _register_faults() -> None:
    import jax

    jax.tree_util.register_pytree_node(
        EngineFaults,
        lambda f: f.tree_flatten(),
        EngineFaults.tree_unflatten,
    )


_register_faults()


class EngineState(NamedTuple):
    tick: object                      # i32 scalar (absolute oracle tick)
    member: object                    # bool [C]
    uid_hi: object                    # u32 [C]
    uid_lo: object                    # u32 [C]
    mfp_hi: object                    # u32 [C] member-fingerprint limbs
    mfp_lo: object                    # u32 [C]
    idfp_hi: object                   # u32 [C] identifier-fp limbs (joiners)
    idfp_lo: object                   # u32 [C]
    idsum_hi: object                  # u32 scalar: identifier-fp sum
    idsum_lo: object                  # u32 scalar
    memsum_hi: object                 # u32 scalar: member-fp sum
    memsum_lo: object                 # u32 scalar
    # static per-ring hash order (boot-time lexsort; only identifier
    # redraws move it, via topology.rank_and_insert)
    ring_order: object                # i32 [C, K] slot at each ring position
    ring_rank: object                 # i32 [C, K] ring position of each slot
    # topology (re-scanned from ring_order/ring_rank on view change)
    subj_idx: object                  # i32 [C, K]
    obs_idx: object                   # i32 [C, K]
    gk_idx: object                    # i32 [C, K] join gatekeepers (dormant rows)
    fd_active: object                 # bool [C, K] first-ring slot per unique subject
    fd_first: object                  # i32 [C, K] first ring slot with same subject
    # monitoring
    fc: object                        # i32 [C, K] failure counters (active slots)
    notified: object                  # bool [C, K] notified-once latch
    fd_gate: object                   # i32 scalar: probes only at t > fd_gate
    # alert pipeline (per observer slot x ring, already ring-expanded)
    pending_flush: object             # bool [C, K]: notified at t, flushes t+1
    pending_deliver: object           # bool [C, K]: flushed at t, delivers t+1
    # churn alert pipeline (per *destination* slot; sources via obs/gk_idx)
    churn_flush: object               # bool [C]: enqueued at t, flushes t+1
    churn_deliver: object             # bool [C]: flushed at t, delivers t+1
    # cut detection (shared detector of all alive receivers)
    reports: object                   # bool [C, K] per (dst, ring)
    seen_down: object                 # bool scalar: DOWN alert seen this config
    announced: object                 # bool scalar
    proposal: object                  # bool [C] announced proposal mask
    announce_tick: object             # i32 scalar
    vote_pending: object              # bool scalar: votes in flight
    voters: object                    # bool [C] who voted at announce_tick
    phash_hi: object                  # u32 scalar proposal fingerprint
    phash_lo: object                  # u32 scalar
    epoch: object                     # i32 scalar: decided view changes so far
    # classic-Paxos fallback (rapid_tpu.engine.paxos). Per-slot rank pairs
    # mirror the oracle's Rank(round, node_index); the c1a/c1b/c2a/c2b
    # scalars are the one in-flight classic chain (single round per
    # instance within the fallback envelope). Inert zeros when the step
    # runs with fallback=None.
    px_rnd_r: object                  # i32 [C] promised rank (round, index)
    px_rnd_i: object
    px_vrnd_r: object                 # i32 [C] accepted-vote rank
    px_vrnd_i: object
    px_vval: object                   # i32 [C] accepted proposal pid (-1 none)
    px_crnd_r: object                 # i32 [C] coordinator's own rank
    px_crnd_i: object
    px_cval: object                   # i32 [C] coordinator's chosen pid
    px_timer: object                  # i32 [C] fallback fire tick (I32_MAX)
    px_pos: object                    # i32 [C] ring-0 position among members
    c1a_tick: object                  # i32: phase-1a broadcast send tick
    c1a_coord: object                 # i32: coordinator slot
    c1a_rank_r: object                # i32: coordinator rank
    c1a_rank_i: object
    c1a_epoch: object                 # i32: config epoch at send
    c1b_tick: object                  # i32: phase-1b unicast send tick
    c1b_epoch: object
    c1b_mask: object                  # bool [C]: promisers
    c2a_tick: object                  # i32: phase-2a broadcast send tick
    c2a_rank_r: object
    c2a_rank_i: object
    c2a_pid: object                   # i32: value in flight (-1 none)
    c2a_epoch: object
    c2b_tick: object                  # i32: phase-2b broadcast send tick
    c2b_cnt: object                   # i32: accepting acceptors
    c2b_pid: object
    c2b_epoch: object


class StepLog(NamedTuple):
    """Per-tick observable outputs collected by ``lax.scan``.

    Counter fields are small per-tick *factors* (numbers of senders and
    recipients), not products: at 100k nodes the products overflow int32 and
    jax without x64 has no int64, so the host computes ``sent = flushers *
    recipients`` etc. exactly in Python (see ``diff.expand_counters``).

    The trailing gauge fields are protocol observables for the telemetry
    layer (``rapid_tpu.telemetry``): end-of-tick snapshots of alert-pipeline
    occupancy, cut-detector fill toward H, fast-round vote progress, and the
    configuration epoch. They are log-only — nothing in the step reads them.
    """

    tick: object                      # i32
    announce_now: object              # bool
    proposal: object                  # bool [C]
    decide_now: object                # bool
    decision: object                  # bool [C]
    config_hi: object                 # u32 (config id after this tick)
    config_lo: object                 # u32
    n_member: object                  # i32 (after this tick)
    probes_sent: object               # i32
    probes_failed: object             # i32
    flushers: object                  # i32: nodes broadcasting an alert batch
    flush_recipients: object          # i32: membership size at flush
    flushers_alive: object            # i32: batches surviving src-crash check
    deliver_alive: object             # i32: alive recipients at delivery
    vote_senders: object              # i32: nodes broadcasting a fast vote
    vote_recipients: object           # i32
    vote_senders_alive: object        # i32: votes surviving src-crash check
    vote_deliver_alive: object        # i32
    # --- telemetry gauges (end-of-tick snapshots) -----------------------
    alerts_in_flight: object          # i32: alert batches in the pipeline
    cut_reports: object               # i32: filled (dst, ring) report cells
    implicit_reports: object          # i32: cells added by edge invalidation
    vote_tally: object                # i32: best proposal's delivered votes
    quorum: object                    # i32: fast quorum at the vote count
    epoch: object                     # i32: config epoch after this tick
    churn_injected: object            # i32: churn alerts enqueued this tick
    partitioned_edges: object         # i32: directed member pairs blocked by
                                      # active link windows (per window, self
                                      # edges excluded; 0 when W = 0)
    link_dropped: object              # i32: deliveries dropped by link masks
                                      # this tick (0 in the shared step,
                                      # whose delivery path is crash-only)
    # --- classic-Paxos fallback factors + gauges ------------------------
    pxvote_senders: object            # i32: scripted fast-vote broadcasters
    pxvote_recipients: object         # i32
    px1a_senders: object              # i32: phase-1a broadcasters (timer fires)
    px1a_recipients: object           # i32
    px1b_senders: object              # i32: promisers (unicast: 1 recipient)
    px2a_senders: object              # i32: coordinators sending phase 2a
    px2a_recipients: object           # i32
    px2b_senders: object              # i32: acceptors sending phase 2b
    px2b_recipients: object           # i32
    px_timers_armed: object           # i32 gauge: armed fallback timers
    px_coord_round: object            # i32 gauge: max classic round started
    # --- on-device invariant monitor (rapid_tpu.engine.invariants) ------
    inv_bits: object                  # i32: violation bitmask (0 = clean;
                                      # constant 0 when the monitor is off)


class ReceiverState(NamedTuple):
    """Per-receiver protocol state: every slot carries its *own* view.

    The shared-state ``EngineState`` stands in for all N per-node detector
    and consensus copies — exact for crash faults, an approximation for
    link faults (see the module docstring). ``ReceiverState`` replicates
    the view-dependent state per receiver: ``member``/``reports``/topology
    become ``[C, C(, K)]`` with axis 0 the *receiver* slot, and the wire
    is explicit (one bounded in-flight *delivery ring* per message kind),
    so ``LinkWindow`` reachability is evaluated at delivery per (sender,
    receiver) edge — bit-exact against ``engine.adversary`` for link-fault
    and link-delay scenarios. Memory is quadratic by design;
    ``engine.receiver.receiver_state_bytes`` sizes it and
    ``Settings.receiver_capacity_cap`` bounds it.

    Wire layout: every wire tensor carries a leading ``[D]`` axis
    (``D = Settings.delivery_ring_depth``) indexed by arrival tick mod D —
    a message sent at tick ``t`` on an edge with delay ``d`` lands in ring
    slot ``(t + 1 + d) % D`` and is read back when the engine reaches that
    tick. The per-sender broadcast fan (formerly separate ``*_bcast``
    snapshots) is resolved at send time into the ``[D, C, C]`` presence
    rings, since per-edge delays split one broadcast across ring slots.
    ``D = 1`` with no delay rules is exactly the old next-tick wire.

    Naming: ``rx_*``/``own_*`` are per-receiver-diagonal quantities (the
    slot's own row in its own view), ``w*`` are wire rings (stamped at
    send, delivered at their arrival slot), ``pf``/``pd`` the alert
    batcher pipeline (pending-flush / in-flight ring), ``pb``/``p2`` the
    phase-1b / phase-2b stores of a slot acting as coordinator / listener.
    """

    tick: object            # i32
    # --- identity (replicated statics) -------------------------------
    uid_hi: object          # u32 [C]
    uid_lo: object          # u32 [C]
    mfp_hi: object          # u32 [C] membership fingerprints
    mfp_lo: object          # u32 [C]
    idsum_hi: object        # u32 scalar
    idsum_lo: object        # u32 scalar
    rank_idx: object        # i32 [C] classic-Paxos rank index per slot
    ring_order: object      # i32 [C, K] static boot ring order
    ring_rank: object       # i32 [C, K]
    delay_table: object     # i32 [C, D, C+1] precomputed fallback delays
    draws: object           # i32 [C] fallback-delay draws consumed
    # --- per-receiver view -------------------------------------------
    member: object          # bool [C, C]: row r = r's membership view
    memsum_hi: object       # u32 [C]
    memsum_lo: object       # u32 [C]
    cfg_hi: object          # u32 [C] configuration id per receiver
    cfg_lo: object          # u32 [C]
    epoch: object           # i32 [C]
    stopped: object         # bool [C]: r decided itself out of the view
    rx_pos: object          # i32 [C]: r's ring-0 position in its own view
    px_n: object            # i32 [C]: r's paxos instance size
    # --- per-receiver topology ---------------------------------------
    obs_full: object        # i32 [C, C, K]: observer table in r's view
    own_subj: object        # i32 [C, K]: r's own ring subjects
    own_fd_active: object   # bool [C, K]
    own_fd_first: object    # i32 [C, K]
    # --- failure detectors -------------------------------------------
    fc: object              # i32 [C, K] tombstone counters
    notified: object        # bool [C, K]
    fd_gate: object         # i32 [C]: FD jobs fire at t % I == 0, t > gate
    # --- alert batcher pipeline --------------------------------------
    pf: object              # bool [C, K]: enqueued this tick (flush next)
    pf_dst: object          # i32 [C, K]
    pf_cfg_hi: object       # u32 [C] cfg stamp at enqueue
    pf_cfg_lo: object       # u32 [C]
    pd: object              # bool [D, C, K]: batch in-flight delivery ring
    pd_dst: object          # i32 [D, C, K]
    pd_cfg_hi: object       # u32 [D, C]
    pd_cfg_lo: object       # u32 [D, C]
    pd_bcast: object        # bool [D, C, C] recipient snapshot at flush
    # --- cut detector ------------------------------------------------
    reports: object         # bool [C, C, K] (receiver, dst, ring)
    seen_down: object       # bool [C]
    announced: object       # bool [C]
    ar_seq: object          # i32 [C]: announce order key t*(C+1)+rx_pos
    # --- proposal registry (never cleared; fp -> member mask) --------
    reg_valid: object       # bool [C]
    reg_mask: object        # bool [C, C] announced proposal of slot r
    reg_fp_hi: object       # u32 [C]
    reg_fp_lo: object       # u32 [C]
    # --- fast-round votes --------------------------------------------
    wv: object              # bool [D, C, C] vote ring (sender, receiver)
    wv_fp_hi: object        # u32 [D, C]
    wv_fp_lo: object        # u32 [D, C]
    wv_cfg_hi: object       # u32 [D, C]
    wv_cfg_lo: object       # u32 [D, C]
    wv_seq: object          # i32 [D, C] sender announce-order key
    vt_seen: object         # bool [C, C] (receiver, voter)
    vt_fp_hi: object        # u32 [C, C]
    vt_fp_lo: object        # u32 [C, C]
    # --- classic-Paxos per-receiver instance -------------------------
    px_rnd_r: object        # i32 [C]
    px_rnd_i: object        # i32 [C]
    px_vrnd_r: object       # i32 [C]
    px_vrnd_i: object       # i32 [C]
    px_vv_fp_hi: object     # u32 [C] accepted value fingerprint
    px_vv_fp_lo: object     # u32 [C]
    px_vv_set: object       # bool [C]
    px_crnd_r: object       # i32 [C] (crnd index is rank_idx when set)
    px_cval_set: object     # bool [C]
    px_timer: object        # i32 [C] absolute fire tick, I32_MAX idle
    # --- phase-1b store (coordinator, promiser) ----------------------
    pb_seen: object         # bool [C, C]
    pb_vrnd_r: object       # i32 [C, C]
    pb_vrnd_i: object       # i32 [C, C]
    pb_fp_hi: object        # u32 [C, C]
    pb_fp_lo: object        # u32 [C, C]
    pb_set: object          # bool [C, C] vval non-empty
    pb_seq: object          # i32 [C, C] send key t*(C+1)+rx_pos(promiser)
    # --- phase-2b store (listener, acceptor), single tracked round ---
    p2_rnd: object          # i32 [C] rank index of tracked round, -1 none
    p2_seen: object         # bool [C, C]
    p2_mask: object         # bool [C, C] decide contents (member mask)
    # --- wires: phase 1a ---------------------------------------------
    w1a: object             # bool [D, C, C] (coordinator, receiver)
    w1a_cfg_hi: object      # u32 [D, C]
    w1a_cfg_lo: object      # u32 [D, C]
    w1a_seq: object         # i32 [D, C] announce key (within-tick order)
    w1a_tick: object        # i32 [D, C] send tick (cross-tick order)
    # --- wires: phase 1b (promiser, coordinator) ---------------------
    w1b: object             # bool [D, C, C]
    w1b_vrnd_r: object      # i32 [D, C] payload per promiser
    w1b_vrnd_i: object      # i32 [D, C]
    w1b_fp_hi: object       # u32 [D, C]
    w1b_fp_lo: object       # u32 [D, C]
    w1b_set: object         # bool [D, C]
    w1b_cfg_hi: object      # u32 [D, C]
    w1b_cfg_lo: object      # u32 [D, C]
    w1b_seq: object         # i32 [D, C] send key t*(C+1)+rx_pos(promiser)
    # --- wires: phase 2a ---------------------------------------------
    w2a: object             # bool [D, C, C] (coordinator, receiver)
    w2a_fp_hi: object       # u32 [D, C]
    w2a_fp_lo: object       # u32 [D, C]
    w2a_mask: object        # bool [D, C, C] resolved proposal on the wire
    w2a_cfg_hi: object      # u32 [D, C]
    w2a_cfg_lo: object      # u32 [D, C]
    w2a_seq: object         # i32 [D, C] announce key (within-tick order)
    w2a_tick: object        # i32 [D, C] send tick (cross-tick order)
    # --- wires: phase 2b, up to 2 accepts per acceptor per tick ------
    w2b: object             # bool [D, 2, C, C] (slot, acceptor, receiver)
    w2b_rnd: object         # i32 [D, 2, C] rank index of accepted round
    w2b_fp_hi: object       # u32 [D, 2, C]
    w2b_fp_lo: object       # u32 [D, 2, C]
    w2b_mask: object        # bool [D, 2, C, C]
    w2b_cfg_hi: object      # u32 [D, C] one snapshot per acceptor
    w2b_cfg_lo: object      # u32 [D, C]
    # --- envelope / error flags (sticky bitmask, see receiver.FLAGS) --
    flags: object           # i32 scalar


class ReceiverStepLog(NamedTuple):
    """Per-tick outputs of the per-receiver step.

    Unlike ``StepLog`` these are exact on-device counter *values* (the
    per-receiver wire makes sender x recipient products cheap and int32-
    safe at per-receiver scales), matching ``AdversaryRun`` tick rows
    field for field; event masks carry per-slot announce/decide streams
    for ``diff.run_receiver_differential``.
    """

    tick: object            # i32
    sent: object            # i32
    delivered: object       # i32
    dropped: object         # i32
    probes_sent: object     # i32
    probes_failed: object   # i32
    fv_sent: object         # i32 per-phase pairs, oracle _PHASE_OF order
    fv_delivered: object    # i32
    p1a_sent: object        # i32
    p1a_delivered: object   # i32
    p1b_sent: object        # i32
    p1b_delivered: object   # i32
    p2a_sent: object        # i32
    p2a_delivered: object   # i32
    p2b_sent: object        # i32
    p2b_delivered: object   # i32
    partitioned_edges: object   # i32 (over non-crashed slots, per window)
    link_dropped: object    # i32
    announce: object        # bool [C] slot announced its proposal this tick
    ann_prop: object        # bool [C, C] the announced proposal masks
    ann_cfg_hi: object      # u32 [C] cfg at announce (pre-decide)
    ann_cfg_lo: object      # u32 [C]
    decide: object          # bool [C] slot decided a view change this tick
    dec_hosts: object       # bool [C, C] removed hosts
    dec_cfg_hi: object      # u32 [C] cfg after the decide
    dec_cfg_lo: object      # u32 [C]
    flags: object           # i32 sticky envelope/error bitmask snapshot


def config_id_limbs(xp, idsum_hi, idsum_lo, memsum_hi, memsum_lo):
    """Limb version of ``membership_view.configuration_id``."""
    shi, slo = hashing.splitmix64_limbs(xp, idsum_hi, idsum_lo)
    hi, lo = hashing.add64(xp, shi, slo, memsum_hi, memsum_lo)
    return hashing.splitmix64_limbs(xp, hi, lo)


def state_config_id(state: EngineState) -> int:
    """Current configuration id of the engine state as a python int."""
    import jax.numpy as jnp

    hi, lo = config_id_limbs(jnp, state.idsum_hi, state.idsum_lo,
                             state.memsum_hi, state.memsum_lo)
    return hashing.from_limbs(int(hi), int(lo))


def init_state(uids: Sequence[int], id_fp_sum: int, settings: Settings,
               start_tick: int = 0, member: Optional[Sequence[bool]] = None,
               id_fps: Optional[Sequence[int]] = None) -> EngineState:
    """Build the engine state for a converged membership plus dormant slots.

    ``uids`` are the 64-bit node identities in slot order (from
    ``membership_view.uid_of`` for oracle parity, or any synthetic uint64s
    for benchmarks); ``id_fp_sum`` is the oracle's identifier-fingerprint
    sum over the *initial members* (``MembershipView._id_fp_sum``), carried
    so configuration ids agree. ``member`` marks the initially-active
    slots (default: all); ``id_fps`` carries each dormant slot's
    identifier fingerprint (``membership_view.id_fingerprint`` of the
    NodeId it will join with), added to the identifier sum when its join
    is decided. If ``settings.capacity`` exceeds ``len(uids)``, extra
    inert dormant slots pad the universe to that capacity.
    """
    import jax.numpy as jnp

    from rapid_tpu.engine.paxos import ring0_positions
    from rapid_tpu.engine.topology import build_topology, ring_permutations
    from rapid_tpu.oracle.membership_view import _SEED_MEMBER

    uids_np = np.asarray(uids, dtype=np.uint64)
    member_np = (np.ones(len(uids_np), bool) if member is None
                 else np.asarray(member, bool))
    id_fps_np = (np.zeros(len(uids_np), np.uint64) if id_fps is None
                 else np.asarray(id_fps, dtype=np.uint64))
    if settings.capacity > len(uids_np):
        pad = settings.capacity - len(uids_np)
        pad_uids = np.asarray(
            [hashing.hash64(i, seed=0x636170) for i in range(pad)],
            dtype=np.uint64)
        uids_np = np.concatenate([uids_np, pad_uids])
        member_np = np.concatenate([member_np, np.zeros(pad, bool)])
        id_fps_np = np.concatenate([id_fps_np, np.zeros(pad, np.uint64)])
    c = len(uids_np)
    k = settings.K
    uid_hi, uid_lo = hashing.np_to_limbs(uids_np)
    mhi, mlo = hashing.hash64_limbs(np, uid_hi, uid_lo, seed=_SEED_MEMBER)
    memsum = sum(int(h) << 32 | int(l)
                 for h, l, m in zip(mhi, mlo, member_np) if m) & hashing.MASK64
    ifp_hi, ifp_lo = hashing.np_to_limbs(id_fps_np)
    idh, idl = hashing.to_limbs(id_fp_sum)
    msh, msl = hashing.to_limbs(memsum)

    # The once-per-universe lexsort: host numpy, before anything touches
    # the device. Every later view change re-scans this static order.
    ring_order_np, ring_rank_np = ring_permutations(np, uid_hi, uid_lo, k)

    member_arr = jnp.asarray(member_np)
    uid_hi = jnp.asarray(uid_hi)
    uid_lo = jnp.asarray(uid_lo)
    ring_order = jnp.asarray(ring_order_np)
    ring_rank = jnp.asarray(ring_rank_np)
    subj_idx, obs_idx, gk_idx, fd_active, fd_first = build_topology(
        jnp, member_arr, ring_order, ring_rank)
    zero_ck_i = jnp.zeros((c, k), jnp.int32)
    zero_ck_b = jnp.zeros((c, k), bool)
    u32 = lambda v: jnp.uint32(v)
    return EngineState(
        tick=jnp.int32(start_tick),
        member=member_arr,
        uid_hi=uid_hi, uid_lo=uid_lo,
        mfp_hi=jnp.asarray(mhi), mfp_lo=jnp.asarray(mlo),
        idfp_hi=jnp.asarray(ifp_hi), idfp_lo=jnp.asarray(ifp_lo),
        idsum_hi=u32(idh), idsum_lo=u32(idl),
        memsum_hi=u32(msh), memsum_lo=u32(msl),
        ring_order=ring_order, ring_rank=ring_rank,
        subj_idx=subj_idx, obs_idx=obs_idx, gk_idx=gk_idx,
        fd_active=fd_active, fd_first=fd_first,
        fc=zero_ck_i, notified=zero_ck_b,
        fd_gate=jnp.int32(start_tick),
        pending_flush=zero_ck_b, pending_deliver=zero_ck_b,
        churn_flush=jnp.zeros((c,), bool),
        churn_deliver=jnp.zeros((c,), bool),
        reports=zero_ck_b,
        seen_down=jnp.asarray(False),
        announced=jnp.asarray(False),
        proposal=jnp.zeros((c,), bool),
        announce_tick=jnp.int32(-1),
        vote_pending=jnp.asarray(False),
        voters=jnp.zeros((c,), bool),
        phash_hi=u32(0), phash_lo=u32(0),
        epoch=jnp.int32(0),
        px_rnd_r=jnp.zeros((c,), jnp.int32),
        px_rnd_i=jnp.zeros((c,), jnp.int32),
        px_vrnd_r=jnp.zeros((c,), jnp.int32),
        px_vrnd_i=jnp.zeros((c,), jnp.int32),
        px_vval=jnp.full((c,), -1, jnp.int32),
        px_crnd_r=jnp.zeros((c,), jnp.int32),
        px_crnd_i=jnp.zeros((c,), jnp.int32),
        px_cval=jnp.full((c,), -1, jnp.int32),
        px_timer=jnp.full((c,), I32_MAX, jnp.int32),
        px_pos=ring0_positions(jnp, member_arr, ring_order, ring_rank),
        c1a_tick=jnp.int32(I32_MAX), c1a_coord=jnp.int32(0),
        c1a_rank_r=jnp.int32(0), c1a_rank_i=jnp.int32(0),
        c1a_epoch=jnp.int32(-1),
        c1b_tick=jnp.int32(I32_MAX), c1b_epoch=jnp.int32(-1),
        c1b_mask=jnp.zeros((c,), bool),
        c2a_tick=jnp.int32(I32_MAX), c2a_rank_r=jnp.int32(0),
        c2a_rank_i=jnp.int32(0), c2a_pid=jnp.int32(-1),
        c2a_epoch=jnp.int32(-1),
        c2b_tick=jnp.int32(I32_MAX), c2b_cnt=jnp.int32(0),
        c2b_pid=jnp.int32(-1), c2b_epoch=jnp.int32(-1),
    )


def crash_faults(crash_ticks: Sequence[int]) -> EngineFaults:
    """EngineFaults for a pure crash scenario; I32_MAX/None = never."""
    import jax.numpy as jnp

    arr = np.array([I32_MAX if t is None else t for t in crash_ticks],
                   dtype=np.int32)
    return EngineFaults(crash_tick=jnp.asarray(arr))


def link_faults(crash_ticks: Sequence[int], windows,
                capacity: int, delays=(), delay_seed: int = 0) -> EngineFaults:
    """EngineFaults for crashes plus ``faults.LinkWindow`` link masks plus
    ``faults.DelayRule`` per-edge latencies.

    ``windows``/``delays`` are sequences of slot-indexed rules; empty
    sequences degenerate to ``crash_faults`` (W = 0 / R = 0, the link and
    delay logic compiled out). ``delay_seed`` is the schedule seed feeding
    the shared per-(edge, tick) jitter hash.
    """
    import jax.numpy as jnp

    base = crash_faults(crash_ticks)
    windows = tuple(windows)
    delays = tuple(delays)
    kw = {}
    if windows:
        w = len(windows)
        src = np.zeros((w, capacity), bool)
        dst = np.zeros((w, capacity), bool)
        start = np.zeros(w, np.int32)
        end = np.zeros(w, np.int32)
        period = np.zeros(w, np.int32)
        two_way = np.zeros(w, bool)
        for i, win in enumerate(windows):
            src[i, list(win.src_slots)] = True
            dst[i, list(win.dst_slots)] = True
            start[i] = win.start_tick
            end[i] = min(win.end_tick, I32_MAX)
            period[i] = win.period_ticks
            two_way[i] = win.two_way
        kw.update(
            link_src=jnp.asarray(src), link_dst=jnp.asarray(dst),
            link_start=jnp.asarray(start), link_end=jnp.asarray(end),
            link_period=jnp.asarray(period),
            link_two_way=jnp.asarray(two_way))
    if delays:
        r = len(delays)
        dsrc = np.zeros((r, capacity), bool)
        ddst = np.zeros((r, capacity), bool)
        dbase = np.zeros(r, np.int32)
        drev = np.zeros(r, np.int32)
        djit = np.zeros(r, np.int32)
        dstart = np.zeros(r, np.int32)
        dend = np.zeros(r, np.int32)
        for i, rule in enumerate(delays):
            dsrc[i, list(rule.src_slots)] = True
            ddst[i, list(rule.dst_slots)] = True
            dbase[i] = rule.delay_ticks
            drev[i] = rule.reverse_delay_ticks
            djit[i] = rule.jitter_ticks
            dstart[i] = rule.start_tick
            dend[i] = min(rule.end_tick, I32_MAX)
        shi, slo = hashing.to_limbs((delay_seed ^ 0x6A1770) & hashing.MASK64)
        kw.update(
            delay_src=jnp.asarray(dsrc), delay_dst=jnp.asarray(ddst),
            delay_base=jnp.asarray(dbase), delay_rev=jnp.asarray(drev),
            delay_jit=jnp.asarray(djit), delay_start=jnp.asarray(dstart),
            delay_end=jnp.asarray(dend),
            delay_seed_hi=jnp.uint32(shi), delay_seed_lo=jnp.uint32(slo))
    if not kw:
        return base
    return EngineFaults(crash_tick=base.crash_tick, **kw)


def pad_link_windows(faults: EngineFaults, w: int) -> EngineFaults:
    """Pad the link-window tensors to exactly ``w`` rows with inert windows.

    An inert window has empty endpoint sets and ``start == end == 0``, so
    it is never active, blocks no edge, and contributes zero to
    ``partitioned_edge_count``. Fleet mode (``rapid_tpu.engine.fleet``)
    stacks member fault pytrees with ``jnp.stack``, which requires every
    member to share one treedef and shape — padding all members to the
    fleet's max W is how schedules with different window counts batch.
    ``w == n_windows`` is a no-op; shrinking is an error.
    """
    import jax.numpy as jnp

    cur = faults.n_windows
    if w == cur:
        return faults
    if w < cur:
        raise ValueError(f"cannot shrink {cur} link windows to {w}")
    c = int(faults.crash_tick.shape[0])
    pad = w - cur

    def grow(existing, fill_dtype, row_shape):
        tail = jnp.zeros((pad,) + row_shape, fill_dtype)
        if existing is None:
            return tail
        return jnp.concatenate([existing, tail], axis=0)

    return EngineFaults(
        crash_tick=faults.crash_tick,
        drop_p=faults.drop_p, drop_seed=faults.drop_seed,
        drop_targets=faults.drop_targets,
        drop_ingress=faults.drop_ingress, drop_egress=faults.drop_egress,
        link_src=grow(faults.link_src, bool, (c,)),
        link_dst=grow(faults.link_dst, bool, (c,)),
        link_start=grow(faults.link_start, jnp.int32, ()),
        link_end=grow(faults.link_end, jnp.int32, ()),
        link_period=grow(faults.link_period, jnp.int32, ()),
        link_two_way=grow(faults.link_two_way, bool, ()),
        delay_src=faults.delay_src, delay_dst=faults.delay_dst,
        delay_base=faults.delay_base, delay_rev=faults.delay_rev,
        delay_jit=faults.delay_jit, delay_start=faults.delay_start,
        delay_end=faults.delay_end,
        delay_seed_hi=faults.delay_seed_hi,
        delay_seed_lo=faults.delay_seed_lo)


def pad_delay_rules(faults: EngineFaults, r: int) -> EngineFaults:
    """Pad the delay-rule tensors to exactly ``r`` rows with inert rules.

    An inert rule has empty slot sets, zero base/jitter, no reverse
    direction, and ``start == end == 0``, so every edge falls through to
    the zero-delay default and the jitter hash is drawn mod 1 — provably
    zero regardless of seed (``tests/test_delay.py`` pins this
    bit-identically). Members with *no* delay rules get their seed limbs
    materialized as zeros so all stacked members share one treedef.
    ``r == n_delay_rules`` on a member that already has rules is a no-op;
    shrinking is an error.
    """
    import jax.numpy as jnp

    cur = faults.n_delay_rules
    if r == cur:
        # r == 0: the whole stack is delay-free, None leaves match.
        # r > 0: link_faults materialized the seed limbs already.
        return faults
    if r < cur:
        raise ValueError(f"cannot shrink {cur} delay rules to {r}")
    c = int(faults.crash_tick.shape[0])
    pad = r - cur

    def grow(existing, fill_dtype, row_shape, fill=0):
        tail = jnp.full((pad,) + row_shape, fill, fill_dtype)
        if existing is None:
            return tail
        return jnp.concatenate([existing, tail], axis=0)

    u32 = lambda v: jnp.uint32(0) if v is None else v
    return EngineFaults(
        crash_tick=faults.crash_tick,
        drop_p=faults.drop_p, drop_seed=faults.drop_seed,
        drop_targets=faults.drop_targets,
        drop_ingress=faults.drop_ingress, drop_egress=faults.drop_egress,
        link_src=faults.link_src, link_dst=faults.link_dst,
        link_start=faults.link_start, link_end=faults.link_end,
        link_period=faults.link_period, link_two_way=faults.link_two_way,
        delay_src=grow(faults.delay_src, bool, (c,)),
        delay_dst=grow(faults.delay_dst, bool, (c,)),
        delay_base=grow(faults.delay_base, jnp.int32, ()),
        delay_rev=grow(faults.delay_rev, jnp.int32, (), fill=-1),
        delay_jit=grow(faults.delay_jit, jnp.int32, ()),
        delay_start=grow(faults.delay_start, jnp.int32, ()),
        delay_end=grow(faults.delay_end, jnp.int32, ()),
        delay_seed_hi=u32(faults.delay_seed_hi),
        delay_seed_lo=u32(faults.delay_seed_lo))
