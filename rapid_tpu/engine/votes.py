"""Consensus kernel: Fast Paxos fast-round vote counting.

Mirrors ``FastPaxos._handle_fast_round_proposal``: every alive node that
announced a proposal broadcasts one fast-round vote; a receiver decides
when it has seen at least ``N - floor((N-1)/4)`` votes total (the fast
quorum) *and* one proposal value holds that many votes.

Votes are counted as a segmented bincount over 64-bit proposal
fingerprints: sort the (hi, lo) vote hashes, mark segment starts, and
``segment_sum`` the valid votes — O(C log C), no [C, C] comparison matrix.
The engine's crash-fault pipeline produces a single proposal value per
configuration (every alive receiver aggregates the identical alert
stream), but the counter is written for the general multi-proposal case so
the classic-round fallback kernel (``engine.paxos``) can reuse it.
"""
from __future__ import annotations

import jax

from rapid_tpu import hashing
from rapid_tpu.engine import sharding


def proposal_fingerprint(xp, proposal_mask, uid_hi, uid_lo):
    """64-bit fingerprint of a proposal mask, as (hi, lo) uint32 scalars.

    Order-independent sum of per-member hashes finalized with splitmix64 —
    the same shape as the configuration-id formula, so identical proposals
    hash identically regardless of slot order.
    """
    phi, plo = hashing.hash64_limbs(xp, uid_hi, uid_lo, seed=0x70726F70)
    m = proposal_mask.astype(xp.uint32)
    shi, slo = hashing.sum64(xp, phi * m, plo * m)
    return hashing.splitmix64_limbs(xp, shi, slo)


def segmented_vote_count(xp, vote_hi, vote_lo, valid, mesh=None):
    """i32 [C]: for each slot, the number of valid votes equal to its vote.

    Invalid slots count 0. Ties are grouped by sorting on (valid, hi, lo)
    and summing run lengths with ``segment_sum``.

    ``mesh`` (static) re-commits the slot sharding on the scattered
    output: the lexsort itself is a global all-gather (sorting is the
    one cross-slot stage of the tally), but the constraint stops the
    replicated layout from leaking into the consumers — the per-slot
    count vector re-partitions before the quorum reductions.
    """
    c = vote_hi.shape[0]
    invalid = (~valid).astype(xp.uint32)
    order = xp.lexsort((vote_lo, vote_hi, invalid))
    shi = vote_hi[order]
    slo = vote_lo[order]
    sval = valid[order]
    prev_differs = xp.ones((c,), bool).at[1:].set(
        (shi[1:] != shi[:-1]) | (slo[1:] != slo[:-1]))
    seg_id = xp.cumsum(prev_differs.astype(xp.int32)) - 1
    seg_counts = jax.ops.segment_sum(sval.astype(xp.int32), seg_id,
                                     num_segments=c)
    counts_sorted = seg_counts[seg_id] * sval.astype(xp.int32)
    out = xp.zeros((c,), xp.int32).at[order].set(counts_sorted)
    return sharding.constrain(out, mesh, c)


def scan_vote_count(xp, vote_hi, vote_lo, valid, mesh=None):
    """i32 [C]: same tally as ``segmented_vote_count``, lowered through
    an associative scan instead of ``segment_sum``.

    The ring dissemination variant (``rapid_tpu.variants.ring``) counts
    votes by circulating partial tallies around the static ring-0 order;
    this kernel is its aggregation core: after the same lexsort, a
    forward max-scan propagates each run's start index and the count of
    a run is ``end - start + 1`` — the prefix-sum shape a ring lap
    lowers to, with no segment scatter. Bit-identical to
    ``segmented_vote_count`` over every (mask, fingerprint) input;
    ``tests/test_variants.py`` property-tests the pair.
    """
    c = vote_hi.shape[0]
    invalid = (~valid).astype(xp.uint32)
    order = xp.lexsort((vote_lo, vote_hi, invalid))
    shi = vote_hi[order]
    slo = vote_lo[order]
    sval = valid[order]
    prev_differs = xp.ones((c,), bool).at[1:].set(
        (shi[1:] != shi[:-1]) | (slo[1:] != slo[:-1]))
    idx = xp.arange(c, dtype=xp.int32)
    # Forward max-scan propagates each run's start index; the mirrored
    # reverse min-scan propagates its (inclusive) end index. A run's
    # count is then a prefix-sum difference over the valid mask, so
    # invalid slots (sorted last, but possibly fingerprint-equal to a
    # valid run's tail) contribute zero, exactly as segment_sum does.
    start = jax.lax.associative_scan(
        xp.maximum, xp.where(prev_differs, idx, -1))
    next_differs = xp.ones((c,), bool).at[:-1].set(prev_differs[1:])
    run_end = jax.lax.associative_scan(
        xp.minimum, xp.where(next_differs, idx, c), reverse=True)
    csum = xp.cumsum(sval.astype(xp.int32))
    base = xp.where(start > 0, csum[xp.maximum(start - 1, 0)], 0)
    counts_sorted = (csum[run_end] - base) * sval.astype(xp.int32)
    out = xp.zeros((c,), xp.int32).at[order].set(counts_sorted)
    return sharding.constrain(out, mesh, c)


def fast_quorum(xp, n_member):
    """The fast-round quorum as the reference computes it:
    ``N - floor((N-1)/4)``, i.e. ``N - f`` for ``f = floor((N-1)/4)``.

    This is *not* ceil(3N/4): they diverge whenever ``N % 4 == 0``
    (e.g. N=4 -> 4 vs ceil(3N/4)=3, N=8 -> 7 vs 6).
    ``tests/test_paxos.py`` pins this against the oracle at small N.
    """
    return (n_member - (n_member - 1) // 4).astype(xp.int32)


def count_fast_round(xp, vote_hi, vote_lo, valid, n_member, mesh=None):
    """Returns (decided, winner_count): quorum check over delivered votes.

    ``valid[n]`` marks a delivered vote from slot n; a decision needs both
    the total delivered votes and some single value's count at quorum.
    ``mesh`` (static) keeps the per-slot tally partitioned — see
    ``segmented_vote_count``.
    """
    quorum = fast_quorum(xp, n_member)
    per_vote = segmented_vote_count(xp, vote_hi, vote_lo, valid, mesh=mesh)
    winner_count = per_vote.max()
    total = valid.sum().astype(xp.int32)
    return (total >= quorum) & (winner_count >= quorum), winner_count
