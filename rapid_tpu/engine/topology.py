"""Topology kernel: the K-ring observer/subject graph as index arrays.

Each ring ``k`` orders the membership by ``hash64(uid, seed=k)`` with the
uid as tiebreak — the *same* sort key the oracle's ``MembershipView`` uses
(it adds the Endpoint as a final tiebreak, reachable only on a full 128-bit
collision), computed with the *same* ``hash64_limbs`` — so ring order agrees
by construction (SURVEY.md §7 "hash parity").

The ring order is an *invariant of the slot universe*: node identities are
fixed at boot and churn only flips the ``member`` mask, so the per-ring
lexsort runs exactly once — ``ring_permutations`` sorts each ring at boot
into static ``ring_order[C, K]`` / ``ring_rank[C, K]`` arrays carried in
``EngineState``. A view change is then sort-free: gather the ``member``
mask into ring order, cumsum it into member ranks, and compact the
members so every ring-neighbour query is rank arithmetic plus a gather —
one O(C) scan per ring instead of an O(C log C) sort, with no
``lexsort``/``argsort`` traced in the jitted step. The scan yields two
things from the same static order:

- member ring neighbours: predecessor = subject, successor = observer;
- joiner gatekeepers: a dormant slot's nearest member *predecessor* is
  exactly the oracle's ``get_expected_observers_of`` — the predecessor of
  the joiner's would-be ring position (MembershipView.java:292-303).

The one event that can move a slot's ring position — a UUID-retry
identifier redraw during a join handshake — is applied incrementally by
``rank_and_insert``: recompute the redrawn slot's rank per ring with an
O(C) smaller-key count and shift the order arrays by one position, no
global re-sort. The churn planner schedules redraws through the
``ChurnSchedule`` (``rapid_tpu.engine.churn``) so they stay inside the
jitted path.

Everything is shape-static and jit-compatible: membership changes only
flip the ``member`` mask and re-run the scans over the static order.
"""
from __future__ import annotations

import numpy as np

from rapid_tpu import hashing
from rapid_tpu.engine import sharding as sharding_mod


#: Block width of the member scans in ``_pred_succ_pos`` — pinned to 8:
#: each block is one ``packbits`` byte, so every block-local scan is a
#: pure table lookup on that byte.
_SCAN_BLOCK = 8


def _scan_luts():
    """Per-byte nearest-set-bit tables, [256, 8] int8 plus [256] int8.

    For mask ``m`` and bit ``j``: ``pred[m, j]`` is the last set bit
    strictly below ``j`` when bit ``j`` is set (a member's predecessor
    query) and at-or-below ``j`` otherwise (a non-member's gatekeeper
    query), -1 if none; ``succ[m, j]`` is the first set bit strictly
    above ``j``, 8 if none; ``last``/``first`` are the block carries.
    """
    bits = ((np.arange(256)[:, None] >> np.arange(8)[None, :]) & 1) \
        .astype(bool)
    pred = np.full((256, 8), -1, np.int8)
    succ = np.full((256, 8), 8, np.int8)
    last = np.full(256, -1, np.int8)
    first = np.full(256, 8, np.int8)
    for m in range(256):
        for j in range(8):
            below = np.flatnonzero(bits[m, :j + (0 if bits[m, j] else 1)])
            if below.size:
                pred[m, j] = below[-1]
            above = np.flatnonzero(bits[m, j + 1:])
            if above.size:
                succ[m, j] = above[0] + j + 1
        set_ = np.flatnonzero(bits[m])
        if set_.size:
            last[m] = set_[-1]
            first[m] = set_[0]
    return pred, succ, last, first


_LUT_PRED, _LUT_SUCC, _LUT_LAST, _LUT_FIRST = _scan_luts()


def _pred_succ_pos(xp, member_s, n, mesh=None):
    """Nearest-member ring positions from the mask in ring order.

    Returns ``(pgpos, succpos)``, i32 ``[C]``: for ring position ``p``,
    ``pgpos[p]`` is the position of the last member strictly before
    ``p`` (members) or at-or-before ``p`` (non-members — the gatekeeper
    query), and ``succpos[p]`` the first member strictly after ``p``;
    both wrap around the ring, and an empty view falls back to position
    0 (bit-identical to the legacy sort path's empty-ring scan).

    The device path blocks the scans ``[C/B, B]`` with *block-local*
    int8 offsets, so the O(C·log C) log-depth associative scan shrinks
    to one short reduce-window per block plus a B-times-smaller i32
    carry scan — this and the two gathers that consume the positions
    are what bounds ``build_topology``'s FLOPs/bytes, no scatter and no
    prefix-sum compaction anywhere in the tick path (XLA's CPU scatter
    alone cost more wall clock than the whole kernel does now).

    Under a device ``mesh``, the whole block scan is pinned *replicated*
    (``sharding.replicate``): the ``[C/8]`` block carries are smaller
    than the mesh, and letting the partitioner spread them produced a
    miscompile (shard-padding garbage out of the ``bprev[:-1]`` slice on
    the CPU backend). The scan is already global — its input is the
    ring-ordered gather of the member mask — so replication costs one
    small all-gather the kernel needed anyway; ``build_topology``
    re-shards the final index arrays.
    """
    c = member_s.shape[0]
    if xp is np:
        pos = np.arange(c, dtype=np.int32)
        prev_incl = np.maximum.accumulate(
            np.where(member_s, pos, np.int32(-1)))
        prev_excl = np.concatenate([np.full(1, -1, np.int32),
                                    prev_incl[:-1]])
        pgpos = np.where(member_s, prev_excl, prev_incl)
        lastf = np.where(n > 0, prev_incl[-1], np.int32(0))
        pgpos = np.where(pgpos < 0, lastf, pgpos).astype(np.int32)
        next_incl = np.minimum.accumulate(
            np.where(member_s, pos, np.int32(c))[::-1])[::-1]
        next_excl = np.concatenate([next_incl[1:], np.full(1, c, np.int32)])
        firstf = np.where(n > 0, next_incl[0], np.int32(0))
        succpos = np.where(next_excl >= c, firstf,
                           next_excl).astype(np.int32)
        return pgpos, succpos

    from jax import lax

    b = _SCAN_BLOCK
    # packbits zero-pads the last byte, and zero bits are non-members,
    # so no explicit padding is needed anywhere.
    member_s = sharding_mod.replicate(member_s, mesh)
    packed = xp.packbits(member_s, bitorder="little")  # uint8 [ceil(C/8)]
    packed = sharding_mod.replicate(packed, mesh)
    nb = packed.shape[0]
    base = xp.arange(nb, dtype=xp.int32) * b
    end = xp.int32(nb * b)  # past-the-end sentinel for the carries

    # Forward: the block-local "last member at-or-before (strictly
    # before for members)" scan is a pure per-byte table lookup — a
    # gather, zero FLOPs — and only the i32 carry scan over per-block
    # last-member positions runs at full precision, on C/8 elements.
    # The ring wrap is resolved on the carries too, never on the full
    # vector.
    loc = xp.asarray(_LUT_PRED)[packed]          # int8 [nb, 8]
    blast = xp.asarray(_LUT_LAST)[packed]        # int8 [nb]
    bpos = xp.where(blast >= 0, base + blast, xp.int32(-1))
    bprev = lax.cummax(bpos)
    lastf = xp.where(n > 0, bprev[-1], xp.int32(0))
    bprev_excl = xp.concatenate([xp.full(1, -1, xp.int32), bprev[:-1]])
    bprev_excl = xp.where(bprev_excl < 0, lastf, bprev_excl)
    bprev_excl = sharding_mod.replicate(bprev_excl, mesh)
    pgpos = xp.where(loc >= 0, base[:, None] + loc.astype(xp.int32),
                     bprev_excl[:, None]).reshape(-1)[:c]
    pgpos = sharding_mod.replicate(pgpos, mesh)

    # Backward mirror: first member strictly after, local sentinel B,
    # block carries wrapped to the first member (padding bits are
    # non-members, so they never perturb a real position's answer, and
    # a local hit is always a real member — wrap only ever comes from
    # the carries).
    sloc = xp.asarray(_LUT_SUCC)[packed]         # int8 [nb, 8]
    bfirst = xp.asarray(_LUT_FIRST)[packed]      # int8 [nb]
    bposf = xp.where(bfirst < b, base + bfirst, end)
    bnext = lax.cummin(bposf, reverse=True)
    firstf = xp.where(n > 0, bnext[0], xp.int32(0))
    bnext_excl = xp.concatenate([bnext[1:], end[None]])
    bnext_excl = xp.where(bnext_excl >= c, firstf, bnext_excl)
    bnext_excl = sharding_mod.replicate(bnext_excl, mesh)
    succpos = xp.where(sloc < b, base[:, None] + sloc.astype(xp.int32),
                       bnext_excl[:, None]).reshape(-1)[:c]
    succpos = sharding_mod.replicate(succpos, mesh)
    return pgpos, succpos


def _inverse_permutation(xp, perm, pos):
    """rank[slot] = position, given order[position] = slot — an O(C)
    scatter, not an argsort."""
    if xp is np:
        rank = np.empty_like(perm)
        rank[perm] = pos
        return rank
    return xp.zeros_like(perm).at[perm].set(pos)


def ring_permutations(xp, uid_hi, uid_lo, k: int):
    """The static per-ring sort: (ring_order, ring_rank), each i32 [C, K].

    ``ring_order[p, j]`` is the slot at sorted position ``p`` of ring
    ``j``; ``ring_rank[s, j]`` is slot ``s``'s position (they are inverse
    permutations per ring). This is the *only* place the per-ring lexsort
    runs — boot time (``engine.state.init_state``), never inside the
    jitted step. Key order matches the oracle: ``hash64(uid, seed=ring)``
    primary, uid tiebreak.
    """
    pos = xp.arange(uid_hi.shape[0], dtype=xp.int32)
    orders, ranks = [], []
    for ring in range(k):
        khi, klo = hashing.hash64_limbs(xp, uid_hi, uid_lo, seed=ring)
        # last key is primary: (key_hi, key_lo, uid_hi, uid_lo)
        order = xp.lexsort((uid_lo, uid_hi, klo, khi)).astype(xp.int32)
        orders.append(order)
        ranks.append(_inverse_permutation(xp, order, pos))
    return xp.stack(orders, axis=1), xp.stack(ranks, axis=1)


def rank_and_insert(xp, slot, uid_hi, uid_lo, ring_order, ring_rank):
    """Re-rank one slot after an identifier redraw — sort-free.

    ``uid_hi``/``uid_lo`` must already carry ``slot``'s *new* limbs. Per
    ring: recompute the slot's rank as the count of lexicographically
    smaller keys (O(C) compares, keys are unique because uids are), then
    shift every rank between the old and new position by one and scatter
    the inverse back into the order array. ``slot`` may be a traced i32
    scalar. Returns the updated (ring_order, ring_rank).
    """
    c, k = ring_order.shape
    pos = xp.arange(c, dtype=xp.int32)
    new_orders, new_ranks = [], []
    for ring in range(k):
        khi, klo = hashing.hash64_limbs(xp, uid_hi, uid_lo, seed=ring)
        skhi, sklo = khi[slot], klo[slot]
        suhi, sulo = uid_hi[slot], uid_lo[slot]
        less = (khi < skhi) | ((khi == skhi) & (
            (klo < sklo) | ((klo == sklo) & (
                (uid_hi < suhi) | ((uid_hi == suhi) & (uid_lo < sulo))))))
        r_new = less.sum().astype(xp.int32)
        rank = ring_rank[:, ring]
        r_old = rank[slot]
        shift_down = ((rank > r_old) & (rank <= r_new)).astype(xp.int32)
        shift_up = ((rank >= r_new) & (rank < r_old)).astype(xp.int32)
        rank = rank - shift_down + shift_up
        rank = xp.where(pos == slot, r_new, rank)
        new_ranks.append(rank)
        new_orders.append(_inverse_permutation(xp, rank, pos))
    return xp.stack(new_orders, axis=1), xp.stack(new_ranks, axis=1)


def build_topology(xp, member, ring_order, ring_rank, mesh=None):
    """Compute (subj_idx, obs_idx, gk_idx, fd_active, fd_first), each ``[C, K]``,
    from the static per-ring order — no sort traced.

    ``mesh`` (static) re-commits the slot sharding on every output: the
    per-ring nearest-member scans gather through the global ring
    permutation (inherently cross-slot), so the constraint is what
    brings the rebuilt ``[C, K]`` index arrays back to the partitioned
    layout the rest of the tick consumes. ``mesh=None`` (and the host
    ``xp=np`` path) compiles to the identical kernel as before.

    - ``subj_idx[n, j]``: slot of node n's ring-j subject (predecessor);
    - ``obs_idx[n, j]``: slot of node n's ring-j observer (successor);
    - ``gk_idx[n, j]``: for a *non-member* slot n, its ring-j join
      gatekeeper (the member preceding its would-be position); member rows
      point at themselves;
    - ``fd_active[n, j]``: True on the *first* ring slot of each unique
      subject of n — the oracle creates one failure detector per unique
      subject (``MembershipService._create_failure_detectors`` dedupes in
      ring order), so monitor state lives at these slots;
    - ``fd_first[n, j]``: the first ring slot with the same subject as slot
      j (= j itself where ``fd_active``), used to fan a notification back
      out to every ring it covers.

    Non-member rows of ``subj_idx``/``obs_idx`` point at themselves and are
    fully masked.
    """
    c, k = ring_order.shape
    member = member.astype(bool)
    n = member.sum().astype(xp.int32)
    slots = xp.arange(c, dtype=xp.int32)

    subj_cols = []
    obs_cols = []
    gk_cols = []
    for ring in range(k):
        order = ring_order[:, ring]
        rank = ring_rank[:, ring]
        member_s = member[order]

        # The legacy nearest-member scan pair, but over the static boot
        # order: pgpos doubles as the member's subject position and the
        # non-member's gatekeeper position. Positions become slots by
        # gathering through each slot's own ring position and then
        # through the order — one shared [2, C] gather pair for both
        # neighbour columns.
        pgpos, succpos = _pred_succ_pos(xp, member_s, n, mesh=mesh)
        if xp is np:
            pg = order[pgpos[rank]]
            succ = order[succpos[rank]]
        else:
            vals = order[xp.stack([pgpos, succpos])[:, rank]]
            pg, succ = vals[0], vals[1]
        subj_cols.append(xp.where(member, pg, slots))
        obs_cols.append(xp.where(member, succ, slots))
        gk_cols.append(xp.where(member, slots, pg))
    subj_idx = xp.stack(subj_cols, axis=1)
    obs_idx = xp.stack(obs_cols, axis=1)
    gk_idx = xp.stack(gk_cols, axis=1)

    # Dedup per unique subject: slot j is active iff no earlier ring slot
    # has the same subject, and fd_first[:, j] is the first such slot — a
    # first-occurrence scan over the (static, small) K ring slots. Largest
    # temporary is [C], replacing the [C, K, K] pairwise-eq tensor that
    # used to dominate this kernel's temp_bytes.
    usable = member & (n >= 2)  # a <=1-member view has no subjects
    fd_first_cols = []
    fd_active_cols = []
    for j in range(k):
        first = xp.full((c,), j, xp.int32)
        for i in range(j - 1, -1, -1):
            first = xp.where(subj_cols[i] == subj_cols[j], xp.int32(i), first)
        fd_first_cols.append(first)
        fd_active_cols.append((first == j) & usable)
    fd_first = xp.stack(fd_first_cols, axis=1)
    fd_active = xp.stack(fd_active_cols, axis=1)
    if mesh is not None and xp is not np:
        con = lambda a: sharding_mod.constrain(a, mesh, c)
        subj_idx, obs_idx, gk_idx, fd_active, fd_first = map(
            con, (subj_idx, obs_idx, gk_idx, fd_active, fd_first))
    return subj_idx, obs_idx, gk_idx, fd_active, fd_first
