"""Topology kernel: the K-ring observer/subject graph as index arrays.

Each ring ``k`` orders the membership by ``hash64(uid, seed=k)`` with the
uid as tiebreak — the *same* sort key the oracle's ``MembershipView`` uses
(it adds the Endpoint as a final tiebreak, reachable only on a full 128-bit
collision), computed with the *same* ``hash64_limbs`` — so ring order agrees
by construction (SURVEY.md §7 "hash parity").

Non-members sort after all members via a leading non-member key, so one
``lexsort`` over the full slot universe yields members in ring order as a
prefix; successors/predecessors wrap around within that prefix. Everything
is shape-static and jit-compatible: membership changes only flip the
``member`` mask and re-run the sort.
"""
from __future__ import annotations

from rapid_tpu import hashing


def build_topology(xp, uid_hi, uid_lo, member, k: int):
    """Compute (subj_idx, obs_idx, fd_active, fd_first), each ``[C, K]``.

    - ``subj_idx[n, j]``: slot of node n's ring-j subject (predecessor);
    - ``obs_idx[n, j]``: slot of node n's ring-j observer (successor);
    - ``fd_active[n, j]``: True on the *first* ring slot of each unique
      subject of n — the oracle creates one failure detector per unique
      subject (``MembershipService._create_failure_detectors`` dedupes in
      ring order), so monitor state lives at these slots;
    - ``fd_first[n, j]``: the first ring slot with the same subject as slot
      j (= j itself where ``fd_active``), used to fan a notification back
      out to every ring it covers.

    Non-member rows point at themselves and are fully masked.
    """
    c = uid_hi.shape[0]
    member = member.astype(bool)
    n = member.sum().astype(xp.int32)
    slots = xp.arange(c, dtype=xp.int32)
    nonmember_key = (~member).astype(xp.uint32)

    subj_cols = []
    obs_cols = []
    for ring in range(k):
        khi, klo = hashing.hash64_limbs(xp, uid_hi, uid_lo, seed=ring)
        # last key is primary: (nonmember, key_hi, key_lo, uid_hi, uid_lo)
        order = xp.lexsort((uid_lo, uid_hi, klo, khi, nonmember_key))
        order = order.astype(xp.int32)
        rank = xp.argsort(order).astype(xp.int32)  # rank[slot] = ring position
        nn = xp.maximum(n, 1)
        succ = order[(rank + 1) % nn]
        pred = order[(rank - 1) % nn]
        subj_cols.append(xp.where(member, pred, slots))
        obs_cols.append(xp.where(member, succ, slots))
    subj_idx = xp.stack(subj_cols, axis=1)
    obs_idx = xp.stack(obs_cols, axis=1)

    # Dedup per unique subject: slot j is active iff no earlier ring slot
    # has the same subject. eq[n, j, i] = subj[n, j] == subj[n, i].
    eq = subj_idx[:, :, None] == subj_idx[:, None, :]
    earlier = xp.tril(xp.ones((k, k), bool), k=-1)[None, :, :]
    usable = member & (n >= 2)  # a <=1-member view has no subjects
    fd_active = ~(eq & earlier).any(axis=2) & usable[:, None]
    # First ring slot with the same subject (argmax finds the first True).
    fd_first = xp.argmax(eq, axis=2).astype(xp.int32)
    return subj_idx, obs_idx, fd_active, fd_first
