"""Topology kernel: the K-ring observer/subject graph as index arrays.

Each ring ``k`` orders the membership by ``hash64(uid, seed=k)`` with the
uid as tiebreak — the *same* sort key the oracle's ``MembershipView`` uses
(it adds the Endpoint as a final tiebreak, reachable only on a full 128-bit
collision), computed with the *same* ``hash64_limbs`` — so ring order agrees
by construction (SURVEY.md §7 "hash parity").

One lexsort per ring orders the *full slot universe* — members and dormant
slots interleaved. Members are then linked by nearest-member prefix scans
(cummax/cummin over member positions), which yields two things from the
same sort:

- member ring neighbours: predecessor = subject, successor = observer;
- joiner gatekeepers: a dormant slot's nearest member *predecessor* is
  exactly the oracle's ``get_expected_observers_of`` — the predecessor of
  the joiner's would-be ring position (MembershipView.java:292-303).

Everything is shape-static and jit-compatible: membership changes only
flip the ``member`` mask and re-run the sort.
"""
from __future__ import annotations

import numpy as np

from rapid_tpu import hashing


def _cummax(xp, x):
    if xp is np:
        return np.maximum.accumulate(x)
    from jax import lax

    return lax.cummax(x, axis=0)


def _cummin_rev(xp, x):
    if xp is np:
        return np.minimum.accumulate(x[::-1])[::-1]
    from jax import lax

    return lax.cummin(x, axis=0, reverse=True)


def build_topology(xp, uid_hi, uid_lo, member, k: int):
    """Compute (subj_idx, obs_idx, gk_idx, fd_active, fd_first), each ``[C, K]``.

    - ``subj_idx[n, j]``: slot of node n's ring-j subject (predecessor);
    - ``obs_idx[n, j]``: slot of node n's ring-j observer (successor);
    - ``gk_idx[n, j]``: for a *non-member* slot n, its ring-j join
      gatekeeper (the member preceding its would-be position); member rows
      point at themselves;
    - ``fd_active[n, j]``: True on the *first* ring slot of each unique
      subject of n — the oracle creates one failure detector per unique
      subject (``MembershipService._create_failure_detectors`` dedupes in
      ring order), so monitor state lives at these slots;
    - ``fd_first[n, j]``: the first ring slot with the same subject as slot
      j (= j itself where ``fd_active``), used to fan a notification back
      out to every ring it covers.

    Non-member rows of ``subj_idx``/``obs_idx`` point at themselves and are
    fully masked.
    """
    c = uid_hi.shape[0]
    member = member.astype(bool)
    n = member.sum().astype(xp.int32)
    slots = xp.arange(c, dtype=xp.int32)
    pos = xp.arange(c, dtype=xp.int32)

    subj_cols = []
    obs_cols = []
    gk_cols = []
    for ring in range(k):
        khi, klo = hashing.hash64_limbs(xp, uid_hi, uid_lo, seed=ring)
        # last key is primary: (key_hi, key_lo, uid_hi, uid_lo)
        order = xp.lexsort((uid_lo, uid_hi, klo, khi)).astype(xp.int32)
        member_s = member[order]

        # Nearest member strictly before each sorted position (wrap to the
        # last member overall); -1 only when there are no members at all.
        midx = xp.where(member_s, pos, xp.int32(-1))
        incl = _cummax(xp, midx)
        prev = xp.concatenate([xp.full((1,), -1, xp.int32), incl[:-1]])
        prev = xp.where(prev < 0, incl[-1], prev)
        prev = xp.maximum(prev, 0)  # safe gather when memberless

        # Nearest member strictly after each sorted position (wrap to the
        # first member overall); sentinel c when there are none.
        nidx = xp.where(member_s, pos, xp.int32(c))
        incl_n = _cummin_rev(xp, nidx)
        nxt = xp.concatenate([incl_n[1:], xp.full((1,), c, xp.int32)])
        first_m = xp.minimum(incl_n[0], c - 1)
        nxt = xp.where(nxt >= c, first_m, nxt)

        rank = xp.argsort(order).astype(xp.int32)  # rank[slot] = ring position
        pred = order[prev][rank]
        succ = order[nxt][rank]
        subj_cols.append(xp.where(member, pred, slots))
        obs_cols.append(xp.where(member, succ, slots))
        gk_cols.append(xp.where(member, slots, pred))
    subj_idx = xp.stack(subj_cols, axis=1)
    obs_idx = xp.stack(obs_cols, axis=1)
    gk_idx = xp.stack(gk_cols, axis=1)

    # Dedup per unique subject: slot j is active iff no earlier ring slot
    # has the same subject. eq[n, j, i] = subj[n, j] == subj[n, i].
    eq = subj_idx[:, :, None] == subj_idx[:, None, :]
    earlier = xp.tril(xp.ones((k, k), bool), k=-1)[None, :, :]
    usable = member & (n >= 2)  # a <=1-member view has no subjects
    fd_active = ~(eq & earlier).any(axis=2) & usable[:, None]
    # First ring slot with the same subject (argmax finds the first True).
    fd_first = xp.argmax(eq, axis=2).astype(xp.int32)
    return subj_idx, obs_idx, gk_idx, fd_active, fd_first
