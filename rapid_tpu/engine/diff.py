"""Differential harness: replay a scenario through oracle and engine.

The oracle (``rapid_tpu.oracle``) is the semantic reference: N python
objects exchanging messages one event at a time. The engine is the batched
jax port. This module runs the *same* crash-fault scenario through both and
compares:

- **cut decisions, bit-identical**: every proposal announcement and every
  view-change decision must agree on emission tick, membership content and
  64-bit configuration id;
- **per-tick message counts**: the engine logs per-tick sender/recipient
  factors (``StepLog``); ``expand_counters`` multiplies them host-side into
  exact sent/delivered/dropped/probe tallies that must equal the oracle
  ``SimNetwork`` counters at every tick.

Two execution regimes share this harness. The *fleet* differentials
(``run_differential``, ``run_churn_differential``,
``run_fallback_differential``) drive the jitted shared-view engine, whose
planners still require crashes within one burst to share their first
failing failure-detector tick and bursts to be separated by a full
removal (~fd_threshold * fd_interval + 3 ticks) — one global view per
tick cannot carry nodes whose views disagree. The *adversarial*
differential (``run_adversarial_differential``) has no such envelope: it
drives ``engine.adversary.AdversaryEngine``, which keeps per-slot views,
config epochs, cut-detector tables and fallback timers, and therefore
executes unscripted seeded schedules — asymmetric one-way partitions,
flip-flop links, crash bursts straddling FD-interval boundaries, tied or
mid-fast-count fallback timers, rank races — with nothing pre-rejected,
asserting per-slot events, per-tick counters, per-phase consensus
traffic and per-slot final config ids against the oracle.

Bootstrapping N oracle nodes through the join protocol is O(N^3) messages;
``boot_static_cluster`` instead wires every ``MembershipService`` directly
from a shared converged ``MembershipView`` (the same shortcut the oracle
test-suite uses for single nodes), so differentials at N=256 run in
seconds.
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from rapid_tpu.events import ClusterEvents
from rapid_tpu.faults import HEALTHY, CrashFault, FaultModel
from rapid_tpu.oracle.cluster import Cluster
from rapid_tpu.oracle.membership_view import MembershipView, uid_of
from rapid_tpu.oracle.simulation import SimNetwork
from rapid_tpu.settings import Settings
from rapid_tpu.types import Endpoint, NodeId


@dataclass(frozen=True)
class ViewEvent:
    """One protocol-visible event, in canonical (slot-index) coordinates."""

    tick: int
    kind: str               # "proposal" | "view_change"
    config_id: int          # at fire time: pre-change for proposals,
                            # post-change for view changes
    slots: Tuple[int, ...]  # proposed / removed slots, ascending

    def as_dict(self) -> Dict[str, object]:
        return {"tick": self.tick, "kind": self.kind,
                "config_id": self.config_id, "slots": list(self.slots)}

    @staticmethod
    def from_dict(d: Dict[str, object]) -> "ViewEvent":
        return ViewEvent(tick=int(d["tick"]), kind=str(d["kind"]),
                         config_id=int(d["config_id"]),
                         slots=tuple(int(s) for s in d["slots"]))


def write_events_jsonl(events: Sequence[ViewEvent], path) -> None:
    """One ViewEvent per line, so oracle and engine streams written to two
    files diff cleanly with standard line tools."""
    with open(path, "w") as fh:
        for e in events:
            fh.write(json.dumps(e.as_dict(), sort_keys=True) + "\n")


def read_events_jsonl(path) -> List[ViewEvent]:
    out: List[ViewEvent] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                out.append(ViewEvent.from_dict(json.loads(line)))
    return out


def default_endpoints(n: int) -> List[Endpoint]:
    """Deterministic distinct endpoints for an n-node scenario."""
    return [Endpoint(f"n{i}.sim", 5000) for i in range(n)]


def default_node_ids(n: int) -> List[NodeId]:
    return [NodeId(i + 1, (i + 1) * 7919) for i in range(n)]


class _Recorder:
    """Collects ViewEvents fired by one oracle node."""

    def __init__(self, network: SimNetwork,
                 slot_of: Dict[Endpoint, int]) -> None:
        self._network = network
        self._slot_of = slot_of
        self.events: List[ViewEvent] = []

    def subscribe(self, cluster: Cluster) -> None:
        cluster.register_subscription(
            ClusterEvents.VIEW_CHANGE_PROPOSAL, self._on("proposal"))
        cluster.register_subscription(
            ClusterEvents.VIEW_CHANGE, self._on("view_change"))

    def _on(self, kind: str):
        def callback(change):
            # Endpoints joining after the static boot get slots on demand
            # (shared dict, deterministic fire order => stable numbering).
            slots = tuple(sorted(
                self._slot_of.setdefault(nc.endpoint, len(self._slot_of))
                for nc in change.status_changes))
            self.events.append(ViewEvent(
                self._network.tick, kind, change.configuration_id, slots))
        return callback

    def write_jsonl(self, path) -> None:
        """Dump this node's recorded stream for offline diffing."""
        write_events_jsonl(self.events, path)


def boot_static_cluster(
    settings: Settings,
    endpoints: Sequence[Endpoint],
    node_ids: Sequence[NodeId],
    fault_model: FaultModel = HEALTHY,
    rngs: Optional[Sequence] = None,
) -> Tuple[SimNetwork, List[Cluster], List[_Recorder]]:
    """Wire one converged oracle node per endpoint, in slot order.

    Slot order = service creation order, which fixes the scheduler-handle
    order of the periodic jobs — the property that makes the oracle's
    intra-tick alert order canonical and engine-reproducible. ``rngs``
    injects one ``random.Random`` per slot for the fallback-jitter draws
    (the cluster's default rng hashes the listen address object, which is
    ``PYTHONHASHSEED``-dependent — differentials that exercise organic
    timers must pin the streams).
    """
    network = SimNetwork(settings, fault_model)
    slot_of = {e: i for i, e in enumerate(endpoints)}
    clusters: List[Cluster] = []
    recorders: List[_Recorder] = []
    for i, ep in enumerate(endpoints):
        cluster = Cluster(network, ep, settings,
                          rng=rngs[i] if rngs is not None else None)
        recorder = _Recorder(network, slot_of)
        recorder.subscribe(cluster)
        view = MembershipView(settings.K, list(node_ids), list(endpoints))
        cluster._wire_service(view, {})
        clusters.append(cluster)
        recorders.append(recorder)
    # The initial VIEW_CHANGE each service fires at creation is boot noise,
    # not a protocol event: drop it from every recorder.
    for recorder in recorders:
        recorder.events = [e for e in recorder.events if e.tick > 0
                           or e.kind != "view_change"]
    return network, clusters, recorders


def run_oracle(network: SimNetwork, n_ticks: int) -> List[Dict[str, int]]:
    """Step the oracle ``n_ticks`` times; returns per-tick counter dicts.

    The same records accumulate on ``network.tick_history`` (the full
    run), which ``telemetry.oracle_metrics`` consumes."""
    start = len(network.tick_history)
    for _ in range(n_ticks):
        network.step()
    return [dict(d) for d in network.tick_history[start:]]


def oracle_events(
    recorders: Sequence[_Recorder],
    alive_slots: Sequence[int],
) -> List[ViewEvent]:
    """The canonical oracle event stream.

    Every never-crashed node must have seen the identical stream (they
    process identical alert/vote traffic under crash faults); asserts that
    and returns one copy.
    """
    assert alive_slots, "need at least one alive node to define the stream"
    reference = recorders[alive_slots[0]].events
    for slot in alive_slots[1:]:
        assert recorders[slot].events == reference, (
            f"oracle node {slot} diverged from node {alive_slots[0]}: "
            f"{recorders[slot].events} != {reference}")
    return list(reference)


def engine_events(logs) -> List[ViewEvent]:
    """Extract the engine's event stream from stacked StepLogs."""
    ticks = np.asarray(logs.tick)
    ann = np.asarray(logs.announce_now)
    dec = np.asarray(logs.decide_now)
    proposal = np.asarray(logs.proposal)
    decision = np.asarray(logs.decision)
    cfg_hi = np.asarray(logs.config_hi).astype(np.uint64)
    cfg_lo = np.asarray(logs.config_lo).astype(np.uint64)
    cfg = (cfg_hi << np.uint64(32)) | cfg_lo
    events: List[ViewEvent] = []
    for i in range(len(ticks)):
        if ann[i]:
            events.append(ViewEvent(
                int(ticks[i]), "proposal", int(cfg[i]),
                tuple(int(s) for s in np.nonzero(proposal[i])[0])))
        if dec[i]:
            events.append(ViewEvent(
                int(ticks[i]), "view_change", int(cfg[i]),
                tuple(int(s) for s in np.nonzero(decision[i])[0])))
    return events


def expand_counters(logs) -> List[Dict[str, int]]:
    """Per-tick exact message counts from the engine's StepLog factors.

    Products are computed in python ints (a 100k-node broadcast tick is
    10^10 messages — far past int32, which is why the engine logs factors).
    ``dropped`` at tick t is what came due at t and was not delivered:
    last tick's sends minus this tick's deliveries, per traffic class.
    """
    flushers = np.asarray(logs.flushers)
    flush_rcpt = np.asarray(logs.flush_recipients)
    flush_alive = np.asarray(logs.flushers_alive)
    deliver_alive = np.asarray(logs.deliver_alive)
    vote_send = np.asarray(logs.vote_senders)
    vote_rcpt = np.asarray(logs.vote_recipients)
    vote_alive = np.asarray(logs.vote_senders_alive)
    vote_deliver = np.asarray(logs.vote_deliver_alive)
    probes_sent = np.asarray(logs.probes_sent)
    probes_failed = np.asarray(logs.probes_failed)
    px = expand_fallback_counters(logs)

    out: List[Dict[str, int]] = []
    prev_batch_sent = 0
    prev_vote_sent = 0
    for i in range(len(flushers)):
        batch_sent = int(flushers[i]) * int(flush_rcpt[i])
        vote_sent = int(vote_send[i]) * int(vote_rcpt[i])
        batch_delivered = int(flush_alive[i]) * int(deliver_alive[i])
        vote_delivered = int(vote_alive[i]) * int(vote_deliver[i])
        px_sent = sum(v for k, v in px[i].items() if k.endswith("_sent"))
        px_delivered = sum(v for k, v in px[i].items()
                           if k.endswith("_delivered"))
        out.append({
            "sent": batch_sent + vote_sent + px_sent,
            "delivered": batch_delivered + vote_delivered + px_delivered,
            "dropped": (prev_batch_sent - batch_delivered)
                       + (prev_vote_sent - vote_delivered),
            "timeouts": 0,
            "probes_sent": int(probes_sent[i]),
            "probes_failed": int(probes_failed[i]),
        })
        prev_batch_sent = batch_sent
        prev_vote_sent = vote_sent
    return out


#: (log field pair -> oracle phase key) for the fallback message classes.
_PX_CLASSES = (
    ("pxvote_senders", "pxvote_recipients", "fast_vote"),
    ("px1a_senders", "px1a_recipients", "phase1a"),
    ("px1b_senders", None, "phase1b"),              # unicast: 1 recipient
    ("px2a_senders", "px2a_recipients", "phase2a"),
    ("px2b_senders", "px2b_recipients", "phase2b"),
)


def expand_fallback_counters(logs) -> List[Dict[str, int]]:
    """Per-tick per-phase consensus message counts from the StepLog factors.

    Key set matches ``SimNetwork.consensus_history``. The fallback envelope
    is crash-free, so every message sent at t-1 is delivered at t (kicked
    nodes keep their registered server; network-level delivery counts them
    exactly as the oracle does).
    """
    fields = {name: np.asarray(getattr(logs, name))
              for s, r, _ in _PX_CLASSES
              for name in (s, r) if name is not None}
    n_ticks = len(np.asarray(logs.tick))
    out: List[Dict[str, int]] = []
    prev = {phase: 0 for _, _, phase in _PX_CLASSES}
    for i in range(n_ticks):
        row: Dict[str, int] = {}
        for s_name, r_name, phase in _PX_CLASSES:
            sent = int(fields[s_name][i])
            if r_name is not None:
                sent *= int(fields[r_name][i])
            row[f"{phase}_sent"] = sent
            row[f"{phase}_delivered"] = prev[phase]
            prev[phase] = sent
        out.append(row)
    return out


def _raise_divergence(report, artifact: Optional[str]) -> None:
    from rapid_tpu.telemetry.forensics import DivergenceError

    path = artifact or os.environ.get("RAPID_TPU_FORENSICS")
    if path:
        report.write_jsonl(path)
    raise DivergenceError(report, path)


@dataclass
class DiffResult:
    n: int
    n_ticks: int
    oracle_events: List[ViewEvent]
    engine_events: List[ViewEvent]
    oracle_counters: List[Dict[str, int]]
    engine_counters: List[Dict[str, int]]
    oracle_config_id: int
    engine_config_id: int
    # unified TickMetrics streams (telemetry), populated by run_differential
    engine_metrics: Optional[List] = None
    oracle_metrics: Optional[List] = None

    def first_divergence(self):
        """The earliest (tick, field) where engine and oracle disagree,
        as a ``DivergenceReport`` with trailing context — None if
        bit-identical."""
        from rapid_tpu.telemetry import forensics as fz

        div = fz.earliest([
            fz.events_divergence(self.engine_events, self.oracle_events),
            fz.counters_divergence(self.engine_counters,
                                   self.oracle_counters),
            fz.scalar_divergence("config_id", self.engine_config_id,
                                 self.oracle_config_id, tick=self.n_ticks),
        ])
        if div is None:
            return None
        return fz.build_report(div, engine_metrics=self.engine_metrics,
                               oracle_metrics=self.oracle_metrics,
                               events=self.oracle_events)

    def assert_identical(self, artifact: Optional[str] = None) -> None:
        """Raise ``DivergenceError`` (an AssertionError) at the first
        divergence, naming tick and field with context records; writes a
        JSONL forensics artifact to ``artifact`` (or the path in the
        ``RAPID_TPU_FORENSICS`` env var) when given."""
        report = self.first_divergence()
        if report is not None:
            _raise_divergence(report, artifact)


def run_differential(
    n: int,
    crash_ticks: Dict[int, int],
    n_ticks: int,
    settings: Optional[Settings] = None,
    mesh=None,
) -> DiffResult:
    """Replay a crash scenario through oracle and engine and collect both.

    ``crash_ticks`` maps slot index -> crash tick. Call
    ``result.assert_identical()`` for the bit-identical checks.
    ``mesh`` (optional 1-D device mesh) runs the engine side sharded over
    the slot axis — the differential then proves sharded == oracle.
    """
    from rapid_tpu.engine import sharding as sharding_mod
    from rapid_tpu.engine.state import I32_MAX, crash_faults, init_state
    from rapid_tpu.engine.state import state_config_id
    from rapid_tpu.engine.step import simulate

    settings = settings or Settings()
    endpoints = default_endpoints(n)
    node_ids = default_node_ids(n)

    # --- oracle side ----------------------------------------------------
    fault_model = CrashFault({endpoints[s]: t for s, t in crash_ticks.items()})
    network, clusters, recorders = boot_static_cluster(
        settings, endpoints, node_ids, fault_model)
    oracle_counts = run_oracle(network, n_ticks)
    alive = [s for s in range(n) if s not in crash_ticks]
    events_oracle = oracle_events(recorders, alive)
    oracle_cfg = clusters[alive[0]].membership_service.view \
        .get_current_configuration_id()

    # --- engine side ----------------------------------------------------
    uids = [uid_of(e) for e in endpoints]
    id_fp_sum = clusters[0].membership_service.view._id_fp_sum
    state = init_state(uids, id_fp_sum, settings)
    faults = crash_faults([crash_ticks.get(s, I32_MAX) for s in range(n)])
    if mesh is not None:
        capacity = int(state.member.shape[0])
        state = sharding_mod.shard_put(state, mesh, capacity)
        faults = sharding_mod.shard_put(faults, mesh, capacity)
    final_state, logs = simulate(state, faults, n_ticks, settings, mesh=mesh)

    from rapid_tpu.telemetry import metrics as telemetry_metrics

    return DiffResult(
        n=n, n_ticks=n_ticks,
        oracle_events=events_oracle,
        engine_events=engine_events(logs),
        oracle_counters=oracle_counts,
        engine_counters=expand_counters(logs),
        oracle_config_id=oracle_cfg,
        engine_config_id=state_config_id(final_state),
        engine_metrics=telemetry_metrics.engine_metrics(logs),
        oracle_metrics=telemetry_metrics.oracle_metrics(
            oracle_counts, events_oracle),
    )


# ---------------------------------------------------------------------------
# churn differential: joins + graceful leaves (+ crashes) vs the oracle
# ---------------------------------------------------------------------------


@dataclass
class ChurnDiffResult:
    """Oracle vs engine vs planner for a dynamic-membership scenario.

    Message counters are *not* compared here: the join/leave RPC traffic
    (PreJoin, JoinMessage, LeaveMessage, streamed join responses) is
    host-side protocol the engine deliberately does not send. The
    bit-identical contract covers the protocol-visible stream — proposal
    announcements, view-change decisions, their ticks, member slots and
    64-bit configuration ids — plus the final membership.
    """

    n_initial: int
    capacity: int
    n_ticks: int
    oracle_events: List[ViewEvent]
    engine_events: List[ViewEvent]
    plan_events: List[ViewEvent]
    oracle_config_id: int
    engine_config_id: int
    plan_config_id: int
    oracle_members: frozenset
    engine_members: frozenset
    plan_members: frozenset
    # engine TickMetrics stream (telemetry); oracle counters are not
    # compared for churn, so no oracle stream here
    engine_metrics: Optional[List] = field(default=None)

    def first_divergence(self):
        """Earliest disagreement across the engine/plan/oracle triangle
        (``plan_*`` fields hold the planner's value in the engine slot),
        as a ``DivergenceReport`` — None when all three agree."""
        from rapid_tpu.telemetry import forensics as fz

        div = fz.earliest([
            fz.events_divergence(self.engine_events, self.oracle_events),
            fz.events_divergence(self.plan_events, self.oracle_events,
                                 prefix="plan_events"),
            fz.scalar_divergence("config_id", self.engine_config_id,
                                 self.oracle_config_id, tick=self.n_ticks),
            fz.scalar_divergence("plan_config_id", self.plan_config_id,
                                 self.oracle_config_id, tick=self.n_ticks),
            fz.scalar_divergence("members", self.engine_members,
                                 self.oracle_members, tick=self.n_ticks),
            fz.scalar_divergence("plan_members", self.plan_members,
                                 self.oracle_members, tick=self.n_ticks),
        ])
        if div is None:
            return None
        return fz.build_report(div, engine_metrics=self.engine_metrics,
                               events=self.oracle_events)

    def assert_identical(self, artifact: Optional[str] = None) -> None:
        """Raise ``DivergenceError`` at the first triangle divergence;
        see ``DiffResult.assert_identical`` for the artifact contract."""
        report = self.first_divergence()
        if report is not None:
            _raise_divergence(report, artifact)


@dataclass
class FallbackDiffResult:
    """Oracle vs engine for a scripted contested-consensus scenario.

    On top of the ``DiffResult`` contract (events, total per-tick message
    counts, final configuration id), compares the per-*phase* consensus
    message counts — fast-round votes and classic phase 1a/1b/2a/2b — at
    every tick: the engine's ``expand_fallback_counters`` against the
    oracle's ``SimNetwork.consensus_history``.
    """

    n: int
    n_ticks: int
    plan_info: Dict[str, object]
    oracle_events: List[ViewEvent]
    engine_events: List[ViewEvent]
    oracle_counters: List[Dict[str, int]]
    engine_counters: List[Dict[str, int]]
    oracle_phase_counters: List[Dict[str, int]]
    engine_phase_counters: List[Dict[str, int]]
    oracle_config_id: int
    engine_config_id: int
    engine_metrics: Optional[List] = None
    oracle_metrics: Optional[List] = None

    def first_divergence(self):
        """Earliest (tick, field) disagreement across events, total
        counters, per-phase counters and the final config id — None when
        bit-identical."""
        from rapid_tpu.telemetry import forensics as fz

        div = fz.earliest([
            fz.events_divergence(self.engine_events, self.oracle_events),
            fz.counters_divergence(self.engine_counters,
                                   self.oracle_counters),
            fz.counters_divergence(self.engine_phase_counters,
                                   self.oracle_phase_counters),
            fz.scalar_divergence("config_id", self.engine_config_id,
                                 self.oracle_config_id, tick=self.n_ticks),
        ])
        if div is None:
            return None
        return fz.build_report(div, engine_metrics=self.engine_metrics,
                               oracle_metrics=self.oracle_metrics,
                               events=self.oracle_events)

    def assert_identical(self, artifact: Optional[str] = None) -> None:
        """Raise ``DivergenceError`` at the first divergence; see
        ``DiffResult.assert_identical`` for the artifact contract."""
        report = self.first_divergence()
        if report is not None:
            _raise_divergence(report, artifact)


def run_fallback_differential(
    n: int,
    values: Sequence[Sequence[int]],
    votes: Dict[int, Tuple[int, int]],
    delays: Dict[int, int],
    n_ticks: int,
    settings: Optional[Settings] = None,
) -> FallbackDiffResult:
    """Replay one contested consensus instance through oracle and engine.

    ``values[p]`` lists the member slots proposal ``p`` removes;
    ``votes[s] = (tick, pid)`` scripts slot ``s``'s ``propose`` call at
    that tick with that value; ``delays[s]`` is its explicit fallback
    delay in ticks (``recovery_delay_ticks`` on the oracle side, the
    schedule's ``prop_delay`` on the engine side — one shared
    deterministic draw instead of two RNG streams). The planner raises
    ``FallbackEnvelopeError`` for scenarios outside the bit-identical
    envelope before either simulation runs.
    """
    from rapid_tpu.engine.paxos import plan_fallback
    from rapid_tpu.engine.state import I32_MAX, crash_faults, init_state
    from rapid_tpu.engine.state import state_config_id
    from rapid_tpu.engine.step import simulate

    settings = settings or Settings()
    endpoints = default_endpoints(n)
    node_ids = default_node_ids(n)
    uids = np.asarray([uid_of(e) for e in endpoints], np.uint64)

    # --- plan: validates the envelope, predicts the outcome -------------
    sched, info = plan_fallback(n, values, votes, delays, settings,
                                uids=uids)

    # --- oracle side (crash-free: contention comes from the script) -----
    network, clusters, recorders = boot_static_cluster(
        settings, endpoints, node_ids)
    # Proposals reach FastPaxos.propose sorted by the ring-0 key, exactly
    # as _handle_batched_alerts orders a cut-detector proposal.
    view0 = clusters[0].membership_service.view
    ordered = [sorted((endpoints[s] for s in val), key=view0.ring0_sort_key)
               for val in values]
    # Registration in (tick, slot) order gives same-tick proposes the
    # scheduler-handle order the planner and engine assume.
    for tick, s in sorted((vt, vs) for vs, (vt, _) in votes.items()):
        pid = votes[s][1]
        network.at(tick, lambda svc=clusters[s].membership_service,
                   prop=ordered[pid], d=delays[s]:
                   svc.fast_paxos.propose(prop, recovery_delay_ticks=d))
    oracle_counts = run_oracle(network, n_ticks)
    oracle_phase = [dict(d) for d in network.consensus_history]

    removed = set(values[int(info["winner"])]) if info["winner"] is not None \
        and int(info["winner"]) >= 0 else set()
    survivors = [s for s in range(n) if s not in removed]
    events_oracle = oracle_events(recorders, survivors)
    oracle_cfg = clusters[survivors[0]].membership_service.view \
        .get_current_configuration_id()

    # --- engine side ----------------------------------------------------
    id_fp_sum = view0._id_fp_sum
    state = init_state(uids, id_fp_sum, settings)
    faults = crash_faults([I32_MAX] * n)
    final_state, logs = simulate(state, faults, n_ticks, settings,
                                 fallback=sched)

    from rapid_tpu.telemetry import metrics as telemetry_metrics

    return FallbackDiffResult(
        n=n, n_ticks=n_ticks, plan_info=info,
        oracle_events=events_oracle,
        engine_events=engine_events(logs),
        oracle_counters=oracle_counts,
        engine_counters=expand_counters(logs),
        oracle_phase_counters=oracle_phase,
        engine_phase_counters=expand_fallback_counters(logs),
        oracle_config_id=oracle_cfg,
        engine_config_id=state_config_id(final_state),
        engine_metrics=telemetry_metrics.engine_metrics(logs),
        oracle_metrics=telemetry_metrics.oracle_metrics(
            oracle_counts, events_oracle),
    )


@dataclass
class VariantDiffResult:
    """Oracle vs engine under a ``Settings.protocol_variant`` message model.

    The oracle still runs the reference protocol; its counters are
    recomputed under the variant's wire accounting by
    ``rapid_tpu.variants.oracle`` (which also certifies the scenario is
    inside the variant's envelope — see ``VariantEnvelopeError``). The
    bit-identical contract covers events, per-tick transformed message
    counts, the final configuration id, and — for contested scenarios —
    the per-phase consensus counts including the ring-shaped fast votes.
    """

    variant: str
    n: int
    n_ticks: int
    contested: bool
    oracle_events: List[ViewEvent]
    engine_events: List[ViewEvent]
    oracle_counters: List[Dict[str, int]]
    engine_counters: List[Dict[str, int]]
    oracle_config_id: int
    engine_config_id: int
    # per-phase consensus streams, compared only for contested scenarios
    # (organic fast votes live in the vote class, not the px class)
    oracle_phase_counters: Optional[List[Dict[str, int]]] = None
    engine_phase_counters: Optional[List[Dict[str, int]]] = None
    engine_metrics: Optional[List] = None
    oracle_metrics: Optional[List] = None

    @property
    def oracle_message_total(self) -> int:
        """Total variant-model messages the oracle accounts for the run."""
        return sum(d["sent"] for d in self.oracle_counters)

    @property
    def engine_message_total(self) -> int:
        """Total messages the engine's expanded factors account."""
        return sum(d["sent"] for d in self.engine_counters)

    def first_divergence(self):
        """Earliest (tick, field) disagreement — None when bit-identical."""
        from rapid_tpu.telemetry import forensics as fz

        candidates = [
            fz.events_divergence(self.engine_events, self.oracle_events),
            fz.counters_divergence(self.engine_counters,
                                   self.oracle_counters),
            fz.scalar_divergence("config_id", self.engine_config_id,
                                 self.oracle_config_id, tick=self.n_ticks),
        ]
        if self.oracle_phase_counters is not None:
            candidates.append(fz.counters_divergence(
                self.engine_phase_counters, self.oracle_phase_counters))
        div = fz.earliest(candidates)
        if div is None:
            return None
        return fz.build_report(div, engine_metrics=self.engine_metrics,
                               oracle_metrics=self.oracle_metrics,
                               events=self.oracle_events)

    def assert_identical(self, artifact: Optional[str] = None) -> None:
        """Raise ``DivergenceError`` at the first divergence; see
        ``DiffResult.assert_identical`` for the artifact contract."""
        report = self.first_divergence()
        if report is not None:
            _raise_divergence(report, artifact)


def run_variant_differential(
    n: int,
    crash_ticks: Dict[int, int],
    n_ticks: int,
    variant: str,
    settings: Optional[Settings] = None,
    contested: Optional[Tuple] = None,
    mesh=None,
) -> VariantDiffResult:
    """Replay a scenario through the variant engine and the variant-aware
    oracle accounting.

    With ``contested=None`` this is a crash scenario (``crash_ticks``
    maps slot -> crash tick, like ``run_differential``); with
    ``contested=(values, votes, delays)`` it is a scripted contested
    consensus instance (like ``run_fallback_differential``;
    ``crash_ticks`` must be empty). The engine runs with
    ``settings.protocol_variant = variant`` while the oracle's counters
    are transformed host-side by
    ``rapid_tpu.variants.oracle.variant_oracle_counters`` — proving the
    variant's decisions, config ids and per-tick message counts exactly.
    Raises ``rapid_tpu.variants.oracle.VariantEnvelopeError`` for
    scenarios where the variant legitimately behaves differently.
    """
    from rapid_tpu.variants import oracle as variants_oracle

    settings = (settings or Settings()).with_(protocol_variant=variant)
    uids = [uid_of(e) for e in default_endpoints(n)]

    if contested is not None:
        if crash_ticks:
            raise ValueError("contested variant scenarios are crash-free; "
                             "pass crash_ticks={}")
        values, votes, delays = contested
        base = run_fallback_differential(n, values, votes, delays, n_ticks,
                                         settings=settings)
        o_tick, o_phase = variants_oracle.variant_oracle_counters(
            variant, n, {}, base.oracle_events, base.oracle_counters,
            base.oracle_phase_counters, uids, contested=True)
        return VariantDiffResult(
            variant=variant, n=n, n_ticks=n_ticks, contested=True,
            oracle_events=base.oracle_events,
            engine_events=base.engine_events,
            oracle_counters=o_tick,
            engine_counters=base.engine_counters,
            oracle_phase_counters=o_phase,
            engine_phase_counters=base.engine_phase_counters,
            oracle_config_id=base.oracle_config_id,
            engine_config_id=base.engine_config_id,
            engine_metrics=base.engine_metrics,
            oracle_metrics=base.oracle_metrics,
        )

    # --- crash scenario: run_differential plus the per-phase capture ----
    from rapid_tpu.engine import sharding as sharding_mod
    from rapid_tpu.engine.state import I32_MAX, crash_faults, init_state
    from rapid_tpu.engine.state import state_config_id
    from rapid_tpu.engine.step import simulate

    endpoints = default_endpoints(n)
    node_ids = default_node_ids(n)
    fault_model = CrashFault({endpoints[s]: t
                              for s, t in crash_ticks.items()})
    network, clusters, recorders = boot_static_cluster(
        settings, endpoints, node_ids, fault_model)
    oracle_counts = run_oracle(network, n_ticks)
    oracle_phase = [dict(d) for d in network.consensus_history]
    alive = [s for s in range(n) if s not in crash_ticks]
    events_oracle = oracle_events(recorders, alive)
    oracle_cfg = clusters[alive[0]].membership_service.view \
        .get_current_configuration_id()
    o_tick, _ = variants_oracle.variant_oracle_counters(
        variant, n, dict(crash_ticks), events_oracle, oracle_counts,
        oracle_phase, uids, contested=False)

    id_fp_sum = clusters[0].membership_service.view._id_fp_sum
    state = init_state(uids, id_fp_sum, settings)
    faults = crash_faults([crash_ticks.get(s, I32_MAX) for s in range(n)])
    if mesh is not None:
        capacity = int(state.member.shape[0])
        state = sharding_mod.shard_put(state, mesh, capacity)
        faults = sharding_mod.shard_put(faults, mesh, capacity)
    final_state, logs = simulate(state, faults, n_ticks, settings, mesh=mesh)

    from rapid_tpu.telemetry import metrics as telemetry_metrics

    return VariantDiffResult(
        variant=variant, n=n, n_ticks=n_ticks, contested=False,
        oracle_events=events_oracle,
        engine_events=engine_events(logs),
        oracle_counters=o_tick,
        engine_counters=expand_counters(logs),
        oracle_config_id=oracle_cfg,
        engine_config_id=state_config_id(final_state),
        engine_metrics=telemetry_metrics.engine_metrics(logs),
        oracle_metrics=telemetry_metrics.oracle_metrics(
            oracle_counts, events_oracle),
    )


def run_churn_differential(
    n: int,
    capacity: int,
    n_ticks: int,
    joins: Optional[Dict[int, int]] = None,
    leaves: Optional[Dict[int, int]] = None,
    crashes: Optional[Dict[int, int]] = None,
    settings: Optional[Settings] = None,
    seed_slot: int = 0,
    node_ids: Optional[List[NodeId]] = None,
) -> ChurnDiffResult:
    """Replay a join/leave/crash scenario through planner, oracle, engine.

    Slots ``[0, n)`` boot as converged members; ``[n, capacity)`` are
    dormant joiner slots. ``joins[s]`` is the tick slot ``s`` calls
    ``Cluster.join(seed)``, ``leaves[s]`` the tick it calls
    ``leave_gracefully()``, ``crashes[s]`` its crash tick. The planner
    raises ``ChurnEnvelopeError`` for scenarios outside the bit-identical
    envelope *before* either simulation runs. ``node_ids`` overrides the
    initial members' NodeIds (default ``default_node_ids``) — tests use
    it to force a joiner's first NodeId draw to collide and exercise the
    UUID-retry redraw path on both sides.
    """
    from rapid_tpu.engine.churn import plan_churn
    from rapid_tpu.engine.state import I32_MAX, crash_faults, init_state
    from rapid_tpu.engine.state import state_config_id
    from rapid_tpu.engine.step import simulate

    joins = dict(joins or {})
    leaves = dict(leaves or {})
    crashes = dict(crashes or {})
    settings = settings or Settings()
    endpoints = default_endpoints(capacity)
    if node_ids is None:
        node_ids = default_node_ids(n)
    elif len(node_ids) != n:
        raise ValueError(f"node_ids must cover the {n} initial members")

    # --- plan: host protocol mirror, raises if out of envelope ----------
    plan = plan_churn(endpoints, n, node_ids, n_ticks, settings,
                      joins=joins, leaves=leaves, crashes=crashes,
                      seed_slot=seed_slot)

    # --- oracle side ----------------------------------------------------
    fault_model = CrashFault({endpoints[s]: t for s, t in crashes.items()}) \
        if crashes else HEALTHY
    network, clusters, recorders = boot_static_cluster(
        settings, endpoints[:n], node_ids, fault_model)
    # Pre-number every dormant slot so joiner events land on canonical
    # slot indices (the recorders share one slot_of dict).
    recorders[0]._slot_of.update(
        {endpoints[s]: s for s in range(n, capacity)})

    joiner_recorders: Dict[int, _Recorder] = {}
    cluster_of: Dict[int, Cluster] = dict(enumerate(clusters))
    for s in sorted(joins):
        cluster = Cluster(network, endpoints[s], settings)
        recorder = _Recorder(network, recorders[0]._slot_of)
        recorder.subscribe(cluster)
        cluster_of[s] = cluster
        joiner_recorders[s] = recorder
    # Host actions scheduled up front get the smallest scheduler handles,
    # so same-tick operations run in (tick, slot) order ahead of message
    # processing — the order the planner assumes.
    ops = sorted([(t, s, "join") for s, t in joins.items()]
                 + [(t, s, "leave") for s, t in leaves.items()])
    seed_ep = endpoints[seed_slot]
    for t, s, kind in ops:
        if kind == "join":
            network.at(t, lambda cl=cluster_of[s]: cl.join(seed_ep))
        else:
            network.at(t, lambda cl=cluster_of[s]: cl.leave_gracefully())
    run_oracle(network, n_ticks)

    # Reference stream: initial members that neither crash nor leave.
    alive = [s for s in range(n) if s not in crashes and s not in leaves]
    events_oracle = oracle_events(recorders, alive)
    reference = events_oracle

    # Leavers see a prefix of the reference (they vote on and apply their
    # own removal before the service stops).
    for s in leaves:
        if s in crashes or s >= n:
            continue
        seen = recorders[s].events
        assert seen == reference[:len(seen)], (
            f"leaver {s} saw a non-prefix stream: {seen}")
    # Joiners see the suffix after their wiring tick, once the boot
    # VIEW_CHANGE their service fires at creation is dropped.
    for s, recorder in joiner_recorders.items():
        if s in crashes:
            continue
        wired = plan.wired.get(s)
        assert wired is not None, f"joiner {s} never wired in the oracle run"
        seen = [e for e in recorder.events
                if not (e.kind == "view_change" and e.tick == wired)]
        expect = [e for e in reference if e.tick > wired]
        assert seen == expect[:len(seen)] and (
            len(seen) == len(expect) or s in leaves), (
            f"joiner {s} (wired {wired}) diverged: {seen} != {expect}")

    oracle_view = cluster_of[alive[0]].membership_service.view
    oracle_cfg = oracle_view.get_current_configuration_id()
    oracle_members = frozenset(
        recorders[0]._slot_of[e] for e in oracle_view.get_ring(0))

    # --- engine side ----------------------------------------------------
    uids = [uid_of(e) for e in endpoints]
    id_fp_sum = MembershipView(settings.K, node_ids, [])._id_fp_sum
    member0 = [True] * n + [False] * (capacity - n)
    state = init_state(uids, id_fp_sum, settings, member=member0,
                       id_fps=plan.id_fps)
    faults = crash_faults(
        [crashes.get(s, I32_MAX) for s in range(capacity)])
    final_state, logs = simulate(state, faults, n_ticks, settings,
                                 churn=plan.schedule)
    engine_members = frozenset(
        int(s) for s in np.nonzero(np.asarray(final_state.member))[0])

    from rapid_tpu.telemetry import metrics as telemetry_metrics

    return ChurnDiffResult(
        engine_metrics=telemetry_metrics.engine_metrics(logs),
        n_initial=n, capacity=capacity, n_ticks=n_ticks,
        oracle_events=events_oracle,
        engine_events=engine_events(logs),
        plan_events=[ViewEvent(*e) for e in plan.events],
        oracle_config_id=oracle_cfg,
        engine_config_id=state_config_id(final_state),
        plan_config_id=plan.final_config_id,
        oracle_members=oracle_members,
        engine_members=engine_members,
        plan_members=plan.final_members,
    )


# ---------------------------------------------------------------------------
# adversarial differential: unscripted fault schedules, no planner envelope
# ---------------------------------------------------------------------------


@dataclass
class AdversaryDiffResult:
    """Oracle vs the per-slot adversary engine for one fault schedule.

    Under partitions the nodes legitimately see *different* event
    streams, so the comparison is per slot: every slot's engine stream
    (proposals, view changes, config ids) against the same slot's oracle
    recorder, plus total per-tick message counters, per-phase consensus
    counters, and every slot's final configuration id (meaningful for
    kicked and crashed nodes too — their views freeze where the protocol
    left them).
    """

    n: int
    n_ticks: int
    schedule: object
    oracle_events_by_slot: List[List[ViewEvent]]
    engine_events_by_slot: List[List[ViewEvent]]
    oracle_counters: List[Dict[str, int]]
    engine_counters: List[Dict[str, int]]
    oracle_phase_counters: List[Dict[str, int]]
    engine_phase_counters: List[Dict[str, int]]
    oracle_config_ids: List[int]
    engine_config_ids: List[int]
    engine_metrics: Optional[List] = None
    oracle_metrics: Optional[List] = None

    def first_divergence(self):
        """Earliest (tick, field) disagreement across all per-slot event
        streams, counters, phase counters and final per-slot config ids —
        None when bit-identical."""
        from rapid_tpu.telemetry import forensics as fz

        candidates = [
            fz.counters_divergence(self.engine_counters,
                                   self.oracle_counters),
            fz.counters_divergence(self.engine_phase_counters,
                                   self.oracle_phase_counters),
        ]
        for s in range(self.n):
            candidates.append(fz.events_divergence(
                self.engine_events_by_slot[s],
                self.oracle_events_by_slot[s], prefix=f"slot{s}.events"))
            candidates.append(fz.scalar_divergence(
                f"slot{s}.config_id", self.engine_config_ids[s],
                self.oracle_config_ids[s], tick=self.n_ticks))
        div = fz.earliest(candidates)
        if div is None:
            return None
        events = max(self.oracle_events_by_slot, key=len, default=[])
        return fz.build_report(div, engine_metrics=self.engine_metrics,
                               oracle_metrics=self.oracle_metrics,
                               events=events)

    def assert_identical(self, artifact: Optional[str] = None) -> None:
        """Raise ``DivergenceError`` at the first divergence; see
        ``DiffResult.assert_identical`` for the artifact contract."""
        report = self.first_divergence()
        if report is not None:
            _raise_divergence(report, artifact)


def run_adversarial_differential(
    schedule,
    n_ticks: int,
    settings: Optional[Settings] = None,
) -> AdversaryDiffResult:
    """Replay an unscripted :class:`rapid_tpu.faults.AdversarySchedule`
    through oracle and the per-slot adversary engine.

    Nothing scenario-shaped is screened: the schedule's crashes may
    straddle FD-interval boundaries, its link windows may partition the
    monitoring topology asymmetrically or flip-flop, and its scripted
    proposes may tie timers, fire mid-fast-count, or race coordinator
    ranks — ``faults.validate_schedule`` only checks genuine input
    validity. Both sides draw organic fallback jitter from identical
    per-slot rng streams seeded by ``schedule.seed``.
    """
    from rapid_tpu.engine.adversary import AdversaryEngine, adversary_rngs
    from rapid_tpu.faults import validate_schedule
    from rapid_tpu.oracle.membership_view import id_fingerprint

    validate_schedule(schedule)
    settings = settings or Settings()
    n = schedule.n
    endpoints = default_endpoints(n)
    node_ids = default_node_ids(n)

    # --- oracle side ----------------------------------------------------
    network, clusters, recorders = boot_static_cluster(
        settings, endpoints, node_ids, schedule.fault_model(endpoints),
        rngs=adversary_rngs(schedule.seed, n))
    view0 = clusters[0].membership_service.view
    # Scripted proposes register after boot in schedule order — the same
    # handle order the engine replicates. ``fast_paxos`` resolves at fire
    # time so a propose after a view change lands on the live instance.
    for p in schedule.proposes:
        ordered = sorted((endpoints[s] for s in p.proposal),
                         key=view0.ring0_sort_key)
        network.at(p.tick,
                   lambda svc=clusters[p.slot].membership_service,
                   prop=ordered, d=p.delay_ticks:
                   svc.fast_paxos.propose(prop, recovery_delay_ticks=d))
    oracle_counts = run_oracle(network, n_ticks)
    oracle_phase = [dict(d) for d in network.consensus_history]
    oracle_cfgs = [c.membership_service.view.get_current_configuration_id()
                   for c in clusters]

    # --- engine side ----------------------------------------------------
    uids = [uid_of(e) for e in endpoints]
    id_fp_sum = sum(id_fingerprint(nid) for nid in node_ids) & ((1 << 64) - 1)
    engine = AdversaryEngine(schedule, uids, id_fp_sum, settings)
    run = engine.run(n_ticks)

    from rapid_tpu.telemetry import metrics as telemetry_metrics

    all_oracle_events = sorted(
        {e for r in recorders for e in r.events},
        key=lambda e: (e.tick, e.kind))
    return AdversaryDiffResult(
        n=n, n_ticks=n_ticks, schedule=schedule,
        oracle_events_by_slot=[list(r.events) for r in recorders],
        engine_events_by_slot=[
            [ViewEvent(tick=t, kind=k, config_id=c, slots=slots)
             for t, k, c, slots in evs]
            for evs in run.events_by_slot],
        oracle_counters=oracle_counts,
        engine_counters=run.tick_history,
        oracle_phase_counters=oracle_phase,
        engine_phase_counters=run.phase_history,
        oracle_config_ids=oracle_cfgs,
        engine_config_ids=run.config_ids,
        engine_metrics=run.metrics(),
        oracle_metrics=telemetry_metrics.oracle_metrics(
            oracle_counts, all_oracle_events),
    )


def run_receiver_differential(
    schedule,
    n_ticks: int,
    settings: Optional[Settings] = None,
) -> AdversaryDiffResult:
    """Replay a link-fault :class:`rapid_tpu.faults.AdversarySchedule`
    through the host per-slot adversary engine and the *device*
    per-receiver kernel (``engine.receiver``).

    This is the fidelity proof for fleet per-receiver members: the device
    side runs the whole scenario inside one jitted ``lax.scan`` —
    per-slot views, explicit wire, link reachability evaluated per
    (sender, receiver) edge at delivery — and must reproduce the host
    referee's per-slot event streams, per-tick counters, per-phase
    consensus traffic and per-slot final config ids bit-identically.
    Campaign spot checks call this as belt-and-suspenders; the campaign
    result itself is device-exact without it.

    Delay rules are in-envelope: the schedule's ``DelayRule`` set lowers
    to the device delivery ring (depth ``settings.delivery_ring_depth``,
    budget-checked here before anything allocates) and the host referee
    evaluates the identical tick-quantized send-time delay, so delayed,
    jittered and reordered deliveries are part of the bit-exactness
    contract, not an approximation.

    Scripted proposes are outside the per-receiver envelope (fleet
    lowering keeps those members on the shared-state path), and a sticky
    device flag raises :class:`rapid_tpu.engine.receiver.ReceiverEnvelopeError`
    rather than letting an out-of-envelope run masquerade as exact.
    """
    from rapid_tpu.engine import receiver as receiver_mod
    from rapid_tpu.engine.adversary import AdversaryEngine
    from rapid_tpu.engine.state import link_faults
    from rapid_tpu.faults import validate_schedule
    from rapid_tpu.oracle.membership_view import id_fingerprint, uid_of

    settings = settings or Settings()
    validate_schedule(schedule, ring_depth=settings.delivery_ring_depth)
    if schedule.proposes:
        raise ValueError("per-receiver mode does not support scripted "
                         "proposes; use run_adversarial_differential")
    n = schedule.n
    uids = [uid_of(e) for e in default_endpoints(n)]
    id_fp_sum = sum(id_fingerprint(nid)
                    for nid in default_node_ids(n)) & ((1 << 64) - 1)

    # --- host referee ---------------------------------------------------
    host = AdversaryEngine(schedule, uids, id_fp_sum, settings).run(n_ticks)

    # --- device side ----------------------------------------------------
    rs = receiver_mod.init_receiver_state(uids, id_fp_sum, settings,
                                          seed=schedule.seed)
    faults = link_faults(schedule.crash_tick_array().tolist(),
                         schedule.windows, rs.member.shape[0],
                         delays=schedule.delays, delay_seed=schedule.seed)
    final, logs = receiver_mod.receiver_simulate(rs, faults, n_ticks,
                                                 settings)
    receiver_mod.check_flags(final.flags)
    dev = receiver_mod.receiver_run_payload(final, logs, n, n_ticks)

    def as_view_events(evs):
        return [[ViewEvent(tick=t, kind=k, config_id=c, slots=slots)
                 for t, k, c, slots in slot_evs] for slot_evs in evs]

    return AdversaryDiffResult(
        n=n, n_ticks=n_ticks, schedule=schedule,
        oracle_events_by_slot=as_view_events(host.events_by_slot),
        engine_events_by_slot=as_view_events(dev.events_by_slot),
        oracle_counters=host.tick_history,
        engine_counters=dev.tick_history,
        oracle_phase_counters=host.phase_history,
        engine_phase_counters=dev.phase_history,
        oracle_config_ids=host.config_ids,
        engine_config_ids=dev.config_ids,
        engine_metrics=dev.metrics(),
        oracle_metrics=host.metrics(),
    )


# ---------------------------------------------------------------------------
# lineage differential: phase-attributed span streams, oracle vs engine
# ---------------------------------------------------------------------------

#: Scenario families the lineage differential covers.
LINEAGE_FAMILIES = ("steady", "crash_burst", "delay", "contested")


@dataclass
class LineageDiffResult:
    """Oracle vs engine lineage span streams for one scenario family.

    Lineage spans are *derived* data — the fold runs independently over
    the oracle's counter/event timeline and the engine's expanded
    ``StepLog`` factors (or the adversary referee's counter streams for
    the delay family), and the comparison is the
    :func:`rapid_tpu.telemetry.lineage.comparable` projection of every
    span: window boundaries, ``ticks_to_view_change``, the fallback
    flag, every oracle-observable milestone tick and all five phase
    durations. Engine-only fields (fallback timer arm ticks, critical
    path) are excluded by the projection, not fudged to match.
    """

    family: str
    n: int
    n_ticks: int
    oracle_spans: Dict[str, List[Dict[str, object]]]
    engine_spans: Dict[str, List[Dict[str, object]]]

    def first_divergence(self) -> Optional[str]:
        """Human-readable description of the earliest span disagreement,
        or None when every stream is bit-identical under the comparable
        projection."""
        from rapid_tpu.telemetry.lineage import comparable

        for label in sorted(self.oracle_spans):
            oracle = [comparable(s) for s in self.oracle_spans[label]]
            engine = [comparable(s) for s in self.engine_spans.get(label, [])]
            if len(oracle) != len(engine):
                return (f"{label}: engine has {len(engine)} spans, "
                        f"oracle has {len(oracle)}")
            for i, (e, o) in enumerate(zip(engine, oracle)):
                if e != o:
                    keys = [k for k in o if e.get(k) != o.get(k)]
                    return (f"{label}: span {i} differs on {keys}: "
                            f"engine={e} oracle={o}")
        return None

    def assert_identical(self) -> None:
        div = self.first_divergence()
        if div is not None:
            raise AssertionError("lineage divergence: " + div)


def _lineage_crash_burst(n: int) -> Dict[int, int]:
    return {max(1, n // 5): 5, max(2, n // 3): 5, n - 2: 7}


def run_lineage_differential(
    family: str,
    n: int,
    n_ticks: int = 200,
    settings: Optional[Settings] = None,
    seed: int = 5,
) -> LineageDiffResult:
    """Fold lineage spans independently on oracle and engine sides.

    Families (see :data:`LINEAGE_FAMILIES`):

    - ``steady``: healthy cluster, no faults — both sides must fold zero
      spans (the empty stream is part of the contract);
    - ``crash_burst``: a three-slot crash burst drives organic cut
      detection, announce and fast-quorum decide;
    - ``delay``: a crash plus a ``DelayRule`` over a slot block, folded
      per slot over the adversary referee's per-slot event streams;
    - ``contested``: a scripted two-way vote split forces the classic
      fallback, covering the 1a/1b/2a/2b milestones.

    Oracle spans always come from :func:`counter_phase_columns` over
    ``SimNetwork`` history (``tick_history`` + ``consensus_history`` +
    recorder events); engine spans come from
    :func:`engine_phase_columns` over raw ``StepLog`` factor logs for
    the shared-scan families, and from the adversary engine's counter
    streams for the delay family.
    """
    from rapid_tpu.telemetry import lineage as lineage_mod

    settings = settings or Settings()
    if family not in LINEAGE_FAMILIES:
        raise ValueError(f"unknown lineage family {family!r}; "
                         f"expected one of {LINEAGE_FAMILIES}")

    if family in ("steady", "crash_burst"):
        from rapid_tpu.engine.state import I32_MAX, crash_faults, init_state
        from rapid_tpu.engine.step import simulate

        crash_ticks = {} if family == "steady" else _lineage_crash_burst(n)
        endpoints = default_endpoints(n)
        node_ids = default_node_ids(n)
        fault_model = CrashFault({endpoints[s]: t
                                  for s, t in crash_ticks.items()})
        network, clusters, recorders = boot_static_cluster(
            settings, endpoints, node_ids, fault_model)
        oracle_counts = run_oracle(network, n_ticks)
        oracle_phase = [dict(d) for d in network.consensus_history]
        alive = [s for s in range(n) if s not in crash_ticks]
        events_oracle = oracle_events(recorders, alive)

        uids = [uid_of(e) for e in endpoints]
        id_fp_sum = clusters[0].membership_service.view._id_fp_sum
        state = init_state(uids, id_fp_sum, settings)
        faults = crash_faults([crash_ticks.get(s, I32_MAX)
                               for s in range(n)])
        _, logs = simulate(state, faults, n_ticks, settings)

        oracle_cols = lineage_mod.counter_phase_columns(
            oracle_counts, oracle_phase, events_oracle)
        engine_cols = lineage_mod.engine_phase_columns(logs)
        return LineageDiffResult(
            family=family, n=n, n_ticks=n_ticks,
            oracle_spans={"all": lineage_mod.fold_spans(oracle_cols,
                                                        start_tick=0)},
            engine_spans={"all": lineage_mod.fold_spans(engine_cols,
                                                        start_tick=0)},
        )

    if family == "contested":
        # Two-way split: half vote to remove slot 0, half slot 1; no fast
        # quorum forms, slot 0's timer fires and the classic round decides.
        values = [[0], [1]]
        votes = {s: (6, s % 2) for s in range(n)}
        delays = {s: (10 if s == 0 else 100) for s in range(n)}
        base = _run_fallback_with_logs(n, values, votes, delays,
                                       min(n_ticks, 40), settings,
                                       lineage_mod)
        return LineageDiffResult(family=family, n=n,
                                 n_ticks=min(n_ticks, 40),
                                 oracle_spans=base[0], engine_spans=base[1])

    # family == "delay"
    from rapid_tpu.faults import AdversarySchedule, DelayRule

    block = max(2, n // 8)
    schedule = AdversarySchedule(
        n=n,
        crashes=((n - 1, 11),),
        delays=(DelayRule(src_slots=frozenset(range(block)),
                          dst_slots=frozenset(range(block, n // 2)),
                          delay_ticks=2),),
        seed=seed)
    base = run_adversarial_differential(schedule, n_ticks, settings)
    oracle_spans = {}
    engine_spans = {}
    for s in range(n):
        o_cols = lineage_mod.counter_phase_columns(
            base.oracle_counters, base.oracle_phase_counters,
            base.oracle_events_by_slot[s])
        e_cols = lineage_mod.counter_phase_columns(
            base.engine_counters, base.engine_phase_counters,
            base.engine_events_by_slot[s])
        oracle_spans[f"slot{s}"] = lineage_mod.fold_spans(o_cols,
                                                          start_tick=0)
        engine_spans[f"slot{s}"] = lineage_mod.fold_spans(e_cols,
                                                          start_tick=0)
    return LineageDiffResult(family=family, n=n, n_ticks=n_ticks,
                             oracle_spans=oracle_spans,
                             engine_spans=engine_spans)


def _run_fallback_with_logs(n, values, votes, delays, n_ticks, settings,
                            lineage_mod):
    """Contested-fallback orchestration that keeps the raw engine logs
    (``run_fallback_differential`` discards them), so engine-side lineage
    exercises the ``StepLog`` builder used by campaign and replay."""
    from rapid_tpu.engine.paxos import plan_fallback
    from rapid_tpu.engine.state import I32_MAX, crash_faults, init_state
    from rapid_tpu.engine.step import simulate

    endpoints = default_endpoints(n)
    node_ids = default_node_ids(n)
    uids = np.asarray([uid_of(e) for e in endpoints], np.uint64)
    sched, info = plan_fallback(n, values, votes, delays, settings,
                                uids=uids)

    network, clusters, recorders = boot_static_cluster(
        settings, endpoints, node_ids)
    view0 = clusters[0].membership_service.view
    ordered = [sorted((endpoints[s] for s in val),
                      key=view0.ring0_sort_key) for val in values]
    for tick, s in sorted((vt, vs) for vs, (vt, _) in votes.items()):
        pid = votes[s][1]
        network.at(tick, lambda svc=clusters[s].membership_service,
                   prop=ordered[pid], d=delays[s]:
                   svc.fast_paxos.propose(prop, recovery_delay_ticks=d))
    oracle_counts = run_oracle(network, n_ticks)
    oracle_phase = [dict(d) for d in network.consensus_history]
    removed = set(values[int(info["winner"])]) if info["winner"] is not None \
        and int(info["winner"]) >= 0 else set()
    survivors = [s for s in range(n) if s not in removed]
    events_oracle = oracle_events(recorders, survivors)

    state = init_state(uids, view0._id_fp_sum, settings)
    faults = crash_faults([I32_MAX] * n)
    _, logs = simulate(state, faults, n_ticks, settings, fallback=sched)

    oracle_cols = lineage_mod.counter_phase_columns(
        oracle_counts, oracle_phase, events_oracle)
    engine_cols = lineage_mod.engine_phase_columns(logs)
    return ({"all": lineage_mod.fold_spans(oracle_cols, start_tick=0)},
            {"all": lineage_mod.fold_spans(engine_cols, start_tick=0)})
