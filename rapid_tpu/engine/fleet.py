"""Fleet mode: a batch of independent clusters as one XLA program.

One engine dispatch normally simulates one cluster. Fleet mode ``vmap``s
the jitted ``lax.scan`` tick loop (``step.fleet_body``) over a leading
fleet axis ``F``, so F clusters — each with its own fault script, churn
mix and scripted consensus — advance together in a single device
program. The tick body is traced exactly once regardless of F; adding
clusters grows an XLA batch dimension, not compile time.

Adversary lowering
------------------
``lower_schedule`` compiles an unscripted ``faults.AdversarySchedule``
straight into the device pytrees the scan already consumes — no host
planner, no per-tick host loop:

- crashes -> ``EngineFaults.crash_tick`` (padded to capacity with the
  never-sentinel);
- directed / flip-flop partitions -> the ``LinkWindow`` tensors
  ``state.link_faults`` lowers (``link_src/dst/start/end/period``);
- scripted proposes -> a single-instance ``FallbackSchedule``: the
  explicit ``delay_ticks`` becomes the per-slot fallback timer, the
  distinct proposals become the fingerprint table rows, and
  ``inst_epoch = 0`` gates the instance on the boot configuration
  exactly like the oracle's config-id filter (a decide before the
  propose tick expires it);
- planner-scripted churn joins/leaves ride along as the per-member
  ``ChurnSchedule`` (see ``campaign.py`` for the sampled mixes).

Slot identities default to the differential harness universe
(``diff.default_endpoints`` / ``default_node_ids``), so a lowered member
is the device twin of exactly the scenario the host adversary referees.

Fidelity envelope
-----------------
Fleet members run in one of two modes, chosen statically per member
kind at lowering time:

- **Shared-state** (``lower_schedule`` / ``stack_members``): one merged
  cut/consensus state per cluster, ``O(C·K)`` memory, exact for crash,
  scripted-propose and scheduled-churn scenarios. This stays the fast
  path for the crash/churn/contested member kinds.
- **Per-receiver** (``lower_receiver_schedule`` /
  ``stack_receiver_members``): every slot carries its own view, wire
  messages are stamped with the sender's config and recipient snapshot,
  and ``LinkWindow`` reachability is evaluated per (sender, receiver)
  edge at delivery — the semantics the host adversary
  (``engine.adversary``) replays sequentially, now on device inside the
  same ``lax.scan``. Partition and flip-flop members are **device-exact**:
  campaigns report their per-slot event streams and counters without any
  host referee in the loop, and
  ``diff.run_receiver_differential`` re-proves the bit-identity as a
  belt-and-suspenders spot check. The cost is quadratic per-member state
  (``[C, C, K]`` report/topology tensors plus explicit wire buffers);
  ``receiver.receiver_state_bytes`` sizes it exactly and
  ``check_receiver_budget`` refuses fleets beyond
  ``Settings.receiver_capacity_cap`` with a structured
  :class:`ReceiverBudgetError` before anything is allocated.
"""
from __future__ import annotations

import functools
import time
from typing import Dict, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from rapid_tpu.engine import churn as churn_mod
from rapid_tpu.engine import paxos as paxos_mod
from rapid_tpu.engine.state import (EngineFaults, EngineState, init_state,
                                    link_faults, pad_delay_rules,
                                    pad_link_windows)
from rapid_tpu.engine.step import (_fleet_simulate, _fleet_simulate_donated,
                                   fleet_trace_count,
                                   reset_fleet_trace_count)
from rapid_tpu.faults import AdversarySchedule, validate_schedule
from rapid_tpu.settings import Settings

__all__ = [
    "FleetMember",
    "ReceiverBudgetError",
    "ReceiverMember",
    "check_receiver_budget",
    "clear_boot_caches",
    "enable_compile_cache",
    "fleet_aot_compile",
    "fleet_simulate",
    "fleet_trace_count",
    "lower_receiver_schedule",
    "lower_schedule",
    "member_logs",
    "receiver_fleet_aot_compile",
    "receiver_fleet_simulate",
    "reset_fleet_trace_count",
    "stack_members",
    "stack_receiver_members",
]


class FleetMember(NamedTuple):
    """One cluster's complete device program: state + lowered scripts.

    A plain pytree; ``stack_members`` turns a list of these into the
    batched fleet pytree ``fleet_simulate`` consumes. ``churn`` and
    ``fallback`` are always present (inert schedules instead of None) so
    every member shares one treedef.
    """

    state: EngineState
    faults: EngineFaults
    churn: churn_mod.ChurnSchedule
    fallback: paxos_mod.FallbackSchedule


@functools.lru_cache(maxsize=None)
def _default_identities_cached(n: int) -> Tuple[Tuple[int, ...], int]:
    from rapid_tpu.engine.diff import default_endpoints, default_node_ids
    from rapid_tpu.oracle.membership_view import id_fingerprint, uid_of

    uids = tuple(uid_of(e) for e in default_endpoints(n))
    id_fp_sum = sum(id_fingerprint(nid)
                    for nid in default_node_ids(n)) & ((1 << 64) - 1)
    return uids, id_fp_sum


def _default_identities(n: int):
    """The differential-harness identity universe for an n-slot scenario.

    Memoized per N: a campaign lowers hundreds of members of the same
    size, and the uid/fingerprint hash loop is pure host work that never
    changes for a given universe.
    """
    uids, id_fp_sum = _default_identities_cached(n)
    return list(uids), id_fp_sum


#: Booted default-universe EngineStates keyed by
#: (n, n_uids, id_fp_sum, settings). Members differ only in their fault
#: scripts and dormant-slot id fingerprints, so the expensive boot —
#: host lexsort ring permutations, device build_topology/ring0_positions,
#: LUT materialization — is computed once per shape and shared;
#: per-member ``id_fps`` are patched in with a cheap ``_replace``. Safe
#: because lowered states are read-only inputs to ``jnp.stack`` (every
#: dispatch stacks fresh buffers; donation only ever consumes those).
_BOOT_CACHE: Dict[Tuple, EngineState] = {}

#: Booted default-universe ReceiverState templates keyed by
#: (n, id_fp_sum, settings). The only seed-dependent leaf of
#: ``init_receiver_state`` is the jitter ``delay_table``
#: (``build_delay_table(seed, ...)``); everything else — the base boot
#: plus the [C, C(, K)] per-slot broadcasts — is identical across
#: members, so the template is built once with seed 0 and each member
#: replaces just its delay table.
_RX_BOOT_CACHE: Dict[Tuple, object] = {}

#: Packed twins of the rx boot templates (``rx_kernel != "xla"``), same
#: key: the packed carry is delay-table-independent by construction
#: (the table rides ``PackedReceiverBundle``, outside the carry).
_RX_PACKED_CACHE: Dict[Tuple, object] = {}


def clear_boot_caches() -> None:
    """Drop the memoized boot states (tests; long multi-config runs)."""
    _BOOT_CACHE.clear()
    _RX_BOOT_CACHE.clear()
    _RX_PACKED_CACHE.clear()
    _default_identities_cached.cache_clear()


#: Resolved persistent-cache directory once enabled (None = not enabled).
_COMPILE_CACHE_DIR: Optional[str] = None


def enable_compile_cache(cache_dir: Optional[str] = None) -> str:
    """Persist AOT executables to an on-disk XLA compilation cache.

    The per-pool executable cache dedupes compiles *within* one
    campaign; this extends it *across* processes: XLA serializes each
    compiled program keyed by its HLO fingerprint, so a re-run of the
    same campaign (or any campaign whose pools hit the same program
    shapes) loads executables from disk instead of re-running LLVM.
    Identical programs by construction — only compile wall changes.

    Resolution order: explicit ``cache_dir`` argument, then the
    ``RAPID_TPU_COMPILE_CACHE`` environment variable, then
    ``~/.cache/rapid_tpu/xla``. Idempotent; returns the directory in
    effect (the first enabled directory wins, matching XLA's own
    process-global cache config).

    Call before the process's first compilation: XLA binds the cache
    when the first program compiles, and enabling the directory after
    that point is silently a no-op (``bench.py`` enables it at the top
    of ``main`` for exactly this reason).
    """
    global _COMPILE_CACHE_DIR
    if _COMPILE_CACHE_DIR is not None:
        return _COMPILE_CACHE_DIR
    import os

    import jax
    cache_dir = (cache_dir
                 or os.environ.get("RAPID_TPU_COMPILE_CACHE")
                 or os.path.join(os.path.expanduser("~"), ".cache",
                                 "rapid_tpu", "xla"))
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    _COMPILE_CACHE_DIR = cache_dir
    return cache_dir


def _compile_proposes(schedule: AdversarySchedule, uids_np: np.ndarray,
                      c: int) -> paxos_mod.FallbackSchedule:
    """Scripted proposes -> one consensus instance gated on epoch 0.

    The explicit ``ScriptedPropose.delay_ticks`` is the oracle's
    ``recovery_delay_ticks``, so no jitter table is involved: both sides
    share the same deterministic timer arithmetic. Distinct proposals
    become fingerprint-table rows; split camps whose per-proposal tally
    stays under the fast quorum recover through the device classic
    chain (phase 1a/1b/2a/2b in ``engine.paxos``).
    """
    values = sorted({tuple(p.proposal) for p in schedule.proposes})
    sched = paxos_mod.empty_fallback_schedule(c, instances=1,
                                              pids=max(1, len(values)))
    if not values:
        return sched
    pid_of = {v: i for i, v in enumerate(values)}
    for p in schedule.proposes:
        sched.prop_tick[0, p.slot] = p.tick
        sched.prop_pid[0, p.slot] = pid_of[tuple(p.proposal)]
        sched.prop_delay[0, p.slot] = p.delay_ticks
    for v, pid in pid_of.items():
        sched.table_mask[0, pid, list(v)] = True
    paxos_mod._fingerprint_tables(sched, uids_np, c)
    return sched


def lower_schedule(schedule: AdversarySchedule, settings: Settings, *,
                   churn: Optional[churn_mod.ChurnSchedule] = None,
                   id_fps: Optional[np.ndarray] = None,
                   uids: Optional[Sequence[int]] = None,
                   id_fp_sum: Optional[int] = None) -> FleetMember:
    """Compile one ``AdversarySchedule`` into a device ``FleetMember``.

    ``uids``/``id_fp_sum`` default to the differential-harness universe
    so the member is the device twin of the scenario
    ``diff.run_adversarial_differential`` replays. ``churn`` (with its
    dormant-slot ``id_fps``) rides along; it must carry no redraw script
    (fleet members batch with one treedef) and defaults to the inert
    schedule. The universe is padded to ``settings.capacity`` when that
    exceeds ``schedule.n``. Delay rules are per-receiver-only (the shared
    wire has no per-edge arrival ticks) and are rejected here.
    """
    validate_schedule(schedule)
    if schedule.delays:
        raise ValueError("shared-state members do not support delay rules; "
                         "lower with lower_receiver_schedule instead")
    n = schedule.n
    default_universe = uids is None
    if uids is None:
        uids, default_sum = _default_identities(n)
        if id_fp_sum is None:
            id_fp_sum = default_sum
    elif id_fp_sum is None:
        id_fp_sum = 0
    c = max(settings.capacity, n)
    eff = settings if settings.capacity == c else settings.with_(capacity=c)

    if id_fps is not None and len(id_fps) > len(uids):
        # id_fps spanning the padded universe (synthetic churn schedules
        # cover dormant slots too): extend the uid list with init_state's
        # own pad rule so the two stay slot-aligned.
        from rapid_tpu import hashing

        uids = list(uids) + [hashing.hash64(i, seed=0x636170)
                             for i in range(len(id_fps) - len(uids))]
    if default_universe:
        # Memoized boot: the uid universe is a pure function of
        # (n, len(uids)) here, so the booted state is shared across the
        # fleet and only the dormant-slot id fingerprints differ.
        key = (n, len(uids), id_fp_sum, eff)
        state = _BOOT_CACHE.get(key)
        if state is None:
            state = init_state(uids, id_fp_sum, eff)
            _BOOT_CACHE[key] = state
        if id_fps is not None:
            state = _patch_id_fps(state, id_fps, c)
    else:
        state = init_state(uids, id_fp_sum, eff, id_fps=id_fps)
    uids_np = _uids_np_from_state(state)

    crash = np.full(c, np.iinfo(np.int32).max, np.int64)
    crash[:n] = schedule.crash_tick_array()
    faults = link_faults(crash.tolist(), schedule.windows, c)
    fallback = _compile_proposes(schedule, uids_np, c)
    if churn is None:
        churn = churn_mod.empty_schedule(c)
    elif churn.redraw_tick is not None:
        raise ValueError("fleet members cannot carry redraw scripts "
                         "(treedefs must match across the fleet axis)")
    return FleetMember(state=state, faults=faults, churn=churn,
                       fallback=fallback)


def _patch_id_fps(state: EngineState, id_fps, c: int) -> EngineState:
    """Swap a member's dormant-slot id fingerprints into a cached boot
    state — bit-identical to ``init_state(..., id_fps=...)``, which only
    ever feeds ``id_fps`` (zero-padded to capacity) into the
    ``idfp_hi/lo`` limbs."""
    import jax.numpy as jnp

    from rapid_tpu import hashing

    id_fps_np = np.asarray(id_fps, dtype=np.uint64)
    if len(id_fps_np) < c:
        id_fps_np = np.concatenate(
            [id_fps_np, np.zeros(c - len(id_fps_np), np.uint64)])
    ifp_hi, ifp_lo = hashing.np_to_limbs(id_fps_np)
    return state._replace(idfp_hi=jnp.asarray(ifp_hi),
                          idfp_lo=jnp.asarray(ifp_lo))


def _uids_np_from_state(state: EngineState) -> np.ndarray:
    """Recover the padded uint64 uid universe from a booted state."""
    from rapid_tpu import hashing

    return hashing.np_from_limbs(np.asarray(state.uid_hi),
                                 np.asarray(state.uid_lo))


def _pad_fallback(sched: paxos_mod.FallbackSchedule, n_inst: int,
                  n_pids: int) -> paxos_mod.FallbackSchedule:
    """Pad instances/pids so fallback pytrees batch across the fleet.

    Pad instances get negative ``inst_epoch`` (the epoch counter never
    goes negative, so they are dead rows); pad pids are all-False mask
    rows no ``prop_pid`` ever points at.
    """
    i0, p0 = sched.table_mask.shape[0], sched.table_mask.shape[1]
    c = sched.table_mask.shape[2]
    if (i0, p0) == (n_inst, n_pids):
        return sched
    i_pad, p_pad = n_inst - i0, n_pids - p0
    if i_pad < 0 or p_pad < 0:
        raise ValueError("cannot shrink a fallback schedule")
    i32max = np.iinfo(np.int32).max

    def pad_ic(a, fill):
        return np.concatenate(
            [a, np.full((i_pad, c), fill, a.dtype)], axis=0)

    mask = np.concatenate(
        [sched.table_mask, np.zeros((i0, p_pad, c), bool)], axis=1)
    mask = np.concatenate([mask, np.zeros((i_pad, n_pids, c), bool)], axis=0)
    hi = np.concatenate(
        [sched.table_hi, np.zeros((i0, p_pad), np.uint32)], axis=1)
    hi = np.concatenate([hi, np.zeros((i_pad, n_pids), np.uint32)], axis=0)
    lo = np.concatenate(
        [sched.table_lo, np.zeros((i0, p_pad), np.uint32)], axis=1)
    lo = np.concatenate([lo, np.zeros((i_pad, n_pids), np.uint32)], axis=0)
    return paxos_mod.FallbackSchedule(
        inst_epoch=np.concatenate(
            [sched.inst_epoch, -np.arange(1, i_pad + 1, dtype=np.int32)]),
        prop_tick=pad_ic(sched.prop_tick, i32max),
        prop_pid=pad_ic(sched.prop_pid, -1),
        prop_delay=pad_ic(sched.prop_delay, 0),
        table_mask=mask, table_hi=hi, table_lo=lo)


def _resolve_max(requested: Optional[int], fleet_max: int,
                 what: str) -> int:
    if requested is None:
        return fleet_max
    if requested < fleet_max:
        raise ValueError(f"{what}={requested} below the fleet max "
                         f"{fleet_max}; padding cannot shrink")
    return requested


def stack_members(members: Sequence[FleetMember], *,
                  n_windows: Optional[int] = None,
                  n_instances: Optional[int] = None,
                  n_pids: Optional[int] = None) -> FleetMember:
    """Stack per-cluster pytrees along a new leading fleet axis.

    Members must share capacity, K and fault configuration (the static
    aux data of ``EngineFaults``); link-window counts and fallback
    instance/pid counts are padded to the fleet max with inert rows so
    all treedefs (and shapes) match before ``jnp.stack``.

    ``n_windows``/``n_instances``/``n_pids`` raise the padding targets
    above this fleet's own maxima (never below). A campaign passes its
    *global* maxima so every dispatch of a mode shares one batched
    program shape — one XLA executable for the whole campaign instead
    of a recompile per dispatch shape. The cost is inert padding rows,
    which the dispatch observatory reports per dispatch.
    """
    import jax
    import jax.numpy as jnp

    if not members:
        raise ValueError("empty fleet")
    c0 = int(members[0].state.member.shape[0])
    for m in members:
        if int(m.state.member.shape[0]) != c0:
            raise ValueError("fleet members must share one capacity")
        if m.churn.redraw_tick is not None:
            raise ValueError("fleet members cannot carry redraw scripts")
    w = _resolve_max(n_windows,
                     max(m.faults.n_windows for m in members), "n_windows")
    n_inst = _resolve_max(
        n_instances, max(m.fallback.inst_epoch.shape[0] for m in members),
        "n_instances")
    pids = _resolve_max(
        n_pids, max(m.fallback.table_mask.shape[1] for m in members),
        "n_pids")
    members = [
        m._replace(faults=pad_link_windows(m.faults, w),
                   fallback=_pad_fallback(m.fallback, n_inst, pids))
        for m in members
    ]
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *members)


def fleet_simulate(fleet: FleetMember, n_ticks: int,
                   settings: Settings, mesh=None, fleet_mesh=None) -> tuple:
    """Run every fleet member ``n_ticks`` ticks in one jitted dispatch.

    ``fleet`` is the batched pytree from ``stack_members``. Returns
    ``(final_states, logs)`` where every leaf carries a leading fleet
    axis: states are ``[F, ...]``, logs are member-major ``[F, T, ...]``.
    With ``settings.flight_recorder_window > 0`` the result grows to
    ``(final_states, logs, recorders)`` — one ``[F, W, G]`` gauge ring
    plus per-member stamps (``rapid_tpu.engine.recorder``). The tick
    body compiles once per (shape, settings) — re-dispatching with
    fresh scenarios of the same shape is compile-free.

    ``mesh`` (static) shards every member's slot axis over the device
    mesh while the fleet axis stays replicated (``P(None, 'slots')`` on
    ``[F, C]`` leaves) — the vmapped campaign and the single-member run
    produce bit-identical results either way. ``fleet_mesh`` (static,
    mutually exclusive with ``mesh``) instead shards the *fleet* axis as
    ``P('fleet')``: whole members per device, no collectives, also
    bit-identical.
    """
    return _fleet_simulate(fleet.state, fleet.faults, fleet.churn,
                           fleet.fallback, int(n_ticks), settings, mesh,
                           fleet_mesh)


def _aot_info(lowered, lower_s: float) -> Tuple[object, Dict[str, object]]:
    """Compile a lowered program, timing the compile separately and
    attaching XLA's memory analysis of the executable."""
    from rapid_tpu.telemetry.profile import compiled_memory_stats

    t0 = time.perf_counter()
    compiled = lowered.compile()
    compile_s = time.perf_counter() - t0
    info: Dict[str, object] = {"lower_s": round(lower_s, 6),
                               "compile_s": round(compile_s, 6)}
    info.update(compiled_memory_stats(compiled))
    return compiled, info


def fleet_aot_compile(fleet: FleetMember, n_ticks: int, settings: Settings,
                      mesh=None, fleet_mesh=None,
                      donate: bool = False) -> Tuple[object, Dict[str, object]]:
    """AOT-compile the shared-state fleet program for ``fleet``'s shape.

    Returns ``(compiled, info)``: ``compiled(state, faults, churn,
    fallback)`` is the executable (static args baked in), ``info``
    carries the lower/compile wall split plus XLA memory analysis
    (``AOT_COMPILE_SPEC``). The campaign observatory uses this instead
    of the jit cache so the first-dispatch compile cost is an explicit
    measurement, not an inference from trace counters — every dispatch
    of the same stacked shape reuses the executable with zero compile
    wall.

    ``donate=True`` compiles the single-shot variant whose input buffers
    are consumed by the outputs (the pipelined campaign driver's choice:
    each stacked fleet is executed exactly once). ``fleet_mesh`` shards
    the fleet axis — see ``fleet_simulate``.
    """
    fn = _fleet_simulate_donated if donate else _fleet_simulate
    t0 = time.perf_counter()
    lowered = fn.lower(fleet.state, fleet.faults, fleet.churn,
                       fleet.fallback, int(n_ticks), settings, mesh,
                       fleet_mesh)
    return _aot_info(lowered, time.perf_counter() - t0)


def member_logs(logs, i: int):
    """Slice member ``i``'s ``[T, ...]`` StepLog out of fleet logs."""
    import jax

    return jax.tree_util.tree_map(lambda x: x[i], logs)


# --- per-receiver fleet members (exact link faults on device) ------------


class ReceiverMember(NamedTuple):
    """One per-receiver cluster: quadratic state + its fault program."""

    state: object               # receiver.ReceiverState
    faults: EngineFaults


class ReceiverBudgetError(ValueError):
    """A per-receiver fleet would exceed the sized memory budget.

    Raised *before* any device allocation, with the measured per-member
    and total byte costs in the message — the structured alternative to
    an opaque device OOM mid-campaign."""

    def __init__(self, capacity: int, fleet_size: int, cap: int,
                 member_bytes: int, total_bytes: int, *,
                 packed_bytes: Optional[int] = None,
                 unpacked_bytes: Optional[int] = None) -> None:
        self.capacity = capacity
        self.fleet_size = fleet_size
        self.cap = cap
        self.member_bytes = member_bytes
        self.total_bytes = total_bytes
        self.packed_bytes = packed_bytes
        self.unpacked_bytes = unpacked_bytes
        diet = ""
        if packed_bytes is not None and unpacked_bytes:
            diet = (f"; packed layout {packed_bytes / 2**20:.1f} MiB vs "
                    f"{unpacked_bytes / 2**20:.1f} MiB dense "
                    f"({unpacked_bytes / packed_bytes:.1f}x headroom via "
                    f"Settings.rx_kernel)")
        super().__init__(
            f"per-receiver fleet over budget: capacity {capacity} > "
            f"receiver_capacity_cap {cap} "
            f"({member_bytes / 2**20:.1f} MiB/member, "
            f"{total_bytes / 2**20:.1f} MiB for fleet of {fleet_size}; "
            f"raise Settings.receiver_capacity_cap to override{diet})")


def check_receiver_budget(capacity: int, fleet_size: int,
                          settings: Settings) -> int:
    """Size a per-receiver fleet; returns per-member bytes or raises
    :class:`ReceiverBudgetError` when ``capacity`` exceeds
    ``settings.receiver_capacity_cap``.

    The byte figure is derived from the *actual* state pytree the fleet
    program is lowered over — ``jax.eval_shape`` over the boot skeleton
    (and, for ``rx_kernel != "xla"``, over ``rx_packed``'s real pack
    function) — so it cannot drift when the layout changes; the dense
    figure is additionally asserted against the historical shape table
    (``receiver_state_bytes``). ``profile.receiver_memory_block`` pins
    this figure against XLA's measured argument bytes within 1%."""
    from rapid_tpu.engine import rx_packed
    from rapid_tpu.engine.receiver import receiver_state_bytes

    dense_bytes = rx_packed.dense_state_bytes(capacity, settings)
    assert dense_bytes == receiver_state_bytes(
        capacity, settings.K, ring_depth=settings.delivery_ring_depth)
    packed_bytes = None
    member_bytes = dense_bytes
    if settings.rx_kernel != "xla":
        packed_bytes = rx_packed.bundle_state_bytes(capacity, settings)
        member_bytes = packed_bytes
    if capacity > settings.receiver_capacity_cap:
        raise ReceiverBudgetError(capacity, fleet_size,
                                  settings.receiver_capacity_cap,
                                  member_bytes, member_bytes * fleet_size,
                                  packed_bytes=(
                                      packed_bytes if packed_bytes is not None
                                      else rx_packed.bundle_state_bytes(
                                          capacity, settings)),
                                  unpacked_bytes=dense_bytes)
    return member_bytes


def lower_receiver_schedule(schedule: AdversarySchedule,
                            settings: Settings, *,
                            uids: Optional[Sequence[int]] = None,
                            id_fp_sum: Optional[int] = None,
                            fleet_size: int = 1) -> ReceiverMember:
    """Compile one link-fault ``AdversarySchedule`` into a device
    :class:`ReceiverMember` (the per-receiver analogue of
    ``lower_schedule``).

    Scripted proposes and churn are shared-state-only member kinds and
    are rejected here — campaign dispatch routes them to the fast path.
    Delay rules lower to the ``EngineFaults`` delay leaves the delivery
    ring consumes; ``validate_schedule`` budget-checks them against
    ``settings.delivery_ring_depth`` (structured ``DelayBudgetError``).
    The budget check runs first so oversized fleets fail structurally
    before any quadratic allocation.
    """
    from rapid_tpu.engine.receiver import init_receiver_state

    validate_schedule(schedule, ring_depth=settings.delivery_ring_depth)
    if schedule.proposes:
        raise ValueError("per-receiver members do not support scripted "
                         "proposes; lower with lower_schedule instead")
    n = schedule.n
    c = max(settings.capacity, n)
    eff = settings if settings.capacity == c else settings.with_(capacity=c)
    check_receiver_budget(c, fleet_size, eff)
    default_universe = uids is None
    if uids is None:
        uids, default_sum = _default_identities(n)
        if id_fp_sum is None:
            id_fp_sum = default_sum
    elif id_fp_sum is None:
        id_fp_sum = 0
    if default_universe:
        # Memoized boot template: everything but the seeded jitter
        # delay_table is schedule-independent, and booting the quadratic
        # receiver state (base boot + [C, C(, K)] broadcasts) dominated
        # per-member lowering wall before this cache.
        from rapid_tpu.engine.receiver import N_DRAWS
        from rapid_tpu.engine.paxos import build_delay_table

        key = (n, id_fp_sum, eff)
        template = _RX_BOOT_CACHE.get(key)
        if template is None:
            template = init_receiver_state(uids, id_fp_sum, eff, seed=0)
            _RX_BOOT_CACHE[key] = template
        import jax.numpy as jnp

        if eff.rx_kernel != "xla":
            # The packed carry is delay-table-independent (the table
            # rides the bundle, not the carry), so members sharing a
            # boot template share one packed template too.
            from rapid_tpu.engine import rx_packed

            packed = _RX_PACKED_CACHE.get(key)
            if packed is None:
                packed = rx_packed.pack_receiver_state(template, eff)
                _RX_PACKED_CACHE[key] = packed
            state = rx_packed.PackedReceiverBundle(
                packed=packed, delay_table=jnp.asarray(
                    build_delay_table(schedule.seed, c, N_DRAWS, eff)))
        else:
            state = template._replace(delay_table=jnp.asarray(
                build_delay_table(schedule.seed, c, N_DRAWS, eff)))
    else:
        state = init_receiver_state(uids, id_fp_sum, eff, seed=schedule.seed)
        if eff.rx_kernel != "xla":
            from rapid_tpu.engine import rx_packed

            state = rx_packed.bundle_from_dense(state, eff)
    crash = np.full(c, np.iinfo(np.int32).max, np.int64)
    crash[:n] = schedule.crash_tick_array()
    faults = link_faults(crash.tolist(), schedule.windows, c,
                         delays=schedule.delays, delay_seed=schedule.seed)
    return ReceiverMember(state=state, faults=faults)


def stack_receiver_members(members: Sequence[ReceiverMember], *,
                           n_windows: Optional[int] = None,
                           n_delay_rules: Optional[int] = None
                           ) -> ReceiverMember:
    """Stack per-receiver members along a new leading fleet axis.

    Same contract as ``stack_members``: shared capacity, link windows
    *and delay rules* padded to the fleet max with inert rows
    (``n_windows``/``n_delay_rules`` raise the targets to campaign-global
    maxima so all per-receiver dispatches share one program shape; an
    inert delay rule contributes delay 0 on every edge, see
    ``state.pad_delay_rules``). The ``[C, C, K]`` leaves become
    ``[F, C, C, K]`` — ``sharding.fleet_spec_for`` keeps the fleet axis
    replicated and shards only the slot axis.
    """
    import jax
    import jax.numpy as jnp

    if not members:
        raise ValueError("empty fleet")

    def _capacity(state) -> int:
        # Packed bundles keep the slot axis first on every plane, so
        # ``packed.member`` is [C, ceil(C/8)] — shape[0] is C either way.
        packed = getattr(state, "packed", None)
        inner = packed if packed is not None else state
        return int(inner.member.shape[0])

    c0 = _capacity(members[0].state)
    for m in members:
        if _capacity(m.state) != c0:
            raise ValueError("fleet members must share one capacity")
    w = _resolve_max(n_windows,
                     max(m.faults.n_windows for m in members), "n_windows")
    r = _resolve_max(n_delay_rules,
                     max(m.faults.n_delay_rules for m in members),
                     "n_delay_rules")
    members = [m._replace(
        faults=pad_delay_rules(pad_link_windows(m.faults, w), r))
        for m in members]
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *members)


def receiver_fleet_simulate(fleet: ReceiverMember, n_ticks: int,
                            settings: Settings, fleet_mesh=None) -> tuple:
    """Run a stacked per-receiver fleet in one jitted dispatch.

    Returns ``(final_states, logs)`` with a leading fleet axis on every
    leaf, like ``fleet_simulate``. The tick body traces once regardless
    of F. ``fleet_mesh`` optionally shards the member axis."""
    from rapid_tpu.engine.receiver import receiver_fleet_simulate as _run

    return _run(fleet.state, fleet.faults, int(n_ticks), settings,
                fleet_mesh)


def receiver_fleet_aot_compile(fleet: ReceiverMember, n_ticks: int,
                               settings: Settings, fleet_mesh=None,
                               donate: bool = False
                               ) -> Tuple[object, Dict[str, object]]:
    """AOT-compile the per-receiver fleet program (the
    ``fleet_aot_compile`` analogue): ``compiled(state, faults)`` plus
    the lower/compile/memory info record. ``donate``/``fleet_mesh`` as
    in ``fleet_aot_compile``."""
    from rapid_tpu.engine import receiver as receiver_mod

    fn = (receiver_mod._fleet_simulate_donated if donate
          else receiver_mod._fleet_simulate)
    t0 = time.perf_counter()
    lowered = fn.lower(fleet.state, fleet.faults, int(n_ticks),
                       settings, fleet_mesh)
    return _aot_info(lowered, time.perf_counter() - t0)
