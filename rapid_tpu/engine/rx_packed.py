"""Packed per-receiver scan carry: the receiver memory diet.

The dense ``ReceiverState`` carry is quadratic per member with most of
the quadratic planes boolean (``[C, C]`` seen/mask planes, ``[D, C, C]``
wire rings, ``[C, C, K]`` reports) — one byte per bit under XLA's bool
layout. This module re-expresses the scan carry as
:class:`PackedReceiverState`:

- every bool plane becomes a little-endian uint8 bit-plane packed along
  its trailing slot axis (``[C, C] -> [C, ceil(C/8)]``), the same
  ``packbits`` convention the sort-free topology machinery uses
  (``topology._SCAN_BLOCK`` LUT blocks are 8 bits for the same reason);
  ``reports [C, C, K]`` is transposed to ``[C, K, C]`` first so the
  packed axis is the C-sized observer axis, not the K-sized ring axis;
- per-slot epochs are carried as narrow deltas from a shared
  ``epoch_base`` (the fleet-wide min, rebased at every pack). A delta
  that does not fit ``Settings.rx_epoch_delta_bits`` is clamped AND
  flagged sticky (``receiver.FLAG_EPOCH_DELTA_SAT``) so ``check_flags``
  refuses the run — the fallback is explicit widening to 16-bit deltas,
  never a silently wrong epoch;
- ``obs_full`` (the ``[C, C, K]`` int32 observer topology, the single
  largest dense leaf) is dropped from the carry entirely and recomputed
  from membership at unpack: the step maintains the invariant
  ``obs_full[r] == build_topology(member[r], ...)`` at every tick start
  (group 12 rebuilds every row on any decide; boot broadcasts a single
  row build), so the plane is pure derived state;
- ``delay_table`` (read-only inside the step) leaves the carry for
  :class:`PackedReceiverBundle` — ``lax.scan`` then treats it as a
  closed-over constant instead of a threaded carry leaf;
- ``pb_vrnd_r``/``pb_vrnd_i`` (classic-round numbers {0, 1, 2} and rank
  indices < C <= receiver_capacity_cap) narrow to int8/int16 with the
  same clamp-and-flag guard (``receiver.FLAG_PACK_NARROW_SAT``).

Exactness contract: ``unpack(pack(rs)) == rs`` bit-for-bit whenever no
saturation flag fires, and the packed scan runs the *unmodified* dense
``receiver_step`` between unpack/pack — decisions, counters and logs are
bit-identical to the dense scan by construction. ``Settings.rx_kernel``
selects the layout statically; ``"xla"`` never touches this module.
"""
from __future__ import annotations

import collections
import functools
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from rapid_tpu.engine import receiver as receiver_mod
from rapid_tpu.engine import recorder as recorder_mod
from rapid_tpu.engine import sharding as sharding_mod
from rapid_tpu.engine.state import ReceiverState
from rapid_tpu.settings import Settings

#: Dense leaves that leave the packed carry entirely.
OMITTED_FIELDS = ("obs_full", "delay_table", "epoch")

#: Bool leaves carried as packed uint8 bit-planes (trailing axis / 8).
BIT_FIELDS = frozenset((
    "stopped", "seen_down", "announced", "reg_valid", "px_vv_set",
    "px_cval_set",
    "own_fd_active", "notified", "pf",
    "pd",
    "w1b_set",
    "member", "reg_mask", "vt_seen", "pb_seen", "pb_set", "p2_seen",
    "p2_mask",
    "wv", "w1a", "w1b", "w2a", "w2a_mask", "pd_bcast",
    "w2b", "w2b_mask",
    "reports",
))

#: BIT_FIELDS whose trailing (packed) axis is K-sized, not C-sized.
_K_LAST = frozenset(("own_fd_active", "notified", "pf", "pd"))

#: int32 leaves narrowed in the packed carry: name -> (dtype, lo, hi).
NARROW_FIELDS = {
    "pb_vrnd_r": (jnp.int8, -128, 127),
    "pb_vrnd_i": (jnp.int16, -32768, 32767),
}

PackedReceiverState = collections.namedtuple(
    "PackedReceiverState",
    [f for f in ReceiverState._fields if f not in OMITTED_FIELDS]
    + ["epoch_base", "epoch_delta"])

#: The packed carry plus the scan-constant delay table (read-only in the
#: step, so it rides outside the ``lax.scan`` carry).
PackedReceiverBundle = collections.namedtuple(
    "PackedReceiverBundle", ("packed", "delay_table"))

#: The dense fields host-side extraction reads off a final state
#: (``receiver_run_payload`` / ``receiver_config_ids`` / ``check_flags``)
#: — what ``receiver.receiver_final_view`` unpacks from a packed final.
ReceiverFinalView = collections.namedtuple(
    "ReceiverFinalView", ("member", "stopped", "cfg_hi", "cfg_lo", "flags"))


def _pack_bits(xp, x):
    return xp.packbits(x, axis=-1, bitorder="little")


def _unpack_bits(xp, x, count):
    return xp.unpackbits(x, axis=-1, count=count,
                         bitorder="little").astype(bool)


def _delta_width(settings: Settings) -> Tuple[object, int]:
    if settings.rx_epoch_delta_bits == 8:
        return jnp.int8, 127
    return jnp.int16, 32767


def pack_receiver_state(rs: ReceiverState,
                        settings: Settings) -> PackedReceiverState:
    """Dense -> packed, clamping-and-flagging any value that does not fit
    its narrow carry dtype (see module docstring for the exactness
    contract)."""
    xp = jnp
    flags = rs.flags
    ddtype, dmax = _delta_width(settings)
    base = rs.epoch.min()
    delta = rs.epoch - base
    flags = flags | xp.where((delta > dmax).any(),
                             receiver_mod.FLAG_EPOCH_DELTA_SAT, 0)
    kw = {"epoch_base": base,
          "epoch_delta": xp.clip(delta, 0, dmax).astype(ddtype)}
    for name in PackedReceiverState._fields:
        if name in kw:
            continue
        if name == "flags":
            continue
        leaf = getattr(rs, name)
        if name == "reports":
            kw[name] = _pack_bits(xp, leaf.swapaxes(-1, -2))
        elif name in BIT_FIELDS:
            kw[name] = _pack_bits(xp, leaf)
        elif name in NARROW_FIELDS:
            ndtype, lo, hi = NARROW_FIELDS[name]
            flags = flags | xp.where(((leaf < lo) | (leaf > hi)).any(),
                                     receiver_mod.FLAG_PACK_NARROW_SAT, 0)
            kw[name] = xp.clip(leaf, lo, hi).astype(ndtype)
        else:
            kw[name] = leaf
    kw["flags"] = flags
    return PackedReceiverState(**kw)


def unpack_receiver_state(ps: PackedReceiverState, delay_table,
                          settings: Settings) -> ReceiverState:
    """Packed -> dense, recomputing ``obs_full`` from membership (the
    step's group-12 invariant makes the plane pure derived state)."""
    from rapid_tpu.engine.topology import build_topology

    xp = jnp
    c = ps.member.shape[0]
    k = ps.ring_order.shape[1]
    kw = {"delay_table": delay_table,
          "epoch": ps.epoch_base + ps.epoch_delta.astype(xp.int32)}
    for name in ReceiverState._fields:
        if name in kw or name == "obs_full":
            continue
        leaf = getattr(ps, name)
        if name == "reports":
            kw[name] = _unpack_bits(xp, leaf, c).swapaxes(-1, -2)
        elif name in BIT_FIELDS:
            kw[name] = _unpack_bits(xp, leaf, k if name in _K_LAST else c)
        elif name in NARROW_FIELDS:
            kw[name] = leaf.astype(xp.int32)
        else:
            kw[name] = leaf
    kw["obs_full"] = jax.vmap(
        lambda m: build_topology(xp, m, ps.ring_order, ps.ring_rank)[1])(
            kw["member"])
    return ReceiverState(**kw)


_pack_jit = functools.partial(jax.jit, static_argnums=(1,))(
    pack_receiver_state)


def bundle_from_dense(rs: ReceiverState,
                      settings: Settings) -> PackedReceiverBundle:
    """Wrap a booted dense state as the packed scan input."""
    return PackedReceiverBundle(packed=_pack_jit(rs, settings),
                                delay_table=rs.delay_table)


def as_bundle(state, settings: Settings) -> PackedReceiverBundle:
    if isinstance(state, PackedReceiverBundle):
        return state
    return bundle_from_dense(state, settings)


def final_view(ps: PackedReceiverState) -> ReceiverFinalView:
    """Host-side dense view of a packed final (see ``ReceiverFinalView``)."""
    c = ps.member.shape[-2]
    member = np.unpackbits(np.asarray(ps.member), axis=-1, count=c,
                           bitorder="little").astype(bool)
    stopped = np.unpackbits(np.asarray(ps.stopped), axis=-1, count=c,
                            bitorder="little").astype(bool)
    return ReceiverFinalView(member=member, stopped=stopped,
                             cfg_hi=np.asarray(ps.cfg_hi),
                             cfg_lo=np.asarray(ps.cfg_lo),
                             flags=np.asarray(ps.flags))


# --- sizing --------------------------------------------------------------

def abstract_dense_state(capacity: int, settings: Settings) -> ReceiverState:
    """A ``ShapeDtypeStruct`` skeleton of the dense per-member state —
    the input the packed byte accounting runs ``jax.eval_shape`` over, so
    the reported bytes come from the *actual* pack function and cannot
    drift from the layout."""
    shapes = receiver_mod.receiver_field_shapes(
        capacity, settings.K, ring_depth=settings.delivery_ring_depth)
    return ReceiverState(**{
        name: jax.ShapeDtypeStruct(shape, jnp.bool_ if item == 1
                                   else jnp.int32)
        for name, (shape, item) in shapes.items()})


def _tree_bytes(tree) -> int:
    return sum(int(np.prod(leaf.shape, dtype=np.int64)) * leaf.dtype.itemsize
               for leaf in jax.tree_util.tree_leaves(tree))


@functools.lru_cache(maxsize=None)
def dense_state_bytes(capacity: int, settings: Settings) -> int:
    """Exact bytes of one dense carry, from the abstract boot skeleton
    (equals ``receiver.receiver_state_bytes`` — asserted by the budget
    check so the shape table cannot drift)."""
    return _tree_bytes(abstract_dense_state(capacity, settings))


@functools.lru_cache(maxsize=None)
def packed_state_bytes(capacity: int, settings: Settings) -> int:
    """Exact bytes of one packed carry (``PackedReceiverState``), derived
    by tracing ``pack_receiver_state`` over the abstract dense state."""
    dense = abstract_dense_state(capacity, settings)
    packed = jax.eval_shape(
        functools.partial(pack_receiver_state, settings=settings), dense)
    return _tree_bytes(packed)


@functools.lru_cache(maxsize=None)
def bundle_state_bytes(capacity: int, settings: Settings) -> int:
    """Exact per-member bytes of the packed scan input: the packed carry
    plus the scan-constant delay table."""
    dense = abstract_dense_state(capacity, settings)
    return packed_state_bytes(capacity, settings) + _tree_bytes(
        dense.delay_table)


# --- packed scan ---------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _simulate_packed(bundle: PackedReceiverBundle, faults, n_ticks: int,
                     settings: Settings, dense_final: bool):
    """The packed twin of ``receiver._simulate``: unpack -> the unmodified
    dense ``receiver_step`` -> repack, each tick. Only the packed carry
    crosses scan iterations, so the persistent working set is the diet
    figure; the dense state is a per-tick temporary. ``dense_final``
    (static) unpacks the final carry inside the jit — the single-member
    drop-in used by ``diff.run_receiver_differential``."""
    delay_table = bundle.delay_table

    def step(ps, _):
        rs = unpack_receiver_state(ps, delay_table, settings)
        nxt, log = receiver_mod.receiver_step(rs, faults, settings)
        return pack_receiver_state(nxt, settings), log

    if settings.flight_recorder_window:
        def rec_body(carry, _):
            st, rec = carry
            nxt, log = step(st, None)
            return (nxt, recorder_mod.record_receiver_step(
                rec, log, settings)), log

        (final, rec), logs = lax.scan(
            rec_body, (bundle.packed, recorder_mod.init(settings)), None,
            length=n_ticks)
        if dense_final:
            final = unpack_receiver_state(final, delay_table, settings)
        return final, logs, rec

    final, logs = lax.scan(step, bundle.packed, None, length=n_ticks)
    if dense_final:
        final = unpack_receiver_state(final, delay_table, settings)
    return final, logs


def simulate(state, faults, n_ticks: int, settings: Settings):
    """Single-member packed scan returning a *dense* final state (plus
    logs, plus the recorder ring when enabled) — a drop-in for the dense
    ``receiver_simulate`` contract. ``state`` may be a booted dense
    ``ReceiverState`` or an already-packed bundle."""
    return _simulate_packed(as_bundle(state, settings), faults,
                            int(n_ticks), settings, True)


# --- streaming chunks ----------------------------------------------------
#
# The resident service re-enters the packed scan chunk by chunk. The
# delay table is split out of the jit signature so the packed carry (and
# resumed recorder) can be donated without consuming the table — it is a
# scan constant reused by every chunk, and with ``dense_final=False``
# semantics the final stays packed so the carry type round-trips.

def _chunk_body(packed, delay_table, faults, n_ticks: int,
                settings: Settings):
    def step(ps, _):
        rs = unpack_receiver_state(ps, delay_table, settings)
        nxt, log = receiver_mod.receiver_step(rs, faults, settings)
        return pack_receiver_state(nxt, settings), log

    if settings.flight_recorder_window:
        def rec_body(carry, _):
            st, rec = carry
            nxt, log = step(st, None)
            return (nxt, recorder_mod.record_receiver_step(
                rec, log, settings)), log

        (final, rec), logs = lax.scan(
            rec_body, (packed, recorder_mod.init(settings)), None,
            length=n_ticks)
        return final, logs, rec

    final, logs = lax.scan(step, packed, None, length=n_ticks)
    return final, logs


def _chunk_resumed_body(packed, rec, delay_table, faults, n_ticks: int,
                        settings: Settings):
    def rec_body(carry, _):
        ps, r = carry
        rs = unpack_receiver_state(ps, delay_table, settings)
        nxt, log = receiver_mod.receiver_step(rs, faults, settings)
        return (pack_receiver_state(nxt, settings),
                recorder_mod.record_receiver_step(r, log, settings)), log

    (final, rec), logs = lax.scan(rec_body, (packed, rec), None,
                                  length=n_ticks)
    return final, logs, rec


_chunk_jit = functools.partial(
    jax.jit, static_argnums=(3, 4))(_chunk_body)
_chunk_donated = functools.partial(
    jax.jit, static_argnums=(3, 4), donate_argnums=(0,))(_chunk_body)
_chunk_resumed_jit = functools.partial(
    jax.jit, static_argnums=(4, 5))(_chunk_resumed_body)
_chunk_resumed_donated = functools.partial(
    jax.jit, static_argnums=(4, 5), donate_argnums=(0, 1))(
        _chunk_resumed_body)


def simulate_chunk(bundle, faults, n_ticks: int, settings: Settings,
                   rec=None, donate: bool = True):
    """One streaming chunk over the packed carry: bundle in, bundle out.

    Returns ``(PackedReceiverBundle, logs)`` — or ``(..., logs, rec)``
    when the recorder window is nonzero, resuming from ``rec`` when
    given. Chained chunks are bit-identical to one uninterrupted
    :func:`simulate` of the summed length (same unpack/step/repack body,
    same carry)."""
    bundle = as_bundle(bundle, settings)
    n_ticks = int(n_ticks)
    dt = bundle.delay_table
    if settings.flight_recorder_window and rec is not None:
        fn = _chunk_resumed_donated if donate else _chunk_resumed_jit
        final, logs, rec = fn(bundle.packed, rec, dt, faults, n_ticks,
                              settings)
        return PackedReceiverBundle(packed=final, delay_table=dt), logs, rec
    fn = _chunk_donated if donate else _chunk_jit
    out = fn(bundle.packed, dt, faults, n_ticks, settings)
    if settings.flight_recorder_window:
        final, logs, rec = out
        return (PackedReceiverBundle(packed=final, delay_table=dt), logs,
                rec)
    final, logs = out
    return PackedReceiverBundle(packed=final, delay_table=dt), logs


def fleet_body(bundle, faults, n_ticks: int, settings: Settings,
               fleet_mesh=None):
    """The packed twin of ``receiver._fleet_body`` — finals stay *packed*
    (the memory diet applies to dispatch outputs too); hosts fold them
    via ``receiver.receiver_final_view``."""
    if fleet_mesh is not None:
        f = bundle.packed.member.shape[0]
        bundle = sharding_mod.fleet_axis_constrain_tree(
            bundle, fleet_mesh, f)
        faults = sharding_mod.fleet_axis_constrain_tree(
            faults, fleet_mesh, f)
    sim = lambda b, f_: _simulate_packed(b, f_, n_ticks, settings, False)
    outs = jax.vmap(sim)(bundle, faults)
    if fleet_mesh is not None:
        outs = tuple(sharding_mod.fleet_axis_constrain_tree(
            o, fleet_mesh, f) for o in outs)
    return outs
