"""On-device flight recorder: a bounded gauge ring in the jitted scan.

Campaign folds collapse thousands of clusters into percentiles, so by
the time the host learns a member is anomalous (never decided, tripped
an invariant, left the envelope) its per-tick history is gone — the
fleet scan keeps full ``StepLog`` columns on device, but shipping
``[F, T, ...]`` logs to the host for 100k members is exactly the
transfer the campaign driver exists to avoid. The recorder is the
middle ground: a static-size ``[W, G]`` ring of small per-tick gauges
(W = ``Settings.flight_recorder_window``) plus first-occurrence tick
stamps, carried through ``lax.scan`` alongside the engine state, cheap
enough to keep for *every* member and only pulled to the host for the
members the triage classifier flags (``campaign.py``).

Zero-overhead discipline (mirrors ``engine.invariants``): the window is
a *static* settings field; ``W == 0`` (the default) compiles the
recorder out entirely — the scan bodies in ``engine.step`` and
``engine.receiver`` keep their recorder-less code verbatim, so the
disabled jaxpr is byte-identical to a build without this module. Both
scan bodies reach the recorder through module attributes
(``recorder.record_step`` / ``recorder.record_receiver_step``) so tests
can monkeypatch a spy and prove the disabled path never calls in.

Gauge schema
------------
One shared ``GAUGE_NAMES`` row schema covers both kernels; gauges a
kernel does not observe hold ``UNOBSERVED`` (-1) so a triage consumer
can mix shared-state and per-receiver rings without per-kind schemas.
The ring holds the *last* W ticks (write position ``count % W``);
:func:`ring_rows` restores chronological order on the host.
"""
from __future__ import annotations

from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from rapid_tpu.settings import Settings

#: Value recorded for gauges the emitting kernel does not observe.
UNOBSERVED = -1

#: One row of the ring, in column order. The shared-state step fills
#: the protocol/engine gauges; the per-receiver step fills the exact
#: wire counters and the sticky flags word. ``announces``/``decides``
#: are counts (0/1 for the shared step, per-slot sums for receiver).
GAUGE_NAMES = (
    "tick",
    "n_member",
    "alerts_in_flight",
    "cut_reports",
    "vote_tally",
    "epoch",
    "px_timers_armed",
    "px_coord_round",
    "inv_bits",
    "announces",
    "decides",
    "sent",
    "delivered",
    "dropped",
    "flags",
)

N_GAUGES = len(GAUGE_NAMES)


class RecorderState(NamedTuple):
    """The extra scan carry; every leaf is i32 so fleet stacking is a
    plain vmap axis. Stamps are -1 until the event first occurs."""

    ring: object             # i32 [W, G] last-W gauge rows, ring order
    count: object            # i32 ticks recorded (write pos = count % W)
    first_announce: object   # i32 first tick any proposal was announced
    first_decide: object     # i32 first tick a view change decided
    first_fallback: object   # i32 first tick classic-Paxos traffic moved
    first_violation: object  # i32 first tick inv_bits/flags went nonzero


def init(settings: Settings) -> RecorderState:
    """Fresh recorder for one member. Only valid when the static window
    is nonzero — the W == 0 path must never construct a recorder."""
    w = int(settings.flight_recorder_window)
    if w <= 0:
        raise ValueError("recorder.init called with flight_recorder_window=0")
    neg = jnp.int32(-1)
    return RecorderState(
        ring=jnp.full((w, N_GAUGES), UNOBSERVED, jnp.int32),
        count=jnp.int32(0),
        first_announce=neg,
        first_decide=neg,
        first_fallback=neg,
        first_violation=neg,
    )


def _push(rec: RecorderState, row, tick, announced, decided, fallback,
          violated) -> RecorderState:
    """Write one gauge row at ``count % W`` and fold the stamps."""
    w = rec.ring.shape[0]
    pos = lax.rem(rec.count, jnp.int32(w))
    ring = lax.dynamic_update_slice(rec.ring, row[None, :],
                                    (pos, jnp.int32(0)))
    t = tick.astype(jnp.int32)
    stamp = lambda old, cond: jnp.where((old < 0) & cond, t, old)
    return RecorderState(
        ring=ring,
        count=rec.count + 1,
        first_announce=stamp(rec.first_announce, announced),
        first_decide=stamp(rec.first_decide, decided),
        first_fallback=stamp(rec.first_fallback, fallback),
        first_violation=stamp(rec.first_violation, violated),
    )


def record_step(rec: RecorderState, log, settings: Settings
                ) -> RecorderState:
    """Fold one shared-state ``StepLog`` tick into the recorder."""
    i32 = lambda x: jnp.asarray(x).astype(jnp.int32)
    un = jnp.int32(UNOBSERVED)
    announced = jnp.asarray(log.announce_now, bool)
    decided = jnp.asarray(log.decide_now, bool)
    fallback = (i32(log.pxvote_senders) + i32(log.px1a_senders)
                + i32(log.px1b_senders) + i32(log.px2a_senders)
                + i32(log.px2b_senders)) > 0
    violated = i32(log.inv_bits) != 0
    row = jnp.stack([
        i32(log.tick),
        i32(log.n_member),
        i32(log.alerts_in_flight),
        i32(log.cut_reports),
        i32(log.vote_tally),
        i32(log.epoch),
        i32(log.px_timers_armed),
        i32(log.px_coord_round),
        i32(log.inv_bits),
        announced.astype(jnp.int32),
        decided.astype(jnp.int32),
        un, un, un, un,          # sent / delivered / dropped / flags
    ])
    return _push(rec, row, log.tick, announced, decided, fallback, violated)


def record_receiver_step(rec: RecorderState, log, settings: Settings
                         ) -> RecorderState:
    """Fold one ``ReceiverStepLog`` tick into the recorder.

    Consumes the step *log* only — never the carry — so the packed
    receiver layouts (``Settings.rx_kernel``) ride through unchanged:
    ``rx_packed._simulate_packed`` folds the identical log pytree the
    dense scan emits."""
    i32 = lambda x: jnp.asarray(x).astype(jnp.int32)
    un = jnp.int32(UNOBSERVED)
    announced = jnp.asarray(log.announce, bool).any()
    decided = jnp.asarray(log.decide, bool).any()
    fallback = (i32(log.p1a_sent) + i32(log.p1b_sent)
                + i32(log.p2a_sent) + i32(log.p2b_sent)) > 0
    violated = i32(log.flags) != 0
    row = jnp.stack([
        i32(log.tick),
        un, un, un, un, un, un, un, un,   # shared-engine-only gauges
        jnp.asarray(log.announce, bool).sum().astype(jnp.int32),
        jnp.asarray(log.decide, bool).sum().astype(jnp.int32),
        i32(log.sent),
        i32(log.delivered),
        i32(log.dropped),
        i32(log.flags),
    ])
    return _push(rec, row, log.tick, announced, decided, fallback, violated)


# --- host-side extraction ------------------------------------------------

def member_recorder(recs: RecorderState, i: int) -> RecorderState:
    """Slice member ``i`` out of a fleet-stacked recorder pytree."""
    return jax.tree_util.tree_map(lambda x: x[i], recs)


def ring_rows(rec: RecorderState) -> np.ndarray:
    """The recorded rows in chronological order, ``[min(count, W), G]``
    (partial fills return only the written prefix; full rings unroll the
    wrap so row 0 is the oldest retained tick)."""
    ring = np.asarray(rec.ring)
    count = int(np.asarray(rec.count))
    w = ring.shape[0]
    if count <= w:
        return ring[:count]
    pos = count % w
    return np.concatenate([ring[pos:], ring[:pos]], axis=0)


def stamps(rec: RecorderState) -> dict:
    """First-occurrence tick stamps as python ints (-1 = never)."""
    return {
        "first_announce": int(np.asarray(rec.first_announce)),
        "first_decide": int(np.asarray(rec.first_decide)),
        "first_fallback": int(np.asarray(rec.first_fallback)),
        "first_violation": int(np.asarray(rec.first_violation)),
    }


def recorder_payload(rec: RecorderState) -> dict:
    """JSON-ready block for one member's recorder (the form embedded in
    ``campaign.triage`` exemplars and validated by
    ``telemetry.schema.FLIGHT_RECORDER_SPEC``)."""
    rows = ring_rows(rec)
    return {
        "window": int(np.asarray(rec.ring).shape[0]),
        "gauges": list(GAUGE_NAMES),
        "ticks_recorded": int(np.asarray(rec.count)),
        "rows": [[int(v) for v in row] for row in rows],
        "stamps": stamps(rec),
    }
