"""On-device protocol invariant monitor for the batched tick engine.

The differential harness (``rapid_tpu.engine.diff``) catches divergence
from the oracle, but only for scenarios the oracle can replay. This
module checks the protocol's *internal* invariants on-device, every tick,
inside the jitted step — so corruption is caught at the tick it happens
even in oracle-free runs (benchmarks, sweeps, future pjit shards):

- **ring_degree** — the K-ring topology is well formed: every member
  row's subjects and observers are members (and not the node itself once
  the view has >= 2 members); every dormant row self-points;
- **report_monotone** — cut-detector report cells only ever fill within
  a configuration; the only thing that clears them is a decided view
  change (``MultiNodeCutDetector`` has no report-retraction path);
- **unique_decide** — at most one decided proposal per configuration
  epoch: the fast round and the classic chain never both claim the same
  tick, a decision always carries a non-empty proposal mask, and a fast
  quorum can only form for a proposal that was actually announced;
- **rank_order** — classic-Paxos rank sanity per slot: an accepted-vote
  rank never exceeds the promised rank (``vrnd <= rnd``), a non-zero
  ``vrnd`` carries a value, and a chosen coordinator value implies a
  started round (mirrors ``oracle/paxos.py``'s Rank ordering);
- **epoch_monotone** — the configuration epoch advances by exactly the
  number of decisions this tick (one), and never regresses;
- **memsum** — the incremental membership-fingerprint sum (limb-added /
  subtracted on view changes) still equals the sum recomputed from the
  member mask, so configuration ids cannot silently drift;
- **ghost_reports** — no ghost cut reports: every report cell that fills
  this tick re-derives from a live alert that was actually in flight (or
  from the edge-invalidation predicate — both its destination and its
  ring observer at/above the low watermark). After a partition heals and
  a view change resets the detector, report state must be rebuilt from
  live traffic; a cell that reappears without a delivering alert is
  exactly the stale-partition ghost this bit flags.

Each check folds to one boolean; ``check_step`` packs them into an
``int32`` bitmask logged per tick in ``StepLog.inv_bits`` and surfaced as
the ``invariant_violations`` telemetry gauge. The monitor is compiled in
only when ``Settings.invariant_checks`` is True (a static jit argument):
with the flag off, the step never calls into this module and its jaxpr is
unchanged — zero overhead.

Host side, ``check_run`` scans a run's stacked logs and escalates the
first violating tick as an ``InvariantViolationError`` — a
``telemetry.forensics.DivergenceError`` whose report names the tick, the
decoded invariant names, and every violating tick as context (optionally
written as a JSONL artifact, ``RAPID_TPU_FORENSICS``-style).
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

from rapid_tpu import hashing
from rapid_tpu.engine.paxos import rank_lt
from rapid_tpu.telemetry.forensics import DivergenceError, DivergenceReport

#: Violation bit registry, in bit order. The bit assignment is part of
#: the telemetry contract (logged bitmasks persist in BENCH artifacts),
#: so bits are append-only: never renumber an existing invariant.
INVARIANT_BITS = (
    ("ring_degree", 0),
    ("report_monotone", 1),
    ("unique_decide", 2),
    ("rank_order", 3),
    ("epoch_monotone", 4),
    ("memsum", 5),
    ("ghost_reports", 6),
)

BIT_OF = {name: bit for name, bit in INVARIANT_BITS}
ALL_BITS = sum(1 << bit for _, bit in INVARIANT_BITS)


def describe_bits(mask: int) -> List[str]:
    """Decode a violation bitmask into invariant names (bit order)."""
    return [name for name, bit in INVARIANT_BITS if (mask >> bit) & 1]


# ---------------------------------------------------------------------------
# per-invariant device checks (each returns a traced boolean scalar)
# ---------------------------------------------------------------------------


def _ring_degree(xp, post) -> object:
    """K-ring well-formedness on the post-tick topology.

    ``build_topology`` guarantees member rows point at member slots (and,
    with >= 2 members, never at themselves — each ring is a single cycle
    over the members) and that dormant rows self-point in both
    directions. Any index escaping those sets means the topology arrays
    were corrupted after the last rebuild.
    """
    c = post.member.shape[0]
    slots = xp.arange(c, dtype=xp.int32)[:, None]
    m_rows = post.member[:, None]
    multi = post.member.sum() >= 2
    bad_member = m_rows & (
        ~post.member[post.subj_idx]
        | ~post.member[post.obs_idx]
        | (multi & ((post.subj_idx == slots) | (post.obs_idx == slots))))
    bad_dormant = ~m_rows & ((post.subj_idx != slots)
                             | (post.obs_idx != slots))
    return (bad_member | bad_dormant).any()


def _rank_order(xp, post) -> object:
    """Classic-Paxos per-slot rank sanity (oracle Rank lexicographic
    order): vrnd <= rnd always, vrnd > 0 carries a value, and a chosen
    coordinator value implies the coordinator started a round."""
    bad = rank_lt(post.px_rnd_r, post.px_rnd_i,
                  post.px_vrnd_r, post.px_vrnd_i)
    bad = bad | ((post.px_vrnd_r > 0) & (post.px_vval < 0))
    bad = bad | ((post.px_cval >= 0) & (post.px_crnd_r <= 0))
    return bad.any()


def _memsum(xp, post) -> object:
    """The incremental member-fingerprint sum must equal the sum
    recomputed from scratch over the member mask (catches member-bit or
    limb-arithmetic corruption that would shift every config id)."""
    m = post.member.astype(xp.uint32)
    hi, lo = hashing.sum64(xp, post.mfp_hi * m, post.mfp_lo * m)
    return (hi != post.memsum_hi) | (lo != post.memsum_lo)


def _ghost_reports(xp, pre, post, settings) -> object:
    """Every newly-filled report cell must be justified by this tick's
    traffic: either its reporter had an alert in flight (monitor pipeline,
    re-indexed like ``cut.deliver_reports``; churn batches justify all of
    their destination's rings), or the edge-invalidation predicate holds —
    the destination *and* the cell's ring observer both sit at/above the
    low watermark on the end-of-tick counts. Cells surviving a view-change
    reset without such a derivation are partition ghosts."""
    added = post.reports & ~pre.reports
    eff_obs = xp.where(post.member[:, None], post.obs_idx, post.gk_idx)
    in_flight = xp.take_along_axis(pre.pending_deliver, eff_obs, axis=0)
    explicit = in_flight | pre.churn_deliver[:, None]
    counts = post.reports.sum(axis=1)
    implicit = (counts >= settings.L)[:, None] & (counts >= settings.L)[eff_obs]
    return (added & ~explicit & ~implicit).any()


def check_step(xp, pre, post, *, decide_now, fast_decide, classic_decide,
               fast_mask, classic_mask, settings=None):
    """All invariant checks for one tick, packed into an i32 bitmask.

    ``pre``/``post`` are the EngineState before and after the tick;
    ``fast_decide``/``classic_decide`` are this tick's decision sources
    with ``fast_mask``/``classic_mask`` their proposal masks (the step
    passes the pre-tick announced proposal and the schedule's classic
    mask). ``settings`` carries the cut watermarks for the ghost-report
    check (``None`` — legacy callers — falls back to the defaults).
    Returns 0 when every invariant holds.
    """
    if settings is None:
        from rapid_tpu.settings import DEFAULT_SETTINGS as settings
    win_mask = xp.where(classic_decide, classic_mask, fast_mask)
    flags = {
        "ring_degree": _ring_degree(xp, post),
        "report_monotone": ~decide_now & (pre.reports
                                          & ~post.reports).any(),
        "unique_decide": ((fast_decide & classic_decide)
                          | (decide_now & ~win_mask.any())
                          | (fast_decide & ~pre.announced)),
        "rank_order": _rank_order(xp, post),
        "epoch_monotone": post.epoch != pre.epoch
        + decide_now.astype(xp.int32),
        "memsum": _memsum(xp, post),
        "ghost_reports": _ghost_reports(xp, pre, post, settings),
    }
    bits = xp.int32(0)
    for name, bit in INVARIANT_BITS:
        bits = bits | (flags[name].astype(xp.int32) << bit)
    return bits


# ---------------------------------------------------------------------------
# host-side escalation
# ---------------------------------------------------------------------------


def expand_violations(logs) -> List[Tuple[int, int, List[str]]]:
    """Nonzero violation rows of a stacked run log, as
    ``(tick, bitmask, [invariant names])`` in tick order."""
    ticks = np.asarray(logs.tick)
    bits = np.asarray(logs.inv_bits)
    out: List[Tuple[int, int, List[str]]] = []
    for i in range(len(bits)):
        b = int(bits[i])
        if b:
            out.append((int(ticks[i]), b, describe_bits(b)))
    return out


class InvariantViolationError(DivergenceError):
    """An on-device invariant check fired; ``report`` names the first
    violating tick and the decoded invariants (still an AssertionError,
    like every forensics escalation)."""

    def __init__(self, report: DivergenceReport,
                 artifact: Optional[str] = None) -> None:
        self.report = report
        self.artifact = artifact
        lines = [f"on-device invariant monitor fired at tick "
                 f"{report.tick}: {report.field} "
                 f"(bitmask {report.engine:#x})"]
        for rec in report.context:
            if rec.get("record") == "invariant_violation":
                lines.append(f"  tick {rec['tick']}: "
                             f"{'+'.join(rec['invariants'])} "
                             f"(bits {rec['bits']:#x})")
        if artifact:
            lines.append(f"forensics artifact: {artifact}")
        AssertionError.__init__(self, "\n".join(lines))


def check_run(logs, metrics: Optional[Sequence] = None,
              artifact: Optional[str] = None,
              context_n: int = 16) -> None:
    """Escalate a run's logged violations; no-op on a clean run.

    Raises ``InvariantViolationError`` naming the first violating tick
    and its invariants, with up to ``context_n`` violating ticks (and,
    when ``metrics`` is given, the trailing ``TickMetrics`` rows before
    the first violation) as report context. ``artifact`` — or the
    ``RAPID_TPU_FORENSICS`` env var — writes the report as JSONL.
    """
    violations = expand_violations(logs)
    if not violations:
        return
    tick, bits, names = violations[0]
    context = []
    if metrics:
        context += [dict(m.as_dict(), record="tick_metrics")
                    for m in metrics if m.tick <= tick][-4:]
    context += [{"record": "invariant_violation", "tick": t, "bits": b,
                 "invariants": ns} for t, b, ns in violations[:context_n]]
    report = DivergenceReport(
        tick=tick, field="invariants." + "+".join(names),
        engine=bits, oracle=0, context=context)
    artifact = artifact or os.environ.get("RAPID_TPU_FORENSICS")
    if artifact:
        report.write_jsonl(artifact)
    raise InvariantViolationError(report, artifact)
