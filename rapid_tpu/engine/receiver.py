"""Per-receiver tick engine: exact link faults on device.

The shared-state step (``engine.step``) collapses all N per-node detector
and consensus copies into one — exact for crash faults, where every alive
receiver observes the identical alert stream, but an approximation under
``LinkWindow`` faults, which split the receiver set. This module runs the
protocol with *every slot carrying its own view* (``state.ReceiverState``)
and an explicit wire — a bounded in-flight delivery ring, ``D`` slots
deep, indexed by arrival tick mod D — evaluating link reachability at
delivery per (sender, receiver) edge inside ``lax.scan`` — the same
semantics ``engine.adversary`` replays sequentially on the host, now as a
single XLA program that ``vmap``s over a fleet axis.

Wire order
----------
The oracle delivers messages in global send order (wseq). Sends at tick
``t-1`` happen in a fixed sequence — 2b during 2a delivery, 2a during 1b
delivery, 1b during 1a delivery, votes during batch delivery (announce),
then ``_run_due``: 1a from timers, batches from batchers — so deliveries
at ``t`` group exactly as ``2b, 2a, 1b, vote, 1a, batch``, which is the
phase order of :func:`receiver_step`. Within a group, arrival order is
recovered from keys stamped at send time: send tick first (delay rules
let messages from different ticks share an arrival tick), then the
announce-order key ``t*(C+1) + ring0 position`` — the oracle's scheduler
handles are creation-ordered, and every racing sender acquired its key
at announce time. Order-dependent triggers (fast-vote quorum crossing,
1a rank prefix-max, 1b majority crossing + value selection,
ascending-rank 2a accept chains) are evaluated as prefix reductions over
that order — exact, not approximate, for the scenarios the differential
suite pins (see ``Envelope`` below).

Delivery ring
-------------
A message sent at tick ``t`` on an edge with delay ``d`` (from
``monitor.delay_matrix``, evaluated at *send* time) lands in ring slot
``(t + 1 + d) % D`` and is read back at tick ``t + 1 + d``; arrival
ticks within the D-deep window map to distinct slots, so the largest
representable extra delay is ``D - 1`` (``D =
Settings.delivery_ring_depth``, budget-checked up front by
``faults.validate_schedule``). Per-edge jitter legally splits one
broadcast across ring slots — the recipient fan is resolved into the
``[D, C, C]`` presence rings at send. ``D = 1`` with no delay rules is
bit-for-bit the old next-tick wire.

Envelope
--------
Supported fault inputs: crash schedules plus arbitrary ``LinkWindow``
sets (one-way/two-way, flip-flop periods) plus ``DelayRule`` sets
(per-edge delay, bounded jitter, asymmetric reverse paths — and the
message reordering they induce). Scripted proposes and churn are *not*
supported — fleet lowering keeps those member kinds on the shared-state
fast path. Deep races outside the committed differential envelope set
sticky ``flags`` bits rather than silently diverging: multiple tracked
2b rounds per listener, more than two same-tick 2a accepts per acceptor,
a proposal fingerprint missing from the announce registry, a slot
exhausting its precomputed fallback-delay draws, two same-kind
messages from one sender jittered onto the same arrival tick (the ring
holds one payload per (slot, sender)), or a cross-phase send-order
inversion — a delayed message (say a jittered fast vote) landing on the
same arrival tick as a *later-sent* message of an earlier-processed
group, where the fixed group order above stops matching oracle wseq
order. Campaign-sampled delays cannot reach that corner: the ring
budget caps them at ``D - 1`` ticks while classic traffic starts no
earlier than ``fallback_base_delay_ticks`` after the votes it could
race.
``diff.run_receiver_differential`` asserts the flags stay zero for every
scenario it verifies.

Memory is quadratic per member by design (``[C, C, K]`` report/topology
tensors): :func:`receiver_state_bytes` sizes it, and fleet lowering
refuses capacities above ``Settings.receiver_capacity_cap`` with a
structured error (see ``engine.fleet.ReceiverBudgetError``).
"""
from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from rapid_tpu import hashing
from rapid_tpu.engine import cut, monitor
from rapid_tpu.engine import recorder as recorder_mod
from rapid_tpu.engine import sharding as sharding_mod
from rapid_tpu.engine.state import (
    I32_MAX, EngineFaults, ReceiverState, ReceiverStepLog, config_id_limbs)
from rapid_tpu.settings import Settings

#: Fallback-delay draws precomputed per slot (one per announce; a slot
#: announcing in more than N_DRAWS configurations overflows -> flag bit).
N_DRAWS = 4

# Sticky envelope / error bits in ``ReceiverState.flags``.
FLAG_DECIDE_NOT_IN_VIEW = 1   # device analogue of AdversaryExecutionError
FLAG_DRAWS_EXHAUSTED = 2
FLAG_MULTI_2A_ACCEPTS = 4     # >2 same-tick ascending-rank accepts
FLAG_MULTI_2B_ROUNDS = 8      # 2b traffic across distinct rounds
FLAG_REGISTRY_MISS = 16       # vote/2a fingerprint not in announce registry
FLAG_RING_COLLISION = 32      # same-kind same-sender same-arrival-tick pair
FLAG_CROSS_PHASE_REORDER = 64  # older send arrived behind a fresher group
FLAG_EPOCH_DELTA_SAT = 128    # packed epoch delta clamped (widen to 16-bit)
FLAG_PACK_NARROW_SAT = 256    # packed narrow int leaf clamped (rx_packed)

_FLAG_NAMES = {
    FLAG_DECIDE_NOT_IN_VIEW: "decide-host-not-in-view",
    FLAG_DRAWS_EXHAUSTED: "fallback-delay-draws-exhausted",
    FLAG_MULTI_2A_ACCEPTS: "more-than-two-same-tick-2a-accepts",
    FLAG_MULTI_2B_ROUNDS: "multiple-2b-rounds-tracked",
    FLAG_REGISTRY_MISS: "proposal-registry-miss",
    FLAG_RING_COLLISION: "delivery-ring-collision",
    FLAG_CROSS_PHASE_REORDER: "cross-phase-send-order-inversion",
    FLAG_EPOCH_DELTA_SAT: "epoch-delta-saturated",
    FLAG_PACK_NARROW_SAT: "packed-narrow-overflow",
}


class ReceiverEnvelopeError(RuntimeError):
    """A per-receiver run tripped a sticky envelope flag: the scenario
    drove the protocol outside the race depth the kernel tracks exactly,
    so its results must not be reported as device-exact."""


def decode_flags(flags) -> List[str]:
    f = int(np.asarray(flags))
    return [name for bit, name in sorted(_FLAG_NAMES.items()) if f & bit]


def check_flags(flags) -> None:
    names = decode_flags(flags)
    if names:
        raise ReceiverEnvelopeError(
            "per-receiver run left the exactness envelope: "
            + ", ".join(names))


def _cfg_eq(a_hi, a_lo, b_hi, b_lo):
    return (a_hi == b_hi) & (a_lo == b_lo)


def _account(xp, msgs, crashed, emat, pallas=False):
    """Delivery mask + (delivered, dropped, link_dropped) counts for one
    message set ``msgs[src, dst]``, with the oracle's drop precedence:
    crashed src first, then crashed dst / link block (``link_dropped``
    only counts blocks whose endpoints are both alive). With ``pallas``
    (static, from ``Settings.rx_kernel``) the loop runs as the packed
    bit-plane kernel in ``engine.rx_pallas`` — ``emat`` is then the
    packed ``[C, ceil(C/8)]`` blocked plane, and the counts/mask are
    bit-identical to this dense program."""
    if pallas:
        from rapid_tpu.engine import rx_pallas
        return rx_pallas.account(msgs, crashed, emat)
    src_ok = ~crashed[:, None]
    dst_ok = ~crashed[None, :]
    deliv = msgs & src_ok & dst_ok & ~emat
    dropped = (msgs & ~deliv).sum().astype(xp.int32)
    linkd = (msgs & src_ok & dst_ok & emat).sum().astype(xp.int32)
    return deliv, deliv.sum().astype(xp.int32), dropped, linkd


def _prefmax_excl(xp, vals):
    """Exclusive running max along the last axis (identity = -1)."""
    inc = lax.cummax(vals, axis=vals.ndim - 1)
    pad = xp.full(vals.shape[:-1] + (1,), -1, vals.dtype)
    return xp.concatenate([pad, inc[..., :-1]], axis=-1)


def _proposal_fp_rows(xp, masks, uid_hi, uid_lo):
    """Row-wise ``votes.proposal_fingerprint``: ``[R, C]`` masks -> two
    ``[R]`` limb arrays (same hash, batched via ``sum64_axis``)."""
    phi, plo = hashing.hash64_limbs(xp, uid_hi, uid_lo, seed=0x70726F70)
    m = masks.astype(xp.uint32)
    shi, slo = hashing.sum64_axis(xp, phi[None, :] * m, plo[None, :] * m)
    return hashing.splitmix64_limbs(xp, shi, slo)


def _registry_lookup(xp, reg_valid, reg_mask, reg_fp_hi, reg_fp_lo,
                     fp_hi, fp_lo, want):
    """Resolve per-receiver fingerprints ``[R]`` to proposal masks
    ``[R, C]`` via the announce registry; ``found`` is False (and the
    mask empty) on a miss."""
    hit = (reg_valid[None, :] & (reg_fp_hi[None, :] == fp_hi[:, None])
           & (reg_fp_lo[None, :] == fp_lo[:, None]))
    found = hit.any(axis=1) & want
    idx = xp.argmax(hit, axis=1)
    mask = reg_mask[idx] & found[:, None]
    return mask, found, (want & ~found).any()


def _pick_min_seq(xp, mask, seqs):
    """Per row: index of the mask element with the smallest seq key."""
    keyed = xp.where(mask, seqs, I32_MAX)
    return xp.argmin(keyed, axis=1), mask.any(axis=1)


def _arrival_perm(xp, present, ticks, seqs):
    """Sender permutation recovering oracle wseq order for one ring slot:
    ascending send tick first (delayed links let sends from different
    ticks share an arrival tick), then the stamped within-tick key;
    absent senders sort last. Both argsorts are stable, so with a single
    send tick in the slot (always true at D = 1) this degenerates to the
    plain within-tick key sort."""
    p1 = xp.argsort(xp.where(present, seqs, I32_MAX))
    k2 = xp.where(present, ticks, I32_MAX)[p1]
    return p1[xp.argsort(k2, stable=True)]


class _Vars:
    """Mutable working copy of the per-tick state (threaded through the
    step's delivery groups; ``finalize`` rebuilds the NamedTuple)."""

    def __init__(self, rs: ReceiverState):
        for name in ReceiverState._fields:
            setattr(self, name, getattr(rs, name))


def _apply_decides(xp, v: _Vars, t, dm, hosts):
    """Apply a wave of view-change decides: remove ``hosts[r]`` from
    ``r``'s view where ``dm[r]``, recompute cfg, reset per-config state
    (the oracle's ``_decide_view_change``). The alert queue (``pf``) is
    deliberately *not* reset — its stale contents flush next tick with
    old cfg stamps to the new recipient set (dead traffic the oracle
    reproduces). Returns the post-decide cfg limbs for the event log."""
    c = v.member.shape[1]
    bad = dm & (hosts & ~v.member).any(axis=1)
    v.flags = v.flags | xp.where(bad.any(), FLAG_DECIDE_NOT_IN_VIEW, 0)
    hosts = hosts & v.member & dm[:, None]

    hm = hosts.astype(xp.uint32)
    rem_hi, rem_lo = hashing.sum64_axis(
        xp, v.mfp_hi[None, :] * hm, v.mfp_lo[None, :] * hm)
    ms_hi, ms_lo = hashing.sub64(xp, v.memsum_hi, v.memsum_lo,
                                 rem_hi, rem_lo)
    v.memsum_hi = xp.where(dm, ms_hi, v.memsum_hi)
    v.memsum_lo = xp.where(dm, ms_lo, v.memsum_lo)
    cfg2_hi, cfg2_lo = config_id_limbs(
        xp, v.idsum_hi, v.idsum_lo, v.memsum_hi, v.memsum_lo)
    v.cfg_hi = xp.where(dm, cfg2_hi, v.cfg_hi)
    v.cfg_lo = xp.where(dm, cfg2_lo, v.cfg_lo)

    v.member = v.member & ~hosts
    v.epoch = v.epoch + dm.astype(xp.int32)
    ridx = xp.arange(c, dtype=xp.int32)
    self_in = v.member[ridx, ridx]
    v.stopped = v.stopped | (dm & ~self_in)
    v.px_n = xp.where(dm, v.member.sum(axis=1).astype(xp.int32), v.px_n)

    z1, z2, z3 = dm, dm[:, None], dm[:, None, None]
    v.reports = v.reports & ~z3
    v.seen_down = v.seen_down & ~z1
    v.announced = v.announced & ~z1
    v.ar_seq = xp.where(z1, I32_MAX, v.ar_seq)
    v.fc = xp.where(z2, 0, v.fc)
    v.notified = v.notified & ~z2
    v.fd_gate = xp.where(z1, t, v.fd_gate)
    v.vt_seen = v.vt_seen & ~z2
    zero_i = xp.zeros_like(v.px_rnd_r)
    v.px_rnd_r = xp.where(z1, zero_i, v.px_rnd_r)
    v.px_rnd_i = xp.where(z1, zero_i, v.px_rnd_i)
    v.px_vrnd_r = xp.where(z1, zero_i, v.px_vrnd_r)
    v.px_vrnd_i = xp.where(z1, zero_i, v.px_vrnd_i)
    v.px_vv_set = v.px_vv_set & ~z1
    v.px_crnd_r = xp.where(z1, zero_i, v.px_crnd_r)
    v.px_cval_set = v.px_cval_set & ~z1
    v.px_timer = xp.where(z1, I32_MAX, v.px_timer)
    v.pb_seen = v.pb_seen & ~z2
    v.p2_rnd = xp.where(z1, -1, v.p2_rnd)
    v.p2_seen = v.p2_seen & ~z2
    return v.cfg_hi, v.cfg_lo


def receiver_step(rs: ReceiverState, faults: EngineFaults,
                  settings: Settings
                  ) -> Tuple[ReceiverState, ReceiverStepLog]:
    """One tick of the per-receiver engine (see module docstring for the
    delivery-group order and its wseq-equivalence argument)."""
    xp = jnp
    v = _Vars(rs)
    t = rs.tick + 1
    c = rs.member.shape[0]
    ridx = xp.arange(c, dtype=xp.int32)
    jidx = ridx
    crashed = monitor.crashed_at(faults, t)
    # Static kernel select: the pallas path never materializes the dense
    # [C, C] reachability plane — deliveries consume the packed bit-plane
    # and FD probes evaluate their edges lazily (group 10).
    pallas_rx = settings.rx_kernel == "pallas"
    if pallas_rx:
        emat = monitor.link_blocked_packed(xp, faults, t)
    else:
        emat = monitor.link_blocked_matrix(xp, faults, t)
    D = settings.delivery_ring_depth
    am = t % D                  # ring slot arriving this tick
    i32 = lambda x: xp.int32(x)
    pop = lambda m: m.sum(axis=1).astype(xp.int32)   # popcount of mask rows

    sent = i32(0)
    delivered = i32(0)
    dropped = i32(0)
    link_dropped = i32(0)
    phase_sent = {p: i32(0) for p in ("fv", "p1a", "p1b", "p2a", "p2b")}
    phase_del = {p: i32(0) for p in ("fv", "p1a", "p1b", "p2a", "p2b")}

    dec_mask = xp.zeros((c,), bool)
    dec_hosts = xp.zeros((c, c), bool)
    dec_cfg_hi = xp.zeros((c,), xp.uint32)
    dec_cfg_lo = xp.zeros((c,), xp.uint32)

    def deliver(msgs, phase=None):
        nonlocal delivered, dropped, link_dropped
        dv, dn, dr, ld = _account(xp, msgs, crashed, emat,
                                  pallas=pallas_rx)
        delivered += dn
        dropped += dr
        link_dropped += ld
        if phase is not None:
            phase_del[phase] = phase_del[phase] + dn
        return dv

    def record_decides(dm, hosts, cfg_hi, cfg_lo):
        nonlocal dec_mask, dec_hosts, dec_cfg_hi, dec_cfg_lo
        dec_mask = dec_mask | dm
        dec_hosts = xp.where(dm[:, None], hosts, dec_hosts)
        dec_cfg_hi = xp.where(dm, cfg_hi, dec_cfg_hi)
        dec_cfg_lo = xp.where(dm, cfg_lo, dec_cfg_lo)

    # ---- group 1: phase-2b delivery -> decide wave A --------------------
    w2b_ring = rs.w2b[am]
    w2b_rnd_r = rs.w2b_rnd[am]
    w2b_mask_r = rs.w2b_mask[am]
    w2b_cfg_hi_r, w2b_cfg_lo_r = rs.w2b_cfg_hi[am], rs.w2b_cfg_lo[am]
    gates = []
    for slot in (0, 1):
        msgs = w2b_ring[slot]
        dv = deliver(msgs, "p2b")
        arr = dv.T
        gates.append(arr & ~v.stopped[:, None]
                     & _cfg_eq(w2b_cfg_hi_r[None, :], w2b_cfg_lo_r[None, :],
                               v.cfg_hi[:, None], v.cfg_lo[:, None]))
    g2b_any = (gates[0] | gates[1]).any(axis=1)
    rnd0 = xp.where(gates[0], w2b_rnd_r[0][None, :], -1)
    rnd1 = xp.where(gates[1], w2b_rnd_r[1][None, :], -1)
    mx_in = xp.maximum(rnd0.max(axis=1), rnd1.max(axis=1))
    mx = xp.maximum(v.p2_rnd, mx_in)
    reset = mx > v.p2_rnd
    use0 = gates[0] & (w2b_rnd_r[0][None, :] == mx[:, None])
    use1 = gates[1] & (w2b_rnd_r[1][None, :] == mx[:, None])
    low_seen = ((gates[0] & ~use0).any() | (gates[1] & ~use1).any()
                | (reset & (v.p2_rnd >= 0) & v.p2_seen.any(axis=1)).any())
    v.flags = v.flags | xp.where(low_seen, FLAG_MULTI_2B_ROUNDS, 0)
    add = use0 | use1
    seen_base = v.p2_seen & ~reset[:, None]
    v.p2_seen = seen_base | add
    a_star = xp.argmax(add, axis=1)
    pick0 = use0[ridx, a_star]
    gathered = xp.where(pick0[:, None], w2b_mask_r[0][a_star],
                        w2b_mask_r[1][a_star])
    refresh = reset & add.any(axis=1)
    v.p2_mask = xp.where(refresh[:, None], gathered, v.p2_mask)
    v.p2_rnd = mx
    dec_a = (v.p2_seen.sum(axis=1) > v.px_n // 2) & add.any(axis=1)
    hosts_a = v.p2_mask & dec_a[:, None]

    # ---- group 2: apply decide wave A -----------------------------------
    ncfg_hi, ncfg_lo = _apply_decides(xp, v, t, dec_a, hosts_a)
    record_decides(dec_a, hosts_a, ncfg_hi, ncfg_lo)

    # ---- group 3: phase-2a delivery -> accept chain -> 2b emission ------
    w2a_ring = rs.w2a[am]
    w2a_fp_hi_r, w2a_fp_lo_r = rs.w2a_fp_hi[am], rs.w2a_fp_lo[am]
    w2a_mask_arr = rs.w2a_mask[am]
    msgs = w2a_ring
    dv = deliver(msgs, "p2a")
    arr = dv.T
    gate = (arr & ~v.stopped[:, None]
            & _cfg_eq(rs.w2a_cfg_hi[am][None, :], rs.w2a_cfg_lo[am][None, :],
                      v.cfg_hi[:, None], v.cfg_lo[:, None]))
    send2a_min = xp.where(gate, rs.w2a_tick[am][None, :], I32_MAX).min(axis=1)
    send2a_max = xp.where(gate, rs.w2a_tick[am][None, :], -1).max(axis=1)
    perm3 = _arrival_perm(xp, w2a_ring.any(axis=1),
                          rs.w2a_tick[am], rs.w2a_seq[am])
    gate_s = gate[:, perm3]
    rank_j = rs.rank_idx[perm3]
    ge0 = ((v.px_rnd_r[:, None] < 2)
           | ((v.px_rnd_r[:, None] == 2)
              & (v.px_rnd_i[:, None] <= rank_j[None, :])))
    ne0 = ~((v.px_vrnd_r[:, None] == 2)
            & (v.px_vrnd_i[:, None] == rank_j[None, :]))
    arrived = xp.where(gate_s, rank_j[None, :], -1)
    accept = gate_s & ge0 & ne0 & (rank_j[None, :] > _prefmax_excl(xp, arrived))
    n_acc = accept.sum(axis=1).astype(xp.int32)
    v.flags = v.flags | xp.where((n_acc > 2).any(), FLAG_MULTI_2A_ACCEPTS, 0)
    j1 = xp.argmax(accept, axis=1)
    j2 = xp.argmax(accept & (jidx[None, :] > j1[:, None]), axis=1)
    jl = c - 1 - xp.argmax(accept[:, ::-1], axis=1)
    c1, c2, cl = perm3[j1], perm3[j2], perm3[jl]
    emit0 = n_acc >= 1
    emit1 = n_acc >= 2
    w2b_rnd_new = xp.stack([rs.rank_idx[c1], rs.rank_idx[c2]])
    w2b_fp_hi_new = xp.stack([w2a_fp_hi_r[c1], w2a_fp_hi_r[c2]])
    w2b_fp_lo_new = xp.stack([w2a_fp_lo_r[c1], w2a_fp_lo_r[c2]])
    w2b_mask_new = xp.stack([w2a_mask_arr[c1], w2a_mask_arr[c2]])
    w2b_cfg_hi_new, w2b_cfg_lo_new = v.cfg_hi, v.cfg_lo
    # Recipient snapshot captured here: wave-B decides below must not
    # retroactively shrink this tick's fan (oracle sends 2b during 2a
    # delivery, before votes are processed).
    w2b_fan = xp.stack([emit0[:, None] & v.member, emit1[:, None] & v.member])
    n_2b = (emit0 * pop(v.member) + emit1 * pop(v.member)).sum().astype(
        xp.int32)
    phase_sent["p2b"] += n_2b
    sent += n_2b
    rank_last = rs.rank_idx[cl]
    v.px_rnd_r = xp.where(emit0, 2, v.px_rnd_r)
    v.px_rnd_i = xp.where(emit0, rank_last, v.px_rnd_i)
    v.px_vrnd_r = xp.where(emit0, 2, v.px_vrnd_r)
    v.px_vrnd_i = xp.where(emit0, rank_last, v.px_vrnd_i)
    v.px_vv_fp_hi = xp.where(emit0, w2a_fp_hi_r[cl], v.px_vv_fp_hi)
    v.px_vv_fp_lo = xp.where(emit0, w2a_fp_lo_r[cl], v.px_vv_fp_lo)
    v.px_vv_set = v.px_vv_set | emit0

    # ---- group 4: phase-1b delivery -> crossing + selection -> 2a -------
    w1b_ring = rs.w1b[am]
    w1b_set_r = rs.w1b_set[am]
    msgs = w1b_ring
    dv = deliver(msgs, "p1b")
    arr = dv.T                                   # [coordinator, promiser]
    gate = (arr & ~v.stopped[:, None] & (v.px_crnd_r[:, None] == 2)
            & _cfg_eq(rs.w1b_cfg_hi[am][None, :], rs.w1b_cfg_lo[am][None, :],
                      v.cfg_hi[:, None], v.cfg_lo[:, None]))
    new = gate & ~v.pb_seen
    seq_in = rs.w1b_seq[am]      # send key: tick*(C+1) + promiser rx_pos
    t1b = seq_in // (c + 1)
    send1b_min = xp.where(new, t1b[None, :], I32_MAX).min(axis=1)
    send1b_max = xp.where(new, t1b[None, :], -1).max(axis=1)
    v.pb_seen = v.pb_seen | new
    v.pb_vrnd_r = xp.where(new, rs.w1b_vrnd_r[am][None, :], v.pb_vrnd_r)
    v.pb_vrnd_i = xp.where(new, rs.w1b_vrnd_i[am][None, :], v.pb_vrnd_i)
    v.pb_fp_hi = xp.where(new, rs.w1b_fp_hi[am][None, :], v.pb_fp_hi)
    v.pb_fp_lo = xp.where(new, rs.w1b_fp_lo[am][None, :], v.pb_fp_lo)
    v.pb_set = xp.where(new, w1b_set_r[None, :], v.pb_set)
    v.pb_seq = xp.where(new, seq_in[None, :], v.pb_seq)

    prior = v.pb_seen & ~new
    prior_tot = prior.sum(axis=1).astype(xp.int32)
    prior_ne = (prior & v.pb_set).sum(axis=1).astype(xp.int32)
    perm2 = xp.argsort(xp.where(w1b_ring.any(axis=1), seq_in, I32_MAX))
    new_s = new[:, perm2]
    ne_new_s = new_s & w1b_set_r[perm2][None, :]
    cum_tot = prior_tot[:, None] + xp.cumsum(new_s, axis=1)
    cum_ne = prior_ne[:, None] + xp.cumsum(ne_new_s, axis=1)
    thr = v.px_n // 2 + 1
    elig = new_s & (cum_tot >= thr[:, None]) & (cum_ne >= 1)
    cross = elig.any(axis=1) & ~v.px_cval_set
    jstar = xp.argmax(elig, axis=1)
    sstar = seq_in[perm2[jstar]]
    prefix = v.pb_seen & (v.pb_seq <= sstar[:, None])

    vr = xp.where(prefix, v.pb_vrnd_r, -1)
    mr = vr.max(axis=1)
    vi = xp.where(prefix & (v.pb_vrnd_r == mr[:, None]), v.pb_vrnd_i, -1)
    mi = vi.max(axis=1)
    maxmask = prefix & (v.pb_vrnd_r == mr[:, None]) & (v.pb_vrnd_i == mi[:, None])
    collected = maxmask & v.pb_set
    ncoll = collected.sum(axis=1).astype(xp.int32)
    if settings.rx_kernel != "xla":
        # Same pairwise-fingerprint math, evaluated one receiver row at
        # a time (lax.map) so no [C, C, C] temp is ever materialized —
        # bool/int ops only, so the row-chunked reduction is bit-exact.
        # XLA fuses the dense form into a cubic int32 buffer (283 GiB
        # at C=4096), which is what walls dense campaigns at ~1k slots.
        def _pb_occ_row(args):
            fp_hi, fp_lo, coll, seq = args
            eq = ((fp_hi[:, None] == fp_hi[None, :])
                  & (fp_lo[:, None] == fp_lo[None, :]))
            uneq = (coll[:, None] & coll[None, :] & ~eq).any()
            occ_r = (coll[None, :] & eq
                     & (seq[None, :] < seq[:, None])).sum(
                         axis=1).astype(xp.int32)
            return uneq, occ_r

        pair_uneq, occ = lax.map(
            _pb_occ_row, (v.pb_fp_hi, v.pb_fp_lo, collected, v.pb_seq))
        single = (ncoll >= 1) & ~pair_uneq
    else:
        eqf = ((v.pb_fp_hi[:, :, None] == v.pb_fp_hi[:, None, :])
               & (v.pb_fp_lo[:, :, None] == v.pb_fp_lo[:, None, :]))
        pair_uneq = (collected[:, :, None] & collected[:, None, :]
                     & ~eqf).any(axis=(1, 2))
        single = (ncoll >= 1) & ~pair_uneq
        earlier = v.pb_seq[:, None, :] < v.pb_seq[:, :, None]
        occ = (collected[:, None, :] & eqf & earlier).sum(
            axis=2).astype(xp.int32)
    cand = collected & pair_uneq[:, None] & (occ == (v.px_n // 4)[:, None])
    d_single, _ = _pick_min_seq(xp, collected, v.pb_seq)
    d_cand, has_cand = _pick_min_seq(xp, cand, v.pb_seq)
    d_fall, _ = _pick_min_seq(xp, prefix & v.pb_set, v.pb_seq)
    d_star = xp.where(single, d_single, xp.where(has_cand, d_cand, d_fall))
    chosen_fp_hi = v.pb_fp_hi[ridx, d_star]
    chosen_fp_lo = v.pb_fp_lo[ridx, d_star]
    res_mask, _, miss = _registry_lookup(
        xp, v.reg_valid, v.reg_mask, v.reg_fp_hi, v.reg_fp_lo,
        chosen_fp_hi, chosen_fp_lo, cross)
    v.flags = v.flags | xp.where(miss, FLAG_REGISTRY_MISS, 0)
    w2a_fp_hi_new = xp.where(cross, chosen_fp_hi, 0).astype(xp.uint32)
    w2a_fp_lo_new = xp.where(cross, chosen_fp_lo, 0).astype(xp.uint32)
    w2a_mask_new = res_mask
    w2a_cfg_hi_new, w2a_cfg_lo_new = v.cfg_hi, v.cfg_lo
    w2a_seq_new = v.ar_seq
    # Snapshot before wave-B decides can shrink the view (oracle sends 2a
    # during 1b delivery, ahead of this tick's votes).
    w2a_fan = cross[:, None] & v.member
    v.px_cval_set = v.px_cval_set | cross
    n_2a = (cross * pop(v.member)).sum().astype(xp.int32)
    phase_sent["p2a"] += n_2a
    sent += n_2a

    # ---- group 5: fast-vote delivery -> decide wave B -------------------
    # Vote seq keys are announce keys, and a vote is sent at its announce
    # tick, so the single stamped sort is already send-tick-major.
    wv_ring = rs.wv[am]
    wv_fp_hi_r, wv_fp_lo_r = rs.wv_fp_hi[am], rs.wv_fp_lo[am]
    msgs = wv_ring
    dv = deliver(msgs, "fv")
    arr = dv.T
    gate = (arr & ~v.stopped[:, None]
            & _cfg_eq(rs.wv_cfg_hi[am][None, :], rs.wv_cfg_lo[am][None, :],
                      v.cfg_hi[:, None], v.cfg_lo[:, None]))
    process = gate & ~v.vt_seen
    # A vote's send tick is its announce tick (votes broadcast at announce).
    tv = rs.wv_seq[am] // (c + 1)
    sendv_min = xp.where(process, tv[None, :], I32_MAX).min(axis=1)
    sendv_max = xp.where(process, tv[None, :], -1).max(axis=1)
    perm_v = xp.argsort(xp.where(wv_ring.any(axis=1), rs.wv_seq[am], I32_MAX))
    proc_s = process[:, perm_v]
    # Baseline: stored votes equal to each arriving fingerprint.
    if settings.rx_kernel != "xla":
        # Row-chunked (lax.map) form of the stored-vote fingerprint
        # match: the dense einsum-shaped broadcast below builds a
        # [C, C, C] bool temp that XLA keeps live as int32 — the other
        # half of the cubic memory wall. Equality + masked sum per row
        # is bit-exact regardless of chunking.
        wv_hi_p = wv_fp_hi_r[perm_v]
        wv_lo_p = wv_fp_lo_r[perm_v]

        def _vt_baseline_row(args):
            th, tl, seen = args
            eq = ((th[:, None] == wv_hi_p[None, :])
                  & (tl[:, None] == wv_lo_p[None, :]))
            return (seen[:, None] & eq).sum(axis=0).astype(xp.int32)

        baseline = lax.map(
            _vt_baseline_row, (v.vt_fp_hi, v.vt_fp_lo, v.vt_seen))
    else:
        fp_eq_stored = ((v.vt_fp_hi[:, :, None]
                         == wv_fp_hi_r[perm_v][None, None, :])
                        & (v.vt_fp_lo[:, :, None]
                           == wv_fp_lo_r[perm_v][None, None, :]))
        baseline = (v.vt_seen[:, :, None] & fp_eq_stored).sum(axis=1).astype(
            xp.int32)
    prior_tot = v.vt_seen.sum(axis=1).astype(xp.int32)
    # Arrival-prefix counts of equal fingerprints, in announce order.
    fp_eq_wire = ((wv_fp_hi_r[perm_v][:, None] == wv_fp_hi_r[perm_v][None, :])
                  & (wv_fp_lo_r[perm_v][:, None]
                     == wv_fp_lo_r[perm_v][None, :]))
    lower_tri = jidx[None, :] <= jidx[:, None]          # [j, j2]: j2 <= j
    prefix_cnt = xp.einsum('rj,kj->rk', proc_s.astype(xp.int32),
                           (fp_eq_wire & lower_tri).astype(xp.int32))
    count_after = baseline + prefix_cnt
    total_after = prior_tot[:, None] + xp.cumsum(proc_s, axis=1)
    quorum = v.px_n - (v.px_n - 1) // 4
    trig = (proc_s & (count_after >= quorum[:, None])
            & (total_after >= quorum[:, None]))
    dec_b = trig.any(axis=1)
    win_j = xp.argmax(trig, axis=1)
    win_fp_hi = wv_fp_hi_r[perm_v[win_j]]
    win_fp_lo = wv_fp_lo_r[perm_v[win_j]]
    hosts_b, _, miss = _registry_lookup(
        xp, v.reg_valid, v.reg_mask, v.reg_fp_hi, v.reg_fp_lo,
        win_fp_hi, win_fp_lo, dec_b)
    v.flags = v.flags | xp.where(miss, FLAG_REGISTRY_MISS, 0)
    v.vt_seen = v.vt_seen | process
    v.vt_fp_hi = xp.where(process, wv_fp_hi_r[None, :], v.vt_fp_hi)
    v.vt_fp_lo = xp.where(process, wv_fp_lo_r[None, :], v.vt_fp_lo)

    # ---- group 6: apply decide wave B -----------------------------------
    ncfg_hi, ncfg_lo = _apply_decides(xp, v, t, dec_b, hosts_b)
    record_decides(dec_b, hosts_b, ncfg_hi, ncfg_lo)

    # ---- group 7: phase-1a delivery -> promises -> 1b emission ----------
    w1a_ring = rs.w1a[am]
    msgs = w1a_ring
    dv = deliver(msgs, "p1a")
    arr = dv.T                                   # [promiser, coordinator]
    gate = (arr & ~v.stopped[:, None]
            & _cfg_eq(rs.w1a_cfg_hi[am][None, :], rs.w1a_cfg_lo[am][None, :],
                      v.cfg_hi[:, None], v.cfg_lo[:, None]))
    send1a_min = xp.where(gate, rs.w1a_tick[am][None, :], I32_MAX).min(axis=1)
    perm1 = _arrival_perm(xp, w1a_ring.any(axis=1),
                          rs.w1a_tick[am], rs.w1a_seq[am])
    gate_s = gate[:, perm1]
    rank_j = rs.rank_idx[perm1]
    above_cur = ((v.px_rnd_r[:, None] < 2)
                 | ((v.px_rnd_r[:, None] == 2)
                    & (v.px_rnd_i[:, None] < rank_j[None, :])))
    arrived = xp.where(gate_s, rank_j[None, :], -1)
    promise_s = gate_s & above_cur & (rank_j[None, :]
                                      > _prefmax_excl(xp, arrived))
    pr_any = promise_s.any(axis=1)
    max_promised = xp.where(promise_s, rank_j[None, :], -1).max(axis=1)
    v.px_rnd_r = xp.where(pr_any, 2, v.px_rnd_r)
    v.px_rnd_i = xp.where(pr_any, max_promised, v.px_rnd_i)
    inv1 = xp.zeros_like(perm1).at[perm1].set(jidx)
    promise = promise_s[:, inv1]                 # back to slot coordinates
    w1b_new = promise
    w1b_vrnd_r_new, w1b_vrnd_i_new = v.px_vrnd_r, v.px_vrnd_i
    w1b_fp_hi_new, w1b_fp_lo_new = v.px_vv_fp_hi, v.px_vv_fp_lo
    w1b_set_new = v.px_vv_set
    w1b_cfg_hi_new, w1b_cfg_lo_new = v.cfg_hi, v.cfg_lo
    n_1b = promise.sum().astype(xp.int32)
    phase_sent["p1b"] += n_1b
    sent += n_1b

    # ---- cross-phase send-order guard -----------------------------------
    # The fixed group order above equals oracle wseq order only while all
    # of a tick's processed arrivals left the wire on the same tick. A
    # delay rule can land an older send on the same arrival tick as a
    # fresher message of an earlier-processed group — the oracle delivers
    # the older send first, this kernel cannot, so the inversion sets a
    # sticky flag instead of silently diverging. 2b payloads carry no
    # send stamp: a gated 2b arrival counts as sent at t-1, the
    # conservative maximum.
    run_max = xp.where(g2b_any, t - 1, -1)
    inv = send2a_min < run_max
    run_max = xp.maximum(run_max, send2a_max)
    inv |= send1b_min < run_max
    run_max = xp.maximum(run_max, send1b_max)
    inv |= sendv_min < run_max
    run_max = xp.maximum(run_max, sendv_max)
    inv |= send1a_min < run_max
    v.flags = v.flags | xp.where(inv.any(), FLAG_CROSS_PHASE_REORDER, 0)

    # ---- group 8: batch delivery -> cut aggregation -> announce ---------
    pd_ring = rs.pd[am]
    msgs = pd_ring.any(axis=1)[:, None] & rs.pd_bcast[am]
    dv = deliver(msgs)
    recv = (dv.T & ~v.stopped[:, None] & ~v.announced[:, None]
            & _cfg_eq(rs.pd_cfg_hi[am][None, :], rs.pd_cfg_lo[am][None, :],
                      v.cfg_hi[:, None], v.cfg_lo[:, None]))
    onehot = (pd_ring[:, :, None]
              & (rs.pd_dst[am][:, :, None] == ridx[None, None, :]))
    down = xp.einsum('rs,skd->rdk', recv.astype(xp.int32),
                     onehot.astype(xp.int32)) > 0
    gate8 = ~v.announced & ~v.stopped
    (v.reports, v.seen_down, any_new, in_flux, crossed) = cut.receiver_aggregate(
        xp, v.reports, v.member, v.obs_full, down, gate8, v.seen_down,
        settings)
    announce = (any_new & ~in_flux & crossed.any(axis=1)
                & ~v.announced & ~v.stopped)
    prop_fp_hi, prop_fp_lo = _proposal_fp_rows(xp, crossed, v.uid_hi, v.uid_lo)
    v.announced = v.announced | announce
    new_seq = t * (c + 1) + v.rx_pos
    v.ar_seq = xp.where(announce, new_seq, v.ar_seq)
    v.reg_valid = v.reg_valid | announce
    v.reg_mask = xp.where(announce[:, None], crossed, v.reg_mask)
    v.reg_fp_hi = xp.where(announce, prop_fp_hi, v.reg_fp_hi)
    v.reg_fp_lo = xp.where(announce, prop_fp_lo, v.reg_fp_lo)
    wv_fp_hi_new = xp.where(announce, prop_fp_hi, 0).astype(xp.uint32)
    wv_fp_lo_new = xp.where(announce, prop_fp_lo, 0).astype(xp.uint32)
    wv_cfg_hi_new, wv_cfg_lo_new = v.cfg_hi, v.cfg_lo
    wv_seq_new = v.ar_seq
    wv_fan = announce[:, None] & v.member
    n_fv = (announce * pop(v.member)).sum().astype(xp.int32)
    phase_sent["fv"] += n_fv
    sent += n_fv
    # Seed the fast round unless classic activity already raised the rnd
    # (the oracle's ``if not px.rnd[0] > 1`` guard in ``_propose``).
    seed_px = announce & (v.px_rnd_r <= 1)
    one = xp.ones_like(v.px_rnd_r)
    v.px_rnd_r = xp.where(seed_px, one, v.px_rnd_r)
    v.px_rnd_i = xp.where(seed_px, one, v.px_rnd_i)
    v.px_vrnd_r = xp.where(seed_px, one, v.px_vrnd_r)
    v.px_vrnd_i = xp.where(seed_px, one, v.px_vrnd_i)
    v.px_vv_fp_hi = xp.where(seed_px, prop_fp_hi, v.px_vv_fp_hi)
    v.px_vv_fp_lo = xp.where(seed_px, prop_fp_lo, v.px_vv_fp_lo)
    v.px_vv_set = v.px_vv_set | seed_px
    # Arm the recovery timer with the slot's next precomputed delay draw.
    d_idx = xp.clip(v.draws, 0, N_DRAWS - 1)
    m_idx = xp.clip(v.px_n, 0, c)
    delay = v.delay_table[ridx, d_idx, m_idx]
    v.flags = v.flags | xp.where((announce & (v.draws >= N_DRAWS)).any(),
                                 FLAG_DRAWS_EXHAUSTED, 0)
    v.px_timer = xp.where(announce, t + delay, v.px_timer)
    v.draws = v.draws + announce.astype(xp.int32)
    ann_cfg_hi, ann_cfg_lo = v.cfg_hi, v.cfg_lo
    ann_prop = crossed & announce[:, None]

    # ---- group 9: recovery timers fire -> 1a emission -------------------
    fire = v.px_timer == t
    v.px_crnd_r = xp.where(fire, 2, v.px_crnd_r)
    v.px_timer = xp.where(fire, I32_MAX, v.px_timer)
    w1a_cfg_hi_new, w1a_cfg_lo_new = v.cfg_hi, v.cfg_lo
    w1a_seq_new = v.ar_seq
    w1a_fan = fire[:, None] & v.member
    n_1a = (fire * pop(v.member)).sum().astype(xp.int32)
    phase_sent["p1a"] += n_1a
    sent += n_1a

    # ---- group 10: failure detectors ------------------------------------
    is_fd = ((t % settings.fd_interval_ticks == 0) & (t > v.fd_gate)
             & ~v.stopped)
    at_thr = v.fc >= settings.fd_failure_threshold
    probing = v.own_fd_active & ~at_thr & is_fd[:, None]
    subj = v.own_subj
    if pallas_rx:
        # Lazy per-edge reachability (monitor.link_blocked): W masked
        # gathers over the [C, K] probe edges, never a [C, C] plane.
        probe_fail = (crashed[subj] | crashed[:, None]
                      | monitor.link_blocked(
                          xp, faults,
                          xp.broadcast_to(ridx[:, None], subj.shape),
                          subj, t))
    else:
        probe_fail = (crashed[subj] | crashed[:, None]
                      | emat[ridx[:, None], subj])
    probes_sent = probing.sum().astype(xp.int32)
    probes_failed = (probing & probe_fail).sum().astype(xp.int32)
    v.fc = xp.where(probing & probe_fail, v.fc + 1, v.fc)
    notify_now = v.own_fd_active & at_thr & ~v.notified & is_fd[:, None]
    v.notified = v.notified | notify_now
    pf_new = xp.take_along_axis(notify_now, v.own_fd_first, axis=1)

    # ---- group 11: batcher flush (last tick's queue -> the wire) --------
    flush = rs.pf.any(axis=1) & ~v.stopped
    pd_new = rs.pf & flush[:, None]
    pd_dst_new = rs.pf_dst
    pd_cfg_hi_new, pd_cfg_lo_new = rs.pf_cfg_hi, rs.pf_cfg_lo
    pd_fan = flush[:, None] & v.member
    sent += (flush * pop(v.member)).sum().astype(xp.int32)
    v.pf = pf_new
    v.pf_dst = v.own_subj
    v.pf_cfg_hi, v.pf_cfg_lo = v.cfg_hi, v.cfg_lo

    # ---- group 12: topology rebuild after decides -----------------------
    from rapid_tpu.engine.paxos import ring0_positions
    from rapid_tpu.engine.topology import build_topology

    def _rebuild(member):
        topo = jax.vmap(
            lambda m: build_topology(xp, m, rs.ring_order, rs.ring_rank))(
                member)
        subj_all, obs_all, _gk, fda_all, fdf_all = topo
        pos_all = jax.vmap(
            lambda m: ring0_positions(xp, m, rs.ring_order, rs.ring_rank))(
                member)
        return (obs_all, subj_all[ridx, ridx], fda_all[ridx, ridx],
                fdf_all[ridx, ridx], pos_all[ridx, ridx])

    def _keep(_member):
        return (v.obs_full, v.own_subj, v.own_fd_active, v.own_fd_first,
                v.rx_pos)

    (v.obs_full, v.own_subj, v.own_fd_active, v.own_fd_first,
     v.rx_pos) = lax.cond(dec_mask.any(), _rebuild, _keep, v.member)

    # ---- finalize: rotate the delivery ring ------------------------------
    # Messages sent this tick land in ring slot (t + 1 + delay) % D, the
    # per-edge delay evaluated at *send* time (latency is a property of
    # the wire a message entered; the crash/window masks above applied at
    # delivery). Slot ``am`` was consumed this tick, so it is cleared
    # before inserts — a max-delay send (D - 1 ticks extra) legally
    # re-fills it for tick t + D. In-flight arrival ticks map to distinct
    # slots, so a (slot, sender) overlap means two same-kind messages
    # jittered onto one arrival tick — more than the per-sender payload
    # lanes can hold: flagged sticky rather than silently merged.
    dmat = monitor.delay_matrix(xp, faults, t)
    darange = xp.arange(D, dtype=xp.int32)
    keep = (darange != am)[:, None, None]
    slot_hit = ((t + 1 + dmat) % D)[None, :, :] == darange[:, None, None]
    coll = xp.zeros((), bool)

    def ring_put(ring, fan):
        cleared = ring & keep
        ins = slot_hit & fan[None]
        hit_old = (cleared.any(axis=-1) & ins.any(axis=-1)).any()
        return cleared | ins, ins.any(axis=-1), hit_old

    def stamp(old, new, landed):
        mask = landed.reshape(landed.shape + (1,) * (old.ndim - landed.ndim))
        return xp.where(mask, new[None], old)

    v.tick = t
    v.wv, landed, hit_old = ring_put(rs.wv, wv_fan)
    coll |= hit_old
    v.wv_fp_hi = stamp(rs.wv_fp_hi, wv_fp_hi_new, landed)
    v.wv_fp_lo = stamp(rs.wv_fp_lo, wv_fp_lo_new, landed)
    v.wv_cfg_hi = stamp(rs.wv_cfg_hi, wv_cfg_hi_new, landed)
    v.wv_cfg_lo = stamp(rs.wv_cfg_lo, wv_cfg_lo_new, landed)
    v.wv_seq = stamp(rs.wv_seq, wv_seq_new, landed)

    v.w1a, landed, hit_old = ring_put(rs.w1a, w1a_fan)
    coll |= hit_old
    v.w1a_cfg_hi = stamp(rs.w1a_cfg_hi, w1a_cfg_hi_new, landed)
    v.w1a_cfg_lo = stamp(rs.w1a_cfg_lo, w1a_cfg_lo_new, landed)
    v.w1a_seq = stamp(rs.w1a_seq, w1a_seq_new, landed)
    v.w1a_tick = xp.where(landed, t, rs.w1a_tick)

    v.w1b, landed, hit_old = ring_put(rs.w1b, w1b_new)
    coll |= hit_old
    v.w1b_vrnd_r = stamp(rs.w1b_vrnd_r, w1b_vrnd_r_new, landed)
    v.w1b_vrnd_i = stamp(rs.w1b_vrnd_i, w1b_vrnd_i_new, landed)
    v.w1b_fp_hi = stamp(rs.w1b_fp_hi, w1b_fp_hi_new, landed)
    v.w1b_fp_lo = stamp(rs.w1b_fp_lo, w1b_fp_lo_new, landed)
    v.w1b_set = stamp(rs.w1b_set, w1b_set_new, landed)
    v.w1b_cfg_hi = stamp(rs.w1b_cfg_hi, w1b_cfg_hi_new, landed)
    v.w1b_cfg_lo = stamp(rs.w1b_cfg_lo, w1b_cfg_lo_new, landed)
    # Promiser send key, stamped post-rebuild: rx_pos here equals the
    # value the delivery-tick prefix logic read off the state before.
    v.w1b_seq = stamp(rs.w1b_seq, t * (c + 1) + v.rx_pos, landed)

    v.w2a, landed, hit_old = ring_put(rs.w2a, w2a_fan)
    coll |= hit_old
    v.w2a_fp_hi = stamp(rs.w2a_fp_hi, w2a_fp_hi_new, landed)
    v.w2a_fp_lo = stamp(rs.w2a_fp_lo, w2a_fp_lo_new, landed)
    v.w2a_mask = stamp(rs.w2a_mask, w2a_mask_new, landed)
    v.w2a_cfg_hi = stamp(rs.w2a_cfg_hi, w2a_cfg_hi_new, landed)
    v.w2a_cfg_lo = stamp(rs.w2a_cfg_lo, w2a_cfg_lo_new, landed)
    v.w2a_seq = stamp(rs.w2a_seq, w2a_seq_new, landed)
    v.w2a_tick = xp.where(landed, t, rs.w2a_tick)

    # 2b: the two payload lanes share one sender row (and cfg snapshot),
    # so old/new overlap is checked per (slot, sender) across lanes.
    cleared = rs.w2b & keep[:, None]
    ins = slot_hit[:, None] & w2b_fan[None]
    coll |= (cleared.any(axis=(1, 3)) & ins.any(axis=(1, 3))).any()
    v.w2b = cleared | ins
    lane_landed = ins.any(axis=-1)                       # [D, 2, C]
    v.w2b_rnd = stamp(rs.w2b_rnd, w2b_rnd_new, lane_landed)
    v.w2b_fp_hi = stamp(rs.w2b_fp_hi, w2b_fp_hi_new, lane_landed)
    v.w2b_fp_lo = stamp(rs.w2b_fp_lo, w2b_fp_lo_new, lane_landed)
    v.w2b_mask = stamp(rs.w2b_mask, w2b_mask_new, lane_landed)
    sender_landed = lane_landed.any(axis=1)              # [D, C]
    v.w2b_cfg_hi = stamp(rs.w2b_cfg_hi, w2b_cfg_hi_new, sender_landed)
    v.w2b_cfg_lo = stamp(rs.w2b_cfg_lo, w2b_cfg_lo_new, sender_landed)

    v.pd_bcast, landed, hit_old = ring_put(rs.pd_bcast, pd_fan)
    coll |= hit_old
    v.pd = stamp(rs.pd, pd_new, landed)
    v.pd_dst = stamp(rs.pd_dst, pd_dst_new, landed)
    v.pd_cfg_hi = stamp(rs.pd_cfg_hi, pd_cfg_hi_new, landed)
    v.pd_cfg_lo = stamp(rs.pd_cfg_lo, pd_cfg_lo_new, landed)

    v.flags = v.flags | xp.where(coll, FLAG_RING_COLLISION, 0)

    log = ReceiverStepLog(
        tick=t,
        sent=sent, delivered=delivered, dropped=dropped,
        probes_sent=probes_sent, probes_failed=probes_failed,
        fv_sent=phase_sent["fv"], fv_delivered=phase_del["fv"],
        p1a_sent=phase_sent["p1a"], p1a_delivered=phase_del["p1a"],
        p1b_sent=phase_sent["p1b"], p1b_delivered=phase_del["p1b"],
        p2a_sent=phase_sent["p2a"], p2a_delivered=phase_del["p2a"],
        p2b_sent=phase_sent["p2b"], p2b_delivered=phase_del["p2b"],
        partitioned_edges=monitor.partitioned_edge_count(
            xp, faults, ~crashed, t),
        link_dropped=link_dropped,
        announce=announce, ann_prop=ann_prop,
        ann_cfg_hi=ann_cfg_hi, ann_cfg_lo=ann_cfg_lo,
        decide=dec_mask, dec_hosts=dec_hosts,
        dec_cfg_hi=dec_cfg_hi, dec_cfg_lo=dec_cfg_lo,
        flags=v.flags,
    )
    nxt = ReceiverState(**{name: getattr(v, name)
                           for name in ReceiverState._fields})
    return nxt, log


def init_receiver_state(uids: Sequence[int], id_fp_sum: int,
                        settings: Settings, *, seed: int,
                        member: Optional[Sequence[bool]] = None,
                        ) -> ReceiverState:
    """Boot a per-receiver universe: every slot starts with the identical
    converged view (rows of ``member``), padding slots beyond the real
    membership boot *stopped* (they own no protocol state). ``seed`` is
    the schedule seed — it keys the precomputed fallback-delay table to
    the same per-slot rng streams the host adversary draws from."""
    from rapid_tpu.engine.paxos import (
        build_delay_table, classic_rank_index, ring0_positions)
    from rapid_tpu.engine.state import init_state
    from rapid_tpu.engine.topology import build_topology

    if settings.batching_window_ticks != 1:
        raise ValueError("per-receiver mode assumes the oracle's 1-tick "
                         "alert batching window, got "
                         f"{settings.batching_window_ticks}")
    base = init_state(uids, id_fp_sum, settings, member=member)
    c, k = base.ring_order.shape
    d = settings.delivery_ring_depth
    xp = jnp
    member_row = base.member
    member_cc = xp.broadcast_to(member_row[None, :], (c, c))
    ridx = xp.arange(c, dtype=xp.int32)

    subj_idx, obs_idx, _gk, fd_active, fd_first = build_topology(
        xp, member_row, base.ring_order, base.ring_rank)
    pos = ring0_positions(xp, member_row, base.ring_order, base.ring_rank)
    rank_idx = classic_rank_index(xp, base.uid_hi, base.uid_lo)
    delay_table = jnp.asarray(
        build_delay_table(seed, c, N_DRAWS, settings))

    u32z = lambda *s: xp.zeros(s, xp.uint32)
    i32z = lambda *s: xp.zeros(s, xp.int32)
    bz = lambda *s: xp.zeros(s, bool)
    return ReceiverState(
        tick=xp.int32(0),
        uid_hi=base.uid_hi, uid_lo=base.uid_lo,
        mfp_hi=base.mfp_hi, mfp_lo=base.mfp_lo,
        idsum_hi=base.idsum_hi, idsum_lo=base.idsum_lo,
        rank_idx=rank_idx,
        ring_order=base.ring_order, ring_rank=base.ring_rank,
        delay_table=delay_table, draws=i32z(c),
        member=member_cc,
        memsum_hi=xp.broadcast_to(base.memsum_hi, (c,)),
        memsum_lo=xp.broadcast_to(base.memsum_lo, (c,)),
        cfg_hi=xp.broadcast_to(
            config_id_limbs(xp, base.idsum_hi, base.idsum_lo,
                            base.memsum_hi, base.memsum_lo)[0], (c,)),
        cfg_lo=xp.broadcast_to(
            config_id_limbs(xp, base.idsum_hi, base.idsum_lo,
                            base.memsum_hi, base.memsum_lo)[1], (c,)),
        epoch=i32z(c),
        stopped=~member_row,
        rx_pos=xp.where(member_row, pos, I32_MAX).astype(xp.int32),
        px_n=xp.broadcast_to(member_row.sum().astype(xp.int32), (c,)),
        obs_full=xp.broadcast_to(obs_idx[None, :, :], (c, c, k)),
        own_subj=subj_idx,
        own_fd_active=fd_active & member_row[:, None],
        own_fd_first=fd_first,
        fc=i32z(c, k), notified=bz(c, k), fd_gate=i32z(c),
        pf=bz(c, k), pf_dst=i32z(c, k),
        pf_cfg_hi=u32z(c), pf_cfg_lo=u32z(c),
        pd=bz(d, c, k), pd_dst=i32z(d, c, k),
        pd_cfg_hi=u32z(d, c), pd_cfg_lo=u32z(d, c), pd_bcast=bz(d, c, c),
        reports=bz(c, c, k), seen_down=bz(c), announced=bz(c),
        ar_seq=xp.full((c,), I32_MAX, xp.int32),
        reg_valid=bz(c), reg_mask=bz(c, c),
        reg_fp_hi=u32z(c), reg_fp_lo=u32z(c),
        wv=bz(d, c, c), wv_fp_hi=u32z(d, c), wv_fp_lo=u32z(d, c),
        wv_cfg_hi=u32z(d, c), wv_cfg_lo=u32z(d, c),
        wv_seq=xp.full((d, c), I32_MAX, xp.int32),
        vt_seen=bz(c, c), vt_fp_hi=u32z(c, c), vt_fp_lo=u32z(c, c),
        px_rnd_r=i32z(c), px_rnd_i=i32z(c),
        px_vrnd_r=i32z(c), px_vrnd_i=i32z(c),
        px_vv_fp_hi=u32z(c), px_vv_fp_lo=u32z(c), px_vv_set=bz(c),
        px_crnd_r=i32z(c), px_cval_set=bz(c),
        px_timer=xp.full((c,), I32_MAX, xp.int32),
        pb_seen=bz(c, c), pb_vrnd_r=i32z(c, c), pb_vrnd_i=i32z(c, c),
        pb_fp_hi=u32z(c, c), pb_fp_lo=u32z(c, c), pb_set=bz(c, c),
        pb_seq=i32z(c, c),
        p2_rnd=xp.full((c,), -1, xp.int32), p2_seen=bz(c, c),
        p2_mask=bz(c, c),
        w1a=bz(d, c, c), w1a_cfg_hi=u32z(d, c), w1a_cfg_lo=u32z(d, c),
        w1a_seq=xp.full((d, c), I32_MAX, xp.int32), w1a_tick=i32z(d, c),
        w1b=bz(d, c, c), w1b_vrnd_r=i32z(d, c), w1b_vrnd_i=i32z(d, c),
        w1b_fp_hi=u32z(d, c), w1b_fp_lo=u32z(d, c), w1b_set=bz(d, c),
        w1b_cfg_hi=u32z(d, c), w1b_cfg_lo=u32z(d, c),
        w1b_seq=xp.full((d, c), I32_MAX, xp.int32),
        w2a=bz(d, c, c), w2a_fp_hi=u32z(d, c), w2a_fp_lo=u32z(d, c),
        w2a_mask=bz(d, c, c), w2a_cfg_hi=u32z(d, c), w2a_cfg_lo=u32z(d, c),
        w2a_seq=xp.full((d, c), I32_MAX, xp.int32), w2a_tick=i32z(d, c),
        w2b=bz(d, 2, c, c), w2b_rnd=i32z(d, 2, c),
        w2b_fp_hi=u32z(d, 2, c), w2b_fp_lo=u32z(d, 2, c),
        w2b_mask=bz(d, 2, c, c),
        w2b_cfg_hi=u32z(d, c), w2b_cfg_lo=u32z(d, c),
        flags=xp.int32(0),
    )


@functools.partial(jax.jit, static_argnums=(2, 3))
def _simulate(rs, faults, n_ticks: int, settings: Settings):
    # Static flight-recorder gate (``engine.recorder``, same discipline
    # as step._simulate): W > 0 threads a bounded gauge ring through the
    # scan and returns a 3-tuple; W == 0 keeps the recorder-less scan
    # verbatim so its jaxpr is byte-identical. Module-attribute call so
    # tests can monkeypatch a spy on the record hook.
    if settings.flight_recorder_window:
        def rec_body(carry, _):
            st, rec = carry
            nxt, log = receiver_step(st, faults, settings)
            return (nxt, recorder_mod.record_receiver_step(
                rec, log, settings)), log

        (final, rec), logs = lax.scan(
            rec_body, (rs, recorder_mod.init(settings)), None,
            length=n_ticks)
        return final, logs, rec

    def body(carry, _):
        return receiver_step(carry, faults, settings)

    return lax.scan(body, rs, None, length=n_ticks)


def receiver_simulate(rs: ReceiverState, faults: EngineFaults,
                      n_ticks: int, settings: Settings):
    """Run the jitted per-receiver scan; returns (final_state, logs) —
    or (final_state, logs, recorder) when
    ``settings.flight_recorder_window > 0``. Under
    ``settings.rx_kernel != "xla"`` the scan carries the packed layout
    (``engine.rx_packed``) and unpacks the final state in-jit — same
    return contract, bit-identical results."""
    if settings.rx_kernel != "xla":
        from rapid_tpu.engine import rx_packed
        return rx_packed.simulate(rs, faults, n_ticks, settings)
    return _simulate(rs, faults, n_ticks, settings)


@functools.partial(jax.jit, static_argnums=(3, 4))
def _simulate_resumed(rs, rec, faults, n_ticks: int, settings: Settings):
    """``_simulate`` with the flight recorder carried in — the chunked
    continuation entry (``receiver_simulate_chunk``, chunks 2+)."""
    def rec_body(carry, _):
        st, r = carry
        nxt, log = receiver_step(st, faults, settings)
        return (nxt, recorder_mod.record_receiver_step(
            r, log, settings)), log

    (final, rec), logs = lax.scan(rec_body, (rs, rec), None,
                                  length=n_ticks)
    return final, logs, rec


# Donated twins for the resident service: the dense carry (and recorder)
# buffers are reused for the chunk's outputs, so a soak holds one
# state-sized working set. Faults stay undonated — the same pytree feeds
# every chunk.
_simulate_donated = functools.partial(
    jax.jit, static_argnums=(2, 3), donate_argnums=(0,))(
        _simulate.__wrapped__)
_simulate_resumed_donated = functools.partial(
    jax.jit, static_argnums=(3, 4), donate_argnums=(0, 1))(
        _simulate_resumed.__wrapped__)


def receiver_simulate_chunk(carry, faults, n_ticks: int, settings: Settings,
                            rec=None, donate: bool = True):
    """One streaming chunk of the per-receiver scan, layout-preserving.

    Under ``rx_kernel="xla"`` the carry is a dense ``ReceiverState`` and
    the final comes back dense; under the packed layouts the carry is a
    ``rx_packed.PackedReceiverBundle`` (boot one via
    ``rx_packed.as_bundle``) and the final comes back as a bundle — the
    carry type round-trips, so the service re-feeds it verbatim. ``rec``
    resumes the flight recorder (required for chunks after the first when
    ``settings.flight_recorder_window > 0``); ``donate`` hands the carry
    buffers to the executable. Chained chunks are bit-identical to one
    uninterrupted ``receiver_simulate`` of the summed length."""
    if settings.rx_kernel != "xla":
        from rapid_tpu.engine import rx_packed
        return rx_packed.simulate_chunk(carry, faults, n_ticks, settings,
                                        rec=rec, donate=donate)
    n_ticks = int(n_ticks)
    if settings.flight_recorder_window and rec is not None:
        fn = _simulate_resumed_donated if donate else _simulate_resumed
        return fn(carry, rec, faults, n_ticks, settings)
    fn = _simulate_donated if donate else _simulate
    return fn(carry, faults, n_ticks, settings)


def receiver_final_view(final):
    """Dense view of the final-state fields host extraction reads
    (member, stopped, cfg limbs, flags): the identity on dense finals,
    a selective unpack on packed fleet finals (``rx_kernel != "xla"``
    dispatches return ``rx_packed.PackedReceiverState`` finals to keep
    the output transfer on the diet)."""
    if isinstance(final, ReceiverState):
        return final
    from rapid_tpu.engine import rx_packed
    return rx_packed.final_view(final)


def _fleet_body(rs, faults, n_ticks: int, settings: Settings,
                fleet_mesh=None):
    # ``fleet_mesh`` (static) partitions the vmapped member axis as
    # P("fleet") — each device owns whole members, no collectives. The
    # default None path traces a byte-identical jaxpr (no constraint
    # eqns), mirroring step.fleet_body's contract. Packed-layout fleets
    # (``rx_kernel != "xla"`` — the stacked state is then a
    # ``rx_packed.PackedReceiverBundle``) take the packed twin, which
    # returns packed finals.
    if settings.rx_kernel != "xla":
        from rapid_tpu.engine import rx_packed
        return rx_packed.fleet_body(rs, faults, n_ticks, settings,
                                    fleet_mesh)
    if fleet_mesh is not None:
        f = rs.member.shape[0]
        rs = sharding_mod.fleet_axis_constrain_tree(rs, fleet_mesh, f)
        faults = sharding_mod.fleet_axis_constrain_tree(
            faults, fleet_mesh, f)
    if settings.flight_recorder_window:
        finals, logs, recs = jax.vmap(
            lambda s, f_: _simulate(s, f_, n_ticks, settings))(rs, faults)
        if fleet_mesh is not None:
            finals = sharding_mod.fleet_axis_constrain_tree(
                finals, fleet_mesh, f)
            logs = sharding_mod.fleet_axis_constrain_tree(
                logs, fleet_mesh, f)
            recs = sharding_mod.fleet_axis_constrain_tree(
                recs, fleet_mesh, f)
        return finals, logs, recs
    finals, logs = jax.vmap(
        lambda s, f_: _simulate(s, f_, n_ticks, settings))(rs, faults)
    if fleet_mesh is not None:
        finals = sharding_mod.fleet_axis_constrain_tree(
            finals, fleet_mesh, f)
        logs = sharding_mod.fleet_axis_constrain_tree(logs, fleet_mesh, f)
    return finals, logs


_fleet_simulate = functools.partial(
    jax.jit, static_argnums=(2, 3, 4))(_fleet_body)

# Donated twin for single-shot campaign dispatches: input buffers are
# reused for outputs, halving the per-dispatch working set (the O(C^2)
# receiver planes dominate fleet memory).
_fleet_simulate_donated = functools.partial(
    jax.jit, static_argnums=(2, 3, 4), donate_argnums=(0, 1))(_fleet_body)


def receiver_fleet_simulate(stacked_rs, stacked_faults, n_ticks: int,
                            settings: Settings, fleet_mesh=None):
    """vmap the per-receiver scan over a leading fleet axis (the tick body
    traces once regardless of F, like the shared fleet path).
    ``fleet_mesh`` optionally shards the member axis over the devices."""
    return _fleet_simulate(stacked_rs, stacked_faults, n_ticks, settings,
                           fleet_mesh)


# --- host-side extraction ------------------------------------------------

def receiver_events(logs) -> List[List[Tuple[int, str, int, Tuple[int, ...]]]]:
    """Per-slot ``(tick, kind, config_id, slots)`` event streams in
    ``AdversaryRun.events_by_slot`` format (a slot announces at most once
    and decides at most once per tick, and never both, so tick order is
    total per slot)."""
    ann = np.asarray(logs.announce)
    dec = np.asarray(logs.decide)
    ann_prop = np.asarray(logs.ann_prop)
    dec_hosts = np.asarray(logs.dec_hosts)
    ann_cfg = (np.asarray(logs.ann_cfg_hi).astype(np.uint64) << 32) \
        | np.asarray(logs.ann_cfg_lo).astype(np.uint64)
    dec_cfg = (np.asarray(logs.dec_cfg_hi).astype(np.uint64) << 32) \
        | np.asarray(logs.dec_cfg_lo).astype(np.uint64)
    ticks = np.asarray(logs.tick)
    n_ticks, c = ann.shape
    events: List[List[Tuple[int, str, int, Tuple[int, ...]]]] = [
        [] for _ in range(c)]
    for ti in range(n_ticks):
        t = int(ticks[ti])
        for r in np.nonzero(dec[ti])[0]:
            events[int(r)].append(
                (t, "view_change", int(dec_cfg[ti, r]),
                 tuple(int(s) for s in np.nonzero(dec_hosts[ti, r])[0])))
        for r in np.nonzero(ann[ti])[0]:
            events[int(r)].append(
                (t, "proposal", int(ann_cfg[ti, r]),
                 tuple(int(s) for s in np.nonzero(ann_prop[ti, r])[0])))
    return events


def receiver_counters(logs) -> List[dict]:
    """Per-tick counter deltas, ``AdversaryRun.tick_history`` format."""
    fields = {"sent": logs.sent, "delivered": logs.delivered,
              "dropped": logs.dropped, "probes_sent": logs.probes_sent,
              "probes_failed": logs.probes_failed}
    arrs = {k: np.asarray(a) for k, a in fields.items()}
    n_ticks = arrs["sent"].shape[0]
    return [{"sent": int(arrs["sent"][i]),
             "delivered": int(arrs["delivered"][i]),
             "dropped": int(arrs["dropped"][i]),
             "timeouts": 0,
             "probes_sent": int(arrs["probes_sent"][i]),
             "probes_failed": int(arrs["probes_failed"][i])}
            for i in range(n_ticks)]


def receiver_phase_counters(logs) -> List[dict]:
    """Per-tick phase deltas, ``AdversaryRun.phase_history`` format."""
    pairs = (("fast_vote", logs.fv_sent, logs.fv_delivered),
             ("phase1a", logs.p1a_sent, logs.p1a_delivered),
             ("phase1b", logs.p1b_sent, logs.p1b_delivered),
             ("phase2a", logs.p2a_sent, logs.p2a_delivered),
             ("phase2b", logs.p2b_sent, logs.p2b_delivered))
    arrs = [(p, np.asarray(s), np.asarray(d)) for p, s, d in pairs]
    n_ticks = arrs[0][1].shape[0]
    return [{f"{p}_{kind}": int(a[i]) for p, s, d in arrs
             for kind, a in (("sent", s), ("delivered", d))}
            for i in range(n_ticks)]


def receiver_config_ids(rs: ReceiverState) -> List[int]:
    """Final per-slot configuration ids as python ints."""
    hi = np.asarray(rs.cfg_hi).astype(np.uint64)
    lo = np.asarray(rs.cfg_lo).astype(np.uint64)
    return [int(h << 32 | l) for h, l in zip(hi, lo)]


def receiver_run_payload(rs: ReceiverState, logs, n: int, n_ticks: int):
    """Bundle a finished device run into an ``AdversaryRun`` so existing
    diff/metrics tooling consumes it unchanged."""
    from rapid_tpu.engine.adversary import AdversaryRun

    events = receiver_events(logs)
    counters = receiver_counters(logs)
    phases = receiver_phase_counters(logs)
    member = np.asarray(rs.member)
    totals = {k: sum(row[k] for row in counters)
              for k in ("sent", "delivered", "dropped", "probes_sent",
                        "probes_failed")}
    totals["timeouts"] = 0
    phase_totals = {k: sum(row[k] for row in phases) for k in phases[0]} \
        if phases else {}
    return AdversaryRun(
        n=n, n_ticks=n_ticks,
        events_by_slot=[events[s] for s in range(n)],
        tick_history=counters,
        phase_history=phases,
        partitioned_edges=[int(x) for x in np.asarray(logs.partitioned_edges)],
        link_dropped=[int(x) for x in np.asarray(logs.link_dropped)],
        config_ids=receiver_config_ids(rs)[:n],
        members_by_slot=[frozenset(int(i) for i in np.nonzero(member[s])[0])
                         for s in range(n)],
        stopped=[bool(x) for x in np.asarray(rs.stopped)[:n]],
        totals=totals, phase_totals=phase_totals,
    )


# --- memory sizing -------------------------------------------------------

def receiver_field_shapes(capacity: int, k: int, n_draws: int = N_DRAWS,
                          ring_depth: int = 4):
    """``{field: (shape, itemsize)}`` for every ``ReceiverState`` leaf —
    the sizing ground truth (``tests/test_receiver.py`` pins each entry
    against a real instantiation so the table cannot drift). ``ring_depth``
    must match ``Settings.delivery_ring_depth`` (default mirrors it)."""
    c, d = capacity, ring_depth
    B, I, U = 1, 4, 4          # bool, int32, uint32 itemsizes
    s = {"tick": ((), I), "flags": ((), I),
         "idsum_hi": ((), U), "idsum_lo": ((), U),
         "delay_table": ((c, n_draws, c + 1), I),
         "ring_order": ((c, k), I), "ring_rank": ((c, k), I),
         "obs_full": ((c, c, k), I), "reports": ((c, c, k), B),
         "own_subj": ((c, k), I), "own_fd_first": ((c, k), I),
         "own_fd_active": ((c, k), B), "fc": ((c, k), I),
         "notified": ((c, k), B), "pf": ((c, k), B),
         "pf_dst": ((c, k), I),
         "pd": ((d, c, k), B), "pd_dst": ((d, c, k), I),
         "w2b": ((d, 2, c, c), B), "w2b_rnd": ((d, 2, c), I),
         "w2b_fp_hi": ((d, 2, c), U), "w2b_fp_lo": ((d, 2, c), U),
         "w2b_mask": ((d, 2, c, c), B)}
    for f in ("uid_hi", "uid_lo", "mfp_hi", "mfp_lo", "memsum_hi",
              "memsum_lo", "cfg_hi", "cfg_lo", "pf_cfg_hi", "pf_cfg_lo",
              "reg_fp_hi", "reg_fp_lo", "px_vv_fp_hi", "px_vv_fp_lo"):
        s[f] = ((c,), U)
    for f in ("pd_cfg_hi", "pd_cfg_lo", "wv_fp_hi", "wv_fp_lo",
              "wv_cfg_hi", "wv_cfg_lo", "w1a_cfg_hi", "w1a_cfg_lo",
              "w1b_fp_hi", "w1b_fp_lo", "w1b_cfg_hi", "w1b_cfg_lo",
              "w2a_fp_hi", "w2a_fp_lo", "w2a_cfg_hi", "w2a_cfg_lo",
              "w2b_cfg_hi", "w2b_cfg_lo"):
        s[f] = ((d, c), U)
    for f in ("rank_idx", "draws", "epoch", "rx_pos", "px_n", "fd_gate",
              "ar_seq", "px_rnd_r", "px_rnd_i", "px_vrnd_r",
              "px_vrnd_i", "px_crnd_r", "px_timer", "p2_rnd"):
        s[f] = ((c,), I)
    for f in ("wv_seq", "w1a_seq", "w1a_tick", "w1b_vrnd_r", "w1b_vrnd_i",
              "w1b_seq", "w2a_seq", "w2a_tick"):
        s[f] = ((d, c), I)
    for f in ("stopped", "seen_down", "announced", "reg_valid",
              "px_vv_set", "px_cval_set"):
        s[f] = ((c,), B)
    s["w1b_set"] = ((d, c), B)
    for f in ("member", "reg_mask", "vt_seen",
              "pb_seen", "pb_set", "p2_seen", "p2_mask"):
        s[f] = ((c, c), B)
    for f in ("wv", "w1a", "w1b", "w2a", "w2a_mask", "pd_bcast"):
        s[f] = ((d, c, c), B)
    for f in ("vt_fp_hi", "vt_fp_lo", "pb_fp_hi", "pb_fp_lo"):
        s[f] = ((c, c), U)
    for f in ("pb_vrnd_r", "pb_vrnd_i", "pb_seq"):
        s[f] = ((c, c), I)
    assert set(s) == set(ReceiverState._fields), \
        sorted(set(s) ^ set(ReceiverState._fields))
    return s


def receiver_state_bytes(capacity: int, k: int,
                         n_draws: int = N_DRAWS,
                         ring_depth: int = 4) -> int:
    """Exact per-member footprint of one ``ReceiverState`` in bytes."""
    return sum(int(np.prod(shape, dtype=np.int64)) * item
               for shape, item in
               receiver_field_shapes(capacity, k, n_draws,
                                     ring_depth).values())


def receiver_log_bytes(capacity: int, n_ticks: int) -> int:
    """Per-member log footprint for ``n_ticks`` scanned ticks."""
    c = capacity
    per_tick = (18 * 4            # scalar i32 counters/gauges
                + 2 * c + 2 * c * c          # announce/decide masks
                + 4 * c * 4)      # cfg limb columns
    return per_tick * n_ticks
