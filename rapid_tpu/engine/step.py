"""The tick engine: one jitted step composing all four kernels.

Phase order inside a tick mirrors the oracle's ``SimScheduler.step`` — the
virtual network delivers messages (in send order) *before* due tasks run,
and within the delivery phase votes (sent during the previous tick's
delivery phase) sort before alert batches (sent during its run_due phase):

1. **decide** — fast-round votes sent at the announce tick arrive; a
   quorum triggers the view change (membership XOR with the proposal:
   leavers/crashed limb-subtract their member fingerprints from the
   membership sum, joiners limb-add theirs and fold their identifier
   fingerprint into the identifier sum; topology rebuild, full
   monitor/cut/consensus reset, FD re-alignment via ``fd_gate``, and an
   ``epoch`` increment that expires any in-flight churn alerts — the
   oracle's config-id filter);
2. **deliver** — alert batches flushed last tick (monitor DOWNs plus the
   churn pipeline's leave-DOWNs and join-UPs) land in the cut detector;
   an H-crossing with no destination in flux announces the proposal and
   broadcasts the fast-round votes;
3. **flush** — batches enqueued by last FD tick (and churn alerts
   injected last tick) move to the delivery buffers (the oracle's 1-tick
   batching-window quiescence);
4. **churn + monitor** — scheduled join/leave alerts whose epoch still
   matches are injected into the churn pipeline (the oracle's
   gatekeeper/observer enqueue tick); on global ticks
   ``t % fd_interval == 0`` past the ``fd_gate``, every node probes its
   unique subjects and saturated counters enqueue their DOWN alerts.

``step`` is pure and shape-static: ``engine_step`` is its jit, and
``simulate`` drives it through ``lax.scan`` inside a single jit so an
n-tick run is one device dispatch. ``churn`` is an optional
``ChurnSchedule`` pytree; passing None compiles the churn phase out.
``trace_count()`` exposes how many times the step body has been traced
(tests assert a single compilation).
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from rapid_tpu import hashing
from rapid_tpu.engine import cut, monitor
from rapid_tpu.engine import votes as votes_mod
from rapid_tpu.engine.state import (EngineFaults, EngineState, StepLog,
                                    config_id_limbs)
from rapid_tpu.engine.topology import build_topology
from rapid_tpu.settings import Settings

_TRACE_COUNT = 0


def trace_count() -> int:
    """How many times the step body has been traced (re-compiled)."""
    return _TRACE_COUNT


def reset_trace_count() -> None:
    """Zero the trace counter.

    Single-compilation assertions should call this first so they measure
    their own traces, independent of which tests (and in which order)
    already compiled the step at other shapes.
    """
    global _TRACE_COUNT
    _TRACE_COUNT = 0


def step(state: EngineState, faults: EngineFaults, settings: Settings,
         churn=None) -> tuple:
    """Advance the engine by one tick; returns (new_state, StepLog)."""
    global _TRACE_COUNT
    _TRACE_COUNT += 1

    t = state.tick + 1
    crashed = monitor.crashed_at(faults, t)

    # ---- phase 1: vote delivery & decision -----------------------------
    votes_arriving = state.vote_pending & (state.announce_tick + 1 == t)
    valid = state.voters & ~crashed & votes_arriving
    n_member = state.member.sum().astype(jnp.int32)
    c = state.member.shape[0]
    decided, tally = votes_mod.count_fast_round(
        jnp,
        jnp.broadcast_to(state.phash_hi, (c,)),
        jnp.broadcast_to(state.phash_lo, (c,)),
        valid, n_member)
    vote_tally = jnp.where(votes_arriving, tally, 0).astype(jnp.int32)
    vote_quorum = jnp.where(
        votes_arriving, votes_mod.fast_quorum(jnp, n_member), 0
    ).astype(jnp.int32)
    # A decision needs an alive receiver to count the votes.
    decide_now = votes_arriving & decided & (state.member & ~crashed).any()
    decision = state.proposal & decide_now

    vote_senders_alive = jnp.where(
        votes_arriving, valid.sum(), 0).astype(jnp.int32)
    vote_deliver_alive = jnp.where(
        votes_arriving, (state.member & ~crashed).sum(), 0).astype(jnp.int32)

    def do_view_change(_):
        removed = state.proposal & state.member
        joined = state.proposal & ~state.member
        member = state.member ^ state.proposal
        rm = removed.astype(jnp.uint32)
        jn = joined.astype(jnp.uint32)
        rhi, rlo = hashing.sum64(jnp, state.mfp_hi * rm, state.mfp_lo * rm)
        ahi, alo = hashing.sum64(jnp, state.mfp_hi * jn, state.mfp_lo * jn)
        ms_hi, ms_lo = hashing.sub64(
            jnp, state.memsum_hi, state.memsum_lo, rhi, rlo)
        ms_hi, ms_lo = hashing.add64(jnp, ms_hi, ms_lo, ahi, alo)
        # Identifiers are remembered forever (MembershipView.java:51):
        # joins add their id fingerprint, removals never subtract.
        ihi, ilo = hashing.sum64(jnp, state.idfp_hi * jn, state.idfp_lo * jn)
        id_hi, id_lo = hashing.add64(
            jnp, state.idsum_hi, state.idsum_lo, ihi, ilo)
        topo = build_topology(jnp, state.uid_hi, state.uid_lo, member,
                              settings.K)
        return (member, ms_hi, ms_lo, id_hi, id_lo) + topo

    def keep_view(_):
        return (state.member, state.memsum_hi, state.memsum_lo,
                state.idsum_hi, state.idsum_lo,
                state.subj_idx, state.obs_idx, state.gk_idx,
                state.fd_active, state.fd_first)

    (member, memsum_hi, memsum_lo, idsum_hi, idsum_lo, subj_idx, obs_idx,
     gk_idx, fd_active, fd_first) = lax.cond(
        decide_now, do_view_change, keep_view, None)

    mid = state._replace(
        tick=t, member=member,
        memsum_hi=memsum_hi, memsum_lo=memsum_lo,
        idsum_hi=idsum_hi, idsum_lo=idsum_lo,
        subj_idx=subj_idx, obs_idx=obs_idx, gk_idx=gk_idx,
        fd_active=fd_active, fd_first=fd_first,
        fc=jnp.where(decide_now, 0, state.fc),
        notified=state.notified & ~decide_now,
        fd_gate=jnp.where(decide_now, t, state.fd_gate),
        pending_flush=state.pending_flush & ~decide_now,
        pending_deliver=state.pending_deliver & ~decide_now,
        churn_flush=state.churn_flush & ~decide_now,
        churn_deliver=state.churn_deliver & ~decide_now,
        reports=state.reports & ~decide_now,
        seen_down=state.seen_down & ~decide_now,
        announced=state.announced & ~decide_now,
        proposal=state.proposal & ~decide_now,
        vote_pending=state.vote_pending & ~votes_arriving,
        voters=state.voters & ~decide_now,
        epoch=state.epoch + decide_now.astype(jnp.int32),
    )

    # ---- phase 2: alert delivery, aggregation, announce + vote cast ----
    src_alive = ~crashed
    batch_src = mid.pending_deliver.any(axis=1)
    flushers_alive = (batch_src & src_alive).sum().astype(jnp.int32)
    n_alive = (mid.member & ~crashed).sum().astype(jnp.int32)
    delivered_down = cut.deliver_reports(jnp, mid, src_alive)
    delivered_up = jnp.zeros_like(delivered_down)
    if churn is not None:
        churn_down, churn_up = cut.deliver_churn_reports(jnp, mid, src_alive)
        delivered_down = delivered_down | churn_down
        delivered_up = churn_up
    (reports, seen_down, announce_now, crossed, _explicit_added,
     implicit_added) = cut.aggregate(
        jnp, mid, delivered_down, delivered_up, n_alive > 0, settings)

    ph_hi, ph_lo = votes_mod.proposal_fingerprint(
        jnp, crossed, mid.uid_hi, mid.uid_lo)
    mid = mid._replace(
        reports=reports,
        seen_down=seen_down,
        announced=mid.announced | announce_now,
        proposal=jnp.where(announce_now, crossed, mid.proposal),
        announce_tick=jnp.where(announce_now, t, mid.announce_tick),
        vote_pending=mid.vote_pending | announce_now,
        voters=jnp.where(announce_now, mid.member & ~crashed, mid.voters),
        phash_hi=jnp.where(announce_now, ph_hi, mid.phash_hi),
        phash_lo=jnp.where(announce_now, ph_lo, mid.phash_lo),
    )
    n_member_now = mid.member.sum().astype(jnp.int32)
    vote_senders = jnp.where(announce_now, n_alive, 0).astype(jnp.int32)
    vote_recipients = jnp.where(
        announce_now, n_member_now, 0).astype(jnp.int32)

    # ---- phase 3: batch flush (1-tick quiescence) ----------------------
    flusher_mask = mid.pending_flush.any(axis=1)
    flushers = flusher_mask.sum().astype(jnp.int32)
    flush_recipients = jnp.where(
        flusher_mask.any(), n_member_now, 0).astype(jnp.int32)
    mid = mid._replace(pending_deliver=mid.pending_flush,
                       pending_flush=jnp.zeros_like(mid.pending_flush),
                       churn_deliver=mid.churn_flush,
                       churn_flush=jnp.zeros_like(mid.churn_flush))

    # ---- phase 4a: churn alert injection (scheduled enqueue ticks) -----
    if churn is not None:
        # The enqueue fires only while the slot's epoch expectation holds:
        # a view change in between expired the scheduled alert, exactly as
        # the oracle's config-id check at enqueue would drop it.
        join_now = ((t == churn.join_tick) & ~mid.member
                    & (mid.epoch == churn.join_epoch))
        leave_now = ((t == churn.leave_tick) & mid.member
                     & (mid.epoch == churn.leave_epoch))
        mid = mid._replace(churn_flush=mid.churn_flush | join_now | leave_now)
        churn_injected = (join_now | leave_now).sum().astype(jnp.int32)
    else:
        churn_injected = jnp.int32(0)

    # ---- phase 4b: failure-detector interval ---------------------------
    is_fd = (t % settings.fd_interval_ticks == 0) & (t > mid.fd_gate)
    fc_new, notified_new, notify_exp, probes_sent, probes_failed = (
        monitor.monitor_tick(jnp, mid, faults, settings))
    new_state = mid._replace(
        fc=jnp.where(is_fd, fc_new, mid.fc),
        notified=jnp.where(is_fd, notified_new, mid.notified),
        pending_flush=notify_exp & is_fd,
    )

    cfg_hi, cfg_lo = config_id_limbs(
        jnp, new_state.idsum_hi, new_state.idsum_lo,
        new_state.memsum_hi, new_state.memsum_lo)
    alerts_in_flight = (
        new_state.pending_flush.any(axis=1).sum()
        + new_state.pending_deliver.any(axis=1).sum()
        + new_state.churn_flush.sum()
        + new_state.churn_deliver.sum()
    ).astype(jnp.int32)
    log = StepLog(
        tick=t,
        announce_now=announce_now,
        proposal=crossed & announce_now,
        decide_now=decide_now,
        decision=decision,
        config_hi=cfg_hi, config_lo=cfg_lo,
        n_member=n_member_now,
        probes_sent=jnp.where(is_fd, probes_sent, 0).astype(jnp.int32),
        probes_failed=jnp.where(is_fd, probes_failed, 0).astype(jnp.int32),
        flushers=flushers,
        flush_recipients=flush_recipients,
        flushers_alive=flushers_alive,
        deliver_alive=jnp.where(batch_src.any(), n_alive, 0).astype(jnp.int32),
        vote_senders=vote_senders,
        vote_recipients=vote_recipients,
        vote_senders_alive=vote_senders_alive,
        vote_deliver_alive=vote_deliver_alive,
        alerts_in_flight=alerts_in_flight,
        cut_reports=new_state.reports.sum().astype(jnp.int32),
        implicit_reports=implicit_added,
        vote_tally=vote_tally,
        quorum=vote_quorum,
        epoch=new_state.epoch,
        churn_injected=churn_injected,
    )
    return new_state, log


@partial(jax.jit, static_argnums=(2,))
def engine_step(state: EngineState, faults: EngineFaults,
                settings: Settings, churn=None) -> tuple:
    """One jitted tick — a single device dispatch per call."""
    return step(state, faults, settings, churn)


@partial(jax.jit, static_argnums=(2, 3))
def _simulate(state, faults, n_ticks: int, settings: Settings, churn=None):
    def body(carry, _):
        return step(carry, faults, settings, churn)

    return lax.scan(body, state, None, length=n_ticks)


def simulate(state: EngineState, faults: EngineFaults, n_ticks: int,
             settings: Settings, churn=None) -> tuple:
    """Run ``n_ticks`` engine steps as one jitted ``lax.scan``.

    Returns (final_state, logs) where each ``logs`` field is stacked with
    a leading ``n_ticks`` axis. ``churn`` is an optional ``ChurnSchedule``
    (see ``rapid_tpu.engine.churn``); None compiles to the crash-only
    engine.
    """
    return _simulate(state, faults, int(n_ticks), settings, churn)
