"""The tick engine: one jitted step composing all four kernels.

Phase order inside a tick mirrors the oracle's ``SimScheduler.step`` — the
virtual network delivers messages (in send order) *before* due tasks run,
and within the delivery phase votes (sent during the previous tick's
delivery phase) sort before alert batches (sent during its run_due phase):

1. **decide** — fast-round votes sent at the announce tick arrive; a
   quorum triggers the view change (membership XOR with the proposal:
   leavers/crashed limb-subtract their member fingerprints from the
   membership sum, joiners limb-add theirs and fold their identifier
   fingerprint into the identifier sum; a sort-free topology re-scan of
   the static ``ring_order``/``ring_rank`` arrays, full
   monitor/cut/consensus reset, FD re-alignment via ``fd_gate``, and an
   ``epoch`` increment that expires any in-flight churn alerts — the
   oracle's config-id filter);
2. **deliver** — alert batches flushed last tick (monitor DOWNs plus the
   churn pipeline's leave-DOWNs and join-UPs) land in the cut detector;
   an H-crossing with no destination in flux announces the proposal and
   broadcasts the fast-round votes;
3. **flush** — batches enqueued by last FD tick (and churn alerts
   injected last tick) move to the delivery buffers (the oracle's 1-tick
   batching-window quiescence);
4. **churn + monitor** — scheduled join/leave alerts whose epoch still
   matches are injected into the churn pipeline (the oracle's
   gatekeeper/observer enqueue tick); on global ticks
   ``t % fd_interval == 0`` past the ``fd_gate``, every node probes its
   unique subjects and saturated counters enqueue their DOWN alerts.

With a ``fallback`` schedule (``rapid_tpu.engine.paxos``), the delivery
phase grows the classic-Paxos chain in oracle seq order: phase-2b/2a/1b
messages (sent during the previous tick's delivery phase) land *before*
fast-round votes, and phase-1a broadcasts (task-phase timer sends) land
*after* them; the task phase appends scripted proposes and fallback-timer
fires. A classic majority decides through the same view-change path as a
fast quorum.

``step`` is pure and shape-static: ``engine_step`` is its jit, and
``simulate`` drives it through ``lax.scan`` inside a single jit so an
n-tick run is one device dispatch. ``churn`` is an optional
``ChurnSchedule`` pytree and ``fallback`` an optional
``FallbackSchedule``; passing None compiles the respective phase out.
``trace_count()`` exposes how many times the step body has been traced
(tests assert a single compilation).
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from rapid_tpu import hashing
from rapid_tpu.engine import cut, invariants, monitor
from rapid_tpu.engine import churn as churn_mod
from rapid_tpu.engine import paxos as paxos_mod
from rapid_tpu.engine import recorder as recorder_mod
from rapid_tpu.engine import sharding as sharding_mod
from rapid_tpu.engine import votes as votes_mod
from rapid_tpu.engine.state import (I32_MAX, EngineFaults, EngineState,
                                    StepLog, config_id_limbs)
from rapid_tpu.engine.topology import build_topology
from rapid_tpu.settings import Settings
from rapid_tpu.variants import hier as hier_mod
from rapid_tpu.variants import ring as ring_mod

_TRACE_COUNT = 0


def trace_count() -> int:
    """How many times the step body has been traced (re-compiled)."""
    return _TRACE_COUNT


def reset_trace_count() -> None:
    """Zero the trace counter.

    Single-compilation assertions should call this first so they measure
    their own traces, independent of which tests (and in which order)
    already compiled the step at other shapes.
    """
    global _TRACE_COUNT
    _TRACE_COUNT = 0


def step(state: EngineState, faults: EngineFaults, settings: Settings,
         churn=None, fallback=None, mesh=None) -> tuple:
    """Advance the engine by one tick; returns (new_state, StepLog).

    ``mesh`` (static, default None) partitions the capacity axis of
    every slot-universe array over a 1-D device mesh
    (``rapid_tpu.engine.sharding``): the kernels re-commit the slot
    sharding after their cross-slot stages and the returned state/log
    are constrained so the ``lax.scan`` carry never reshards between
    ticks. ``mesh=None`` compiles every constraint out — the
    single-device jaxpr is unchanged.
    """
    global _TRACE_COUNT
    _TRACE_COUNT += 1

    t = state.tick + 1
    crashed = monitor.crashed_at(faults, t)
    n_member = state.member.sum().astype(jnp.int32)
    c = state.member.shape[0]

    # ---- phase 0: classic-Paxos chain deliveries (earliest seq order) --
    if fallback is not None:
        state, px_counts, classic_decide, classic_pid = \
            paxos_mod.chain_deliver(jnp, state, fallback, t, n_member,
                                    mesh=mesh)
        fast2_decide, win_pid, px_tally, px_quorum = paxos_mod.fast_tally(
            jnp, state, fallback, t, n_member, classic_decide, mesh=mesh)
        n_pids = fallback.table_mask.shape[1]
        sc_pid = jnp.clip(
            jnp.where(classic_decide, classic_pid, win_pid), 0, n_pids - 1)
        e = jnp.clip(state.epoch, 0, fallback.inst_epoch.shape[0] - 1)
        sc_mask = fallback.table_mask[e][sc_pid]
        sc_decide = classic_decide | fast2_decide
    else:
        sc_decide = jnp.asarray(False)
        sc_mask = jnp.zeros_like(state.member)
        px_tally = px_quorum = jnp.int32(0)

    # ---- phase 1: vote delivery & decision -----------------------------
    votes_arriving = state.vote_pending & (state.announce_tick + 1 == t)
    valid = state.voters & ~crashed & votes_arriving
    # Protocol-variant dispatch (static knob, ``rapid_tpu.variants``):
    # the "rapid" branch is the pre-knob code verbatim, so its traced
    # jaxpr stays byte-identical (pinned in ``tests/test_variants.py``).
    if settings.protocol_variant == "ring":
        decided, tally = ring_mod.ring_count_fast_round(
            jnp, state,
            jnp.broadcast_to(state.phash_hi, (c,)),
            jnp.broadcast_to(state.phash_lo, (c,)),
            valid, n_member, mesh=mesh)
    elif settings.protocol_variant == "hier":
        decided, tally = hier_mod.hier_count_fast_round(
            jnp, state.member, valid, state.uid_hi, state.uid_lo,
            hier_mod.hier_group_count(c), mesh=mesh)
    else:
        decided, tally = votes_mod.count_fast_round(
            jnp,
            jnp.broadcast_to(state.phash_hi, (c,)),
            jnp.broadcast_to(state.phash_lo, (c,)),
            valid, n_member, mesh=mesh)
    vote_tally = jnp.where(votes_arriving, tally, 0).astype(jnp.int32)
    vote_quorum = jnp.where(
        votes_arriving, votes_mod.fast_quorum(jnp, n_member), 0
    ).astype(jnp.int32)
    vote_tally = jnp.maximum(vote_tally, px_tally)
    vote_quorum = jnp.maximum(vote_quorum, px_quorum)
    # A decision needs an alive receiver to count the votes.
    alert_decide = (votes_arriving & decided & ~sc_decide
                    & (state.member & ~crashed).any())
    decide_now = alert_decide | sc_decide
    decision_mask = jnp.where(sc_decide, sc_mask, state.proposal)
    decision = decision_mask & decide_now

    vote_senders_alive = jnp.where(
        votes_arriving, valid.sum(), 0).astype(jnp.int32)
    vote_deliver_alive = jnp.where(
        votes_arriving, (state.member & ~crashed).sum(), 0).astype(jnp.int32)
    # Variant message accounting for the vote *delivery* side. Ring: the
    # surviving votes arrive as one aggregation lap + one dissemination
    # lap (sender factor 2); hier: the whole exchange (intra-group votes
    # + inter-group verdict + relay) is one factor with recipient 1.
    if settings.protocol_variant == "ring":
        vote_senders_alive = jnp.where(
            votes_arriving & valid.any(), 2, 0).astype(jnp.int32)
    elif settings.protocol_variant == "hier":
        hier_vgate = (votes_arriving & valid.any()
                      & (state.member & ~crashed).any())
        vote_senders_alive = jnp.where(
            hier_vgate,
            hier_mod.hier_exchange_messages(
                jnp, valid, state.member & ~crashed,
                state.uid_hi, state.uid_lo,
                hier_mod.hier_group_count(c)),
            0).astype(jnp.int32)
        vote_deliver_alive = jnp.where(hier_vgate, 1, 0).astype(jnp.int32)

    def do_view_change(pmask):
        removed = pmask & state.member
        joined = pmask & ~state.member
        member = state.member ^ pmask
        rm = removed.astype(jnp.uint32)
        jn = joined.astype(jnp.uint32)
        rhi, rlo = hashing.sum64(jnp, state.mfp_hi * rm, state.mfp_lo * rm)
        ahi, alo = hashing.sum64(jnp, state.mfp_hi * jn, state.mfp_lo * jn)
        ms_hi, ms_lo = hashing.sub64(
            jnp, state.memsum_hi, state.memsum_lo, rhi, rlo)
        ms_hi, ms_lo = hashing.add64(jnp, ms_hi, ms_lo, ahi, alo)
        # Identifiers are remembered forever (MembershipView.java:51):
        # joins add their id fingerprint, removals never subtract.
        ihi, ilo = hashing.sum64(jnp, state.idfp_hi * jn, state.idfp_lo * jn)
        id_hi, id_lo = hashing.add64(
            jnp, state.idsum_hi, state.idsum_lo, ihi, ilo)
        topo = build_topology(jnp, member, state.ring_order, state.ring_rank,
                              mesh=mesh)
        pos = (paxos_mod.ring0_positions(jnp, member, state.ring_order,
                                         state.ring_rank)
               if fallback is not None else state.px_pos)
        return (member, ms_hi, ms_lo, id_hi, id_lo, pos) + topo

    def keep_view(_):
        return (state.member, state.memsum_hi, state.memsum_lo,
                state.idsum_hi, state.idsum_lo, state.px_pos,
                state.subj_idx, state.obs_idx, state.gk_idx,
                state.fd_active, state.fd_first)

    (member, memsum_hi, memsum_lo, idsum_hi, idsum_lo, px_pos, subj_idx,
     obs_idx, gk_idx, fd_active, fd_first) = lax.cond(
        decide_now, do_view_change, keep_view, decision_mask)

    px_resets = {}
    if fallback is not None:
        # A decision replaces the consensus instance: ranks back to zero,
        # chosen values cleared, every fallback timer cancelled and the
        # in-flight classic chain dropped (the oracle's fresh FastPaxos
        # plus the configuration-id filter on stale messages).
        zero_c = jnp.zeros((c,), jnp.int32)
        neg_c = jnp.full((c,), -1, jnp.int32)
        px_resets = dict(
            px_rnd_r=jnp.where(decide_now, zero_c, state.px_rnd_r),
            px_rnd_i=jnp.where(decide_now, zero_c, state.px_rnd_i),
            px_vrnd_r=jnp.where(decide_now, zero_c, state.px_vrnd_r),
            px_vrnd_i=jnp.where(decide_now, zero_c, state.px_vrnd_i),
            px_vval=jnp.where(decide_now, neg_c, state.px_vval),
            px_crnd_r=jnp.where(decide_now, zero_c, state.px_crnd_r),
            px_crnd_i=jnp.where(decide_now, zero_c, state.px_crnd_i),
            px_cval=jnp.where(decide_now, neg_c, state.px_cval),
            px_timer=jnp.where(decide_now, I32_MAX, state.px_timer),
            c1a_tick=jnp.where(decide_now, I32_MAX, state.c1a_tick),
            c1b_tick=jnp.where(decide_now, I32_MAX, state.c1b_tick),
            c1b_mask=state.c1b_mask & ~decide_now,
            c2a_tick=jnp.where(decide_now, I32_MAX, state.c2a_tick),
            c2b_tick=jnp.where(decide_now, I32_MAX, state.c2b_tick),
        )

    mid = state._replace(
        tick=t, member=member,
        memsum_hi=memsum_hi, memsum_lo=memsum_lo,
        idsum_hi=idsum_hi, idsum_lo=idsum_lo,
        px_pos=px_pos,
        subj_idx=subj_idx, obs_idx=obs_idx, gk_idx=gk_idx,
        fd_active=fd_active, fd_first=fd_first,
        fc=jnp.where(decide_now, 0, state.fc),
        notified=state.notified & ~decide_now,
        fd_gate=jnp.where(decide_now, t, state.fd_gate),
        pending_flush=state.pending_flush & ~decide_now,
        pending_deliver=state.pending_deliver & ~decide_now,
        churn_flush=state.churn_flush & ~decide_now,
        churn_deliver=state.churn_deliver & ~decide_now,
        reports=state.reports & ~decide_now,
        seen_down=state.seen_down & ~decide_now,
        announced=state.announced & ~decide_now,
        proposal=state.proposal & ~decide_now,
        vote_pending=state.vote_pending & ~votes_arriving,
        voters=state.voters & ~decide_now,
        epoch=state.epoch + decide_now.astype(jnp.int32),
        **px_resets,
    )

    # ---- phase 1b: late phase-1a delivery (task-phase send, last seq) --
    if fallback is not None:
        mid, px1b_counts = paxos_mod.phase1a_deliver(
            jnp, mid, fallback, t, n_member, decide_now, mesh=mesh)
        px_counts.update(px1b_counts)

    # ---- phase 2: alert delivery, aggregation, announce + vote cast ----
    src_alive = ~crashed
    batch_src = mid.pending_deliver.any(axis=1)
    flushers_alive = (batch_src & src_alive).sum().astype(jnp.int32)
    n_alive = (mid.member & ~crashed).sum().astype(jnp.int32)
    if settings.protocol_variant == "ring":
        flushers_alive = ring_mod.ring_pair_factor(jnp, batch_src & src_alive)
        delivered_down = cut.ring_deliver_reports(jnp, mid, src_alive)
    else:
        delivered_down = cut.deliver_reports(jnp, mid, src_alive)
    delivered_up = jnp.zeros_like(delivered_down)
    if churn is not None:
        churn_down, churn_up = cut.deliver_churn_reports(jnp, mid, src_alive)
        delivered_down = delivered_down | churn_down
        delivered_up = churn_up
    (reports, seen_down, announce_now, crossed, _explicit_added,
     implicit_added) = cut.aggregate(
        jnp, mid, delivered_down, delivered_up, n_alive > 0, settings,
        mesh=mesh)

    ph_hi, ph_lo = votes_mod.proposal_fingerprint(
        jnp, crossed, mid.uid_hi, mid.uid_lo)
    mid = mid._replace(
        reports=reports,
        seen_down=seen_down,
        announced=mid.announced | announce_now,
        proposal=jnp.where(announce_now, crossed, mid.proposal),
        announce_tick=jnp.where(announce_now, t, mid.announce_tick),
        vote_pending=mid.vote_pending | announce_now,
        voters=jnp.where(announce_now, mid.member & ~crashed, mid.voters),
        phash_hi=jnp.where(announce_now, ph_hi, mid.phash_hi),
        phash_lo=jnp.where(announce_now, ph_lo, mid.phash_lo),
    )
    n_member_now = mid.member.sum().astype(jnp.int32)
    vote_senders = jnp.where(announce_now, n_alive, 0).astype(jnp.int32)
    vote_recipients = jnp.where(
        announce_now, n_member_now, 0).astype(jnp.int32)
    # Variant accounting for the vote *send* side (the announce tick).
    if settings.protocol_variant == "ring":
        vote_senders = jnp.where(announce_now, 2, 0).astype(jnp.int32)
    elif settings.protocol_variant == "hier":
        vote_senders = jnp.where(
            announce_now,
            hier_mod.hier_exchange_messages(
                jnp, mid.member & ~crashed, mid.member,
                mid.uid_hi, mid.uid_lo, hier_mod.hier_group_count(c)),
            0).astype(jnp.int32)
        vote_recipients = jnp.where(announce_now, 1, 0).astype(jnp.int32)

    # ---- phase 3: batch flush (1-tick quiescence) ----------------------
    flusher_mask = mid.pending_flush.any(axis=1)
    flushers = flusher_mask.sum().astype(jnp.int32)
    flush_recipients = jnp.where(
        flusher_mask.any(), n_member_now, 0).astype(jnp.int32)
    if settings.protocol_variant == "ring":
        flushers = ring_mod.ring_pair_factor(jnp, flusher_mask)
    mid = mid._replace(pending_deliver=mid.pending_flush,
                       pending_flush=jnp.zeros_like(mid.pending_flush),
                       churn_deliver=mid.churn_flush,
                       churn_flush=jnp.zeros_like(mid.churn_flush))

    # ---- phase 4a': scripted identifier redraws (UUID-retry hop) -------
    # A joiner whose NodeId collided redraws at the oracle's response
    # hop: swap the dormant slot's identity limbs and move its ring
    # position incrementally (topology.rank_and_insert) — no sort.
    # Schedules without redraws carry None and compile this out.
    if churn is not None and churn.redraw_tick is not None:
        mid = churn_mod.apply_redraws(jnp, mid, churn, t)

    # ---- phase 4a: churn alert injection (scheduled enqueue ticks) -----
    if churn is not None:
        # The enqueue fires only while the slot's epoch expectation holds:
        # a view change in between expired the scheduled alert, exactly as
        # the oracle's config-id check at enqueue would drop it.
        join_now = ((t == churn.join_tick) & ~mid.member
                    & (mid.epoch == churn.join_epoch))
        leave_now = ((t == churn.leave_tick) & mid.member
                     & (mid.epoch == churn.leave_epoch))
        mid = mid._replace(churn_flush=mid.churn_flush | join_now | leave_now)
        churn_injected = (join_now | leave_now).sum().astype(jnp.int32)
    else:
        churn_injected = jnp.int32(0)

    # ---- phase 4b: failure-detector interval ---------------------------
    is_fd = (t % settings.fd_interval_ticks == 0) & (t > mid.fd_gate)
    fc_new, notified_new, notify_exp, probes_sent, probes_failed = (
        monitor.monitor_tick(jnp, mid, faults, settings))
    new_state = mid._replace(
        fc=jnp.where(is_fd, fc_new, mid.fc),
        notified=jnp.where(is_fd, notified_new, mid.notified),
        pending_flush=notify_exp & is_fd,
    )

    # ---- phase 4c: fallback task phase (proposes + timer fires) --------
    if fallback is not None:
        new_state, px_task_counts = paxos_mod.task_phase(
            jnp, new_state, fallback, t, n_member_now, decide_now, mesh=mesh)
        px_counts.update(px_task_counts)
        px_timers_armed = (new_state.px_timer != I32_MAX).sum() \
            .astype(jnp.int32)
        px_coord_round = new_state.px_crnd_r.max().astype(jnp.int32)
    else:
        zero = jnp.int32(0)
        px_counts = {f: zero for f in (
            "pxvote_senders", "pxvote_recipients", "px1a_senders",
            "px1a_recipients", "px1b_senders", "px2a_senders",
            "px2a_recipients", "px2b_senders", "px2b_recipients")}
        px_timers_armed = px_coord_round = zero
    if fallback is not None and settings.protocol_variant == "ring":
        # The scripted fast-round votes are broadcast-shaped, so the ring
        # carries them in two laps like the live vote path; the classic
        # Paxos phases (1a/1b/2a/2b) are coordinator unicasts/broadcasts
        # among the quorum and stay dense in both engine and oracle.
        px_counts["pxvote_senders"] = jnp.where(
            px_counts["pxvote_senders"] > 0, 2, 0).astype(jnp.int32)

    # ---- on-device invariant monitor (static flag; see engine.invariants)
    # Module-attribute call so tests can monkeypatch a spy and prove the
    # disabled path never traces a single check op.
    if settings.invariant_checks:
        inv_bits = invariants.check_step(
            jnp, state, new_state,
            decide_now=decide_now,
            fast_decide=alert_decide,
            classic_decide=sc_decide,
            fast_mask=state.proposal,
            classic_mask=sc_mask,
            settings=settings,
        )
    else:
        inv_bits = jnp.int32(0)

    cfg_hi, cfg_lo = config_id_limbs(
        jnp, new_state.idsum_hi, new_state.idsum_lo,
        new_state.memsum_hi, new_state.memsum_lo)
    alerts_in_flight = (
        new_state.pending_flush.any(axis=1).sum()
        + new_state.pending_deliver.any(axis=1).sum()
        + new_state.churn_flush.sum()
        + new_state.churn_deliver.sum()
    ).astype(jnp.int32)
    log = StepLog(
        tick=t,
        announce_now=announce_now,
        proposal=crossed & announce_now,
        decide_now=decide_now,
        decision=decision,
        config_hi=cfg_hi, config_lo=cfg_lo,
        n_member=n_member_now,
        probes_sent=jnp.where(is_fd, probes_sent, 0).astype(jnp.int32),
        probes_failed=jnp.where(is_fd, probes_failed, 0).astype(jnp.int32),
        flushers=flushers,
        flush_recipients=flush_recipients,
        flushers_alive=flushers_alive,
        deliver_alive=jnp.where(batch_src.any(), n_alive, 0).astype(jnp.int32),
        vote_senders=vote_senders,
        vote_recipients=vote_recipients,
        vote_senders_alive=vote_senders_alive,
        vote_deliver_alive=vote_deliver_alive,
        alerts_in_flight=alerts_in_flight,
        cut_reports=new_state.reports.sum().astype(jnp.int32),
        implicit_reports=implicit_added,
        vote_tally=vote_tally,
        quorum=vote_quorum,
        epoch=new_state.epoch,
        churn_injected=churn_injected,
        partitioned_edges=monitor.partitioned_edge_count(
            jnp, faults, new_state.member, t),
        link_dropped=jnp.int32(0),
        pxvote_senders=px_counts["pxvote_senders"],
        pxvote_recipients=px_counts["pxvote_recipients"],
        px1a_senders=px_counts["px1a_senders"],
        px1a_recipients=px_counts["px1a_recipients"],
        px1b_senders=px_counts["px1b_senders"],
        px2a_senders=px_counts["px2a_senders"],
        px2a_recipients=px_counts["px2a_recipients"],
        px2b_senders=px_counts["px2b_senders"],
        px2b_recipients=px_counts["px2b_recipients"],
        px_timers_armed=px_timers_armed,
        px_coord_round=px_coord_round,
        inv_bits=inv_bits,
    )
    # Pin the carry (and the scanned log's [C] columns) to the slot
    # partition: without this the next tick would open with whatever
    # layout the last cross-slot op left behind — a per-tick reshard.
    new_state = sharding_mod.constrain_tree(new_state, mesh, c)
    log = sharding_mod.constrain_tree(log, mesh, c)
    return new_state, log


@partial(jax.jit, static_argnums=(2, 5))
def engine_step(state: EngineState, faults: EngineFaults,
                settings: Settings, churn=None, fallback=None,
                mesh=None) -> tuple:
    """One jitted tick — a single device dispatch per call.

    ``mesh`` (static; a hashable ``jax.sharding.Mesh`` or None) shards
    the tick over the slot axis — see ``rapid_tpu.engine.sharding``.
    """
    return step(state, faults, settings, churn, fallback, mesh)


@partial(jax.jit, static_argnums=(2, 3, 6))
def _simulate(state, faults, n_ticks: int, settings: Settings, churn=None,
              fallback=None, mesh=None):
    # Commit the initial carry to the slot partition before the scan so
    # tick 0 starts sharded instead of resharding on first use.
    if mesh is not None:
        c = state.member.shape[0]
        state = sharding_mod.constrain_tree(state, mesh, c)
        faults = sharding_mod.constrain_tree(faults, mesh, c)

    # Static recorder gate (``engine.recorder``): W > 0 threads a
    # bounded gauge ring through the scan as an extra carry and returns
    # a 3-tuple; the W == 0 branch keeps the recorder-less scan verbatim
    # so its jaxpr is byte-identical to a build without the recorder.
    # Module-attribute calls so tests can monkeypatch a spy (same
    # discipline as the invariant monitor above).
    if settings.flight_recorder_window:
        def rec_body(carry, _):
            st, rec = carry
            nxt, log = step(st, faults, settings, churn, fallback, mesh)
            return (nxt, recorder_mod.record_step(rec, log, settings)), log

        (final, rec), logs = lax.scan(
            rec_body, (state, recorder_mod.init(settings)), None,
            length=n_ticks)
        return final, logs, rec

    def body(carry, _):
        return step(carry, faults, settings, churn, fallback, mesh)

    return lax.scan(body, state, None, length=n_ticks)


def simulate(state: EngineState, faults: EngineFaults, n_ticks: int,
             settings: Settings, churn=None, fallback=None,
             mesh=None) -> tuple:
    """Run ``n_ticks`` engine steps as one jitted ``lax.scan``.

    Returns (final_state, logs) where each ``logs`` field is stacked with
    a leading ``n_ticks`` axis. ``churn`` is an optional ``ChurnSchedule``
    (see ``rapid_tpu.engine.churn``) and ``fallback`` an optional
    ``FallbackSchedule`` (see ``rapid_tpu.engine.paxos``); None compiles
    the respective subsystem out. ``mesh`` is an optional 1-D device mesh
    (``rapid_tpu.engine.sharding.slot_mesh``): the scan carry stays
    partitioned over the slot axis across all ticks, and results are
    bit-identical to the unsharded run.

    With ``settings.flight_recorder_window > 0`` the return grows to
    ``(final_state, logs, recorder)`` — see ``rapid_tpu.engine.recorder``.
    """
    return _simulate(state, faults, int(n_ticks), settings, churn, fallback,
                     mesh)


# ---------------------------------------------------------------------------
# streaming chunks: re-enter the scan with the previous chunk's carry
# ---------------------------------------------------------------------------
#
# The resident service (``rapid_tpu.service.resident``) runs an unbounded
# stream as fixed-size scan segments: every chunk re-enters the same jitted
# executable with the previous chunk's final state as its initial carry, so
# one compile serves the whole stream and the host drains chunk k-1's logs
# while the device computes chunk k. Two wrinkles vs ``_simulate``:
#
# - the flight recorder must *resume*, not restart — ``_simulate`` always
#   scans from ``recorder.init``, so chunks 2+ go through
#   ``_simulate_resumed`` which takes the ring as an explicit input carry;
# - the state (and recorder) buffers are donated so XLA reuses them for
#   the outputs — a soak keeps one state-sized working set alive instead
#   of accreting input+output per chunk. Faults/churn/fallback are NOT
#   donated: the fault pytree is reused across every chunk and the churn
#   schedule is still referenced by the traffic generator after dispatch.

@partial(jax.jit, static_argnums=(3, 4, 7))
def _simulate_resumed(state, rec, faults, n_ticks: int, settings: Settings,
                      churn=None, fallback=None, mesh=None):
    """``_simulate`` with the recorder carried in (chunks 2+, W > 0)."""
    if mesh is not None:
        c = state.member.shape[0]
        state = sharding_mod.constrain_tree(state, mesh, c)
        faults = sharding_mod.constrain_tree(faults, mesh, c)

    def rec_body(carry, _):
        st, r = carry
        nxt, log = step(st, faults, settings, churn, fallback, mesh)
        return (nxt, recorder_mod.record_step(r, log, settings)), log

    (final, rec), logs = lax.scan(rec_body, (state, rec), None,
                                  length=n_ticks)
    return final, logs, rec


_simulate_donated = partial(
    jax.jit, static_argnums=(2, 3, 6), donate_argnums=(0,))(
        lambda state, faults, n_ticks, settings, churn=None, fallback=None,
        mesh=None: _simulate.__wrapped__(state, faults, n_ticks, settings,
                                         churn, fallback, mesh))

_simulate_resumed_donated = partial(
    jax.jit, static_argnums=(3, 4, 7), donate_argnums=(0, 1))(
        lambda state, rec, faults, n_ticks, settings, churn=None,
        fallback=None, mesh=None: _simulate_resumed.__wrapped__(
            state, rec, faults, n_ticks, settings, churn, fallback, mesh))


def simulate_chunk(state: EngineState, faults: EngineFaults, n_ticks: int,
                   settings: Settings, churn=None, fallback=None, mesh=None,
                   rec=None, donate: bool = True) -> tuple:
    """One streaming chunk: ``n_ticks`` steps from an arbitrary carry.

    Identical semantics to ``simulate`` except the flight recorder
    resumes from ``rec`` when given (required for chunks after the first
    whenever ``settings.flight_recorder_window > 0``), and ``donate=True``
    (the default) donates the state (and recorder) buffers to the
    executable. Returns ``(final, logs)`` — or ``(final, logs, rec)``
    when the recorder window is nonzero. Chaining
    ``simulate_chunk(...); simulate_chunk(final, ..., rec=rec)`` is
    bit-identical to one uninterrupted ``simulate`` of the summed length
    (proven in ``tests/test_service.py``)."""
    n_ticks = int(n_ticks)
    if settings.flight_recorder_window and rec is not None:
        fn = _simulate_resumed_donated if donate else _simulate_resumed
        return fn(state, rec, faults, n_ticks, settings, churn, fallback,
                  mesh)
    fn = _simulate_donated if donate else _simulate
    return fn(state, faults, n_ticks, settings, churn, fallback, mesh)


# ---------------------------------------------------------------------------
# fleet axis: vmap the scanned step over a leading batch of clusters
# ---------------------------------------------------------------------------

_FLEET_TRACE_COUNT = 0


def fleet_trace_count() -> int:
    """How many times the fleet body has been traced (re-compiled)."""
    return _FLEET_TRACE_COUNT


def reset_fleet_trace_count() -> None:
    """Zero the fleet trace counter (see ``reset_trace_count``)."""
    global _FLEET_TRACE_COUNT
    _FLEET_TRACE_COUNT = 0


def fleet_body(states, faults, churn, fallback, n_ticks: int,
               settings: Settings, mesh=None, fleet_mesh=None):
    """The un-jitted fleet computation: ``vmap(scan(step))``.

    Every argument is a pytree whose leaves carry a leading fleet axis
    ``F`` (built by ``rapid_tpu.engine.fleet.stack_members``); the tick
    body is traced exactly once regardless of F — batching is an XLA
    dimension, not a python loop. ``churn`` and ``fallback`` are
    mandatory here (fleet members use inert schedules rather than None)
    so all members share one treedef. Exposed un-jitted so tests can
    ``jax.make_jaxpr`` it and prove the jaxpr size is F-invariant.

    ``mesh`` (static) composes with the fleet vmap: each member's slot
    axis is partitioned while the fleet axis stays replicated — the
    batched constraint lowers to ``P(None, 'slots')`` on ``[F, C]``
    leaves, so a vmapped campaign shards exactly like a single member.

    ``fleet_mesh`` (static) is the orthogonal routing: the *fleet* axis
    is partitioned as ``P("fleet")`` while each member stays whole on
    its owning device — embarrassingly parallel, no collectives. The
    two routings are mutually exclusive; both ``None`` traces a
    byte-identical jaxpr to the unsharded engine.
    """
    global _FLEET_TRACE_COUNT
    _FLEET_TRACE_COUNT += 1
    if mesh is not None and fleet_mesh is not None:
        raise ValueError(
            "mesh (slot-axis sharding) and fleet_mesh (fleet-axis "
            "sharding) are mutually exclusive routings")
    if fleet_mesh is not None:
        f = states.member.shape[0]
        states = sharding_mod.fleet_axis_constrain_tree(
            states, fleet_mesh, f)
        faults = sharding_mod.fleet_axis_constrain_tree(
            faults, fleet_mesh, f)
        churn = sharding_mod.fleet_axis_constrain_tree(
            churn, fleet_mesh, f)
        fallback = sharding_mod.fleet_axis_constrain_tree(
            fallback, fleet_mesh, f)

    # Same static recorder gate as ``_simulate``: W > 0 carries a
    # per-member gauge ring through each scan (one extra vmapped carry,
    # [F, W, G] total) and the fleet result grows to a 3-tuple; W == 0
    # keeps the recorder-less body verbatim (byte-identical jaxpr).
    if settings.flight_recorder_window:
        def one_rec(state, member_faults, member_churn, member_fallback):
            def rec_body(carry, _):
                st, rec = carry
                nxt, log = step(st, member_faults, settings, member_churn,
                                member_fallback, mesh)
                return (nxt,
                        recorder_mod.record_step(rec, log, settings)), log

            (final, rec), logs = lax.scan(
                rec_body, (state, recorder_mod.init(settings)), None,
                length=n_ticks)
            return final, logs, rec

        finals, logs, recs = jax.vmap(one_rec)(states, faults, churn,
                                               fallback)
        if fleet_mesh is not None:
            finals = sharding_mod.fleet_axis_constrain_tree(
                finals, fleet_mesh, f)
            logs = sharding_mod.fleet_axis_constrain_tree(
                logs, fleet_mesh, f)
            recs = sharding_mod.fleet_axis_constrain_tree(
                recs, fleet_mesh, f)
        return finals, logs, recs

    def one(state, member_faults, member_churn, member_fallback):
        def body(carry, _):
            return step(carry, member_faults, settings, member_churn,
                        member_fallback, mesh)

        return lax.scan(body, state, None, length=n_ticks)

    finals, logs = jax.vmap(one)(states, faults, churn, fallback)
    if fleet_mesh is not None:
        finals = sharding_mod.fleet_axis_constrain_tree(
            finals, fleet_mesh, f)
        logs = sharding_mod.fleet_axis_constrain_tree(logs, fleet_mesh, f)
    return finals, logs


@partial(jax.jit, static_argnums=(4, 5, 6, 7))
def _fleet_simulate(states, faults, churn, fallback, n_ticks: int,
                    settings: Settings, mesh=None, fleet_mesh=None):
    return fleet_body(states, faults, churn, fallback, n_ticks, settings,
                      mesh, fleet_mesh)


# Donating the stacked carries lets XLA reuse the dispatch's input
# buffers for its outputs: a pipelined campaign keeps at most the
# in-flight working sets alive instead of input+output per dispatch.
# Each stacked fleet is executed exactly once, so donation is safe —
# the campaign driver drops its input reference at launch.
_fleet_simulate_donated = partial(
    jax.jit, static_argnums=(4, 5, 6, 7),
    donate_argnums=(0, 1, 2, 3))(
        lambda states, faults, churn, fallback, n_ticks, settings,
        mesh=None, fleet_mesh=None: fleet_body(
            states, faults, churn, fallback, n_ticks, settings, mesh,
            fleet_mesh))
